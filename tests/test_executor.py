"""Executor tests against the simulated cluster backend.

Mirrors reference ExecutionTaskPlannerTest + ExecutorTest (embedded-cluster
integration, SURVEY §4.5) with the SimulatedClusterAdmin standing in for
embedded brokers.
"""

import dataclasses

import numpy as np
import pytest

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor import (
    ExecutionOptions,
    ExecutionTaskPlanner,
    Executor,
    ExecutorState,
    NoOngoingExecutionError,
    OngoingExecutionError,
    PrioritizeLargeReplicaMovementStrategy,
    PrioritizeSmallReplicaMovementStrategy,
    SimulatedClusterAdmin,
    TaskState,
    TaskType,
)
from cruise_control_tpu.monitor.topology import (
    BrokerNode,
    ClusterTopology,
    PartitionInfo,
    StaticMetadataProvider,
)


def proposal(topic, part, old, new, old_leader=None, new_leader=None, data=100.0):
    return ExecutionProposal(
        partition=part,
        topic=topic,
        old_leader=old[0] if old_leader is None else old_leader,
        new_leader=new[0] if new_leader is None else new_leader,
        old_replicas=tuple(old),
        new_replicas=tuple(new),
        inter_broker_data_to_move=data,
    )


def topo_4brokers(partitions):
    brokers = tuple(BrokerNode(i, rack=f"r{i % 2}", host=f"h{i}") for i in range(4))
    return ClusterTopology(brokers=brokers, partitions=tuple(partitions))


@pytest.fixture()
def sim():
    parts = [
        PartitionInfo("T0", 0, leader=0, replicas=(0, 1)),
        PartitionInfo("T0", 1, leader=1, replicas=(1, 2)),
        PartitionInfo("T1", 0, leader=2, replicas=(2, 3)),
        PartitionInfo("T1", 1, leader=3, replicas=(3, 0)),
    ]
    meta = StaticMetadataProvider(topo_4brokers(parts))
    return SimulatedClusterAdmin(meta, link_rate_bytes_per_s=200.0)


def test_planner_concurrency_and_fairness():
    pl = ExecutionTaskPlanner()
    props = [proposal(0, i, [0, 1], [0, 2], data=10.0 * (i + 1)) for i in range(6)]
    pl.add_execution_proposals(props)
    # broker 1 (drop) and 2 (add) involved in every move; cap 2 each
    tasks = pl.get_inter_broker_replica_movement_tasks({0: 5, 1: 2, 2: 2, 3: 5}, set())
    assert len(tasks) == 2
    assert len(pl.remaining_inter_broker_moves) == 4
    # in-progress partitions are excluded
    tasks2 = pl.get_inter_broker_replica_movement_tasks(
        {1: 5, 2: 5}, {(0, tasks[0].proposal.partition)}
    )
    assert all(t.proposal.partition != tasks[0].proposal.partition for t in tasks2)


def test_strategy_ordering():
    props = [proposal(0, i, [0], [1], data=d) for i, d in enumerate([50.0, 200.0, 100.0])]
    pl = ExecutionTaskPlanner(PrioritizeLargeReplicaMovementStrategy())
    pl.add_execution_proposals(props)
    sizes = [t.proposal.inter_broker_data_to_move for t in pl.remaining_inter_broker_moves]
    assert sizes == sorted(sizes, reverse=True)
    pl2 = ExecutionTaskPlanner(PrioritizeSmallReplicaMovementStrategy())
    pl2.add_execution_proposals(props)
    sizes2 = [t.proposal.inter_broker_data_to_move for t in pl2.remaining_inter_broker_moves]
    assert sizes2 == sorted(sizes2)


def test_execute_replica_and_leader_moves(sim):
    ex = Executor(sim, topic_names={0: "T0", 1: "T1"})
    props = [
        proposal(0, 0, [0, 1], [2, 1], old_leader=0, new_leader=2, data=100.0),
        proposal(1, 0, [2, 3], [2, 3], old_leader=2, new_leader=3, data=0.0),  # leader only
    ]
    res = ex.execute_proposals(props, ExecutionOptions(progress_check_interval_s=0.5))
    # proposal 0 emits a replica task + a leadership task; proposal 1 one task
    assert res.completed == 3 and res.dead == 0
    topo = sim.topology()
    by_key = {(p.topic, p.partition): p for p in topo.partitions}
    assert set(by_key[("T0", 0)].replicas) == {1, 2}
    assert by_key[("T0", 0)].leader == 2
    assert by_key[("T1", 0)].leader == 3
    assert ex.state == ExecutorState.NO_TASK_IN_PROGRESS
    assert sim.election_calls >= 1


def test_throttle_set_and_cleared(sim):
    observed = []
    orig_tick = sim.tick

    def spy_tick(seconds):
        observed.append(sim.throttle_rate)
        return orig_tick(seconds)

    sim.tick = spy_tick
    ex = Executor(sim, topic_names={0: "T0"})
    props = [proposal(0, 0, [0, 1], [2, 1], data=500.0)]
    ex.execute_proposals(
        props,
        ExecutionOptions(
            replication_throttle_bytes_per_s=100.0, progress_check_interval_s=1.0
        ),
    )
    assert observed and all(r == 100.0 for r in observed)
    assert sim.throttle_rate is None  # cleared afterwards
    # throttled rate (100/s) on 500 bytes -> at least 5 ticks
    assert len(observed) >= 5


def test_per_broker_concurrency_cap(sim):
    # all proposals touch broker 0 -> cap 1 means strictly serial execution
    parts = [PartitionInfo("T0", i, leader=0, replicas=(0, 1)) for i in range(4)]
    meta = StaticMetadataProvider(topo_4brokers(parts))
    admin = SimulatedClusterAdmin(meta, link_rate_bytes_per_s=1000.0)
    max_concurrent = []
    orig = admin.tick

    def spy(seconds):
        max_concurrent.append(len(admin.in_progress_reassignments()))
        return orig(seconds)

    admin.tick = spy
    ex = Executor(admin, topic_names={0: "T0"})
    props = [proposal(0, i, [0, 1], [2, 1], data=1000.0) for i in range(4)]
    res = ex.execute_proposals(
        props,
        ExecutionOptions(
            concurrent_partition_movements_per_broker=1, progress_check_interval_s=1.0
        ),
    )
    # 4 replica tasks + 4 leadership tasks (leader 0 left the replica set)
    assert res.completed == 8
    assert max(max_concurrent) == 1


def test_force_stop_aborts(sim):
    ex = Executor(sim, topic_names={0: "T0"})
    orig = sim.tick
    calls = []

    def stop_after_2(seconds):
        calls.append(1)
        if len(calls) == 2:
            ex.stop_execution(force=True)
        return orig(seconds)

    sim.tick = stop_after_2
    props = [proposal(0, i, [0, 1], [2, 1], data=10_000.0) for i in range(2)]
    res = ex.execute_proposals(props, ExecutionOptions(progress_check_interval_s=1.0))
    assert res.stopped
    assert res.aborted >= 1
    assert sim.in_progress_reassignments() == set()


def test_dead_destination_marks_task_dead(sim):
    ex = Executor(sim, topic_names={0: "T0"})
    orig = sim.tick
    calls = []

    def kill_broker_2(seconds):
        calls.append(1)
        if len(calls) == 1:
            topo = sim.metadata.topology()
            brokers = tuple(
                dataclasses.replace(b, alive=(b.broker_id != 2)) for b in topo.brokers
            )
            sim.metadata.set_topology(dataclasses.replace(topo, brokers=brokers))
        return orig(seconds)

    sim.tick = kill_broker_2
    props = [proposal(0, 0, [0, 1], [2, 1], data=100_000.0)]
    res = ex.execute_proposals(props, ExecutionOptions(progress_check_interval_s=1.0))
    # the replica move to dead broker 2 is DEAD, and so is the leadership
    # transfer onto it (its election can never be confirmed)
    assert res.dead == 2
    assert res.completed == 0


def test_ongoing_execution_guard(sim):
    ex = Executor(sim)
    ex.state = ExecutorState.STARTING_EXECUTION
    with pytest.raises(OngoingExecutionError):
        ex.execute_proposals([proposal(0, 0, [0], [1])])


def test_optimizer_to_executor_full_loop():
    """Monitor-model -> optimizer -> executor -> topology reflects proposals
    (the SURVEY §3.3 rebalance stack minus HTTP)."""
    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig
    from cruise_control_tpu.monitor import (
        FixedCapacityResolver,
        LoadMonitor,
        MetricFetcherManager,
        ModelCompletenessRequirements,
        StaticMetadataProvider as SMP,
        WindowedMetricSampleAggregator,
        KAFKA_METRIC_DEF,
    )
    from cruise_control_tpu.testing.synthetic import (
        SyntheticWorkloadSampler,
        WorkloadSpec,
        synthetic_topology,
    )

    topo = synthetic_topology(num_brokers=5, topics={"T0": 10, "T1": 10}, seed=5)
    meta = SMP(topo)
    sampler = SyntheticWorkloadSampler(topo, WorkloadSpec(), seed=5)
    agg = WindowedMetricSampleAggregator(3, 1000, 1, KAFKA_METRIC_DEF)
    fetcher = MetricFetcherManager(sampler, agg, None)
    parts = sampler.all_partition_entities()
    for w in range(4):
        fetcher.fetch_once(parts, w * 1000, (w + 1) * 1000 - 1)
    monitor = LoadMonitor(meta, FixedCapacityResolver([100.0, 1e5, 1e5, 1e6]), agg)
    state = monitor.cluster_model(ModelCompletenessRequirements(min_required_num_windows=2))

    cfg = OptimizerConfig(
        num_candidates=128, leadership_candidates=32, steps_per_round=16, num_rounds=2
    )
    res = GoalOptimizer(config=cfg).optimize(state)
    if not res.proposals:
        pytest.skip("optimizer found nothing to move on this fixture")

    admin = SimulatedClusterAdmin(meta, link_rate_bytes_per_s=1e12)
    ex = Executor(admin, catalog=monitor.last_catalog)
    out = ex.execute_proposals(res.proposals, ExecutionOptions(progress_check_interval_s=1.0))
    assert out.dead == 0 and out.completed > 0

    # post-execution topology must match the optimizer's target placement
    after = meta.topology()
    by_key = {(p.topic, p.partition): p for p in after.partitions}
    for p in res.proposals:
        got = by_key[monitor.last_catalog.partition_key(p.partition)]
        assert set(got.replicas) == set(p.new_replicas)
        if p.new_leader >= 0:
            assert got.leader == p.new_leader


def test_dropped_reassignments_are_reexecuted(sim):
    """A reassignment the controller silently drops must be detected (the
    target placement never landed) and re-submitted until it completes —
    reference Executor.maybeReexecuteTasks:1430."""
    ex = Executor(sim, topic_names={0: "T0", 1: "T1"})
    sim._drop_once.update({("T0", 0), ("T1", 0)})
    props = [
        proposal(0, 0, [0, 1], [2, 1], old_leader=0, new_leader=2, data=100.0),
        proposal(0, 1, [1, 2], [1, 3], old_leader=1, new_leader=1, data=100.0),
        proposal(1, 0, [2, 3], [0, 3], old_leader=2, new_leader=0, data=100.0),
    ]
    res = ex.execute_proposals(props, ExecutionOptions(progress_check_interval_s=0.5))
    assert sim.dropped_reassignments == [("T0", 0), ("T1", 0)]
    assert res.dead == 0 and res.completed == len(ex.tracker.tasks())
    by_key = {(p.topic, p.partition): set(p.replicas) for p in sim.topology().partitions}
    assert by_key[("T0", 0)] == {2, 1}
    assert by_key[("T0", 1)] == {1, 3}
    assert by_key[("T1", 0)] == {0, 3}
    state = ex.executor_state()
    assert state["numReexecutedTasks"] == 2
    assert state["taskStatus"]["INTER_BROKER_REPLICA_ACTION"] == {"COMPLETED": 3}


def test_reexecution_bound_marks_task_dead(sim):
    """A reassignment dropped more times than max_reexecution_attempts goes
    DEAD instead of looping forever (ExecutionTask.java:26-40 DEAD state)."""
    ex = Executor(sim, topic_names={0: "T0"})
    sim._drop_once.add(("T0", 0))
    props = [proposal(0, 0, [0, 1], [2, 1], old_leader=0, new_leader=2, data=100.0)]
    res = ex.execute_proposals(
        props,
        ExecutionOptions(progress_check_interval_s=0.5, max_reexecution_attempts=0),
    )
    assert res.dead == 1
    dead = ex.tracker.tasks(state=TaskState.DEAD)
    assert len(dead) == 1 and dead[0].task_type == TaskType.INTER_BROKER_REPLICA_ACTION
    # the topology still shows the OLD placement (the move never landed)
    by_key = {(p.topic, p.partition): set(p.replicas) for p in sim.topology().partitions}
    assert by_key[("T0", 0)] == {0, 1}


def test_mid_execution_concurrency_change(sim):
    """Operator raises the per-broker cap on a LIVE execution via
    set_requested_concurrency (reference Executor.java:485-510,
    driven by POST /admin) — the change applies on the next tick."""
    parts = [PartitionInfo("T0", i, leader=0, replicas=(0, 1)) for i in range(4)]
    meta = StaticMetadataProvider(topo_4brokers(parts))
    admin = SimulatedClusterAdmin(meta, link_rate_bytes_per_s=1000.0)
    concurrent = []
    orig = admin.tick

    def spy(seconds):
        concurrent.append(len(admin.in_progress_reassignments()))
        if len(concurrent) == 6:
            ex.set_requested_concurrency(inter_broker=4)
        return orig(seconds)

    admin.tick = spy
    ex = Executor(admin, topic_names={0: "T0"})
    props = [proposal(0, i, [0, 1], [2, 1], data=3000.0) for i in range(4)]
    res = ex.execute_proposals(
        props,
        ExecutionOptions(
            concurrent_partition_movements_per_broker=1, progress_check_interval_s=1.0
        ),
    )
    assert res.completed == len(ex.tracker.tasks()) and res.dead == 0
    # before the change: strictly serial; after: parallel drains appear
    assert max(concurrent[:6]) == 1
    assert max(concurrent[6:]) > 1
    # the override is reported in STATE and dies with the next execution
    assert ex.executor_state()["requestedConcurrency"] == {"inter_broker": 4}
    ex.execute_proposals([], ExecutionOptions())
    assert ex.requested_concurrency() == {}


def test_mid_execution_concurrency_decrease(sim):
    """Lowering the cap mid-flight throttles NEW submissions immediately
    (in-flight moves finish, but the steady state honors the new cap)."""
    parts = [PartitionInfo("T0", i, leader=0, replicas=(0, 1)) for i in range(8)]
    meta = StaticMetadataProvider(topo_4brokers(parts))
    admin = SimulatedClusterAdmin(meta, link_rate_bytes_per_s=1000.0)
    concurrent = []
    orig = admin.tick

    def spy(seconds):
        concurrent.append(len(admin.in_progress_reassignments()))
        if len(concurrent) == 2:
            ex.set_requested_concurrency(inter_broker=1)
        return orig(seconds)

    admin.tick = spy
    ex = Executor(admin, topic_names={0: "T0"})
    props = [proposal(0, i, [0, 1], [2, 1], data=3000.0) for i in range(8)]
    res = ex.execute_proposals(
        props,
        ExecutionOptions(
            concurrent_partition_movements_per_broker=4, progress_check_interval_s=1.0
        ),
    )
    assert res.completed == len(ex.tracker.tasks()) and res.dead == 0
    assert max(concurrent[:2]) == 4
    # once the initial burst drains, the loop never again exceeds 1
    drained = next(i for i, c in enumerate(concurrent) if i >= 2 and c <= 1)
    assert max(concurrent[drained:]) <= 1


def test_concurrency_change_rejected_when_idle(sim):
    """set_requested_concurrency raises atomically (under the executor
    lock) when nothing is executing — an execution finishing between the
    caller's check and the call must yield a loud error, not a lingering
    no-op override (ADVICE r4: /admin TOCTOU)."""
    ex = Executor(sim, topic_names={0: "T0"})
    with pytest.raises(NoOngoingExecutionError):
        ex.set_requested_concurrency(inter_broker=4)
    assert ex.requested_concurrency() == {}
    # validation still precedes the liveness check: bad values always raise
    with pytest.raises(ValueError):
        ex.set_requested_concurrency(inter_broker=0)


def test_progress_check_interval_change_mid_execution(sim):
    """execution_progress_check_interval_ms applies to the running loop."""
    intervals = []
    orig = sim.tick

    def spy(seconds):
        intervals.append(seconds)
        if len(intervals) == 2:
            ex.set_requested_concurrency(progress_check_interval_s=0.25)
        return orig(seconds)

    sim.tick = spy
    ex = Executor(sim, topic_names={0: "T0"})
    props = [proposal(0, 0, [0, 1], [2, 1], data=5000.0)]
    ex.execute_proposals(props, ExecutionOptions(progress_check_interval_s=1.0))
    assert intervals[:2] == [1.0, 1.0]
    assert set(intervals[3:]) == {0.25}


def test_graceful_stop_drains_in_flight(sim):
    """A non-forced stop submits nothing new but WAITS for in-flight moves
    to land, so no task is left IN_PROGRESS and the result counts add up
    (completed + aborted + dead == total)."""
    parts = [PartitionInfo("T0", i, leader=0, replicas=(0, 1)) for i in range(4)]
    meta = StaticMetadataProvider(topo_4brokers(parts))
    admin = SimulatedClusterAdmin(meta, link_rate_bytes_per_s=1000.0)
    orig = admin.tick
    calls = []

    def stop_after_1(seconds):
        calls.append(1)
        if len(calls) == 1:
            ex.stop_execution(force=False)
        return orig(seconds)

    admin.tick = stop_after_1
    ex = Executor(admin, topic_names={0: "T0"})
    props = [proposal(0, i, [0, 1], [2, 1], data=3000.0) for i in range(4)]
    res = ex.execute_proposals(
        props,
        ExecutionOptions(
            concurrent_partition_movements_per_broker=2, progress_check_interval_s=1.0
        ),
    )
    assert res.stopped
    total = len(ex.tracker.tasks())
    assert res.completed + res.aborted + res.dead == total
    assert not ex.tracker.tasks(state=TaskState.IN_PROGRESS)
    # the 2 in-flight moves were allowed to finish (graceful semantics)
    assert res.completed >= 2
    # and the topology reflects exactly the completed moves
    by_key = {(p.topic, p.partition): set(p.replicas) for p in admin.topology().partitions}
    moved = sum(1 for i in range(4) if by_key[("T0", i)] == {2, 1})
    assert moved == sum(
        1 for t in ex.tracker.tasks(state=TaskState.COMPLETED)
        if t.task_type == TaskType.INTER_BROKER_REPLICA_ACTION
    )


# ---------------------------------------------------------------- fault-
# injection coverage (testing/faults.py): dead-task timeout paths and
# force-stop under a misbehaving admin


def test_leader_movement_timeout_declares_dead(sim):
    """A leadership election the controller accepts but never performs
    (injected drop) must go DEAD at leader.movement.timeout.ms instead of
    spinning the confirmation loop until max_ticks."""
    from cruise_control_tpu.common.sensors import SensorRegistry
    from cruise_control_tpu.testing import faults

    sensors = SensorRegistry()
    ex = Executor(sim, topic_names={0: "T0"}, sensors=sensors)
    # leadership-only move: replicas unchanged, leader 0 -> 1
    props = [proposal(0, 0, [0, 1], [0, 1], old_leader=0, new_leader=1)]
    with faults.method_fault(sim, "elect_leaders", faults.dropping()) as log:
        res = ex.execute_proposals(
            props,
            ExecutionOptions(
                leader_movement_timeout_s=3.0, progress_check_interval_s=1.0
            ),
        )
    assert log.fired["elect_leaders"] == 1
    assert res.dead == 1 and res.completed == 0
    assert sensors.counter("executor.leader-movement-timeout").count == 1
    # simulated clock: the wait burned the timeout window, not max_ticks
    assert res.ticks <= 10


def test_force_stop_with_slow_and_hung_admin(sim):
    """stop_execution(force=True) mid-flight while the admin answers
    slowly (every progress probe injected +50ms) still aborts promptly:
    in-flight reassignments are cancelled, nothing stays IN_PROGRESS, and
    the executor returns well before the un-stopped execution would."""
    import threading
    import time as _time

    from cruise_control_tpu.testing import faults

    ex = Executor(sim, topic_names={0: "T0"})
    # slow enough (200 B/s link, 100 MB each) that the execution cannot
    # finish on its own within the test
    props = [proposal(0, i, [0, 1], [2, 1], data=100e6) for i in range(4)]
    started = threading.Event()

    def progress_probe(orig, *a, **k):
        started.set()
        _time.sleep(0.05)
        return orig(*a, **k)

    box = {}

    def run():
        try:
            box["res"] = ex.execute_proposals(
                props, ExecutionOptions(progress_check_interval_s=0.01)
            )
        except Exception as e:  # pragma: no cover - surfaced by the assert below
            box["err"] = e

    with faults.method_fault(sim, "in_progress_reassignments", progress_probe):
        t = threading.Thread(target=run, daemon=True)
        t.start()
        assert started.wait(10.0)
        ex.stop_execution(force=True)
        t.join(timeout=30.0)
    assert not t.is_alive(), "force stop did not terminate the execution"
    assert "err" not in box, box.get("err")
    res = box["res"]
    assert res.stopped
    assert sim.in_progress_reassignments() == set()  # cancelled on the wire
    assert not ex.tracker.tasks(state=TaskState.IN_PROGRESS)
    assert res.completed + res.aborted + res.dead == len(ex.tracker.tasks())
    assert not ex.has_ongoing_execution
