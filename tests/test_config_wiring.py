"""Config-key wiring tests: every key added for reference parity must
actually change behavior (VERDICT r2 missing #6 — config-key surface).

Reference anchors: config/constants/AnomalyDetectorConfig.java,
ExecutorConfig.java, AnalyzerConfig.java.
"""

import time

import pytest

from cruise_control_tpu.config import ConfigException, CruiseControlConfig
from cruise_control_tpu.service.main import build_simulated_service


def test_new_key_defaults_match_reference():
    c = CruiseControlConfig({})
    assert c.get("anomaly.detection.goals") == [
        "RackAwareGoal", "ReplicaCapacityGoal", "DiskCapacityGoal",
    ]
    assert c.get("self.healing.goals") == []
    assert c.get("num.cached.recent.anomaly.states") == 10
    assert c.get("max.num.cluster.movements") == 1250
    assert c.get("leader.movement.timeout.ms") == 180_000
    assert c.get("removal.history.retention.time.ms") == 1_209_600_000
    assert c.get("fixable.failed.broker.count.threshold") == 10
    assert c.get("fixable.failed.broker.percentage.threshold") == 0.4
    assert c.get("goal.balancedness.priority.weight") == 1.1
    assert c.get("goal.balancedness.strictness.weight") == 1.5
    # per-detector interval overrides default to unset (fall back to the
    # base anomaly.detection.interval.ms)
    assert c.get("goal.violation.detection.interval.ms") is None


def test_goal_list_keys_are_validated():
    for key in ("anomaly.detection.goals", "self.healing.goals",
                "intra.broker.goals"):
        with pytest.raises(ConfigException):
            CruiseControlConfig({key: "NoSuchGoal"})


def test_detector_interval_scheduling():
    """Per-detector cadence: a detector with a long interval runs once per
    window while unset-interval detectors run every scheduled round."""
    from cruise_control_tpu.detector.detector import AnomalyDetector
    from cruise_control_tpu.detector.notifier import SelfHealingNotifier

    class _IdleActions:
        is_busy = False

    det = AnomalyDetector(SelfHealingNotifier(), _IdleActions())
    calls = {"fast": 0, "slow": 0}
    det.register_detector(lambda: calls.__setitem__("fast", calls["fast"] + 1))
    det.register_detector(
        lambda: calls.__setitem__("slow", calls["slow"] + 1), interval_s=3600
    )
    for _ in range(3):
        det.run_once(respect_intervals=True)
    assert calls["fast"] == 3
    assert calls["slow"] == 1
    # forced rounds (default) ignore cadence — deterministic for tests
    det.run_once()
    assert calls["slow"] == 2


def test_anomaly_history_size_config():
    from cruise_control_tpu.detector.detector import AnomalyDetector
    from cruise_control_tpu.detector.notifier import SelfHealingNotifier

    class _IdleActions:
        is_busy = False

    det = AnomalyDetector(SelfHealingNotifier(), _IdleActions(), history_size=2)
    assert det.state.recent[next(iter(det.state.recent))].maxlen == 2


def test_executor_history_retention_and_drop():
    from cruise_control_tpu.executor.admin import SimulatedClusterAdmin
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.monitor.topology import StaticMetadataProvider
    from cruise_control_tpu.testing.synthetic import synthetic_topology

    admin = SimulatedClusterAdmin(
        StaticMetadataProvider(synthetic_topology(num_brokers=3, topics={"T0": 3}))
    )
    ex = Executor(admin, removal_history_retention_ms=50,
                  demotion_history_retention_ms=10_000)
    ex.execute_proposals([], removed_brokers={1}, demoted_brokers={2})
    assert ex.removed_brokers == {1}
    assert ex.demoted_brokers == {2}
    time.sleep(0.06)
    # removal history expired; demotion retention is longer
    assert ex.removed_brokers == set()
    assert ex.demoted_brokers == {2}
    ex.drop_demoted_brokers([2])
    assert ex.demoted_brokers == set()


def test_planner_max_total_budget():
    from cruise_control_tpu.analyzer.proposals import ExecutionProposal
    from cruise_control_tpu.executor.planner import ExecutionTaskPlanner

    planner = ExecutionTaskPlanner()
    proposals = [
        ExecutionProposal(
            topic="T0", partition=i, old_leader=0, new_leader=1,
            old_replicas=(0,), new_replicas=(1,),
            inter_broker_data_to_move=1.0,
        )
        for i in range(10)
    ]
    planner.add_execution_proposals(proposals, None)
    ready = {0: 100, 1: 100}
    got = planner.get_inter_broker_replica_movement_tasks(ready, set(), max_total=3)
    assert len(got) == 3
    # the rest stay queued for later rounds
    more = planner.get_inter_broker_replica_movement_tasks(
        {0: 100, 1: 100}, set(), max_total=100
    )
    assert len(more) == 7


@pytest.fixture(scope="module")
def wired_service():
    config = CruiseControlConfig(
        {
            "partition.metrics.window.ms": 1000,
            "min.samples.per.partition.metrics.window": 1,
            "execution.progress.check.interval.ms": 100,
            "webserver.http.port": 0,
            "tpu.num.candidates": 128,
            "tpu.leadership.candidates": 32,
            "tpu.steps.per.round": 16,
            "tpu.num.rounds": 2,
            "anomaly.detection.goals": "RackAwareGoal,ReplicaCapacityGoal",
            "self.healing.goals": "RackAwareGoal,ReplicaCapacityGoal,DiskCapacityGoal",
            "fixable.failed.broker.count.threshold": "2",
            "fixable.failed.broker.percentage.threshold": "0.5",
            "topics.excluded.from.partition.movement": "T1",
        }
    )
    app, fetcher, admin, sampler = build_simulated_service(config, seed=11)
    yield app


def test_anomaly_detection_goals_chain(wired_service):
    cc = wired_service.cc
    # the violation detector watches its own configured (smaller) chain
    gvd_chain_names = None
    for fn, _interval, _backoff in cc.anomaly_detector._detectors:
        owner = getattr(fn, "__self__", None)
        if owner is not None and hasattr(owner, "chain"):
            gvd_chain_names = owner.chain.names()
            break
    assert gvd_chain_names == ["RackAwareGoal", "ReplicaCapacityGoal"]


def test_self_healing_kwargs(wired_service):
    cc = wired_service.cc
    cc.executor._removed_history[4] = int(time.time() * 1000)
    cc.executor._demoted_history[5] = int(time.time() * 1000)
    try:
        kwargs = cc.actions._healing_kwargs()
        assert kwargs["goals"] == [
            "RackAwareGoal", "ReplicaCapacityGoal", "DiskCapacityGoal",
        ]
        assert kwargs["excluded_brokers_for_replica_move"] == [4]
        assert kwargs["excluded_brokers_for_leadership"] == [5]
    finally:
        cc.executor.drop_removed_brokers([4])
        cc.executor.drop_demoted_brokers([5])


def test_fixable_failed_broker_thresholds(wired_service):
    cc = wired_service.cc
    # count gate: 3 > threshold 2
    assert cc.actions.remove_brokers([0, 1, 2], reason="test") is False
    # percentage gate: 2 of 6 brokers is fine by count (<=2) and <= 50%,
    # so the guard passes through to the (dryrun=False) operation which we
    # do not want to actually run here — patch the facade call
    called = {}
    orig = cc.remove_brokers
    cc.remove_brokers = lambda *a, **k: called.setdefault("yes", True) or {}
    try:
        assert cc.actions.remove_brokers([0, 1], reason="test") is True
        assert called
    finally:
        cc.remove_brokers = orig


def test_config_excluded_topics_merged(wired_service):
    cc = wired_service.cc
    from cruise_control_tpu.service.progress import OperationProgress

    state = cc._cluster_model(OperationProgress())
    opts = cc._build_options(state)
    assert opts.excluded_topics is not None
    catalog = cc.monitor.last_catalog
    t1 = catalog.topics.index("T1")
    assert bool(opts.excluded_topics[t1])
    t0 = catalog.topics.index("T0")
    assert not bool(opts.excluded_topics[t0])
    # request pattern widens, never narrows
    opts2 = cc._build_options(state, excluded_topics_pattern="T0")
    assert bool(opts2.excluded_topics[t0]) and bool(opts2.excluded_topics[t1])


# ------------------------------------------------------------- pluggables


def test_strategy_chain_resolution_and_pool():
    from cruise_control_tpu.executor.strategy import (
        PrioritizeLargeReplicaMovementStrategy,
        resolve_strategy_chain,
    )

    chain = resolve_strategy_chain(
        ["PostponeUrpReplicaMovementStrategy", "PrioritizeLargeReplicaMovementStrategy"]
    )
    assert "PostponeUrp" in chain.name and "PrioritizeLarge" in chain.name
    # pool restriction (reference replica.movement.strategies)
    with pytest.raises(ValueError):
        resolve_strategy_chain(
            ["PrioritizeLargeReplicaMovementStrategy"],
            allowed={"BaseReplicaMovementStrategy"},
        )
    # dotted path resolves a custom class
    custom = resolve_strategy_chain(
        ["cruise_control_tpu.executor.strategy.PrioritizeSmallReplicaMovementStrategy"]
    )
    assert custom.name == "PrioritizeSmallReplicaMovementStrategy"
    with pytest.raises(ValueError):
        resolve_strategy_chain(["NoSuchStrategy"])


def test_executor_notifier_called():
    from cruise_control_tpu.executor.admin import SimulatedClusterAdmin
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.monitor.topology import StaticMetadataProvider
    from cruise_control_tpu.testing.synthetic import synthetic_topology

    calls = []

    class Notifier:
        def on_execution_finished(self, result, uuid):
            calls.append((result.completed, uuid))

    admin = SimulatedClusterAdmin(
        StaticMetadataProvider(synthetic_topology(num_brokers=3, topics={"T0": 3}))
    )
    ex = Executor(admin, notifier=Notifier())
    ex.execute_proposals([], uuid="op-1")
    assert calls == [(0, "op-1")]


def test_regression_bucket_gate_and_auto_train():
    import numpy as np

    from cruise_control_tpu.monitor.cpu_model import LinearRegressionModelParameters

    lr = LinearRegressionModelParameters(
        min_samples_to_train=6,
        cpu_util_bucket_size=10,
        required_samples_per_bucket=2,
        min_num_cpu_util_buckets=3,
    )
    rng = np.random.default_rng(1)
    # all samples in one CPU bucket: floor met but coverage insufficient
    for _ in range(6):
        x = rng.uniform(0, 1000, 3)
        lr.add_sample(*x, cpu_util=0.05)
    assert not lr.ready_to_train()
    assert not lr.train()
    # force (explicit /train) overrides coverage, not the sample floor
    assert lr.train(force=True)
    lr2 = LinearRegressionModelParameters(
        min_samples_to_train=6, cpu_util_bucket_size=10,
        required_samples_per_bucket=2, min_num_cpu_util_buckets=3,
    )
    for cpu in (0.05, 0.05, 0.35, 0.35, 0.65, 0.65):
        x = rng.uniform(0, 1000, 3)
        lr2.add_sample(*x, cpu_util=cpu)
    assert lr2.ready_to_train()
    assert lr2.train()


def test_rf_finder_uses_topic_config_provider():
    import dataclasses

    from cruise_control_tpu.detector.detectors import (
        TopicReplicationFactorAnomalyFinder,
    )
    from cruise_control_tpu.monitor.topic_config import StaticTopicConfigProvider
    from cruise_control_tpu.testing.synthetic import synthetic_topology

    topo = synthetic_topology(num_brokers=4, topics={"T0": 2, "T1": 2}, seed=0)
    # force both topics to RF 2
    parts = tuple(
        dataclasses.replace(p, replicas=tuple(p.replicas[:2])) for p in topo.partitions
    )
    topo = dataclasses.replace(topo, partitions=parts)
    provider = StaticTopicConfigProvider({"T0": {"min.insync.replicas": "2"}})
    finder = TopicReplicationFactorAnomalyFinder(
        lambda: topo, target_rf=2, topic_config_provider=provider
    )
    anomaly = finder.detect()
    # T0 needs RF >= minISR+1 = 3 -> flagged; T1 (minISR 1) is fine at RF 2
    assert anomaly is not None
    assert set(anomaly.bad_topics) == {"T0"}
    # without a provider, RF 2 meets the global target -> no anomaly
    assert TopicReplicationFactorAnomalyFinder(lambda: topo, target_rf=2).detect() is None


def test_sampler_cpu_estimation_flag():
    from cruise_control_tpu.config import CruiseControlConfig

    c = CruiseControlConfig({})
    assert c.get("sampling.allow.cpu.capacity.estimation") is True
    assert c.get("use.linear.regression.model") is False
    assert c.get("skip.loading.samples") is False
    assert c.get("max.allowed.extrapolations.per.broker") == 5


def test_cpu_weight_keys_wired():
    from cruise_control_tpu.monitor.cpu_model import follower_cpu_util

    # default weights (0.7, 0.15, 0.15)
    base = follower_cpu_util(100.0, 100.0, 0.5)
    alt = follower_cpu_util(100.0, 100.0, 0.5, weights=(0.5, 0.25, 0.25))
    assert base != alt
    assert base == pytest.approx(0.5 * 0.15 * 100.0 / (0.7 * 100.0 + 0.15 * 100.0))


def test_reference_spelled_override_keys_accepted():
    from cruise_control_tpu.service.parameters import (
        EndpointParameters,
        build_override_maps,
    )

    class MyParams(EndpointParameters):
        def __init__(self, endpoint, builtin):
            super().__init__(endpoint, builtin.params)

    # reference dotted spelling of add_broker.parameters.class (CLASS-typed
    # keys accept a class object directly)
    c = CruiseControlConfig({"add.broker.parameters.class": MyParams})
    parsers, handlers = build_override_maps(c)
    assert isinstance(parsers["add_broker"], MyParams)


def test_slow_task_rate_alerting():
    """A long-running task alerts only when ALSO slower than the MB/s floor
    (reference ExecutorConfig:142-158)."""
    from cruise_control_tpu.analyzer.proposals import ExecutionProposal
    from cruise_control_tpu.executor.admin import SimulatedClusterAdmin
    from cruise_control_tpu.executor.executor import ExecutionOptions, Executor
    from cruise_control_tpu.monitor.topology import StaticMetadataProvider
    from cruise_control_tpu.testing.synthetic import synthetic_topology

    admin = SimulatedClusterAdmin(
        StaticMetadataProvider(synthetic_topology(num_brokers=4, topics={"T0": 4})),
        link_rate_bytes_per_s=1.0,  # glacial: tasks run long
    )
    p0 = admin.metadata.topology().partitions[0]
    dest = next(
        b.broker_id
        for b in admin.metadata.topology().brokers
        if b.broker_id not in p0.replicas
    )
    # 50 KB over the many simulated seconds the 1 B/s link needs puts the
    # rate far under the default 0.1 MB/s floor — the DEFAULT threshold
    # must fire (units: data_to_move is bytes, the threshold is MB/s)
    prop = ExecutionProposal(
        topic=p0.topic, partition=p0.partition, old_leader=p0.leader,
        new_leader=p0.leader, old_replicas=tuple(p0.replicas),
        new_replicas=tuple(list(p0.replicas[1:]) + [dest]),
        inter_broker_data_to_move=50_000.0,
    )
    alerts = []

    class Notifier:
        def on_execution_finished(self, result, uuid):
            pass

        def on_task_alert(self, task):
            alerts.append(task)

    ex = Executor(admin, topic_names={0: "T0"}, notifier=Notifier())
    ex.execute_proposals(
        [prop],
        ExecutionOptions(
            progress_check_interval_s=1.0,
            task_execution_alerting_s=2.0,
            max_ticks=30,
        ),
    )
    assert alerts, "slow task should have alerted at the default floor"
    # a fast mover (same elapsed, vastly more data) must NOT alert
    admin2 = SimulatedClusterAdmin(
        StaticMetadataProvider(synthetic_topology(num_brokers=4, topics={"T0": 4})),
        link_rate_bytes_per_s=1e9,
    )
    q0 = admin2.metadata.topology().partitions[0]
    dest2 = next(
        b.broker_id
        for b in admin2.metadata.topology().brokers
        if b.broker_id not in q0.replicas
    )
    fast = ExecutionProposal(
        topic=q0.topic, partition=q0.partition, old_leader=q0.leader,
        new_leader=q0.leader, old_replicas=tuple(q0.replicas),
        new_replicas=tuple(list(q0.replicas[1:]) + [dest2]),
        inter_broker_data_to_move=5e9,
    )
    alerts2 = []

    class Notifier2:
        def on_execution_finished(self, result, uuid):
            pass

        def on_task_alert(self, task):
            alerts2.append(task)

    ex2 = Executor(admin2, topic_names={0: "T0"}, notifier=Notifier2())
    ex2.execute_proposals(
        [fast],
        ExecutionOptions(
            progress_check_interval_s=1.0,
            task_execution_alerting_s=2.0,
            max_ticks=30,
        ),
    )
    assert not alerts2, "fast mover must not rate-alert"


# ------------------------------------------------------------- webserver


def test_jwt_cookie_and_audience():
    from cruise_control_tpu.service.security import JwtSecurityProvider

    p = JwtSecurityProvider("s3cret", cookie_name="CCJWT",
                            expected_audiences=["cruise-control"])
    from cruise_control_tpu.service.security import jwt_encode

    good = jwt_encode({"sub": "u", "role": "ADMIN", "aud": "cruise-control"},
                      "s3cret")
    wrong_aud = jwt_encode({"sub": "u", "role": "ADMIN", "aud": "other"},
                           "s3cret")
    no_aud = jwt_encode({"sub": "u", "role": "ADMIN"}, "s3cret")
    assert p.authenticate({"Authorization": f"Bearer {good}"}) == ("u", "ADMIN")
    assert p.authenticate({"Cookie": f"CCJWT={good}"}) == ("u", "ADMIN")
    assert p.authenticate({"Authorization": f"Bearer {wrong_aud}"}) is None
    assert p.authenticate({"Authorization": f"Bearer {no_aud}"}) is None
    # header outranks cookie
    assert p.authenticate(
        {"Authorization": f"Bearer {wrong_aud}", "Cookie": f"CCJWT={good}"}
    ) is None


def test_purgatory_max_requests():
    from cruise_control_tpu.service.purgatory import Purgatory

    p = Purgatory(max_requests=2)
    p.add("rebalance", {})
    p.add("rebalance", {})
    with pytest.raises(ValueError):
        p.add("rebalance", {})
    # reviewing one frees a slot
    info = p.board()[0]
    p.review(info["Id"] if isinstance(info, dict) else info.review_id, approve=False)
    p.add("rebalance", {})


def test_access_log_ncsa_and_retention(tmp_path):
    import os

    from cruise_control_tpu.service.server import AccessLog

    path = tmp_path / "logs" / "access.log"
    log = AccessLog(str(path), retention_days=1)
    log.log("127.0.0.1", "admin", "GET", "/kafkacruisecontrol/state", 200, 42)
    line = path.read_text().strip()
    assert line.startswith("127.0.0.1 - admin [")
    assert '"GET /kafkacruisecontrol/state HTTP/1.1" 200 42' in line
    # a rolled file older than retention is pruned on the next roll
    old = tmp_path / "logs" / "access.log.2020-01-01"
    old.write_text("old\n")
    os.utime(old, (0, 0))
    log._day = "2020-01-02"  # force a roll on next write
    log.log("127.0.0.1", "-", "GET", "/x", 200, 1)
    assert not old.exists()


def test_user_task_category_retention():
    import time as _time

    from cruise_control_tpu.service.tasks import UserTaskManager

    m = UserTaskManager(
        completed_retention_ms=3_600_000,
        category_retention_ms={"KAFKA_MONITOR": 0},  # evict instantly
    )
    t_monitor = m.submit("proposals", lambda p: {})
    t_admin = m.submit("rebalance", lambda p: {})
    t_monitor.future.result()
    t_admin.future.result()
    _time.sleep(0.01)
    m._maybe_evict()
    assert m.get(t_monitor.task_id) is None  # KAFKA_MONITOR retention 0
    assert m.get(t_admin.task_id) is not None  # general retention applies


def test_endpoint_types_cover_all_endpoints():
    from cruise_control_tpu.config.endpoints import ALL_ENDPOINTS, ENDPOINT_TYPES

    assert set(ENDPOINT_TYPES) == set(ALL_ENDPOINTS)
    assert set(ENDPOINT_TYPES.values()) == {
        "KAFKA_MONITOR", "CRUISE_CONTROL_MONITOR",
        "KAFKA_ADMIN", "CRUISE_CONTROL_ADMIN",
    }


@pytest.fixture(scope="module")
def http_service(tmp_path_factory):
    """Live HTTP service exercising the CORS/access-log/reason-required keys."""
    import urllib.request

    from cruise_control_tpu.config import CruiseControlConfig

    logdir = tmp_path_factory.mktemp("accesslog")
    config = CruiseControlConfig(
        {
            "partition.metrics.window.ms": 1000,
            "min.samples.per.partition.metrics.window": 1,
            "execution.progress.check.interval.ms": 100,
            "webserver.http.port": 0,
            "tpu.num.candidates": 128,
            "tpu.leadership.candidates": 32,
            "tpu.steps.per.round": 8,
            "tpu.num.rounds": 2,
            "webserver.http.cors.enabled": "true",
            "webserver.http.cors.origin": "https://ops.example.com",
            "webserver.accesslog.enabled": "true",
            "webserver.accesslog.path": str(logdir / "access.log"),
            "request.reason.required": "true",
        }
    )
    app, fetcher, admin, sampler = build_simulated_service(config, seed=13)
    app.start()
    yield app, logdir
    app.stop()


def test_cors_headers_and_preflight(http_service):
    import http.client
    import json as _json
    import urllib.request

    app, _ = http_service
    url = f"http://{app.host}:{app.port}{app.prefix}/state"
    with urllib.request.urlopen(url, timeout=30) as resp:
        assert resp.headers["Access-Control-Allow-Origin"] == "https://ops.example.com"
        assert "User-Task-ID" in resp.headers["Access-Control-Expose-Headers"]
        _json.loads(resp.read())
    conn = http.client.HTTPConnection(app.host, app.port, timeout=30)
    conn.request("OPTIONS", f"{app.prefix}/state")
    pre = conn.getresponse()
    assert pre.status == 200
    assert pre.headers["Access-Control-Allow-Methods"] == "OPTIONS, GET, POST"
    assert "Authorization" in pre.headers["Access-Control-Allow-Headers"]
    conn.close()


def test_session_cookie_issued(http_service):
    import urllib.request

    app, _ = http_service
    url = f"http://{app.host}:{app.port}{app.prefix}/state"
    with urllib.request.urlopen(url, timeout=30) as resp:
        cookie = resp.headers.get("Set-Cookie", "")
    assert cookie.startswith("CCSESSION=")
    assert "Path=/" in cookie and "HttpOnly" in cookie


def test_reason_required_on_posts(http_service):
    import urllib.error
    import urllib.request

    app, _ = http_service
    base = f"http://{app.host}:{app.port}{app.prefix}"
    req = urllib.request.Request(f"{base}/pause_sampling", method="POST")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 400
    req = urllib.request.Request(
        f"{base}/pause_sampling?reason=maintenance", method="POST"
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.status == 200
    req = urllib.request.Request(
        f"{base}/resume_sampling?reason=done", method="POST"
    )
    urllib.request.urlopen(req, timeout=30).read()


def test_access_log_written(http_service):
    app, logdir = http_service
    content = (logdir / "access.log").read_text()
    assert '"GET ' in content and "HTTP/1.1" in content


def test_cache_not_served_when_estimation_forbidden(wired_service):
    """A request with allow_capacity_estimation=false must not be served
    from a cache filled with estimation allowed (reference sanity-checks
    capacityEstimationInfoByBrokerId on cached results)."""
    import dataclasses

    from cruise_control_tpu.monitor.load_monitor import (
        BrokerCapacityEstimationError,
    )
    from cruise_control_tpu.service.progress import OperationProgress

    cc = wired_service.cc
    cc.proposals(OperationProgress())  # fill the cache (estimation allowed)
    resolver = cc.monitor.capacity_resolver
    orig = resolver.capacity_for_broker
    resolver.capacity_for_broker = lambda r, h, b: dataclasses.replace(
        orig(r, h, b), estimation_info="estimated"
    )
    try:
        with pytest.raises(BrokerCapacityEstimationError):
            cc.proposals(OperationProgress(), allow_capacity_estimation=False)
    finally:
        resolver.capacity_for_broker = orig
        cc.invalidate_proposal_cache()


def test_capacity_estimation_forbidden(wired_service):
    import dataclasses

    from cruise_control_tpu.monitor.load_monitor import (
        BrokerCapacityEstimationError,
    )
    from cruise_control_tpu.service.progress import OperationProgress

    cc = wired_service.cc
    resolver = cc.monitor.capacity_resolver
    orig = resolver.capacity_for_broker

    def estimated(rack, host, broker_id):
        return dataclasses.replace(
            orig(rack, host, broker_id), estimation_info="default capacity"
        )

    resolver.capacity_for_broker = estimated
    try:
        with pytest.raises(BrokerCapacityEstimationError):
            cc._cluster_model(OperationProgress(), allow_capacity_estimation=False)
        # allowed by default
        cc._cluster_model(OperationProgress())
    finally:
        resolver.capacity_for_broker = orig
