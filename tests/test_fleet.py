"""Fleet controller tests: one service instance over N Kafka clusters.

Covers the acceptance contract of the fleet subsystem (fleet/manager.py):

  * shared compiled engines — clusters whose bucketed shapes coincide
    rebind ONE engine (engine-cache counters on the shared core prove it)
  * batched same-bucket scoring through the ScenarioEvaluator's
    one-dispatch path
  * per-cluster isolation — namespaced executor journals (a fleet restart
    reconciles every cluster's journal with zero cross-adoption),
    per-cluster labeled sensor registries (no last-writer-wins collisions
    in /metrics), per-cluster trace components
  * the REST surface — `cluster=` routing, GET /fleet rollups, per-tenant
    admission control (429), single-cluster deployments unchanged
  * 3 live FakeKafkaClusters under one facade (slow, socket-level)
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from cruise_control_tpu.config.app_config import CruiseControlConfig
from cruise_control_tpu.service.main import (
    build_simulated_fleet,
    build_simulated_service,
)
from cruise_control_tpu.service.progress import OperationProgress
from cruise_control_tpu.service.schemas import validate_response


# ---------------------------------------------------------------- fixtures


@pytest.fixture(scope="module")
def fleet_service():
    """One 3-cluster simulated fleet shared by the module: east/west share
    a bucketed shape, south has its own."""
    app, fleet = build_simulated_fleet(seed=11)
    app.start()
    try:
        yield app, fleet
    finally:
        fleet.shutdown()
        app.stop()


def _req(app, method, endpoint, headers=None, **params):
    base = f"http://{app.host}:{app.port}{app.prefix}"
    q = "&".join(f"{k}={v}" for k, v in params.items())
    r = urllib.request.Request(
        f"{base}/{endpoint}" + (f"?{q}" if q else ""),
        method=method, headers=headers or {},
    )
    try:
        with urllib.request.urlopen(r, timeout=120) as resp:
            body = resp.read()
            ctype = resp.headers.get("Content-Type", "")
            payload = (
                json.loads(body) if ctype.startswith("application/json")
                else body.decode()
            )
            return resp.status, payload, dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def _poll(app, method, endpoint, **params):
    status, payload, headers = _req(app, method, endpoint, **params)
    tid = headers.get("User-Task-ID")
    deadline = time.time() + 300
    while status == 202 and time.time() < deadline:
        time.sleep(0.2)
        status, payload, _ = _req(
            app, method, endpoint, headers={"User-Task-ID": tid}, **params
        )
    return status, payload


# ------------------------------------------------- shared engine economics


def test_same_bucket_clusters_share_one_compiled_engine(fleet_service):
    """The tentpole economics: east and west have identical bucketed
    shapes, so the second cluster's proposal run must REBIND the first's
    compiled engine (cache hit), and the fleet must end with fewer
    compiled engines than clusters."""
    app, fleet = fleet_service
    opt = fleet.core.optimizer
    h0, m0 = opt.engine_cache_hits, opt.engine_cache_misses
    results = {}
    for cid in ("east", "west", "south"):
        results[cid] = fleet.facade(cid).proposals(
            OperationProgress(), ignore_cache=True
        )
    assert opt.engine_cache_misses - m0 == 2, (
        "east+west share one engine, south compiles its own"
    )
    assert opt.engine_cache_hits - h0 >= 1, "west must hit east's engine"
    assert opt.cache_size < len(fleet.contexts)
    # the shared registry carries the proof counters
    snap = fleet.core.sensors.snapshot()
    assert snap["analyzer.engine-cache-hits"]["count"] >= 1
    # every cluster still got its own independent proposal set
    assert all(r is not None for r in results.values())
    shapes = {cid: r.state_before.shape for cid, r in results.items()}
    assert shapes["east"] == shapes["west"] != shapes["south"]


def test_score_clusters_batches_same_bucket_clusters(fleet_service):
    app, fleet = fleet_service
    before = fleet.sensors.counter("fleet.batched-score-runs").count
    scores = fleet.score_clusters()
    assert set(scores) == {"east", "west", "south"}
    assert scores["east"]["batchedWith"] == 2, (
        "east+west share a shape -> ONE batched dispatch for both"
    )
    assert scores["west"]["batchedWith"] == 2
    assert scores["south"]["batchedWith"] == 1
    for s in scores.values():
        assert 0.0 <= s["balancedness"] <= 100.0
        assert isinstance(s["violatedGoals"], list)
    # 2 shape groups -> 2 batched runs recorded
    assert fleet.sensors.counter("fleet.batched-score-runs").count - before == 2


# ------------------------------------------------------------ REST surface


def test_fleet_rollup_endpoint(fleet_service):
    app, fleet = fleet_service
    status, payload, _ = _req(app, "GET", "fleet")
    assert status == 200
    assert validate_response("fleet", payload) == []
    assert payload["numClusters"] == 3
    assert set(payload["clusters"]) == {"east", "west", "south"}
    for rollup in payload["clusters"].values():
        assert "proposalReady" in rollup
        assert "executorState" in rollup
    shared = payload["shared"]
    assert shared["compiledEngines"] >= 1
    assert shared["tenantMaxPendingTasks"] == 8
    # ?cluster= narrows, ?score=true scores (batched)
    status, payload, _ = _req(app, "GET", "fleet", cluster="east", score="true")
    assert status == 200
    assert set(payload["clusters"]) == {"east"}
    assert set(payload["scores"]) == {"east", "west", "south"}


def test_cluster_param_routing(fleet_service):
    app, fleet = fleet_service
    # cluster-scoped endpoint without cluster= -> 400 naming the clusters
    status, payload, _ = _req(app, "GET", "state")
    assert status == 400 and "cluster" in payload["errorMessage"]
    assert "east" in payload["errorMessage"]
    # unknown cluster -> 400
    status, payload, _ = _req(app, "GET", "state", cluster="nope")
    assert status == 400 and "nope" in payload["errorMessage"]
    # per-cluster /state resolves the right facade
    status, payload, _ = _req(
        app, "GET", "state", cluster="south", substates="monitor"
    )
    assert status == 200 and "MonitorState" in payload
    # an async op on one cluster tags its user task with the cluster
    status, payload = _poll(app, "GET", "proposals", cluster="east")
    assert status == 200, payload
    status, tasks, _ = _req(app, "GET", "user_tasks", clusters="east")
    assert status == 200
    assert tasks["userTasks"], "the east proposals task must be listed"
    assert all(t["Cluster"] == "east" for t in tasks["userTasks"])
    # ... and its trace filed under east's component namespace
    trace_id = payload.get("_traceId")
    assert trace_id
    status, trace, _ = _req(app, "GET", "trace", id=trace_id)
    assert status == 200
    components = {s["component"] for s in trace["spans"]}
    assert any(c.startswith("east:") for c in components), components


def test_metrics_exposition_with_n_clusters_lints_clean(fleet_service):
    """Satellite: two clusters registering the same sensor family must be
    distinct labeled series (no last-writer-wins), and the N-cluster
    exposition must pass the strict lint parser."""
    from cruise_control_tpu.common.exposition import parse_exposition

    app, fleet = fleet_service
    # every cluster builds a model first so the per-cluster monitor
    # sensor families exist regardless of which tests ran before
    fleet.score_clusters()
    status, body, headers = _req(app, "GET", "metrics")
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain")
    families = parse_exposition(body)  # raises on any lint violation
    # the same per-cluster family carries one sample per cluster, each
    # labeled with its cluster id
    fam = "cruisecontrol_monitor_cluster_model_creation_timer_seconds"
    count_labels = {
        labels.get("cluster")
        for name, labels, _ in families[fam]["samples"]
        if name == fam + "_count"
    }
    assert count_labels == {"east", "west", "south"}
    # shared-core families ride unlabeled beside them
    fam = "cruisecontrol_analyzer_engine_cache_hits_total"
    assert all(
        "cluster" not in labels for _, labels, _ in families[fam]["samples"]
    )


def test_tenant_admission_control_429(fleet_service):
    """Satellite: one noisy cluster's pending tasks must 429 at the cap
    while the other clusters keep being admitted."""
    app, fleet = fleet_service
    cap = fleet.tenant_max_pending
    release = threading.Event()
    blockers = [
        app.user_tasks.submit(
            "proposals", lambda progress: release.wait(30),
            cluster_id="east", client_id=f"noisy-{i}",
        )
        for i in range(cap)
    ]
    try:
        status, payload, _ = _req(
            app, "POST", "rebalance", cluster="east", dryrun="true"
        )
        assert status == 429, payload
        assert "pending" in payload["errorMessage"]
        rejections = fleet.facade("east").sensors.counter(
            "fleet.tenant-rejections"
        )
        assert rejections.count >= 1
        # the quiet cluster is NOT starved: its request is admitted
        status, payload, _ = _req(
            app, "POST", "rebalance", cluster="west", dryrun="true"
        )
        assert status in (200, 202), payload
    finally:
        release.set()
        for b in blockers:
            b.future.result(timeout=60)


# -------------------------------------------------- single-cluster parity


def test_single_cluster_deployment_unchanged():
    """A deployment without fleet.clusters keeps the classic surface:
    cluster= is rejected, /fleet answers a one-entry rollup, and the
    journal path has no cluster namespace."""
    app, fetcher, admin, sampler = build_simulated_service(seed=7)
    app.start()
    try:
        status, payload, _ = _req(app, "GET", "state", cluster="east")
        assert status == 400
        assert "no fleet" in payload["errorMessage"]
        status, payload, _ = _req(app, "GET", "state", substates="executor")
        assert status == 200
        status, payload, _ = _req(app, "GET", "fleet")
        assert status == 200
        assert validate_response("fleet", payload) == []
        assert payload["numClusters"] == 1
        assert set(payload["clusters"]) == {"default"}
        assert payload["shared"]["tenantMaxPendingTasks"] == 0
    finally:
        app.stop()


def test_single_cluster_journal_path_has_no_namespace(tmp_path):
    from cruise_control_tpu.executor.admin import SimulatedClusterAdmin
    from cruise_control_tpu.monitor.topology import StaticMetadataProvider
    from cruise_control_tpu.monitor import LoadMonitor, FixedCapacityResolver
    from cruise_control_tpu.monitor import WindowedMetricSampleAggregator
    from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF
    from cruise_control_tpu.service.facade import CruiseControl
    from cruise_control_tpu.testing.synthetic import synthetic_topology

    config = CruiseControlConfig({"executor.journal.dir": str(tmp_path)})
    topo = synthetic_topology(num_brokers=4)
    metadata = StaticMetadataProvider(topo)
    agg = WindowedMetricSampleAggregator(
        num_windows=3, window_ms=1000, min_samples_per_window=1,
        metric_def=KAFKA_METRIC_DEF,
    )
    monitor = LoadMonitor(
        metadata, FixedCapacityResolver([100.0, 1e5, 1e5, 1e6]), agg
    )
    cc = CruiseControl(config, monitor, SimulatedClusterAdmin(metadata))
    assert cc.executor.journal.path == str(
        tmp_path / "execution-journal.jsonl"
    )


def test_cluster_config_rejects_shared_core_overrides():
    """A fleet.<id>.<key> override of a key the SHARED core or webserver
    consumes (goal chain, tpu.* engine knobs, balancing thresholds,
    planner/trace/webserver) must be rejected at config time — it would
    validate, fold into the cluster's facade config, and then be silently
    ignored because those subsystems are built once from the base."""
    from cruise_control_tpu.config.app_config import ConfigException

    for key, value in [
        ("tpu.num.candidates", "64"),
        ("default.goals", "DiskUsageDistributionGoal"),
        ("disk.capacity.threshold", "0.9"),
        ("planner.max.scenarios", "4"),
        ("webserver.http.port", "9999"),
    ]:
        config = CruiseControlConfig(
            {"fleet.clusters": "east,west", f"fleet.east.{key}": value}
        )
        with pytest.raises(ConfigException, match="shared"):
            config.cluster_config("east")
    # cluster-scoped overrides still fold
    config = CruiseControlConfig({
        "fleet.clusters": "east,west",
        "fleet.east.executor.reaper.enabled": "false",
    })
    assert config.cluster_config("east").get("executor.reaper.enabled") is False
    assert config.cluster_config("west").get("executor.reaper.enabled") is True
    # ... and a typo'd cluster prefix fails at CONFIG time, not by
    # silently folding nothing
    with pytest.raises(ConfigException, match="eastt"):
        CruiseControlConfig({
            "fleet.clusters": "east,west",
            "fleet.eastt.bootstrap.servers": "kafka-east:9092",
        })


def test_tenant_cap_enforced_atomically_in_submit():
    """The per-tenant cap is counted and enforced inside
    UserTaskManager.submit under its lock (not check-then-submit at the
    server), so racing submissions cannot breach it."""
    from cruise_control_tpu.service.tasks import (
        TenantOverloadError,
        UserTaskManager,
    )

    mgr = UserTaskManager(max_active_tasks=50)
    release = threading.Event()
    try:
        for _ in range(2):
            mgr.submit("proposals", lambda p: release.wait(30),
                       cluster_id="east", cluster_max_active=2)
        with pytest.raises(TenantOverloadError, match="pending"):
            mgr.submit("proposals", lambda p: release.wait(30),
                       cluster_id="east", cluster_max_active=2)
        # other tenants and uncapped submissions are unaffected
        mgr.submit("proposals", lambda p: release.wait(30),
                   cluster_id="west", cluster_max_active=2)
        mgr.submit("proposals", lambda p: release.wait(30))
    finally:
        release.set()
        for t in mgr.all_tasks():
            t.future.result(timeout=30)
        mgr.shutdown()


# -------------------------------------------- journal namespace isolation


def _journal_with_inflight(path, uuid, topic, partition, old, new):
    """Craft an unfinished execution journal: a durable start record with
    one inter-broker move and no `finished` record — what a crashed fleet
    leaves on disk."""
    from cruise_control_tpu.analyzer.proposals import ExecutionProposal
    from cruise_control_tpu.executor.journal import (
        ExecutionJournal,
        task_to_journal,
    )
    from cruise_control_tpu.executor.tasks import ExecutionTask, TaskType

    proposal = ExecutionProposal(
        partition=partition, topic=0, old_leader=old[0], new_leader=old[0],
        old_replicas=tuple(old), new_replicas=tuple(new),
        inter_broker_data_to_move=1.0,
    )
    task = ExecutionTask(
        execution_id=0, proposal=proposal,
        task_type=TaskType.INTER_BROKER_REPLICA_ACTION,
    )
    j = ExecutionJournal(path)
    j.start_execution({
        "uuid": uuid, "ms": 0,
        "tasks": [task_to_journal(task, (topic, partition))],
        "options": {}, "removed": {}, "demoted": {},
    })
    j.close()
    return j.path


def test_fleet_restart_replays_every_journal_without_cross_adoption(tmp_path):
    """Satellite: two clusters crash mid-execution; the restarted fleet
    reconciles EACH cluster's journal into ITS OWN executor — east never
    adopts west's in-flight moves and vice versa."""
    jdir = tmp_path / "journals"
    _journal_with_inflight(
        str(jdir / "east" / "execution-journal.jsonl"),
        "uuid-east", "T0", 0, old=(0, 1), new=(2, 1),
    )
    _journal_with_inflight(
        str(jdir / "west" / "execution-journal.jsonl"),
        "uuid-west", "T1", 3, old=(1, 2), new=(0, 2),
    )
    app, fleet = build_simulated_fleet(
        props={"executor.journal.dir": str(jdir)},
        clusters={
            "east": dict(num_brokers=4, topics={"T0": 8}),
            "west": dict(num_brokers=4, topics={"T1": 8}),
            "south": dict(num_brokers=4, topics={"T2": 8}),
        },
        seed=3,
    )
    try:
        east = fleet.facade("east").executor
        west = fleet.facade("west").executor
        south = fleet.facade("south").executor
        # each executor reconciled exactly its own cluster's execution
        assert east.recovery_info() is not None
        assert east.recovery_info()["uuid"] == "uuid-east"
        assert west.recovery_info() is not None
        assert west.recovery_info()["uuid"] == "uuid-west"
        # a cluster that crashed idle recovers nothing
        assert south.recovery_info() is None
        # zero cross-adoption: the recovered tasks reference only the
        # owning cluster's journal
        east_tasks = east.tracker.tasks()
        west_tasks = west.tracker.tasks()
        assert {t.proposal.partition for t in east_tasks} == {0}
        assert {t.proposal.partition for t in west_tasks} == {3}
        # each cluster journals into its OWN namespaced directory
        assert east.journal.path.endswith("east/execution-journal.jsonl")
        assert west.journal.path.endswith("west/execution-journal.jsonl")
        assert south.journal.path.endswith("south/execution-journal.jsonl")
    finally:
        fleet.shutdown()


# ----------------------------------- live-socket fleet (3 FakeKafkaClusters)


def _skewed_topology(num_brokers: int, topics: dict[str, int]) -> dict:
    """Every replica packed onto brokers 0+1 (the rest idle) — a blatant
    distribution violation each cluster's rebalance must fix."""
    parts = {}
    for t, n in topics.items():
        parts[t] = [
            {"partition": p, "leader": p % 2, "replicas": [p % 2, 1 - p % 2]}
            for p in range(n)
        ]
    return parts


@pytest.mark.slow
def test_three_fake_kafka_clusters_under_one_facade():
    """The fleet acceptance story over live sockets: 3 FakeKafkaClusters
    behind ONE service — same-bucket clusters share a compiled engine,
    rebalances execute independently with zero cross-cluster task leakage,
    the noisy tenant 429s at the admission cap, and GET /fleet rolls the
    whole thing up."""
    from cruise_control_tpu.kafka import (
        KafkaAdminClient,
        KafkaClusterAdmin,
        KafkaMetadataProvider,
    )
    from cruise_control_tpu.service.main import build_fleet_service
    from cruise_control_tpu.testing.fake_kafka import FakeKafkaCluster
    from cruise_control_tpu.testing.synthetic import SyntheticWorkloadSampler

    specs = {
        # east/west: identical geometry -> one shared compiled engine
        "east": dict(num_brokers=4, topics={"T0": 8, "T1": 8}),
        "west": dict(num_brokers=4, topics={"T0": 8, "T1": 8}),
        # south: different geometry -> its own engine
        "south": dict(num_brokers=6, topics={"T0": 16, "T1": 16}),
    }
    fakes: dict[str, FakeKafkaCluster] = {}
    clients: list[KafkaAdminClient] = []
    try:
        backends = {}
        samplers = {}
        for i, (cid, spec) in enumerate(specs.items()):
            fakes[cid] = FakeKafkaCluster(
                brokers={
                    b: {"rack": f"r{b % 2}"} for b in range(spec["num_brokers"])
                },
                topics=_skewed_topology(**spec),
            ).start()
            client = KafkaAdminClient(fakes[cid].bootstrap(), timeout_s=10.0)
            clients.append(client)
            metadata = KafkaMetadataProvider(client)
            admin = KafkaClusterAdmin(client)
            sampler = SyntheticWorkloadSampler(metadata.topology(), seed=i)
            backends[cid] = (metadata, admin, sampler)
            samplers[cid] = sampler

        window_ms = 60_000
        config = CruiseControlConfig({
            "fleet.clusters": "east,west,south",
            "fleet.tenant.max.pending.tasks": "2",
            "partition.metrics.window.ms": str(window_ms),
            "min.samples.per.partition.metrics.window": "1",
            "num.partition.metrics.windows": "2",
            "execution.progress.check.interval.ms": "100",
            "webserver.http.port": "0",
            "tpu.num.candidates": "128",
            "tpu.leadership.candidates": "32",
            "tpu.steps.per.round": "16",
            "tpu.num.rounds": "2",
        })
        app, fleet = build_fleet_service(config, backends)
        for cid, ctx in fleet.contexts.items():
            parts = samplers[cid].all_partition_entities()
            for w in range(3):
                n = ctx.fetcher.fetch_once(
                    parts, w * window_ms, (w + 1) * window_ms - 1
                )
                assert n > 0, f"{cid} window {w} absorbed no samples"
        app.start()

        def placement(cid):
            return {
                (t, p["partition"]): tuple(p["replicas"])
                for t, pmap in fakes[cid].topics.items()
                for p in pmap.values()
            }

        before = {cid: placement(cid) for cid in specs}
        for fake in fakes.values():
            fake.auto_complete_after(2)

        # --- east rebalances; west and south are untouched ---
        status, payload = _poll(
            app, "POST", "rebalance", cluster="east", dryrun="false"
        )
        assert status == 200, payload
        assert payload["numReplicaMovements"] > 0
        assert placement("east") != before["east"]
        assert placement("west") == before["west"], "cross-cluster leakage"
        assert placement("south") == before["south"], "cross-cluster leakage"
        east_after = placement("east")

        # --- west rebalances on the SAME compiled engine (shared cache) ---
        opt = fleet.core.optimizer
        hits_before = opt.engine_cache_hits
        status, payload = _poll(
            app, "POST", "rebalance", cluster="west", dryrun="false"
        )
        assert status == 200, payload
        assert opt.engine_cache_hits > hits_before, (
            "west's identical bucketed shape must rebind east's engine"
        )
        assert placement("west") != before["west"]
        assert placement("east") == east_after, "cross-cluster leakage"
        assert placement("south") == before["south"], "cross-cluster leakage"

        # fewer compiled engines than clusters after south's run too
        status, payload = _poll(
            app, "POST", "rebalance", cluster="south", dryrun="false"
        )
        assert status == 200, payload
        assert opt.cache_size < len(fleet.contexts)

        # zero task leakage at the executor level: every cluster executed
        # its own tasks, and the three executors saw disjoint executions
        for cid in specs:
            assert fleet.facade(cid).executor.tracker.tasks(), cid

        # --- noisy tenant: 429 at the cap, quiet cluster still admitted ---
        release = threading.Event()
        blockers = [
            app.user_tasks.submit(
                "proposals", lambda progress: release.wait(30),
                cluster_id="south", client_id=f"noisy-{i}",
            )
            for i in range(2)
        ]
        try:
            status, payload, _ = _req(
                app, "POST", "rebalance", cluster="south", dryrun="true"
            )
            assert status == 429, payload
            status, payload, _ = _req(
                app, "POST", "rebalance", cluster="east", dryrun="true"
            )
            assert status in (200, 202), payload
        finally:
            release.set()
            for b in blockers:
                b.future.result(timeout=60)

        # --- GET /fleet rollup over the live fleet ---
        status, payload, _ = _req(app, "GET", "fleet")
        assert status == 200
        assert validate_response("fleet", payload) == []
        assert payload["numClusters"] == 3
        assert payload["shared"]["compiledEngines"] < 3
        assert payload["shared"]["engineCacheHits"] >= 1

        fleet.shutdown()
        app.stop()
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        for fake in fakes.values():
            fake.stop()


# ----------------------------------------------------- labeled exposition


def test_labeled_registries_render_distinct_series():
    """Unit twin of the /metrics test: same sensor family in two labeled
    registries + an unlabeled shared one -> three distinct series, one
    TYPE line, lint-clean."""
    from cruise_control_tpu.common.exposition import (
        parse_exposition,
        prometheus_text,
    )
    from cruise_control_tpu.common.sensors import SensorRegistry

    shared = SensorRegistry()
    a = SensorRegistry(base_labels={"cluster": "a"})
    b = SensorRegistry(base_labels={"cluster": "b"})
    shared.counter("analyzer.engine-cache-hits").inc(5)
    a.counter("monitor.model-builds").inc(1)
    b.counter("monitor.model-builds").inc(2)
    a.histogram("analyzer.proposal-computation-seconds").observe(0.5)
    b.histogram("analyzer.proposal-computation-seconds").observe(2.0)
    a.timer("monitor.cluster-model-creation-timer").update(0.1)
    b.timer("monitor.cluster-model-creation-timer").update(0.2)
    text = prometheus_text([shared, a, b])
    fams = parse_exposition(text)  # strict lint must pass
    fam = "cruisecontrol_monitor_model_builds_total"
    samples = {
        labels["cluster"]: v for _, labels, v in fams[fam]["samples"]
    }
    assert samples == {"a": 1.0, "b": 2.0}
    # one TYPE line per family even though two registries emitted it
    assert text.count(f"# TYPE {fam} counter") == 1
    # per-label histogram ladders each hold the bucket invariants (the
    # parser validated them); both clusters' ladders are present
    hfam = "cruisecontrol_analyzer_proposal_computation_seconds"
    ladders = {
        labels["cluster"]
        for name, labels, _ in fams[hfam]["samples"]
        if name == hfam + "_bucket"
    }
    assert ladders == {"a", "b"}
