"""Black-box telemetry tests (common/blackbox.py).

The acceptance story: a process killed -9 (or hang-timed-out) mid-anneal
leaves an on-disk spool that replays to the EXACT in-flight dispatch —
bucket, slice index, wait class — and the multichip dryrun's timeout
verdict embeds structured last-dispatch records instead of a bare rc
tail.  Plus the recorder invariants those post-mortems depend on: torn
tails tolerated, the ring bounded, the disabled path writing nothing and
changing nothing.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from cruise_control_tpu.common.blackbox import (
    BlackBoxRecorder,
    RECORDER,
    blackbox_context,
    in_flight_from_records,
    read_spool,
    spool_verdict,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_recorder():
    """The module-level recorder is process-wide state: every test leaves
    it disabled so suite ordering can never leak a spool."""
    yield
    RECORDER.configure(None)


def _small_state(seed=0):
    from cruise_control_tpu.testing.fixtures import (
        RandomClusterSpec,
        random_cluster,
    )

    return random_cluster(
        RandomClusterSpec(
            num_brokers=6, num_racks=3, num_topics=4, num_partitions=24,
            skew=1.0,
        ),
        seed=seed,
    )


def _small_config(**over):
    from cruise_control_tpu.analyzer import OptimizerConfig

    base = dict(
        num_candidates=64, leadership_candidates=16, swap_candidates=0,
        steps_per_round=2, num_rounds=3, seed=0,
    )
    base.update(over)
    return OptimizerConfig(**base)


# ----------------------------------------------------------------------
# recorder mechanics
# ----------------------------------------------------------------------


def test_recorder_roundtrip_context_and_in_flight(tmp_path):
    rec = BlackBoxRecorder()
    rec.configure(str(tmp_path / "spool-1.jsonl"))
    with blackbox_context(bucket="R64.B8", work_class="background"):
        seq = rec.begin("engine-slice", slice=0, rounds=2)
        rec.end(seq, done=False)
        rec.event("sched-grant", queue_wait_s=0.1)
        open_seq = rec.begin("engine-slice", slice=1, rounds=2)
    # the open dispatch is visible in-process...
    inflight = rec.in_flight()
    assert len(inflight) == 1
    assert inflight[0]["slice"] == 1
    assert inflight[0]["bucket"] == "R64.B8"
    # ...and from the on-disk records (the post-mortem view)
    records = read_spool(rec.path)
    assert [r["ph"] for r in records] == ["B", "E", "I", "B"]
    assert records[2]["work_class"] == "background"
    disk_inflight = in_flight_from_records(records)
    assert len(disk_inflight) == 1 and disk_inflight[0]["seq"] == open_seq
    # closing it clears both views
    rec.end(open_seq)
    assert rec.in_flight() == []
    assert in_flight_from_records(read_spool(rec.path)) == []


def test_exception_lands_in_end_record(tmp_path):
    rec = BlackBoxRecorder()
    rec.configure(str(tmp_path / "spool-1.jsonl"))
    with pytest.raises(ValueError):
        with rec.record("device-op", op="engine.run"):
            raise ValueError("boom")
    records = read_spool(rec.path)
    assert records[-1]["ph"] == "E"
    assert records[-1]["ok"] is False
    assert "boom" in records[-1]["error"]
    assert rec.in_flight() == []


def test_torn_tail_tolerated(tmp_path):
    rec = BlackBoxRecorder()
    rec.configure(str(tmp_path / "spool-1.jsonl"))
    s = rec.begin("supervised", op="optimize")
    rec.end(s)
    # the crash happened mid-write: a torn final line must end the
    # replay, not poison it
    with open(rec.path, "a", encoding="utf-8") as f:
        f.write('{"t": "super')
    records = read_spool(rec.path)
    assert len(records) == 2
    assert records[-1]["ph"] == "E"


def test_ring_rotation_keeps_one_generation(tmp_path):
    rec = BlackBoxRecorder()
    rec.configure(str(tmp_path / "spool-1.jsonl"), max_records=10)
    for i in range(35):
        rec.event("tick", i=i)
    assert os.path.exists(rec.path + ".1")
    records = read_spool(rec.path)
    # bounded: at most two generations' worth ever exists, newest last
    assert len(records) <= 20
    assert records[-1]["i"] == 34
    # the tail spans the rotation seamlessly
    assert [r["i"] for r in records] == list(
        range(records[0]["i"], 35)
    )


def test_unwritable_spool_disables_instead_of_raising(tmp_path):
    """Default-on telemetry must never prevent the service it observes
    from booting: an unopenable spool path leaves the recorder disabled
    (a regular file as a path component fails even for root, unlike
    permission bits)."""
    (tmp_path / "occupied").write_text("")
    rec = BlackBoxRecorder()
    rec.configure(str(tmp_path / "occupied" / "sub" / "spool-1.jsonl"))
    assert not rec.enabled and rec.write_errors == 1
    assert rec.begin("device-op", op="x") == 0  # silent no-op


def test_rotation_preserves_in_flight_begin_records(tmp_path):
    """A long-hung dispatch must survive any number of ring rotations
    driven by healthy traffic: its Begin is re-emitted into each new
    generation, so the post-mortem is never empty for exactly the
    long-hang case the spool exists for."""
    rec = BlackBoxRecorder()
    rec.configure(str(tmp_path / "spool-1.jsonl"), max_records=10)
    hung = rec.begin("engine-slice", slice=3, rounds=1)
    for i in range(45):  # > 4 whole generations of other traffic
        rec.event("tick", i=i)
    inflight = in_flight_from_records(read_spool(rec.path))
    assert [r["seq"] for r in inflight] == [hung]
    assert inflight[0]["slice"] == 3
    rec.end(hung)
    assert in_flight_from_records(read_spool(rec.path)) == []


def test_configure_prunes_dead_pid_spools(tmp_path):
    """'Bounded disk forever' across restarts: configuring a spool in a
    directory deletes sibling spool files of pids that no longer exist
    (a daily-restarted service must not accumulate a file pair per
    run)."""
    dead = tmp_path / "spool-999999999.jsonl"
    dead.write_text("{}\n")
    (tmp_path / "spool-999999999.jsonl.1").write_text("{}\n")
    live = tmp_path / f"spool-{os.getpid() + 0}.jsonl"  # ours, kept
    rec = BlackBoxRecorder()
    rec.configure(str(live))
    assert not dead.exists()
    assert not (tmp_path / "spool-999999999.jsonl.1").exists()
    assert live.exists()


def test_core_disables_recorder_when_config_says_off(tmp_path):
    """blackbox.enabled=false (or an explicitly empty dir) must disable
    a recorder an earlier service in this process turned on — the
    recorder is process-wide and the zero-writes contract is pinned."""
    from cruise_control_tpu.config.app_config import CruiseControlConfig
    from cruise_control_tpu.service.facade import AnalyzerCore

    AnalyzerCore(CruiseControlConfig({
        "blackbox.dir": str(tmp_path / "bb"),
    }))
    assert RECORDER.enabled
    AnalyzerCore(CruiseControlConfig({"blackbox.enabled": False}))
    assert not RECORDER.enabled


def test_spool_verdict_never_raises(tmp_path):
    assert spool_verdict(str(tmp_path / "absent")) == {
        "records": [], "in_flight": [],
    }


# ----------------------------------------------------------------------
# disabled-path pin
# ----------------------------------------------------------------------


def test_disabled_path_writes_nothing_and_results_identical(tmp_path):
    """Recording is pure observation: spool-on and spool-off runs of the
    same seeded anneal produce byte-identical placements, and the
    disabled recorder never touches disk."""
    from cruise_control_tpu.analyzer import DEFAULT_CHAIN, Engine

    state = _small_state()
    results = {}
    for mode in ("recorded", "disabled"):
        if mode == "recorded":
            RECORDER.configure(str(tmp_path / "spool-1.jsonl"))
        else:
            RECORDER.configure(None)
        eng = Engine(state, DEFAULT_CHAIN, config=_small_config())
        final, _ = eng.run()
        results[mode] = np.asarray(final.replica_broker)
    assert (results["recorded"] == results["disabled"]).all()
    recorded = read_spool(str(tmp_path / "spool-1.jsonl"))
    assert recorded, "the enabled run must have spooled its dispatches"
    assert {r["t"] for r in recorded} == {"device-op"}
    # disabled mode wrote nothing: record count unchanged after its run
    assert len(read_spool(str(tmp_path / "spool-1.jsonl"))) == len(recorded)


# ----------------------------------------------------------------------
# hang-timeout: the supervisor's abandonment verdict
# ----------------------------------------------------------------------


def test_hang_timeout_leaves_in_flight_trail(tmp_path):
    """A supervised dispatch that hangs past its budget leaves (a) the
    supervised End record with the abandonment verdict and (b) the
    in-worker device-op Begin permanently in flight — with the
    optimizer's bucket context stamped on it."""
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.common.device_watchdog import DeviceSupervisor
    from cruise_control_tpu.testing import faults

    RECORDER.configure(str(tmp_path / "spool-1.jsonl"))
    sup = DeviceSupervisor(
        op_timeout_s=0.4, max_retries=0, breaker_failure_threshold=100,
        probe=lambda: None,
    )
    opt = GoalOptimizer(config=_small_config(), supervisor=sup)
    opt.optimize(_small_state())  # healthy warm-up: compiles + records
    with faults.device_wedged(ops=("engine.run",)):
        result = opt.optimize(_small_state(seed=1))
        # read while the fault still holds: device_wedged releases its
        # abandoned workers at context exit (their late completion would
        # close the in-flight pair — exactly what a REAL hang never does)
        records = read_spool(str(tmp_path / "spool-1.jsonl"))
    assert result.degraded, "the hang must degrade to the CPU greedy path"
    abandoned = [
        r for r in records
        if r["t"] == "supervised" and r["ph"] == "E" and not r["ok"]
    ]
    assert abandoned and abandoned[-1]["hang"] is True
    inflight = in_flight_from_records(records)
    assert any(
        r["t"] == "device-op" and r["op"] == "engine.run" for r in inflight
    ), f"the hung engine dispatch must stay in flight: {inflight}"
    stuck = next(r for r in inflight if r["t"] == "device-op")
    assert "bucket" in stuck and stuck["config_fp"]


# ----------------------------------------------------------------------
# kill -9 mid-anneal: the acceptance story
# ----------------------------------------------------------------------

_KILL_CHILD = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax; jax.config.update("jax_platforms", "cpu")

    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig
    from cruise_control_tpu.analyzer.engine import Engine
    from cruise_control_tpu.common.blackbox import RECORDER
    from cruise_control_tpu.fleet.scheduler import DeviceScheduler, WorkClass
    from cruise_control_tpu.testing import faults
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster

    RECORDER.configure(os.path.join({spool_dir!r}, f"spool-{{os.getpid()}}.jsonl"))
    state = random_cluster(RandomClusterSpec(
        num_brokers=6, num_racks=3, num_topics=4, num_partitions=24, skew=1.0
    ), seed=0)
    cfg = OptimizerConfig(num_candidates=64, leadership_candidates=16,
                          swap_candidates=0, steps_per_round=2, num_rounds=8,
                          early_stop_violations=-1.0,  # all 8 rounds run
                          seed=0)
    opt = GoalOptimizer(config=cfg)
    sched = DeviceScheduler(slice_budget_s=0.0001)  # tiny budget: 1-round slices
    # the injected hang IS the wedged XLA program: slice dispatch #2
    # (0-based) blocks forever inside the device call
    with faults.method_fault(
        Engine, "_seg_fn", faults.hanging(__import__("threading").Event()),
        schedule=faults.FaultSchedule(calls={{2}}),
    ):
        sched.run(WorkClass.BACKGROUND, lambda: opt.optimize(state))
    print("UNREACHABLE")  # the parent kills us mid-slice
""")


def test_kill9_mid_anneal_spool_replays_to_in_flight_slice(tmp_path):
    """Kill -9 a process wedged inside a segmented-anneal slice (fault
    injected at the engine's slice-program seam): the surviving spool
    must replay to the exact in-flight dispatch — slice index, bucket,
    scheduler work class and queue wait."""
    spool_dir = str(tmp_path / "spool")
    os.makedirs(spool_dir)
    child = subprocess.Popen(
        [sys.executable, "-c",
         _KILL_CHILD.format(repo=REPO, spool_dir=spool_dir)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        # wait until the spool shows slice 2 dispatched (the child is now
        # hung inside it), then kill -9 — no cooperation from the child
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            records = read_spool(spool_dir)
            if any(
                r["t"] == "engine-slice" and r["ph"] == "B"
                and r.get("slice") == 2
                for r in records
            ):
                break
            if child.poll() is not None:
                out, err = child.communicate(timeout=10)
                pytest.fail(
                    f"child exited rc={child.returncode} before hanging:\n"
                    f"{err.decode(errors='replace')[-2000:]}"
                )
            time.sleep(0.05)
        else:
            pytest.fail("child never reached slice 2")
        os.kill(child.pid, signal.SIGKILL)
        child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()
    verdict = spool_verdict(spool_dir)
    stuck = [r for r in verdict["in_flight"] if r["t"] == "engine-slice"]
    assert stuck, f"no in-flight slice in {verdict['in_flight']}"
    assert stuck[-1]["slice"] == 2
    # slices 0 and 1 completed — their pairs closed
    closed = [
        r for r in read_spool(spool_dir)
        if r["t"] == "engine-slice" and r["ph"] == "E"
    ]
    assert len(closed) == 2
    # cross-layer context rode down to the leaf record: the scheduler's
    # wait class + the optimizer's bucket name the wedged dispatch
    assert stuck[-1]["work_class"] == "background"
    assert "queue_wait_s" in stuck[-1]
    assert stuck[-1]["bucket"].startswith("R")
    # the scheduler's grant instant is in the trail too
    assert any(
        r["t"] == "sched-grant" and r["work_class"] == "background"
        for r in read_spool(spool_dir)
    )


# ----------------------------------------------------------------------
# dryrun timeout verdict
# ----------------------------------------------------------------------


def test_child_failure_fields_structured(tmp_path):
    """The dryrun failure verdict builder: output tails + spool tail +
    in-flight records, never raising."""
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as g
    finally:
        sys.path.remove(REPO)
    spool = tmp_path / "spool-99.jsonl"
    rec = BlackBoxRecorder()
    rec.configure(str(spool))
    s = rec.begin("device-op", op="portfolio.run")
    rec.end(s)
    rec.begin("engine-slice", slice=7, rounds=4)  # left in flight
    rec.close()
    fields = g._child_failure_fields(
        "x" * 10_000, b"warning: tpu sad\n", str(tmp_path)
    )
    assert len(fields["stdout_tail"]) == g._VERDICT_TAIL_BYTES
    assert fields["stderr_tail"] == "warning: tpu sad\n"
    assert [r["t"] for r in fields["blackbox_tail"]] == [
        "device-op", "device-op", "engine-slice",
    ]
    assert fields["in_flight"][0]["slice"] == 7
    assert "wall_age_s" in fields["in_flight"][0]
    assert fields["spool_configured"] is True
    # unreadable spool dir: empty diagnosis, no exception
    empty = g._child_failure_fields(None, None, str(tmp_path / "absent"))
    assert empty["blackbox_tail"] == [] and empty["in_flight"] == []
    assert empty["spool_configured"] is False


def test_child_failure_fields_empty_spool_vs_never_started(tmp_path):
    """'No data' must be distinguishable from 'recorder never started':
    a spool FILE with zero records (the child configured the recorder,
    then hung before the first dispatch) reads spool_configured=True with
    structured in_flight=[]; a spool DIR with no spool files (the child
    died before RECORDER.configure — import/platform-init hang) reads
    spool_configured=False, in_flight still structurally []."""
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as g
    finally:
        sys.path.remove(REPO)
    # recorder configured, zero records written
    configured = tmp_path / "configured"
    configured.mkdir()
    (configured / "spool-123.jsonl").write_text("")
    fields = g._child_failure_fields(None, None, str(configured))
    assert fields["spool_configured"] is True
    assert fields["blackbox_tail"] == []
    assert fields["in_flight"] == []
    # spool dir minted by the parent, child never reached configure
    never = tmp_path / "never"
    never.mkdir()
    fields = g._child_failure_fields(None, None, str(never))
    assert fields["spool_configured"] is False
    assert fields["blackbox_tail"] == []
    assert fields["in_flight"] == []
    # no spool dir at all (recorder disabled by configuration)
    fields = g._child_failure_fields(None, None, None)
    assert fields["spool_configured"] is False
    assert fields["in_flight"] == []


@pytest.mark.slow
def test_dryrun_timeout_verdict_embeds_spool(monkeypatch, capsys):
    """The real timeout path: a dryrun child killed at its budget yields
    a JSON verdict with combined output tails AND the child's black-box
    records (regression for the bare-rc=124 MULTICHIP_r05 class)."""
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as g
    finally:
        sys.path.remove(REPO)
    monkeypatch.setenv("DRYRUN_SUBPROC_TIMEOUT_S", "3")
    monkeypatch.setenv("GRAFT_FORCE_CPU", "1")
    monkeypatch.delenv("GRAFT_DRYRUN_CHILD", raising=False)
    monkeypatch.delenv("BLACKBOX_SPOOL_DIR", raising=False)
    with pytest.raises(RuntimeError, match="killed after"):
        g.dryrun_multichip(8)
    out = capsys.readouterr().out
    verdict = json.loads(
        [l for l in out.splitlines() if '"dryrun_multichip"' in l][-1]
    )
    assert verdict["value"] == -1.0
    for key in ("stdout_tail", "stderr_tail", "blackbox_tail", "in_flight",
                "spool_configured"):
        assert key in verdict, f"timeout verdict missing {key}"
    # the structured fields are typed even when the 3 s budget killed the
    # child before anything was recorded — "no data" stays machine-readable
    assert isinstance(verdict["in_flight"], list)
    assert isinstance(verdict["spool_configured"], bool)
    if not verdict["blackbox_tail"]:
        assert verdict["in_flight"] == []
