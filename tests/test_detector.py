"""Detector + notifier + self-healing tests.

Mirrors reference AnomalyDetectorTest / SelfHealingNotifierTest (SURVEY §4.4)
and the RandomSelfHealingTest idea: dead brokers must end with their
replicas rebuilt elsewhere.
"""

import dataclasses

import numpy as np
import pytest

from cruise_control_tpu.detector import (
    Action,
    AnomalyDetector,
    AnomalyType,
    BrokerFailureDetector,
    BrokerFailures,
    DiskFailureDetector,
    GoalViolationDetector,
    GoalViolations,
    SelfHealingNotifier,
    SlowBrokerFinder,
    TopicReplicationFactorAnomalyFinder,
)
from cruise_control_tpu.analyzer.objective import DEFAULT_CHAIN
from cruise_control_tpu.monitor.topology import (
    BrokerNode,
    ClusterTopology,
    PartitionInfo,
    StaticMetadataProvider,
)
from cruise_control_tpu.testing.fixtures import (
    RandomClusterSpec,
    random_cluster,
    small_cluster,
)


class RecordingActions:
    def __init__(self, busy=False):
        self.calls = []
        self.busy = busy

    def rebalance(self, reason):
        self.calls.append(("rebalance", reason))
        return True

    def remove_brokers(self, broker_ids, reason):
        self.calls.append(("remove_brokers", tuple(broker_ids)))
        return True

    def demote_brokers(self, broker_ids, reason):
        self.calls.append(("demote_brokers", tuple(broker_ids)))
        return True

    def fix_offline_replicas(self, reason):
        self.calls.append(("fix_offline_replicas",))
        return True

    def fix_topic_replication_factor(self, topics, target_rf, reason):
        self.calls.append(("fix_rf", tuple(sorted(topics)), target_rf))
        return True

    @property
    def is_busy(self):
        return self.busy


def topo(dead=(), offline_logdirs=None, rf=2):
    offline_logdirs = offline_logdirs or {}
    brokers = tuple(
        BrokerNode(
            i,
            rack=f"r{i % 2}",
            host=f"h{i}",
            alive=i not in dead,
            offline_logdirs=tuple(offline_logdirs.get(i, ())),
        )
        for i in range(4)
    )
    parts = tuple(
        PartitionInfo("T0", p, leader=p % 4, replicas=tuple((p + i) % 4 for i in range(rf)))
        for p in range(8)
    )
    return ClusterTopology(brokers=brokers, partitions=parts)


def test_goal_violation_detector_on_unbalanced_cluster():
    det = GoalViolationDetector(small_cluster, DEFAULT_CHAIN)
    v = det.detect()
    assert v is not None and v.fixable_violations
    # balanced-enough random cluster: optimizer output should not flag hard goals
    state = random_cluster(RandomClusterSpec(num_brokers=8, num_partitions=100), seed=1)
    v2 = GoalViolationDetector(lambda: state, DEFAULT_CHAIN).detect()
    if v2 is not None:
        assert "RackAwareGoal" not in v2.unfixable_violations


def test_broker_failure_detector_persists_times(tmp_path):
    clock = {"now": 1000}
    p = str(tmp_path / "failed.json")
    provider = {"topo": topo(dead=(3,))}
    det = BrokerFailureDetector(
        lambda: provider["topo"], persist_path=p, now_ms=lambda: clock["now"]
    )
    a = det.detect()
    assert isinstance(a, BrokerFailures) and a.failed_brokers == {3: 1000}
    # restart: failure time must survive (reference ZK-persisted times :123-127)
    clock["now"] = 5000
    det2 = BrokerFailureDetector(
        lambda: provider["topo"], persist_path=p, now_ms=lambda: clock["now"]
    )
    a2 = det2.detect()
    assert a2.failed_brokers == {3: 1000}
    # broker recovers -> anomaly clears and persistence resets
    provider["topo"] = topo(dead=())
    assert det2.detect() is None
    det3 = BrokerFailureDetector(
        lambda: provider["topo"], persist_path=p, now_ms=lambda: clock["now"]
    )
    assert det3.detect() is None


def test_disk_failure_detector():
    det = DiskFailureDetector(lambda: topo(offline_logdirs={1: ["/d2"]}))
    a = det.detect()
    assert a is not None and a.failed_disks == {1: ["/d2"]}
    assert DiskFailureDetector(lambda: topo()).detect() is None


def test_slow_broker_finder_peer_and_history():
    finder = SlowBrokerFinder(peer_ratio=2.0, removal_threshold=3)
    normal = {0: 10.0, 1: 12.0, 2: 11.0, 3: 9.0}
    for _ in range(5):
        assert finder.detect(normal) is None
    slow = {**normal, 2: 100.0}
    a = finder.detect(slow)
    assert a is not None and 2 in a.slow_brokers and not a.remove_slow_brokers
    finder.detect(slow)
    a3 = finder.detect(slow)
    assert a3 is not None and a3.remove_slow_brokers  # escalates after strikes


def test_topic_rf_finder():
    det = TopicReplicationFactorAnomalyFinder(lambda: topo(rf=1), target_rf=2)
    a = det.detect()
    assert a is not None and a.bad_topics == {"T0": 1}


def test_self_healing_notifier_broker_failure_thresholds():
    clock = {"now": 0}
    n = SelfHealingNotifier(
        self_healing={AnomalyType.BROKER_FAILURE: True},
        broker_failure_alert_threshold_ms=1000,
        broker_failure_self_healing_threshold_ms=2000,
        now_ms=lambda: clock["now"],
    )
    anomaly = BrokerFailures(failed_brokers={3: 0})
    clock["now"] = 500  # before alert threshold
    r = n.on_anomaly(anomaly)
    assert r.action == Action.CHECK and r.delay_ms == 500
    clock["now"] = 1500  # alert, but not yet heal
    r = n.on_anomaly(anomaly)
    assert r.action == Action.CHECK and n.alerts[-1][1] is False
    clock["now"] = 2500  # past self-healing threshold
    r = n.on_anomaly(anomaly)
    assert r.action == Action.FIX and n.alerts[-1][1] is True
    # healing disabled -> IGNORE at fix time
    n.set_self_healing(AnomalyType.BROKER_FAILURE, False)
    assert n.on_anomaly(anomaly).action == Action.IGNORE


def test_detector_dispatch_and_busy_backoff():
    clock = {"now": 10_000}
    notifier = SelfHealingNotifier(
        self_healing={t: True for t in AnomalyType},
        broker_failure_alert_threshold_ms=0,
        broker_failure_self_healing_threshold_ms=0,
        now_ms=lambda: clock["now"],
    )
    actions = RecordingActions()
    det = AnomalyDetector(notifier, actions, now_ms=lambda: clock["now"])
    det.register_detector(lambda: GoalViolations(fixable_violations=["DiskCapacityGoal"]))
    recs = det.run_once()
    assert [r.status for r in recs] == ["FIX_STARTED"]
    assert actions.calls and actions.calls[0][0] == "rebalance"

    # busy executor defers the anomaly instead of fixing
    actions2 = RecordingActions(busy=True)
    det2 = AnomalyDetector(notifier, actions2, now_ms=lambda: clock["now"])
    det2.add_anomaly(BrokerFailures(failed_brokers={1: 0}))
    recs2 = det2._drain()
    assert recs2[0].status == "CHECKED" and not actions2.calls
    # after backoff elapses and executor frees up, the fix lands
    actions2.busy = False
    clock["now"] += 31_000
    recs3 = det2.run_once()
    assert ("remove_brokers", (1,)) in actions2.calls
    state = det2.detector_state()
    assert state["numSelfHealingStarted"] == 1


def test_self_healing_end_to_end_dead_broker():
    """Broker dies -> detector fires -> fix rebuilds replicas elsewhere
    (reference RandomSelfHealingTest semantics)."""
    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig
    from cruise_control_tpu.executor import ExecutionOptions, Executor, SimulatedClusterAdmin
    from cruise_control_tpu.monitor import (
        FixedCapacityResolver,
        KAFKA_METRIC_DEF,
        LoadMonitor,
        MetricFetcherManager,
        ModelCompletenessRequirements,
        StaticMetadataProvider,
        WindowedMetricSampleAggregator,
    )
    from cruise_control_tpu.testing.synthetic import (
        SyntheticWorkloadSampler,
        synthetic_topology,
    )

    base = synthetic_topology(num_brokers=5, topics={"T0": 10}, seed=9)
    meta = StaticMetadataProvider(base)
    sampler = SyntheticWorkloadSampler(base, seed=9)
    agg = WindowedMetricSampleAggregator(3, 1000, 1, KAFKA_METRIC_DEF)
    fetcher = MetricFetcherManager(sampler, agg, None)
    for w in range(4):
        fetcher.fetch_once(sampler.all_partition_entities(), w * 1000, (w + 1) * 1000 - 1)
    monitor = LoadMonitor(meta, FixedCapacityResolver([100.0, 1e5, 1e5, 1e6]), agg)

    # kill broker 4
    t = meta.topology()
    brokers = tuple(dataclasses.replace(b, alive=b.broker_id != 4) for b in t.brokers)
    meta.set_topology(dataclasses.replace(t, brokers=brokers))

    admin = SimulatedClusterAdmin(meta, link_rate_bytes_per_s=1e12)
    req = ModelCompletenessRequirements(min_required_num_windows=2)

    class Actions(RecordingActions):
        def remove_brokers(self, broker_ids, reason):
            state = monitor.cluster_model(req)
            cfg = OptimizerConfig(
                num_candidates=128, leadership_candidates=32, steps_per_round=16, num_rounds=2
            )
            res = GoalOptimizer(config=cfg).optimize(state)
            ex = Executor(admin, catalog=monitor.last_catalog)
            ex.execute_proposals(
                res.proposals,
                ExecutionOptions(progress_check_interval_s=1.0),
                removed_brokers=set(broker_ids),
            )
            self.calls.append(("remove_brokers", tuple(broker_ids)))
            return True

    notifier = SelfHealingNotifier(
        self_healing={AnomalyType.BROKER_FAILURE: True},
        broker_failure_alert_threshold_ms=0,
        broker_failure_self_healing_threshold_ms=0,
    )
    actions = Actions()
    det = AnomalyDetector(notifier, actions)
    bfd = BrokerFailureDetector(meta.topology)
    det.register_detector(bfd.detect)
    recs = det.run_once()
    assert any(r.status == "FIX_STARTED" for r in recs)

    # no partition may keep a replica on the dead broker
    after = meta.topology()
    for p in after.partitions:
        assert 4 not in p.replicas, f"partition {p} still on dead broker"


def test_slow_broker_detector_wired_into_service():
    """The facade registers a SlowBrokerFinder fed from the broker
    aggregator (reference AnomalyDetector.java:63-68 wiring + metric
    sources SlowBrokerFinder.java:99)."""
    from cruise_control_tpu.service.main import build_simulated_service

    app, fetcher, admin, sampler = build_simulated_service(seed=17)
    try:
        assert app.cc.slow_broker_finder is not None
        # a full detection round must execute the slow-broker feed without
        # error against the live broker aggregator
        records = app.cc.anomaly_detector.run_once()
        assert isinstance(records, list)
    finally:
        app.stop()


def test_slow_broker_finder_requires_majority_of_metric_families():
    """One noisy family spiking must NOT flag a broker; a majority of the
    evidence agreeing must (reference SlowBrokerFinder.java:99 multi-source
    evidence: byte rates + request latencies)."""
    finder = SlowBrokerFinder(peer_ratio=2.0, removal_threshold=3)

    def evidence(flush, produce, queue, broker=2):
        out = {}
        for b in range(4):
            out[b] = {
                "log_flush_time_ms_mean": 10.0 + b,
                "produce_local_time_ms_mean": 5.0 + b,
                "request_queue_size": 3.0,
            }
        out[broker] = {
            "log_flush_time_ms_mean": flush,
            "produce_local_time_ms_mean": produce,
            "request_queue_size": queue,
        }
        return out

    for _ in range(5):
        assert finder.detect(evidence(12.0, 6.0, 3.0)) is None
    # only ONE of three families spikes: not slow
    assert finder.detect(evidence(500.0, 6.0, 3.0)) is None
    # two of three agree (majority): slow
    a = finder.detect(evidence(500.0, 200.0, 3.0))
    assert a is not None and set(a.slow_brokers) == {2}
    assert not a.remove_slow_brokers
    # recovery clears the strikes
    assert finder.detect(evidence(12.0, 6.0, 3.0)) is None
    a2 = finder.detect(evidence(500.0, 200.0, 3.0))
    assert a2 is not None and not a2.remove_slow_brokers
