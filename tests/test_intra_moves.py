"""Intra-broker (logdir) move completion tracking + partition-size finder.

Reference: Executor.java:1036 intraBrokerMoveReplicas waits for
AlterReplicaLogDirs copies via DescribeLogDirs future replicas
(ExecutorAdminUtils); detector/PartitionSizeAnomalyFinder.java.
"""

import dataclasses

import numpy as np
import pytest

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor.admin import SimulatedClusterAdmin
from cruise_control_tpu.executor.executor import ExecutionOptions, Executor
from cruise_control_tpu.executor.tasks import TaskState, TaskType
from cruise_control_tpu.monitor.topology import StaticMetadataProvider
from cruise_control_tpu.testing.synthetic import synthetic_topology


def _intra_proposal(topo, data=1000.0):
    p0 = topo.partitions[0]
    return ExecutionProposal(
        topic=p0.topic, partition=p0.partition, old_leader=p0.leader,
        new_leader=p0.leader, old_replicas=tuple(p0.replicas),
        new_replicas=tuple(p0.replicas),
        disk_moves=((p0.replicas[0], 0, 1),),
        intra_broker_data_to_move=data,
    )


def test_intra_move_completes_only_when_copy_lands():
    topo = synthetic_topology(num_brokers=3, topics={"T0": 2}, seed=0)
    admin = SimulatedClusterAdmin(
        StaticMetadataProvider(topo),
        link_rate_bytes_per_s=100.0,
        intra_move_bytes=250.0,  # needs ~2.5 simulated seconds
    )
    ex = Executor(admin, topic_names={0: "T0"})
    res = ex.execute_proposals(
        [_intra_proposal(topo)], ExecutionOptions(progress_check_interval_s=1.0)
    )
    assert res.completed == 1
    # the copy took multiple ticks — it was NOT completed on submit
    assert res.ticks >= 2
    task = ex.tracker.tasks(state=TaskState.COMPLETED)[0]
    assert task.task_type == TaskType.INTRA_BROKER_REPLICA_ACTION


def test_intra_move_instant_when_admin_cannot_track():
    """Admins without logdir-progress reporting keep the submit-completes
    behavior (pre-KIP-113)."""
    topo = synthetic_topology(num_brokers=3, topics={"T0": 2}, seed=0)
    admin = SimulatedClusterAdmin(StaticMetadataProvider(topo))

    class NoTrackAdmin:
        def __getattr__(self, name):
            if name == "in_progress_logdir_moves":
                raise AttributeError(name)
            return getattr(admin, name)

    ex = Executor(NoTrackAdmin(), topic_names={0: "T0"})
    res = ex.execute_proposals(
        [_intra_proposal(topo)], ExecutionOptions(progress_check_interval_s=1.0)
    )
    assert res.completed == 1


def test_slow_intra_copy_alerts():
    topo = synthetic_topology(num_brokers=3, topics={"T0": 2}, seed=0)
    admin = SimulatedClusterAdmin(
        StaticMetadataProvider(topo),
        link_rate_bytes_per_s=1.0,
        intra_move_bytes=50.0,  # 50 simulated seconds at 1 B/s
    )
    alerts = []

    class Notifier:
        def on_execution_finished(self, result, uuid):
            pass

        def on_task_alert(self, task):
            alerts.append(task.task_type)

    ex = Executor(admin, topic_names={0: "T0"}, notifier=Notifier())
    res = ex.execute_proposals(
        [_intra_proposal(topo, data=1000.0)],
        ExecutionOptions(progress_check_interval_s=1.0, task_execution_alerting_s=2.0),
    )
    assert res.completed == 1
    assert TaskType.INTRA_BROKER_REPLICA_ACTION in alerts


def test_transient_describe_failure_keeps_copies_pending():
    """A DescribeLogDirs timeout must not read as 'no copies pending' —
    the executor treats absence as completion (kafka/admin.py
    in_progress_logdir_moves last-known fallback)."""
    from cruise_control_tpu.kafka.admin import KafkaClusterAdmin

    class FlakyClient:
        def __init__(self):
            self.fail_next = False
            self.dirs = {
                "/d0": {"error_code": 0, "replicas": {}, "future_replicas": {("T0", 0)}},
                "/d1": {"error_code": 0, "replicas": {("T0", 0): 10}, "future_replicas": set()},
            }

        def describe_logdirs(self, node_id):
            if self.fail_next:
                self.fail_next = False
                raise OSError("socket timeout")
            return self.dirs

    admin = KafkaClusterAdmin(FlakyClient())
    admin._logdir_move_brokers = {3}

    assert admin.in_progress_logdir_moves() == {("T0", 0, 3)}
    # transient failure: last-known pending set still reported
    admin.client.fail_next = True
    assert admin.in_progress_logdir_moves() == {("T0", 0, 3)}
    # copy finishes: broker drops out of the polling set
    admin.client.dirs["/d0"]["future_replicas"] = set()
    assert admin.in_progress_logdir_moves() == set()
    assert admin._logdir_move_brokers == set()
    # landed-verification: the replica reports under dense dir index 1
    assert admin.logdir_of("T0", 0, 3) == 1


def test_unreachable_broker_backs_off_but_can_recover():
    """Past the consecutive-failure cap the broker is only PROBED every
    few polls (no per-tick socket timeout), its copies stay pending, and a
    recovered broker is re-observed — landed copies are not reported dead."""
    from cruise_control_tpu.kafka.admin import KafkaClusterAdmin

    class FlakyDeadClient:
        calls = 0
        recovered = False

        def describe_logdirs(self, node_id):
            FlakyDeadClient.calls += 1
            if not FlakyDeadClient.recovered:
                raise OSError("unreachable")
            return {
                "/d0": {"error_code": 0, "replicas": {("T0", 0): 10},
                        "future_replicas": set()},
            }

    admin = KafkaClusterAdmin(FlakyDeadClient())
    admin._logdir_move_brokers = {7}
    admin._last_futures = {7: {("T0", 0, 7)}}
    # while failing: copies stay pending (a timeout is not completion)
    for _ in range(admin._max_describe_failures + 1):
        assert admin.in_progress_logdir_moves() == {("T0", 0, 7)}
    # backed off: most polls do NOT dial, pending still reported
    before = FlakyDeadClient.calls
    for _ in range(admin._probe_every - 1):
        assert admin.in_progress_logdir_moves() == {("T0", 0, 7)}
    assert FlakyDeadClient.calls == before
    # broker recovers; the next probe observes the landed copy
    FlakyDeadClient.recovered = True
    for _ in range(admin._probe_every + 1):
        pending = admin.in_progress_logdir_moves()
    assert pending == set()
    assert admin.logdir_of("T0", 0, 7) == 0


def test_intra_copy_on_dead_broker_goes_dead():
    """A logdir copy whose broker dies mid-copy is killed, not spun on
    until max_ticks."""
    topo = synthetic_topology(num_brokers=3, topics={"T0": 2}, seed=0)
    admin = SimulatedClusterAdmin(
        StaticMetadataProvider(topo),
        link_rate_bytes_per_s=1.0,
        intra_move_bytes=1e9,  # would take forever
    )
    prop = _intra_proposal(topo)
    dead_broker = prop.disk_moves[0][0]
    orig_tick = admin.tick
    ticks = {"n": 0}

    def kill_broker(seconds):
        ticks["n"] += 1
        if ticks["n"] == 2:
            t = admin.metadata.topology()
            brokers = tuple(
                dataclasses.replace(b, alive=(b.broker_id != dead_broker))
                for b in t.brokers
            )
            admin.metadata.set_topology(dataclasses.replace(t, brokers=brokers))
        return orig_tick(seconds)

    admin.tick = kill_broker
    ex = Executor(admin, topic_names={0: "T0"})
    res = ex.execute_proposals(
        [prop], ExecutionOptions(progress_check_interval_s=1.0)
    )
    assert res.dead == 1
    assert res.ticks < 20


def test_unverifiable_copy_bounded_then_dead():
    """A copy that vanishes but can never be VERIFIED (logdir_of None —
    e.g. network-partitioned broker still alive in metadata) goes DEAD
    after max_intra_verify_failures ticks instead of spinning to
    max_ticks."""
    topo = synthetic_topology(num_brokers=3, topics={"T0": 2}, seed=0)
    admin = SimulatedClusterAdmin(
        StaticMetadataProvider(topo),
        link_rate_bytes_per_s=100.0,
        intra_move_bytes=150.0,
    )
    orig_tick = admin.tick

    def vanish_first(seconds):
        admin._intra_inflight.clear()  # copy aborts, never lands
        return orig_tick(seconds)

    admin.tick = vanish_first
    admin.logdir_of = lambda *a: None  # and the broker cannot be asked
    ex = Executor(admin, topic_names={0: "T0"})
    res = ex.execute_proposals(
        [_intra_proposal(topo)],
        ExecutionOptions(progress_check_interval_s=1.0, max_intra_verify_failures=3),
    )
    assert res.dead == 1
    assert res.ticks < 10


def test_vanished_copy_without_landing_is_reexecuted():
    """A copy that disappears from the future set WITHOUT landing on the
    target dir is re-submitted (broker restart aborts the future log),
    mirroring the inter-broker landed-check."""
    topo = synthetic_topology(num_brokers=3, topics={"T0": 2}, seed=0)
    admin = SimulatedClusterAdmin(
        StaticMetadataProvider(topo),
        link_rate_bytes_per_s=100.0,
        intra_move_bytes=150.0,
    )
    drops = {"n": 0}
    resubmits = []
    orig_alter = admin.alter_replica_logdirs

    def dropping_alter(moves):
        resubmits.append(list(moves))
        orig_alter(moves)

    admin.alter_replica_logdirs = dropping_alter
    # logdir_of: first query reports the OLD dir (copy aborted), later the
    # target — simulates a broker restart aborting the first attempt
    def logdir_of(topic, partition, broker):
        drops["n"] += 1
        return 0 if drops["n"] == 1 else 1

    admin.logdir_of = logdir_of
    orig_tick = admin.tick

    def tick_dropping_first(seconds):
        # abort the first copy attempt mid-flight once
        if drops["n"] == 0 and admin._intra_inflight:
            admin._intra_inflight.clear()
        return orig_tick(seconds)

    admin.tick = tick_dropping_first
    ex = Executor(admin, topic_names={0: "T0"})
    res = ex.execute_proposals(
        [_intra_proposal(topo)], ExecutionOptions(progress_check_interval_s=1.0)
    )
    assert res.completed == 1
    assert len(resubmits) >= 2, "aborted copy must be re-submitted"
    assert ex.executor_state()["numReexecutedTasks"] >= 1


def test_partition_size_finder_wired_and_excludable():
    from cruise_control_tpu.detector.detectors import PartitionSizeAnomalyFinder
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster

    state = random_cluster(
        RandomClusterSpec(num_brokers=5, num_partitions=50, num_topics=2), seed=2
    )

    class Catalog:
        def partition_key(self, pid):
            return ("T0" if pid % 2 == 0 else "T1", pid)

    sizes = np.asarray(state.replica_load_leader)[:, 3]
    lead = np.asarray(state.replica_is_leader) & np.asarray(state.replica_valid)
    threshold = float(np.percentile(sizes[lead], 50))
    finder = PartitionSizeAnomalyFinder(
        lambda: state, Catalog, max_partition_size=threshold
    )
    finder.catalog_provider = lambda: Catalog()
    anomaly = finder.detect()
    assert anomaly is not None and anomaly.oversized
    # excluding every topic silences it
    silent = PartitionSizeAnomalyFinder(
        lambda: state, lambda: Catalog(), max_partition_size=threshold,
        excluded_topics_pattern="T.*",
    )
    assert silent.detect() is None


def test_partition_size_detection_enabled_via_config():
    from cruise_control_tpu.config import CruiseControlConfig
    from cruise_control_tpu.service.main import build_simulated_service

    config = CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "min.samples.per.partition.metrics.window": 1,
        "webserver.http.port": 0,
        "tpu.num.candidates": 128,
        "tpu.leadership.candidates": 32,
        "tpu.steps.per.round": 8,
        "tpu.num.rounds": 2,
        "partition.size.detection.enabled": "true",
        "self.healing.partition.size.threshold.byte": "1",  # everything flags
    })
    app, *_ = build_simulated_service(config, seed=5)
    records = app.cc.anomaly_detector.run_once()
    kinds = {type(r.anomaly).__name__ for r in records}
    assert "TopicPartitionSizeAnomaly" in kinds


def _multi_intra_proposals(topo, n, broker, data=1000.0):
    """n intra-broker disk moves all on the same broker."""
    out = []
    for i, p in enumerate(topo.partitions[:n]):
        out.append(ExecutionProposal(
            topic=p.topic, partition=p.partition, old_leader=p.leader,
            new_leader=p.leader, old_replicas=tuple(p.replicas),
            new_replicas=tuple(p.replicas),
            disk_moves=((broker, 0, 1),),
            intra_broker_data_to_move=data,
        ))
    return out


def test_intra_concurrency_cap_holds_while_copies_drain():
    """num.concurrent.intra.broker.partition.movements caps CONCURRENT
    copies per broker: copies still in flight consume their broker's
    slots, so the executor must not submit a fresh full-cap batch every
    tick (reference Executor per-broker intra concurrency)."""
    topo = synthetic_topology(num_brokers=3, topics={"T0": 8}, seed=0)
    broker = topo.partitions[0].replicas[0]
    # pin every proposal's broker to the same one so the cap is the binding
    # constraint; each copy takes ~3 ticks (250 bytes at 100 B/s)
    admin = SimulatedClusterAdmin(
        StaticMetadataProvider(topo),
        link_rate_bytes_per_s=100.0,
        intra_move_bytes=250.0,
    )
    concurrent = []
    orig = admin.tick

    def spy(seconds):
        concurrent.append(len(admin._intra_inflight))
        return orig(seconds)

    admin.tick = spy
    ex = Executor(admin, topic_names={0: "T0"})
    props = _multi_intra_proposals(topo, 6, broker)
    res = ex.execute_proposals(
        props,
        ExecutionOptions(
            concurrent_intra_broker_partition_movements=2,
            progress_check_interval_s=1.0,
        ),
    )
    assert res.completed == 6
    assert max(concurrent) <= 2, (
        f"intra cap violated: up to {max(concurrent)} concurrent copies"
    )


def test_intra_cap_change_mid_execution():
    """Raising the intra cap on a live execution speeds the drain."""
    topo = synthetic_topology(num_brokers=3, topics={"T0": 8}, seed=0)
    broker = topo.partitions[0].replicas[0]
    admin = SimulatedClusterAdmin(
        StaticMetadataProvider(topo),
        link_rate_bytes_per_s=100.0,
        intra_move_bytes=250.0,
    )
    concurrent = []
    orig = admin.tick

    def spy(seconds):
        concurrent.append(len(admin._intra_inflight))
        if len(concurrent) == 4:
            ex.set_requested_concurrency(intra_broker=4)
        return orig(seconds)

    admin.tick = spy
    ex = Executor(admin, topic_names={0: "T0"})
    props = _multi_intra_proposals(topo, 8, broker)
    res = ex.execute_proposals(
        props,
        ExecutionOptions(
            concurrent_intra_broker_partition_movements=1,
            progress_check_interval_s=1.0,
        ),
    )
    assert res.completed == 8
    assert max(concurrent[:4]) <= 1
    assert max(concurrent[4:]) > 1
    assert max(concurrent) <= 4


def test_graceful_stop_drains_tracked_copies():
    """Graceful stop waits for in-flight logdir copies instead of leaving
    them IN_PROGRESS in the tracker forever."""
    topo = synthetic_topology(num_brokers=3, topics={"T0": 4}, seed=0)
    broker = topo.partitions[0].replicas[0]
    admin = SimulatedClusterAdmin(
        StaticMetadataProvider(topo),
        link_rate_bytes_per_s=100.0,
        intra_move_bytes=350.0,
    )
    orig = admin.tick
    calls = []

    def stop_after_1(seconds):
        calls.append(1)
        if len(calls) == 1:
            ex.stop_execution(force=False)
        return orig(seconds)

    admin.tick = stop_after_1
    ex = Executor(admin, topic_names={0: "T0"})
    props = _multi_intra_proposals(topo, 3, broker)
    res = ex.execute_proposals(
        props,
        ExecutionOptions(
            concurrent_intra_broker_partition_movements=1,
            progress_check_interval_s=1.0,
        ),
    )
    assert res.stopped
    assert not ex.tracker.tasks(state=TaskState.IN_PROGRESS)
    assert res.completed + res.aborted + res.dead == len(ex.tracker.tasks())
    assert res.completed >= 1  # the tracked copy was drained, not dropped
