"""Cold-start prewarm tests: the boot manifest, AOT-serialized engine
programs, the fallback ladder, and warm-pool priority ordering.

The contract under test (analyzer/prewarm.py + engine.precompile_async):
a restart may be FASTER because of the manifest/AOT artifacts but must
never be DIFFERENT — any version/fingerprint/aval/checksum mismatch, a
truncated artifact, or a missing manifest falls back rung by rung
(AOT -> fresh trace+compile -> plain lazy jit) to byte-identical
results.  The round-4 in-line AOT cache regressed exactly this
(engine.precompile_async docstring); these are its regression guards.
"""

import dataclasses
import json
import os
import threading
import time

import numpy as np
import pytest

from cruise_control_tpu.analyzer.engine import (
    Engine,
    OptimizerConfig,
    _WarmedFn,
    _WarmPool,
)
from cruise_control_tpu.analyzer.objective import DEFAULT_CHAIN, GoalChain
from cruise_control_tpu.analyzer.prewarm import PrewarmStore, bucket_key
from cruise_control_tpu.common import compilation_cache
from cruise_control_tpu.common.sensors import SensorRegistry
from cruise_control_tpu.config.balancing import DEFAULT_CONSTRAINT
from cruise_control_tpu.models.builder import prewarm_state
from cruise_control_tpu.models.state import ClusterShape
from cruise_control_tpu.testing.fixtures import (
    RandomClusterSpec,
    random_cluster_fast,
)

CFG = OptimizerConfig(
    num_candidates=128, leadership_candidates=32, swap_candidates=16,
    steps_per_round=8, num_rounds=2, seed=0,
)


@pytest.fixture(autouse=True)
def _aot_worthwhile_at_toy_scale(monkeypatch):
    """Production gates artifacts by engine scale (engine.AOT_MIN_*);
    these tests exercise the artifact ladder on toy engines, so lower
    the floor to zero for the duration of each test."""
    import cruise_control_tpu.analyzer.engine as engine_mod

    monkeypatch.setattr(engine_mod, "AOT_MIN_REPLICAS", 0)
    monkeypatch.setattr(engine_mod, "AOT_MIN_CANDIDATES", 0)


@pytest.fixture(scope="module")
def state():
    return random_cluster_fast(
        RandomClusterSpec(
            num_brokers=10, num_partitions=160, num_racks=4, num_topics=6,
            skew=1.0,
        ),
        seed=3,
    )


@pytest.fixture(scope="module")
def golden_artifact(state, tmp_path_factory):
    """ONE cold engine run + AOT export shared by the ladder tests (each
    corruption/drift test copies the artifact into its own directory).
    Module-scoped, so the function-scoped threshold fixture is not yet
    active — lower the floor manually around the build."""
    import cruise_control_tpu.analyzer.engine as engine_mod

    d = tmp_path_factory.mktemp("golden-aot")
    old = (engine_mod.AOT_MIN_REPLICAS, engine_mod.AOT_MIN_CANDIDATES)
    engine_mod.AOT_MIN_REPLICAS = engine_mod.AOT_MIN_CANDIDATES = 0
    try:
        store = _store(d)
        e = Engine(state, DEFAULT_CHAIN, config=CFG, prewarm_store=store)
        e.precompile_async()
        final, _ = e.run()
        assert store.drain(300)
    finally:
        engine_mod.AOT_MIN_REPLICAS, engine_mod.AOT_MIN_CANDIDATES = old
    (name,) = [f for f in os.listdir(d) if f.endswith(".aot")]
    return dict(
        name=name,
        data=open(os.path.join(d, name), "rb").read(),
        placement=_placement(final),
    )


def _install_artifact(tmp_path, golden, data=None):
    with open(os.path.join(tmp_path, golden["name"]), "wb") as f:
        f.write(golden["data"] if data is None else data)


def _store(tmp_path, **kw):
    kw.setdefault("chain", DEFAULT_CHAIN)
    kw.setdefault("constraint", DEFAULT_CONSTRAINT)
    return PrewarmStore(str(tmp_path), **kw)


def _placement(state):
    return tuple(
        np.asarray(getattr(state, f))
        for f in ("replica_broker", "replica_is_leader", "replica_disk")
    )


def _same_placement(a, b) -> bool:
    return all(bool((x == y).all()) for x, y in zip(_placement(a), _placement(b)))


# ---------------------------------------------------------------- manifest


def test_manifest_round_trip(tmp_path, state):
    store = _store(tmp_path)
    store.note(state.shape, 3, CFG, parallel_mode="single")
    # dedup: the same (bucket, config) noted again is ONE entry
    store.note(state.shape, 3, CFG, parallel_mode="single")
    doc = json.load(open(store.manifest_path))
    assert len(doc["entries"]) == 1
    # the second note is a recency touch: deduped in memory (uses=2);
    # its disk write is throttled, so the file may still say uses=1
    assert next(iter(store._entries.values()))["uses"] == 2

    fresh = _store(tmp_path)
    rows = fresh.claim_boot_entries()
    assert len(rows) == 1
    shape, max_rf, cfg, pmode = fresh.entry_engine_inputs(rows[0])
    assert shape == state.shape and max_rf == 3
    assert cfg == CFG  # exact dataclass equality: the engine-cache key
    assert pmode == "single"
    # claimed once per store: a second facade over the same store gets []
    assert fresh.claim_boot_entries() == []


def test_manifest_rejects_foreign_environment(tmp_path, state):
    store = _store(tmp_path)
    store.note(state.shape, 2, CFG)
    other_chain = GoalChain.from_names(["ReplicaCapacityGoal"])
    other = _store(tmp_path, chain=other_chain)
    assert other.claim_boot_entries() == []  # chain fingerprint mismatch
    assert _store(tmp_path).claim_boot_entries()  # same env still claims


def test_manifest_version_and_corruption_tolerance(tmp_path, state):
    store = _store(tmp_path)
    store.note(state.shape, 2, CFG)
    doc = json.load(open(store.manifest_path))
    doc["version"] = 99
    open(store.manifest_path, "w").write(json.dumps(doc))
    assert _store(tmp_path).claim_boot_entries() == []
    open(store.manifest_path, "w").write("{ not json")
    assert _store(tmp_path).claim_boot_entries() == []
    # and a corrupt file never breaks recording: the next note rebuilds it
    store2 = _store(tmp_path)
    store2.note(state.shape, 2, CFG)
    assert _store(tmp_path).claim_boot_entries()


def test_manifest_merges_concurrent_stores_not_last_writer_wins(tmp_path):
    """Two stores over ONE directory (two fleet cores, or two processes
    sharing a cache dir) must UNION their working sets."""
    a, b = _store(tmp_path), _store(tmp_path)
    s1 = ClusterShape(32, 8, 8, 2, 2, 8, 1)
    s2 = ClusterShape(64, 16, 16, 4, 2, 16, 1)
    a.note(s1, 2, CFG)
    b.note(s2, 2, CFG)  # b never saw a's entry in memory
    keys = set(_store(tmp_path).manifest_bucket_keys())
    assert keys == {bucket_key(s1), bucket_key(s2)}


def test_manifest_bounded_by_max_entries(tmp_path):
    store = _store(tmp_path, max_entries=2)
    shapes = [ClusterShape(32 * k, 8, 8, 2, 2, 8, 1) for k in (1, 2, 3)]
    for s in shapes:
        store.note(s, 2, CFG)
        time.sleep(0.002)  # distinct last_used_ms for the recency order
    rows = _store(tmp_path, max_entries=2).claim_boot_entries()
    # most recent two survive, most recent FIRST (the active bucket leads)
    got = [r["bucket"]["R"] for r in rows]
    assert got == [96, 64]


# ------------------------------------------------------------ AOT ladder


def test_cold_engine_records_fresh_trace_and_exports(tmp_path, state):
    compilation_cache.reset_engine_trace_counts()
    store = _store(tmp_path)
    e1 = Engine(state, DEFAULT_CHAIN, config=CFG, prewarm_store=store)
    e1.precompile_async()
    e1.run()
    assert store.drain(300)
    arts = [f for f in os.listdir(tmp_path) if f.endswith(".aot")]
    assert len(arts) == 1
    bk = bucket_key(state.shape)
    assert compilation_cache.engine_trace_counts()[bk] == {"fresh": 1, "aot": 0}


def test_restart_loads_artifact_and_skips_tracing(tmp_path, state, golden_artifact):
    # "restart": fresh store + engine in this process — the artifact (not
    # the jit cache: a new Engine has its own) serves the fused program
    _install_artifact(tmp_path, golden_artifact)
    compilation_cache.reset_engine_trace_counts()
    e2 = Engine(state, DEFAULT_CHAIN, config=CFG, prewarm_store=_store(tmp_path))
    e2.precompile_async()
    final2, _ = e2.run()
    bk = bucket_key(state.shape)
    assert compilation_cache.engine_trace_counts()[bk] == {"fresh": 0, "aot": 1}
    assert all(
        bool((a == b).all())
        for a, b in zip(golden_artifact["placement"], _placement(final2))
    ), "AOT path changed the result"


def test_corrupt_artifact_falls_back_to_fresh_compile(tmp_path, state, golden_artifact):
    raw = golden_artifact["data"]
    _install_artifact(tmp_path, golden_artifact, raw[: len(raw) // 2])  # torn
    sensors = SensorRegistry()
    compilation_cache.reset_engine_trace_counts()
    store = _store(tmp_path, sensors=sensors)
    e2 = Engine(state, DEFAULT_CHAIN, config=CFG, prewarm_store=store)
    e2.precompile_async()
    final2, _ = e2.run()  # no crash: the ladder steps to the fresh path
    bk = bucket_key(state.shape)
    assert compilation_cache.engine_trace_counts()[bk]["fresh"] == 1
    assert sensors.counter("analyzer.prewarm-aot-rejects").count == 1
    assert all(
        bool((a == b).all())
        for a, b in zip(golden_artifact["placement"], _placement(final2))
    )
    store.drain(300)


def test_aval_drift_in_artifact_header_is_rejected(tmp_path, state, golden_artifact):
    """Defensive rung: an artifact whose key matches but whose recorded
    avals do not (the exact r4 failure mode: stale program, fresh data)
    must be rejected at load, never called."""
    header_line, _, payload = golden_artifact["data"].partition(b"\n")
    header = json.loads(header_line)
    header["avals"][0][0][0] += 1  # drift one dimension
    _install_artifact(
        tmp_path, golden_artifact, json.dumps(header).encode() + b"\n" + payload
    )
    sensors = SensorRegistry()
    store = _store(tmp_path, sensors=sensors)
    e2 = Engine(state, DEFAULT_CHAIN, config=CFG, prewarm_store=store)
    e2.precompile_async()
    e2.run()
    assert sensors.counter("analyzer.prewarm-aot-rejects").count == 1
    store.drain(300)


def test_fused_out_def_matches_traced_structure(state):
    """The AOT-hit path rebuilds the fused program's output treedef from
    FUSED_YS_KEYS instead of tracing (tracing is the cost artifacts
    exist to skip) — pin the constructed structure to the traced one so
    a ys-schema change cannot silently unflatten garbage."""
    import jax
    import jax.numpy as jnp

    e = Engine(state, DEFAULT_CHAIN, config=CFG)
    sx_av = e.statics_avals()
    key_av = jax.ShapeDtypeStruct((2,), jnp.uint32)
    carry_av = jax.eval_shape(e._init_impl, sx_av, key_av)
    traced = jax.tree.structure(
        jax.eval_shape(e._run_fused_impl, sx_av, carry_av)
    )
    assert e._fused_out_def(carry_av) == traced


def test_aot_never_loads_on_the_request_path(tmp_path, state, golden_artifact):
    """Deserialization runs ONLY on warm-pool workers: a run() without
    precompile_async must never touch an artifact (the r4 cache loaded
    in-line on the request path and regressed warm start)."""
    _install_artifact(tmp_path, golden_artifact)
    store = _store(tmp_path)
    e2 = Engine(state, DEFAULT_CHAIN, config=CFG, prewarm_store=store)
    e2.run()  # no precompile: plain lazy jit
    assert store.aot_load_attempts == 0


def test_no_artifacts_no_manifest_matches_plain_engine_bit_for_bit(tmp_path, state):
    """The acceptance pin: a cold run with an EMPTY store (and one with
    no store at all) produces byte-identical placements — the prewarm
    machinery is a pure warm-up accelerator."""
    plain, _ = Engine(state, DEFAULT_CHAIN, config=CFG).run()
    store = _store(tmp_path / "empty")
    e = Engine(state, DEFAULT_CHAIN, config=CFG, prewarm_store=store)
    e.precompile_async()
    with_store, _ = e.run()
    assert _same_placement(plain, with_store)
    store.drain(300)


def test_warmed_fn_aval_drift_falls_back_to_plain_jit():
    """engine.py _WarmedFn: a precompiled executable whose avals no
    longer match the rebound statics (max_rf drift inside one shape
    bucket) must fall back to the ordinary jit path, not crash."""
    shape = ClusterShape(32, 8, 8, 2, 2, 8, 1)
    s2 = prewarm_state(shape, max_rf=2)
    s3 = prewarm_state(shape, max_rf=3)
    e = Engine(s2, DEFAULT_CHAIN, config=CFG)
    e.precompile_async()
    final2, _ = e.run()  # consumes the warm future -> _WarmedFn installed
    assert isinstance(e._jit_run_fused, _WarmedFn)
    assert final2.shape == shape
    e.rebind(s3)  # same ClusterShape, wider replica table: avals drift
    final3, _ = e.run()  # must not raise; falls back + retraces
    ref, _ = Engine(s3, DEFAULT_CHAIN, config=CFG).run()
    assert _same_placement(final3, ref)


# -------------------------------------------------- compilation_cache scan


def test_scan_and_boot_report_under_concurrent_writer(tmp_path):
    d = str(tmp_path / "cache")
    os.makedirs(d)
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            p = os.path.join(d, f"entry-{i % 17}")
            try:
                with open(p, "wb") as f:
                    f.write(b"x" * 128)
                if i % 3 == 0:
                    os.unlink(p)
            except OSError:
                pass
            i += 1

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        for _ in range(200):
            entries, total = compilation_cache._scan(d)
            assert total >= 0 and isinstance(entries, set)
        # boot_report tolerates the same racing directory when enabled
        report = compilation_cache.boot_report()
        assert report is None or "engineTraces" in report
    finally:
        stop.set()
        t.join(timeout=5)


# ------------------------------------------------------ warm-pool priority


def test_warm_pool_runs_higher_priority_first():
    pool = _WarmPool()
    pool.ensure_workers(1)
    release = threading.Event()
    order: list[str] = []
    blocker = pool.submit(lambda: release.wait(10))
    lo = pool.submit(lambda: order.append("speculative"), priority=100)
    hi = pool.submit(lambda: order.append("active"), priority=0)
    release.set()
    blocker.result(10)
    hi.result(10)
    lo.result(10)
    assert order == ["active", "speculative"]


# ------------------------------------------------------------ service layer


def _service(props, tmp, seed=3, **geometry):
    from cruise_control_tpu.config.app_config import CruiseControlConfig
    from cruise_control_tpu.service.main import build_simulated_service

    base = {
        "partition.metrics.window.ms": 1000,
        "min.samples.per.partition.metrics.window": 1,
        "num.partition.metrics.windows": 3,
        "webserver.http.port": 0,
        "tpu.num.candidates": 128, "tpu.leadership.candidates": 32,
        "tpu.steps.per.round": 8, "tpu.num.rounds": 2,
        "tpu.compile.cache.dir": os.path.join(tmp, "xla"),
        "tpu.prewarm.manifest.dir": os.path.join(tmp, "prewarm"),
    }
    base.update(props)
    return build_simulated_service(CruiseControlConfig(base), seed=seed, **geometry)


@pytest.mark.slow
def test_start_up_boot_prewarms_manifest_bucket(tmp_path):
    from cruise_control_tpu.service.progress import OperationProgress

    tmp = str(tmp_path)
    app, fetcher, admin, sampler = _service({}, tmp)
    cc = app.cc
    r1 = cc.proposals(OperationProgress(), ignore_cache=True)
    cc.core.prewarm_store.drain(300)
    cc.shutdown()

    app2, fetcher2, admin2, sampler2 = _service({}, tmp)
    cc2 = app2.cc
    cc2.start_up(detection_interval_s=3600)
    assert cc2._boot_prewarm_done.wait(120)
    assert cc2.optimizer.has_engine_for(r1.state_before.shape)
    snap = cc2.sensors.snapshot()
    assert snap["analyzer.boot-prewarm-buckets"]["count"] >= 1
    r2 = cc2.proposals(OperationProgress(), ignore_cache=True)
    assert _same_placement(r1.state_after, r2.state_after)
    cc2.shutdown()


@pytest.mark.slow
def test_fleet_facades_merge_one_manifest_and_both_prewarm(tmp_path):
    """Fleet satellite: two clusters with DIFFERENT shape buckets over
    one shared AnalyzerCore record into ONE merged manifest (dedup, not
    last-writer-wins), and a restart prewarns BOTH clusters' buckets."""
    from cruise_control_tpu.service.main import build_simulated_fleet
    from cruise_control_tpu.service.progress import OperationProgress

    tmp = str(tmp_path)
    clusters = {
        "east": dict(num_brokers=6, topics={"T0": 12, "T1": 12}),
        "south": dict(num_brokers=12, topics={"T0": 48, "T1": 48}),
    }
    props = {
        "tpu.compile.cache.dir": os.path.join(tmp, "xla"),
        "tpu.prewarm.manifest.dir": os.path.join(tmp, "prewarm"),
    }
    app, fleet = build_simulated_fleet(props, clusters=clusters, seed=31)
    shapes = {}
    for cid in ("east", "south"):
        res = fleet.facade(cid).proposals(OperationProgress(), ignore_cache=True)
        shapes[cid] = res.state_before.shape
        # twice: recency touches must dedup, not duplicate
        fleet.facade(cid).proposals(OperationProgress(), ignore_cache=True)
    assert shapes["east"] != shapes["south"], "test needs two distinct buckets"
    store = fleet.core.prewarm_store
    assert store is not None
    store.drain(300)
    keys = store.manifest_bucket_keys()
    assert sorted(keys) == sorted(
        {bucket_key(shapes["east"]), bucket_key(shapes["south"])}
    )
    fleet.shutdown()

    app2, fleet2 = build_simulated_fleet(props, clusters=clusters, seed=31)
    fleet2.start_up(detection_interval_s=3600)
    for cid in ("east", "south"):
        assert fleet2.facade(cid)._boot_prewarm_done.wait(120)
    opt = fleet2.core.optimizer
    assert opt.has_engine_for(shapes["east"]), "east bucket not prewarmed"
    assert opt.has_engine_for(shapes["south"]), "south bucket not prewarmed"
    fleet2.shutdown()


def test_controller_first_cycle_waits_for_boot_gate(tmp_path):
    """Boot-prewarm-under-the-controller satellite: the controller thread
    starts immediately (running=True) but its first cycle waits for the
    boot gate, so manifest compiles are in flight before it takes
    ownership of proposal publishing."""
    tmp = str(tmp_path)
    app, fetcher, admin, sampler = _service(
        {"controller.enabled": True, "controller.poll.interval.ms": 50}, tmp
    )
    cc = app.cc
    ctl = cc.controller
    gate = threading.Event()
    ctl.start(boot_gate=gate)
    assert ctl.running
    parts = sampler.all_partition_entities()
    fetcher.fetch_once(parts, 4000, 4999)  # a rolled window is waiting
    time.sleep(0.5)
    assert ctl._stats["windowRolls"] == 0, "cycle ran before the boot gate"
    gate.set()
    deadline = time.monotonic() + 30
    while ctl._stats["windowRolls"] == 0 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert ctl._stats["windowRolls"] >= 1
    cc.shutdown()
