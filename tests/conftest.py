"""Test config: force an 8-device virtual CPU platform before any compute.

Multi-chip sharding paths are exercised on a virtual device mesh (real TPU
hardware in CI is single-chip; the driver separately dry-runs
__graft_entry__.dryrun_multichip).

Note: the environment's sitecustomize registers/pins the 'axon' TPU
platform at interpreter start, so setting JAX_PLATFORMS here is not enough
— the jax config value itself must be overridden before first backend use.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache (common/compilation_cache.py): the suite
# is compile-dominated — dozens of Engine instances re-compile structurally
# identical programs (jit caches are per-instance, the disk cache is keyed
# by HLO fingerprint) — so both repeat suite runs and same-shape engines
# within one run load executables in ~ms instead of seconds.  Override the
# location with TEST_COMPILE_CACHE=; set it empty to disable.
from cruise_control_tpu.common.compilation_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache(
    os.environ.get("TEST_COMPILE_CACHE", "~/.cache/cruise_control_tpu/xla")
)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: full-stack integration tests (embedded wire cluster)"
    )
