"""Test config: force an 8-device virtual CPU platform before jax imports.

Multi-chip sharding paths are exercised on a virtual device mesh (real TPU
hardware in CI is single-chip; the driver separately dry-runs
__graft_entry__.dryrun_multichip).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
