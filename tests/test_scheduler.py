"""Device scheduler tests: QoS classes, preemption, shed/brownout ladder,
segmented-anneal byte parity, and the overload chaos gate.

The scheduler's whole promise is behavioral: an URGENT fix dispatch waits
at most one slice of background work, BACKGROUND is delayed-but-never-
starved, sheds are counted, brownout degrades instead of skipping, and —
above all — segmentation changes WHEN the device is dispatched, never
WHAT it computes (byte parity) and `fleet.scheduler.enabled=false` is
byte-for-byte today's dispatch.
"""

import threading
import time

import numpy as np
import pytest

from cruise_control_tpu.analyzer.engine import (
    Engine,
    OptimizerConfig,
    SegmentContext,
    segmented_execution,
)
from cruise_control_tpu.analyzer.objective import DEFAULT_CHAIN
from cruise_control_tpu.detector.anomalies import AnomalyType, FleetOverload
from cruise_control_tpu.fleet.scheduler import (
    BackgroundShedError,
    DeviceScheduler,
    SchedulerOverloadError,
    WorkClass,
    effective_class,
    tagged,
)
from cruise_control_tpu.service.tasks import UserTaskManager
from cruise_control_tpu.testing import faults
from cruise_control_tpu.testing.fixtures import small_cluster

FAST = OptimizerConfig(
    num_candidates=256, leadership_candidates=64, steps_per_round=24,
    num_rounds=4, seed=1,
)


def _scheduler(**kw):
    kw.setdefault("slice_budget_s", 0.25)
    kw.setdefault("freshness_slo_s", 2.0)
    kw.setdefault("aging_s", 0.2)
    kw.setdefault("shed_queue_depth", 3)
    kw.setdefault("brownout_after_s", 60.0)
    return DeviceScheduler(**kw)


def _sliced_work(n_slices: int, slice_s: float):
    """A background body shaped like a segmented anneal: n slices of
    device wall with the engine's between-slices checkpoint honored."""
    from cruise_control_tpu.analyzer.engine import current_segment_context

    def body():
        ctx = current_segment_context()
        for i in range(n_slices):
            time.sleep(slice_s)
            if ctx is not None and ctx.checkpoint is not None and i < n_slices - 1:
                ctx.checkpoint()
        return "done"

    return body


# ---------------------------------------------------------------- parity


def test_segmented_anneal_byte_parity():
    """The acceptance pin: a segmented run (1-round slices, checkpoints
    firing) produces byte-identical placements, objectives and per-round
    history to the unsegmented fused run at equal total round budget."""
    state = small_cluster()
    e1 = Engine(state, DEFAULT_CHAIN, config=FAST)
    s1, h1 = e1.run()
    e2 = Engine(state, DEFAULT_CHAIN, config=FAST)
    checkpoints = []
    ctx = SegmentContext(1e-9, checkpoint=lambda: checkpoints.append(1))
    with segmented_execution(ctx):
        s2, h2 = e2.run()
    for f in ("replica_broker", "replica_is_leader", "replica_disk"):
        assert np.array_equal(
            np.asarray(getattr(s1, f)), np.asarray(getattr(s2, f))
        ), f
    r1 = [h for h in h1 if not h.get("timing")]
    r2 = [h for h in h2 if not h.get("timing")]
    assert r1 == r2  # identical round trajectories, early stops included
    t2 = next(h for h in h2 if h.get("timing"))
    assert t2["segmented"] and t2["segments"] > 1
    assert len(checkpoints) == t2["segments"] - 1


@pytest.mark.slow
def test_segmented_warm_start_parity():
    """Segmentation composes with the streaming controller's warm start:
    init_carry_from-seeded runs slice byte-identically too."""
    state = small_cluster()
    base = Engine(state, DEFAULT_CHAIN, config=FAST)
    first, _ = base.run()
    warm = (first.replica_broker, first.replica_is_leader, first.replica_disk)
    e1 = Engine(state, DEFAULT_CHAIN, config=FAST)
    s1, _ = e1.run(initial_placement=warm)
    e2 = Engine(state, DEFAULT_CHAIN, config=FAST)
    with segmented_execution(SegmentContext(1e-9)):
        s2, _ = e2.run(initial_placement=warm)
    for f in ("replica_broker", "replica_is_leader", "replica_disk"):
        assert np.array_equal(
            np.asarray(getattr(s1, f)), np.asarray(getattr(s2, f))
        ), f


def test_no_segment_context_means_unsegmented():
    """Scheduler off == no ambient context == the plain fused program
    (one dispatch, one blocking sync) — today's path, untouched."""
    e = Engine(small_cluster(), DEFAULT_CHAIN, config=FAST)
    _, h = e.run()
    t = next(x for x in h if x.get("timing"))
    assert "segmented" not in t
    assert t["blocking_syncs"] == 1


# ----------------------------------------------------------- scheduling


def test_urgent_preempts_background_within_one_slice():
    sched = _scheduler(slice_budget_s=0.25)
    slice_s = 0.2
    started = threading.Event()

    def background():
        started.set()
        return _sliced_work(6, slice_s)()

    bg = threading.Thread(
        target=lambda: sched.run(WorkClass.BACKGROUND, background, op="bg"),
        daemon=True,
    )
    bg.start()
    assert started.wait(5.0)
    time.sleep(slice_s / 2)  # background is mid-slice now
    t0 = time.monotonic()
    sched.run(WorkClass.URGENT, lambda: None, op="fix")
    urgent_wait = time.monotonic() - t0
    bg.join(10.0)
    assert not bg.is_alive()
    # queue-to-dispatch wait bounded by ONE slice (+ scheduling slack)
    assert urgent_wait <= slice_s + 0.25, urgent_wait
    assert sched.stats["preemptions"] >= 1
    assert sched.stats["sheds"]["urgent"] == 0


def test_background_sheds_under_overload_and_is_counted():
    sched = _scheduler(shed_queue_depth=2)
    release = threading.Event()
    hold = threading.Thread(
        target=lambda: sched.run(
            WorkClass.BACKGROUND, release.wait, op="hold", preemptible=False
        ),
        daemon=True,
    )
    hold.start()
    time.sleep(0.05)
    # fill the queue past the shed depth with (never-granted) waiters
    waiters = [
        threading.Thread(
            target=lambda: sched.run(
                WorkClass.INTERACTIVE, lambda: None, op="w"
            ),
            daemon=True,
        )
        for _ in range(2)
    ]
    for w in waiters:
        w.start()
    time.sleep(0.1)
    with pytest.raises(BackgroundShedError):
        sched.run(WorkClass.BACKGROUND, lambda: None, op="cycle")
    assert sched.stats["sheds"]["background"] == 1
    # urgent is NEVER shed: it queues and runs once the holder releases
    done = []
    urgent = threading.Thread(
        target=lambda: done.append(
            sched.run(WorkClass.URGENT, lambda: "ok", op="fix")
        ),
        daemon=True,
    )
    urgent.start()
    release.set()
    urgent.join(5.0)
    for w in waiters:
        w.join(5.0)
    assert done == ["ok"]
    assert sched.stats["sheds"]["urgent"] == 0


def test_background_ages_past_sustained_interactive_load():
    """Delayed, never starved: under a continuous INTERACTIVE stream, an
    aged BACKGROUND ticket is ranked with the interactive class and its
    older deadline wins the EDF tiebreak."""
    sched = _scheduler(aging_s=0.1, freshness_slo_s=0.8, shed_queue_depth=50)
    stop = threading.Event()
    bg_ran = threading.Event()

    def interactive_storm():
        while not stop.is_set():
            sched.run(WorkClass.INTERACTIVE, lambda: time.sleep(0.02), op="i")

    storm = [
        threading.Thread(target=interactive_storm, daemon=True)
        for _ in range(3)
    ]
    for t in storm:
        t.start()
    time.sleep(0.1)
    bg = threading.Thread(
        target=lambda: (
            sched.run(WorkClass.BACKGROUND, lambda: None, op="bg"),
            bg_ran.set(),
        ),
        daemon=True,
    )
    bg.start()
    ran = bg_ran.wait(10.0)
    stop.set()
    for t in storm:
        t.join(5.0)
    assert ran, "background starved under interactive load"


def test_reentrant_run_and_urgent_tagging():
    sched = _scheduler()
    calls = []

    def inner():
        calls.append("inner")
        return "v"

    def outer():
        # nested run executes inline under the held slot — no deadlock
        return sched.run(WorkClass.INTERACTIVE, inner, op="nested")

    assert sched.run(WorkClass.URGENT, outer, op="outer") == "v"
    assert calls == ["inner"]
    # pipeline tagging upgrades (never downgrades) the dispatch class
    with tagged(WorkClass.URGENT):
        assert effective_class(WorkClass.INTERACTIVE) is WorkClass.URGENT
    with tagged(WorkClass.BACKGROUND):
        assert effective_class(WorkClass.INTERACTIVE) is WorkClass.INTERACTIVE
    assert effective_class(WorkClass.INTERACTIVE) is WorkClass.INTERACTIVE


def test_brownout_after_sustained_overload_and_episode_anomaly():
    clock = {"t": 0.0}
    anomalies = []
    sched = DeviceScheduler(
        slice_budget_s=0.25, freshness_slo_s=2.0, aging_s=10.0,
        shed_queue_depth=1, brownout_after_s=5.0,
        brownout_factor=0.5, clock=lambda: clock["t"],
        anomaly_sink=anomalies.append,
    )
    release = threading.Event()
    hold = threading.Thread(
        target=lambda: sched.run(
            WorkClass.BACKGROUND, release.wait, op="hold", preemptible=False
        ),
        daemon=True,
    )
    hold.start()
    time.sleep(0.05)
    waiter = threading.Thread(
        target=lambda: sched.run(WorkClass.INTERACTIVE, lambda: None, op="w"),
        daemon=True,
    )
    waiter.start()
    time.sleep(0.05)
    # depth 1 >= shed_queue_depth -> overload episode starts; shed fires
    with pytest.raises(BackgroundShedError):
        sched.run(WorkClass.BACKGROUND, lambda: None, op="cycle")
    assert len(anomalies) == 1  # FLEET_OVERLOAD, once
    assert isinstance(anomalies[0], FleetOverload)
    assert anomalies[0].anomaly_type is AnomalyType.FLEET_OVERLOAD
    assert anomalies[0].fixable is False
    assert not sched.brownout_active
    # sustained past brownout.after.s: background now RUNS, browned out
    clock["t"] += 6.0
    cfg = OptimizerConfig(num_candidates=2048, leadership_candidates=512)
    assert sched.brownout_active
    reduced = sched.brownout_config(cfg)
    assert reduced.num_candidates == 1024
    assert reduced.leadership_candidates == 256
    assert reduced.prior_enabled == cfg.prior_enabled
    assert sched.stats["brownout_cycles"] == 1
    # still ONE anomaly for the whole episode
    assert len(anomalies) == 1
    release.set()
    hold.join(5.0)
    waiter.join(5.0)
    # queue drained below half depth -> episode ends; the NEXT episode
    # fires a fresh anomaly
    sched.run(WorkClass.INTERACTIVE, lambda: None, op="drain")
    assert not sched.brownout_active
    release2 = threading.Event()
    hold2 = threading.Thread(
        target=lambda: sched.run(
            WorkClass.BACKGROUND, release2.wait, op="hold2", preemptible=False
        ),
        daemon=True,
    )
    hold2.start()
    time.sleep(0.05)
    w2 = threading.Thread(
        target=lambda: sched.run(WorkClass.INTERACTIVE, lambda: None, op="x"),
        daemon=True,
    )
    w2.start()
    time.sleep(0.05)
    with pytest.raises(BackgroundShedError):
        sched.run(WorkClass.BACKGROUND, lambda: None, op="cycle2")
    assert len(anomalies) == 2
    release2.set()
    hold2.join(5.0)
    w2.join(5.0)


def test_interactive_admission_429_with_retry_after():
    sched = _scheduler(shed_queue_depth=1)
    release = threading.Event()
    hold = threading.Thread(
        target=lambda: sched.run(
            WorkClass.BACKGROUND, release.wait, op="hold", preemptible=False
        ),
        daemon=True,
    )
    hold.start()
    time.sleep(0.05)
    waiters = [
        threading.Thread(
            target=lambda: sched.run(
                WorkClass.INTERACTIVE, lambda: None, op="w"
            ),
            daemon=True,
        )
        for _ in range(2)
    ]
    for w in waiters:
        w.start()
    time.sleep(0.1)
    # queue >= 2x depth: severe overload -> 429 + Retry-After
    with pytest.raises(SchedulerOverloadError) as ei:
        sched.admit_interactive(default_retry_after_s=7.0)
    assert ei.value.retry_after_s >= 1.0
    assert sched.stats["sheds"]["interactive"] == 1
    release.set()
    hold.join(5.0)
    for w in waiters:
        w.join(5.0)


def test_abandoned_preempted_ticket_does_not_wedge_scheduler():
    """Regression (review): the DeviceSupervisor abandons a timed-out
    dispatch on the CALLER thread while its worker sits paused in a
    preemption checkpoint.  The release must pull the paused ticket out
    of the queue and cancel it — otherwise the zombie worker later
    re-acquires the slot with nobody left to release it and every
    subsequent dispatch blocks forever."""
    import contextvars

    from cruise_control_tpu.analyzer.engine import current_segment_context

    sched = _scheduler(slice_budget_s=0.1)
    bg_started = threading.Event()
    go_checkpoint = threading.Event()
    urgent_release = threading.Event()
    urgent_started = threading.Event()
    worker_done = threading.Event()
    bg_error = []

    def bg_fn():
        ctx = current_segment_context()
        cvctx = contextvars.copy_context()

        def worker():
            # the supervisor-worker twin: checkpoint once the urgent
            # ticket is queued — it pauses us and hands over the slot
            go_checkpoint.wait(5.0)
            cvctx.run(ctx.checkpoint)
            worker_done.set()

        threading.Thread(target=worker, daemon=True).start()
        bg_started.set()
        # caller side: once the urgent holder owns the slot (our worker
        # is paused), "time out" like DeviceSupervisor._bounded would
        assert urgent_started.wait(5.0)
        time.sleep(0.1)
        raise TimeoutError("supervisor abandoned this dispatch")

    def run_bg():
        try:
            sched.run(WorkClass.BACKGROUND, bg_fn, op="bg")
        except TimeoutError as e:
            bg_error.append(e)

    bg = threading.Thread(target=run_bg, daemon=True)
    bg.start()
    assert bg_started.wait(5.0)
    urgent_t = threading.Thread(
        target=lambda: sched.run(
            WorkClass.URGENT,
            lambda: (urgent_started.set(), urgent_release.wait(10.0)),
            op="fix",
        ),
        daemon=True,
    )
    urgent_t.start()
    time.sleep(0.1)  # the urgent ticket is queued behind the bg holder
    go_checkpoint.set()
    bg.join(10.0)
    assert not bg.is_alive() and bg_error, "background run never unwound"
    assert worker_done.wait(5.0), "paused worker never released"
    urgent_release.set()
    urgent_t.join(5.0)
    # the scheduler is NOT wedged: a fresh dispatch completes promptly
    done = []
    probe = threading.Thread(
        target=lambda: done.append(
            sched.run(WorkClass.INTERACTIVE, lambda: "ok", op="probe")
        ),
        daemon=True,
    )
    probe.start()
    probe.join(5.0)
    assert done == ["ok"], "scheduler wedged after abandoned preemption"


def test_supervisor_hang_budget_excludes_scheduler_pause():
    """Regression (review): time a preempted dispatch spends parked at a
    checkpoint is the scheduler doing its job — it must extend the
    DeviceSupervisor's hang deadline, not consume it."""
    from cruise_control_tpu.common.device_watchdog import (
        DeviceDegradedError,
        DeviceSupervisor,
        pause_clock_scope,
    )

    sup = DeviceSupervisor(op_timeout_s=0.4, max_retries=0)
    pause = {"s": 0.0}

    def paused_fn():
        time.sleep(0.2)
        pause["s"] += 0.5  # "the scheduler paused us for 0.5s"
        time.sleep(0.4)
        return "ok"

    with pause_clock_scope(lambda: pause["s"]):
        # 0.6s wall against a 0.4s budget, but 0.5s of it is pause
        assert sup.call(paused_fn, op="optimize") == "ok"
    # without a pause clock the same wall is a genuine hang
    with pytest.raises(DeviceDegradedError):
        sup.call(lambda: time.sleep(0.6) or "ok", op="optimize")


def test_ticket_pause_clock_includes_in_progress_pause():
    """Regression (review round 2): a pause still in progress must be
    visible to the supervisor's pause clock — a single pause longer than
    the remaining hang budget would otherwise still trip
    DeviceHangError."""
    from cruise_control_tpu.fleet.scheduler import _Ticket

    clock = {"t": 0.0}
    sched = DeviceScheduler(slice_budget_s=0.1, clock=lambda: clock["t"])
    t = _Ticket(WorkClass.BACKGROUND, "", "x", enqueued=0.0, deadline=1.0,
                seq=0)
    t.paused_s = 2.0
    assert sched._ticket_pause_s(t) == 2.0
    t.pause_started = clock["t"]
    clock["t"] += 3.0
    assert sched._ticket_pause_s(t) == 5.0  # 2 completed + 3 in progress


def test_precompute_refresh_is_background_class():
    """The periodic proposal refresh is exactly the steady-state load
    the shed ladder exists to relieve — it must dispatch BACKGROUND."""
    from cruise_control_tpu.service.main import build_simulated_service
    from cruise_control_tpu.service.progress import OperationProgress

    app, fetcher, admin, sampler = build_simulated_service(
        _scheduler_service_config()
    )
    try:
        cc = app.cc
        cc.proposals(
            OperationProgress(), ignore_cache=True,
            work_class=WorkClass.BACKGROUND,
        )
        assert cc.scheduler.stats["dispatches"]["background"] == 1
        assert cc.scheduler.stats["dispatches"]["interactive"] == 0
    finally:
        app.stop()


def test_cluster_override_of_shared_scheduler_and_tenant_keys_rejected():
    from cruise_control_tpu.config.app_config import (
        ConfigException,
        CruiseControlConfig,
    )

    base = {"fleet.clusters": "east"}
    # the per-cluster freshness SLO IS overridable...
    cfg = CruiseControlConfig(
        {**base, "fleet.east.fleet.scheduler.freshness.slo.s": 10.0,
         "fleet.scheduler.freshness.slo.s": 45.0}
    )
    assert cfg.cluster_config("east").get(
        "fleet.scheduler.freshness.slo.s"
    ) == 10.0
    # ...every other scheduler/tenant knob configures the ONE shared
    # scheduler/purgatory and must be rejected, not silently ignored
    for key in (
        "fleet.east.fleet.scheduler.slice.budget.s",
        "fleet.east.fleet.tenant.retry.after.s",
    ):
        with pytest.raises(ConfigException):
            CruiseControlConfig({**base, key: 2.0}).cluster_config("east")


# ----------------------------------------------- Retry-After (admission)


def test_tenant_retry_after_drain_rate_and_fallback():
    m = UserTaskManager(num_threads=2)
    try:
        # no history: config default wins
        assert m.retry_after_s("east", default_s=7.0) == 7.0
        # fabricate a drain history: 5 completions 1s apart -> 1 task/s
        import collections

        stamps = collections.deque(maxlen=32)
        base = time.monotonic()
        for i in range(5):
            stamps.append(base + i)
        m._completions["east"] = stamps
        gate = threading.Event()
        for _ in range(3):
            m.submit("proposals", lambda p: gate.wait(5.0), cluster_id="east")
        ra = m.retry_after_s("east", default_s=7.0)
        # 3 pending / 1 per second ~ 3s (never below 1, never 300)
        assert 2.0 <= ra <= 4.0, ra
        gate.set()
    finally:
        m.shutdown()


def test_tenant_overload_error_carries_retry_after():
    m = UserTaskManager(num_threads=2)
    try:
        gate = threading.Event()
        m.submit("proposals", lambda p: gate.wait(5.0), cluster_id="east",
                 cluster_max_active=1)
        from cruise_control_tpu.service.tasks import TenantOverloadError

        with pytest.raises(TenantOverloadError):
            m.submit("proposals", lambda p: None, cluster_id="east",
                     cluster_max_active=1)
        gate.set()
    finally:
        m.shutdown()


# --------------------------------------------------- slowdown injector


def test_device_slowdown_scales_wall_and_restores():
    from cruise_control_tpu.common.device_watchdog import device_op
    from cruise_control_tpu.common import device_watchdog as wd

    calls = []

    @device_op("engine.run")
    def fake_run():
        calls.append(1)
        time.sleep(0.05)
        return 42

    t0 = time.monotonic()
    assert fake_run() == 42
    base = time.monotonic() - t0

    with faults.device_slowdown(3.0) as log:
        t0 = time.monotonic()
        assert fake_run() == 42
        slowed = time.monotonic() - t0
    assert log.calls.get("engine.run") == 1
    assert log.fired.get("engine.run") == 1
    # ~3x the observed wall (generous bounds for CI noise)
    assert slowed >= 2.0 * base
    # nest-safe restore: the hook is gone, walls are back to normal
    assert wd._DEVICE_OP_HOOK is None
    t0 = time.monotonic()
    fake_run()
    assert time.monotonic() - t0 < 2.0 * base + 0.05


def test_device_slowdown_nests_inside_other_injectors():
    from cruise_control_tpu.common.device_watchdog import device_op

    @device_op("engine.run")
    def fake_run():
        return "ok"

    @device_op("probe")
    def fake_probe():
        return "probe"

    with faults.device_slowdown(1.5) as outer:
        with faults.device_slowdown(
            1.5, ops=("probe",)
        ) as inner:
            assert fake_probe() == "probe"
            assert fake_run() == "ok"
    assert inner.calls.get("probe") == 1
    assert outer.calls.get("engine.run") == 1
    assert outer.calls.get("probe") is None  # inner consumed it first


def test_device_slowdown_rejects_bad_factor():
    with pytest.raises(ValueError):
        with faults.device_slowdown(0.5):
            pass


# -------------------------------------------------------- chaos gate


@pytest.mark.slow
def test_overload_chaos_gate_urgent_wait_bounded():
    """The acceptance soak: under device_slowdown x a 20-cluster synthetic
    burst, an injected broker-failure-fix dispatch's queue-to-dispatch
    wait stays <= one slice budget, BACKGROUND cycles shed (counted),
    zero URGENT sheds, and FLEET_OVERLOAD fires exactly once for the
    episode."""
    from cruise_control_tpu.common.device_watchdog import device_op

    anomalies = []
    slice_s = 0.1
    sched = DeviceScheduler(
        slice_budget_s=slice_s * 1.5, freshness_slo_s=1.0, aging_s=0.5,
        shed_queue_depth=6, brownout_after_s=120.0,
        anomaly_sink=anomalies.append,
    )

    @device_op("engine.run")
    def device_cycle():
        # one "anneal slice" of device wall; the injector scales it
        time.sleep(0.02)

    def background_cycle():
        from cruise_control_tpu.analyzer.engine import current_segment_context

        ctx = current_segment_context()
        for i in range(3):
            device_cycle()
            if ctx is not None and ctx.checkpoint is not None and i < 2:
                ctx.checkpoint()

    shed = [0]
    urgent_waits = []
    stop = threading.Event()

    def cluster_loop(cid):
        while not stop.is_set():
            try:
                sched.run(
                    WorkClass.BACKGROUND, background_cycle,
                    cluster_id=f"c{cid}", op="cycle",
                )
            except BackgroundShedError:
                shed[0] += 1
                time.sleep(0.02)

    with faults.device_slowdown(3.0) as log:
        threads = [
            threading.Thread(target=cluster_loop, args=(i,), daemon=True)
            for i in range(20)
        ]
        for t in threads:
            t.start()
        time.sleep(0.3)  # let the burst overload the queue
        for _ in range(5):
            t0 = time.monotonic()
            sched.run(
                WorkClass.URGENT, device_cycle, cluster_id="cX",
                op="fix:broker-failure",
            )
            urgent_waits.append(time.monotonic() - t0 - 0.02 * 3.0)
            time.sleep(0.1)
        stop.set()
        for t in threads:
            t.join(5.0)
    assert log.total_calls > 0  # the slowdown actually hit device ops
    # URGENT p99 (here: max of 5) queue-to-dispatch wait <= one slice
    # budget — one slowed background slice (0.06s) + scheduling slack
    assert max(urgent_waits) <= sched.slice_budget_s + 0.1, urgent_waits
    assert sched.stats["sheds"]["urgent"] == 0
    assert shed[0] >= 1, "background never shed under the burst"
    assert sched.stats["sheds"]["background"] == shed[0]
    episodes = sched.stats["overload_episodes"]
    assert len(anomalies) == episodes >= 1
    assert all(a.anomaly_type is AnomalyType.FLEET_OVERLOAD for a in anomalies)


# ------------------------------------------------ service integration


def _scheduler_service_config(**extra):
    from cruise_control_tpu.config.app_config import CruiseControlConfig

    props = {
        "partition.metrics.window.ms": 1000,
        "min.samples.per.partition.metrics.window": 1,
        "num.partition.metrics.windows": 3,
        "execution.progress.check.interval.ms": 100,
        "webserver.http.port": 0,
        "tpu.num.candidates": 128,
        "tpu.leadership.candidates": 32,
        "tpu.steps.per.round": 16,
        "tpu.num.rounds": 2,
        # memory note: prewarm threads + pytest teardown don't mix
        "tpu.prewarm.enabled": "false",
        "fleet.scheduler.enabled": "true",
        "fleet.scheduler.slice.budget.s": 0.2,
    }
    props.update(extra)
    return CruiseControlConfig(props)


def test_service_proposals_fast_path_unsegmented_when_alone():
    # A lone INTERACTIVE proposals call takes the scheduler's fast path:
    # with nobody else queued there is no one to preempt for, so the
    # grant runs the plain unsegmented fused program.
    from cruise_control_tpu.service.main import build_simulated_service
    from cruise_control_tpu.service.progress import OperationProgress

    app, fetcher, admin, sampler = build_simulated_service(
        _scheduler_service_config()
    )
    try:
        cc = app.cc
        assert cc.scheduler is not None
        result = cc.proposals(OperationProgress(), ignore_cache=True)
        timing = next(h for h in result.history if h.get("timing"))
        assert timing.get("segmented") is not True
        assert cc.scheduler.stats["dispatches"]["interactive"] == 1
        assert cc.scheduler.stats["fast_path_grants"] == 1
        # published-proposal age surfaces on the gauge and /fleet rollup
        age = cc.sensors.snapshot()["analyzer.proposal-age-seconds"]["value"]
        assert age >= 0.0
        from cruise_control_tpu.fleet.manager import shared_core_rollup

        shared = shared_core_rollup(cc.core)
        assert shared["scheduler"]["enabled"] is True
        assert shared["scheduler"]["dispatches"]["interactive"] == 1
        assert shared["scheduler"]["fastPathGrants"] == 1
    finally:
        app.stop()


def test_service_proposals_run_segmented_with_fast_path_off():
    from cruise_control_tpu.service.main import build_simulated_service
    from cruise_control_tpu.service.progress import OperationProgress

    app, fetcher, admin, sampler = build_simulated_service(
        _scheduler_service_config(
            **{"fleet.scheduler.fast.path.enabled": "false"}
        )
    )
    try:
        cc = app.cc
        assert cc.scheduler is not None
        result = cc.proposals(OperationProgress(), ignore_cache=True)
        timing = next(h for h in result.history if h.get("timing"))
        assert timing.get("segmented") is True
        assert cc.scheduler.stats["dispatches"]["interactive"] == 1
        assert cc.scheduler.stats["fast_path_grants"] == 0
    finally:
        app.stop()


def test_scheduler_default_off_is_todays_path():
    from cruise_control_tpu.service.main import build_simulated_service
    from cruise_control_tpu.service.progress import OperationProgress

    app, fetcher, admin, sampler = build_simulated_service(
        _scheduler_service_config(**{"fleet.scheduler.enabled": "false"})
    )
    try:
        cc = app.cc
        assert cc.scheduler is None
        result = cc.proposals(OperationProgress(), ignore_cache=True)
        timing = next(h for h in result.history if h.get("timing"))
        # the plain fused program: one dispatch, one blocking sync,
        # nothing segmented — byte-for-byte today's dispatch
        assert "segmented" not in timing
        assert timing["blocking_syncs"] == 1
        from cruise_control_tpu.fleet.manager import shared_core_rollup

        assert "scheduler" not in shared_core_rollup(cc.core)
    finally:
        app.stop()


def test_self_healing_fix_dispatches_urgent():
    from cruise_control_tpu.service.main import build_simulated_service

    app, fetcher, admin, sampler = build_simulated_service(
        _scheduler_service_config()
    )
    try:
        cc = app.cc
        assert cc.actions.rebalance("test-fix") is True
        assert cc.scheduler.stats["dispatches"]["urgent"] >= 1
        assert cc.scheduler.stats["sheds"]["urgent"] == 0
    finally:
        app.stop()


def test_controller_cycle_sheds_counted(monkeypatch):
    """A shed controller cycle is counted and skipped — never silent,
    never a crash."""
    from cruise_control_tpu.service.main import build_simulated_service
    from cruise_control_tpu.fleet.scheduler import BackgroundShedError

    app, fetcher, admin, sampler = build_simulated_service(
        _scheduler_service_config(**{"controller.enabled": "true"})
    )
    try:
        cc = app.cc
        ctrl = cc.controller
        assert ctrl is not None

        def always_shed(work_class, fn, **kw):
            if work_class is WorkClass.BACKGROUND:
                cc.scheduler.shed_background(op=kw.get("op", ""))
                raise BackgroundShedError("injected")
            return fn()

        monkeypatch.setattr(cc.scheduler, "run", always_shed)
        info = ctrl.run_once()
        assert info is not None and info.get("shed") is True
        assert ctrl.state_json()["cyclesShed"] == 1
        assert cc.sensors.counter("controller.cycles-shed").count == 1
        assert cc.scheduler.stats["sheds"]["background"] == 1
    finally:
        app.stop()


# ----------------------------------------------------- fast-path grants


def test_interactive_fast_path_unsegmented_when_alone():
    """An INTERACTIVE request granted while nothing else waits gets the
    whole device as ONE unsegmented dispatch (no ambient segment context,
    no between-slice preemption checks) — the streaming controller's
    fused cycles ride this.  Explicit preemptible=True and BACKGROUND
    submissions keep today's segmented grants."""
    from cruise_control_tpu.analyzer.engine import current_segment_context

    sched = _scheduler()
    seen = {}

    def body():
        seen["ctx"] = current_segment_context()
        return "ok"

    assert sched.run(WorkClass.INTERACTIVE, body, op="fused-cycle") == "ok"
    assert seen["ctx"] is None
    assert sched.stats["fast_path_grants"] == 1
    # the caller's explicit preemptible choice always wins
    sched.run(WorkClass.INTERACTIVE, body, op="explicit", preemptible=True)
    assert seen["ctx"] is not None
    assert sched.stats["fast_path_grants"] == 1
    # BACKGROUND never fast-paths, alone or not
    sched.run(WorkClass.BACKGROUND, body, op="bg")
    assert seen["ctx"] is not None
    assert sched.stats["fast_path_grants"] == 1
    assert sched.state_json()["fastPathGrants"] == 1


def test_interactive_fast_path_disabled_stays_segmented():
    """fleet.scheduler.fast.path.enabled=false pins the pre-fast-path
    grant behavior byte-for-byte: every non-urgent grant is segmented."""
    from cruise_control_tpu.analyzer.engine import current_segment_context

    sched = _scheduler(fast_path_enabled=False)
    seen = {}

    def body():
        seen["ctx"] = current_segment_context()

    sched.run(WorkClass.INTERACTIVE, body, op="solo")
    assert seen["ctx"] is not None
    assert sched.stats["fast_path_grants"] == 0
    assert sched.state_json()["fastPathGrants"] == 0
