"""Decision ledger + convergence diagnostics tests: torn-tail
durability, rotation/retention honoring pending outcomes, fleet
two-cluster namespace isolation, the disabled path writing zero bytes,
diagnostics byte-parity across plain/segmented/mesh runs, MODEL_DRIFT
episode discipline, and the decision→outcome→calibration→/explain
acceptance story on the simulated cluster."""

import dataclasses as dc
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from cruise_control_tpu.analyzer.engine import (
    Engine,
    FUSED_DIAG_YS_KEYS,
    FUSED_YS_KEYS,
    OptimizerConfig,
    SegmentContext,
    segmented_execution,
)
from cruise_control_tpu.analyzer.ledger import DecisionLedger
from cruise_control_tpu.analyzer.objective import DEFAULT_CHAIN
from cruise_control_tpu.config.app_config import CruiseControlConfig
from cruise_control_tpu.testing.fixtures import (
    RandomClusterSpec,
    random_cluster_fast,
)

SMALL = RandomClusterSpec(
    num_brokers=12, num_partitions=200, num_racks=4, num_topics=6, skew=1.0
)
CFG = OptimizerConfig(
    num_candidates=128, leadership_candidates=32, swap_candidates=16,
    steps_per_round=8, num_rounds=3, seed=0,
)


def _placements(state):
    return tuple(
        np.asarray(getattr(state, f))
        for f in ("replica_broker", "replica_is_leader", "replica_disk")
    )


def _same_placement(a, b) -> bool:
    return all(bool((x == y).all()) for x, y in zip(_placements(a), _placements(b)))


# ------------------------------------------------------------- store


def test_torn_tail_append_after_truncate(tmp_path):
    """A crash-torn final line must neither poison replay nor glue onto
    the next append: reopening truncates back to the last valid record,
    and the episode written after the tear joins cleanly."""
    path = tmp_path / "decision-ledger.jsonl"
    led = DecisionLedger(str(path))
    did = led.record_decision({"source": "test", "goals": {}})
    led.close()
    with open(path, "ab") as f:
        f.write(b'{"t": "outco')  # torn mid-record
    # replay of the torn file trusts only the complete prefix
    led2 = DecisionLedger(str(path))
    assert [r["t"] for r in led2.replay()] == ["decision"]
    # appending repairs the tear first: the outcome joins its decision
    led2.record_outcome(did, {"completed": 3})
    entries = led2.entries()
    assert len(entries) == 1
    assert entries[0]["decision"]["id"] == did
    assert entries[0]["outcome"]["completed"] == 3
    # the file holds exactly two valid lines — no half-line remains
    lines = path.read_bytes().splitlines()
    assert len(lines) == 2
    for line in lines:
        json.loads(line)


def test_rotation_and_retention_respect_pending_outcomes(tmp_path):
    """The live file never rotates while a decision in it awaits its
    outcome, and prune_archives never deletes an archive holding a
    pending episode."""
    path = tmp_path / "decision-ledger.jsonl"
    led = DecisionLedger(str(path), rotate_records=2, retention_count=1)
    d1 = led.record_decision({"source": "test"})
    led.begin_outcome(d1)
    led.record_decision({"source": "test"})
    # live file is at the rotation bound but d1's outcome is pending:
    # the next decision must NOT rotate it away
    d3 = led.record_decision({"source": "test"})
    assert led._archives() == []
    assert {e["decision"]["id"] for e in led.entries()} >= {d1, d3}
    # outcome lands -> the following decision rotates the full file
    led.record_outcome(d1, {"completed": 1})
    led.record_decision({"source": "test"})
    assert len(led._archives()) == 1
    # retention: a pending episode inside an archive is sacrosanct
    d5 = led.record_decision({"source": "test"})
    led.begin_outcome(d5)
    led.record_decision({"source": "test"})
    led.record_decision({"source": "test"})  # would rotate, but d5 pending
    # force the bookkeeping: rotate only once d5 resolves
    led.record_outcome(d5, {"completed": 1})
    led.record_decision({"source": "test"})
    archives = led._archives()
    assert len(archives) >= 1
    # prune with an artificially pending id living in the oldest archive
    oldest = archives[-1][1]
    ids_in_oldest = {
        r["id"] for r in DecisionLedger._replay_file(oldest)
        if r.get("t") == "decision"
    }
    led.begin_outcome(next(iter(ids_in_oldest)))
    assert led.prune_archives() == 0 or os.path.exists(oldest)
    assert os.path.exists(oldest)
    # resolving it makes the archive prunable again
    for i in ids_in_oldest:
        led.record_outcome(i, {"completed": 0})
    led.prune_archives()
    assert len(led._archives()) <= led.retention_count


def test_entries_join_newest_first(tmp_path):
    led = DecisionLedger(str(tmp_path / "l.jsonl"))
    a = led.record_decision({"source": "a"})
    b = led.record_decision({"source": "b"})
    led.record_outcome(b, {"completed": 2})
    led.record_calibration(b, {"error": {"goalMaxAbs": 0.1}})
    entries = led.entries(limit=10)
    assert [e["decision"]["id"] for e in entries] == [b, a]
    assert entries[0]["calibration"]["error"]["goalMaxAbs"] == 0.1
    assert entries[1]["outcome"] is None
    assert led.find(decision_id=a)["decision"]["source"] == "a"
    assert led.find(decision_id="nope") is None


# ------------------------------------------- convergence diagnostics


def test_diagnostics_byte_parity_plain_and_history_schema():
    state = random_cluster_fast(SMALL, seed=3)
    off, hist_off = Engine(state, DEFAULT_CHAIN, config=CFG).run()
    on, hist_on = Engine(
        state, DEFAULT_CHAIN, config=dc.replace(CFG, diagnostics=True)
    ).run()
    assert _same_placement(off, on)
    rounds_off = [h for h in hist_off if not h.get("timing")]
    rounds_on = [h for h in hist_on if not h.get("timing")]
    assert len(rounds_off) == len(rounds_on)
    # the off path reports today's records bit-for-bit (no diag fields)
    for rec in rounds_off:
        assert "goal_violations" not in rec and "objective" not in rec
    assert "convergence" not in next(h for h in hist_off if h.get("timing"))
    # the on path carries the full per-round diagnostics
    n_goals = len(DEFAULT_CHAIN.goals)
    for rec in rounds_on:
        assert len(rec["goal_violations"]) == n_goals
        assert set(rec["accepted_by_kind"]) == {"replica", "swap", "leadership"}
        assert rec["accepted"] == sum(rec["accepted_by_kind"].values())
        assert rec["prior"] == {"candidates": 0, "accepted": 0}  # prior off
    conv = next(h for h in hist_on if h.get("timing"))["convergence"]
    assert conv["rounds"] == len(rounds_on)
    assert len(conv["objective_trajectory"]) == conv["rounds"]
    assert conv["goal_names"] == DEFAULT_CHAIN.names()
    assert len(conv["final_goal_violations"]) == n_goals
    # the trajectory is a real anneal: monotone-ish improvement start->end
    assert conv["objective_trajectory"][-1] <= conv["objective_trajectory"][0]


def test_diagnostics_byte_parity_segmented():
    state = random_cluster_fast(SMALL, seed=5)
    base, _ = Engine(
        state, DEFAULT_CHAIN, config=dc.replace(CFG, diagnostics=True)
    ).run()
    eng = Engine(state, DEFAULT_CHAIN, config=dc.replace(CFG, diagnostics=True))
    with segmented_execution(SegmentContext(slice_budget_s=1e-4)):
        seg, hist = eng.run()
    assert _same_placement(base, seg)
    timing = next(h for h in hist if h.get("timing"))
    assert timing["segmented"] and timing["segments"] >= 2
    conv = timing["convergence"]
    assert conv["rounds"] >= 1 and conv["goal_names"] == DEFAULT_CHAIN.names()


def test_diagnostics_byte_parity_mesh():
    import jax

    from cruise_control_tpu.parallel.mesh import MeshEngine, model_mesh

    state = random_cluster_fast(SMALL, seed=7)
    off, _ = Engine(state, DEFAULT_CHAIN, config=CFG).run()
    me = MeshEngine(
        state, DEFAULT_CHAIN, mesh=model_mesh(jax.devices()),
        config=dc.replace(CFG, diagnostics=True),
    )
    mstate, mhist = me.run()
    assert _same_placement(off, mstate)
    timing = next(h for h in mhist if h.get("timing"))
    assert timing["convergence"]["rounds"] >= 1
    rounds = [h for h in mhist if not h.get("timing")]
    assert all("goal_violations" in r and "accepted_by_kind" in r for r in rounds)


def test_diag_ys_key_constants_are_consistent():
    assert set(FUSED_YS_KEYS) < set(FUSED_DIAG_YS_KEYS)
    eng_off = Engine(
        random_cluster_fast(SMALL, seed=3), DEFAULT_CHAIN, config=CFG
    )
    eng_on = Engine(
        random_cluster_fast(SMALL, seed=3), DEFAULT_CHAIN,
        config=dc.replace(CFG, diagnostics=True),
    )
    assert eng_off._ys_keys() == FUSED_YS_KEYS
    assert eng_on._ys_keys() == FUSED_DIAG_YS_KEYS


# --------------------------------------------------------- service


def _ledger_service(tmp_path, extra=None, seed=11):
    from cruise_control_tpu.service.main import build_simulated_service

    props = {
        "partition.metrics.window.ms": 1000,
        "min.samples.per.partition.metrics.window": 1,
        "num.partition.metrics.windows": 3,
        "execution.progress.check.interval.ms": 100,
        "webserver.http.port": 0,
        "tpu.num.candidates": 128,
        "tpu.leadership.candidates": 32,
        "tpu.steps.per.round": 16,
        "tpu.num.rounds": 2,
        "executor.journal.dir": str(tmp_path / "journal"),
        "tpu.prewarm.enabled": "false",
    }
    props.update(extra or {})
    return build_simulated_service(CruiseControlConfig(props), seed=seed)


def test_decision_outcome_calibration_explain_acceptance(tmp_path):
    """The acceptance story: one rebalance executed on the simulated
    cluster yields a ledger with linked decision → outcome → calibration
    records, and GET /explain replays it."""
    from cruise_control_tpu.service.progress import OperationProgress

    app, fetcher, admin, sampler = _ledger_service(tmp_path)
    cc = app.cc
    assert cc.ledger is not None  # derived from executor.journal.dir
    result = cc.proposals(OperationProgress(), ignore_cache=True)
    did = cc._ledger_decision_id(result)
    assert did is not None
    out = cc.rebalance(OperationProgress(), dryrun=False)
    assert out["execution"]["completed"] > 0
    entry = cc.ledger.find(decision_id=did)
    assert entry["outcome"] is not None
    assert entry["outcome"]["completed"] == out["execution"]["completed"]
    assert entry["outcome"]["fencedAbort"] is False
    assert entry["calibration"] is None  # no window rolled yet
    # decision features: goals, predicted load, moves, convergence
    d = entry["decision"]
    assert d["goals"]["names"] == cc.chain.names()
    assert d["convergence"]["rounds"] >= 1  # diagnostics default-on
    assert d["predictedLoad"]["avg"]
    assert d["moves"] and "destinations" in d["moves"][0]
    # roll the next complete metric window -> calibration joins
    parts = sampler.all_partition_entities()
    fetcher.fetch_once(parts, 5000, 5999)
    assert cc._detect_model_drift() is None  # healthy: no drift anomaly
    entry = cc.ledger.find(decision_id=did)
    assert entry["calibration"] is not None
    err = entry["calibration"]["error"]
    assert err["goalMaxAbs"] >= 0.0 and "load" in err
    assert cc.calibration_state()["samples"] == 1
    # /explain replays the episode (facade + HTTP)
    ex = cc.explain(decision_id=did)
    assert ex["decisionId"] == did
    assert ex["outcome"]["completed"] == out["execution"]["completed"]
    assert ex["calibration"] is not None
    assert len(ex["goalDeltas"]) == len(cc.chain.names())
    with pytest.raises(KeyError):
        cc.explain(decision_id="nope")
    with pytest.raises(ValueError):
        cc.explain()
    app.start()
    try:
        base = f"http://{app.host}:{app.port}{app.prefix}"
        with urllib.request.urlopen(
            base + f"/explain?proposal={did}", timeout=30
        ) as resp:
            payload = json.loads(resp.read())
        assert payload["decisionId"] == did
        from cruise_control_tpu.service.schemas import validate_response

        assert validate_response("explain", payload) == []
        with urllib.request.urlopen(base + "/ledger?limit=5", timeout=30) as resp:
            led = json.loads(resp.read())
        assert led["enabled"] and led["entries"]
        assert validate_response("ledger", led) == []
        # unknown episode -> 404; bare /explain -> 400
        with pytest.raises(urllib.error.HTTPError) as e404:
            urllib.request.urlopen(base + "/explain?proposal=nope", timeout=30)
        assert e404.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as e400:
            urllib.request.urlopen(base + "/explain", timeout=30)
        assert e400.value.code == 400
    finally:
        app.stop()


def test_disabled_path_writes_zero_bytes(tmp_path):
    """analyzer.ledger.enabled=false: no ledger object, no _ledger
    directory, zero bytes — even across a real execution."""
    from cruise_control_tpu.service.progress import OperationProgress

    app, fetcher, admin, sampler = _ledger_service(
        tmp_path, extra={"analyzer.ledger.enabled": "false"}
    )
    cc = app.cc
    assert cc.ledger is None
    cc.rebalance(OperationProgress(), dryrun=False)
    assert not (tmp_path / "journal" / "_ledger").exists()
    assert cc.ledger_entries() == []
    st = cc.state(["analyzer"])
    assert "ledger" not in st["AnalyzerState"]


def test_model_drift_fires_once_per_episode(tmp_path):
    """Sustained prediction error opens ONE MODEL_DRIFT episode; the
    episode re-arms only after the mean error recovers."""
    app, fetcher, admin, sampler = _ledger_service(
        tmp_path,
        extra={
            "analyzer.calibration.drift.threshold": "0.1",
            "analyzer.calibration.drift.min.samples": "2",
        },
    )
    cc = app.cc

    def feed(goal_err, n=2):
        for _ in range(n):
            cc._calibration_errors.append((goal_err, 0.0))

    feed(0.5)
    anom = cc._detect_model_drift()
    assert anom is not None and anom.episode == 1
    assert anom.mean_goal_error > 0.1 and not anom.fixable
    # still burning: the same episode stays silent
    feed(0.6)
    assert cc._detect_model_drift() is None
    assert cc.calibration_state()["driftActive"]
    # recovery re-arms...
    feed(0.0)
    assert cc._detect_model_drift() is None
    assert not cc.calibration_state()["driftActive"]
    # ...and a new burn opens episode 2
    feed(0.7)
    anom2 = cc._detect_model_drift()
    assert anom2 is not None and anom2.episode == 2


def test_controller_first_publish_excluded_from_calibration(tmp_path):
    """The controller's first (cold-compile) publish is calibration-
    ineligible — a restart can never fire a spurious MODEL_DRIFT —
    while later publishes are eligible (mirrors the PR-13 streaming-
    publish SLO exclusion)."""
    app, fetcher, admin, sampler = _ledger_service(
        tmp_path, extra={"controller.enabled": "true"}
    )
    cc = app.cc
    ctl = cc.controller
    parts = sampler.all_partition_entities()
    for w in range(4, 6):
        fetcher.fetch_once(parts, w * 1000, (w + 1) * 1000 - 1)
        assert ctl.run_once() is not None
    entries = cc.ledger_entries()
    flags = [
        e["decision"]["calibrationEligible"]
        for e in entries
        if e["decision"]["source"] == "controller"
    ]
    # newest first: the LAST publish is eligible, the FIRST is not
    assert flags[-1] is False and flags[0] is True


def test_fleet_two_cluster_ledger_isolation(tmp_path):
    """Each fleet cluster owns a namespaced ledger under the journal
    dir: east's decisions never appear in west's ledger (and vice
    versa), and the /fleet rollup carries per-cluster ledger blocks."""
    from cruise_control_tpu.service.main import build_simulated_fleet
    from cruise_control_tpu.service.progress import OperationProgress

    app, fleet = build_simulated_fleet(
        props={
            "fleet.clusters": "east,west",
            "executor.journal.dir": str(tmp_path / "journal"),
            "tpu.prewarm.enabled": "false",
        },
        clusters={
            "east": dict(num_brokers=6, topics={"T0": 12, "T1": 12}),
            "west": dict(num_brokers=6, topics={"T0": 12, "T1": 12}),
        },
    )
    east = fleet.facade("east")
    west = fleet.facade("west")
    assert east.ledger is not None and west.ledger is not None
    assert east.ledger.path != west.ledger.path
    assert os.path.join("_ledger", "east") in east.ledger.path
    r_e = east.proposals(OperationProgress(), ignore_cache=True)
    did_e = east._ledger_decision_id(r_e)
    r_w = west.proposals(OperationProgress(), ignore_cache=True)
    did_w = west._ledger_decision_id(r_w)
    assert did_e and did_w and did_e != did_w
    assert east.ledger.find(decision_id=did_e) is not None
    assert east.ledger.find(decision_id=did_w) is None
    assert west.ledger.find(decision_id=did_w) is not None
    assert west.ledger.find(decision_id=did_e) is None
    # decision records carry their cluster id
    assert east.ledger.find(decision_id=did_e)["decision"]["cluster"] == "east"
    rollup = fleet.fleet_state()
    for cid in ("east", "west"):
        assert rollup["clusters"][cid]["ledger"]["recordsWritten"] >= 1
        assert "calibration" in rollup["clusters"][cid]
