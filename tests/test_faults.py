"""Supervised optimizer runtime: failure classification, circuit breaker,
degraded CPU-greedy serving, and the deterministic fault-injection harness.

Every breaker transition, retry schedule, and degraded proposal asserted
here is driven by injected faults (cruise_control_tpu/testing/faults.py) —
nothing depends on real device misbehavior.  The acceptance test at the
bottom pins the full story: a permanent engine hang degrades `proposals()`
to a bounded greedy answer, /state reports the open breaker, an
OPTIMIZER_DEGRADED anomaly is recorded, and clearing the fault lets the
half-open probe close the breaker and TPU serving resume.
"""

import random
import threading
import time

import numpy as np
import pytest

from cruise_control_tpu.analyzer.engine import OptimizerConfig
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.common.device_watchdog import (
    BreakerState,
    CircuitBreaker,
    DeviceDegradedError,
    DeviceHangError,
    DeviceSupervisor,
    FailureClass,
    classify_failure,
    device_watchdog,
    jittered_backoff_s,
)
from cruise_control_tpu.common.sensors import SensorRegistry
from cruise_control_tpu.config import CruiseControlConfig
from cruise_control_tpu.service.progress import OperationProgress
from cruise_control_tpu.testing import faults
from cruise_control_tpu.testing.fixtures import small_cluster

FAST_CFG = OptimizerConfig(
    num_candidates=64, leadership_candidates=16, swap_candidates=16,
    steps_per_round=8, num_rounds=2,
)


# ------------------------------------------------------------ classification


def test_classification_taxonomy():
    assert classify_failure(ValueError("bad mask")) is None
    assert classify_failure(KeyError("x")) is None
    assert classify_failure(DeviceHangError("optimize", 1.0)) is FailureClass.HANG
    assert classify_failure(MemoryError()) is FailureClass.OOM
    assert classify_failure(faults.transient_error("op")) is FailureClass.TRANSIENT
    assert classify_failure(faults.oom_error("op")) is FailureClass.OOM
    assert classify_failure(faults.compile_error("op")) is FailureClass.COMPILE
    # a plain RuntimeError with no runtime-layer markers is application code
    assert classify_failure(RuntimeError("business logic broke")) is None


def test_jittered_backoff_bounds_and_determinism():
    rng = random.Random(7)
    draws = [
        jittered_backoff_s(a, base_s=0.1, cap_s=1.0, rng=rng) for a in (1, 2, 3, 8)
    ]
    ceilings = [0.1, 0.2, 0.4, 1.0]
    for d, c in zip(draws, ceilings):
        assert 0.0 < d <= c
    # seeded rng pins the exact sequence
    rng2 = random.Random(7)
    assert draws == [
        jittered_backoff_s(a, base_s=0.1, cap_s=1.0, rng=rng2) for a in (1, 2, 3, 8)
    ]


def test_fault_schedule_keying():
    s = faults.FaultSchedule(calls=(0, 2))
    assert [s.fires(n) for n in range(4)] == [True, False, True, False]
    w = faults.FaultSchedule(after=1, limit=2)
    assert [w.fires(n) for n in range(4)] == [False, True, True, False]
    r1 = faults.FaultSchedule(rate=0.5, seed=3)
    r2 = faults.FaultSchedule(rate=0.5, seed=3)
    pattern = [r1.fires(n) for n in range(64)]
    assert pattern == [r2.fires(n) for n in range(64)]  # seeded: reproducible
    assert any(pattern) and not all(pattern)
    assert [faults.first(2).fires(n) for n in range(3)] == [True, True, False]


# ------------------------------------------------------------ circuit breaker


def test_breaker_transitions():
    clock = [0.0]
    b = CircuitBreaker(failure_threshold=2, probe_interval_s=10.0, clock=lambda: clock[0])
    assert b.state is BreakerState.CLOSED
    assert not b.record_failure()
    b.record_success()  # success resets the consecutive count
    assert not b.record_failure()
    assert b.record_failure()  # second consecutive -> opens
    assert b.state is BreakerState.OPEN and b.open_epoch == 1
    assert not b.probe_due()  # interval not elapsed
    clock[0] = 11.0
    assert b.probe_due() and b.begin_probe()
    assert b.state is BreakerState.HALF_OPEN
    b.probe_failed()
    assert b.state is BreakerState.OPEN
    assert not b.probe_due()  # re-armed
    clock[0] = 22.0
    assert b.begin_probe()
    b.probe_succeeded()
    assert b.state is BreakerState.CLOSED and b.consecutive_failures == 0
    # reopen bumps the epoch (edge-trigger for anomaly reporting)
    assert b.record_failure() is False and b.record_failure() is True
    assert b.open_epoch == 2


# ------------------------------------------------------------ supervisor


def test_supervised_hang_is_bounded():
    sup = DeviceSupervisor(op_timeout_s=0.2, breaker_failure_threshold=1)
    t0 = time.monotonic()
    with pytest.raises(DeviceDegradedError) as ei:
        sup.call(lambda: time.sleep(30), op="optimize")
    assert time.monotonic() - t0 < 5.0  # nowhere near the 30s hang
    assert ei.value.failure_class is FailureClass.HANG
    assert sup.breaker.state is BreakerState.OPEN


def test_transient_retries_with_backoff_then_success():
    sleeps: list[float] = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise faults.transient_error("flaky")
        return "ok"

    sensors = SensorRegistry()
    sup = DeviceSupervisor(
        op_timeout_s=5.0, max_retries=2, retry_backoff_s=0.01,
        sensors=sensors, sleep=sleeps.append, rng=random.Random(0),
    )
    assert sup.call(flaky, op="optimize") == "ok"
    assert calls["n"] == 3 and len(sleeps) == 2
    assert all(0 < s <= 0.02 * 2 for s in sleeps)
    assert sup.breaker.state is BreakerState.CLOSED  # success reset it
    assert sensors.counter("analyzer.supervisor.retries").count == 2
    assert sensors.counter("analyzer.supervisor.failures.transient").count == 2


def test_transient_retries_exhausted_counts_one_breaker_failure():
    sup = DeviceSupervisor(
        op_timeout_s=5.0, max_retries=1, retry_backoff_s=0.001,
        breaker_failure_threshold=2, sleep=lambda s: None,
    )

    def always_transient():
        raise faults.transient_error("x")

    with pytest.raises(DeviceDegradedError):
        sup.call(always_transient, op="optimize")
    # two raises (original + retry) but ONE operation-level breaker count
    assert sup.breaker.consecutive_failures == 1
    assert sup.breaker.state is BreakerState.CLOSED


def test_unclassified_errors_propagate_untouched():
    sup = DeviceSupervisor(op_timeout_s=5.0, breaker_failure_threshold=1)

    def bad_request():
        raise ValueError("broker ids [99] are not in the cluster model")

    with pytest.raises(ValueError):
        sup.call(bad_request, op="optimize")
    assert sup.breaker.state is BreakerState.CLOSED
    assert sup.breaker.consecutive_failures == 0


def test_probe_recovery_closes_breaker():
    probe_results = ["wedged", "wedged", None]  # two failed probes, then healthy
    sup = DeviceSupervisor(
        op_timeout_s=0.1, breaker_failure_threshold=1, probe_interval_s=0.0,
        probe=lambda: probe_results.pop(0),
    )
    with pytest.raises(DeviceDegradedError):
        sup.call(lambda: time.sleep(5), op="optimize")
    assert sup.is_degraded
    assert not sup.available()  # probe 1 fails
    assert not sup.available()  # probe 2 fails
    assert sup.available()  # probe 3 heals -> closed
    assert sup.breaker.state is BreakerState.CLOSED
    assert sup.num_probes == 3 and sup.num_probe_failures == 2
    js = sup.state_json()
    assert js["breaker"] == "closed" and js["numProbeFailures"] == 2


def test_device_watchdog_wedges_under_harness():
    with faults.device_wedged(ops=(faults.PROBE_OP,)):
        diagnosis = device_watchdog(timeout_s=0.1)
    assert diagnosis is not None and "did not complete" in diagnosis
    assert device_watchdog(timeout_s=30.0) is None  # fault cleared


# ------------------------------------------------------------ supervised optimizer


def _supervised_optimizer(**sup_kwargs):
    # op_timeout generous: a post-purge rebuild pays a real trace+compile,
    # which must never be misclassified as a hang in these tests
    defaults = dict(
        op_timeout_s=120.0, max_retries=0, breaker_failure_threshold=1,
        probe_interval_s=0.0, probe=lambda: None,
    )
    defaults.update(sup_kwargs)
    sensors = SensorRegistry()
    sup = DeviceSupervisor(sensors=sensors, **defaults)
    opt = GoalOptimizer(
        config=FAST_CFG, supervisor=sup, degraded_budget_s=10.0, sensors=sensors,
    )
    return opt, sup, sensors


def test_injected_oom_degrades_and_recovery_restores_device_path():
    opt, sup, sensors = _supervised_optimizer()
    state = small_cluster()
    with faults.device_oom(schedule=faults.first(1)) as log:
        r = opt.optimize(state)
        assert r.degraded and sup.is_degraded
        assert log.fired["engine.run"] == 1
    rec = r.history[0]
    assert rec["degraded"] and rec["reason"] == "oom"
    assert sensors.counter("analyzer.supervisor.failures.oom").count == 1
    assert sensors.counter("analyzer.degraded-proposals").count == 1
    # the greedy answer is a usable proposal set over the same model
    assert r.summary()["degraded"] is True
    assert r.balancedness_after >= r.balancedness_before - 1e-6
    # fault gone: probe heals on the next call, device path resumes
    r2 = opt.optimize(state)
    assert not r2.degraded and not sup.is_degraded


def test_breaker_open_skips_device_entirely():
    opt, sup, _ = _supervised_optimizer(probe=lambda: "still wedged")
    state = small_cluster()
    with faults.xla_errors(schedule=faults.first(1)) as log:
        assert opt.optimize(state).degraded
        fired_during_fault = log.total_fired
        # breaker is open and the probe keeps failing: no engine invocation
        assert opt.optimize(state).degraded
        assert log.calls.get("engine.run", 0) == fired_during_fault == 1


def test_engine_cache_purged_on_breaker_open():
    opt, sup, _ = _supervised_optimizer()
    state = small_cluster()
    assert not opt.optimize(state).degraded
    assert opt.has_engine_for(state.shape, config=FAST_CFG)
    with faults.xla_errors(schedule=faults.first(1)):
        assert opt.optimize(state).degraded
    # open transition dropped the compiled engines (wedged-device buffers)
    assert not opt.has_engine_for(state.shape, config=FAST_CFG)
    assert not opt.optimize(state).degraded  # rebuilt fresh after recovery
    assert opt.has_engine_for(state.shape, config=FAST_CFG)


def test_degraded_mode_honors_exclusion_masks():
    """A DEGRADED self-healing fix keeps its exclusion contract: the
    greedy fallback never lands replicas or leadership on excluded
    brokers (recently removed/demoted)."""
    from cruise_control_tpu.analyzer.options import OptimizationOptions

    opt, sup, _ = _supervised_optimizer()
    state = small_cluster()
    excl = np.zeros(state.shape.B, bool)
    excl[2] = True
    options = OptimizationOptions(
        excluded_brokers_for_replica_move=excl,
        excluded_brokers_for_leadership=excl,
    )
    r = opt._optimize_degraded(state, options, FAST_CFG, reason="test")
    assert r.degraded
    for p in list(r.proposals):
        assert 2 not in set(p.new_replicas) - set(p.old_replicas)
        if p.new_leader != p.old_leader:
            assert p.new_leader != 2


def test_application_error_propagates_not_degraded():
    import dataclasses

    import jax.numpy as jnp

    opt, sup, _ = _supervised_optimizer()
    state = small_cluster()
    bad_broker = np.asarray(state.replica_broker).copy()
    bad_broker[0] = state.shape.B + 7  # out of range: host validator rejects
    bad = dataclasses.replace(state, replica_broker=jnp.asarray(bad_broker))
    with pytest.raises(ValueError):
        opt.optimize(bad)
    assert not sup.is_degraded  # malformed input must not trip the breaker


# ------------------------------------------------------------ satellites


def test_detector_loop_survives_handler_exceptions():
    from cruise_control_tpu.detector import AnomalyDetector
    from cruise_control_tpu.detector.anomalies import GoalViolations

    class ExplodingNotifier:
        def on_anomaly(self, anomaly):
            raise RuntimeError("notifier crashed")

        def self_healing_enabled(self):
            return {}

    class Actions:
        is_busy = False

    sensors = SensorRegistry()
    det = AnomalyDetector(ExplodingNotifier(), Actions(), sensors=sensors)
    det.register_detector(lambda: GoalViolations(fixable_violations=["DiskUsage"]))
    det.start(interval_s=0.01)
    try:
        deadline = time.monotonic() + 5.0
        while (
            sensors.counter("detector.loop-failures").count < 2
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
    finally:
        det.shutdown()
    # the loop kept ticking across >= 2 failing rounds instead of dying
    assert sensors.counter("detector.loop-failures").count >= 2


def test_kafka_transport_backoff_and_connection_retry():
    from cruise_control_tpu.kafka import protocol as proto
    from cruise_control_tpu.kafka.client import KafkaProtocolError
    from cruise_control_tpu.kafka.transport import KafkaMetricsTransport

    class FakeClient:
        """Scripted broker: responses[i] is an error_code or an exception
        for the i-th Produce."""

        def __init__(self, script):
            self.script = list(script)
            self.produces = 0

        def metadata(self, topics):
            return {"topics": [{
                "name": topics[0], "error_code": 0,
                "partitions": [
                    {"partition_index": 0, "leader_id": 1, "error_code": 0}
                ],
            }]}

        def broker_request(self, node, api, body):
            assert api is proto.PRODUCE
            self.produces += 1
            step = self.script.pop(0)
            if isinstance(step, Exception):
                raise step
            return {"responses": [{"partition_responses": [
                {"error_code": step, "index": 0}
            ]}]}

    sleeps: list[float] = []

    def make(script):
        client = FakeClient(script)
        t = KafkaMetricsTransport(
            client, flush_every=1, rng=random.Random(1), sleep=sleeps.append,
        )
        return client, t

    # NOT_LEADER -> jittered backoff -> reroute succeeds
    client, t = make([6, 0])
    t.send(b"m1")
    assert client.produces == 2 and len(sleeps) == 1
    assert 0 < sleeps[0] <= 0.5

    # transient connection error -> backoff -> retry succeeds
    sleeps.clear()
    client, t = make([ConnectionError("reset"), 0])
    t.send(b"m2")
    assert client.produces == 2 and len(sleeps) == 1

    # double failure surfaces AND the buffer is restored (contract)
    sleeps.clear()
    client, t = make([ConnectionError("reset"), ConnectionError("reset")])
    with pytest.raises(ConnectionError):
        t.send(b"m3")
    assert t._buffer == [b"m3"]

    # hard protocol errors do not retry
    client, t = make([3])
    with pytest.raises(KafkaProtocolError):
        t.send(b"m4")
    assert client.produces == 1 and t._buffer == [b"m4"]


# ------------------------------------------------------------ service-level


@pytest.fixture(scope="module")
def supervised_service():
    """In-process facade with aggressive supervisor timings so breaker
    stories run in seconds (no HTTP listener needed)."""
    from cruise_control_tpu.service.main import build_simulated_service

    config = CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "min.samples.per.partition.metrics.window": 1,
        "num.partition.metrics.windows": 3,
        "execution.progress.check.interval.ms": 100,
        "webserver.http.port": 0,
        "tpu.num.candidates": 128,
        "tpu.leadership.candidates": 32,
        "tpu.steps.per.round": 16,
        "tpu.num.rounds": 2,
        # generous: real compiles on a loaded CI box must never classify
        # as hangs (the acceptance test tightens it around the wedge only)
        "tpu.supervisor.op.timeout.s": 300.0,
        "tpu.supervisor.probe.timeout.s": 0.2,
        "tpu.supervisor.probe.interval.s": 0.0,
        "tpu.supervisor.breaker.failure.threshold": 1,
        "tpu.supervisor.max.retries": 0,
        "tpu.supervisor.degraded.greedy.budget.s": 20.0,
    })
    app, fetcher, admin, sampler = build_simulated_service(config)
    return app.cc


def test_acceptance_permanent_hang_degrades_then_probe_recovers(supervised_service):
    """ISSUE 3 acceptance: injected permanent engine hang =>
    * proposals() returns a valid greedy proposal set within the budget,
    * /state shows analyzer.degraded=true, breaker open,
    * an OPTIMIZER_DEGRADED anomaly is recorded,
    * after the fault clears the probe closes the breaker and the next
      proposal is TPU-backed again."""
    cc = supervised_service
    from cruise_control_tpu.detector.anomalies import AnomalyType

    # healthy warmup: TPU-backed proposals (generous budget — a slow cold
    # compile on a loaded box is not a hang)
    r0 = cc.proposals(OperationProgress(), ignore_cache=True)
    assert not r0.degraded

    # tight budget ONLY while the hang is injected: the wedge fires on the
    # first engine dispatch, so the bounded wait is exactly this budget
    cc.supervisor.op_timeout_s = 5.0
    try:
        with faults.device_wedged():
            t0 = time.monotonic()
            r1 = cc.proposals(OperationProgress(), ignore_cache=True)
            elapsed = time.monotonic() - t0
            # bounded: op budget (5s) + greedy fallback, nowhere near a hang
            assert elapsed < 90.0
            assert r1.degraded
            # a valid proposal set over the live model: every proposal
            # diffs the before placement, and the summary is servable
            summary = r1.summary()
            assert summary["degraded"] is True
            for p in list(r1.proposals)[:10]:
                assert p.old_replicas != p.new_replicas or p.old_leader != p.new_leader
            st = cc.state(["analyzer"])
            assert st["AnalyzerState"]["degraded"] is True
            assert st["AnalyzerState"]["supervisor"]["breaker"] == "open"
            assert st["AnalyzerState"]["supervisor"]["failureCounts"]["hang"] >= 1
            # the detector records the degradation anomaly (edge-triggered)
            records = cc.anomaly_detector.run_once()
            assert any(
                r.anomaly.anomaly_type is AnomalyType.OPTIMIZER_DEGRADED
                for r in records
            )
            # ... once per open episode, not once per round
            assert not any(
                r.anomaly.anomaly_type is AnomalyType.OPTIMIZER_DEGRADED
                for r in cc.anomaly_detector.run_once()
            )
            # still degraded while wedged: the half-open probe fails too
            r2 = cc.proposals(OperationProgress(), ignore_cache=True)
            assert r2.degraded
    finally:
        # recovery pays a fresh trace+compile (caches were purged on open):
        # back to the generous budget
        cc.supervisor.op_timeout_s = 300.0

    # fault cleared: the next call's half-open probe heals the breaker
    r3 = cc.proposals(OperationProgress(), ignore_cache=True)
    assert not r3.degraded
    st = cc.state(["analyzer"])
    assert st["AnalyzerState"]["degraded"] is False
    assert st["AnalyzerState"]["supervisor"]["breaker"] == "closed"


def test_self_healing_fix_failure_is_visible(supervised_service):
    cc = supervised_service
    before = cc.sensors.counter("self-healing.fix-failed").count
    with faults.method_fault(
        cc, "rebalance", faults.raising(lambda: RuntimeError("boom"))
    ):
        assert cc.actions.rebalance("test-reason") is False
    assert cc.sensors.counter("self-healing.fix-failed").count == before + 1
    info = cc.anomaly_detector.detector_state()["lastSelfHealingFixFailure"]
    assert info["operation"] == "rebalance" and "boom" in info["error"]


def test_precompute_loop_counts_consecutive_failures(supervised_service):
    cc = supervised_service
    saved_expiration = cc._proposal_expiration_ms
    cc._proposal_expiration_ms = 20  # 10ms cycle
    cc._stop_precompute.clear()
    t = None
    try:
        with faults.method_fault(
            cc, "proposals", faults.raising(lambda: RuntimeError("model build broke"))
        ), faults.method_fault(cc, "_prewarm_next_bucket", faults.dropping()):
            t = threading.Thread(target=cc._precompute_loop, daemon=True)
            t.start()
            deadline = time.monotonic() + 5.0
            while (
                cc.sensors.counter("analyzer.precompute-failures").count < 3
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
            assert cc.sensors.counter("analyzer.precompute-failures").count >= 3
            assert (
                cc.sensors.gauge("analyzer.precompute-consecutive-failures").value >= 3
            )
            cc._stop_precompute.set()
            t.join(timeout=5)
            assert not t.is_alive()
    finally:
        cc._stop_precompute.set()
        if t is not None:
            t.join(timeout=5)
        cc._proposal_expiration_ms = saved_expiration
