"""Full-stack embedded integration test.

The one test that closes every seam at once, the role of the reference's
CruiseControlIntegrationTestHarness (cruise-control/src/test/java/com/
linkedin/kafka/cruisecontrol/CruiseControlIntegrationTestHarness.java:1-30)
+ ExecutorTest's embedded-cluster runs:

  per-broker MetricsReporter -> KafkaMetricsTransport (wire produce)
    -> fake_kafka reporter topic (live sockets)
    -> CruiseControlMetricsReporterSampler (wire fetch, columnar decode)
    -> MetricFetcherManager -> WindowedMetricSampleAggregator
    -> LoadMonitor -> REST POST /rebalance?dryrun=false
    -> Executor -> KafkaClusterAdmin.AlterPartitionReassignments
    -> fake_kafka topology CHANGES
  and the KafkaSampleStore replays the same history into a fresh
  aggregator ("restart") without re-sampling.

Each seam has its own contract test elsewhere; this exists to catch
cross-seam wiring drift.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from cruise_control_tpu.config.app_config import CruiseControlConfig
from cruise_control_tpu.kafka import KafkaAdminClient
from cruise_control_tpu.kafka.sample_store import KafkaSampleStore
from cruise_control_tpu.kafka.transport import (
    KafkaMetricsConsumer,
    KafkaMetricsTransport,
)
from cruise_control_tpu.monitor.reporter_sampler import (
    CruiseControlMetricsReporterSampler,
)
from cruise_control_tpu.reporter.metrics import MetricType
from cruise_control_tpu.reporter.reporter import (
    MetricsRegistrySnapshotter,
    MetricsReporter,
)
from cruise_control_tpu.testing.fake_kafka import FakeKafkaCluster

WINDOW_MS = 60_000
METRICS_TOPIC = "__CruiseControlMetrics"


def _skewed_cluster() -> FakeKafkaCluster:
    """4 brokers / 2 racks; every replica packed onto brokers 0+1 (brokers
    2 and 3 idle) — a blatant distribution violation the rebalance must fix."""
    parts = {}
    for t, n in (("T0", 8), ("T1", 8)):
        parts[t] = [
            {"partition": p, "leader": p % 2, "replicas": [p % 2, 1 - p % 2]}
            for p in range(n)
        ]
    # the reporter topic exists up front, as on a real cluster
    parts[METRICS_TOPIC] = [
        {"partition": p, "leader": p % 4, "replicas": [p % 4]} for p in range(4)
    ]
    return FakeKafkaCluster(
        brokers={
            0: {"rack": "r0"}, 1: {"rack": "r1"},
            2: {"rack": "r0"}, 3: {"rack": "r1"},
        },
        topics=parts,
    )


def _broker_metric_source(cluster: FakeKafkaCluster, broker_id: int):
    """Live per-broker metrics view: sizes/rates follow the CURRENT fake
    topology (what a real broker's metrics registry would show)."""

    def source():
        topics: dict = {}
        partitions: dict = {}
        for t, pmap in cluster.topics.items():
            led = [p for p in pmap.values() if p["leader"] == broker_id]
            for p in led:
                # partition p of topic t: deterministic size, heavier for T0
                size = 1000.0 * (p["partition"] + 1) * (2.0 if t == "T0" else 1.0)
                partitions[(t, p["partition"])] = size
            if led:
                topics[t] = {
                    MetricType.TOPIC_BYTES_IN: 500.0 * len(led),
                    MetricType.TOPIC_BYTES_OUT: 800.0 * len(led),
                }
        return {
            "broker": {
                MetricType.BROKER_CPU_UTIL: 10.0 + 5.0 * len(partitions),
                MetricType.BROKER_PRODUCE_REQUEST_RATE: 100.0,
            },
            "topics": topics,
            "partitions": partitions,
        }

    return source


@pytest.mark.slow
def test_full_stack_reporter_to_executor_round_trip():
    cluster = _skewed_cluster().start()
    clients: list[KafkaAdminClient] = []

    def new_client() -> KafkaAdminClient:
        c = KafkaAdminClient(cluster.bootstrap(), timeout_s=10.0)
        clients.append(c)
        return c

    try:
        # --- reporter side: one agent per broker over the wire ---
        reporter_client = new_client()
        transport = KafkaMetricsTransport(reporter_client, METRICS_TOPIC)
        reporters = [
            MetricsReporter(
                MetricsRegistrySnapshotter(b, _broker_metric_source(cluster, b)),
                transport,
            )
            for b in range(4)
        ]

        # --- service side: sampler consumes the reporter topic ---
        from cruise_control_tpu.service.main import build_kafka_service

        service_client = new_client()
        sample_store = KafkaSampleStore(
            new_client(),
            topic_name_fn={0: "T0", 1: "T1"}.__getitem__,
            topic_id_fn={"T0": 0, "T1": 1}.__getitem__,
        )
        import tempfile

        journal_dir = tempfile.mkdtemp(prefix="ledger-integ-")
        config = CruiseControlConfig({
            "num.partition.metrics.windows": "2",
            "partition.metrics.window.ms": str(WINDOW_MS),
            "min.samples.per.partition.metrics.window": "1",
            "num.broker.metrics.windows": "2",
            "broker.metrics.window.ms": str(WINDOW_MS),
            "webserver.http.port": "0",
            # durable surfaces: the execution journal + the decision
            # ledger (derived beneath it) record this rebalance's episode
            "executor.journal.dir": journal_dir,
            "tpu.prewarm.enabled": "false",
        })
        from cruise_control_tpu.kafka import KafkaMetadataProvider

        metadata_for_sampler = KafkaMetadataProvider(new_client())
        sampler = CruiseControlMetricsReporterSampler(
            KafkaMetricsConsumer(service_client, METRICS_TOPIC),
            metadata_for_sampler.topology,
        )
        app, fetcher, admin, client = build_kafka_service(
            config, f"127.0.0.1:{cluster.bootstrap()[0][1]}", sampler,
            sample_store=sample_store,
        )
        clients.append(client)

        # --- drive three sampling windows through every seam ---
        parts_fn = app.cc.task_runner.partitions_fn
        entities = parts_fn()
        assert len(entities) == 16
        for w in range(3):
            t_mid = w * WINDOW_MS + WINDOW_MS // 2
            for r in reporters:
                r.report_once(now_ms=t_mid)
            n = fetcher.fetch_once(entities, w * WINDOW_MS, (w + 1) * WINDOW_MS - 1)
            assert n > 0, f"window {w} absorbed no samples"
        # the sampler interned topics in the declared order
        assert sampler._topic_ids == {"T0": 0, "T1": 1}

        # --- REST rebalance, non-dryrun, against the live fake cluster ---
        app.start()
        base = f"http://{app.host}:{app.port}{app.prefix}"

        def req(method, ep, headers=None, **params):
            q = "&".join(f"{k}={v}" for k, v in params.items())
            r = urllib.request.Request(
                f"{base}/{ep}" + (f"?{q}" if q else ""),
                method=method, headers=headers or {},
            )
            with urllib.request.urlopen(r, timeout=120) as resp:
                return resp.status, json.loads(resp.read()), dict(resp.headers)

        def workload_placement():
            return {
                (t, p["partition"]): tuple(p["replicas"])
                for t, pmap in cluster.topics.items()
                if t in ("T0", "T1")
                for p in pmap.values()
            }

        before = workload_placement()
        assert not any(
            2 in r or 3 in r for r in before.values()
        ), "fixture must start with brokers 2/3 empty"

        cluster.auto_complete_after(2)
        status, payload, headers = req("POST", "rebalance", dryrun="false")
        tid = headers.get("User-Task-ID")
        deadline = time.time() + 180
        while status == 202 and time.time() < deadline:
            time.sleep(0.5)
            status, payload, headers = req(
                "POST", "rebalance", headers={"User-Task-ID": tid}, dryrun="false"
            )
        assert status == 200, payload
        assert payload["numReplicaMovements"] > 0
        assert payload["balancednessAfter"] >= payload["balancednessBefore"]
        if "execution" in payload:
            assert payload["execution"]["dead"] == 0

        after = workload_placement()
        assert after != before, "executor must have changed the fake topology"
        touched_brokers = {b for r in after.values() for b in r}
        assert {2, 3} & touched_brokers, "idle brokers must have received replicas"
        # executor really went through the admin path
        st, state, _ = req("GET", "state", substates="executor")
        assert state["ExecutorState"]["numFinishedMovements"] > 0

        # --- flight recorder: ONE trace id covers the whole pipeline ---
        trace_id = payload.get("_traceId")
        assert trace_id, "rebalance response must carry _traceId"
        st, trace, _ = req("GET", "trace", id=trace_id)
        assert st == 200 and trace["traceId"] == trace_id

        def flatten(nodes):
            out = []
            for n in nodes:
                out.append(n)
                out.extend(flatten(n["children"]))
            return out

        spans = flatten(trace["spans"])
        assert {s["traceId"] for s in spans} == {trace_id}
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], s)
        # root: the submitted operation
        assert by_name["service.rebalance"]["parentId"] is None
        # stage 1: monitor model build
        assert "monitor.cluster_model" in by_name
        # stage 2: engine run with the timing record as attributes
        opt_attrs = by_name["analyzer.optimize"]["attributes"]
        assert "device_s" in opt_attrs
        assert "engine_cache_hit" in opt_attrs
        assert "bucket" in opt_attrs
        # stage 3: the supervised device op
        assert by_name["device.optimize"]["component"] == "device"
        # stage 4: execution, with EVERY task transition as span events
        exc = by_name["executor.execution"]
        task_events = [e for e in exc["events"] if e["name"] == "task"]
        ids_seen = {e["id"] for e in task_events}
        assert len(ids_seen) == exc["attributes"]["num_tasks"]
        completed = {
            e["id"] for e in task_events if e["state"] == "COMPLETED"
        }
        assert completed == ids_seen, "every task must reach COMPLETED"
        assert exc["attributes"]["completed"] == len(ids_seen)

        # --- decision ledger: the executed rebalance is one joined
        # decision -> outcome episode, calibrated once the next complete
        # metric window measures the post-move cluster, and GET /explain
        # replays it (analyzer/ledger.py acceptance story) ---
        cc = app.cc
        assert cc.ledger is not None
        episode = cc.ledger.entries(limit=10)
        executed = [e for e in episode if e["outcome"] is not None]
        assert executed, "the executed rebalance must have joined an outcome"
        entry = executed[0]
        did = entry["decision"]["id"]
        assert entry["decision"]["goals"]["names"] == cc.chain.names()
        assert entry["decision"]["convergence"]["rounds"] >= 1
        assert entry["outcome"]["completed"] == exc["attributes"]["completed"]
        # roll the NEXT complete metric window, then calibrate
        t_mid = 3 * WINDOW_MS + WINDOW_MS // 2
        for rep in reporters:
            rep.report_once(now_ms=t_mid)
        fetcher.fetch_once(entities, 3 * WINDOW_MS, 4 * WINDOW_MS - 1)
        cc._detect_model_drift()
        entry = cc.ledger.find(decision_id=did)
        assert entry["calibration"] is not None
        assert entry["calibration"]["error"]["goalMaxAbs"] >= 0.0
        st, explained, _ = req("GET", "explain", proposal=did)
        assert st == 200 and explained["decisionId"] == did
        assert explained["outcome"]["completed"] > 0
        assert explained["calibration"] is not None

        # --- Prometheus exposition over the live service ---
        from cruise_control_tpu.common.exposition import parse_exposition

        r = urllib.request.Request(f"{base}/metrics")
        with urllib.request.urlopen(r, timeout=30) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            fams = parse_exposition(resp.read().decode())
        assert "cruisecontrol_executor_execution_started_total" in fams

        # --- scenario planner against the live fake cluster ---
        def poll(method, ep, **params):
            s, p, h = req(method, ep, **params)
            t_id = h.get("User-Task-ID")
            dl = time.time() + 180
            while s == 202 and time.time() < dl:
                time.sleep(0.5)
                s, p, _ = req(method, ep, headers={"User-Task-ID": t_id}, **params)
            return s, p

        placement_before_planning = workload_placement()
        # compact separators: the raw-URL helper does not percent-encode
        scenarios = json.dumps([
            {"name": "lose-a-broker", "removeBrokers": [3]},
            {"name": "add-two", "addBrokers": [{"count": 2}]},
            {"name": "t0-doubles", "topicLoadFactors": {"T0": 2.0}},
        ], separators=(",", ":"))
        status, sim = poll("POST", "simulate", scenarios=scenarios, optimize="true")
        assert status == 200, sim
        from cruise_control_tpu.service.schemas import validate_response

        assert validate_response("simulate", sim) == []
        by_name = {s["name"]: s for s in sim["scenarios"]}
        base_alive = sim["baseline"]["brokersAlive"]
        assert by_name["lose-a-broker"]["brokersAlive"] == base_alive - 1
        assert by_name["add-two"]["brokersAlive"] == base_alive + 2
        assert by_name["t0-doubles"]["objective"] >= sim["baseline"]["objective"]
        assert by_name["lose-a-broker"]["fix"]["numReplicaMovements"] > 0

        status, rsz = poll("GET", "rightsize")
        assert status == 200, rsz
        assert validate_response("rightsize", rsz) == []
        assert rsz["currentBrokers"] == base_alive
        assert rsz["provisionStatus"] in (
            "RIGHT_SIZED", "OVER_PROVISIONED", "UNDER_PROVISIONED", "UNDECIDED"
        )
        # planning is READ-ONLY: the fake cluster's placement is untouched
        assert workload_placement() == placement_before_planning

        # --- "restart": replay the sample store into a FRESH aggregator ---
        from cruise_control_tpu.monitor import (
            KAFKA_METRIC_DEF,
            MetricFetcherManager,
            WindowedMetricSampleAggregator,
        )

        fresh_agg = WindowedMetricSampleAggregator(
            num_windows=2, window_ms=WINDOW_MS, min_samples_per_window=1,
            metric_def=KAFKA_METRIC_DEF,
        )
        fresh_store = KafkaSampleStore(
            new_client(),
            topic_name_fn={0: "T0", 1: "T1"}.__getitem__,
            topic_id_fn={"T0": 0, "T1": 1}.__getitem__,
        )
        fresh_fetcher = MetricFetcherManager(
            sampler, fresh_agg, None, sample_store=fresh_store
        )
        replayed = fresh_fetcher.load_samples()
        assert replayed > 0
        res = fresh_agg.aggregate()
        assert res.values.shape[1] >= 2  # both completed windows restored
        assert bool(np.any(res.window_valid))

        app.stop()
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        cluster.stop()


@pytest.mark.slow
def test_live_socket_self_healing_broker_crash():
    """Chaos through the live-socket stack (reference RandomSelfHealingTest
    + ExecutorTest-with-killed-embedded-brokers semantics,
    detector/BrokerFailureDetector.java:44):

      kill a fake broker mid-run -> metadata stops listing it (its replica
      assignments remain) -> the SERVICE'S OWN BrokerFailureDetector loop
      fires -> SelfHealingNotifier FIXes -> remove_brokers runs through the
      real facade/optimizer/executor/admin path over sockets -> every
      workload replica is evacuated off the crashed broker.
    """
    parts = {}
    for t in ("T0", "T1"):
        parts[t] = [
            {"partition": p, "leader": p % 4, "replicas": [p % 4, (p + 1) % 4]}
            for p in range(8)
        ]
    # metrics topic lives on broker 0 only: the crash must not orphan it
    parts[METRICS_TOPIC] = [{"partition": 0, "leader": 0, "replicas": [0]}]
    cluster = FakeKafkaCluster(
        brokers={
            0: {"rack": "r0"}, 1: {"rack": "r1"},
            2: {"rack": "r0"}, 3: {"rack": "r1"},
        },
        topics=parts,
    ).start()
    clients: list[KafkaAdminClient] = []

    def new_client() -> KafkaAdminClient:
        c = KafkaAdminClient(cluster.bootstrap(), timeout_s=10.0)
        clients.append(c)
        return c

    try:
        reporter_client = new_client()
        transport = KafkaMetricsTransport(reporter_client, METRICS_TOPIC)
        reporters = [
            MetricsReporter(
                MetricsRegistrySnapshotter(b, _broker_metric_source(cluster, b)),
                transport,
            )
            for b in range(4)
        ]

        from cruise_control_tpu.service.main import build_kafka_service

        config = CruiseControlConfig({
            "num.partition.metrics.windows": "2",
            "partition.metrics.window.ms": str(WINDOW_MS),
            "min.samples.per.partition.metrics.window": "1",
            "num.broker.metrics.windows": "2",
            "broker.metrics.window.ms": str(WINDOW_MS),
            "webserver.http.port": "0",
            "execution.progress.check.interval.ms": "200",
            # self-healing: fire immediately on a detected broker failure
            "self.healing.broker.failure.enabled": "true",
            "broker.failure.alert.threshold.ms": "0",
            "broker.failure.self.healing.threshold.ms": "0",
            "anomaly.detection.interval.ms": "500",
        })
        from cruise_control_tpu.kafka import KafkaMetadataProvider

        sampler = CruiseControlMetricsReporterSampler(
            KafkaMetricsConsumer(new_client(), METRICS_TOPIC),
            KafkaMetadataProvider(new_client()).topology,
        )
        app, fetcher, admin, client = build_kafka_service(
            config, f"127.0.0.1:{cluster.bootstrap()[0][1]}", sampler,
        )
        clients.append(client)

        entities = app.cc.task_runner.partitions_fn()
        assert len(entities) == 16
        for w in range(3):
            t_mid = w * WINDOW_MS + WINDOW_MS // 2
            for r in reporters:
                r.report_once(now_ms=t_mid)
            n = fetcher.fetch_once(entities, w * WINDOW_MS, (w + 1) * WINDOW_MS - 1)
            assert n > 0

        def workload_replicas_on(broker_id: int) -> int:
            return sum(
                broker_id in p["replicas"]
                for t in ("T0", "T1")
                for p in cluster.topics[t].values()
            )

        assert workload_replicas_on(3) > 0

        app.start()
        # reassignments complete after a couple of executor progress polls
        cluster.auto_complete_after(2)
        # the service's own detection loop (not a test harness calling
        # detect()) must notice the crash and drive the fix
        app.cc.start_up(detection_interval_s=0.5)

        cluster.kill_broker(3)

        # evacuated AND the execution drained (the fix compiles a fresh
        # engine for the post-failure shape: allow several minutes on CPU)
        deadline = time.time() + 420
        while time.time() < deadline and (
            workload_replicas_on(3) > 0 or app.cc.executor.has_ongoing_execution
        ):
            time.sleep(0.5)
        det_state = app.cc.anomaly_detector.state.to_json(app.cc.notifier)
        assert workload_replicas_on(3) == 0, (
            f"self-healing did not evacuate the crashed broker; detector "
            f"state: {det_state}"
        )

        # the fix went through the real anomaly pipeline and the executor
        recent = det_state["recentAnomalies"].get("BROKER_FAILURE", [])
        assert any(r["status"].startswith("FIX") for r in recent), det_state
        assert app.cc.executor.tracker.tasks(), "executor executed no tasks"
        # survivors only, and leadership everywhere is on live brokers
        for t in ("T0", "T1"):
            for p in cluster.topics[t].values():
                assert 3 not in p["replicas"]
                assert p["leader"] in (0, 1, 2)

        app.cc.shutdown()
        app.stop()
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        cluster.stop()
