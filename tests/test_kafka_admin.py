"""Kafka wire-protocol adapter tests.

Three layers (reference test strategy SURVEY §4):
  1. codec golden bytes — the encoding pinned against hand-computed frames
     from the public protocol spec (not self-round-trip only);
  2. ClusterAdmin CONTRACT suite — the same assertions run against both
     SimulatedClusterAdmin and KafkaClusterAdmin-over-fake-broker-sockets
     (the embedded-harness analog, CCKafkaIntegrationTestHarness);
  3. executor end-to-end through real sockets: Executor drives
     KafkaClusterAdmin against the fake cluster and the reassignment
     completes via the live progress loop.
"""

import dataclasses

import pytest

from cruise_control_tpu.executor.admin import (
    LeadershipSpec,
    ReassignmentSpec,
    SimulatedClusterAdmin,
)
from cruise_control_tpu.kafka import (
    KafkaAdminClient,
    KafkaClusterAdmin,
    KafkaMetadataProvider,
)
from cruise_control_tpu.kafka import codec, protocol as proto
from cruise_control_tpu.monitor.topology import (
    BrokerNode,
    ClusterTopology,
    PartitionInfo,
    StaticMetadataProvider,
)
from cruise_control_tpu.testing.fake_kafka import FakeKafkaCluster

# ------------------------------------------------------------------ codec


def test_uvarint_roundtrip_and_spec_values():
    for v, expect in [(0, b"\x00"), (1, b"\x01"), (127, b"\x7f"),
                      (128, b"\x80\x01"), (300, b"\xac\x02")]:
        out = bytearray()
        codec.write_uvarint(out, v)
        assert bytes(out) == expect
        got, off = codec.read_uvarint(out, 0)
        assert got == v and off == len(out)


def test_metadata_request_golden_bytes():
    """Metadata v1 request for topic "a", correlation 7, client "cc":
    hand-assembled per the public spec (classic encoding)."""
    frame = proto.encode_request(proto.METADATA, 7, "cc", {"topics": ["a"]})
    expect = (
        b"\x00\x00\x00\x13"          # length = 19
        b"\x00\x03" b"\x00\x01"      # api_key=3, version=1
        b"\x00\x00\x00\x07"          # correlation_id=7
        b"\x00\x02" b"cc"            # client_id
        b"\x00\x00\x00\x01"          # 1 topic
        b"\x00\x01" b"a"             # "a"
    )
    assert frame == expect


def test_alter_reassignments_golden_bytes():
    """AlterPartitionReassignments v0 (flexible: compact arrays + tag
    buffers + header v2)."""
    frame = proto.encode_request(
        proto.ALTER_PARTITION_REASSIGNMENTS, 1, "c",
        {"timeout_ms": 1000,
         "topics": [{"name": "t", "partitions": [
             {"partition_index": 0, "replicas": [1, 2]}]}]},
    )
    expect = (
        b"\x00\x00\x00\x24"              # length = 36
        b"\x00\x2d" b"\x00\x00"          # api_key=45, version=0
        b"\x00\x00\x00\x01"              # correlation
        b"\x00\x01" b"c"                 # client_id (classic in header v2)
        b"\x00"                          # header tag buffer
        b"\x00\x00\x03\xe8"              # timeout_ms=1000
        b"\x02"                          # compact array: 1 topic (len+1)
        b"\x02" b"t"                     # compact string "t"
        b"\x02"                          # 1 partition
        b"\x00\x00\x00\x00"              # partition_index=0
        b"\x03"                          # compact nullable array: 2 replicas
        b"\x00\x00\x00\x01" b"\x00\x00\x00\x02"
        b"\x00" b"\x00" b"\x00"          # partition/topic/request tag buffers
    )
    assert frame == expect


def test_all_schemas_roundtrip():
    """Every API's request+response schema encodes/decodes losslessly."""
    samples = {
        "ApiVersions": ({}, {"error_code": 0, "api_keys": [
            {"api_key": 3, "min_version": 0, "max_version": 9}]}),
        "CreateTopics": (
            {"topics": [{"name": "t", "num_partitions": 2,
                         "replication_factor": 1, "assignments": [],
                         "configs": [{"name": "k", "value": None}]}],
             "timeout_ms": 100},
            {"topics": [{"name": "t", "error_code": 0}]},
        ),
        "Produce": (
            {"transactional_id": None, "acks": 1, "timeout_ms": 100,
             "topic_data": [{"name": "t", "partition_data": [
                 {"index": 0, "records": b"\x01\x02"}]}]},
            {"responses": [{"name": "t", "partition_responses": [
                {"index": 0, "error_code": 0, "base_offset": 7,
                 "log_append_time_ms": -1}]}],
             "throttle_time_ms": 0},
        ),
        "Fetch": (
            {"replica_id": -1, "max_wait_ms": 0, "min_bytes": 0,
             "max_bytes": 1024, "isolation_level": 0,
             "topics": [{"topic": "t", "partitions": [
                 {"partition": 0, "fetch_offset": 3,
                  "partition_max_bytes": 1024}]}]},
            {"throttle_time_ms": 0, "responses": [{"topic": "t", "partitions": [
                {"partition_index": 0, "error_code": 0, "high_watermark": 9,
                 "last_stable_offset": 9, "aborted_transactions": None,
                 "records": b"\x00"}]}]},
        ),
        "ListOffsets": (
            {"replica_id": -1, "topics": [{"name": "t", "partitions": [
                {"partition_index": 0, "timestamp": -2}]}]},
            {"topics": [{"name": "t", "partitions": [
                {"partition_index": 0, "error_code": 0, "timestamp": -1,
                 "offset": 0}]}]},
        ),
        "Metadata": (
            {"topics": None},
            {"brokers": [{"node_id": 0, "host": "h", "port": 9092, "rack": None}],
             "controller_id": 0,
             "topics": [{"error_code": 0, "name": "t", "is_internal": False,
                         "partitions": [{"error_code": 0, "partition_index": 0,
                                         "leader_id": 0, "replica_nodes": [0, 1],
                                         "isr_nodes": [0]}]}]},
        ),
        "AlterPartitionReassignments": (
            {"timeout_ms": 1, "topics": [{"name": "t", "partitions": [
                {"partition_index": 0, "replicas": None}]}]},
            {"throttle_time_ms": 0, "error_code": 0, "error_message": None,
             "responses": [{"name": "t", "partitions": [
                 {"partition_index": 0, "error_code": 0, "error_message": "x"}]}]},
        ),
        "ListPartitionReassignments": (
            {"timeout_ms": 1, "topics": None},
            {"throttle_time_ms": 0, "error_code": 0, "error_message": None,
             "topics": [{"name": "t", "partitions": [
                 {"partition_index": 2, "replicas": [1], "adding_replicas": [],
                  "removing_replicas": [3]}]}]},
        ),
        "ElectLeaders": (
            {"election_type": 0, "topic_partitions": [
                {"topic": "t", "partition_ids": [0, 1]}], "timeout_ms": 9},
            {"throttle_time_ms": 0, "error_code": 0,
             "replica_election_results": [
                {"topic": "t", "partition_results": [
                    {"partition_id": 0, "error_code": 0, "error_message": None}]}]},
        ),
        "IncrementalAlterConfigs": (
            {"resources": [{"resource_type": 4, "resource_name": "1",
                            "configs": [{"name": "k", "config_operation": 0,
                                         "value": "v"}]}],
             "validate_only": False},
            {"throttle_time_ms": 0, "responses": [
                {"error_code": 0, "error_message": None, "resource_type": 4,
                 "resource_name": "1"}]},
        ),
        "AlterReplicaLogDirs": (
            {"dirs": [{"path": "/d", "topics": [
                {"name": "t", "partitions": [0]}]}]},
            {"throttle_time_ms": 0, "results": [
                {"topic_name": "t", "partitions": [
                    {"partition_index": 0, "error_code": 0}]}]},
        ),
        "DescribeConfigs": (
            {"resources": [{"resource_type": 4, "resource_name": "1",
                            "configuration_keys": None}]},
            {"throttle_time_ms": 0, "results": [
                {"error_code": 0, "error_message": None, "resource_type": 4,
                 "resource_name": "1", "configs": [
                     {"name": "k", "value": "v", "read_only": False,
                      "is_default": False, "is_sensitive": False}]}]},
        ),
        "DescribeLogDirs": (
            {"topics": None},
            {"throttle_time_ms": 0, "results": [
                {"error_code": 0, "log_dir": "/d", "topics": [
                    {"name": "t", "partitions": [
                        {"partition_index": 0, "partition_size": 5,
                         "offset_lag": 0, "is_future_key": False}]}]}]},
        ),
    }
    for api in proto.ALL_APIS:
        req, resp = samples[api.name]
        assert api.request.decode(api.request.encode(req)) == req, api.name
        assert api.response.decode(api.response.encode(resp)) == resp, api.name


# --------------------------------------------------------------- contract

TOPO = ClusterTopology(
    brokers=tuple(
        BrokerNode(broker_id=i, rack=f"r{i % 2}", host=f"h{i}") for i in range(3)
    ),
    partitions=(
        PartitionInfo("T0", 0, leader=0, replicas=(0, 1)),
        PartitionInfo("T0", 1, leader=1, replicas=(1, 2)),
        PartitionInfo("T1", 0, leader=2, replicas=(2, 0)),
    ),
)


class _SimHarness:
    """SimulatedClusterAdmin under the contract."""

    def __init__(self):
        self.admin = SimulatedClusterAdmin(
            StaticMetadataProvider(TOPO), link_rate_bytes_per_s=1e12
        )

    def advance(self):
        self.admin.tick(1.0)

    def throttle_active(self):
        return self.admin.throttle_rate is not None

    def close(self):
        pass


class _KafkaHarness:
    """KafkaClusterAdmin against the fake wire-protocol cluster."""

    def __init__(self):
        self.cluster = FakeKafkaCluster(
            brokers={i: {"rack": f"r{i % 2}", "logdirs": [f"/d{i}/a", f"/d{i}/b"]}
                     for i in range(3)},
            topics={
                "T0": [{"partition": 0, "leader": 0, "replicas": [0, 1]},
                       {"partition": 1, "leader": 1, "replicas": [1, 2]}],
                "T1": [{"partition": 0, "leader": 2, "replicas": [2, 0]}],
            },
        ).start()
        self.client = KafkaAdminClient(self.cluster.bootstrap(), timeout_s=5.0)
        self.admin = KafkaClusterAdmin(self.client)

    def advance(self):
        self.cluster.complete_reassignments()

    def throttle_active(self):
        return any(
            "leader.replication.throttled.rate" in cfg
            for (rt, _), cfg in self.cluster.configs.items()
            if rt == 4
        )

    def close(self):
        self.client.close()
        self.cluster.stop()


@pytest.fixture(params=["simulated", "kafka"])
def harness(request):
    h = _SimHarness() if request.param == "simulated" else _KafkaHarness()
    yield h
    h.close()


def test_contract_topology(harness):
    topo = harness.admin.topology()
    assert sorted(b.broker_id for b in topo.brokers) == [0, 1, 2]
    parts = {(p.topic, p.partition): p for p in topo.partitions}
    assert parts[("T0", 0)].replicas == (0, 1)
    assert parts[("T1", 0)].leader == 2


def test_contract_reassignment_lifecycle(harness):
    admin = harness.admin
    spec = ReassignmentSpec("T0", 0, (2, 1), data_to_move=10.0)
    admin.reassign_partitions([spec])
    assert ("T0", 0) in admin.in_progress_reassignments()
    harness.advance()
    assert ("T0", 0) not in admin.in_progress_reassignments()
    parts = {(p.topic, p.partition): p for p in admin.topology().partitions}
    assert set(parts[("T0", 0)].replicas) == {1, 2}


def test_contract_cancel(harness):
    admin = harness.admin
    admin.reassign_partitions([ReassignmentSpec("T0", 1, (0, 2), 10.0)])
    assert admin.in_progress_reassignments()
    admin.cancel_reassignments()
    assert admin.in_progress_reassignments() == set()


def test_contract_leadership(harness):
    admin = harness.admin
    # T0 p1: replicas (1, 2), leader 1.  Move leadership to 2 — NOT the
    # preferred replica, so the real-cluster adapter must reorder the
    # assignment before the preferred election (a plain election would
    # re-elect 1 and silently no-op).
    admin.elect_leaders([LeadershipSpec("T0", 1, preferred_leader=2)])
    parts = {(p.topic, p.partition): p for p in admin.topology().partitions}
    assert parts[("T0", 1)].leader == 2
    # already-leader case must be accepted as success, not an error
    admin.elect_leaders([LeadershipSpec("T0", 1, preferred_leader=2)])


def test_contract_throttle(harness):
    admin = harness.admin
    admin.set_replication_throttle(5e6, {"T0"})
    assert harness.throttle_active()
    admin.clear_replication_throttle()
    assert not harness.throttle_active()


# ------------------------------------------------- executor end to end


def test_executor_against_fake_kafka():
    """The real Executor drives KafkaClusterAdmin over live sockets; the
    reassignment completes through the actual progress-check loop."""
    h = _KafkaHarness()
    try:
        h.cluster.auto_complete_after(2)
        from cruise_control_tpu.analyzer.proposals import ExecutionProposal
        from cruise_control_tpu.executor import ExecutionOptions, Executor

        catalog = None
        ex = Executor(h.admin, topic_names={0: "T0", 1: "T1"}, catalog=catalog)
        proposal = ExecutionProposal(
            partition=0, topic=0, old_leader=0, new_leader=2,
            old_replicas=(0, 1), new_replicas=(2, 1),
            inter_broker_data_to_move=10.0,
        )
        result = ex.execute_proposals(
            [proposal],
            ExecutionOptions(progress_check_interval_s=0.05, max_ticks=200),
        )
        assert result.completed >= 1
        assert result.dead == 0
        parts = {
            (p.topic, p.partition): p for p in h.admin.topology().partitions
        }
        assert set(parts[("T0", 0)].replicas) == {1, 2}
    finally:
        h.close()


def test_logdir_moves_against_fake_kafka():
    h = _KafkaHarness()
    try:
        # T0-0 lives on broker 0 logdir /d0/a; move it to /d0/b (index 1)
        h.admin.alter_replica_logdirs([("T0", 0, 0, 1)])
        dirs = h.client.describe_logdirs(0)
        assert ("T0", 0) in dirs["/d0/b"]["replicas"]
        assert ("T0", 0) not in dirs["/d0/a"]["replicas"]
    finally:
        h.close()


def test_throttle_clear_survives_restart():
    """A NEW admin instance (fresh process after a crash) must discover and
    clear throttles set by the old one — via DescribeConfigs, not memory."""
    h = _KafkaHarness()
    try:
        h.admin.set_replication_throttle(5e6, {"T0"})
        assert h.throttle_active()
        fresh = KafkaClusterAdmin(h.client)  # empty in-memory tracking
        fresh.clear_replication_throttle()
        assert not h.throttle_active()
        assert not any(
            cfg for (rt, _), cfg in h.cluster.configs.items() if cfg
        )
    finally:
        h.close()


def test_connection_retries_after_idle_close():
    """The first request after the broker closed an idle connection must
    transparently reconnect (brokers enforce connections.max.idle.ms)."""
    h = _KafkaHarness()
    try:
        h.admin.topology()  # opens connections
        # simulate an idle-close: kill every cached socket server-side view
        for conn in h.client._conns.values():
            if conn._sock is not None:
                conn._sock.close()  # poisoned fd; next send/recv fails
        topo = h.admin.topology()  # must succeed via reconnect
        assert len(topo.brokers) == 3
    finally:
        h.close()


def test_api_version_negotiation():
    """check_api_support passes against the fake broker (which advertises
    exactly our pinned versions) and raises clearly when an API is absent."""
    h = _KafkaHarness()
    try:
        h.client.check_api_support()  # must not raise

        # simulate an older broker missing AlterPartitionReassignments
        real = h.client.api_versions

        def degraded():
            resp = real()
            resp["api_keys"] = [
                a for a in resp["api_keys"] if a["api_key"] != 45
            ]
            return resp

        h.client.api_versions = degraded
        from cruise_control_tpu.kafka import KafkaProtocolError

        with pytest.raises(KafkaProtocolError) as e:
            h.client.check_api_support()
        assert "AlterPartitionReassignments" in str(e.value)
    finally:
        h.close()


# ------------------------------------------------------------------ SASL


def _scram_cluster(users):
    return FakeKafkaCluster(
        brokers={i: {"rack": f"r{i%2}"} for i in range(3)},
        topics={
            "T0": [
                {"partition": p, "leader": p % 3, "replicas": [p % 3, (p + 1) % 3]}
                for p in range(4)
            ],
        },
        scram_users=users,
    ).start()


@pytest.mark.parametrize("mechanism", ["SCRAM-SHA-256", "SCRAM-SHA-512"])
def test_sasl_scram_authenticates_over_live_sockets(mechanism):
    """SaslHandshake + SCRAM exchange against the fake SASL-only listener;
    admin operations work only after authentication (reference gets this
    from JAAS, config/cruise_control_jaas.conf_template)."""
    from cruise_control_tpu.kafka.sasl import SaslCredentials

    cluster = _scram_cluster({"alice": "s3cret"})
    client = KafkaAdminClient(
        cluster.bootstrap(), timeout_s=5.0,
        sasl=SaslCredentials("alice", "s3cret", mechanism),
    )
    try:
        topo = KafkaMetadataProvider(client).topology()
        assert sorted(b.broker_id for b in topo.brokers) == [0, 1, 2]
        # a full admin operation rides the authenticated connection
        admin = KafkaClusterAdmin(client)
        admin.reassign_partitions([ReassignmentSpec("T0", 0, (2, 1), 10.0)])
        assert ("T0", 0) in admin.in_progress_reassignments()
    finally:
        client.close()
        cluster.stop()


def test_sasl_wrong_password_rejected_and_unauthenticated_disconnected():
    from cruise_control_tpu.kafka.client import KafkaProtocolError
    from cruise_control_tpu.kafka.sasl import SaslCredentials

    cluster = _scram_cluster({"alice": "s3cret"})
    bad = KafkaAdminClient(
        cluster.bootstrap(), timeout_s=5.0,
        sasl=SaslCredentials("alice", "wrong"),
    )
    anon = KafkaAdminClient(cluster.bootstrap(), timeout_s=5.0)
    try:
        with pytest.raises(KafkaProtocolError) as e:
            bad.metadata()
        assert e.value.code == 58  # SASL_AUTHENTICATION_FAILED
        # no SASL at all: the listener hangs up
        with pytest.raises((ConnectionError, OSError)):
            anon.metadata()
    finally:
        bad.close()
        anon.close()
        cluster.stop()


def test_scram_client_rejects_forged_server_signature():
    """Mutual auth: a MITM that accepts the password but cannot produce the
    server signature must be detected (RFC 5802 v= check)."""
    from cruise_control_tpu.kafka.sasl import SaslCredentials, ScramClient, ScramServer

    creds = SaslCredentials("alice", "pw")
    c = ScramClient(creds)
    s = ScramServer("SCRAM-SHA-256", {"alice": "pw"})
    server_first, done, ok = s.respond(c.first())
    assert not done and ok
    final = c.final(server_first)
    server_final, done, ok = s.respond(final)
    assert done and ok
    c.verify(server_final)  # genuine signature passes
    c2 = ScramClient(creds)
    s2 = ScramServer("SCRAM-SHA-256", {"alice": "pw"})
    first2, _, _ = s2.respond(c2.first())
    c2.final(first2)
    with pytest.raises(PermissionError):
        c2.verify(b"v=" + __import__("base64").b64encode(b"x" * 32))


def test_scram_sha256_rfc7677_test_vector():
    """Exact-bytes conformance against the published SCRAM-SHA-256 test
    vector (RFC 7677 §3) — the wire exchange must interoperate with real
    brokers, not merely with our own server half."""
    from cruise_control_tpu.kafka.sasl import SaslCredentials, ScramClient

    c = ScramClient(
        SaslCredentials("user", "pencil"), nonce="rOprNGfwEbeRWgbNEkqO"
    )
    assert c.first() == b"n,,n=user,r=rOprNGfwEbeRWgbNEkqO"
    server_first = (
        b"r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF$k0,"
        b"s=W22ZaJ0SNY7soEsUEjb6gQ==,i=4096"
    )
    assert c.final(server_first) == (
        b"c=biws,r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF$k0,"
        b"p=dHzbZapWIk4jUhN+Ute9ytag9zjfMHgsqmmiz7AndVQ="
    )
    c.verify(b"v=6rriTRBi23WpRR/wtup+mMhUZUn/dB5nLTJRsjl95G4=")  # no raise


def test_intra_broker_copy_tracked_over_wire():
    """Executor + KafkaClusterAdmin against fake brokers with GRADUAL
    logdir copies: the task stays in flight while DescribeLogDirs reports
    a future replica, completes once the copy lands on the target dir,
    and the landed dir is verifiable (reference ExecutorAdminUtils)."""
    from cruise_control_tpu.analyzer.proposals import ExecutionProposal
    from cruise_control_tpu.executor.executor import ExecutionOptions, Executor

    cluster = FakeKafkaCluster(
        brokers={i: {"rack": f"r{i % 2}", "logdirs": [f"/d{i}/a", f"/d{i}/b"]}
                 for i in range(3)},
        topics={
            "T0": [{"partition": 0, "leader": 0, "replicas": [0, 1]}],
        },
    ).start()
    try:
        cluster.intra_copy_polls = 2
        client = KafkaAdminClient(cluster.bootstrap(), timeout_s=5.0)
        admin = KafkaClusterAdmin(client)
        prop = ExecutionProposal(
            topic=0, partition=0, old_leader=0, new_leader=0,
            old_replicas=(0, 1), new_replicas=(0, 1),
            disk_moves=((0, 0, 1),),  # broker 0: /d0/a -> /d0/b
            intra_broker_data_to_move=512.0,
        )
        ex = Executor(admin, topic_names={0: "T0"})
        res = ex.execute_proposals(
            [prop], ExecutionOptions(progress_check_interval_s=0.05)
        )
        assert res.completed == 1 and res.dead == 0
        # the replica physically lives on the target dir now
        assert ("T0", 0) in cluster.placement[0]["/d0/b"]
        assert ("T0", 0) not in cluster.placement[0]["/d0/a"]
        assert admin.logdir_of("T0", 0, 0) == 1
        assert admin.in_progress_logdir_moves() == set()
    finally:
        cluster.stop()
