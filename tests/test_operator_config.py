"""Shipped operator sample configs must actually work (VERDICT r4 missing
#4: the reference ships config/cruisecontrol.properties +
capacity*.json; an operator must not have to author them from scratch).

Reference analogs: config/cruisecontrol.properties:1, capacity.json,
capacityJBOD.json, capacityCores.json +
config/BrokerCapacityConfigFileResolver.java (schema semantics).
"""

import os

import numpy as np
import pytest

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.app_config import CruiseControlConfig, load_properties
from cruise_control_tpu.monitor.capacity import FileCapacityResolver

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONF = os.path.join(REPO, "config")


def test_properties_file_parses_with_no_unknown_values():
    props = load_properties(os.path.join(CONF, "cruisecontrol.properties"))
    assert props, "sample properties must not be empty"
    config = CruiseControlConfig(props)
    # every uncommented key resolves through the typed config
    for key in props:
        config.get(key)
    # spot-check typed parsing happened (not raw strings)
    assert config.get("tpu.num.candidates") == 16384
    assert config.get("partition.metrics.window.ms") == 300_000
    assert config.get("cruise.control.metrics.serde.format") == "native"
    assert config.get("capacity.config.file") == "config/capacity.json"


def test_capacity_json_plain():
    r = FileCapacityResolver(os.path.join(CONF, "capacity.json"))
    default = r.capacity_for_broker("r0", "h0", 99)  # falls back to -1
    assert default.capacity[Resource.DISK] == 500_000.0
    assert default.capacity[Resource.CPU] == 100.0
    b0 = r.capacity_for_broker("r0", "h0", 0)
    assert b0.capacity[Resource.DISK] == 1_000_000.0
    assert b0.capacity[Resource.NW_IN] == 100_000.0


def test_capacity_json_jbod():
    r = FileCapacityResolver(os.path.join(CONF, "capacityJBOD.json"))
    default = r.capacity_for_broker("r0", "h0", 42)
    assert default.disk_capacities == {"/data/d0": 250_000.0, "/data/d1": 250_000.0}
    assert default.capacity[Resource.DISK] == 500_000.0  # sum of logdirs
    b0 = r.capacity_for_broker("r0", "h0", 0)
    assert len(b0.disk_capacities) == 3
    assert b0.capacity[Resource.DISK] == 1_000_000.0


def test_capacity_json_cores():
    r = FileCapacityResolver(os.path.join(CONF, "capacityCores.json"))
    default = r.capacity_for_broker("r0", "h0", 7)
    assert default.num_cores == 16
    assert default.capacity[Resource.CPU] == 100.0  # percent-based
    assert r.capacity_for_broker("r0", "h0", 0).num_cores == 32


def test_openapi_spec_is_current():
    """docs/openapi.json must match what scripts/gen_api_spec.py derives
    from the served endpoint/parameter/schema declarations — a drifted
    spec is worse than none (reference regenerates its Swagger wiki via
    build_api_wiki.sh)."""
    import json
    import importlib.util

    spec_path = os.path.join(REPO, "docs", "openapi.json")
    gen_path = os.path.join(REPO, "scripts", "gen_api_spec.py")
    s = importlib.util.spec_from_file_location("gen_api_spec", gen_path)
    mod = importlib.util.module_from_spec(s)
    s.loader.exec_module(mod)
    with open(spec_path) as f:
        committed = json.load(f)
    assert committed == mod.build_spec(), (
        "docs/openapi.json is stale — run scripts/gen_api_spec.py"
    )
    # every served endpoint appears with its method
    from cruise_control_tpu.config.endpoints import GET_ENDPOINTS, POST_ENDPOINTS

    for ep in GET_ENDPOINTS:
        assert "get" in committed["paths"][f"/{ep}"]
    for ep in POST_ENDPOINTS:
        assert "post" in committed["paths"][f"/{ep}"]


def test_openapi_paths_match_endpoint_tables_exactly():
    """Bidirectional openapi <-> GET_ENDPOINTS/POST_ENDPOINTS drift gate:
    the committed spec must cover EXACTLY the served endpoint set — no
    endpoint missing from the spec, no ghost path lingering after an
    endpoint is removed, no method served that the spec does not declare."""
    import json

    from cruise_control_tpu.config.endpoints import GET_ENDPOINTS, POST_ENDPOINTS

    with open(os.path.join(REPO, "docs", "openapi.json")) as f:
        spec = json.load(f)
    served = {f"/{ep}" for ep in GET_ENDPOINTS} | {f"/{ep}" for ep in POST_ENDPOINTS}
    assert set(spec["paths"]) == served, (
        "docs/openapi.json paths drifted from config/endpoints.py — "
        "run scripts/gen_api_spec.py"
    )
    for ep in GET_ENDPOINTS:
        assert set(spec["paths"][f"/{ep}"]) >= {"get"}
    for ep in POST_ENDPOINTS:
        assert set(spec["paths"][f"/{ep}"]) >= {"post"}
    # and no method is declared that the server does not dispatch
    for path, ops in spec["paths"].items():
        ep = path.lstrip("/")
        for method in ops:
            assert (method == "get" and ep in GET_ENDPOINTS) or (
                method == "post" and ep in POST_ENDPOINTS
            ), f"{method.upper()} {path} declared in the spec but not served"


def test_service_boots_from_shipped_properties():
    """The start script's exact path: load the shipped properties, boot the
    service from them (simulated backend — no bootstrap.servers), serve a
    request, and verify the configured JBOD capacity file reached the
    monitor's resolver."""
    import json
    import urllib.request

    from cruise_control_tpu.service.main import build_simulated_service

    props = load_properties(os.path.join(CONF, "cruisecontrol.properties"))
    # ephemeral port + JBOD capacities + tiny engine so the test is fast
    props.update({
        "webserver.http.port": "0",
        "capacity.config.file": os.path.join(CONF, "capacityJBOD.json"),
        "tpu.num.candidates": "128",
        "tpu.leadership.candidates": "32",
        "tpu.steps.per.round": "8",
        "tpu.num.rounds": "2",
        "num.partition.metrics.windows": "3",
        "partition.metrics.window.ms": "1000",
    })
    config = CruiseControlConfig(props)
    app, fetcher, admin, sampler = build_simulated_service(config)
    app.start()
    try:
        url = f"http://{app.host}:{app.port}{app.prefix}/state?substates=monitor"
        with urllib.request.urlopen(url, timeout=30) as resp:
            assert resp.status == 200
            payload = json.loads(resp.read())
        assert "MonitorState" in payload
        # the JBOD capacity file is live in the monitor
        cap = app.cc.monitor.capacity_resolver.capacity_for_broker("r0", "h0", 1)
        assert cap.disk_capacities == {"/data/d0": 250_000.0, "/data/d1": 250_000.0}
    finally:
        app.stop()
