"""Shipped operator sample configs must actually work (VERDICT r4 missing
#4: the reference ships config/cruisecontrol.properties +
capacity*.json; an operator must not have to author them from scratch).

Reference analogs: config/cruisecontrol.properties:1, capacity.json,
capacityJBOD.json, capacityCores.json +
config/BrokerCapacityConfigFileResolver.java (schema semantics).
"""

import os

import numpy as np
import pytest

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.app_config import CruiseControlConfig, load_properties
from cruise_control_tpu.monitor.capacity import FileCapacityResolver

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONF = os.path.join(REPO, "config")


def test_properties_file_parses_with_no_unknown_values():
    props = load_properties(os.path.join(CONF, "cruisecontrol.properties"))
    assert props, "sample properties must not be empty"
    config = CruiseControlConfig(props)
    # every uncommented key resolves through the typed config
    for key in props:
        config.get(key)
    # spot-check typed parsing happened (not raw strings)
    assert config.get("tpu.num.candidates") == 16384
    assert config.get("partition.metrics.window.ms") == 300_000
    assert config.get("cruise.control.metrics.serde.format") == "native"
    assert config.get("capacity.config.file") == "config/capacity.json"


def test_capacity_json_plain():
    r = FileCapacityResolver(os.path.join(CONF, "capacity.json"))
    default = r.capacity_for_broker("r0", "h0", 99)  # falls back to -1
    assert default.capacity[Resource.DISK] == 500_000.0
    assert default.capacity[Resource.CPU] == 100.0
    b0 = r.capacity_for_broker("r0", "h0", 0)
    assert b0.capacity[Resource.DISK] == 1_000_000.0
    assert b0.capacity[Resource.NW_IN] == 100_000.0


def test_capacity_json_jbod():
    r = FileCapacityResolver(os.path.join(CONF, "capacityJBOD.json"))
    default = r.capacity_for_broker("r0", "h0", 42)
    assert default.disk_capacities == {"/data/d0": 250_000.0, "/data/d1": 250_000.0}
    assert default.capacity[Resource.DISK] == 500_000.0  # sum of logdirs
    b0 = r.capacity_for_broker("r0", "h0", 0)
    assert len(b0.disk_capacities) == 3
    assert b0.capacity[Resource.DISK] == 1_000_000.0


def test_capacity_json_cores():
    r = FileCapacityResolver(os.path.join(CONF, "capacityCores.json"))
    default = r.capacity_for_broker("r0", "h0", 7)
    assert default.num_cores == 16
    assert default.capacity[Resource.CPU] == 100.0  # percent-based
    assert r.capacity_for_broker("r0", "h0", 0).num_cores == 32


def test_openapi_spec_is_current():
    """docs/openapi.json must match what scripts/gen_api_spec.py derives
    from the served endpoint/parameter/schema declarations — a drifted
    spec is worse than none (reference regenerates its Swagger wiki via
    build_api_wiki.sh)."""
    import json
    import importlib.util

    spec_path = os.path.join(REPO, "docs", "openapi.json")
    gen_path = os.path.join(REPO, "scripts", "gen_api_spec.py")
    s = importlib.util.spec_from_file_location("gen_api_spec", gen_path)
    mod = importlib.util.module_from_spec(s)
    s.loader.exec_module(mod)
    with open(spec_path) as f:
        committed = json.load(f)
    assert committed == mod.build_spec(), (
        "docs/openapi.json is stale — run scripts/gen_api_spec.py"
    )
    # every served endpoint appears with its method
    from cruise_control_tpu.config.endpoints import GET_ENDPOINTS, POST_ENDPOINTS

    for ep in GET_ENDPOINTS:
        assert "get" in committed["paths"][f"/{ep}"]
    for ep in POST_ENDPOINTS:
        assert "post" in committed["paths"][f"/{ep}"]


def test_openapi_paths_match_endpoint_tables_exactly():
    """Bidirectional openapi <-> GET_ENDPOINTS/POST_ENDPOINTS drift gate:
    the committed spec must cover EXACTLY the served endpoint set — no
    endpoint missing from the spec, no ghost path lingering after an
    endpoint is removed, no method served that the spec does not declare."""
    import json

    from cruise_control_tpu.config.endpoints import GET_ENDPOINTS, POST_ENDPOINTS

    with open(os.path.join(REPO, "docs", "openapi.json")) as f:
        spec = json.load(f)
    served = {f"/{ep}" for ep in GET_ENDPOINTS} | {f"/{ep}" for ep in POST_ENDPOINTS}
    assert set(spec["paths"]) == served, (
        "docs/openapi.json paths drifted from config/endpoints.py — "
        "run scripts/gen_api_spec.py"
    )
    for ep in GET_ENDPOINTS:
        assert set(spec["paths"][f"/{ep}"]) >= {"get"}
    for ep in POST_ENDPOINTS:
        assert set(spec["paths"][f"/{ep}"]) >= {"post"}
    # and no method is declared that the server does not dispatch
    for path, ops in spec["paths"].items():
        ep = path.lstrip("/")
        for method in ops:
            assert (method == "get" and ep in GET_ENDPOINTS) or (
                method == "post" and ep in POST_ENDPOINTS
            ), f"{method.upper()} {path} declared in the spec but not served"


def test_service_boots_from_shipped_properties():
    """The start script's exact path: load the shipped properties, boot the
    service from them (simulated backend — no bootstrap.servers), serve a
    request, and verify the configured JBOD capacity file reached the
    monitor's resolver."""
    import json
    import urllib.request

    from cruise_control_tpu.service.main import build_simulated_service

    props = load_properties(os.path.join(CONF, "cruisecontrol.properties"))
    # ephemeral port + JBOD capacities + tiny engine so the test is fast
    props.update({
        "webserver.http.port": "0",
        "capacity.config.file": os.path.join(CONF, "capacityJBOD.json"),
        "tpu.num.candidates": "128",
        "tpu.leadership.candidates": "32",
        "tpu.steps.per.round": "8",
        "tpu.num.rounds": "2",
        "num.partition.metrics.windows": "3",
        "partition.metrics.window.ms": "1000",
    })
    config = CruiseControlConfig(props)
    app, fetcher, admin, sampler = build_simulated_service(config)
    app.start()
    try:
        url = f"http://{app.host}:{app.port}{app.prefix}/state?substates=monitor"
        with urllib.request.urlopen(url, timeout=30) as resp:
            assert resp.status == 200
            payload = json.loads(resp.read())
        assert "MonitorState" in payload
        # the JBOD capacity file is live in the monitor
        cap = app.cc.monitor.capacity_resolver.capacity_for_broker("r0", "h0", 1)
        assert cap.disk_capacities == {"/data/d0": 250_000.0, "/data/d1": 250_000.0}
    finally:
        app.stop()


# ------------------------------------------------ sensor-catalog drift gate


def _documented_sensor_names():
    """Parse the docs/sensors.md table into (concrete names, regex
    patterns).  Cell grammar the parser understands:

      * ```a.b.c` ``                       one name
      * ```a.b.c` / `.d` ``                suffix shorthand: second name
                                           replaces the last segment(s)
      * ```a.{x,y}` ``                     brace expansion
      * ```a.<type>.rate` ``               placeholder -> regex pattern
    """
    import re

    names: set[str] = set()
    patterns: list[str] = []
    with open(os.path.join(REPO, "docs", "sensors.md")) as f:
        for line in f:
            if not line.startswith("|") or line.startswith("|---"):
                continue
            cell = line.split("|")[1].strip()
            if cell in ("sensor", ""):
                continue
            base = None
            for tok in re.findall(r"`([^`]+)`", cell):
                if tok.startswith("."):
                    assert base is not None, f"suffix {tok!r} with no base"
                    suffix = tok[1:].split(".")
                    parts = base.split(".")
                    tok = ".".join(parts[: len(parts) - len(suffix)] + suffix)
                else:
                    base = tok
                m = re.match(r"(.*)\{([^}]+)\}(.*)", tok)
                expanded = (
                    [f"{m.group(1)}{alt}{m.group(3)}" for alt in m.group(2).split(",")]
                    if m
                    else [tok]
                )
                for name in expanded:
                    if "<" in name:
                        patterns.append(
                            "^"
                            + re.sub(r"<[^>]+>", r"[a-z0-9_-]+", re.escape(name).replace(
                                re.escape("<"), "<").replace(re.escape(">"), ">"))
                            + "$"
                        )
                    else:
                        names.add(name)
    assert names, "docs/sensors.md table parsed empty"
    return names, patterns


def test_runtime_sensor_names_are_documented():
    """Every sensor a full-service smoke registers must appear in
    docs/sensors.md — the sensors twin of the openapi<->endpoint-table
    drift gate.  (The reverse direction is
    test_documented_sensor_names_exist_in_source.)"""
    import re

    from cruise_control_tpu.service.main import build_simulated_service

    documented, patterns = _documented_sensor_names()
    app, fetcher, admin, sampler = build_simulated_service(seed=23)
    try:
        cc = app.cc
        # drive the proposal pipeline + an execution so the monitor,
        # analyzer, device-supervisor and executor surfaces all register
        from cruise_control_tpu.service.progress import OperationProgress

        result = cc.proposals(OperationProgress(), ignore_cache=True)
        cc.rebalance(OperationProgress(), dryrun=False)
        runtime = set(cc.sensors.snapshot())
        assert result is not None and runtime
        undocumented = {
            n
            for n in runtime
            if n not in documented
            and not any(re.match(p, n) for p in patterns)
        }
        assert not undocumented, (
            f"sensors registered at runtime but missing from docs/sensors.md: "
            f"{sorted(undocumented)}"
        )
    finally:
        app.stop()


def test_documented_sensor_names_exist_in_source():
    """Every name docs/sensors.md lists must still exist in the package
    source — a renamed/removed sensor must not leave a ghost row. Dynamic
    (pattern) rows are checked by their literal fragments."""
    import re

    documented, patterns = _documented_sensor_names()
    src = []
    for dirpath, _dirs, files in os.walk(os.path.join(REPO, "cruise_control_tpu")):
        for fn in files:
            if fn.endswith(".py"):
                with open(os.path.join(dirpath, fn)) as f:
                    src.append(f.read())
    blob = "\n".join(src)

    def in_source(name: str) -> bool:
        if name in blob:
            return True
        # f-string-built families (f"executor.recovery.{name}",
        # f"analyzer.engine-cache-{name}"): accept a documented name whose
        # prefix appears in source immediately followed by a placeholder
        for i, ch in enumerate(name):
            if ch in ".-" and name[: i + 1] + "{" in blob:
                return True
        return False

    ghosts = [n for n in documented if not in_source(n)]
    assert not ghosts, f"docs/sensors.md rows with no source analog: {ghosts}"
    for p in patterns:
        # ^anomaly\-detector\.[a-z0-9_-]+\.rate$ -> fragments around the
        # placeholder must both appear in source
        frags = [
            re.sub(r"\\(.)", r"\1", frag)
            for frag in re.split(r"\[[^\]]+\]\+", p.strip("^$"))
        ]
        for frag in frags:
            assert frag.strip(".") == "" or frag in blob or frag.strip(".") in blob, (
                f"pattern fragment {frag!r} from docs/sensors.md not in source"
            )
