"""CLI client tests against a live in-process service (reference
cruise-control-client has no in-repo tests; we hold ours to the service)."""

import json

import pytest

from cruise_control_tpu.client.cccli import ENDPOINTS, build_parser, main
from cruise_control_tpu.service.main import build_simulated_service
from cruise_control_tpu.service.server import GET_ENDPOINTS, POST_ENDPOINTS


@pytest.fixture(scope="module")
def service():
    app, fetcher, admin, sampler = build_simulated_service(seed=7)
    app.start()
    yield app
    app.stop()


def run_cli(service, capsys, *argv):
    rc = main(["-a", f"http://{service.host}:{service.port}", *argv])
    out = capsys.readouterr().out
    return rc, json.loads(out)


def test_cli_covers_every_endpoint():
    covered = {spec["endpoint"] for spec in ENDPOINTS.values()}
    assert set(GET_ENDPOINTS) <= covered
    assert set(POST_ENDPOINTS) <= covered


def test_cli_parameter_validation():
    p = build_parser()
    with pytest.raises(SystemExit):
        p.parse_args(["remove_broker", "--brokers", "abc"])  # not a csv int list
    with pytest.raises(SystemExit):
        p.parse_args(["rebalance", "--dryrun", "maybe"])  # not boolean
    args = p.parse_args(["add_broker", "--brokers", "1,2,3", "--dryrun", "true"])
    assert args.brokerid == "1,2,3"


def test_cli_state(service, capsys):
    rc, payload = run_cli(service, capsys, "state")
    assert rc == 0 and "MonitorState" in payload


def test_cli_async_proposals(service, capsys):
    rc, payload = run_cli(service, capsys, "proposals")
    assert rc == 0 and "balancednessAfter" in payload


def test_cli_rebalance_dryrun(service, capsys):
    rc, payload = run_cli(service, capsys, "rebalance", "--dryrun", "true")
    assert rc == 0 and "proposals" in payload
    # per-phase ETA derived from data-to-move over active caps (ADVICE r4
    # weak #8: dataToMoveMB alone was surfaced)
    eta = payload["estimatedExecutionTime"]
    assert set(eta) == {
        "interBrokerSeconds", "intraBrokerSeconds", "leadershipSeconds",
        "assumptions",
    }
    assert eta["assumptions"]["concurrentLeaderMovements"] >= 1
    assert eta["assumptions"]["dataToMoveMB"] == payload["dataToMoveMB"]


def test_cli_user_tasks_filters(service, capsys):
    """user_tasks filter flags reach the server-side filters
    (service/parameters.py user_task_ids/client_ids/endpoints/types)."""
    rc, _ = run_cli(service, capsys, "proposals")  # async op -> user task
    rc, payload = run_cli(service, capsys, "user_tasks",
                          "--endpoints", "PROPOSALS")
    assert rc == 0
    tasks = payload["userTasks"]
    assert tasks and all("proposals" in t["RequestURL"].lower() for t in tasks)
    # a filter that matches nothing returns an empty list, not an error
    rc, payload = run_cli(service, capsys, "user_tasks",
                          "--endpoints", "TRAIN")
    assert rc == 0 and payload["userTasks"] == []


def test_cli_admin_concurrency_flags(service, capsys):
    """ADMIN mid-execution concurrency flags serialize to the server's
    parameter names; with no live execution the server answers 400 and
    the CLI reports the error body (exit 1)."""
    p = build_parser()
    args = p.parse_args([
        "admin",
        "--concurrent-partition-movements-per-broker", "8",
        "--concurrent-leader-movements", "500",
        "--execution-progress-check-interval-ms", "100",
    ])
    assert args.concurrent_partition_movements_per_broker == "8"
    with pytest.raises(SystemExit):
        p.parse_args(["admin", "--concurrent-leader-movements", "0"])  # < 1
    rc, payload = run_cli(
        service, capsys, "admin",
        "--concurrent-partition-movements-per-broker", "8",
    )
    assert rc == 1 and "no ongoing execution" in json.dumps(payload)


def test_cli_error_reporting(service, capsys):
    rc, payload = run_cli(service, capsys, "topic_configuration",
                          "--topic", "NoSuchTopic", "--replication-factor", "3")
    assert rc == 0  # unknown topic -> zero proposals, not an error
    assert payload["numProposals"] == 0


def test_cli_basic_auth(tmp_path, capsys):
    """-u user:password sends the Authorization header the server's
    BasicSecurityProvider expects (reference cccli auth flags)."""
    from cruise_control_tpu.config import CruiseControlConfig

    creds = tmp_path / "credentials"
    creds.write_text("admin:secret:ADMIN\nviewer:ro:VIEWER\n")
    config = CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "min.samples.per.partition.metrics.window": 1,
        "execution.progress.check.interval.ms": 100,
        "webserver.http.port": 0,
        "webserver.security.enable": "true",
        "basic.auth.credentials.file": str(creds),
        "tpu.num.candidates": 64, "tpu.leadership.candidates": 16,
        "tpu.steps.per.round": 8, "tpu.num.rounds": 2,
    })
    app, fetcher, admin, sampler = build_simulated_service(config, seed=8)
    app.start()
    try:
        addr = f"http://{app.host}:{app.port}"
        # no credentials -> 401 -> nonzero exit with the error payload
        rc = main(["-a", addr, "state"])
        capsys.readouterr()
        assert rc == 1
        rc = main(["-a", addr, "-u", "admin:secret", "state"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0 and "MonitorState" in out
        # VIEWER may GET but not POST
        rc = main(["-a", addr, "-u", "viewer:ro", "state"])
        capsys.readouterr()
        assert rc == 0
        rc = main(["-a", addr, "-u", "viewer:ro", "pause_sampling"])
        err = json.loads(capsys.readouterr().out)
        assert rc == 1 and "errorMessage" in err
    finally:
        app.stop()


def test_cli_trace_and_metrics(service, capsys):
    # seed a traced operation, then replay it through the CLI
    rc, payload = run_cli(service, capsys, "proposals")
    assert rc == 0
    tid = payload.get("_traceId")
    assert tid
    rc, idx = run_cli(service, capsys, "trace")
    assert rc == 0 and idx["traces"]
    rc, tree = run_cli(service, capsys, "trace", "--id", tid)
    assert rc == 0
    assert tree["traceId"] == tid and tree["spans"]
    # metrics is raw Prometheus text, passed through verbatim (not JSON)
    rc = main(["-a", f"http://{service.host}:{service.port}", "metrics"])
    out = capsys.readouterr().out
    assert rc == 0
    from cruise_control_tpu.common.exposition import parse_exposition

    assert parse_exposition(out), "CLI must emit lintable exposition text"
