"""Shape-bucketed engine serving tests.

Pin the tentpole contract of the bucketing layer (models.state.
ShapeBucketPolicy + padded-broker masking + the optimizer's LRU engine
cache): an exact and a bucketed build of the same cluster are
indistinguishable in every observable output (objective, per-goal
violations, balancedness, extracted proposal set), and topology churn
within a bucket rebinds the cached engine with zero recompilation.
"""

import dataclasses
from types import SimpleNamespace

import numpy as np
import pytest

from cruise_control_tpu.analyzer import (
    DEFAULT_CHAIN,
    GoalOptimizer,
    OptimizerConfig,
)
from cruise_control_tpu.common.sensors import SensorRegistry
from cruise_control_tpu.models.builder import (
    BrokerSpec,
    ClusterModelBuilder,
    PartitionSpec,
    pad_state,
)
from cruise_control_tpu.models.state import ShapeBucketPolicy, validate
from cruise_control_tpu.testing.fixtures import (
    dead_broker_cluster,
    jbod_cluster,
    small_cluster,
)

FAST = OptimizerConfig(
    num_candidates=128, leadership_candidates=32, swap_candidates=16,
    steps_per_round=8, num_rounds=2, max_extra_rounds=2, seed=3,
)

POLICY = ShapeBucketPolicy(growth=1.25, floor=8)


# ----------------------------------------------------------------------
# policy series
# ----------------------------------------------------------------------


def test_bucket_series_monotone_and_stable():
    pol = POLICY
    prev = 0
    for n in range(1, 4000, 7):
        b = pol.bucket(n)
        assert b >= n, (n, b)
        assert b >= prev  # monotone in n
        assert pol.bucket(b) == b  # buckets are fixed points
        prev = b
    # everything inside a bucket maps to the same bucket (the property
    # that makes churned generations share a compile key)
    assert pol.bucket(pol.bucket(100) - 1) == pol.bucket(100)
    assert ShapeBucketPolicy(enabled=False).bucket(37) == 37


def test_bucket_policy_validates():
    with pytest.raises(ValueError):
        ShapeBucketPolicy(growth=1.0)
    with pytest.raises(ValueError):
        ShapeBucketPolicy(floor=0)


def test_next_bucket_shape_strictly_grows_replica_axes():
    shape = small_cluster().shape
    cur = POLICY.bucket_shape(shape)
    nxt = POLICY.next_bucket_shape(shape)
    assert nxt.num_replicas > cur.num_replicas
    assert nxt.num_partitions > cur.num_partitions
    assert nxt.num_brokers == cur.num_brokers


# ----------------------------------------------------------------------
# exact vs bucketed parity
# ----------------------------------------------------------------------


def _proposal_keys(proposals):
    return sorted(
        (p.partition, p.topic, p.old_leader, p.new_leader,
         p.old_replicas, p.new_replicas, p.disk_moves)
        for p in proposals
    )


#: compact goal chains for the non-headline fixtures — the full 19-goal
#: chain rides the small-cluster parity test; every extra goal inflates
#: the engine compile this CPU suite pays twice per fixture
from cruise_control_tpu.analyzer.objective import GoalChain  # noqa: E402

_JBOD_CHAIN = GoalChain.from_names([
    "OfflineReplicaGoal", "RackAwareGoal", "DiskCapacityGoal",
    "IntraBrokerDiskCapacityGoal", "IntraBrokerDiskUsageDistributionGoal",
    "DiskUsageDistributionGoal",
])
_COMPACT_CHAIN = GoalChain.from_names([
    "OfflineReplicaGoal", "RackAwareGoal", "ReplicaCapacityGoal",
    "DiskCapacityGoal", "ReplicaDistributionGoal",
    "LeaderReplicaDistributionGoal", "NetworkInboundUsageDistributionGoal",
])


@pytest.mark.parametrize(
    "fixture,chain", [
        (small_cluster, DEFAULT_CHAIN),
        (dead_broker_cluster, _COMPACT_CHAIN),
        (jbod_cluster, _JBOD_CHAIN),
    ],
    ids=["small", "dead-broker", "jbod"],
)
def test_exact_vs_bucketed_parity(fixture, chain):
    """Bucket padding must be invisible: identical objective, per-goal
    violations, balancedness, and proposal set — not merely close."""
    exact = fixture()
    bucketed = pad_state(exact, POLICY.bucket_shape(exact.shape))
    assert bucketed.shape != exact.shape  # the test must actually pad
    assert validate(bucketed) == []

    o1, v1, s1 = chain.evaluate(exact)
    o2, v2, s2 = chain.evaluate(bucketed)
    assert float(o1) == float(o2)
    assert np.array_equal(np.asarray(v1), np.asarray(v2))
    assert np.array_equal(np.asarray(s1), np.asarray(s2))

    r1 = GoalOptimizer(chain=chain, config=FAST).optimize(exact)
    r2 = GoalOptimizer(chain=chain, config=FAST).optimize(bucketed)
    assert r1.objective_after == r2.objective_after
    assert np.array_equal(r1.violations_after, r2.violations_after)
    assert r1.balancedness_after == r2.balancedness_after
    assert _proposal_keys(r1.proposals) == _proposal_keys(r2.proposals)


def test_sharded_exact_vs_bucketed_parity():
    """The model-sharded path must also be padding-blind: with the bucket
    policy the engine pads its input before the shard split, so the exact
    and the bucketed build shard — and anneal — identically (8-device
    mesh)."""
    from cruise_control_tpu.parallel.sharded import ShardedEngine, model_mesh
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster

    exact = random_cluster(
        RandomClusterSpec(num_brokers=10, num_partitions=120, skew=1.5), seed=61
    )
    bucketed = pad_state(exact, POLICY.bucket_shape(exact.shape))
    cfg = dataclasses.replace(FAST, num_candidates=48, leadership_candidates=12,
                              swap_candidates=6, steps_per_round=4)
    from cruise_control_tpu.analyzer.objective import GoalChain

    # a compact chain: the sharded parity is about shard mechanics (split,
    # candidate-column all_gather), not goal coverage — the full chain
    # rides the single-device parity tests above
    chain = GoalChain.from_names([
        "RackAwareGoal", "ReplicaCapacityGoal", "DiskCapacityGoal",
        "ReplicaDistributionGoal", "LeaderReplicaDistributionGoal",
    ])
    se1 = ShardedEngine(exact, chain, mesh=model_mesh(), config=cfg,
                        bucket=POLICY)
    se2 = ShardedEngine(bucketed, chain, mesh=model_mesh(), config=cfg,
                        bucket=POLICY)
    # both pad to the SAME bucketed shape before the shard split, so the
    # compiled mesh programs are layout-identical -> rebind survives churn
    assert se1.engine.state.shape == se2.engine.state.shape
    f1, _ = se1.run()
    f2, _ = se2.run()
    n = int(np.asarray(exact.replica_valid).sum())
    assert np.array_equal(
        np.asarray(f1.replica_broker)[:n], np.asarray(f2.replica_broker)[:n]
    )
    assert np.array_equal(
        np.asarray(f1.replica_is_leader)[:n], np.asarray(f2.replica_is_leader)[:n]
    )
    # the reassembled result keeps the caller's own replica axis
    assert f1.shape == exact.shape and f2.shape == bucketed.shape


# ----------------------------------------------------------------------
# churn: same bucket -> zero recompiles
# ----------------------------------------------------------------------


def _churn_builder(extra_partitions=0, extra_broker=False):
    """Cluster rebuilt from scratch each generation, as the monitor would:
    base topology plus `extra_partitions` created partitions.  Sized so the
    churn stays INSIDE one bucket (40 partitions x rf2 = 80 replicas sits
    well below its 94-replica ×1.25 bucket)."""
    b = ClusterModelBuilder(bucket_policy=POLICY)
    cap = np.array([100.0, 1000.0, 1000.0, 10000.0], np.float32)
    n_brokers = 4 + (1 if extra_broker else 0)
    for i in range(n_brokers):
        b.add_broker(BrokerSpec(i, rack=f"r{i % 2}", capacity=cap))
    for p in range(40 + extra_partitions):
        b.add_partition(PartitionSpec(
            "T0", p, [p % 4, (p + 1) % 4],
            np.array([5.0, 40.0, 50.0, 300.0], np.float32),
        ))
    return b.build()


def test_topology_churn_hits_engine_cache():
    """A partition create — and then a broker add + more partitions —
    between optimize() calls must trigger ZERO engine compiles (acceptance
    criterion, asserted via cache counters)."""
    opt = GoalOptimizer(chain=_COMPACT_CHAIN, config=FAST, sensors=SensorRegistry())
    s0 = _churn_builder()
    s1 = _churn_builder(extra_partitions=1)  # partition created
    s2 = _churn_builder(extra_broker=True, extra_partitions=2)  # broker added
    assert s0.shape == s1.shape == s2.shape  # bucketing absorbed the churn
    r0 = opt.optimize(s0)
    assert opt.engine_cache_misses == 1 and opt.engine_cache_hits == 0
    r1 = opt.optimize(s1)
    assert opt.engine_cache_misses == 1, "partition churn recompiled the engine"
    assert opt.engine_cache_hits == 1
    r2 = opt.optimize(s2)
    assert opt.engine_cache_misses == 1, "broker add recompiled the engine"
    assert opt.engine_cache_hits == 2
    # the added broker is a real (valid) broker in the third model
    assert int(np.asarray(s2.broker_valid).sum()) == 5
    assert validate(r2.state_after) == []
    # the outcome is observable in the result timing record
    t0 = next(h for h in r0.history if h.get("timing"))
    t1 = next(h for h in r1.history if h.get("timing"))
    assert t0["engine_cache_hit"] is False and t1["engine_cache_hit"] is True
    assert t1["bucket"] == t0["bucket"]
    # and in the sensor registry
    snap = opt.sensors.snapshot()
    assert snap["analyzer.engine-cache-hits"]["count"] == 2
    assert snap["analyzer.engine-cache-misses"]["count"] == 1
    assert snap["analyzer.engine-cache-size"]["value"] == 1


def test_prewarm_builds_engine_without_counting():
    opt = GoalOptimizer(chain=_COMPACT_CHAIN, config=FAST)
    state = _churn_builder()
    nxt = POLICY.next_bucket_shape(state.shape)
    opt.prewarm(pad_state(state, nxt))
    assert opt.engine_cache_misses == 0 and opt.engine_cache_hits == 0
    # an overflow generation lands on the prewarmed engine: a cache HIT
    opt.optimize(pad_state(state, nxt))
    assert opt.engine_cache_hits == 1 and opt.engine_cache_misses == 0


# ----------------------------------------------------------------------
# LRU eviction
# ----------------------------------------------------------------------


def test_engine_cache_lru_eviction_releases_buffers():
    import jax

    opt = GoalOptimizer(chain=_COMPACT_CHAIN, config=FAST, engine_cache_size=1)
    s_small = small_cluster()
    s_big = pad_state(s_small, POLICY.bucket_shape(s_small.shape))
    opt.optimize(s_small)
    first = next(iter(opt._engines.values()))
    # engine-DERIVED statics arrays are released on eviction; the
    # caller-owned ClusterState arrays must survive (they are alive as
    # result.state_before / in other engines)
    derived = [
        leaf
        for f in dataclasses.fields(type(first.statics))
        if f.name != "state"
        for leaf in jax.tree.leaves(getattr(first.statics, f.name))
        if hasattr(leaf, "is_deleted")
    ]
    caller = [
        leaf for leaf in jax.tree.leaves(s_small)
        if hasattr(leaf, "is_deleted")
    ]
    assert derived and not any(leaf.is_deleted() for leaf in derived)
    opt.optimize(s_big)  # different shape -> second engine -> evicts first
    assert len(opt._engines) == 1
    assert all(leaf.is_deleted() for leaf in derived), (
        "evicted engine's device buffers were not freed"
    )
    assert not any(leaf.is_deleted() for leaf in caller), (
        "eviction deleted the caller's ClusterState buffers"
    )
    assert first.statics is None  # state de-referenced for GC
    assert opt.engine_cache_misses == 2
    # the caller's state is still fully usable after the eviction
    assert validate(s_small) == []
    # the surviving engine still serves its shape
    res = opt.optimize(s_big)
    assert opt.engine_cache_hits == 1
    assert validate(res.state_after) == []


def test_engine_cache_size_validated():
    with pytest.raises(ValueError):
        GoalOptimizer(engine_cache_size=0)


# ----------------------------------------------------------------------
# monitor path + satellites
# ----------------------------------------------------------------------


def test_monitor_builds_bucketed_shapes_stable_under_churn():
    """LoadMonitor with a bucket policy: creating a partition between two
    cluster_model() calls yields the SAME ClusterShape."""
    from cruise_control_tpu.monitor import (
        KAFKA_METRIC_DEF,
        FixedCapacityResolver,
        LoadMonitor,
        ModelCompletenessRequirements,
        WindowedMetricSampleAggregator,
    )
    from cruise_control_tpu.monitor.sampling import PartitionEntity
    from cruise_control_tpu.monitor.topology import StaticMetadataProvider
    from cruise_control_tpu.testing.synthetic import synthetic_topology

    def build_monitor(parts):
        topo = synthetic_topology(num_brokers=6, topics={"t0": parts}, seed=1)
        cols = topo.columns()
        ents = [
            PartitionEntity(int(t), int(p))
            for t, p in zip(cols.part_topic, cols.part_num)
        ]
        agg = WindowedMetricSampleAggregator(4, 1000, 1, KAFKA_METRIC_DEF)
        rng = np.random.default_rng(0)
        for w in range(3):
            agg.add_samples_columnar(
                ents, w * 1000 + 5,
                rng.uniform(1, 10, (len(ents), KAFKA_METRIC_DEF.num_metrics))
                .astype(np.float32),
            )
        return LoadMonitor(
            StaticMetadataProvider(topo), FixedCapacityResolver([100.0, 1e5, 1e5, 1e6]),
            agg, bucket_policy=POLICY,
        )

    req = ModelCompletenessRequirements(min_required_num_windows=1)
    st0 = build_monitor(40).cluster_model(req)
    st1 = build_monitor(41).cluster_model(req)  # one partition created
    assert st0.shape == st1.shape
    assert st0.shape.num_partitions >= 41
    assert validate(st1) == []


def test_config_shape_bucket_keys_wire_through():
    from cruise_control_tpu.config import CruiseControlConfig

    cfg = CruiseControlConfig({
        "tpu.shape.bucket.growth": 1.5,
        "tpu.shape.bucket.floor": 16,
        "tpu.engine.cache.size": 3,
    })
    pol = cfg.shape_bucket_policy()
    assert pol.enabled and pol.growth == 1.5 and pol.floor == 16
    assert cfg.get("tpu.engine.cache.size") == 3
    off = CruiseControlConfig({"tpu.shape.bucket.enabled": "false"})
    assert off.shape_bucket_policy().bucket(37) == 37


def test_catalog_topic_id_is_dict_backed():
    from cruise_control_tpu.models.builder import ClusterCatalog

    cat = ClusterCatalog(topics=("a", "b", "c"), partitions=(("a", 0),))
    assert [cat.topic_id(t) for t in ("a", "b", "c")] == [0, 1, 2]
    with pytest.raises(KeyError):
        cat.topic_id("nope")
    # replace() re-derives the index for the new topic tuple
    cat2 = dataclasses.replace(cat, topics=("x", "a"))
    assert cat2.topic_id("a") == 1


def test_proposal_cache_expiry_uses_monotonic_clock(monkeypatch):
    """A backwards wall-clock step must not make cached proposals
    immortal: expiry is judged on time.monotonic()."""
    import time as time_mod

    from cruise_control_tpu.service.facade import CruiseControl, _CachedResult

    gen = object()
    dummy = SimpleNamespace(
        _cache_lock=__import__("threading").Lock(),
        _cache=_CachedResult(
            result="RESULT",
            computed_ms=int(time_mod.time() * 1000) + 10**12,  # wall far future
            computed_mono=time_mod.monotonic() - 100.0,  # monotonic: 100s old
            model_generation=gen,
        ),
        _proposal_expiration_ms=50_000,
        monitor=SimpleNamespace(model_generation=lambda: gen),
    )
    # 100s old > 50s expiry -> stale, even though wall clock says "future"
    assert CruiseControl._valid_cache(dummy) is None
    dummy._cache = _CachedResult(
        "RESULT", 0, time_mod.monotonic(), gen
    )
    assert CruiseControl._valid_cache(dummy) == "RESULT"


def test_strict_destination_mask_rejects_padding_brokers():
    """add_broker aimed at a padding-row id must fail loudly, not silently
    degrade into an unconstrained rebalance."""
    state = pad_state(small_cluster(), POLICY.bucket_shape(small_cluster().shape))
    assert state.shape.B > 3  # padded broker axis
    cc = SimpleNamespace(
        config=SimpleNamespace(get=lambda k: ""),
        monitor=SimpleNamespace(last_catalog=None),
    )
    from cruise_control_tpu.service.facade import CruiseControl

    with pytest.raises(ValueError, match="not in the cluster model"):
        CruiseControl._build_options(cc, state, destination_broker_ids=[state.shape.B - 1])
    opts = CruiseControl._build_options(cc, state, destination_broker_ids=[1])
    assert opts.requested_destination_brokers is not None
