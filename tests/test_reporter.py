"""Metrics reporter + reporter-sampler tests.

Mirrors reference CruiseControlMetricsReporterTest (reporter produces real
metrics that the sampler consumes, SURVEY §4.5) fully in-process.
"""

import numpy as np
import pytest

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.monitor.reporter_sampler import CruiseControlMetricsReporterSampler
from cruise_control_tpu.reporter import (
    BrokerMetric,
    InMemoryTransport,
    MetricSerde,
    MetricsRegistrySnapshotter,
    MetricsReporter,
    MetricType,
    PartitionMetric,
    TopicMetric,
)
from cruise_control_tpu.monitor.topology import BrokerNode, ClusterTopology, PartitionInfo


def test_serde_roundtrip():
    cases = [
        BrokerMetric(MetricType.BROKER_CPU_UTIL, 12345, 3, 0.75),
        TopicMetric(MetricType.TOPIC_BYTES_IN, 99, 1, 1024.5, topic="T0"),
        PartitionMetric(MetricType.PARTITION_SIZE, 7, 2, 5e6, topic="T1", partition=42),
    ]
    for m in cases:
        out = MetricSerde.deserialize(MetricSerde.serialize(m))
        assert out == m


def topo():
    brokers = (BrokerNode(0, "r0", "h0"), BrokerNode(1, "r1", "h1"))
    parts = (
        PartitionInfo("T0", 0, leader=0, replicas=(0, 1)),
        PartitionInfo("T0", 1, leader=0, replicas=(0, 1)),
        PartitionInfo("T0", 2, leader=1, replicas=(1, 0)),
    )
    return ClusterTopology(brokers=brokers, partitions=parts)


def test_reporter_to_sampler_pipeline():
    t = topo()
    transport = InMemoryTransport()

    def source_b0():
        return {
            "broker": {
                MetricType.BROKER_CPU_UTIL: 40.0,
                MetricType.BROKER_LOG_FLUSH_TIME_MS_MEAN: 5.0,
            },
            "topics": {"T0": {MetricType.TOPIC_BYTES_IN: 300.0,
                              MetricType.TOPIC_BYTES_OUT: 600.0}},
            "partitions": {("T0", 0): 1000.0, ("T0", 1): 2000.0},
        }

    reporter = MetricsReporter(
        MetricsRegistrySnapshotter(0, source_b0), transport, reporting_interval_ms=10
    )
    n = reporter.report_once(now_ms=1000)
    assert n == 6  # 2 broker + 2 topic + 2 partition records

    sampler = CruiseControlMetricsReporterSampler(transport, lambda: t)
    result = sampler.get_samples([], 0, 2000)
    # broker 0 leads T0-0 and T0-1
    assert len(result.partition_samples) == 2
    by_part = {s.entity.partition: s.values for s in result.partition_samples}
    md = sampler.metric_def
    nwin = md.metric_id("LEADER_BYTES_IN")
    disk = md.metric_id("DISK_USAGE")
    cpu = md.metric_id("CPU_USAGE")
    # byte attribution by size share: partition 1 is 2x partition 0
    assert by_part[1][nwin] == pytest.approx(200.0)
    assert by_part[0][nwin] == pytest.approx(100.0)
    assert by_part[0][disk] == 1000.0
    # CPU attribution sums to the broker CPU
    assert by_part[0][cpu] + by_part[1][cpu] == pytest.approx(40.0)
    # broker-only metrics surface as broker samples
    assert len(result.broker_samples) == 1
    bs = result.broker_samples[0]
    assert bs.values[md.metric_id("BROKER_LOG_FLUSH_TIME_MS_MEAN")] == 5.0
    # second poll: stream drained
    assert sampler.get_samples([], 0, 2000).partition_samples == []
