"""Metrics reporter + reporter-sampler tests.

Mirrors reference CruiseControlMetricsReporterTest (reporter produces real
metrics that the sampler consumes, SURVEY §4.5) fully in-process.
"""

import numpy as np
import pytest

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.monitor.reporter_sampler import CruiseControlMetricsReporterSampler
from cruise_control_tpu.reporter import (
    BrokerMetric,
    InMemoryTransport,
    MetricSerde,
    MetricsRegistrySnapshotter,
    MetricsReporter,
    MetricType,
    PartitionMetric,
    TopicMetric,
)
from cruise_control_tpu.monitor.topology import BrokerNode, ClusterTopology, PartitionInfo


def test_serde_roundtrip():
    cases = [
        BrokerMetric(MetricType.BROKER_CPU_UTIL, 12345, 3, 0.75),
        TopicMetric(MetricType.TOPIC_BYTES_IN, 99, 1, 1024.5, topic="T0"),
        PartitionMetric(MetricType.PARTITION_SIZE, 7, 2, 5e6, topic="T1", partition=42),
    ]
    for m in cases:
        out = MetricSerde.deserialize(MetricSerde.serialize(m))
        assert out == m


def topo():
    brokers = (BrokerNode(0, "r0", "h0"), BrokerNode(1, "r1", "h1"))
    parts = (
        PartitionInfo("T0", 0, leader=0, replicas=(0, 1)),
        PartitionInfo("T0", 1, leader=0, replicas=(0, 1)),
        PartitionInfo("T0", 2, leader=1, replicas=(1, 0)),
    )
    return ClusterTopology(brokers=brokers, partitions=parts)


def test_reporter_to_sampler_pipeline():
    t = topo()
    transport = InMemoryTransport()

    def source_b0():
        return {
            "broker": {
                MetricType.BROKER_CPU_UTIL: 40.0,
                MetricType.BROKER_LOG_FLUSH_TIME_MS_MEAN: 5.0,
            },
            "topics": {"T0": {MetricType.TOPIC_BYTES_IN: 300.0,
                              MetricType.TOPIC_BYTES_OUT: 600.0}},
            "partitions": {("T0", 0): 1000.0, ("T0", 1): 2000.0},
        }

    reporter = MetricsReporter(
        MetricsRegistrySnapshotter(0, source_b0), transport, reporting_interval_ms=10
    )
    n = reporter.report_once(now_ms=1000)
    assert n == 6  # 2 broker + 2 topic + 2 partition records

    sampler = CruiseControlMetricsReporterSampler(transport, lambda: t)
    result = sampler.get_samples([], 0, 2000)
    # broker 0 leads T0-0 and T0-1
    assert len(result.partition_samples) == 2
    by_part = {s.entity.partition: s.values for s in result.partition_samples}
    md = sampler.metric_def
    nwin = md.metric_id("LEADER_BYTES_IN")
    disk = md.metric_id("DISK_USAGE")
    cpu = md.metric_id("CPU_USAGE")
    # byte attribution by size share: partition 1 is 2x partition 0
    assert by_part[1][nwin] == pytest.approx(200.0)
    assert by_part[0][nwin] == pytest.approx(100.0)
    assert by_part[0][disk] == 1000.0
    # CPU attribution sums to the broker CPU
    assert by_part[0][cpu] + by_part[1][cpu] == pytest.approx(40.0)
    # broker-only metrics surface as broker samples
    assert len(result.broker_samples) == 1
    bs = result.broker_samples[0]
    assert bs.values[md.metric_id("BROKER_LOG_FLUSH_TIME_MS_MEAN")] == 5.0
    # second poll: stream drained
    assert sampler.get_samples([], 0, 2000).partition_samples == []


# ---------------------------------------------------------------------------
# reference wire-format interop (VERDICT r4 missing #2 / do-this #6): records
# produced by the REFERENCE's in-broker plugin decode end-to-end
# ---------------------------------------------------------------------------

import struct

from cruise_control_tpu.reporter.metrics import (
    _REF_ID_BY_TYPE,
    _REF_TYPE_BY_ID,
    ReferenceMetricSerde,
)


def test_reference_serde_golden_bytes():
    """Hand-assembled frames per the reference's layouts:
    MetricSerde.java (class-id header), BrokerMetric.java:30-41,
    TopicMetric.java:37-52, PartitionMetric.java:44-60 — big-endian,
    value LAST, topic length an i32."""
    b = BrokerMetric(MetricType.BROKER_CPU_UTIL, 1234, 7, 0.5)
    expect_b = (
        b"\x00"                      # class id 0 = BROKER_METRIC
        + b"\x00"                    # version 0
        + b"\x05"                    # RawMetricType.BROKER_CPU_UTIL id 5
        + struct.pack(">q", 1234)
        + struct.pack(">i", 7)
        + struct.pack(">d", 0.5)
    )
    assert ReferenceMetricSerde.serialize(b) == expect_b
    assert ReferenceMetricSerde.deserialize(expect_b) == b

    t = TopicMetric(MetricType.TOPIC_BYTES_IN, 99, 1, 1024.5, topic="T0")
    expect_t = (
        b"\x01\x00\x02"              # class 1, version 0, TOPIC_BYTES_IN id 2
        + struct.pack(">q", 99) + struct.pack(">i", 1)
        + struct.pack(">i", 2) + b"T0"
        + struct.pack(">d", 1024.5)
    )
    assert ReferenceMetricSerde.serialize(t) == expect_t
    assert ReferenceMetricSerde.deserialize(expect_t) == t

    p = PartitionMetric(MetricType.PARTITION_SIZE, 7, 2, 5e6, topic="T1", partition=42)
    expect_p = (
        b"\x02\x00\x04"              # class 2, version 0, PARTITION_SIZE id 4
        + struct.pack(">q", 7) + struct.pack(">i", 2)
        + struct.pack(">i", 2) + b"T1"
        + struct.pack(">i", 42)
        + struct.pack(">d", 5e6)
    )
    assert ReferenceMetricSerde.serialize(p) == expect_p
    assert ReferenceMetricSerde.deserialize(expect_p) == p


def test_reference_id_table_complete_and_pinned():
    """All 63 reference RawMetricType ids (0-62) map; spot-pin ids straight
    from RawMetricType.java:27-97."""
    assert sorted(_REF_TYPE_BY_ID) == list(range(63))
    pins = {
        0: MetricType.ALL_TOPIC_BYTES_IN,
        2: MetricType.TOPIC_BYTES_IN,
        4: MetricType.PARTITION_SIZE,
        5: MetricType.BROKER_CPU_UTIL,
        19: MetricType.BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT,
        40: MetricType.BROKER_LOG_FLUSH_RATE,
        43: MetricType.BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_50TH,
        62: MetricType.BROKER_LOG_FLUSH_TIME_MS_999TH,
    }
    for ref_id, mt in pins.items():
        assert _REF_TYPE_BY_ID[ref_id] is mt
        assert _REF_ID_BY_TYPE[mt] == ref_id


def test_reference_serde_roundtrip_every_type():
    for ref_id, mt in _REF_TYPE_BY_ID.items():
        if mt.is_partition_scope:
            m = PartitionMetric(mt, 5, 1, 2.0, topic="t", partition=3)
        elif mt.is_topic_scope:
            m = TopicMetric(mt, 5, 1, 2.0, topic="t")
        else:
            m = BrokerMetric(mt, 5, 1, 2.0)
        assert ReferenceMetricSerde.deserialize(ReferenceMetricSerde.serialize(m)) == m


def test_reference_serde_skips_unknown_class_id():
    """A newer metric class decodes to None (reference fromBytes returns
    null), and the transport drops it instead of failing the poll."""
    frame = b"\x09" + b"\x00\x05" + struct.pack(">qid", 1, 1, 1.0)
    assert ReferenceMetricSerde.deserialize(frame) is None
    tr = InMemoryTransport(serde=ReferenceMetricSerde)
    tr.send(frame)
    tr.send(ReferenceMetricSerde.serialize(BrokerMetric(MetricType.BROKER_CPU_UTIL, 1, 0, 9.0)))
    polled = tr.poll()
    assert len(polled) == 1 and polled[0].value == 9.0


def test_reference_format_records_flow_into_aggregator():
    """End-to-end drop-in: reference-format records (as the reference's
    in-broker plugin produces them — including broker-INTERNAL metrics no
    process-external sidecar could observe) -> transport -> sampler ->
    windowed aggregator -> valid aggregated loads."""
    from cruise_control_tpu.monitor import (
        KAFKA_METRIC_DEF,
        WindowedMetricSampleAggregator,
    )

    t = topo()
    transport = InMemoryTransport(serde=ReferenceMetricSerde)
    assert transport.framed_native is False  # native columnar path is bypassed

    records = [
        BrokerMetric(MetricType.BROKER_CPU_UTIL, 500, 0, 40.0),
        # broker-internal metrics: the SlowBrokerFinder's inputs
        BrokerMetric(MetricType.BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT, 500, 0, 0.8),
        BrokerMetric(MetricType.BROKER_PRODUCE_LOCAL_TIME_MS_MEAN, 500, 0, 3.5),
        BrokerMetric(MetricType.BROKER_PRODUCE_LOCAL_TIME_MS_999TH, 500, 0, 25.0),
        TopicMetric(MetricType.TOPIC_BYTES_IN, 500, 0, 300.0, topic="T0"),
        TopicMetric(MetricType.TOPIC_BYTES_OUT, 500, 0, 600.0, topic="T0"),
        PartitionMetric(MetricType.PARTITION_SIZE, 500, 0, 1000.0, topic="T0", partition=0),
        PartitionMetric(MetricType.PARTITION_SIZE, 500, 0, 2000.0, topic="T0", partition=1),
    ]
    for m in records:
        transport.send(ReferenceMetricSerde.serialize(m))

    sampler = CruiseControlMetricsReporterSampler(transport, lambda: t)
    result = sampler.get_samples([], 0, 1000)
    assert len(result.partition_samples) == 2
    assert len(result.broker_samples) == 1
    md = sampler.metric_def
    bvals = result.broker_samples[0].values
    assert bvals[md.metric_id("BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT")] == pytest.approx(0.8)
    assert bvals[md.metric_id("BROKER_PRODUCE_LOCAL_TIME_MS_MEAN")] == pytest.approx(3.5)
    # percentile latency (reference reporter id-space 43-62) landed too
    assert bvals[md.metric_id("BROKER_PRODUCE_LOCAL_TIME_MS_999TH")] == pytest.approx(25.0)

    agg = WindowedMetricSampleAggregator(3, 1000, 1, KAFKA_METRIC_DEF)
    for s in result.partition_samples:
        assert agg.add_sample(s.entity, s.time_ms, s.values)
    # a second reporting round rolls the window forward so window 0 completes
    import dataclasses as _dc

    for m in records:
        transport.send(
            ReferenceMetricSerde.serialize(_dc.replace(m, time_ms=1500))
        )
    for s in CruiseControlMetricsReporterSampler(
        transport, lambda: t
    ).get_samples([], 1000, 2000).partition_samples:
        agg.add_sample(s.entity, s.time_ms, s.values)
    res = agg.aggregate()
    assert res.entity_valid.sum() == 2
    nwin = md.metric_id("LEADER_BYTES_IN")
    w0 = list(res.window_indices).index(0)
    # byte attribution by size share survived the reference wire format
    total_in = res.values[:, w0, nwin][res.entity_valid].sum()
    assert total_in == pytest.approx(300.0)
