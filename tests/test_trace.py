"""Flight recorder + Prometheus exposition unit tests.

Covers the three new observability modules on their own (common/trace.py,
common/exposition.py, common/profiling.py) plus the Histogram/Collector
sensor types; the end-to-end "one trace ID covers the whole pipeline"
acceptance story lives in tests/test_service.py (it needs the simulated
service).
"""

import threading

import pytest

from cruise_control_tpu.common.exposition import (
    CONTENT_TYPE,
    ExpositionError,
    metric_name,
    parse_exposition,
    prometheus_text,
)
from cruise_control_tpu.common.sensors import (
    Collector,
    Histogram,
    SensorRegistry,
)
from cruise_control_tpu.common.trace import NOOP_SPAN, Tracer


# ------------------------------------------------------------------ tracer


def test_span_lifecycle_and_attributes():
    tr = Tracer()
    with tr.span("analyzer.optimize", component="analyzer") as sp:
        sp.set(bucket="R3.B32.P2048.T16", engine_cache_hit=True)
        sp.event("round", n=1)
    assert sp.duration_s is not None and sp.duration_s >= 0
    j = sp.to_json()
    assert j["name"] == "analyzer.optimize"
    assert j["component"] == "analyzer"
    assert j["attributes"]["engine_cache_hit"] is True
    assert j["events"][0]["name"] == "round"
    assert j["events"][0]["offset_s"] >= 0
    assert not j["inFlight"]


def test_context_parentage_nests_spans():
    tr = Tracer()
    with tr.span("service.proposals") as root:
        with tr.span("monitor.cluster_model", component="monitor") as child:
            with tr.span("analyzer.optimize", component="analyzer") as grand:
                pass
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert grand.parent_id == child.span_id
    tree = tr.trace_tree(root.trace_id)
    assert len(tree) == 1
    assert tree[0]["name"] == "service.proposals"
    assert tree[0]["children"][0]["name"] == "monitor.cluster_model"
    assert tree[0]["children"][0]["children"][0]["name"] == "analyzer.optimize"


def test_root_flag_detaches_from_context():
    """A detector/recovery flow must not attach to whatever request
    context its thread inherited."""
    tr = Tracer()
    with tr.span("service.rebalance") as req:
        with tr.span("detector.handle", root=True) as det:
            pass
    assert det.parent_id is None
    assert det.trace_id != req.trace_id


def test_explicit_trace_id_propagates_cross_thread():
    """The purgatory hands the pool thread an explicit trace id (context
    vars do not cross threads)."""
    tr = Tracer()
    tid = tr.new_trace_id()
    out = {}

    def work():
        with tr.span("service.rebalance", trace_id=tid, root=True) as sp:
            with tr.span("analyzer.optimize", component="analyzer"):
                pass
            out["span"] = sp

    t = threading.Thread(target=work)
    t.start()
    t.join()
    spans = tr.trace(tid)
    assert len(spans) == 2
    assert {s.trace_id for s in spans} == {tid}


def test_disabled_tracer_hands_out_noop():
    tr = Tracer(enabled=False)
    with tr.span("anything") as sp:
        sp.set(x=1)
        sp.event("e")
    assert sp is NOOP_SPAN
    assert tr._all_spans() == []
    # a noop parent never leaks into a real tracer's spans
    assert tr.current() is None


def test_error_recorded_and_reraised():
    tr = Tracer()
    with pytest.raises(RuntimeError):
        with tr.span("service.rebalance") as sp:
            raise RuntimeError("boom")
    assert sp.error is not None and "boom" in sp.error
    assert sp.duration_s is not None


def test_ring_retention_is_per_component():
    tr = Tracer(retention_per_component=4)
    for i in range(10):
        tr.start_span(f"device.op{i}", component="device", root=True).finish()
    keeper = tr.start_span("executor.execution", component="executor", root=True)
    keeper.finish()
    spans = tr._all_spans()
    assert sum(1 for s in spans if s.component == "device") == 4
    # the chatty device ring never evicted the executor's span
    assert any(s.component == "executor" for s in spans)


def test_event_bound_counts_drops():
    tr = Tracer(max_events_per_span=8)
    sp = tr.start_span("executor.execution", component="executor", root=True)
    for i in range(20):
        sp.event("task", n=i)
    sp.finish()
    assert len(sp.events) == 8
    assert sp.events_dropped == 12
    assert sp.to_json()["eventsDropped"] == 12


def test_in_flight_span_visible_immediately():
    """Crash tolerance: a span is published at START, so a live poll shows
    the frontier and a hung stage never vanishes."""
    tr = Tracer()
    sp = tr.start_span("device.engine-run", component="device", root=True)
    [j] = [s.to_json() for s in tr.trace(sp.trace_id)]
    assert j["inFlight"] is True
    assert j["durationMs"] is None
    sp.finish()


def test_orphaned_span_surfaces_as_extra_root():
    """A child whose parent aged out of its ring still appears in the
    tree (as a root) instead of disappearing."""
    tr = Tracer(retention_per_component=1)
    parent = tr.start_span("service.op", component="service", root=True)
    child = tr.start_span("device.op", component="device", parent=parent)
    child.finish()
    parent.finish()
    # evict the parent from the service ring
    tr.start_span("service.other", component="service", root=True).finish()
    tree = tr.trace_tree(parent.trace_id)
    assert [n["name"] for n in tree] == ["device.op"]


def test_recent_traces_and_summary():
    tr = Tracer()
    with tr.span("service.rebalance") as root:
        with tr.span("analyzer.optimize", component="analyzer"):
            pass
        with tr.span("analyzer.optimize", component="analyzer"):
            pass
    recent = tr.recent_traces()
    assert recent[0]["traceId"] == root.trace_id
    assert recent[0]["name"] == "service.rebalance"
    summary = tr.summarize(root.trace_id)
    assert summary["analyzer.optimize"]["count"] == 2
    assert summary["analyzer.optimize"]["totalMs"] >= 0
    assert summary["service.rebalance"]["count"] == 1


def test_tracer_validates_bounds():
    with pytest.raises(ValueError):
        Tracer(retention_per_component=0)
    with pytest.raises(ValueError):
        Tracer(max_events_per_span=0)


# ------------------------------------------------- histogram + collector


def test_histogram_buckets_cumulative():
    h = Histogram(buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    cum, total, n = h.cumulative()
    assert n == 5
    assert abs(total - 56.05) < 1e-9
    assert cum == [(0.1, 1), (1.0, 3), (10.0, 4), (float("inf"), 5)]
    snap = h.snapshot()
    assert snap["type"] == "histogram"
    assert snap["buckets"][-1] == {"le": "+Inf", "count": 5}


def test_histogram_boundary_value_lands_in_its_bucket():
    # le is INCLUSIVE (Prometheus convention): observe(1.0) counts in le=1.0
    h = Histogram(buckets=(1.0, 2.0))
    h.observe(1.0)
    cum, _, _ = h.cumulative()
    assert cum[0] == (1.0, 1)


def test_histogram_rejects_bad_buckets():
    with pytest.raises(ValueError):
        Histogram(buckets=())
    with pytest.raises(ValueError):
        Histogram(buckets=(1.0, 1.0))


def test_collector_labels_and_failing_callback():
    c = Collector(lambda: [({"bucket": "a"}, 1.5), ({"bucket": "b"}, 2.5)])
    assert c.values() == [({"bucket": "a"}, 1.5), ({"bucket": "b"}, 2.5)]

    def boom():
        raise RuntimeError("no")

    assert Collector(boom).values() == []


def test_registry_histogram_and_collector_in_snapshot():
    reg = SensorRegistry()
    reg.histogram("analyzer.proposal-computation-seconds").observe(0.2)
    reg.collector("tpu.device.memory-by-device",
                  lambda: [({"device": "0"}, 123.0)])
    snap = reg.snapshot()
    assert snap["analyzer.proposal-computation-seconds"]["count"] == 1
    assert snap["tpu.device.memory-by-device"]["values"] == [
        {"labels": {"device": "0"}, "value": 123.0}
    ]


# ------------------------------------------------------------ exposition


def test_metric_name_sanitization():
    assert metric_name("analyzer.engine-cache-hits") == (
        "cruisecontrol_analyzer_engine_cache_hits"
    )
    assert metric_name("x", namespace="") == "x"
    assert metric_name("0bad", namespace="") == "_0bad"


def test_prometheus_text_round_trips_through_the_lint_parser():
    reg = SensorRegistry()
    reg.counter("analyzer.engine-cache-hits").inc(3)
    reg.gauge("analyzer.engine-cache-size").set(2.0)
    t = reg.timer("monitor.cluster-model-creation-timer")
    t.update(0.05)
    t.update(0.07)
    reg.meter("anomaly-detector.mean-time-between-anomalies").mark()
    h = reg.histogram("analyzer.proposal-computation-seconds")
    h.observe(0.3)
    h.observe(7.0)
    reg.collector(
        "tpu.device.memory-by-device",
        lambda: [({"device": "0", "platform": "cpu"}, 1024.0)],
    )
    text = prometheus_text(reg)
    assert text.endswith("\n")
    fams = parse_exposition(text)
    assert fams["cruisecontrol_analyzer_engine_cache_hits_total"]["type"] == "counter"
    assert fams["cruisecontrol_analyzer_engine_cache_hits_total"]["samples"][0][2] == 3.0
    summary = fams["cruisecontrol_monitor_cluster_model_creation_timer_seconds"]
    assert summary["type"] == "summary"
    names = [s[0] for s in summary["samples"]]
    assert "cruisecontrol_monitor_cluster_model_creation_timer_seconds_count" in names
    hist = fams["cruisecontrol_analyzer_proposal_computation_seconds"]
    assert hist["type"] == "histogram"
    dev = fams["cruisecontrol_tpu_device_memory_by_device"]
    assert dev["samples"][0][1] == {"device": "0", "platform": "cpu"}


def test_exposition_label_escaping():
    reg = SensorRegistry()
    reg.collector(
        "planner.weird",
        lambda: [({"name": 'a"b\\c\nnewline'}, 1.0)],
    )
    text = prometheus_text(reg)
    fams = parse_exposition(text)
    assert fams["cruisecontrol_planner_weird"]["samples"][0][1]["name"] == (
        'a"b\\c\nnewline'
    )


def test_exposition_detects_family_collision():
    reg = SensorRegistry()
    reg.counter("a.b").inc()
    reg.counter("a-b").inc()
    with pytest.raises(ValueError, match="sanitize to the same"):
        prometheus_text(reg)


def test_lint_rejects_sample_without_type():
    with pytest.raises(ExpositionError, match="no preceding TYPE"):
        parse_exposition("orphan_metric 1\n")


def test_lint_rejects_duplicate_type_and_bad_counter_name():
    with pytest.raises(ExpositionError, match="duplicate TYPE"):
        parse_exposition(
            "# TYPE x_total counter\n# TYPE x_total counter\nx_total 1\n"
        )
    with pytest.raises(ExpositionError, match="must end in _total"):
        parse_exposition("# TYPE x counter\nx 1\n")


def test_lint_rejects_negative_counter_and_bad_value():
    with pytest.raises(ExpositionError, match="negative"):
        parse_exposition("# TYPE x_total counter\nx_total -1\n")
    with pytest.raises(ExpositionError, match="unparseable value"):
        parse_exposition("# TYPE g gauge\ng notanumber\n")


def test_lint_rejects_nonmonotonic_histogram():
    body = (
        "# TYPE h histogram\n"
        'h_bucket{le="1"} 5\n'
        'h_bucket{le="+Inf"} 3\n'
        "h_sum 1\nh_count 3\n"
    )
    with pytest.raises(ExpositionError, match="decreases"):
        parse_exposition(body)


def test_lint_rejects_inf_bucket_count_mismatch():
    body = (
        "# TYPE h histogram\n"
        'h_bucket{le="1"} 1\n'
        'h_bucket{le="+Inf"} 3\n'
        "h_sum 1\nh_count 4\n"
    )
    with pytest.raises(ExpositionError, match="!= _count"):
        parse_exposition(body)


def test_content_type_is_prometheus_text():
    assert "text/plain" in CONTENT_TYPE and "0.0.4" in CONTENT_TYPE


# ------------------------------------------------------------- profiling


def test_profiler_trace_noop_without_dir():
    from cruise_control_tpu.common.profiling import profiler_trace

    ran = []
    with profiler_trace(None):
        ran.append(1)
    with profiler_trace(""):
        ran.append(2)
    assert ran == [1, 2]


def test_profiler_trace_survives_unwritable_dir():
    """A profiler that cannot start must never fail the run it observes."""
    from cruise_control_tpu.common.profiling import profiler_trace

    ran = []
    with profiler_trace("/proc/definitely-not-writable/x"):
        ran.append(1)
    assert ran == [1]


def test_device_gauges_register_and_read():
    from cruise_control_tpu.common.profiling import register_device_gauges

    reg = SensorRegistry()
    register_device_gauges(reg)
    snap = reg.snapshot()
    for name in (
        "tpu.device.memory-in-use-bytes",
        "tpu.device.memory-limit-bytes",
        "tpu.device.live-buffers",
        "tpu.device.memory-by-device",
    ):
        assert name in snap
    # CPU backend: values are numbers (0.0 where no stats), never raising
    assert isinstance(snap["tpu.device.live-buffers"]["value"], float)
