"""Parity tests: the on-device sanity check (models/state.py
validate_on_device, used on the optimizer's hot path to avoid bulk
device->host transfers on tunneled TPUs) must agree with the host
validate() on every invariant (reference ClusterModel.sanityCheck:1081)."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.models.state import (
    DEVICE_CHECKS,
    validate,
    validate_on_device,
)
from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster


@pytest.fixture(scope="module")
def state():
    return random_cluster(
        RandomClusterSpec(num_brokers=10, num_partitions=200), seed=1
    )


def _counts(s):
    return np.asarray(validate_on_device(s))


def test_clean_state_passes_both(state):
    assert not _counts(state).any()
    assert validate(state) == []


def test_duplicate_replica_detected(state):
    brk = np.asarray(state.replica_broker).copy()
    valid = np.asarray(state.replica_valid)
    part = np.asarray(state.replica_partition)
    idx = np.nonzero(valid)[0]
    same = idx[part[idx] == part[idx[0]]]
    brk[same[1]] = brk[same[0]]
    bad = dataclasses.replace(state, replica_broker=jnp.asarray(brk))
    assert _counts(bad)[DEVICE_CHECKS.index(
        "duplicate replica of a partition on one broker")] >= 1
    assert any("duplicate" in p for p in validate(bad, strict=False))


def test_missing_leader_detected(state):
    valid = np.asarray(state.replica_valid)
    part = np.asarray(state.replica_partition)
    lead = np.asarray(state.replica_is_leader).copy()
    idx = np.nonzero(valid)[0]
    lead[idx[part[idx] == part[idx[0]]]] = False
    bad = dataclasses.replace(state, replica_is_leader=jnp.asarray(lead))
    assert _counts(bad)[DEVICE_CHECKS.index(
        "partitions without exactly one leader")] >= 1
    assert any("leader" in p for p in validate(bad, strict=False))


def test_bad_load_detected(state):
    ll = np.asarray(state.replica_load_leader).copy()
    ll[np.nonzero(np.asarray(state.replica_valid))[0][0], 0] = -1.0
    bad = dataclasses.replace(state, replica_load_leader=jnp.asarray(ll))
    assert _counts(bad)[DEVICE_CHECKS.index(
        "non-finite or negative leader loads")] >= 1


def test_out_of_range_broker_detected(state):
    brk = np.asarray(state.replica_broker).copy()
    brk[np.nonzero(np.asarray(state.replica_valid))[0][0]] = state.shape.B + 7
    bad = dataclasses.replace(state, replica_broker=jnp.asarray(brk))
    assert _counts(bad)[DEVICE_CHECKS.index("broker ids out of range")] >= 1
    assert any("out of range" in p for p in validate(bad, strict=False))


def test_optimizer_raises_on_corrupt_result(state, monkeypatch):
    """optimize() must fail loudly when the device check flags the result."""
    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig

    opt = GoalOptimizer(config=OptimizerConfig(
        num_candidates=128, leadership_candidates=32,
        steps_per_round=4, num_rounds=1))

    class _BadEngine:
        def run(self, verbose=False):
            brk = np.asarray(state.replica_broker).copy()
            valid = np.asarray(state.replica_valid)
            brk[np.nonzero(valid)[0][0]] = state.shape.B + 1
            return dataclasses.replace(
                state, replica_broker=jnp.asarray(brk)
            ), []

    monkeypatch.setattr(opt, "_engine_for", lambda *a, **k: (_BadEngine(), {}))
    # the device check flags the corrupt result, then the host validator
    # raises with the detailed per-invariant message
    with pytest.raises(ValueError, match="sanity check"):
        opt.optimize(state)
