"""Load-monitor task runner tests: bootstrap modes, training, state machine.

Mirrors reference LoadMonitorTaskRunnerTest (SURVEY §4.5) over the
simulated backend: BOOTSTRAPPING/TRAINING/LOADING transitions, the three
bootstrap modes (BootstrapTask.java), and the /train -> regression ->
CPU-estimator flip (TrainingTask.java, LinearRegressionModelParameters).
"""

import time

import numpy as np
import pytest

from cruise_control_tpu.config import CruiseControlConfig
from cruise_control_tpu.monitor.load_monitor import MonitorState
from cruise_control_tpu.service.main import build_simulated_service


def _fresh_service(seed=11, **extra):
    config = CruiseControlConfig(
        {
            "partition.metrics.window.ms": 1000,
            "min.samples.per.partition.metrics.window": 1,
            "broker.metrics.window.ms": 1000,
            "execution.progress.check.interval.ms": 100,
            "webserver.http.port": 0,
            **extra,
        }
    )
    return build_simulated_service(config, seed=seed)


def test_bootstrap_range_fills_windows():
    app, fetcher, admin, sampler = _fresh_service()
    runner = app.cc.task_runner
    assert runner is not None
    before = fetcher.total_samples
    n = runner.bootstrap_range(0, 3000, clear_metrics=False)
    assert n > 0
    assert fetcher.total_samples == before + n
    # state machine returned to its pre-bootstrap state
    assert app.cc.monitor.state not in (MonitorState.BOOTSTRAPPING,)
    assert runner.state()["bootstrapProgressPct"] == 100.0


def test_bootstrap_clear_metrics_resets_aggregator():
    app, fetcher, admin, sampler = _fresh_service()
    runner = app.cc.task_runner
    agg_before = app.cc.monitor.partition_aggregator
    runner.bootstrap_range(0, 2000, clear_metrics=True)
    assert app.cc.monitor.partition_aggregator is not agg_before
    assert fetcher.partition_aggregator is app.cc.monitor.partition_aggregator


def test_bootstrap_recent_and_since():
    app, fetcher, admin, sampler = _fresh_service()
    runner = app.cc.task_runner
    assert runner.bootstrap_recent() > 0
    now = int(time.time() * 1000)
    assert runner.bootstrap_since(now - 2000) > 0


def test_busy_state_is_exclusive():
    app, fetcher, admin, sampler = _fresh_service()
    runner = app.cc.task_runner
    runner._enter(MonitorState.BOOTSTRAPPING)
    try:
        with pytest.raises(RuntimeError):
            runner.train(0, 1000)
        with pytest.raises(RuntimeError):
            runner.load_samples()
    finally:
        runner._exit()
    # after exit, training is allowed again
    runner.train(0, int(time.time() * 1000))


def test_training_flips_cpu_estimator():
    app, fetcher, admin, sampler = _fresh_service()
    runner = app.cc.task_runner
    runner.regression.min_samples_to_train = 10
    # feed several windows of broker samples
    parts = sampler.all_partition_entities()
    for w in range(4, 10):
        fetcher.fetch_once(parts, w * 1000, (w + 1) * 1000 - 1)
    out = runner.train(0, int(time.time() * 1000))
    assert out["trained"] is True
    coef = np.asarray(runner.regression.coefficients)
    # synthetic broker CPU = 2e-4*lbin + 5e-5*lbout + 1e-4*fbin (+noise):
    # the closed-form fit must recover the follower-bytes-in weight
    assert coef[2] == pytest.approx(1e-4, rel=0.25)
    # the monitor now uses the trained estimator for follower CPU
    assert app.cc.monitor.regression is runner.regression
    assert app.cc.monitor.regression.trained
    loads = np.tile(np.array([[1.0, 100.0, 120.0, 500.0]], np.float32), (3, 1))
    est = runner.regression.follower_cpu_array(loads)
    assert est == pytest.approx(coef[2] * 100.0, rel=1e-5)


def test_train_without_enough_samples_reports_untrained():
    app, fetcher, admin, sampler = _fresh_service()
    runner = app.cc.task_runner
    runner.regression.min_samples_to_train = 10_000
    out = runner.train(0, int(time.time() * 1000))
    assert out["trained"] is False
    assert app.cc.monitor.regression.trained is False


def test_bootstrap_and_train_endpoints():
    import json
    import urllib.request

    app, fetcher, admin, sampler = _fresh_service()
    app.cc.task_runner.regression.min_samples_to_train = 5
    app.start()
    try:
        def poll(endpoint, **params):
            q = "&".join(f"{k}={v}" for k, v in params.items())
            url = f"http://{app.host}:{app.port}{app.prefix}/{endpoint}" + (
                f"?{q}" if q else ""
            )
            req = urllib.request.Request(url, method="GET")
            with urllib.request.urlopen(req, timeout=30) as resp:
                payload = json.loads(resp.read())
                tid = resp.headers.get("User-Task-ID")
                status = resp.status
            deadline = time.time() + 30
            while status == 202 and time.time() < deadline:
                time.sleep(0.2)
                req = urllib.request.Request(
                    url, method="GET", headers={"User-Task-ID": tid}
                )
                with urllib.request.urlopen(req, timeout=30) as resp:
                    payload = json.loads(resp.read())
                    status = resp.status
            return status, payload

        status, payload = poll("bootstrap", start="0", end="3000")
        assert status == 200
        assert payload["mode"] == "RANGE" and payload["samplesAbsorbed"] > 0
        status, payload = poll("bootstrap", start="0")
        assert status == 200 and payload["mode"] == "SINCE"
        status, payload = poll("train")
        assert status == 200
        assert payload["trained"] is True
        # /state surfaces the training state
        status, payload = poll("state", substates="monitor")
        assert payload["MonitorState"]["trainingState"]["trained"] is True
    finally:
        app.stop()


def test_train_respects_requested_range_with_distinct_broker_window():
    """/train?start&end must filter BROKER windows by the broker window
    span, not the partition span (they differ by 12x under defaults)."""
    app, fetcher, admin, sampler = _fresh_service(
        seed=13, **{"broker.metrics.window.ms": 500}
    )
    runner = app.cc.task_runner
    runner.regression.min_samples_to_train = 1
    # samples were fetched over windows starting at t=0 (build_simulated_service)
    out_none = runner.train(10_000_000, 20_000_000)  # empty range
    assert out_none["numSamples"] == 0
    out_all = runner.train(0, 1_000_000)
    assert out_all["numSamples"] > 0
