"""Multi-device portfolio tests (8-device virtual CPU mesh, conftest.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer import DEFAULT_CHAIN, Engine, OptimizerConfig
from cruise_control_tpu.models.state import validate
from cruise_control_tpu.parallel.portfolio import default_mesh, portfolio_run
from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster


def test_portfolio_runs_on_mesh():
    assert len(jax.devices()) >= 8, "conftest must force 8 virtual devices"
    state = random_cluster(
        RandomClusterSpec(num_brokers=10, num_partitions=150, skew=1.5), seed=11
    )
    cfg = OptimizerConfig(num_candidates=64, leadership_candidates=16, steps_per_round=6)
    eng = Engine(state, DEFAULT_CHAIN, config=cfg)
    temps = jnp.full((6,), 0.05, jnp.float32)
    final, info = portfolio_run(eng, default_mesh(), temps, seed=4)

    assert info["n_chains"] == len(jax.devices())
    # chains must actually explore differently
    assert np.unique(np.round(info["objectives"], 3)).size > 1
    validate(final)
    # the selected winner must be at least as good as the initial state
    obj0, _, _ = DEFAULT_CHAIN.evaluate(state)
    obj1, _, _ = DEFAULT_CHAIN.evaluate(final)
    assert float(obj1) <= float(obj0)


def test_portfolio_winner_matches_best_chain():
    state = random_cluster(
        RandomClusterSpec(num_brokers=8, num_partitions=100, skew=1.0), seed=13
    )
    cfg = OptimizerConfig(num_candidates=32, leadership_candidates=8, steps_per_round=4)
    eng = Engine(state, DEFAULT_CHAIN, config=cfg)
    temps = jnp.full((4,), 0.0, jnp.float32)
    final, info = portfolio_run(eng, default_mesh(), temps, seed=5)
    obj_final, _, _ = DEFAULT_CHAIN.evaluate(final)
    # winner's full objective must track the best chain's SA objective:
    # identical placement, two evaluation paths (engine suff-stats vs goals)
    assert abs(float(obj_final) - float(info["objectives"].min())) < max(
        1e-3, 1e-3 * abs(float(obj_final))
    )


def test_portfolio_multi_round_device_resident():
    """A [rounds, steps] schedule runs every chain's rounds ON-DEVICE
    (plan rebuild + aggregate refresh between rounds in-graph, one
    dispatch) and must beat the single-round run of the same step budget's
    first row — more rounds, never a worse winner than its own prefix."""
    state = random_cluster(
        RandomClusterSpec(num_brokers=10, num_partitions=150, skew=1.5), seed=19
    )
    cfg = OptimizerConfig(num_candidates=64, leadership_candidates=16, steps_per_round=6)
    eng = Engine(state, DEFAULT_CHAIN, config=cfg)
    temps = jnp.zeros((3, 6), jnp.float32)  # 3 greedy rounds, fused
    final, info = portfolio_run(eng, default_mesh(), temps, seed=4)
    validate(final)
    assert info["n_chains"] == len(jax.devices())
    obj0, _, _ = DEFAULT_CHAIN.evaluate(state)
    obj_multi, _, _ = DEFAULT_CHAIN.evaluate(final)
    assert float(obj_multi) < float(obj0)

    final_1, _ = portfolio_run(eng, default_mesh(), temps[0], seed=4)
    obj_1, _, _ = DEFAULT_CHAIN.evaluate(final_1)
    # 3 greedy rounds from the same seeds can only improve on round 1
    assert float(obj_multi) <= float(obj_1) + max(1e-5, abs(float(obj_1)) * 1e-3)


def test_mesh_modes_after_device_committed_service_run():
    """Regression for the r4 multi-device failure: the in-process service
    COMMITS engine arrays to one device (its single-device optimize run),
    and the mesh programs that ran afterwards in the same process crashed
    with a devices mismatch (r4 `portfolio.py:99`).  The mesh layer now
    places explicit mesh-replicated copies (`MeshEngine._place_statics`),
    so service-then-mesh must work in ONE process, in this order."""
    from cruise_control_tpu.analyzer import DEFAULT_CHAIN as CHAIN
    from cruise_control_tpu.parallel.grid import GridEngine, grid_mesh
    from cruise_control_tpu.parallel.sharded import ShardedEngine, model_mesh
    from cruise_control_tpu.service.main import build_simulated_service
    from cruise_control_tpu.service.progress import OperationProgress

    # 1) boot the service and run one proposal computation: engine statics
    #    and carries are now device-committed arrays on jax.devices()[0]
    app, _fetcher, _admin, _sampler = build_simulated_service(seed=1)
    try:
        result = app.cc.proposals(OperationProgress())
        assert result.proposals is not None
    finally:
        app.cc.shutdown()

    # 2) the SAME process now runs every mesh mode on the virtual mesh —
    #    the exact sequence that crashed in r4
    state = random_cluster(
        RandomClusterSpec(num_brokers=10, num_partitions=120, skew=1.5), seed=23
    )
    cfg = OptimizerConfig(
        num_candidates=64, leadership_candidates=16, steps_per_round=4,
        num_rounds=2,
    )
    eng = Engine(state, CHAIN, config=cfg)
    eng.run()  # commit this engine's buffers to device 0 too
    temps = jnp.zeros((2, 4), jnp.float32)
    final, info = portfolio_run(eng, default_mesh(), temps, seed=1)
    validate(final)
    assert info["n_chains"] == len(jax.devices())

    se = ShardedEngine(state, CHAIN, mesh=model_mesh(), config=cfg)
    sharded_final, _ = se.run()
    validate(sharded_final)

    ge = GridEngine(state, CHAIN, mesh=grid_mesh(2, 4), config=cfg)
    grid_final, _ = ge.run()
    validate(grid_final)
