"""Native (C++) batch-serde tests: parity with the Python serde, error
handling, and the sampler's columnar fast path."""

import time

import numpy as np
import pytest

from cruise_control_tpu.native import (
    batch_deserialize,
    frame_records,
    native_available,
)
from cruise_control_tpu.reporter.metrics import (
    BrokerMetric,
    MetricSerde,
    MetricType,
    PartitionMetric,
    TopicMetric,
)


def _random_records(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    recs = []
    topics = ["Topic-A", "tøpic-ünïcode", "T" * 100, "b"]
    for i in range(n):
        kind = rng.integers(0, 3)
        t = int(rng.integers(0, 10_000_000))
        b = int(rng.integers(0, 4000))
        v = float(rng.normal() * 1e6)
        if kind == 0:
            recs.append(BrokerMetric(MetricType.BROKER_CPU_UTIL, t, b, v))
        elif kind == 1:
            recs.append(
                TopicMetric(MetricType.TOPIC_BYTES_IN, t, b, v,
                            topic=topics[i % len(topics)])
            )
        else:
            recs.append(
                PartitionMetric(MetricType.PARTITION_SIZE, t, b, v,
                                topic=topics[i % len(topics)],
                                partition=int(rng.integers(0, 500)))
            )
    return recs


def test_native_builds():
    assert native_available(), "g++ toolchain is baked into this image"


@pytest.mark.parametrize("force_python", [False, True])
def test_batch_parity_with_record_serde(force_python):
    recs = _random_records(500, seed=3)
    framed = frame_records([MetricSerde.serialize(r) for r in recs])
    batch = batch_deserialize(framed, force_python=force_python)
    assert len(batch) == len(recs)
    for i, r in enumerate(recs):
        assert batch.metric_types[i] == int(r.metric_type)
        assert batch.times_ms[i] == r.time_ms
        assert batch.broker_ids[i] == r.broker_id
        assert batch.values[i] == r.value
        if isinstance(r, PartitionMetric):
            assert batch.class_ids[i] == 2
            assert batch.partitions[i] == r.partition
            assert batch.topics[batch.topic_ids[i]] == r.topic
        elif isinstance(r, TopicMetric):
            assert batch.class_ids[i] == 1
            assert batch.topics[batch.topic_ids[i]] == r.topic
        else:
            assert batch.class_ids[i] == 0
            assert batch.topic_ids[i] == -1


def test_native_and_python_paths_agree():
    recs = _random_records(300, seed=9)
    framed = frame_records([MetricSerde.serialize(r) for r in recs])
    a = batch_deserialize(framed, force_python=False)
    b = batch_deserialize(framed, force_python=True)
    np.testing.assert_array_equal(a.class_ids, b.class_ids)
    np.testing.assert_array_equal(a.metric_types, b.metric_types)
    np.testing.assert_array_equal(a.values, b.values)
    np.testing.assert_array_equal(a.partitions, b.partitions)
    assert [a.topics[i] for i in a.topic_ids if i >= 0] == [
        b.topics[i] for i in b.topic_ids if i >= 0
    ]


def test_malformed_batches_rejected():
    good = frame_records([MetricSerde.serialize(
        BrokerMetric(MetricType.BROKER_CPU_UTIL, 1, 2, 3.0))])
    for bad in (good[:-1], good + b"\x01", b"\x05\x00\x00\x00abc"):
        with pytest.raises(ValueError):
            batch_deserialize(bad)
        with pytest.raises(ValueError):
            batch_deserialize(bad, force_python=True)
    assert len(batch_deserialize(b"")) == 0


def test_sampler_columnar_path_matches_object_path():
    """The reporter sampler must produce identical samples through the
    native fast path and the per-record object path."""
    from cruise_control_tpu.monitor.reporter_sampler import (
        CruiseControlMetricsReporterSampler,
    )
    from cruise_control_tpu.reporter.reporter import InMemoryTransport
    from cruise_control_tpu.testing.synthetic import synthetic_topology

    topo = synthetic_topology(num_brokers=4, topics={"T0": 8, "T1": 8}, seed=5)

    def make_transport(records):
        tr = InMemoryTransport()
        for r in records:
            tr.send(MetricSerde.serialize(r))
        return tr

    records = []
    for b in range(4):
        records.append(BrokerMetric(MetricType.BROKER_CPU_UTIL, 1000, b, 40.0 + b))
        for t in ("T0", "T1"):
            records.append(TopicMetric(MetricType.TOPIC_BYTES_IN, 1000, b, 1e5 * (b + 1), topic=t))
            records.append(TopicMetric(MetricType.TOPIC_BYTES_OUT, 1000, b, 2e5 * (b + 1), topic=t))
    for p in topo.partitions:
        records.append(PartitionMetric(
            MetricType.PARTITION_SIZE, 1000, p.leader, 1e6 + p.partition,
            topic=p.topic, partition=p.partition,
        ))

    class ObjectOnlyTransport:
        """Exposes poll() but not poll_framed — forces the object path."""

        def __init__(self, inner):
            self._inner = inner

        def poll(self, max_records=None):
            return self._inner.poll(max_records)

    fast = CruiseControlMetricsReporterSampler(make_transport(records), lambda: topo)
    slow = CruiseControlMetricsReporterSampler(
        ObjectOnlyTransport(make_transport(records)), lambda: topo
    )
    r_fast = fast.get_samples([], 0, 2000)
    r_slow = slow.get_samples([], 0, 2000)

    def key(s):
        return (repr(s.entity), tuple(np.round(np.asarray(s.values, float), 6)))

    assert sorted(map(key, r_fast.partition_samples)) == sorted(
        map(key, r_slow.partition_samples)
    )
    assert sorted(map(key, r_fast.broker_samples)) == sorted(
        map(key, r_slow.broker_samples)
    )


def test_native_throughput_smoke():
    """Native decode should comfortably beat the object loop (informational;
    asserts only a sane lower bound to avoid flakes)."""
    recs = _random_records(20_000, seed=1)
    payloads = [MetricSerde.serialize(r) for r in recs]
    framed = frame_records(payloads)
    t0 = time.perf_counter()
    batch = batch_deserialize(framed)
    native_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    _ = [MetricSerde.deserialize(p) for p in payloads]
    object_s = time.perf_counter() - t0
    assert len(batch) == len(recs)
    if native_available():
        # native columnar decode must not be slower than object-per-record
        assert native_s <= object_s


def test_truncated_topic_record_rejected_by_both_paths():
    """A record whose declared topic length overruns the record must fail in
    BOTH decoders identically (ADVICE r2: the Python path silently produced
    a truncated topic / misread partition)."""
    import struct

    good = MetricSerde.serialize(
        PartitionMetric(MetricType.PARTITION_SIZE, 5, 1, 2.0, topic="abcdef", partition=3)
    )
    # corrupt the topic length field (offset 24) to overrun the record
    bad_topic_len = good[:24] + struct.pack("<H", 1000) + good[26:]
    # partition-class record too short for its partition id: declare a topic
    # length that leaves <4 bytes for the partition
    bad_part = good[:24] + struct.pack("<H", len(good) - 26 - 2) + good[26:]
    for bad in (bad_topic_len, bad_part):
        framed = frame_records([bad])
        for force_python in (False, True):
            with pytest.raises(ValueError):
                batch_deserialize(framed, force_python=force_python)
