"""Sharded-MODEL mesh mode (parallel/model_shard.py): byte parity with the
replicated mesh and the plain engine, psum'd broker-aggregate exactness,
collective hygiene of the sub-threshold path, and the pinned workaround
for the variadic-sort miscompile the mode has to dodge.

All tests run on the conftest-provisioned 8-device virtual CPU mesh.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer.engine import Engine, OptimizerConfig
from cruise_control_tpu.analyzer.objective import DEFAULT_CHAIN
from cruise_control_tpu.models.builder import pad_state
from cruise_control_tpu.models.sharding import shard_multiple_shape
from cruise_control_tpu.parallel.mesh import MeshEngine, grid_mesh, shard_map_compat
from cruise_control_tpu.parallel.model_shard import stable_grouped_order
from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

N = 8

CFG = OptimizerConfig(
    num_candidates=48, leadership_candidates=16, swap_candidates=8,
    steps_per_round=4, num_rounds=2, seed=3,
)


def _small_state():
    """Seeded small cluster, prepared for exact cross-mode comparison:
    integer-quantized loads (psum partial sums add exactly in f32) and
    pre-padded to the shard multiple (goals normalize by the PADDED
    partition count, so all three modes must see the same padded shape)."""
    state = random_cluster_fast(
        RandomClusterSpec(num_brokers=12, num_partitions=160, skew=1.5), seed=21
    )
    state = dataclasses.replace(
        state,
        replica_load_leader=jnp.round(state.replica_load_leader * 8),
        replica_load_follower=jnp.round(state.replica_load_follower * 8),
    )
    return pad_state(state, shard_multiple_shape(state.shape, N))


def test_three_way_byte_parity():
    """One seeded anneal, three execution modes, identical bytes.

    The sharded-model mode's whole contract: partitioning the model over
    MODEL_AXIS is an execution-layout change, never a numerics change —
    placements, objective and per-goal violations match the plain engine
    and the replicated mesh bit-for-bit.  The same runs also pin the
    timing-record contract: sharded history reports its analytic psum
    payload (`model_psum_bytes`, the analyzer.mesh-model-psum-bytes
    sensor source) while replicated records must NOT grow the new keys
    (downstream hashes of replicated history stay stable)."""
    state = _small_state()
    mesh = grid_mesh(1, N)
    runs = {}
    for name, eng in (
        ("plain", Engine(state, DEFAULT_CHAIN, config=CFG)),
        ("replicated", MeshEngine(state, DEFAULT_CHAIN, mesh=mesh, config=CFG)),
        ("sharded", MeshEngine(
            state, DEFAULT_CHAIN, mesh=mesh, config=CFG,
            model_shard_min_partitions=1,
        )),
    ):
        final, hist = eng.run()
        obj, viol, _ = DEFAULT_CHAIN.evaluate(final)
        runs[name] = (final, float(obj), np.asarray(viol), hist)
    assert runs["sharded"][0] is not None
    for f in ("replica_broker", "replica_is_leader", "replica_disk"):
        a, b, c = (np.asarray(getattr(runs[n][0], f))
                   for n in ("plain", "replicated", "sharded"))
        np.testing.assert_array_equal(a, b, err_msg=f"plain vs replicated: {f}")
        np.testing.assert_array_equal(b, c, err_msg=f"replicated vs sharded: {f}")
    assert runs["plain"][1] == runs["replicated"][1] == runs["sharded"][1]
    np.testing.assert_array_equal(runs["plain"][2], runs["sharded"][2])

    timing = next(h for h in runs["sharded"][3] if h.get("timing"))
    assert timing.get("model_sharded") is True
    assert timing.get("model_psum_bytes", 0) > 0
    timing = next(h for h in runs["replicated"][3] if h.get("timing"))
    assert "model_sharded" not in timing
    assert "model_psum_bytes" not in timing


def test_sharded_mode_gate():
    """tpu.mesh.model.shard.min.partitions semantics: 0 disables, a
    threshold above the REAL partition count keeps the replicated model,
    at-or-below engages sharding (and requires a >1 model axis)."""
    state = _small_state()
    mesh = grid_mesh(1, N)
    assert not MeshEngine(state, DEFAULT_CHAIN, mesh=mesh, config=CFG).model_sharded
    assert not MeshEngine(
        state, DEFAULT_CHAIN, mesh=mesh, config=CFG,
        model_shard_min_partitions=10**9,
    ).model_sharded
    assert MeshEngine(
        state, DEFAULT_CHAIN, mesh=mesh, config=CFG,
        model_shard_min_partitions=1,
    ).model_sharded
    assert not MeshEngine(
        state, DEFAULT_CHAIN, mesh=grid_mesh(1, 1), config=CFG,
        model_shard_min_partitions=1,
    ).model_sharded


def test_psum_segment_sum_exactness():
    """Shard-local segment_sum + psum == single-device segment_sum, bit
    for bit, on integer-quantized f32 loads — the identity every broker
    aggregate in the sharded goal chain rests on."""
    from jax.sharding import Mesh, PartitionSpec as P

    rng = np.random.default_rng(5)
    R, B = 2048, 24
    vals = jnp.asarray(rng.integers(0, 512, size=R).astype(np.float32))
    seg = jnp.asarray(rng.integers(0, B, size=R).astype(np.int32))
    reference = jax.ops.segment_sum(vals, seg, num_segments=B)

    mesh = Mesh(np.asarray(jax.devices()[:N]), ("model",))

    def f(v, s):
        part = jax.ops.segment_sum(v, s, num_segments=B)
        return jax.lax.psum(part, "model")[None]

    out = jax.jit(
        shard_map_compat(
            f, mesh, in_specs=(P("model"), P("model")), out_specs=P("model")
        )
    )(vals, seg)
    got = np.asarray(out)  # [N, B]: one psum'd (identical) row per shard
    for i in range(N):
        np.testing.assert_array_equal(got[i], np.asarray(reference))


def test_stable_grouped_order_matches_argsort():
    """stable_grouped_order is a drop-in stable argsort for bucketed int
    keys — single-chunk and (via a shrunken packing span) multi-chunk."""
    import cruise_control_tpu.parallel.model_shard as ms

    rng = np.random.default_rng(0)
    for n, nk in [(51, 14), (408, 14), (1000, 7), (1, 3), (37, 1)]:
        seg = rng.integers(0, nk, size=n).astype(np.int32)
        got = np.asarray(stable_grouped_order(jnp.asarray(seg), nk))
        np.testing.assert_array_equal(got, np.argsort(seg, kind="stable"))
    assert stable_grouped_order(jnp.zeros(0, jnp.int32), 4).shape == (0,)
    span = ms._INT32_SPAN
    try:
        ms._INT32_SPAN = 1 << 8  # forces the chunked counting-sort path
        for n, nk in [(1000, 7), (513, 13), (999, 50)]:
            seg = rng.integers(0, nk, size=n).astype(np.int32)
            got = np.asarray(stable_grouped_order(jnp.asarray(seg), nk))
            np.testing.assert_array_equal(got, np.argsort(seg, kind="stable"))
    finally:
        ms._INT32_SPAN = span


def test_variadic_sort_miscompile_guard():
    """Pinned repro of the bug stable_grouped_order exists to dodge.

    On the pinned jax/XLA build, a VARIADIC (two-operand) lax.sort of
    shard-varying data — jnp.argsort lowers to one — inside a
    shard_map(check_rep=False) program whose result rides a lax.scan ys
    export silently hands every device device 0's sort output, corrupting
    even the scan carry.  The packed SINGLE-operand sort must stay
    correct under the exact graph shape that triggers the miscompile; if
    this test ever fails, the sharded-model mode's sampling order (and
    with it byte parity) is broken on this backend."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.asarray(jax.devices()[:N]).reshape(1, N), ("r", "m"))
    per = 6
    x = jax.device_put(
        jnp.arange(N * per, dtype=jnp.int32) % 7,
        NamedSharding(mesh, P("m")),
    )

    def fn(xb):
        o = stable_grouped_order(xb, 7)
        def body(c, t):
            v = (o * jnp.arange(per, dtype=jnp.int32)).sum()
            return c + v, (o, xb)
        acc, (o_ys, x_ys) = jax.lax.scan(body, jnp.int32(0), jnp.zeros(2))
        return (
            jax.lax.all_gather(acc, "m")[None],
            jax.lax.all_gather(o_ys[0], "m")[None],
            jax.lax.all_gather(x_ys[0], "m")[None],
        )

    acc, o, xs = jax.jit(
        shard_map_compat(
            fn, mesh, in_specs=(P("m"),), out_specs=(P("r"), P("r"), P("r"))
        )
    )(x)
    acc, o, xs = np.asarray(acc)[0], np.asarray(o)[0], np.asarray(xs)[0]
    truth = np.asarray(jax.device_get(x)).reshape(N, per)
    for i in range(N):
        expect = np.argsort(truth[i], kind="stable")
        np.testing.assert_array_equal(o[i], expect, err_msg=f"shard {i} order")
        np.testing.assert_array_equal(xs[i], truth[i], err_msg=f"shard {i} ys x")
        assert acc[i] == 2 * (expect * np.arange(per)).sum(), f"shard {i} carry"


def test_subthreshold_path_emits_no_model_axis_allreduce():
    """HLO hygiene: below the sharding threshold the mesh program's only
    model-axis collective is the candidate-column gather — no psum
    (all-reduce) may appear.  The sharded program, by contrast, carries
    its ownership/aggregate psums as all-reduces."""
    state = _small_state()
    mesh = grid_mesh(1, N)

    def lowered_text(me):
        keys = jax.random.PRNGKey(CFG.seed)[None]
        carry = me._jit_init(me.statics, keys)
        return me._jit_run.lower(me.statics, carry).as_text()

    replicated = MeshEngine(state, DEFAULT_CHAIN, mesh=mesh, config=CFG)
    text = lowered_text(replicated)
    assert "all_reduce" not in text, "replicated mesh program grew an all-reduce"
    assert "all_gather" in text  # the candidate gather is still there

    sharded = MeshEngine(
        state, DEFAULT_CHAIN, mesh=mesh, config=CFG, model_shard_min_partitions=1
    )
    assert "all_reduce" in lowered_text(sharded)
