"""Model-sharded engine tests (8-device virtual CPU mesh, conftest.py).

Exercises parallel/sharded.py: the cluster model's replica/partition axes
are explicitly sharded across the mesh (one shard per device), candidates
are exchanged with all_gather, refresh psums partial aggregates.
"""

import dataclasses

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer import DEFAULT_CHAIN, Engine, OptimizerConfig
from cruise_control_tpu.models.aggregates import compute_aggregates
from cruise_control_tpu.models.state import validate
from cruise_control_tpu.parallel.sharded import (
    ShardedEngine,
    build_layout,
    model_mesh,
)
from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster

CFG = OptimizerConfig(
    num_candidates=64,
    leadership_candidates=16,
    swap_candidates=8,
    steps_per_round=6,
    num_rounds=3,
    seed=3,
)


def _state(seed=21, brokers=12, parts=160):
    return random_cluster(
        RandomClusterSpec(num_brokers=brokers, num_partitions=parts, skew=1.5),
        seed=seed,
    )


def test_layout_partition_aligned_and_invertible():
    state = _state()
    n = 8
    lay = build_layout(state, n)
    assert lay.n_shards == n
    total_valid = int(np.asarray(state.replica_valid).sum())
    owned = lay.orig_index[lay.orig_index >= 0]
    assert owned.size == total_valid
    assert np.unique(owned).size == owned.size  # each replica exactly once
    part = np.asarray(state.replica_partition)
    for i in range(n):
        idx = lay.orig_index[i][lay.orig_index[i] >= 0]
        if idx.size:
            p = part[idx]
            assert p.min() >= i * lay.P_local and p.max() < (i + 1) * lay.P_local
        ls = lay.local_states[i]
        assert ls.shape.R == lay.R_local and ls.shape.P == lay.P_local
        # local loads must match the original rows
        np.testing.assert_allclose(
            np.asarray(ls.replica_load_leader)[: idx.size],
            np.asarray(state.replica_load_leader)[idx],
        )


def _rounds(history):
    """Round records only (history also carries ONE timing record)."""
    return [h for h in history if not h.get("timing")]


def test_sharded_engine_improves_and_validates():
    state = _state()
    mesh = model_mesh(np.asarray(jax.devices()[:8]))
    se = ShardedEngine(state, DEFAULT_CHAIN, mesh=mesh, config=CFG)
    final, history = se.run(verbose=True)
    validate(final)
    obj0, _, _ = DEFAULT_CHAIN.evaluate(state)
    obj1, _, _ = DEFAULT_CHAIN.evaluate(final)
    assert float(obj1) < float(obj0)
    assert sum(h["accepted"] for h in _rounds(history)) > 0
    # fused (default) sharded rounds: O(1) blocking syncs, not O(rounds)
    timing = next(h for h in history if h.get("timing"))
    assert timing["fused"] is True and timing["blocking_syncs"] == 2


def test_sharded_fused_matches_legacy_rounds():
    """Fused-vs-legacy parity on the SHARDED engine: at T=0 with a fixed
    seed the device-resident multi-round program must reproduce the legacy
    per-round dispatch loop's placement exactly."""
    state = _state(seed=27, brokers=10, parts=144)
    mesh = model_mesh(np.asarray(jax.devices()[:8]))
    base = dataclasses.replace(CFG, init_temperature_scale=0.0)
    se_f = ShardedEngine(
        state, DEFAULT_CHAIN, mesh=mesh,
        config=dataclasses.replace(base, fused_rounds=True),
    )
    final_f, hist_f = se_f.run()
    se_l = ShardedEngine(
        state, DEFAULT_CHAIN, mesh=mesh,
        config=dataclasses.replace(base, fused_rounds=False),
    )
    final_l, hist_l = se_l.run()
    np.testing.assert_array_equal(
        np.asarray(final_f.replica_broker), np.asarray(final_l.replica_broker)
    )
    np.testing.assert_array_equal(
        np.asarray(final_f.replica_is_leader), np.asarray(final_l.replica_is_leader)
    )
    assert [h["accepted"] for h in _rounds(hist_f)] == [
        h["accepted"] for h in _rounds(hist_l)
    ]


def test_sharded_aggregates_match_unsharded():
    """The psum'd refresh must produce the same replicated broker aggregates
    a single-device engine derives from the whole model."""
    state = _state(seed=5)
    mesh = model_mesh(np.asarray(jax.devices()[:8]))
    se = ShardedEngine(state, DEFAULT_CHAIN, mesh=mesh, config=CFG)
    keys = jax.random.split(jax.random.PRNGKey(0), se.n)
    carry = se._jit_init(se.statics, keys)

    agg = compute_aggregates(state)
    # stacked replicated copies: every shard must hold the global aggregates
    bl = np.asarray(carry.broker_load)
    for i in range(se.n):
        np.testing.assert_allclose(bl[i], np.asarray(agg.broker_load), rtol=1e-5)
    np.testing.assert_array_equal(
        np.asarray(carry.broker_replica_count)[0],
        np.asarray(agg.broker_replica_count),
    )
    np.testing.assert_array_equal(
        np.asarray(carry.broker_leader_count)[0],
        np.asarray(agg.broker_leader_count),
    )
    # sharded part_rack_count concatenates to the global table (padded P)
    prc = np.asarray(carry.part_rack_count).reshape(-1, state.shape.num_racks)
    np.testing.assert_array_equal(
        prc[: state.shape.P], np.asarray(agg.part_rack_count)
    )


def test_sharded_objective_matches_engine_objective():
    state = _state(seed=9)
    mesh = model_mesh(np.asarray(jax.devices()[:8]))
    se = ShardedEngine(state, DEFAULT_CHAIN, mesh=mesh, config=CFG)
    keys = jax.random.split(jax.random.PRNGKey(0), se.n)
    carry = se._jit_init(se.statics, keys)
    sharded_obj = se.objective(carry)

    eng = Engine(state, DEFAULT_CHAIN, config=CFG)
    c0 = eng.init_carry(jax.random.PRNGKey(0))
    local_obj = float(eng.carry_objective(eng.statics, c0))
    assert abs(sharded_obj - local_obj) < max(1e-4, 1e-4 * abs(local_obj))


def test_sharded_tracks_single_device_quality():
    """Same budget, same seed family: the sharded run must land in the same
    quality regime as the single-device engine (it evaluates n× candidates,
    so equal-or-better is the expectation, with slack for stochasticity)."""
    state = _state(seed=33, brokers=10, parts=120)
    cfg = dataclasses.replace(CFG, num_rounds=4)
    eng = Engine(state, DEFAULT_CHAIN, config=cfg)
    single, _ = eng.run()
    obj_single, _, _ = DEFAULT_CHAIN.evaluate(single)

    mesh = model_mesh(np.asarray(jax.devices()[:8]))
    se = ShardedEngine(state, DEFAULT_CHAIN, mesh=mesh, config=cfg)
    sharded, _ = se.run()
    validate(sharded)
    obj_sharded, _, _ = DEFAULT_CHAIN.evaluate(sharded)

    obj0, _, _ = DEFAULT_CHAIN.evaluate(state)
    # both must improve substantially; sharded within 20% of single's gain
    gain_single = float(obj0 - obj_single)
    gain_sharded = float(obj0 - obj_sharded)
    assert gain_single > 0 and gain_sharded > 0
    assert gain_sharded >= 0.8 * gain_single


def test_grid_engine_2d_mesh():
    """Restart portfolio OVER model-sharded chains on a 2x4 mesh: chains
    are isolated (different final objectives), winner validates and
    improves the cluster."""
    from cruise_control_tpu.parallel.grid import GridEngine, grid_mesh

    state = _state(seed=41, brokers=10, parts=128)
    mesh = grid_mesh(2, 4, jax.devices()[:8])
    ge = GridEngine(state, DEFAULT_CHAIN, mesh=mesh, config=CFG)
    final, history = ge.run(verbose=True)
    info = ge.last_info
    assert info["n_chains"] == 2 and info["n_shards"] == 4
    assert len(info["objectives"]) == 2
    assert _rounds(history) and all("accepted" in h for h in _rounds(history))
    validate(final)
    obj0, _, _ = DEFAULT_CHAIN.evaluate(state)
    obj1, _, _ = DEFAULT_CHAIN.evaluate(final)
    assert float(obj1) < float(obj0)
    # winner must be the argmin chain
    assert info["winner"] == int(np.argmin(info["objectives"]))


@pytest.mark.parametrize("mode", ["sharded", "grid:2x4"])
def test_goal_optimizer_parallel_modes(mode):
    """tpu.parallel.mode wires the multi-device engines into the PRODUCT
    optimizer path (GoalOptimizer -> ShardedEngine / GridEngine)."""
    from cruise_control_tpu.analyzer import GoalOptimizer

    state = _state(seed=51, brokers=10, parts=120)
    opt = GoalOptimizer(config=CFG, parallel_mode=mode)
    res = opt.optimize(state)
    validate(res.state_after)
    assert res.objective_after < res.objective_before
    assert res.proposals  # a real plan came out of the parallel engine


def test_parallel_engine_rebind_honors_new_options():
    """A cached sharded engine rebound with NEW options must honor them —
    the stale-options path would move replicas onto excluded brokers."""
    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizationOptions

    state = _state(seed=61, brokers=10, parts=120)
    opt = GoalOptimizer(config=CFG, parallel_mode="sharded")
    opt.optimize(state)  # populate the parallel-engine cache (default opts)

    excluded = np.zeros(state.shape.B, bool)
    excluded[0] = True
    res = opt.optimize(
        state, options=OptimizationOptions(excluded_brokers_for_replica_move=excluded)
    )
    before, after = res.state_before, res.state_after
    moved = (
        np.asarray(before.replica_broker) != np.asarray(after.replica_broker)
    ) & np.asarray(before.replica_valid)
    assert not (np.asarray(after.replica_broker)[moved] == 0).any(), (
        "cached sharded engine ignored the new exclusion options"
    )
