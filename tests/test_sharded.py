"""Mesh-engine tests (8-device virtual CPU mesh, conftest.py).

Exercises the shared mesh layer (parallel/mesh.py) through its sharded and
grid views: the candidate axis of the anneal is sharded over MODEL_AXIS
(full-K draws from a replicated key, per-shard delta evaluation, one tiled
all_gather of the candidate columns), so a 1-device and an n-device run of
the same seeded anneal are BYTE-IDENTICAL — the property pinned here and
by `bench.py --mesh-smoke`.
"""

import dataclasses

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer import DEFAULT_CHAIN, Engine, OptimizerConfig
from cruise_control_tpu.models.aggregates import compute_aggregates
from cruise_control_tpu.models.state import validate
from cruise_control_tpu.parallel.mesh import (
    MODEL_AXIS,
    RESTART_AXIS,
    normalize_mesh,
)
from cruise_control_tpu.parallel.sharded import ShardedEngine, model_mesh
from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster

# K_r=60 is deliberately NOT divisible by 8: shard slices are edge-padded
# to n*ceil(K/n) and trimmed after the gather, and an aligned-only config
# would leave that path untested.
CFG = OptimizerConfig(
    num_candidates=60,
    leadership_candidates=16,
    swap_candidates=8,
    steps_per_round=6,
    num_rounds=3,
    seed=3,
)


def _state(seed=21, brokers=12, parts=160):
    return random_cluster(
        RandomClusterSpec(num_brokers=brokers, num_partitions=parts, skew=1.5),
        seed=seed,
    )


def _rounds(history):
    """Round records only (history also carries ONE timing record)."""
    return [h for h in history if not h.get("timing")]


def test_sharded_engine_improves_and_validates():
    state = _state()
    mesh = model_mesh(np.asarray(jax.devices()[:8]))
    se = ShardedEngine(state, DEFAULT_CHAIN, mesh=mesh, config=CFG)
    final, history = se.run(verbose=True)
    validate(final)
    obj0, _, _ = DEFAULT_CHAIN.evaluate(state)
    obj1, _, _ = DEFAULT_CHAIN.evaluate(final)
    assert float(obj1) < float(obj0)
    assert sum(h["accepted"] for h in _rounds(history)) > 0
    # the whole multi-round anneal is ONE device program: a single
    # winner/stats sync, and the timing record names the mesh
    timing = next(h for h in history if h.get("timing"))
    assert timing["fused"] is True and timing["blocking_syncs"] == 1
    assert timing["mesh_shape"] == [1, 8]
    assert timing["collective_bytes"] > 0


def test_sharded_byte_parity_plain_vs_1_vs_8_devices():
    """THE mesh-layer invariant: the same seeded anneal on the plain
    engine, a 1-device mesh, and an 8-device mesh produces byte-identical
    placements and identical per-round acceptance counts.  Full-K draws
    from the replicated key + row-local delta math + in-order gather means
    the mesh size can never leak into the trajectory."""
    state = _state(seed=27, brokers=10, parts=144)
    eng = Engine(state, DEFAULT_CHAIN, config=CFG)
    plain, hist_p = eng.run()
    se1 = ShardedEngine(
        state, DEFAULT_CHAIN, mesh=model_mesh(np.asarray(jax.devices()[:1])),
        config=CFG,
    )
    s1, hist_1 = se1.run()
    se8 = ShardedEngine(
        state, DEFAULT_CHAIN, mesh=model_mesh(np.asarray(jax.devices()[:8])),
        config=CFG,
    )
    s8, hist_8 = se8.run()
    for label, other in (("1-device", s1), ("8-device", s8)):
        np.testing.assert_array_equal(
            np.asarray(plain.replica_broker), np.asarray(other.replica_broker),
            err_msg=f"{label} placement diverged from the plain engine",
        )
        np.testing.assert_array_equal(
            np.asarray(plain.replica_is_leader),
            np.asarray(other.replica_is_leader),
        )
        np.testing.assert_array_equal(
            np.asarray(plain.replica_disk), np.asarray(other.replica_disk)
        )
    acc = lambda h: [r["accepted"] for r in _rounds(h)]  # noqa: E731
    assert acc(hist_p) == acc(hist_1) == acc(hist_8)


def test_sharded_n1_emits_no_collective():
    """At n=1 the shard slice is the identity and the traced program IS
    the plain fused program — no all_gather, zero collective payload (the
    <10% n=1 overhead guarantee rests on this)."""
    state = _state(seed=5, brokers=8, parts=96)
    se1 = ShardedEngine(
        state, DEFAULT_CHAIN, mesh=model_mesh(np.asarray(jax.devices()[:1])),
        config=CFG,
    )
    assert se1.collective_bytes_per_step == 0
    se8 = ShardedEngine(
        state, DEFAULT_CHAIN, mesh=model_mesh(np.asarray(jax.devices()[:8])),
        config=CFG,
    )
    # 8 shards exchange the padded candidate columns: nonzero, and the
    # accounting must cover the edge padding (60 -> 8*ceil(60/8) rows)
    assert se8.collective_bytes_per_step > 0
    assert se8.collective_bytes_per_round == (
        se8.collective_bytes_per_step * CFG.steps_per_round
    )


def test_mesh_normalization():
    devs = np.asarray(jax.devices()[:8])
    from jax.sharding import Mesh

    m1 = normalize_mesh(Mesh(devs, (MODEL_AXIS,)))
    assert m1.shape[RESTART_AXIS] == 1 and m1.shape[MODEL_AXIS] == 8
    m2 = normalize_mesh(Mesh(devs, (RESTART_AXIS,)))
    assert m2.shape[RESTART_AXIS] == 8 and m2.shape[MODEL_AXIS] == 1
    m3 = normalize_mesh(Mesh(devs.reshape(2, 4), (RESTART_AXIS, MODEL_AXIS)))
    assert m3 is normalize_mesh(m3)  # canonical form is a fixed point
    with pytest.raises(ValueError, match="mesh axes"):
        normalize_mesh(Mesh(devs, ("data",)))


def test_sharded_carry_aggregates_match_unsharded():
    """The mesh carry is REPLICATED: its broker aggregates must equal the
    global aggregates a single-device engine derives from the whole model
    (no partial/psum'd state anywhere)."""
    state = _state(seed=5)
    mesh = model_mesh(np.asarray(jax.devices()[:8]))
    se = ShardedEngine(state, DEFAULT_CHAIN, mesh=mesh, config=CFG)
    keys = jax.random.PRNGKey(0)[None]
    carry = se._jit_init(se.statics, keys)

    agg = compute_aggregates(state)
    # leading axis is the restart axis (1 chain); the model axis never
    # appears in the carry because every shard holds the same replica
    bl = np.asarray(carry.broker_load)
    assert bl.shape[0] == 1
    np.testing.assert_allclose(bl[0], np.asarray(agg.broker_load), rtol=1e-5)
    np.testing.assert_array_equal(
        np.asarray(carry.broker_replica_count)[0],
        np.asarray(agg.broker_replica_count),
    )
    np.testing.assert_array_equal(
        np.asarray(carry.broker_leader_count)[0],
        np.asarray(agg.broker_leader_count),
    )


def test_grid_engine_2d_mesh():
    """Restart portfolio OVER candidate-sharded chains on a 2x4 mesh:
    chains are isolated (independent keys), winner validates and improves
    the cluster."""
    from cruise_control_tpu.parallel.grid import GridEngine, grid_mesh

    state = _state(seed=41, brokers=10, parts=128)
    mesh = grid_mesh(2, 4, jax.devices()[:8])
    ge = GridEngine(state, DEFAULT_CHAIN, mesh=mesh, config=CFG)
    final, history = ge.run(verbose=True)
    info = ge.last_info
    assert info["n_chains"] == 2 and info["n_shards"] == 4
    assert len(info["objectives"]) == 2
    assert _rounds(history) and all("accepted" in h for h in _rounds(history))
    validate(final)
    obj0, _, _ = DEFAULT_CHAIN.evaluate(state)
    obj1, _, _ = DEFAULT_CHAIN.evaluate(final)
    assert float(obj1) < float(obj0)
    # winner must be the argmin chain
    assert info["winner"] == int(np.argmin(info["objectives"]))


def test_grid_engine_rejects_1d_mesh():
    from cruise_control_tpu.parallel.grid import GridEngine

    state = _state(seed=43, brokers=8, parts=96)
    with pytest.raises(ValueError, match="grid mesh"):
        GridEngine(state, DEFAULT_CHAIN, mesh=model_mesh(), config=CFG)


@pytest.mark.parametrize("mode", ["sharded", "grid:2x4"])
def test_goal_optimizer_parallel_modes(mode):
    """tpu.parallel.mode wires the mesh engines into the PRODUCT optimizer
    path (GoalOptimizer -> ShardedEngine / GridEngine), and the sharded
    mode reproduces the single-device optimizer result exactly."""
    from cruise_control_tpu.analyzer import GoalOptimizer

    state = _state(seed=51, brokers=10, parts=120)
    opt = GoalOptimizer(config=CFG, parallel_mode=mode)
    res = opt.optimize(state)
    validate(res.state_after)
    assert res.objective_after < res.objective_before
    assert res.proposals  # a real plan came out of the parallel engine
    timing = next(h for h in res.history if h.get("timing"))
    assert timing["mesh_shape"] == ([1, 8] if mode == "sharded" else [2, 4])
    if mode == "sharded":
        single = GoalOptimizer(config=CFG, parallel_mode="single").optimize(state)
        np.testing.assert_array_equal(
            np.asarray(res.state_after.replica_broker),
            np.asarray(single.state_after.replica_broker),
        )


def test_goal_optimizer_mesh_max_devices():
    """tpu.mesh.max.devices caps the mesh the service builds its engines
    from: sharded mode on the 8-device test platform with a cap of 4 runs
    a 4-shard mesh (byte parity keeps the result identical anyway), a cap
    of 1 degenerates to the single-device path, and a grid mode needing
    more devices than the cap is rejected at construction."""
    from cruise_control_tpu.analyzer import GoalOptimizer

    state = _state(seed=51, brokers=10, parts=120)
    opt = GoalOptimizer(config=CFG, parallel_mode="sharded", mesh_max_devices=4)
    res = opt.optimize(state)
    timing = next(h for h in res.history if h.get("timing"))
    assert timing["mesh_shape"] == [1, 4]
    assert (
        GoalOptimizer(
            config=CFG, parallel_mode="sharded", mesh_max_devices=1
        ).parallel_mode
        == "single"
    )
    with pytest.raises(ValueError, match="devices"):
        GoalOptimizer(config=CFG, parallel_mode="grid:2x4", mesh_max_devices=4)


def test_parallel_engine_rebind_honors_new_options():
    """A cached mesh engine rebound with NEW options must honor them —
    the stale-options path would move replicas onto excluded brokers."""
    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizationOptions

    state = _state(seed=61, brokers=10, parts=120)
    opt = GoalOptimizer(config=CFG, parallel_mode="sharded")
    opt.optimize(state)  # populate the parallel-engine cache (default opts)

    excluded = np.zeros(state.shape.B, bool)
    excluded[0] = True
    res = opt.optimize(
        state, options=OptimizationOptions(excluded_brokers_for_replica_move=excluded)
    )
    before, after = res.state_before, res.state_after
    moved = (
        np.asarray(before.replica_broker) != np.asarray(after.replica_broker)
    ) & np.asarray(before.replica_valid)
    assert not (np.asarray(after.replica_broker)[moved] == 0).any(), (
        "cached sharded engine ignored the new exclusion options"
    )


def test_parallel_prewarm_through_shared_pool():
    """GoalOptimizer.prewarm covers mesh engines: the shard_map'd
    whole-anneal program compiles on the shared warm pool and the engine
    lands in the parallel cache, so the next optimize() is a cache hit."""
    from cruise_control_tpu.analyzer import GoalOptimizer

    state = _state(seed=71, brokers=10, parts=120)
    opt = GoalOptimizer(config=CFG, parallel_mode="sharded")
    opt.prewarm(state)
    assert opt.has_engine_for(state.shape, config=CFG)
    res = opt.optimize(state)
    timing = next(h for h in res.history if h.get("timing"))
    assert timing["engine_cache_hit"] is True
