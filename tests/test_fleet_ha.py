"""Fleet HA: lease-sharded cluster ownership, fenced journals/admins,
automatic failover (fleet/leases.py + the fencing seams in executor/).

The chaos gate of the subsystem: across kill/stall/partition/clock-skew
schedules the invariants are

  * at most one lease holder per cluster at any instant (provable from
    the lease store's audit trail),
  * zero duplicate reassignment submissions across a kill-and-takeover,
  * zero leaked throttles (the new holder's reconciliation sweeps),
  * a fenced zombie can neither append to the journal nor mutate the
    cluster,

plus the default-off parity pin: `fleet.ha.enabled=false` leaves the
classic single-instance/fleet deployments byte-for-byte unchanged with
no lease store on disk.
"""

import json
import os
import time

import pytest

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor import (
    ExecutionJournal,
    ExecutionOptions,
    Executor,
    ExecutorState,
)
from cruise_control_tpu.executor.admin import FencedClusterAdmin, SimulatedClusterAdmin
from cruise_control_tpu.fleet.leases import (
    FencedError,
    FileLeaseStore,
    LeaseManager,
    single_holder_violations,
)
from cruise_control_tpu.monitor.topology import StaticMetadataProvider
from cruise_control_tpu.service.main import build_simulated_fleet
from cruise_control_tpu.service.schemas import validate_response
from cruise_control_tpu.testing import faults
from cruise_control_tpu.testing.synthetic import (
    SyntheticWorkloadSampler,
    synthetic_topology,
)

# ---------------------------------------------------------------- helpers


class FakeClock:
    """Injected instance clock (seconds float), advanced by tests."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


class StubFence:
    """Minimal fence for journal/admin unit tests."""

    def __init__(self, epoch: int = 1, ok: bool = True):
        self.epoch_value = epoch
        self.ok = ok

    def check(self, op: str = "") -> int:
        if not self.ok:
            raise FencedError(f"stub fence ({op})")
        return self.epoch_value


def shared_backends(cluster_ids=("c1",), *, link_rate=1e12, num_brokers=4,
                    partitions=8, seed=7):
    """{cid: (metadata, admin, sampler)} over ONE set of simulated
    clusters — passed to every instance of a multi-instance harness so
    all of them 'see' the same Kafka fleet."""
    out = {}
    for i, cid in enumerate(cluster_ids):
        topo = synthetic_topology(
            num_brokers=num_brokers, topics={"T0": partitions}, seed=seed + i
        )
        meta = StaticMetadataProvider(topo)
        admin = SimulatedClusterAdmin(meta, link_rate_bytes_per_s=link_rate)
        out[cid] = (meta, admin, SyntheticWorkloadSampler(topo, seed=seed + i))
    return out


def build_instance(instance_id, journal_dir, backends, clock, **extra):
    """One in-process tpu-cruise instance of an HA fleet.  Instances
    share ONLY the journal/lease directory and the simulated backends —
    the coordination surface real instances would share."""
    props = {
        "fleet.clusters": ",".join(backends),
        "fleet.ha.enabled": "true",
        "fleet.ha.instance.id": instance_id,
        "fleet.ha.lease.ttl.s": 10.0,
        "fleet.ha.renew.s": 2.0,
        "fleet.ha.skew.slack.s": 1.0,
        "executor.journal.dir": str(journal_dir),
        "anomaly.detection.interval.ms": 3_600_000,
        # keep start_up free of background compile threads (boot prewarm /
        # warm pool): a live XLA worker at interpreter exit segfaults the
        # pytest process (pre-existing; irrelevant to what HA pins here)
        "tpu.prewarm.enabled": "false",
    }
    props.update(extra)
    return build_simulated_fleet(
        props, backends=backends, ha_clock=clock, sampled_windows=1
    )


def rotation_proposals(admin, *, data=3000.0):
    """Proposals shifting every T0 partition's replicas by one broker —
    real inter-broker moves against the live simulated topology."""
    topo = admin.topology()
    n = len(topo.brokers)
    props = []
    for p in topo.partitions:
        if p.topic != "T0":
            continue
        old = tuple(p.replicas)
        new = tuple((b + 1) % n for b in old)
        props.append(ExecutionProposal(
            partition=p.partition,
            topic=0,
            old_leader=p.leader,
            new_leader=new[0],
            old_replicas=old,
            new_replicas=new,
            inter_broker_data_to_move=data,
        ))
    return props


def wait_until(cond, timeout=30.0):
    """Poll `cond` until true — cluster activation (reconcile + start_up)
    runs on its own thread off the lease heartbeat."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def spy_submissions(admin):
    """Per-partition reassignment submission counts across 'processes'."""
    counts: dict = {}
    orig = admin.reassign_partitions

    def wrapper(specs):
        for s in specs:
            counts[(s.topic, s.partition)] = counts.get((s.topic, s.partition), 0) + 1
        return orig(specs)

    admin.reassign_partitions = wrapper
    return counts


# ------------------------------------------------------------ lease store


def test_lease_store_acquire_renew_expire(tmp_path):
    clock = FakeClock()
    store = FileLeaseStore(str(tmp_path), skew_slack_s=1.0, clock=clock)
    a = store.acquire("c1", "A", 10.0)
    assert (a.epoch, a.holder_id) == (1, "A")
    # exclusive while live (even right at the deadline + slack boundary)
    assert store.acquire("c1", "B", 10.0) is None
    clock.advance(9.0)
    renewed = store.renew(a, 10.0)
    assert renewed.epoch == 1 and renewed.deadline == clock() + 10.0
    # expiry + skew slack opens the takeover window; the epoch bumps
    clock.advance(11.5)
    b = store.acquire("c1", "B", 10.0)
    assert (b.epoch, b.holder_id) == (2, "B")
    # the deposed holder's renewal is fenced
    assert store.renew(renewed, 10.0) is None
    # release -> immediate re-acquire, epoch still monotonic
    store.release(b)
    c = store.acquire("c1", "A", 10.0)
    assert c.epoch == 3
    assert single_holder_violations(store.audit_events()) == []


def test_lease_store_epochs_survive_restart(tmp_path):
    clock = FakeClock()
    store = FileLeaseStore(str(tmp_path), skew_slack_s=0.5, clock=clock)
    a = store.acquire("c1", "A", 5.0)
    store.release(a)
    # a fresh store object (restarted process) continues the epoch chain
    store2 = FileLeaseStore(str(tmp_path), skew_slack_s=0.5, clock=clock)
    b = store2.acquire("c1", "B", 5.0)
    assert b.epoch == 2


def test_epoch_floor_survives_lease_file_loss(tmp_path):
    """A lost/corrupt lease file must not reset the fencing token: with
    execution journals already stamped at higher epochs, an epoch reset
    would make replay's high-water filter drop the NEW holder's
    legitimate writes as zombie writes.  The audit trail is the floor."""
    clock = FakeClock()
    store = FileLeaseStore(str(tmp_path), skew_slack_s=0.5, clock=clock)
    a = store.acquire("c1", "A", 5.0)
    store.release(a)
    b = store.acquire("c1", "B", 5.0)
    assert b.epoch == 2
    os.remove(store._lease_path("c1"))  # operator loses the lease file
    clock.advance(10.0)
    c = store.acquire("c1", "A", 5.0)
    assert c.epoch == 3  # continues past the audit-trail floor, not 1


def test_fence_is_time_based_not_event_based(tmp_path):
    """The zombie shape: the renewal thread stalls, so NO loss event ever
    fires — the fence must still revoke itself by time, strictly before
    the store's takeover window opens."""
    clock = FakeClock()
    store = FileLeaseStore(str(tmp_path), skew_slack_s=1.0, clock=clock)
    mgr = LeaseManager(store, ["c1"], holder_id="A", ttl_s=10.0, renew_s=2.0,
                       skew_slack_s=1.0, clock=clock)
    mgr.poll_once()
    fence = mgr.fence("c1")
    assert fence.check() == 1
    # deadline-slack = +9s: the fence dies at 9 even though the manager
    # never polls again
    clock.advance(9.5)
    with pytest.raises(FencedError):
        fence.check()
    # ...while the store would only grant a takeover at +11
    assert store.acquire("c1", "B", 10.0) is None
    clock.advance(2.0)
    assert store.acquire("c1", "B", 10.0) is not None


def test_lease_manager_loss_and_reacquire_callbacks(tmp_path):
    clock = FakeClock()
    store = FileLeaseStore(str(tmp_path), skew_slack_s=1.0, clock=clock)
    events = []
    a = LeaseManager(store, ["c1"], holder_id="A", ttl_s=10.0, renew_s=2.0,
                     skew_slack_s=1.0, clock=clock,
                     on_acquired=lambda cid, lease, tk: events.append(("A+", cid, tk)),
                     on_lost=lambda cid, lease: events.append(("A-", cid)))
    b = LeaseManager(store, ["c1"], holder_id="B", ttl_s=10.0, renew_s=2.0,
                     skew_slack_s=1.0, clock=clock,
                     on_acquired=lambda cid, lease, tk: events.append(("B+", cid, tk)))
    a.poll_once()
    b.poll_once()  # no-op: A holds
    assert events == [("A+", "c1", False)]
    clock.advance(12.0)  # A stalled past ttl + slack
    b.poll_once()
    assert events[-1] == ("B+", "c1", True)  # marked as a takeover
    a.poll_once()  # A wakes, discovers the loss
    assert events[-1] == ("A-", "c1")
    assert not a.owns("c1") and b.owns("c1")
    assert single_holder_violations(store.audit_events()) == []


def test_lease_manager_validates_timings(tmp_path):
    store = FileLeaseStore(str(tmp_path))
    with pytest.raises(ValueError):
        LeaseManager(store, ["c1"], holder_id="A", ttl_s=5.0, renew_s=5.0)
    with pytest.raises(ValueError):
        LeaseManager(store, ["c1"], holder_id="A", ttl_s=5.0, renew_s=1.0,
                     skew_slack_s=3.0)
    with pytest.raises(ValueError):
        # renewals slower than the fence window (ttl - slack): the
        # rightful holder's fence would expire between heartbeats
        LeaseManager(store, ["c1"], holder_id="A", ttl_s=10.0, renew_s=9.0,
                     skew_slack_s=1.5)


# --------------------------------------------------------------- fencing


def test_journal_append_stamps_and_checks_epoch(tmp_path):
    fence = StubFence(epoch=3)
    j = ExecutionJournal(str(tmp_path / "j.jsonl"), fence=fence)
    j.start_execution({"uuid": "u", "ms": 0, "tasks": [], "options": {}})
    j.append({"t": "task", "id": 0, "state": "IN_PROGRESS", "ms": 1})
    j.flush()
    records = [json.loads(s) for s in open(j.path)]
    assert all(r["epoch"] == 3 for r in records)
    # the fence trips: nothing is written
    size = os.path.getsize(j.path)
    fence.ok = False
    with pytest.raises(FencedError):
        j.append({"t": "task", "id": 0, "state": "COMPLETED", "ms": 2})
    with pytest.raises(FencedError):
        j.start_execution({"uuid": "u2", "ms": 3, "tasks": [], "options": {}})
    j.flush()
    assert os.path.getsize(j.path) == size


def test_replay_drops_zombie_writes_below_high_water(tmp_path):
    """A deposed holder's late write (epoch below one already seen) is
    ignored; legitimate mixed epochs — a takeover appending at a HIGHER
    epoch onto its predecessor's records — replay in full."""
    p = tmp_path / "j.jsonl"
    lines = [
        {"t": "start", "uuid": "u", "ms": 0, "tasks": [], "options": {},
         "epoch": 1},
        {"t": "task", "id": 0, "state": "IN_PROGRESS", "ms": 1, "epoch": 1},
        {"t": "task", "id": 0, "state": "COMPLETED", "ms": 2, "epoch": 2},
        {"t": "task", "id": 1, "state": "IN_PROGRESS", "ms": 3, "epoch": 1},
        {"t": "task", "id": 2, "state": "IN_PROGRESS", "ms": 4, "epoch": 2},
    ]
    p.write_text("".join(json.dumps(r) + "\n" for r in lines))
    records = ExecutionJournal(str(p)).replay()
    assert [r.get("id") for r in records] == [None, 0, 0, 2]  # zombie id=1 gone
    # epoch-less (single-instance) records always replay
    p.write_text('{"t":"start","ms":0}\n{"t":"finished","ms":1}\n')
    assert len(ExecutionJournal(str(p)).replay()) == 2


def test_fenced_cluster_admin_blocks_mutations_passes_reads(tmp_path):
    inner = SimulatedClusterAdmin(
        StaticMetadataProvider(synthetic_topology(num_brokers=4,
                                                  topics={"T0": 2}, seed=1)),
        link_rate_bytes_per_s=1e12,
    )
    fence = StubFence()
    admin = FencedClusterAdmin(inner, fence)
    # optional-capability probes see the wrapped admin's surface
    assert hasattr(admin, "tick") and hasattr(admin, "reassignment_remaining_bytes")
    spec_props = rotation_proposals(inner)[:1]
    from cruise_control_tpu.executor.admin import ReassignmentSpec

    spec = ReassignmentSpec("T0", spec_props[0].partition,
                            spec_props[0].new_replicas, 10.0)
    admin.reassign_partitions([spec])  # fenced-in: allowed
    assert inner.reassign_calls == 1
    fence.ok = False
    for call in (
        lambda: admin.reassign_partitions([spec]),
        lambda: admin.cancel_reassignments(),
        lambda: admin.cancel_partition_reassignments([("T0", 0)]),
        lambda: admin.elect_leaders([]),
        lambda: admin.alter_replica_logdirs([]),
        lambda: admin.set_replication_throttle(1e6, {"T0"}),
        lambda: admin.clear_replication_throttle(),
    ):
        with pytest.raises(FencedError):
            call()
    assert inner.reassign_calls == 1  # nothing reached the cluster
    # reads keep serving (degraded read-only mode)
    assert admin.topology() is not None
    assert admin.in_progress_reassignments() is not None


def test_fenced_executor_aborts_batch_cleanly(tmp_path):
    """Lease lost mid-batch: the executor's FencedError abort resets its
    state, journals nothing after the fence trip, and leaves the throttle
    for the NEW holder's reconciliation to sweep."""
    inner = SimulatedClusterAdmin(
        StaticMetadataProvider(synthetic_topology(num_brokers=4,
                                                  topics={"T0": 4}, seed=2)),
        link_rate_bytes_per_s=1000.0,
    )
    fence = StubFence()
    j = ExecutionJournal(str(tmp_path / "j.jsonl"), fence=fence)
    ex = Executor(FencedClusterAdmin(inner, fence), topic_names={0: "T0"},
                  journal=j)
    props = rotation_proposals(inner, data=3000.0)

    # trip the fence on the 3rd progress tick
    calls = [0]
    orig_tick = inner.tick

    def tick(seconds):
        calls[0] += 1
        if calls[0] == 3:
            fence.ok = False
        return orig_tick(seconds)

    inner.tick = tick
    with pytest.raises(FencedError):
        ex.execute_proposals(props, ExecutionOptions(
            concurrent_partition_movements_per_broker=1,
            progress_check_interval_s=1.0,
            replication_throttle_bytes_per_s=5000.0,
        ))
    assert ex.state == ExecutorState.NO_TASK_IN_PROGRESS
    assert ex.executor_state().get("fencedAbort") is True
    assert ex.sensors.counter("executor.fenced-aborts").count == 1
    # the zombie could NOT clear its throttle (that would race the new
    # holder); the journal shows it set and never cleared, so the new
    # holder's reconciliation sweeps it
    assert inner.throttle_rate == 5000.0
    records = ExecutionJournal(j.path).replay()
    assert any(r["t"] == "throttle_set" for r in records)
    assert not any(r["t"] in ("throttle_cleared", "finished") for r in records)


def test_fenced_start_does_not_wedge_executor(tmp_path):
    """A lease lost between the facade's pre-check and the journal's
    fsync'd start record must abort the request WITHOUT wedging the
    executor in STARTING_EXECUTION — the state resets, the abort is
    counted, and a re-fenced-in executor runs normally."""
    inner = SimulatedClusterAdmin(
        StaticMetadataProvider(synthetic_topology(num_brokers=4,
                                                  topics={"T0": 4}, seed=6)),
        link_rate_bytes_per_s=1e12,
    )
    fence = StubFence()
    j = ExecutionJournal(str(tmp_path / "j.jsonl"), fence=fence)
    ex = Executor(FencedClusterAdmin(inner, fence), topic_names={0: "T0"},
                  journal=j)
    props = rotation_proposals(inner)
    fence.ok = False
    with pytest.raises(FencedError):
        ex.execute_proposals(props[:1], ExecutionOptions())
    assert ex.state == ExecutorState.NO_TASK_IN_PROGRESS
    assert ex.executor_state().get("fencedAbort") is True
    # not wedged: reconciliation and a fenced-in execution both work
    fence.ok = True
    ex.reconcile_journal()
    res = ex.execute_proposals(props, ExecutionOptions(
        progress_check_interval_s=0.1))
    assert res.completed == len(ex.tracker.tasks()) and res.dead == 0


# ---------------------------------------------- journal retention (sat 1)


def _finished_execution(j):
    j.start_execution({"uuid": "u", "ms": 0, "tasks": [], "options": {}})
    j.append({"t": "finished", "ms": 1, "result": {}})


def test_journal_rotation_archives_terminal_executions(tmp_path):
    j = ExecutionJournal(str(tmp_path / "j.jsonl"), retention_count=10,
                         retention_hours=1000.0)
    for _ in range(4):
        _finished_execution(j)
    j.close()
    archives = sorted(tmp_path.glob("j.jsonl.*.done"))
    assert len(archives) == 3  # the 4th execution is the live file
    assert all(b'"t":"finished"' in a.read_bytes() for a in archives)


def test_journal_prune_respects_count_and_hours(tmp_path):
    j = ExecutionJournal(str(tmp_path / "j.jsonl"))  # retention unset
    for _ in range(6):
        _finished_execution(j)
    j.close()
    assert len(list(tmp_path.glob("j.jsonl.*.done"))) == 5
    assert j.prune_archives() == 0  # no bounds configured: prune is a no-op
    j.retention_count, j.retention_hours = 2, 1000.0
    assert j.prune_archives() == 3
    assert len(list(tmp_path.glob("j.jsonl.*.done"))) == 2
    # hours bound: age the survivors out
    j.retention_hours = 0.0
    assert j.prune_archives(now_ms=int(time.time() * 1000) + 10_000) == 2
    assert not list(tmp_path.glob("j.jsonl.*.done"))


def test_prune_never_touches_unfinished_journals(tmp_path):
    """Regression: pruning runs while an unfinished journal awaits
    recovery — the live journal AND any non-terminal file are intact."""
    j = ExecutionJournal(str(tmp_path / "j.jsonl"))
    _finished_execution(j)
    # live journal now holds an UNFINISHED execution awaiting recovery
    j.start_execution({"uuid": "u2", "ms": 2, "tasks": [], "options": {}})
    j.append({"t": "task", "id": 0, "state": "IN_PROGRESS", "ms": 3})
    j.close()
    # a stray non-terminal .done file (no finished record) is never pruned
    stray = tmp_path / "j.jsonl.123.deadbeef.done"
    stray.write_text('{"t":"start","ms":0}\n')
    j.retention_count, j.retention_hours = 0, 0.0  # prune EVERYTHING eligible
    assert j.prune_archives() == 1  # only the terminal archive went
    assert stray.exists()
    je = ExecutionJournal(str(tmp_path / "j.jsonl")).unfinished_execution()
    assert je is not None and je.uuid == "u2"


def test_executor_reconciliation_prunes_archives(tmp_path):
    admin = SimulatedClusterAdmin(
        StaticMetadataProvider(synthetic_topology(num_brokers=4,
                                                  topics={"T0": 2}, seed=3)),
        link_rate_bytes_per_s=1e12,
    )
    j = ExecutionJournal(str(tmp_path / "j.jsonl"), retention_count=1,
                         retention_hours=1000.0)
    for _ in range(4):
        _finished_execution(j)
    j.close()
    ex = Executor(admin, journal=ExecutionJournal(
        str(tmp_path / "j.jsonl"), retention_count=1, retention_hours=1000.0
    ))
    assert ex.state == ExecutorState.NO_TASK_IN_PROGRESS
    assert len(list(tmp_path.glob("j.jsonl.*.done"))) == 1


# ------------------------------------------- zero-length journal (sat 2)


def test_zero_length_journal_is_no_unfinished_execution(tmp_path):
    """Crash between file creation and the fsync'd start record."""
    p = tmp_path / "j.jsonl"
    p.write_bytes(b"")
    j = ExecutionJournal(str(p))
    assert j.replay() == []
    assert j.unfinished_execution() is None
    admin = SimulatedClusterAdmin(
        StaticMetadataProvider(synthetic_topology(num_brokers=4,
                                                  topics={"T0": 2}, seed=4)),
    )
    ex = Executor(admin, journal=ExecutionJournal(str(p)))
    assert ex.state == ExecutorState.NO_TASK_IN_PROGRESS
    assert not ex.has_recovered_execution
    # the file is appendable afterwards (torn-tail repair tolerates empty)
    j2 = ExecutionJournal(str(p))
    j2.append({"t": "task", "id": 0, "state": "PENDING", "ms": 0})
    j2.flush()
    assert len(ExecutionJournal(str(p)).replay()) == 1


def test_torn_first_line_journal_is_no_unfinished_execution(tmp_path):
    p = tmp_path / "j.jsonl"
    p.write_bytes(b'{"t": "sta')  # torn before the first record landed
    j = ExecutionJournal(str(p))
    assert j.unfinished_execution() is None
    j.append({"t": "task", "id": 0, "state": "PENDING", "ms": 0})
    j.flush()
    assert len(ExecutionJournal(str(p)).replay()) == 1  # tail repaired


# -------------------------------------------------- fault injectors (sat 3)


def test_lease_partition_fail_injector_accounting(tmp_path):
    clock = FakeClock()
    store = FileLeaseStore(str(tmp_path), skew_slack_s=1.0, clock=clock)
    mgr = LeaseManager(store, ["c1"], holder_id="A", ttl_s=10.0, renew_s=2.0,
                       skew_slack_s=1.0, clock=clock)
    mgr.poll_once()
    assert mgr.owns("c1")
    with faults.lease_partition(store, mode="fail") as log:
        clock.advance(2.0)
        mgr.poll_once()  # renew fails, but the fence window is still open
        assert mgr.owns("c1")
        clock.advance(7.5)  # past deadline - slack
        mgr.poll_once()  # renew fails AND the window closed: loss
        assert not mgr.owns("c1")
    assert log.calls.get("renew", 0) == 2
    assert log.total_fired == log.total_calls > 0
    # partition healed: the next poll re-acquires
    clock.advance(3.0)
    mgr.poll_once()
    assert mgr.owns("c1")


def test_lease_partition_hang_injector_releases_on_exit(tmp_path):
    import threading

    clock = FakeClock()
    store = FileLeaseStore(str(tmp_path), skew_slack_s=1.0, clock=clock)
    done = threading.Event()
    result = []
    with faults.lease_partition(store, ops=("acquire",), mode="hang") as log:
        def call():
            result.append(store.acquire("c1", "A", 10.0))
            done.set()

        t = threading.Thread(target=call, daemon=True)
        t.start()
        assert not done.wait(0.2)  # hung inside the partition
    assert done.wait(5.0)  # context exit released the call
    assert result[0] is not None and log.fired.get("acquire") == 1


def test_clock_skew_injector(tmp_path):
    clock = FakeClock(1000.0)
    store = FileLeaseStore(str(tmp_path), skew_slack_s=1.0, clock=clock)
    with faults.clock_skew(store, 5.0) as log:
        lease = store.acquire("c1", "A", 10.0)
        assert lease.deadline == 1015.0  # skewed now + ttl
    assert log.calls.get("clock", 0) >= 1
    assert store.clock() == 1000.0  # restored


def test_chaos_schedule_single_holder_invariant(tmp_path):
    """Seeded chaos: two instances, one with a flaky store partition and
    both with (within-slack) clock skew, racing one cluster set across
    many heartbeats — the audit trail must show at most one holder per
    cluster at any instant and both fences never held at once."""
    base = FakeClock()
    slack = 1.0
    store_a = FileLeaseStore(str(tmp_path), skew_slack_s=slack, clock=base)
    store_b = FileLeaseStore(str(tmp_path), skew_slack_s=slack, clock=base)
    a = LeaseManager(store_a, ["c1", "c2"], holder_id="A", ttl_s=6.0,
                     renew_s=1.5, skew_slack_s=slack, clock=base)
    b = LeaseManager(store_b, ["c1", "c2"], holder_id="B", ttl_s=6.0,
                     renew_s=1.5, skew_slack_s=slack, clock=base)
    with faults.clock_skew(store_a, 0.4), faults.clock_skew(a, 0.4), \
            faults.clock_skew(store_b, -0.4), faults.clock_skew(b, -0.4), \
            faults.lease_partition(
                store_a,
                schedule=faults.FaultSchedule(rate=0.35, seed=13),
                mode="fail",
            ):
        for _ in range(120):
            base.advance(1.1)
            a.poll_once()
            b.poll_once()
            for cid in ("c1", "c2"):
                assert not (a.owns(cid) and b.owns(cid)), (
                    f"both instances hold {cid}"
                )
    violations = single_holder_violations(store_a.audit_events())
    assert violations == [], violations


# ---------------------------------------- default-off parity (acceptance)


def test_ha_disabled_default_is_classic_fleet(tmp_path):
    """fleet.ha.enabled=false (the default): no lease store on disk, no
    lease manager, contexts start immediately, journal records carry no
    epoch, /fleet carries no ownership/ha fields."""
    app, fleet = build_simulated_fleet(
        {"executor.journal.dir": str(tmp_path),
         "tpu.prewarm.enabled": "false"},  # see build_instance
        clusters={"solo": dict(num_brokers=4, topics={"T0": 4})},
        sampled_windows=1,
    )
    try:
        assert fleet.lease_manager is None
        assert not (tmp_path / "_leases").exists()
        cc = fleet.facade("solo")
        assert cc.fence is None
        fleet.start_up()
        assert fleet.contexts["solo"].started
        ex = cc.executor
        ex.topic_names[0] = "T0"
        props = rotation_proposals(cc.admin)[:2]
        res = ex.execute_proposals(props, ExecutionOptions(
            progress_check_interval_s=0.1))
        assert res.completed == len(ex.tracker.tasks()) and res.dead == 0
        records = ExecutionJournal(ex.journal.path).replay()
        assert records and all("epoch" not in r for r in records)
        state = fleet.fleet_state()
        assert "ha" not in state
        assert "ownership" not in state["clusters"]["solo"]
        assert validate_response("fleet", state) == []
    finally:
        fleet.shutdown()


# -------------------------------- two-instance failover (the chaos gate)


@pytest.mark.slow
def test_kill_and_takeover_acceptance_story(tmp_path):
    """Instance A crashes mid-inter-broker batch (process_crash); B's
    heartbeat takes the lease over after expiry, replays A's journal,
    sweeps the leaked throttle and resumes the batch with ZERO duplicate
    submissions; the audit trail shows a clean single-holder handover."""
    clock = FakeClock()
    backends = shared_backends(("c1",), link_rate=1000.0)
    inner_admin = backends["c1"][1]
    counts = spy_submissions(inner_admin)

    app_a, fleet_a = build_instance("A", tmp_path, backends, clock)
    lm_a = fleet_a.lease_manager
    lm_a.poll_once()
    assert lm_a.owns("c1")
    ex_a = fleet_a.facade("c1").executor
    assert wait_until(lambda: fleet_a.contexts["c1"].started
                      and not ex_a.has_ongoing_execution)
    ex_a.topic_names[0] = "T0"
    props = rotation_proposals(inner_admin, data=3000.0)
    with faults.process_crash(inner_admin,
                              schedule=faults.FaultSchedule(calls=[4])):
        with pytest.raises(faults.SimulatedProcessCrash):
            ex_a.execute_proposals(props, ExecutionOptions(
                concurrent_partition_movements_per_broker=2,
                progress_check_interval_s=1.0,
                replication_throttle_bytes_per_s=5000.0,
            ))
    # the dead process left its throttle + in-flight moves behind
    assert inner_admin.throttle_rate == 5000.0
    assert inner_admin.in_progress_reassignments()
    journal_path = ex_a.journal.path
    records = ExecutionJournal(journal_path).replay()
    assert all(r["epoch"] == 1 for r in records)

    # A is dead: its heartbeat never runs again; the lease expires
    clock.advance(12.0)
    app_b, fleet_b = build_instance("B", tmp_path, backends, clock)
    lm_b = fleet_b.lease_manager
    lm_b.poll_once()
    assert lm_b.owns("c1")
    cc_b = fleet_b.facade("c1")
    # activation (async) reconciles A's journal: the throttle sweep is
    # journaled into the recovery report before anything resumes
    assert wait_until(lambda: cc_b.executor.recovery_info() is not None)
    info = cc_b.executor.recovery_info()
    assert info["sweptThrottle"] is True

    # the resume thread drives the remainder to completion
    assert wait_until(
        lambda: (not cc_b.executor.has_recovered_execution
                 and not cc_b.executor.has_ongoing_execution
                 and fleet_b.contexts["c1"].started),
        timeout=60,
    )
    assert cc_b.executor.state == ExecutorState.NO_TASK_IN_PROGRESS
    # ZERO duplicate submissions across the kill-and-takeover
    assert counts and all(n == 1 for n in counts.values()), counts
    # every partition landed on its rotated replica set
    topo = inner_admin.topology()
    n = len(topo.brokers)
    by_key = {(p.topic, p.partition): set(p.replicas) for p in topo.partitions}
    for p in props:
        assert by_key[("T0", p.partition)] == set(p.new_replicas)
    assert inner_admin.throttle_rate is None  # zero leaked throttles
    # B's resume journaled at its own (higher) epoch
    records = ExecutionJournal(journal_path).replay()
    assert {r["epoch"] for r in records} == {1, 2}
    violations = single_holder_violations(
        lm_b.store.audit_events()
    )
    assert violations == [], violations

    # A wakes up a zombie: degraded, fenced, loud
    lm_a.poll_once()  # discovers the loss
    ctx_a = fleet_a.contexts["c1"]
    assert ctx_a.degraded
    state = fleet_a.fleet_state()
    own = state["clusters"]["c1"]["ownership"]
    assert own["owned"] is False and own["degraded"] is True
    assert own.get("holderId") == "B"
    assert validate_response("fleet", state) == []
    # the FLEET_LEASE_LOST anomaly reached the notifier (alert-only)
    cc_a = fleet_a.facade("c1")
    handled = cc_a.anomaly_detector._drain()
    assert any(
        r.anomaly.anomaly_type.name == "FLEET_LEASE_LOST" for r in handled
    )
    assert any(
        a.anomaly_type.name == "FLEET_LEASE_LOST"
        for a, _fix in cc_a.notifier.alerts
    )
    fleet_a.shutdown()
    fleet_b.shutdown()


@pytest.mark.slow
def test_zombie_writer_is_fenced_everywhere(tmp_path):
    """A's stalled thread wakes AFTER the takeover: every journal append
    and every admin mutation is rejected with FencedError, and neither
    the journal file nor the cluster sees the write."""
    clock = FakeClock()
    backends = shared_backends(("c1",), link_rate=1000.0)
    inner_admin = backends["c1"][1]

    app_a, fleet_a = build_instance("A", tmp_path, backends, clock)
    fleet_a.lease_manager.poll_once()
    cc_a = fleet_a.facade("c1")
    ex_a = cc_a.executor
    assert wait_until(lambda: fleet_a.contexts["c1"].started
                      and not ex_a.has_ongoing_execution)
    ex_a.topic_names[0] = "T0"
    # A journals a live execution start, then its process stalls
    props = rotation_proposals(inner_admin, data=10_000.0)[:2]
    with faults.process_crash(inner_admin,
                              schedule=faults.FaultSchedule(calls=[1])):
        with pytest.raises(faults.SimulatedProcessCrash):
            ex_a.execute_proposals(props, ExecutionOptions(
                progress_check_interval_s=1.0))
    clock.advance(12.0)  # A's lease expires while it is stalled

    app_b, fleet_b = build_instance("B", tmp_path, backends, clock)
    fleet_b.lease_manager.poll_once()
    assert fleet_b.lease_manager.owns("c1")

    # ...now A's stalled thread wakes and tries to keep going
    reassign_calls = inner_admin.reassign_calls
    journal_size = os.path.getsize(ex_a.journal.path)
    with pytest.raises(FencedError):
        ex_a.journal.append({"t": "task", "id": 0, "state": "COMPLETED",
                             "ms": 99})
    from cruise_control_tpu.executor.admin import ReassignmentSpec

    with pytest.raises(FencedError):
        cc_a.admin.reassign_partitions([
            ReassignmentSpec("T0", 0, (0, 1), 1.0)
        ])
    with pytest.raises(FencedError):
        cc_a.admin.clear_replication_throttle()
    # a full re-execution attempt through the facade gate is fenced too
    with pytest.raises(FencedError):
        cc_a.fence.check(op="execute")
    assert inner_admin.reassign_calls == reassign_calls
    assert os.path.getsize(ex_a.journal.path) == journal_size
    # B is unaffected: its fenced-in resume finishes the batch
    cc_b = fleet_b.facade("c1")
    assert wait_until(
        lambda: (fleet_b.contexts["c1"].started
                 and not cc_b.executor.has_recovered_execution
                 and not cc_b.executor.has_ongoing_execution),
        timeout=60,
    )
    assert cc_b.executor.state == ExecutorState.NO_TASK_IN_PROGRESS
    fleet_a.shutdown()
    fleet_b.shutdown()
