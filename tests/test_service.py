"""Service-layer tests: config system, facade operations, REST API.

Mirrors reference KafkaCruiseControlServletEndpointTest / UserTaskManagerTest
(SURVEY §4.4) over the in-process simulated service.
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from cruise_control_tpu.config import ConfigException, CruiseControlConfig
from cruise_control_tpu.service.main import build_simulated_service
from cruise_control_tpu.service.progress import OperationProgress
from cruise_control_tpu.service.purgatory import Purgatory, ReviewStatus
from cruise_control_tpu.service.server import GET_ENDPOINTS, POST_ENDPOINTS


# ----------------------------------------------------------------- config


def test_config_defaults_and_overrides():
    c = CruiseControlConfig({})
    assert c.get("max.replicas.per.broker") == 10_000
    assert c.get("num.concurrent.partition.movements.per.broker") == 5
    c2 = CruiseControlConfig({"cpu.balance.threshold": "1.25"})
    assert c2.balancing_constraint().balance_threshold[0] == 1.25


def test_config_validation():
    with pytest.raises(ConfigException):
        CruiseControlConfig({"cpu.capacity.threshold": "1.5"})  # > 1.0
    with pytest.raises(ConfigException):
        CruiseControlConfig({"default.goals": "NoSuchGoal"})


def test_purgatory_flow():
    p = Purgatory()
    info = p.add("rebalance", {"dryrun": "false"})
    assert info.status == ReviewStatus.PENDING_REVIEW
    p.review(info.review_id, approve=True)
    taken = p.take_approved("rebalance", info.review_id)
    assert taken.status == ReviewStatus.SUBMITTED
    with pytest.raises(ValueError):
        p.take_approved("rebalance", info.review_id)  # already submitted


# ----------------------------------------------------------------- service


@pytest.fixture(scope="module")
def service():
    app, fetcher, admin, sampler = build_simulated_service(seed=3)
    app.start()
    yield app
    app.stop()


def _url(app, endpoint, **params):
    q = "&".join(f"{k}={v}" for k, v in params.items())
    return f"http://{app.host}:{app.port}{app.prefix}/{endpoint}" + (f"?{q}" if q else "")


def _request(app, method, endpoint, headers=None, **params):
    req = urllib.request.Request(
        _url(app, endpoint, **params), method=method, headers=headers or {}
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


def _poll(app, method, endpoint, **params):
    """Drive the 202 + User-Task-ID pattern to completion."""
    status, payload, headers = _request(app, method, endpoint, **params)
    tid = headers.get("User-Task-ID")
    deadline = time.time() + 60
    while status == 202 and time.time() < deadline:
        time.sleep(0.3)
        status, payload, headers = _request(
            app, method, endpoint, headers={"User-Task-ID": tid}, **params
        )
    return status, payload


def test_state_endpoint(service):
    status, payload, _ = _request(service, "GET", "state")
    assert status == 200
    assert {"MonitorState", "ExecutorState", "AnalyzerState", "AnomalyDetectorState"} <= set(payload)
    assert payload["MonitorState"]["numValidWindows"] >= 2
    # substates filter
    status, payload, _ = _request(service, "GET", "state", substates="monitor")
    assert "ExecutorState" not in payload


def test_kafka_cluster_state(service):
    status, payload, _ = _request(service, "GET", "kafka_cluster_state")
    assert status == 200
    assert payload["KafkaPartitionState"]["numTotalPartitions"] == 24
    assert len(payload["KafkaBrokerState"]) == 6


def test_load_endpoint(service):
    status, payload = _poll(service, "GET", "load")
    assert status == 200
    assert len(payload["brokers"]) == 6
    assert all("CPUPct" in b for b in payload["brokers"])


def test_partition_load_endpoint(service):
    status, payload = _poll(service, "GET", "partition_load", resource="NW_IN", entries=5)
    assert status == 200
    vals = [r["NW_IN"] for r in payload["records"]]
    assert vals == sorted(vals, reverse=True) and len(vals) <= 5


def test_proposals_and_cache(service):
    status, payload = _poll(service, "GET", "proposals")
    assert status == 200
    assert "balancednessAfter" in payload
    # second call should hit the proposal cache (fast, same result)
    t0 = time.time()
    status2, payload2 = _poll(service, "GET", "proposals")
    assert status2 == 200 and time.time() - t0 < 5
    assert payload2["balancednessAfter"] == payload["balancednessAfter"]


def test_rebalance_dryrun_then_execute(service):
    status, payload = _poll(service, "POST", "rebalance", dryrun="true")
    assert status == 200
    status, payload = _poll(service, "POST", "rebalance", dryrun="false")
    assert status == 200
    if "execution" in payload:
        assert payload["execution"]["dead"] == 0
    # post-execution: proposals should find (almost) nothing left to move
    status, after = _poll(service, "GET", "proposals", ignore_proposal_cache="true")
    assert after["balancednessAfter"] >= payload["balancednessAfter"] - 1e-6


def test_user_tasks_listing(service):
    status, payload, _ = _request(service, "GET", "user_tasks")
    assert status == 200
    assert any(t["Status"] in ("Active", "Completed") for t in payload["userTasks"])


def test_pause_resume_sampling(service):
    status, payload, _ = _request(service, "POST", "pause_sampling", reason="test")
    assert status == 200
    assert service.cc.monitor.monitor_state()["state"] == "PAUSED"
    _request(service, "POST", "resume_sampling")
    assert service.cc.monitor.monitor_state()["state"] == "RUNNING"


def test_admin_self_healing_toggle(service):
    status, payload, _ = _request(
        service, "POST", "admin", enable_self_healing_for="goal_violation"
    )
    assert status == 200 and "GOAL_VIOLATION" in payload["selfHealingEnabled"]
    _request(service, "POST", "admin", disable_self_healing_for="goal_violation")
    assert not service.cc.notifier.self_healing_enabled()[
        __import__("cruise_control_tpu.detector", fromlist=["AnomalyType"]).AnomalyType.GOAL_VIOLATION
    ]


def test_demote_broker(service):
    status, payload = _poll(service, "POST", "demote_broker", brokerid="0", dryrun="false")
    assert status == 200
    topo = service.cc.admin.topology()
    leaders = {p.leader for p in topo.partitions}
    assert 0 not in leaders


def test_topic_configuration_rf_change(service):
    status, payload = _poll(
        service, "POST", "topic_configuration", topic="T0", replication_factor="3",
        dryrun="false",
    )
    assert status == 200
    topo = service.cc.admin.topology()
    for p in topo.partitions:
        if p.topic == "T0":
            assert len(p.replicas) == 3


def test_unknown_endpoint_and_bad_params(service):
    with pytest.raises(urllib.error.HTTPError) as e:
        _request(service, "GET", "nonsense")
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _request(service, "POST", "remove_broker")  # missing brokerid
    assert e.value.code == 400


def test_endpoint_surface_complete():
    """The reference exposes 9 GET + 11 POST endpoints
    (CruiseControlEndPoint.java:16-37) — all must exist here, plus the
    planner's read-only /rightsize (GET) and /simulate (POST), the
    observability surface /trace + /metrics + /slo + the decision
    ledger's /explain + /ledger (GET), and the fleet controller's /fleet
    rollup (GET)."""
    assert set(GET_ENDPOINTS) == {
        "bootstrap", "train", "load", "partition_load", "proposals", "state",
        "kafka_cluster_state", "user_tasks", "review_board", "rightsize",
        "trace", "metrics", "fleet", "slo", "explain", "ledger",
    }
    assert set(POST_ENDPOINTS) == {
        "add_broker", "remove_broker", "fix_offline_replicas", "rebalance",
        "stop_proposal_execution", "pause_sampling", "resume_sampling",
        "demote_broker", "admin", "review", "topic_configuration", "simulate",
    }


# ----------------------------------------------------------------- security


def _basic(user, pw):
    import base64

    return {"Authorization": "Basic " + base64.b64encode(f"{user}:{pw}".encode()).decode()}


def _service_config(**extra):
    return CruiseControlConfig(
        {
            "partition.metrics.window.ms": 1000,
            "min.samples.per.partition.metrics.window": 1,
            "execution.progress.check.interval.ms": 100,
            "webserver.http.port": 0,
            **extra,
        }
    )


def test_basic_auth_credentials_parsing(tmp_path):
    from cruise_control_tpu.service.security import BasicSecurityProvider

    creds = tmp_path / "creds"
    creds.write_text("admin:secret\nviewer:vpw:VIEWER\n")
    p = BasicSecurityProvider(str(creds))
    assert p.authenticate({"Authorization": _basic("admin", "secret")["Authorization"]}) == (
        "admin", "ADMIN"
    )
    assert p.authenticate({"Authorization": _basic("viewer", "vpw")["Authorization"]}) == (
        "viewer", "VIEWER"
    )
    assert p.authenticate({"Authorization": _basic("admin", "wrong")["Authorization"]}) is None
    assert p.authenticate({}) is None
    # malformed lines must fail loudly, not create broken users
    bad_role = tmp_path / "bad_role"
    bad_role.write_text("user:pw:WIZARD\n")
    with pytest.raises(ValueError):
        BasicSecurityProvider(str(bad_role))
    no_pw = tmp_path / "no_pw"
    no_pw.write_text("loneuser\n")
    with pytest.raises(ValueError):
        BasicSecurityProvider(str(no_pw))


@pytest.fixture(scope="module")
def basic_auth_service(tmp_path_factory):
    creds = tmp_path_factory.mktemp("auth") / "credentials"
    creds.write_text("admin:adminpw:ADMIN\nviewer:viewerpw:VIEWER\n")
    config = _service_config(**{
        "webserver.security.enable": "true",
        "basic.auth.credentials.file": str(creds),
    })
    app, fetcher, admin, sampler = build_simulated_service(config, seed=5)
    app.start()
    yield app
    app.stop()


def test_unauthenticated_request_gets_401(basic_auth_service):
    with pytest.raises(urllib.error.HTTPError) as e:
        _request(basic_auth_service, "GET", "state")
    assert e.value.code == 401
    assert "WWW-Authenticate" in e.value.headers
    with pytest.raises(urllib.error.HTTPError) as e:
        _request(basic_auth_service, "GET", "state", headers=_basic("admin", "nope"))
    assert e.value.code == 401


def test_role_enforcement(basic_auth_service):
    # VIEWER may GET but not POST (reference DefaultRoleSecurityProvider)
    status, _, _ = _request(
        basic_auth_service, "GET", "state", headers=_basic("viewer", "viewerpw")
    )
    assert status == 200
    with pytest.raises(urllib.error.HTTPError) as e:
        _request(
            basic_auth_service, "POST", "pause_sampling",
            headers=_basic("viewer", "viewerpw"),
        )
    assert e.value.code == 403
    status, _, _ = _request(
        basic_auth_service, "POST", "pause_sampling", headers=_basic("admin", "adminpw")
    )
    assert status == 200
    _request(basic_auth_service, "POST", "resume_sampling", headers=_basic("admin", "adminpw"))


def test_jwt_auth_and_expiry():
    from cruise_control_tpu.service.security import JwtSecurityProvider, jwt_encode

    config = _service_config(**{
        "webserver.security.enable": "true",
        "jwt.secret.key": "test-secret",
    })
    app, fetcher, admin, sampler = build_simulated_service(config, seed=6)
    app.start()
    try:
        provider = app.security
        assert isinstance(provider, JwtSecurityProvider)
        admin_tok = provider.issue("ops", role="ADMIN")
        viewer_tok = provider.issue("watcher", role="VIEWER")
        expired_tok = jwt_encode(
            {"sub": "late", "role": "ADMIN", "exp": time.time() - 10}, "test-secret"
        )
        forged_tok = provider.issue("mallory", role="ADMIN")[:-4] + "AAAA"

        hdr = lambda t: {"Authorization": f"Bearer {t}"}  # noqa: E731
        status, _, _ = _request(app, "GET", "state", headers=hdr(admin_tok))
        assert status == 200
        status, _, _ = _request(
            app, "POST", "pause_sampling", headers=hdr(admin_tok)
        )
        assert status == 200
        with pytest.raises(urllib.error.HTTPError) as e:
            _request(app, "POST", "resume_sampling", headers=hdr(viewer_tok))
        assert e.value.code == 403
        for tok in (expired_tok, forged_tok, "garbage"):
            with pytest.raises(urllib.error.HTTPError) as e:
                _request(app, "GET", "state", headers=hdr(tok))
            assert e.value.code == 401
        _request(app, "POST", "resume_sampling", headers=hdr(admin_tok))
    finally:
        app.stop()


def test_unknown_user_task_id_rejected(service):
    with pytest.raises(urllib.error.HTTPError) as e:
        _request(
            service, "GET", "proposals", headers={"User-Task-ID": "no-such-task"}
        )
    assert e.value.code == 404


def test_session_rebind_resumes_same_task():
    """A client that lost the User-Task-ID header but repeats the identical
    request must resume the in-flight task, not start a second one
    (reference servlet/SessionManager.java)."""
    config = _service_config(**{
        "tpu.num.candidates": 64,
        "tpu.leadership.candidates": 16,
        "tpu.steps.per.round": 8,
        "tpu.num.rounds": 2,
    })
    app, fetcher, admin, sampler = build_simulated_service(config, seed=7)
    app.start()
    try:
        headers = {"X-Client": "c1"}
        status, payload, _ = _request(app, "GET", "proposals", headers=headers)
        n0 = len(app.user_tasks.all_tasks())
        deadline = time.time() + 60
        while status == 202 and time.time() < deadline:
            time.sleep(0.3)
            status, payload, _ = _request(app, "GET", "proposals", headers=headers)
        assert status == 200
        assert len(app.user_tasks.all_tasks()) == n0  # no duplicate task spawned
        assert app.sessions.num_active() == 0  # released once delivered
    finally:
        app.stop()


def test_header_delivery_releases_session_binding():
    """If the final response is delivered via the User-Task-ID header path,
    the session binding must be dropped too — a later identical request must
    execute fresh rather than resume the stale completed task."""
    config = _service_config(**{
        "tpu.num.candidates": 64,
        "tpu.leadership.candidates": 16,
        "tpu.steps.per.round": 8,
        "tpu.num.rounds": 2,
    })
    app, fetcher, admin, sampler = build_simulated_service(config, seed=9)
    app.start()
    try:
        headers = {"X-Client": "c2"}
        status, payload, h = _request(app, "GET", "proposals", headers=headers)
        tid = h.get("User-Task-ID")
        deadline = time.time() + 60
        while status == 202 and time.time() < deadline:
            time.sleep(0.3)
            # poll by HEADER (keeps the session binding out of the loop)
            status, payload, h = _request(
                app, "GET", "proposals",
                headers={"X-Client": "c2", "User-Task-ID": tid},
            )
        assert status == 200
        assert app.sessions.num_active() == 0  # header delivery released it
        # identical request again: must start a NEW task, not resume tid
        status2, _, h2 = _request(app, "GET", "proposals", headers=headers)
        assert h2.get("User-Task-ID") != tid
    finally:
        app.stop()


def test_two_step_verification_flow():
    config = CruiseControlConfig(
        {
            "partition.metrics.window.ms": 1000,
            "min.samples.per.partition.metrics.window": 1,
            "execution.progress.check.interval.ms": 100,
            "webserver.http.port": 0,
            "two.step.verification.enabled": "true",
            "tpu.num.candidates": 64,
            "tpu.leadership.candidates": 16,
            "tpu.steps.per.round": 8,
            "tpu.num.rounds": 2,
        }
    )
    app, fetcher, admin, sampler = build_simulated_service(config, seed=4)
    app.start()
    try:
        status, payload, _ = _request(app, "POST", "rebalance", dryrun="true")
        assert status == 200 and "reviewId" in payload
        rid = payload["reviewId"]
        status, board, _ = _request(app, "GET", "review_board")
        assert any(r["Id"] == rid for r in board["requestInfo"])
        _request(app, "POST", "review", approve=str(rid))
        status, payload = _poll(app, "POST", "rebalance", review_id=str(rid))
        assert status == 200 and "balancednessAfter" in payload
    finally:
        app.stop()


def test_ssl_listener():
    """REST over TLS (reference KafkaCruiseControlApp.java:100-120)."""
    import datetime
    import ssl as ssl_mod
    import tempfile

    pytest.importorskip("cryptography")
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name).issuer_name(name).public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=1))
        .not_valid_after(now + datetime.timedelta(hours=1))
        .sign(key, hashes.SHA256())
    )
    pem = tempfile.NamedTemporaryFile("wb", suffix=".pem", delete=False)
    pem.write(key.private_bytes(
        serialization.Encoding.PEM, serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption()))
    pem.write(cert.public_bytes(serialization.Encoding.PEM))
    pem.close()

    config = _service_config(**{
        "webserver.ssl.enable": "true",
        "webserver.ssl.certificate.location": pem.name,
    })
    app, fetcher, admin, sampler = build_simulated_service(config, seed=2)
    app.start()
    try:
        ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl_mod.CERT_NONE
        url = f"https://{app.host}:{app.port}{app.prefix}/state?substates=monitor"
        with urllib.request.urlopen(url, context=ctx, timeout=30) as resp:
            assert resp.status == 200
            assert "MonitorState" in json.loads(resp.read())
    finally:
        app.stop()


def test_slack_notifier_posts_webhook():
    """SlackSelfHealingNotifier formats + delivers alerts
    (reference SlackSelfHealingNotifier.java); injected poster, no egress."""
    from cruise_control_tpu.detector.anomalies import AnomalyType, GoalViolations
    from cruise_control_tpu.detector.notifier import (
        Action,
        SlackSelfHealingNotifier,
    )

    posts = []
    n = SlackSelfHealingNotifier(
        "https://hooks.slack.invalid/services/X",
        channel="#ops",
        poster=lambda url, body: posts.append((url, json.loads(body))),
        self_healing={AnomalyType.GOAL_VIOLATION: True},
    )
    anomaly = GoalViolations(fixable_violations=["DiskUsageDistributionGoal"])
    result = n.on_anomaly(anomaly)
    assert result.action == Action.FIX
    assert len(posts) == 1
    url, payload = posts[0]
    assert payload["channel"] == "#ops"
    assert "GOAL_VIOLATION" in payload["text"]
    # delivery failure must not propagate
    def boom(url, body):
        raise OSError("no route")
    n2 = SlackSelfHealingNotifier(
        "https://x.invalid", poster=boom,
        self_healing={AnomalyType.GOAL_VIOLATION: True},
    )
    assert n2.on_anomaly(anomaly).action == Action.FIX


def test_execution_overrides_reach_executor():
    """Per-request caps/throttle (reference ParameterUtils request params)
    must override the config-level defaults in ExecutionOptions."""
    from cruise_control_tpu.service.server import _parse_execution_overrides

    ov = _parse_execution_overrides({
        "concurrent_partition_movements_per_broker": ["9"],
        "concurrent_leader_movements": ["77"],
        "replication_throttle": ["12345"],
    })
    assert ov == {
        "concurrent_partition_movements_per_broker": 9,
        "concurrent_leader_movements": 77,
        "replication_throttle": 12345.0,
    }
    with pytest.raises(Exception):
        _parse_execution_overrides({"concurrent_leader_movements": ["xyz"]})

    app, fetcher, admin, sampler = build_simulated_service(seed=21)
    captured = {}
    real = app.cc.executor.execute_proposals

    def spy(proposals, options=None, **kw):
        captured["options"] = options
        return real(proposals, options, **kw)

    app.cc.executor.execute_proposals = spy
    try:
        out = app.cc.rebalance(
            OperationProgress(), dryrun=False,
            execution_overrides={
                "concurrent_partition_movements_per_broker": 9,
                "concurrent_leader_movements": 77,
                "replication_throttle": 12345.0,
            },
        )
        if "execution" in out:  # moves existed -> executor ran
            opts = captured["options"]
            assert opts.concurrent_partition_movements_per_broker == 9
            assert opts.concurrent_leader_movements == 77
            assert opts.replication_throttle_bytes_per_s == 12345.0
    finally:
        app.stop()


def test_operation_audit_log(service, caplog):
    """Every REST operation lands one line in the operations audit logger
    (reference OPERATION_LOGGER)."""
    import logging

    with caplog.at_level(logging.INFO, logger="cruisecontrol.operations"):
        _request(service, "GET", "state")
    recs = [r for r in caplog.records if r.name == "cruisecontrol.operations"]
    assert recs and "GET state" in recs[-1].getMessage()
    assert "-> 200" in recs[-1].getMessage()


def test_parse_bootstrap_servers():
    """IPv4/hostname/IPv6 bootstrap parsing (ADVICE r2: rpartition(':')
    mangled IPv6 literals)."""
    import pytest

    from cruise_control_tpu.service.main import parse_bootstrap_servers as parse

    assert parse("h1:9092,h2:9093") == [("h1", 9092), ("h2", 9093)]
    assert parse("h1") == [("h1", 9092)]
    assert parse(":9094") == [("127.0.0.1", 9094)]
    assert parse("::1") == [("::1", 9092)]
    assert parse("[::1]") == [("::1", 9092)]
    assert parse("[::1]:9095") == [("::1", 9095)]
    assert parse("[2001:db8::2]:9096, h7:9097") == [
        ("2001:db8::2", 9096), ("h7", 9097)
    ]
    for bad in ("h1:x", "[::1", "[::1]9092", "", "h1:"):
        with pytest.raises(ValueError):
            parse(bad)


def test_unknown_and_malformed_parameters_rejected(service):
    """Declared-parameter validation (reference CruiseControlParametersConfig
    parameter classes): unknown names and bad values 400 instead of being
    silently ignored."""
    with pytest.raises(urllib.error.HTTPError) as e:
        _request(service, "POST", "rebalance", dry_run="true")
    assert e.value.code == 400
    assert "unknown parameter" in json.loads(e.value.read())["errorMessage"]
    with pytest.raises(urllib.error.HTTPError) as e:
        _request(service, "GET", "proposals", ignore_proposal_cache="maybe")
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _request(service, "POST", "add_broker", brokerid="zero")
    assert e.value.code == 400


class UpperCaseReasonParameters:
    """Custom parameters class for tests: extends the builtin set."""

    def __init__(self, endpoint, builtin):
        self.builtin = builtin

    def parse(self, raw):
        out = self.builtin.parse({k: v for k, v in raw.items() if k != "shout"})
        if "shout" in raw:
            out["shout"] = raw["shout"][0]
        return out


def custom_pause_handler(app, endpoint, parsed):
    # custom request classes receive the PARSED parameter dict
    reason = parsed.get("reason", "user request")
    app.cc.monitor.pause(reason.upper())
    return 200, {"message": f"sampling paused: {reason.upper()}"}


def test_parameter_and_request_class_override_maps():
    """{endpoint}.parameters.class / {endpoint}.request.class plug custom
    classes per endpoint (reference CruiseControlRequestConfig)."""
    config = CruiseControlConfig({
        "pause_sampling.parameters.class":
            "tests.test_service.UpperCaseReasonParameters",
        "pause_sampling.request.class":
            "tests.test_service.custom_pause_handler",
    })
    app, fetcher, admin, sampler = build_simulated_service(config, seed=21)
    app.start()
    try:
        # the custom parameters class accepts `shout`, builtin would 400
        status, payload, _ = _request(
            app, "POST", "pause_sampling", reason="drill", shout="1"
        )
        assert status == 200
        assert payload["message"] == "sampling paused: DRILL"
    finally:
        app.stop()


def test_two_step_rejects_invalid_params_before_parking():
    """An invalid request must 400 immediately, not park in the purgatory
    with a 200 and burn its approval on resubmit."""
    config = CruiseControlConfig({"two.step.verification.enabled": "true"})
    app, fetcher, admin, sampler = build_simulated_service(config, seed=22)
    app.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _request(app, "POST", "rebalance", dry_run="true")
        assert e.value.code == 400
        # a VALID request still parks for review
        status, payload, _ = _request(app, "POST", "rebalance", dryrun="true")
        assert status == 200 and "reviewId" in payload
    finally:
        app.stop()


def capture_rebalance_handler(app, endpoint, parsed):
    return 200, {"numReplicaMovements": 0, "numLeaderMovements": 0,
                 "dataToMoveMB": 0, "balancednessBefore": 0.0,
                 "balancednessAfter": 0.0, "objectiveBefore": 0.0,
                 "objectiveAfter": 0.0, "violatedGoalsAfter": [],
                 "wallSeconds": 0.0, "proposals": [],
                 "execution": {"parsedSeen": {k: str(v) for k, v in parsed.items()}}}


def test_two_step_resubmit_passes_merged_parsed_to_custom_handler():
    """After approval, a custom request class must see the PARKED parameters
    (merged + re-parsed), not just the resubmit's review_id."""
    config = CruiseControlConfig({
        "two.step.verification.enabled": "true",
        "rebalance.request.class": "tests.test_service.capture_rebalance_handler",
    })
    app, fetcher, admin, sampler = build_simulated_service(config, seed=23)
    app.start()
    try:
        status, payload, _ = _request(
            app, "POST", "rebalance", dryrun="true", excluded_topics="T0"
        )
        assert status == 200 and "reviewId" in payload
        rid = payload["reviewId"]
        status, payload, _ = _request(app, "POST", "review", approve=str(rid))
        assert status == 200
        status, payload, _ = _request(app, "POST", "rebalance", review_id=str(rid))
        assert status == 200
        seen = payload["execution"]["parsedSeen"]
        assert seen.get("dryrun") == "True"
        assert seen.get("excluded_topics") == "T0"
    finally:
        app.stop()


def test_admin_concurrency_change_mid_execution():
    """Reference AdminParameters.java:31-38 ChangeExecutionConcurrency:
    an operator halts/accelerates a LIVE rebalance via POST /admin; the
    executor consults the change on its next progress tick."""
    import threading

    app, fetcher, admin, sampler = build_simulated_service(seed=31)
    app.start()
    try:
        gate = threading.Event()
        orig_tick = admin.tick

        def gated_tick(seconds):
            time.sleep(0.02)
            # no progress until the test releases the gate — keeps the
            # execution alive regardless of proposal sizes
            return orig_tick(seconds if gate.is_set() else 0.0)

        admin.tick = gated_tick

        status, first, headers = _request(app, "POST", "rebalance", dryrun="false")
        tid = headers.get("User-Task-ID")
        deadline = time.time() + 30
        while not app.cc.executor.has_ongoing_execution and time.time() < deadline:
            time.sleep(0.05)
        assert app.cc.executor.has_ongoing_execution, "execution never started"

        status2, payload2, _ = _request(
            app, "POST", "admin",
            concurrent_partition_movements_per_broker="8",
            concurrent_leader_movements="500",
            execution_progress_check_interval_ms="50",
        )
        assert status2 == 200
        assert payload2["ongoingExecution"] is True
        assert payload2["requestedConcurrency"] == {
            "inter_broker": 8, "leadership": 500, "interval_s": 0.05,
        }
        # the LIVE executor sees it (next tick reads these, not the frozen
        # ExecutionOptions)
        assert app.cc.executor.requested_concurrency()["inter_broker"] == 8
        # and STATE surfaces it
        st, state_payload, _ = _request(app, "GET", "state", substates="executor")
        assert state_payload["ExecutorState"]["requestedConcurrency"][
            "inter_broker"] == 8

        gate.set()  # let the execution drain
        status3, payload3, _ = _request(
            app, "POST", "rebalance", dryrun="false",
            headers={"User-Task-ID": tid},
        )
        deadline = time.time() + 60
        while status3 == 202 and time.time() < deadline:
            time.sleep(0.2)
            status3, payload3, _ = _request(
                app, "POST", "rebalance", dryrun="false",
                headers={"User-Task-ID": tid},
            )
        assert status3 == 200
        if "execution" in payload3:
            assert payload3["execution"]["dead"] == 0
    finally:
        app.stop()


def test_admin_concurrency_rejects_bad_values(service):
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as e:
        _request(service, "POST", "admin",
                 concurrent_partition_movements_per_broker="0")
    assert e.value.code == 400


def test_admin_concurrency_requires_ongoing_execution(service):
    """Overrides die with the execution, so accepting one while idle would
    200 a silent no-op — the reference rejects it (AdminParameters)."""
    import urllib.error

    assert not service.cc.executor.has_ongoing_execution
    with pytest.raises(urllib.error.HTTPError) as e:
        _request(service, "POST", "admin", concurrent_leader_movements="5")
    assert e.value.code == 400
    assert service.cc.executor.requested_concurrency() == {}


def test_admin_drop_recently_demoted_brokers(service):
    ex = service.cc.executor
    ex._demoted_history[4] = int(time.time() * 1000)
    status, payload, _ = _request(
        service, "POST", "admin", drop_recently_demoted_brokers="4"
    )
    assert status == 200
    assert 4 not in ex.demoted_brokers
    assert payload["recentlyDemotedBrokers"] == sorted(ex.demoted_brokers)


def test_long_running_task_survives_retention_after_completion():
    """Purgatory-retention audit for long-running async ops: a task whose
    EXECUTION outlives the completed-task retention window (a rightsize
    search, a big simulate batch) must stay pollable for the full window
    AFTER completion — retention counts from completion, not creation.
    Under the old creation-stamped retention the record expired the moment
    it finished, 404ing the poll that was waiting on it."""
    import threading

    from cruise_control_tpu.service.tasks import UserTaskManager

    utm = UserTaskManager(completed_retention_ms=150, max_cached_completed=10)
    try:
        gate = threading.Event()

        def long_op(progress):
            gate.wait(10)
            return {"provisionStatus": "RIGHT_SIZED"}

        task = utm.submit("rightsize", long_op)
        time.sleep(0.4)  # run well past the 150ms retention window
        # in-execution: eviction scans must never touch it
        utm.submit("load", lambda p: {})
        assert utm.get(task.task_id) is not None
        gate.set()
        task.future.result(timeout=10)
        # freshly completed (older than retention since CREATION): an
        # eviction scan must keep it — the client has not polled yet
        utm.submit("load", lambda p: {})
        resumed = utm.get(task.task_id)
        assert resumed is not None, "completed task expired before it could be polled"
        assert resumed.future.result()["provisionStatus"] == "RIGHT_SIZED"
        # ...and once the window has passed SINCE COMPLETION it may expire
        time.sleep(0.4)
        utm.submit("load", lambda p: {})
        assert utm.get(task.task_id) is None
    finally:
        utm.shutdown()


def test_user_tasks_filters(service):
    # seed at least one completed task
    _poll(service, "GET", "load")
    status, payload, _ = _request(service, "GET", "user_tasks")
    all_tasks = payload["userTasks"]
    assert all_tasks
    # endpoints filter
    status, by_ep, _ = _request(service, "GET", "user_tasks", endpoints="load")
    assert by_ep["userTasks"]
    assert all("load" in t["RequestURL"].lower() for t in by_ep["userTasks"])
    # types filter (task status names)
    status, by_type, _ = _request(service, "GET", "user_tasks", types="Completed")
    assert all(t["Status"] == "Completed" for t in by_type["userTasks"])
    # user_task_ids filter
    tid = all_tasks[0]["UserTaskId"]
    status, by_id, _ = _request(service, "GET", "user_tasks", user_task_ids=tid)
    assert [t["UserTaskId"] for t in by_id["userTasks"]] == [tid]
    # client_ids filter with a known client identity
    status, _p, _ = _request(
        service, "GET", "proposals", headers={"X-Client": "filter-me"}
    )
    deadline = time.time() + 30
    while time.time() < deadline:
        status, by_client, _ = _request(
            service, "GET", "user_tasks", client_ids="filter-me"
        )
        if by_client["userTasks"]:
            break
        time.sleep(0.1)
    assert by_client["userTasks"]
    assert all(t["ClientIdentity"] == "filter-me" for t in by_client["userTasks"])
    # non-matching filter returns empty, not everything
    status, none, _ = _request(service, "GET", "user_tasks", client_ids="nobody")
    assert none["userTasks"] == []


# -------------------------------------------- observability (PR 6 surface)


def _raw_get(app, endpoint, **params):
    """GET returning the raw body (the /metrics exposition is text)."""
    req = urllib.request.Request(_url(app, endpoint, **params), method="GET")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, resp.read().decode(), dict(resp.headers)


def _flatten_spans(nodes):
    out = []
    for n in nodes:
        out.append(n)
        out.extend(_flatten_spans(n["children"]))
    return out


def test_metrics_endpoint_is_lintable_prometheus_text(service):
    from cruise_control_tpu.common.exposition import parse_exposition

    # make sure at least one proposal ran so analyzer sensors exist
    _poll(service, "GET", "proposals")
    status, body, headers = _raw_get(service, "metrics")
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain")
    fams = parse_exposition(body)  # raises ExpositionError on any lint hit
    assert "cruisecontrol_analyzer_proposal_computation_timer_seconds" in fams
    assert (
        fams["cruisecontrol_analyzer_proposal_computation_seconds"]["type"]
        == "histogram"
    )
    # the device-memory surface registered by the facade is scrapeable
    assert "cruisecontrol_tpu_device_live_buffers" in fams


def test_trace_of_a_proposal_covers_monitor_analyzer_device(service):
    """A fresh (cache-bypassing) proposal computation yields one trace
    whose tree covers model build -> optimize -> supervised device op,
    with the engine-run timing attached as span attributes."""
    status, payload = _poll(
        service, "GET", "proposals", ignore_proposal_cache="true"
    )
    assert status == 200
    tid = payload.get("_traceId")
    assert tid, "200 responses must carry the flight-recorder trace id"
    status, trace, _ = _request(service, "GET", "trace", id=tid)
    assert status == 200
    assert trace["traceId"] == tid
    spans = _flatten_spans(trace["spans"])
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], s)
    assert "service.proposals" in by_name
    assert by_name["service.proposals"]["parentId"] is None
    assert "monitor.cluster_model" in by_name
    assert by_name["monitor.cluster_model"]["attributes"]["brokers"] >= 6
    opt = by_name["analyzer.optimize"]
    assert opt["component"] == "analyzer"
    attrs = opt["attributes"]
    assert "device_s" in attrs
    assert "engine_cache_hit" in attrs
    assert "bucket" in attrs
    # the supervised device op nests under the optimize span
    dev = by_name["device.optimize"]
    assert dev["component"] == "device"
    assert dev["attributes"]["attempts"] >= 1
    # every span of the tree shares the one trace id
    assert {s["traceId"] for s in spans} == {tid}
    # ...and the user-task record carries the same handle
    status, tasks, _ = _request(service, "GET", "user_tasks")
    assert tid in {t.get("TraceId") for t in tasks["userTasks"]}


def test_trace_index_and_unknown_id(service):
    _poll(service, "GET", "proposals")
    status, payload, _ = _request(service, "GET", "trace")
    assert status == 200
    assert payload["traces"], "recent root traces must be listed"
    names = {t["name"] for t in payload["traces"]}
    assert any(n.startswith("service.") for n in names)
    # limit is respected
    status, one, _ = _request(service, "GET", "trace", limit=1)
    assert len(one["traces"]) == 1
    # unknown id -> 404, not an empty tree
    with pytest.raises(urllib.error.HTTPError) as e:
        _request(service, "GET", "trace", id="deadbeef" * 4)
    assert e.value.code == 404


def test_tracing_disabled_service_serves_empty_surface():
    """trace.enabled=false: no spans recorded, no _traceId riders, but the
    endpoints stay well-formed (a scraper never 500s)."""
    config = _service_config(**{
        "trace.enabled": "false",
        "tpu.num.candidates": 128,
        "tpu.leadership.candidates": 32,
        "tpu.steps.per.round": 16,
        "tpu.num.rounds": 2,
    })
    app, fetcher, admin, sampler = build_simulated_service(config)
    app.start()
    try:
        status, payload = _poll(app, "GET", "proposals")
        assert status == 200
        assert "_traceId" not in payload
        status, idx, _ = _request(app, "GET", "trace")
        assert status == 200 and idx["traces"] == []
    finally:
        app.stop()
