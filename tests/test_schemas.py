"""Response-schema conformance + RS256 JWT tests.

Reference: servlet/response/ResponseTest.java:1 (every response class
declares its schema) + servlet/security/jwt/JwtAuthenticator.java:1
(certificate-based token verification).
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from cruise_control_tpu.config.app_config import CruiseControlConfig
from cruise_control_tpu.service.main import build_simulated_service
from cruise_control_tpu.service.schemas import (
    RESPONSE_SCHEMAS,
    validate_response,
)
from cruise_control_tpu.service.server import GET_ENDPOINTS, POST_ENDPOINTS


@pytest.fixture(scope="module")
def service():
    app, fetcher, admin, sampler = build_simulated_service(seed=11)
    app.start()
    yield app
    app.stop()


def _req(app, method, endpoint, headers=None, **params):
    q = "&".join(f"{k}={v}" for k, v in params.items())
    url = f"http://{app.host}:{app.port}{app.prefix}/{endpoint}" + (f"?{q}" if q else "")
    req = urllib.request.Request(url, method=method, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _poll(app, method, endpoint, **params):
    status, payload, headers = _req(app, method, endpoint, **params)
    tid = headers.get("User-Task-ID")
    deadline = time.time() + 120
    while status == 202 and time.time() < deadline:
        # 202 progress bodies conform too
        assert validate_response(endpoint, payload, status=202) == []
        time.sleep(0.3)
        status, payload, headers = _req(
            app, method, endpoint, headers={"User-Task-ID": tid}, **params
        )
    return status, payload


def test_every_endpoint_has_a_declared_schema():
    """The registry covers the full endpoint surface — adding an endpoint
    without declaring its response schema fails here (ResponseTest role)."""
    assert set(RESPONSE_SCHEMAS) == set(GET_ENDPOINTS) | set(POST_ENDPOINTS)


# (endpoint, method, params) driven against the live simulated service
CASES = [
    ("state", "GET", {}),
    ("state", "GET", {"substates": "monitor,sensors"}),
    ("kafka_cluster_state", "GET", {}),
    ("load", "GET", {}),
    ("partition_load", "GET", {"resource": "NW_IN", "entries": "5"}),
    ("proposals", "GET", {}),
    ("user_tasks", "GET", {}),
    ("review_board", "GET", {}),
    ("train", "GET", {}),
    ("rebalance", "POST", {"dryrun": "true"}),
    ("add_broker", "POST", {"brokerid": "0", "dryrun": "true"}),
    ("remove_broker", "POST", {"brokerid": "1", "dryrun": "true"}),
    ("demote_broker", "POST", {"brokerid": "0", "dryrun": "true"}),
    ("fix_offline_replicas", "POST", {"dryrun": "true"}),
    ("topic_configuration", "POST",
     {"topic": "T0", "replication_factor": "2", "dryrun": "true"}),
    ("pause_sampling", "POST", {}),
    ("resume_sampling", "POST", {}),
    ("admin", "POST", {"enable_self_healing_for": "broker_failure"}),
    ("stop_proposal_execution", "POST", {}),
    # compact JSON: the raw-URL helper does not percent-encode spaces
    ("simulate", "POST",
     {"scenarios": '[{"name":"add-one","addBrokers":[{"count":1}]}]'}),
    ("rightsize", "GET", {}),
    ("trace", "GET", {}),
    ("fleet", "GET", {}),
]
# /metrics is absent from CASES on purpose: its body is Prometheus TEXT,
# validated by the exposition lint gate (scripts/check.sh +
# tests/test_trace.py), not by the JSON schema walker.


@pytest.mark.parametrize("endpoint,method,params", CASES,
                         ids=[f"{m} {e} {p}" for e, m, p in CASES])
def test_live_response_conforms_to_declared_schema(service, endpoint, method, params):
    status, payload = _poll(service, method, endpoint, **params)
    assert status == 200, payload
    problems = validate_response(endpoint, payload, status=status)
    assert problems == [], problems


def test_error_response_schema(service):
    status, payload, _ = _req(service, "GET", "partition_load", resource="BOGUS")
    assert status == 400
    assert validate_response("partition_load", payload, status=status) == []


def test_schema_validator_catches_drift():
    ok = {"message": "sampling resumed"}
    assert validate_response("resume_sampling", ok) == []
    assert validate_response("resume_sampling", {}) != []  # missing field
    assert validate_response("resume_sampling", {"message": 3}) != []  # wrong type
    assert validate_response(
        "resume_sampling", {"message": "x", "surprise": 1}
    ) != []  # undeclared field


# ---------------------------------------------------------------- RS256


def _rsa_keypair(tmp_path):
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pub_pem = key.public_key().public_bytes(
        serialization.Encoding.PEM, serialization.PublicFormat.SubjectPublicKeyInfo
    )
    pub_path = tmp_path / "jwt_pub.pem"
    pub_path.write_bytes(pub_pem)
    return key, str(pub_path)


def _rs256_token(private_key, claims):
    import base64

    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding

    def b64(b):
        return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

    header = b64(json.dumps({"alg": "RS256", "typ": "JWT"}).encode())
    payload = b64(json.dumps(claims).encode())
    sig = private_key.sign(
        f"{header}.{payload}".encode(), padding.PKCS1v15(), hashes.SHA256()
    )
    return f"{header}.{payload}.{b64(sig)}"


def test_rs256_jwt_provider_end_to_end(tmp_path):
    """Service accepts only tokens signed by the certificate's private key
    (reference JwtAuthenticator/JwtLoginService)."""
    key, pub_path = _rsa_keypair(tmp_path)
    config = CruiseControlConfig({
        "webserver.security.enable": "true",
        "jwt.authentication.certificate.location": pub_path,
    })
    app, *_ = build_simulated_service(config, seed=12)
    app.start()
    try:
        good = _rs256_token(
            key, {"sub": "ops", "role": "ADMIN", "exp": time.time() + 600}
        )
        status, payload, _ = _req(
            app, "GET", "state", headers={"Authorization": f"Bearer {good}"}
        )
        assert status == 200

        # no token -> 401
        status, _, _ = _req(app, "GET", "state")
        assert status == 401

        # token signed by a DIFFERENT key -> 401
        from cryptography.hazmat.primitives.asymmetric import rsa

        other = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        forged = _rs256_token(
            other, {"sub": "evil", "role": "ADMIN", "exp": time.time() + 600}
        )
        status, _, _ = _req(
            app, "GET", "state", headers={"Authorization": f"Bearer {forged}"}
        )
        assert status == 401

        # expired token -> 401
        expired = _rs256_token(
            key, {"sub": "ops", "role": "ADMIN", "exp": time.time() - 10}
        )
        status, _, _ = _req(
            app, "GET", "state", headers={"Authorization": f"Bearer {expired}"}
        )
        assert status == 401

        # VIEWER role cannot POST
        viewer = _rs256_token(
            key, {"sub": "ro", "role": "VIEWER", "exp": time.time() + 600}
        )
        status, _, _ = _req(
            app, "POST", "pause_sampling",
            headers={"Authorization": f"Bearer {viewer}"},
        )
        assert status == 403
    finally:
        app.stop()
