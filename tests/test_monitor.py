"""Monitor-layer tests.

Mirrors the reference core test strategy (SURVEY §4.1): aggregator
semantics (window rolling, extrapolation, completeness) on synthetic
entities (reference MetricSampleAggregatorTest / RawMetricValuesTest), plus
end-to-end LoadMonitor -> ClusterState -> optimizer integration
(reference LoadMonitorTest with mocks; here a synthetic sampler).
"""

import json

import numpy as np
import pytest

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.models.state import validate
from cruise_control_tpu.monitor import (
    AggregationOptions,
    Extrapolation,
    FileCapacityResolver,
    FixedCapacityResolver,
    KAFKA_METRIC_DEF,
    LoadMonitor,
    MetricFetcherManager,
    ModelCompletenessRequirements,
    NotEnoughValidWindowsError,
    PartitionEntity,
    StaticMetadataProvider,
    WindowedMetricSampleAggregator,
)
from cruise_control_tpu.monitor.cpu_model import (
    LinearRegressionModelParameters,
    follower_cpu_util,
)
from cruise_control_tpu.monitor.sampling import InMemorySampleStore
from cruise_control_tpu.testing.synthetic import (
    SyntheticWorkloadSampler,
    WorkloadSpec,
    synthetic_topology,
)

WINDOW_MS = 1000
M = KAFKA_METRIC_DEF.num_metrics
CPU = KAFKA_METRIC_DEF.metric_id("CPU_USAGE")
DISK = KAFKA_METRIC_DEF.metric_id("DISK_USAGE")


def agg_factory(num_windows=4, min_samples=2):
    return WindowedMetricSampleAggregator(
        num_windows=num_windows,
        window_ms=WINDOW_MS,
        min_samples_per_window=min_samples,
        metric_def=KAFKA_METRIC_DEF,
    )


def sample(v_cpu, v_disk=0.0):
    v = np.zeros(M, np.float32)
    v[CPU] = v_cpu
    v[DISK] = v_disk
    return v


def test_avg_and_latest_strategies():
    agg = agg_factory()
    e = PartitionEntity(0, 0)
    # window 0: two samples; CPU averages, DISK takes latest by time
    agg.add_sample(e, 100, sample(10.0, 100.0))
    agg.add_sample(e, 900, sample(20.0, 140.0))
    agg.add_sample(e, 1100, sample(0.0))  # opens window 1 -> window 0 completed
    res = agg.aggregate()
    w0 = np.where(res.window_indices == 0)[0][0]
    assert res.values[0, w0, CPU] == pytest.approx(15.0)
    assert res.values[0, w0, DISK] == pytest.approx(140.0)
    assert res.extrapolation[0, w0] == Extrapolation.NONE


def test_extrapolation_ladder():
    agg = agg_factory(num_windows=6, min_samples=4)
    e = PartitionEntity(0, 0)
    # w0: 4 samples (NONE); w1: 2 (AVG_AVAILABLE >= half); w2: 1 (FORCED);
    # w3: 0 with invalid neighbors (NO_VALID); w5 current
    for i in range(4):
        agg.add_sample(e, i * 10, sample(8.0))
    for i in range(2):
        agg.add_sample(e, 1000 + i * 10, sample(6.0))
    agg.add_sample(e, 2000, sample(4.0))
    agg.add_sample(e, 5500, sample(1.0))  # current window = 5
    res = agg.aggregate()
    by_w = {int(w): i for i, w in enumerate(res.window_indices)}
    ext = res.extrapolation[0]
    assert ext[by_w[0]] == Extrapolation.NONE
    assert ext[by_w[1]] == Extrapolation.AVG_AVAILABLE
    assert ext[by_w[2]] == Extrapolation.FORCED_INSUFFICIENT
    assert ext[by_w[3]] == Extrapolation.NO_VALID_EXTRAPOLATION
    assert not res.window_valid[0, by_w[3]]


def test_avg_adjacent_extrapolation():
    agg = agg_factory(num_windows=4, min_samples=1)
    e = PartitionEntity(0, 0)
    agg.add_sample(e, 100, sample(10.0))  # w0 full
    # w1 empty
    agg.add_sample(e, 2100, sample(30.0))  # w2 full
    agg.add_sample(e, 3100, sample(0.0))  # opens w3 (current)
    res = agg.aggregate()
    by_w = {int(w): i for i, w in enumerate(res.window_indices)}
    assert res.extrapolation[0, by_w[1]] == Extrapolation.AVG_ADJACENT
    assert res.values[0, by_w[1], CPU] == pytest.approx(20.0)


def test_window_rolling_evicts_old():
    agg = agg_factory(num_windows=2, min_samples=1)
    e = PartitionEntity(0, 0)
    agg.add_sample(e, 100, sample(1.0))
    agg.add_sample(e, 5100, sample(5.0))  # jump to w5; w0 rolled out
    assert not agg.add_sample(e, 200, sample(9.9))  # too old now
    res = agg.aggregate()
    assert set(int(w) for w in res.window_indices) == {3, 4}


def test_completeness_ratios():
    agg = agg_factory(num_windows=2, min_samples=1)
    e0, e1 = PartitionEntity(0, 0), PartitionEntity(0, 1)
    agg.add_sample(e0, 100, sample(1.0), group=0)
    agg.add_sample(e1, 150, sample(1.0), group=0)
    agg.add_sample(e0, 1100, sample(1.0), group=0)  # e1 misses window 1
    agg.add_sample(e0, 2100, sample(1.0), group=0)  # current w2
    res = agg.aggregate(AggregationOptions(min_valid_entity_ratio=1.0))
    # window 0 has both entities, window 1 only e0
    by_w = {int(w): i for i, w in enumerate(res.window_indices)}
    assert res.completeness.valid_entity_ratio_by_window[by_w[0]] == pytest.approx(1.0)
    assert res.completeness.valid_entity_ratio_by_window[by_w[1]] == pytest.approx(0.5)
    assert list(res.completeness.valid_windows) == [0]
    # ENTITY_GROUP granularity: e1 invalid -> whole topic group invalid
    res2 = agg.aggregate(
        AggregationOptions(min_valid_entity_ratio=0.4, granularity="ENTITY_GROUP")
    )
    assert res2.completeness.valid_entity_group_ratio == 0.0


def test_follower_cpu_model():
    # followers only pay the bytes-in share of leader CPU
    assert follower_cpu_util(100.0, 0.0, 10.0) == pytest.approx(
        10.0 * 0.15 * 100.0 / (0.7 * 100.0)
    )
    assert follower_cpu_util(0.0, 0.0, 10.0) == 0.0

    lr = LinearRegressionModelParameters(
        min_samples_to_train=10,
        # relax the bucket-coverage gate: this fixture's synthetic loads
        # land in few CPU-util buckets (gate itself tested separately)
        required_samples_per_bucket=1,
        min_num_cpu_util_buckets=1,
    )
    rng = np.random.default_rng(0)
    true_w = np.array([0.002, 0.001, 0.0005])
    for _ in range(50):
        x = rng.uniform(0, 1000, 3)
        lr.add_sample(*x, cpu_util=float(true_w @ x))
    assert lr.train()
    est = lr.estimate(100.0, 100.0, 100.0)
    assert est == pytest.approx(float(true_w.sum() * 100.0), rel=1e-3)


def test_file_capacity_resolver_jbod(tmp_path):
    doc = {
        "brokerCapacities": [
            {
                "brokerId": "-1",
                "capacity": {"DISK": "100000", "CPU": "100", "NW_IN": "10000", "NW_OUT": "10000"},
            },
            {
                "brokerId": "0",
                "capacity": {
                    "DISK": {"/d1": "250000", "/d2": "250000"},
                    "CPU": "100",
                    "NW_IN": "50000",
                    "NW_OUT": "50000",
                },
            },
        ]
    }
    p = tmp_path / "capacity.json"
    p.write_text(json.dumps(doc))
    r = FileCapacityResolver(str(p))
    b0 = r.capacity_for_broker("r0", "h0", 0)
    assert b0.is_jbod and b0.capacity[Resource.DISK] == 500000
    b9 = r.capacity_for_broker("r0", "h0", 9)  # falls back to default
    assert b9.capacity[Resource.DISK] == 100000


@pytest.fixture()
def monitored_cluster():
    topo = synthetic_topology(num_brokers=6, topics={"T0": 12, "T1": 12}, seed=2)
    sampler = SyntheticWorkloadSampler(topo, WorkloadSpec(), seed=2)
    agg = agg_factory(num_windows=3, min_samples=1)
    store = InMemorySampleStore()
    fetcher = MetricFetcherManager(sampler, agg, agg_factory(), sample_store=store)
    parts = sampler.all_partition_entities()
    for w in range(4):  # 3 completed windows + current
        fetcher.fetch_once(parts, w * WINDOW_MS, (w + 1) * WINDOW_MS - 1)
    monitor = LoadMonitor(
        StaticMetadataProvider(topo), FixedCapacityResolver([100.0, 1e5, 1e5, 1e6]), agg
    )
    return topo, sampler, monitor, store


def test_load_monitor_builds_valid_state(monitored_cluster):
    topo, sampler, monitor, _ = monitored_cluster
    req = ModelCompletenessRequirements(min_required_num_windows=2)
    assert monitor.meets_completeness_requirements(req)
    state = monitor.cluster_model(req)
    assert validate(state) == []
    assert state.shape.B == 6
    assert int(np.asarray(state.replica_valid).sum()) == topo.num_replicas
    # loads reflect the sampler's base rates (non-zero CPU on every leader)
    leads = np.asarray(state.replica_is_leader) & np.asarray(state.replica_valid)
    assert (np.asarray(state.replica_load_leader)[leads][:, Resource.CPU] > 0).all()


def test_load_monitor_rejects_insufficient_windows(monitored_cluster):
    _, _, monitor, _ = monitored_cluster
    with pytest.raises(NotEnoughValidWindowsError):
        monitor.cluster_model(ModelCompletenessRequirements(min_required_num_windows=50))


def test_sample_store_warm_restart(monitored_cluster):
    topo, sampler, _, store = monitored_cluster
    fresh_agg = agg_factory(num_windows=3, min_samples=1)
    fetcher = MetricFetcherManager(sampler, fresh_agg, agg_factory(), sample_store=store)
    n = fetcher.load_samples()
    assert n > 0
    monitor = LoadMonitor(
        StaticMetadataProvider(topo), FixedCapacityResolver([100.0, 1e5, 1e5, 1e6]), fresh_agg
    )
    state = monitor.cluster_model(ModelCompletenessRequirements(min_required_num_windows=2))
    assert validate(state) == []


def test_monitor_to_optimizer_end_to_end(monitored_cluster):
    """The full monitor -> analyzer slice (SURVEY §3.3 without the servlet)."""
    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig

    _, _, monitor, _ = monitored_cluster
    state = monitor.cluster_model(ModelCompletenessRequirements(min_required_num_windows=2))
    cfg = OptimizerConfig(
        num_candidates=128, leadership_candidates=32, steps_per_round=16, num_rounds=2
    )
    res = GoalOptimizer(config=cfg).optimize(state)
    assert res.objective_after <= res.objective_before


# ------------------------------------------------- parallel sampling


def test_partition_assignor_disjoint_and_balanced():
    """MetricSamplerPartitionAssignor splits the partition universe into
    disjoint, balanced per-fetcher sets (reference
    monitor/sampling/MetricSamplerPartitionAssignor.java:1)."""
    from cruise_control_tpu.monitor.sampling import (
        MetricSamplerPartitionAssignor,
        PartitionEntity,
    )

    parts = [
        PartitionEntity(t, p)
        for t, n in enumerate([40, 17, 9, 5, 3, 1])
        for p in range(n)
    ]
    sets = MetricSamplerPartitionAssignor().assign(parts, 4)
    assert len(sets) == 4
    seen = [pp for s in sets for pp in s]
    assert len(seen) == len(parts) and len(set(seen)) == len(parts)
    sizes = sorted(len(s) for s in sets)
    assert sizes[-1] - sizes[0] <= 1  # balanced within one partition
    # single fetcher: everything in one set
    assert MetricSamplerPartitionAssignor().assign(parts, 1) == [parts]


def test_multi_fetcher_sampling_parallel_and_observed():
    """N fetchers sample DISJOINT partition sets whose union covers the
    round; fetch timers/failure counters and monitor health gauges land in
    the sensor registry (reference MetricFetcherManager.java:35-56,
    Sensors.md monitored-partitions-percentage)."""
    import threading

    from cruise_control_tpu.common.sensors import SensorRegistry
    from cruise_control_tpu.monitor.sampling import (
        MetricSample,
        MetricFetcherManager,
        PartitionEntity,
        SamplingResult,
    )

    calls: list[list] = []
    lock = threading.Lock()

    class RecordingSampler:
        def get_samples(self, assigned, start_ms, end_ms):
            with lock:
                calls.append(list(assigned))
            return SamplingResult(
                [
                    MetricSample(p, end_ms, np.ones(4, np.float32))
                    for p in assigned
                ],
                [],
            )

    class NullAgg:
        def add_sample(self, *a, **k):
            return True

    sensors = SensorRegistry()
    parts = [PartitionEntity(t, p) for t in range(8) for p in range(10)]
    mgr = MetricFetcherManager(
        RecordingSampler(), NullAgg(), None, num_fetchers=4, sensors=sensors
    )
    n = mgr.fetch_once(parts, 0, 1000)
    assert n == len(parts)
    assert len(calls) == 4
    seen = [p for c in calls for p in c]
    assert len(seen) == len(parts) and len(set(seen)) == len(parts)
    snap = sensors.snapshot()
    assert snap["monitor.metric-fetch"]["count"] == 4
    assert snap["monitor.monitored-partitions-percentage"]["value"] == 100.0
    assert snap["monitor.num-partitions-with-flaw"]["value"] == 0


def test_multi_fetcher_partial_failure_and_flaw_gauges():
    """One failing fetcher must not sink the round: the other fetchers'
    samples are absorbed, the failure is counted, and the missing
    partitions show up in monitored-percentage / partitions-with-flaw."""
    from cruise_control_tpu.common.sensors import SensorRegistry
    from cruise_control_tpu.monitor.sampling import (
        MetricSample,
        MetricFetcherManager,
        PartitionEntity,
        SamplingResult,
    )

    class FlakySampler:
        def get_samples(self, assigned, start_ms, end_ms):
            # exactly one fetcher's disjoint set contains (topic 0, part 0)
            if any(p.topic == 0 and p.partition == 0 for p in assigned):
                raise RuntimeError("broker unreachable")
            return SamplingResult(
                [MetricSample(p, end_ms, np.ones(4, np.float32)) for p in assigned],
                [],
            )

    class NullAgg:
        def add_sample(self, *a, **k):
            return True

    sensors = SensorRegistry()
    parts = [PartitionEntity(t, p) for t in range(4) for p in range(10)]
    mgr = MetricFetcherManager(
        FlakySampler(), NullAgg(), None, num_fetchers=4, sensors=sensors
    )
    n = mgr.fetch_once(parts, 0, 1000)
    assert 0 < n < len(parts)
    assert mgr.failed_fetches == 1
    snap = sensors.snapshot()
    assert snap["monitor.metric-fetch-failures"]["count"] == 1
    assert snap["monitor.monitored-partitions-percentage"]["value"] == 75.0
    assert snap["monitor.num-partitions-with-flaw"]["value"] == 10


def test_columnar_sample_add_matches_per_sample():
    """add_samples_columnar is bitwise-equivalent to repeated add_sample
    for every strategy (AVG accumulate, MAX running max, LATEST newest),
    including duplicate entities within one batch."""
    import numpy as np

    from cruise_control_tpu.monitor.aggregator import WindowedMetricSampleAggregator
    from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF
    from cruise_control_tpu.monitor.sampling import PartitionEntity

    rng = np.random.default_rng(3)
    M = KAFKA_METRIC_DEF.num_metrics
    ents = [PartitionEntity(0, i) for i in range(40)] + [PartitionEntity(0, 7)]
    a = WindowedMetricSampleAggregator(3, 1000, 1, KAFKA_METRIC_DEF)
    b = WindowedMetricSampleAggregator(3, 1000, 1, KAFKA_METRIC_DEF)
    for w in range(4):
        vals = rng.uniform(-5, 50, (len(ents), M)).astype(np.float32)
        t = w * 1000 + 123
        assert a.add_samples_columnar(ents, t, vals)
        for e, v in zip(ents, vals):
            b.add_sample(e, t, v)
    ra, rb = a.aggregate(), b.aggregate()
    # row assignment order matches (same first-seen entity order)
    assert a.entity_index() == b.entity_index()
    np.testing.assert_array_equal(ra.values, rb.values)
    np.testing.assert_array_equal(ra.window_valid, rb.window_valid)
    np.testing.assert_array_equal(ra.entity_valid, rb.entity_valid)


def test_cluster_model_columnar_path_at_modest_scale():
    """cluster_model over a purely columnar pipeline: bulk samples ->
    aggregate -> vectorized join -> build_state_columnar; sanity-checks
    totals against the raw loads."""
    import numpy as np

    from cruise_control_tpu.monitor import (
        FixedCapacityResolver,
        LoadMonitor,
        ModelCompletenessRequirements,
        WindowedMetricSampleAggregator,
        KAFKA_METRIC_DEF,
    )
    from cruise_control_tpu.monitor.sampling import PartitionEntity
    from cruise_control_tpu.monitor.topology import StaticMetadataProvider
    from cruise_control_tpu.testing.synthetic import synthetic_topology

    topo = synthetic_topology(num_brokers=12, topics={"a": 40, "b": 60}, seed=2)
    cols = topo.columns()
    ents = [
        PartitionEntity(int(t), int(p))
        for t, p in zip(cols.part_topic, cols.part_num)
    ]
    agg = WindowedMetricSampleAggregator(3, 1000, 1, KAFKA_METRIC_DEF)
    rng = np.random.default_rng(0)
    M = KAFKA_METRIC_DEF.num_metrics
    for w in range(4):
        agg.add_samples_columnar(
            ents, w * 1000 + 5, rng.uniform(1, 10, (len(ents), M)).astype(np.float32)
        )
    monitor = LoadMonitor(
        StaticMetadataProvider(topo), FixedCapacityResolver([100.0, 1e5, 1e5, 1e6]), agg
    )
    state = monitor.cluster_model(ModelCompletenessRequirements(min_required_num_windows=2))
    assert state.shape.P == 100
    from cruise_control_tpu.models import validate

    assert validate(state) == []
    # every monitored partition got a nonzero leader load
    lead = np.asarray(state.replica_load_leader)[
        np.asarray(state.replica_is_leader) & np.asarray(state.replica_valid)
    ]
    assert (lead.sum(1) > 0).all()
    # catalog round-trips partition names
    assert monitor.last_catalog.partition_key(0)[0] in ("a", "b")
