"""Sensor registry tests (reference docs/wiki/User Guide/Sensors.md parity)."""

import time

from cruise_control_tpu.common.sensors import (
    Counter,
    Gauge,
    Meter,
    SensorRegistry,
    Timer,
)


def test_counter_and_gauge():
    reg = SensorRegistry()
    reg.counter("x").inc()
    reg.counter("x").inc(2)
    assert reg.counter("x").count == 3
    reg.gauge("g").set(1.5)
    assert reg.gauge("g").value == 1.5
    reg.gauge("cb", fn=lambda: 7.0)
    snap = reg.snapshot()
    assert snap["x"] == {"type": "counter", "count": 3}
    assert snap["cb"]["value"] == 7.0


def test_timer_statistics():
    t = Timer()
    for ms in (10, 20, 30):
        t.update(ms / 1e3)
    snap = t.snapshot()
    assert snap["count"] == 3
    assert abs(snap["meanMs"] - 20.0) < 1e-6
    assert snap["minMs"] <= snap["p50Ms"] <= snap["maxMs"]
    with t.time():
        time.sleep(0.01)
    assert t.count == 4


def test_meter_mtba():
    clock = iter([0.0, 1.0, 3.0, 10.0])
    m = Meter(clock=lambda: next(clock))
    assert m.mean_time_between_ms() == float("inf")
    m.mark()  # t=0
    m.mark()  # t=1
    m.mark()  # t=3
    # mean time between 3 events spanning 3s = 1500ms
    assert abs(m.mean_time_between_ms() - 1500.0) < 1e-6
    snap = m.snapshot()
    assert snap["count"] == 3


def test_headline_sensors_reach_state_endpoint():
    """facade.state() must expose the (per-instance) sensor catalog under
    /state; a second service instance must not see the first's counters."""
    from cruise_control_tpu.service.main import build_simulated_service

    app, fetcher, admin, sampler = build_simulated_service(seed=5)
    app2, *_ = build_simulated_service(seed=6)
    try:
        app.cc.sensors.timer("analyzer.proposal-computation-timer").update(0.5)
        out = app.cc.state()
        assert "Sensors" in out
        sensors = out["Sensors"]
        assert sensors["analyzer.proposal-computation-timer"]["count"] == 1
        assert "anomaly-detector.self-healing-enabled-ratio" in sensors
        # isolation: instance 2 never computed a proposal
        s2 = app2.cc.state()["Sensors"]
        assert "analyzer.proposal-computation-timer" not in s2
    finally:
        app.stop()
        app2.stop()
