"""Sensor registry tests (reference docs/wiki/User Guide/Sensors.md parity)."""

import math
import time

import pytest

from cruise_control_tpu.common.sensors import (
    Counter,
    Gauge,
    Histogram,
    Meter,
    SensorRegistry,
    Timer,
)


def test_counter_and_gauge():
    reg = SensorRegistry()
    reg.counter("x").inc()
    reg.counter("x").inc(2)
    assert reg.counter("x").count == 3
    reg.gauge("g").set(1.5)
    assert reg.gauge("g").value == 1.5
    reg.gauge("cb", fn=lambda: 7.0)
    snap = reg.snapshot()
    assert snap["x"] == {"type": "counter", "count": 3}
    assert snap["cb"]["value"] == 7.0


def test_timer_statistics():
    t = Timer()
    for ms in (10, 20, 30):
        t.update(ms / 1e3)
    snap = t.snapshot()
    assert snap["count"] == 3
    assert abs(snap["meanMs"] - 20.0) < 1e-6
    assert snap["minMs"] <= snap["p50Ms"] <= snap["maxMs"]
    with t.time():
        time.sleep(0.01)
    assert t.count == 4


def test_meter_mtba():
    clock = iter([0.0, 1.0, 3.0, 10.0])
    m = Meter(clock=lambda: next(clock))
    assert m.mean_time_between_ms() == float("inf")
    m.mark()  # t=0
    m.mark()  # t=1
    m.mark()  # t=3
    # mean time between 3 events spanning 3s = 1500ms
    assert abs(m.mean_time_between_ms() - 1500.0) < 1e-6
    snap = m.snapshot()
    assert snap["count"] == 3


def test_histogram_quantile_interpolates():
    h = Histogram(buckets=(0.1, 1.0, 10.0))
    assert math.isnan(h.quantile(0.5))
    for v in (0.05, 0.3, 0.6, 2.0):
        h.observe(v)
    # rank 2 of 4 falls in the (0.1, 1.0] bucket: linear interpolation
    assert 0.1 < h.quantile(0.5) <= 1.0
    # the +Inf bucket answers its floor, never infinity
    h.observe(100.0)
    assert h.quantile(1.0) == 10.0
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_histogram_exemplars_latest_per_bucket():
    h = Histogram(buckets=(1.0, 10.0))
    h.observe(0.5, exemplar={"trace_id": "t1"})
    h.observe(0.7, exemplar={"trace_id": "t2"})  # same bucket: replaces
    h.observe(5.0)  # no exemplar: bucket stays empty
    ex = h.exemplars()
    assert len(ex) == 1
    bound, value, labels, ts = ex[0]
    assert bound == 1.0 and value == 0.7 and labels == {"trace_id": "t2"}
    assert ts > 0


def test_exposition_exemplars_openmetrics_only():
    from cruise_control_tpu.common.exposition import (
        ExpositionError,
        parse_exposition,
        prometheus_text,
    )

    reg = SensorRegistry()
    reg.histogram("controller.window-roll-to-publish-seconds",
                  buckets=(1.0,)).observe(0.5, exemplar={"trace_id": "abc"})
    plain = prometheus_text(reg)
    assert " # " not in plain, "0.0.4 output must never carry exemplars"
    parse_exposition(plain)
    om = prometheus_text(reg, openmetrics=True)
    assert '# {trace_id="abc"} 0.5' in om
    assert om.rstrip().endswith("# EOF")
    fams = parse_exposition(om)
    assert "cruisecontrol_controller_window_roll_to_publish_seconds" in fams
    # lint: an exemplar on a non-bucket/counter sample is rejected
    bad = (
        "# TYPE g gauge\n"
        'g 1 # {trace_id="x"} 1\n'
    )
    with pytest.raises(ExpositionError, match="exemplar"):
        parse_exposition(bad)


def test_registry_get_never_creates():
    reg = SensorRegistry()
    assert reg.get("controller.window-roll-to-publish-seconds") is None
    h = reg.histogram("h", buckets=(1.0,))
    assert reg.get("h") is h


def test_headline_sensors_reach_state_endpoint():
    """facade.state() must expose the (per-instance) sensor catalog under
    /state; a second service instance must not see the first's counters."""
    from cruise_control_tpu.service.main import build_simulated_service

    app, fetcher, admin, sampler = build_simulated_service(seed=5)
    app2, *_ = build_simulated_service(seed=6)
    try:
        app.cc.sensors.timer("analyzer.proposal-computation-timer").update(0.5)
        out = app.cc.state()
        assert "Sensors" in out
        sensors = out["Sensors"]
        assert sensors["analyzer.proposal-computation-timer"]["count"] == 1
        assert "anomaly-detector.self-healing-enabled-ratio" in sensors
        # isolation: instance 2 never computed a proposal
        s2 = app2.cc.state()["Sensors"]
        assert "analyzer.proposal-computation-timer" not in s2
    finally:
        app.stop()
        app2.stop()
