"""Crash-safe execution: durable journal, restart reconciliation,
stuck-move reaper, load-aware adaptive concurrency.

The matrix kills the executor "process" (testing/faults.process_crash: the
progress loop raises and the dying process's cleanup calls never reach the
cluster) at different execution phases, truncates the journal at arbitrary
byte offsets, and asserts a fresh Executor over the same journal
reconciles against the simulated cluster and resumes to completion —
zero duplicate submissions, zero leaked throttles, reservations intact.
Reference analog: executor/Executor.java persisted-state recovery.
"""

import json
import os

import pytest

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.detector import AnomalyDetector, AnomalyType, SelfHealingNotifier
from cruise_control_tpu.detector.anomalies import ExecutionStuck
from cruise_control_tpu.executor import (
    ConcurrencyAdjuster,
    ExecutionJournal,
    ExecutionOptions,
    Executor,
    ExecutorState,
    OngoingExecutionError,
    SimulatedClusterAdmin,
    TaskState,
    TaskType,
)
from cruise_control_tpu.monitor.topology import (
    BrokerNode,
    ClusterTopology,
    PartitionInfo,
    StaticMetadataProvider,
)
from cruise_control_tpu.testing import faults


def proposal(topic, part, old, new, old_leader=None, new_leader=None, data=100.0,
             disk_moves=(), intra_data=0.0):
    return ExecutionProposal(
        partition=part,
        topic=topic,
        old_leader=old[0] if old_leader is None else old_leader,
        new_leader=new[0] if new_leader is None else new_leader,
        old_replicas=tuple(old),
        new_replicas=tuple(new),
        disk_moves=tuple(disk_moves),
        inter_broker_data_to_move=data,
        intra_broker_data_to_move=intra_data,
    )


def make_cluster(num_partitions=4, link_rate=1000.0, intra_move_bytes=0.0):
    parts = [
        PartitionInfo("T0", i, leader=0, replicas=(0, 1))
        for i in range(num_partitions)
    ]
    brokers = tuple(BrokerNode(i, rack=f"r{i % 2}", host=f"h{i}") for i in range(4))
    meta = StaticMetadataProvider(ClusterTopology(brokers=brokers, partitions=tuple(parts)))
    return SimulatedClusterAdmin(
        meta, link_rate_bytes_per_s=link_rate, intra_move_bytes=intra_move_bytes
    )


def journal_at(tmp_path, name="journal.jsonl"):
    return ExecutionJournal(str(tmp_path / name))


def spy_submissions(admin):
    """Count reassignment submissions per partition key across processes."""
    counts: dict = {}
    orig = admin.reassign_partitions

    def wrapper(specs):
        for s in specs:
            counts[(s.topic, s.partition)] = counts.get((s.topic, s.partition), 0) + 1
        return orig(specs)

    admin.reassign_partitions = wrapper
    return counts


# ---------------------------------------------------------------- journal


def test_journal_replay_tolerates_torn_tail(tmp_path):
    j = journal_at(tmp_path)
    j.start_execution({"uuid": "u1", "ms": 0, "tasks": [], "options": {}})
    j.append({"t": "task", "id": 0, "state": "IN_PROGRESS", "ms": 1})
    j.flush()
    with open(j.path, "a") as f:
        f.write('{"t": "task", "id": 0, "sta')  # torn mid-record
    records = ExecutionJournal(j.path).replay()
    assert [r["t"] for r in records] == ["start", "task"]


def test_finished_execution_is_not_recovered(tmp_path):
    admin = make_cluster()
    ex = Executor(admin, topic_names={0: "T0"}, journal=journal_at(tmp_path))
    props = [proposal(0, 0, [0, 1], [2, 1], data=500.0)]
    res = ex.execute_proposals(props, ExecutionOptions(progress_check_interval_s=1.0))
    assert res.completed == len(ex.tracker.tasks())
    ex2 = Executor(admin, journal=journal_at(tmp_path))
    assert ex2.state == ExecutorState.NO_TASK_IN_PROGRESS
    assert ex2.recovery_info() is None
    assert not ex2.has_recovered_execution


# ------------------------------------------------- crash/restart matrix


def test_crash_mid_inter_broker_move_recovers(tmp_path):
    admin = make_cluster()
    ex = Executor(admin, topic_names={0: "T0"}, journal=journal_at(tmp_path))
    props = [proposal(0, i, [0, 1], [2, 1], data=3000.0) for i in range(4)]
    with faults.process_crash(admin, schedule=faults.FaultSchedule(calls=[4])):
        with pytest.raises(faults.SimulatedProcessCrash):
            ex.execute_proposals(props, ExecutionOptions(
                concurrent_partition_movements_per_broker=2,
                progress_check_interval_s=1.0,
                replication_throttle_bytes_per_s=5000.0,
            ))
    # the dead process left its throttle on the brokers + moves in flight
    assert admin.throttle_rate == 5000.0
    assert admin.in_progress_reassignments()

    counts = spy_submissions(admin)
    ex2 = Executor(admin, journal=journal_at(tmp_path))
    assert ex2.state == ExecutorState.RECOVERING
    assert ex2.has_recovered_execution
    # startup sweep: the orphaned throttle is gone before anything resumes
    assert admin.throttle_rate is None
    info = ex2.recovery_info()
    assert info["sweptThrottle"] is True
    assert info["tasksReadopted"] >= 1

    res = ex2.resume_recovered_execution()
    assert res is not None and res.dead == 0
    assert res.completed == len(ex2.tracker.tasks())
    # re-adopted moves were NOT resubmitted: every submission in the second
    # process is for a task the first one never put on the wire
    assert all(n == 1 for n in counts.values())
    by_key = {(p.topic, p.partition): set(p.replicas)
              for p in admin.topology().partitions}
    assert all(by_key[("T0", i)] == {1, 2} for i in range(4))
    assert admin.throttle_rate is None  # resume cleared its own throttle
    assert ex2.state == ExecutorState.NO_TASK_IN_PROGRESS
    # a second restart finds a cleanly finished journal
    ex3 = Executor(admin, journal=journal_at(tmp_path))
    assert ex3.recovery_info() is None


def test_crash_mid_leadership_recovers(tmp_path):
    admin = make_cluster()
    ex = Executor(admin, topic_names={0: "T0"}, journal=journal_at(tmp_path))
    # leadership-only moves: phase 2 territory
    props = [proposal(0, i, [0, 1], [0, 1], old_leader=0, new_leader=1)
             for i in range(3)]
    with faults.process_crash(admin, on="elect_leaders"):
        with pytest.raises(faults.SimulatedProcessCrash):
            ex.execute_proposals(props, ExecutionOptions(progress_check_interval_s=1.0))

    ex2 = Executor(admin, journal=journal_at(tmp_path))
    assert ex2.state == ExecutorState.RECOVERING
    res = ex2.resume_recovered_execution()
    assert res.completed == 3 and res.dead == 0
    leaders = {(p.topic, p.partition): p.leader for p in admin.topology().partitions}
    assert all(leaders[("T0", i)] == 1 for i in range(3))


def test_crash_mid_intra_broker_logdir_move_recovers(tmp_path):
    admin = make_cluster(intra_move_bytes=3000.0)
    ex = Executor(admin, topic_names={0: "T0"}, journal=journal_at(tmp_path))
    props = [proposal(0, i, [0, 1], [0, 1], data=0.0,
                      disk_moves=((0, 0, 1),), intra_data=3000.0)
             for i in range(2)]
    with faults.process_crash(admin, schedule=faults.FaultSchedule(calls=[1])):
        with pytest.raises(faults.SimulatedProcessCrash):
            ex.execute_proposals(props, ExecutionOptions(
                progress_check_interval_s=1.0,
                concurrent_intra_broker_partition_movements=2,
            ))
    assert admin.in_progress_logdir_moves()  # copies still draining

    ex2 = Executor(admin, journal=journal_at(tmp_path))
    assert ex2.state == ExecutorState.RECOVERING
    info = ex2.recovery_info()
    assert info["tasksReadopted"] >= 1
    res = ex2.resume_recovered_execution()
    assert res.dead == 0
    assert res.completed == len(ex2.tracker.tasks())
    done = ex2.tracker.tasks(
        task_type=TaskType.INTRA_BROKER_REPLICA_ACTION, state=TaskState.COMPLETED
    )
    assert len(done) == 2
    assert not admin.in_progress_logdir_moves()


def test_truncated_journal_replay_recovers(tmp_path):
    """Journal truncated at an arbitrary byte (fsync racing the crash):
    replay trusts the intact prefix; tasks whose completion record was
    lost re-reconcile against the topology instead of re-executing."""
    admin = make_cluster()
    ex = Executor(admin, topic_names={0: "T0"}, journal=journal_at(tmp_path))
    props = [proposal(0, i, [0, 1], [2, 1], data=2000.0) for i in range(4)]
    with faults.process_crash(admin, schedule=faults.FaultSchedule(calls=[3])):
        with pytest.raises(faults.SimulatedProcessCrash):
            ex.execute_proposals(props, ExecutionOptions(
                concurrent_partition_movements_per_broker=2,
                progress_check_interval_s=1.0,
            ))
    path = str(tmp_path / "journal.jsonl")
    # cut mid-way into the record stream, torn final line included — but
    # keep the start record (without it there is nothing to recover)
    with open(path, "rb") as f:
        start_len = len(f.readline())
    size = os.path.getsize(path)
    faults.truncate_file(path, keep_bytes=max(start_len, size - (size - start_len) // 2))

    counts = spy_submissions(admin)
    ex2 = Executor(admin, journal=journal_at(tmp_path))
    assert ex2.state == ExecutorState.RECOVERING
    res = ex2.resume_recovered_execution()
    assert res.dead == 0
    assert res.completed == len(ex2.tracker.tasks())
    # truncation may have erased IN_PROGRESS records, but never causes a
    # double submission: landed moves reconcile COMPLETED off the topology,
    # in-flight ones are re-adopted (the simulated admin REJECTS duplicate
    # submissions for an in-flight partition, so this would raise)
    assert all(n <= 1 for n in counts.values())
    by_key = {(p.topic, p.partition): set(p.replicas)
              for p in admin.topology().partitions}
    assert all(by_key[("T0", i)] == {1, 2} for i in range(4))


def test_reservations_survive_crash(tmp_path):
    admin = make_cluster()
    ex = Executor(admin, topic_names={0: "T0"}, journal=journal_at(tmp_path))
    props = [proposal(0, 0, [0, 1], [2, 1], data=5000.0)]
    with faults.process_crash(admin, schedule=faults.FaultSchedule(calls=[1])):
        with pytest.raises(faults.SimulatedProcessCrash):
            ex.execute_proposals(
                props, ExecutionOptions(progress_check_interval_s=1.0),
                removed_brokers={3}, demoted_brokers={1},
            )
    ex2 = Executor(admin, journal=journal_at(tmp_path))
    assert ex2.removed_brokers == {3}
    assert ex2.demoted_brokers == {1}
    ex2.resume_recovered_execution()
    assert ex2.removed_brokers == {3}  # resume does not drop reservations


def test_new_execution_blocked_while_recovering(tmp_path):
    admin = make_cluster()
    ex = Executor(admin, topic_names={0: "T0"}, journal=journal_at(tmp_path))
    props = [proposal(0, 0, [0, 1], [2, 1], data=5000.0)]
    with faults.process_crash(admin, schedule=faults.FaultSchedule(calls=[1])):
        with pytest.raises(faults.SimulatedProcessCrash):
            ex.execute_proposals(props, ExecutionOptions(progress_check_interval_s=1.0))
    ex2 = Executor(admin, journal=journal_at(tmp_path))
    assert ex2.has_ongoing_execution  # RECOVERING counts as ongoing
    with pytest.raises(OngoingExecutionError):
        ex2.execute_proposals(props)
    ex2.resume_recovered_execution()
    assert not ex2.has_ongoing_execution


# ------------------------------------------------------ stuck-move reaper


def test_reaper_rolls_back_stalled_move(tmp_path):
    admin = make_cluster()
    sink: list = []
    ex = Executor(admin, topic_names={0: "T0"}, journal=journal_at(tmp_path),
                  anomaly_sink=sink.append)
    admin.stall(("T0", 0))
    props = [proposal(0, i, [0, 1], [2, 1], data=1500.0) for i in range(3)]
    res = ex.execute_proposals(props, ExecutionOptions(
        progress_check_interval_s=1.0,
        reaper_stuck_timeout_s=3.0,
    ))
    # the stalled move was reaped via per-partition cancellation (rollback
    # to the original replica set), the rest of the batch kept flowing
    assert res.aborted >= 1
    assert res.dead == 0
    by_key = {(p.topic, p.partition): set(p.replicas)
              for p in admin.topology().partitions}
    assert by_key[("T0", 0)] == {0, 1}  # rolled back
    assert by_key[("T0", 1)] == {1, 2} and by_key[("T0", 2)] == {1, 2}
    assert len(sink) == 1
    anomaly = sink[0]
    assert isinstance(anomaly, ExecutionStuck)
    assert (anomaly.topic, anomaly.partition) == ("T0", 0)
    assert anomaly.rolled_back is True
    # journal carries the reap record (recovery-visible)
    records = journal_at(tmp_path).replay()
    assert any(r["t"] == "reaped" and r["mode"] == "rollback" for r in records)


class _NoCancelAdmin:
    """Delegating admin that hides per-partition cancellation — the
    pre-KIP-455 controller the reaper's DEAD fallback exists for."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        if name == "cancel_partition_reassignments":
            raise AttributeError(name)
        return getattr(self._inner, name)


def test_reaper_dead_when_controller_cannot_cancel():
    admin = make_cluster()
    ex = Executor(_NoCancelAdmin(admin), topic_names={0: "T0"})
    admin.stall(("T0", 0))
    props = [proposal(0, 0, [0, 1], [2, 1], old_leader=1, new_leader=1,
                      data=1500.0)]
    res = ex.execute_proposals(props, ExecutionOptions(
        progress_check_interval_s=1.0, reaper_stuck_timeout_s=3.0,
    ))
    assert res.dead == 1 and res.aborted == 0


def test_reaper_off_by_default():
    admin = make_cluster()
    ex = Executor(admin, topic_names={0: "T0"})
    admin.stall(("T0", 0))
    props = [proposal(0, 0, [0, 1], [2, 1], old_leader=1, new_leader=1,
                      data=500.0)]
    res = ex.execute_proposals(props, ExecutionOptions(
        progress_check_interval_s=1.0, max_ticks=20,
    ))
    # without the reaper the stalled move just burns the loop to max_ticks
    # and stays IN_PROGRESS in the tracker — the pre-reaper behavior
    assert res.aborted == 0 and res.completed == 0 and res.dead == 0
    assert len(ex.tracker.tasks(state=TaskState.IN_PROGRESS)) == 1


# -------------------------------------- load-aware adaptive concurrency


def test_adaptive_backoff_under_urp_spike():
    """An URP spike mid-execution (injected broker death away from the
    moves) multiplicatively backs off the movement caps; concurrency
    observed on the wire drops accordingly."""
    import dataclasses as dc

    from cruise_control_tpu.common.sensors import SensorRegistry

    admin = make_cluster(num_partitions=8, link_rate=1000.0)
    sensors = SensorRegistry()
    ex = Executor(admin, topic_names={0: "T0"}, sensors=sensors)
    concurrent = []
    orig = admin.tick

    def spy(seconds):
        concurrent.append(len(admin.in_progress_reassignments()))
        if len(concurrent) == 3:
            # broker 3 dies: its replicas go under-replicated (it is not a
            # party to any move, so nothing in flight is killed)
            topo = admin.metadata.topology()
            parts = list(topo.partitions) + [
                PartitionInfo("U0", 0, leader=3, replicas=(3,))
            ]
            brokers = tuple(
                dc.replace(b, alive=(b.broker_id != 3)) for b in topo.brokers
            )
            admin.metadata.set_topology(
                dc.replace(topo, brokers=brokers, partitions=tuple(parts))
            )
        return orig(seconds)

    admin.tick = spy
    props = [proposal(0, i, [0, 1], [2, 1], data=4000.0) for i in range(8)]
    res = ex.execute_proposals(props, ExecutionOptions(
        concurrent_partition_movements_per_broker=4,
        progress_check_interval_s=1.0,
        adaptive_enabled=True,
        adaptive_backoff_factor=0.5,
    ))
    assert res.completed == len(ex.tracker.tasks())
    assert sensors.counter("executor.adaptive.backoff").count >= 1
    # before the spike the drain ran at the full cap; afterwards new
    # submissions honored the backed-off cap
    assert max(concurrent[:3]) == 4
    assert sensors.counter("executor.adaptive.recovery").count >= 0


def test_concurrency_adjuster_aimd_unit():
    class _Topo:
        def __init__(self, urps):
            self._urps = urps
            self.partitions = [
                PartitionInfo("T", i, leader=9, replicas=(9,)) for i in range(urps)
            ]

        def alive_broker_ids(self):
            return {0, 1}

    adj = ConcurrencyAdjuster(
        base_inter=8, base_cluster=80, min_cap=1, max_cap=16,
        backoff_factor=0.5, recover_step=1, urp_slack=0, stall_ticks=0,
    )
    assert adj.caps() == (8, 80)
    adj.observe(_Topo(0), completed=1, in_flight=2)  # baseline tick
    inter, cluster = adj.observe(_Topo(3), completed=0, in_flight=2)  # spike
    assert inter == 4 and cluster == 40  # multiplicative, cluster scales
    inter, _ = adj.observe(_Topo(3), completed=0, in_flight=2)
    assert inter == 2
    # spike clears: additive recovery toward the base, one step per tick
    inter, _ = adj.observe(_Topo(0), completed=1, in_flight=2)
    assert inter == 3
    for _ in range(10):
        inter, cluster = adj.observe(_Topo(0), completed=1, in_flight=2)
    assert (inter, cluster) == (8, 80)  # never overshoots the base
    assert adj.num_backoffs == 2


def test_adjuster_throughput_collapse_counts_as_stress():
    class _Topo:
        partitions = []

        @staticmethod
        def alive_broker_ids():
            return {0}

    adj = ConcurrencyAdjuster(
        base_inter=8, base_cluster=80, stall_ticks=3, backoff_factor=0.5,
    )
    adj.observe(_Topo, completed=1, in_flight=1)
    for _ in range(2):
        inter, _ = adj.observe(_Topo, completed=0, in_flight=1)
    assert inter == 8  # not yet: 2 idle ticks < 3
    inter, _ = adj.observe(_Topo, completed=0, in_flight=1)
    assert inter == 4  # third consecutive idle tick backs off


# ------------------------------------------------------ acceptance story


def test_kill_and_restart_acceptance_story(tmp_path):
    """ISSUE 4 acceptance: mixed inter-broker + leadership execution
    crashed mid-flight, journal truncated at an arbitrary record, fresh
    Executor replays + reconciles + resumes to completion — zero duplicate
    submissions, zero leaked throttles, reservations intact — and a
    stalled move is reaped into EXECUTION_STUCK (delivered via the
    notifier) without blocking the remaining tasks."""
    parts = [PartitionInfo("T0", i, leader=0, replicas=(0, 1)) for i in range(6)]
    brokers = tuple(BrokerNode(i, rack=f"r{i % 2}", host=f"h{i}") for i in range(4))
    meta = StaticMetadataProvider(ClusterTopology(brokers=brokers, partitions=tuple(parts)))
    admin = SimulatedClusterAdmin(meta, link_rate_bytes_per_s=1000.0)
    counts = spy_submissions(admin)

    options = ExecutionOptions(
        concurrent_partition_movements_per_broker=2,
        progress_check_interval_s=1.0,
        replication_throttle_bytes_per_s=4000.0,
        reaper_stuck_timeout_s=4.0,
    )
    # 4 inter-broker moves (one of them permanently stalled; leader 1 stays
    # so each is a pure replica action) + 2 leadership-only transfers
    props = [proposal(0, i, [0, 1], [2, 1], old_leader=1, new_leader=1,
                      data=2500.0) for i in range(4)]
    props += [proposal(0, i, [0, 1], [0, 1], old_leader=0, new_leader=1)
              for i in (4, 5)]
    admin.stall(("T0", 0))

    ex = Executor(admin, topic_names={0: "T0"}, journal=journal_at(tmp_path))
    with faults.process_crash(admin, schedule=faults.FaultSchedule(calls=[2])):
        with pytest.raises(faults.SimulatedProcessCrash):
            ex.execute_proposals(props, options, removed_brokers={3})
    assert admin.throttle_rate == 4000.0  # leaked by the "dead" process
    # crash-truncate the journal at an arbitrary record boundary
    path = str(tmp_path / "journal.jsonl")
    faults.truncate_file(path, drop_bytes=17)

    # --- restart: fresh executor, anomaly pipeline wired like the facade
    notifier = SelfHealingNotifier()
    detector = AnomalyDetector(notifier, type("A", (), {"is_busy": False})())
    ex2 = Executor(admin, journal=journal_at(tmp_path),
                   anomaly_sink=detector.add_anomaly)
    assert ex2.state == ExecutorState.RECOVERING
    assert admin.throttle_rate is None  # startup sweep
    assert ex2.removed_brokers == {3}  # reservation intact
    assert ex2.executor_state()["state"] == "RECOVERING"

    res = ex2.resume_recovered_execution()
    assert res is not None
    total = len(ex2.tracker.tasks())
    assert total == 6
    # the stalled move was reaped (rollback -> ABORTED); everything else
    # ran to completion — the reaper did not block the batch
    assert res.aborted == 1
    assert res.completed == total - 1
    assert res.dead == 0
    assert ex2.tracker.tasks(state=TaskState.IN_PROGRESS) == []
    # zero duplicate submissions across both processes
    assert all(n == 1 for n in counts.values()), counts
    # zero leaked throttles
    assert admin.throttle_rate is None and admin.throttled_topics == set()
    # placements: stalled partition rolled back, the others landed
    by_key = {(p.topic, p.partition): p for p in admin.topology().partitions}
    assert set(by_key[("T0", 0)].replicas) == {0, 1}
    for i in (1, 2, 3):
        assert set(by_key[("T0", i)].replicas) == {1, 2}
    for i in (4, 5):
        assert by_key[("T0", i)].leader == 1
    # EXECUTION_STUCK delivered through the detector/notifier pipeline
    records = detector.run_once()
    stuck = [r for r in records
             if r.anomaly.anomaly_type == AnomalyType.EXECUTION_STUCK]
    assert len(stuck) == 1 and stuck[0].status == "IGNORED"
    assert len(notifier.alerts) == 1
    alert_anomaly, auto_fix = notifier.alerts[0]
    assert isinstance(alert_anomaly, ExecutionStuck) and auto_fix is False
    # reservations survived the whole story
    assert ex2.removed_brokers == {3}
    # the journal ends cleanly: a third process has nothing to recover
    assert journal_at(tmp_path).unfinished_execution() is None
    assert any(r["t"] == "finished"
               for r in journal_at(tmp_path).replay())


def test_aborting_task_in_journal_finalizes_as_aborted(tmp_path):
    """A crash between the ABORTING and ABORTED journal records (reaper or
    force-stop mid-cancellation) must finalize the task as ABORTED on
    recovery — never resubmit a deliberately-cancelled move, and never
    crash construction on an illegal ABORTING->COMPLETED transition."""
    admin = make_cluster()
    ex = Executor(admin, topic_names={0: "T0"}, journal=journal_at(tmp_path))
    props = [proposal(0, 0, [0, 1], [2, 1], old_leader=1, new_leader=1,
                      data=5000.0)]
    with faults.process_crash(admin, schedule=faults.FaultSchedule(calls=[1])):
        with pytest.raises(faults.SimulatedProcessCrash):
            ex.execute_proposals(props, ExecutionOptions(progress_check_interval_s=1.0))
    # forge the torn-cancellation tail: ABORTING journaled, ABORTED lost
    j = journal_at(tmp_path)
    j.append({"t": "task", "id": 0, "state": "ABORTING", "ms": 1})
    j.close()
    counts = spy_submissions(admin)
    ex2 = Executor(admin, journal=journal_at(tmp_path))
    res = ex2.resume_recovered_execution()
    aborted = ex2.tracker.tasks(state=TaskState.ABORTED)
    assert len(aborted) == 1 and aborted[0].execution_id == 0
    assert counts == {}  # the cancelled move was never resubmitted
    assert res is None or res.aborted == 1


def test_failed_throttle_sweep_stays_recoverable(tmp_path):
    """A sweep the admin rejects must NOT journal throttle_cleared — the
    next restart has to see the leak and retry."""
    admin = make_cluster()
    ex = Executor(admin, topic_names={0: "T0"}, journal=journal_at(tmp_path))
    props = [proposal(0, 0, [0, 1], [2, 1], old_leader=1, new_leader=1,
                      data=5000.0)]
    with faults.process_crash(admin, schedule=faults.FaultSchedule(calls=[1])):
        with pytest.raises(faults.SimulatedProcessCrash):
            ex.execute_proposals(props, ExecutionOptions(
                progress_check_interval_s=1.0,
                replication_throttle_bytes_per_s=2000.0,
            ))
    assert admin.throttle_rate == 2000.0
    # restart #1: the admin rejects the sweep (still partitioned away)
    with faults.method_fault(admin, "clear_replication_throttle",
                             faults.raising(lambda: ConnectionError("nope"))):
        ex2 = Executor(admin, journal=journal_at(tmp_path))
    assert ex2.recovery_info()["sweptThrottle"] is False
    assert admin.throttle_rate == 2000.0  # still leaked
    # restart #2 (ex2 abandoned before resuming): sweep retried and lands
    ex3 = Executor(admin, journal=journal_at(tmp_path))
    assert ex3.recovery_info()["sweptThrottle"] is True
    assert admin.throttle_rate is None
    ex3.resume_recovered_execution()


def test_stop_during_recovering_is_honored(tmp_path):
    """stop_execution issued while the executor sits RECOVERING must not
    be wiped by the resume — the resumed loop drains instead of driving
    the recovered execution to completion."""
    admin = make_cluster()
    ex = Executor(admin, topic_names={0: "T0"}, journal=journal_at(tmp_path))
    props = [proposal(0, i, [0, 1], [2, 1], old_leader=1, new_leader=1,
                      data=20_000.0) for i in range(4)]
    with faults.process_crash(admin, schedule=faults.FaultSchedule(calls=[1])):
        with pytest.raises(faults.SimulatedProcessCrash):
            ex.execute_proposals(props, ExecutionOptions(
                concurrent_partition_movements_per_broker=1,
                progress_check_interval_s=1.0,
            ))
    ex2 = Executor(admin, journal=journal_at(tmp_path))
    assert ex2.state == ExecutorState.RECOVERING
    ex2.stop_execution(force=True)
    res = ex2.resume_recovered_execution()
    assert res.stopped
    assert res.completed == 0  # 20k bytes never finished in a drain
    assert res.aborted == len(ex2.tracker.tasks())
    assert admin.in_progress_reassignments() == set()  # force-cancelled


def test_resume_restores_journaled_adaptive_cap(tmp_path):
    """A resumed execution picks the adaptive cap back up from the journal
    instead of re-hitting a recently-stressed cluster at full base
    concurrency."""
    import dataclasses as dc

    admin = make_cluster(num_partitions=8)
    ex = Executor(admin, topic_names={0: "T0"}, journal=journal_at(tmp_path))
    calls = []
    orig = admin.tick

    def spy(seconds):
        calls.append(1)
        if len(calls) == 2:  # URP spike -> backoff journaled before crash
            topo = admin.metadata.topology()
            parts = list(topo.partitions) + [
                PartitionInfo("U0", 0, leader=3, replicas=(3,))
            ]
            brokers = tuple(
                dc.replace(b, alive=(b.broker_id != 3)) for b in topo.brokers
            )
            admin.metadata.set_topology(
                dc.replace(topo, brokers=brokers, partitions=tuple(parts))
            )
        return orig(seconds)

    admin.tick = spy
    props = [proposal(0, i, [0, 1], [2, 1], old_leader=1, new_leader=1,
                      data=20_000.0) for i in range(8)]
    with faults.process_crash(admin, on="reassign_partitions",
                              schedule=faults.FaultSchedule(after=2)):
        with pytest.raises(faults.SimulatedProcessCrash):
            ex.execute_proposals(props, ExecutionOptions(
                concurrent_partition_movements_per_broker=4,
                progress_check_interval_s=1.0,
                adaptive_enabled=True,
            ))
    records = journal_at(tmp_path).replay()
    journaled = [r for r in records if r["t"] == "concurrency"]
    assert journaled, "backoff should have been journaled before the crash"
    ex2 = Executor(admin, journal=journal_at(tmp_path))
    seen_caps = []
    orig2 = admin.tick

    def spy2(seconds):
        adj = ex2._adjuster
        if adj is not None:
            seen_caps.append(adj.inter_cap)
        return orig2(seconds)

    admin.tick = spy2
    ex2.resume_recovered_execution()
    # the resumed adjuster started from the journaled (backed-off) cap —
    # at most one additive recovery step above it by the first tick —
    # not from the base of 4
    assert seen_caps, "adjuster never observed"
    assert seen_caps[0] <= journaled[-1]["inter"] + 1
    assert seen_caps[0] < 4


def test_adaptive_not_fooled_by_intra_only_throughput():
    """Intra-broker logdir completions count as throughput: a healthy
    intra-heavy execution must not trip the stall signal."""
    from cruise_control_tpu.common.sensors import SensorRegistry

    admin = make_cluster(intra_move_bytes=2000.0)
    sensors = SensorRegistry()
    ex = Executor(admin, topic_names={0: "T0"}, sensors=sensors)
    props = [proposal(0, i, [0, 1], [0, 1], data=0.0,
                      disk_moves=((0, 0, 1),), intra_data=2000.0)
             for i in range(4)]
    res = ex.execute_proposals(props, ExecutionOptions(
        progress_check_interval_s=1.0,
        concurrent_intra_broker_partition_movements=1,
        adaptive_enabled=True,
        adaptive_stall_ticks=3,  # copies complete every 2 ticks
    ))
    assert res.completed == 4
    assert sensors.counter("executor.adaptive.backoff").count == 0


def test_execution_stuck_alert_not_delayed_by_busy_executor():
    """EXECUTION_STUCK fires mid-execution, while the executor is by
    definition busy — the alert must go out immediately, not park in the
    detector's busy re-check queue until the execution ends."""
    notifier = SelfHealingNotifier()
    detector = AnomalyDetector(notifier, type("A", (), {"is_busy": True})())
    detector.add_anomaly(ExecutionStuck(topic="T0", partition=0, stalled_s=9.0))
    records = detector.run_once()
    assert len(records) == 1 and records[0].status == "IGNORED"
    assert len(notifier.alerts) == 1  # alerted despite the busy executor


# ----------------------------------------------- facade/service wiring


def test_facade_wires_journal_and_recovery(tmp_path):
    """build_simulated_service with executor.journal.dir: executions
    journal + finish cleanly; a second facade over the same dir starts
    clean (no recovery) and /state carries the executor block."""
    from cruise_control_tpu.config import CruiseControlConfig
    from cruise_control_tpu.service.main import build_simulated_service

    cfg = {
        "partition.metrics.window.ms": 1000,
        "min.samples.per.partition.metrics.window": 1,
        "execution.progress.check.interval.ms": 100,
        "webserver.http.port": 0,
        "tpu.num.candidates": 64,
        "tpu.leadership.candidates": 16,
        "tpu.steps.per.round": 8,
        "tpu.num.rounds": 1,
        "executor.journal.dir": str(tmp_path),
    }
    app, fetcher, admin, sampler = build_simulated_service(CruiseControlConfig(cfg))
    cc = app.cc
    assert cc.executor.journal is not None
    assert cc.executor.anomaly_sink == cc.anomaly_detector.add_anomaly
    opts = cc._exec_options({})
    assert opts.reaper_stuck_timeout_s == 900.0
    assert opts.adaptive_enabled is True
    cc.executor.topic_names = {0: "T0"}  # fixture-built proposal below
    res = cc.executor.execute_proposals(
        [proposal(0, 0, [0, 1], [2, 1], old_leader=1, new_leader=1, data=10.0)],
        opts,
    )
    assert res.completed == len(cc.executor.tracker.tasks())
    journal_path = os.path.join(str(tmp_path), "execution-journal.jsonl")
    assert os.path.exists(journal_path)
    records = [json.loads(line) for line in open(journal_path)]
    assert records[0]["t"] == "start" and records[-1]["t"] == "finished"
    # restart: nothing to recover
    app2, *_ = build_simulated_service(CruiseControlConfig(cfg))
    assert app2.cc.executor.recovery_info() is None


def test_executor_injected_clock_drives_reservation_retention():
    """Satellite: _pruned rides the injected clock, so simulated time
    controls reservation expiry (no real sleeps)."""
    admin = make_cluster()
    now = {"ms": 1_000_000}
    ex = Executor(admin, topic_names={0: "T0"}, clock=lambda: now["ms"],
                  removal_history_retention_ms=5_000)
    ex.execute_proposals([], removed_brokers={2})
    assert ex.removed_brokers == {2}
    now["ms"] += 4_999
    assert ex.removed_brokers == {2}
    now["ms"] += 2
    assert ex.removed_brokers == set()
