"""ClusterState / builder / aggregates / stats unit tests (M0)."""

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.models import (
    ClusterModelBuilder,
    BrokerSpec,
    PartitionSpec,
    compute_aggregates,
    compute_stats,
    validate,
)
from cruise_control_tpu.testing.fixtures import (
    RandomClusterSpec,
    dead_broker_cluster,
    rack_violated_cluster,
    random_cluster,
    small_cluster,
)


def test_small_cluster_shapes():
    s = small_cluster()
    assert s.shape.B == 3
    assert s.shape.P == 4
    assert s.shape.R == 8
    assert s.shape.num_racks == 3
    assert s.shape.num_topics == 2
    assert validate(s) == []


def test_effective_load_leadership_split():
    s = small_cluster()
    load = np.asarray(s.replica_load)
    lead = np.asarray(s.replica_is_leader)
    # followers serve no NW_OUT
    assert (load[~lead][:, Resource.NW_OUT] == 0).all()
    # leaders carry their full leader load
    ll = np.asarray(s.replica_load_leader)
    assert np.allclose(load[lead], ll[lead])


def test_broker_load_aggregation_matches_numpy():
    s = random_cluster(RandomClusterSpec(num_brokers=10, num_partitions=200), seed=1)
    agg = compute_aggregates(s)
    load = np.asarray(s.replica_load)
    brk = np.asarray(s.replica_broker)
    expected = np.zeros((10, NUM_RESOURCES), np.float32)
    np.add.at(expected, brk, load)
    assert np.allclose(np.asarray(agg.broker_load), expected, rtol=1e-4, atol=1e-3)


def test_replica_and_leader_counts():
    s = small_cluster()
    agg = compute_aggregates(s)
    # broker 0 holds a replica of every partition and leads all 4
    assert int(agg.broker_replica_count[0]) == 4
    assert int(agg.broker_leader_count[0]) == 4
    assert int(agg.broker_leader_count[1]) == 0
    assert int(np.asarray(agg.broker_replica_count).sum()) == 8


def test_part_rack_count_detects_violations():
    s = rack_violated_cluster()
    agg = compute_aggregates(s)
    prc = np.asarray(agg.part_rack_count)
    # partitions 0 and 1 are rack-violated (2 replicas on one rack)
    assert prc.max() == 2
    assert (prc == 2).sum() == 2


def test_potential_nw_out():
    s = small_cluster()
    agg = compute_aggregates(s)
    ll = np.asarray(s.replica_load_leader)[:, Resource.NW_OUT]
    brk = np.asarray(s.replica_broker)
    expected = np.zeros(3, np.float32)
    np.add.at(expected, brk, ll)
    assert np.allclose(np.asarray(agg.broker_potential_nw_out), expected, rtol=1e-5)


def test_dead_broker_offline_flags():
    s = dead_broker_cluster()
    off = np.asarray(s.replica_offline)
    brk = np.asarray(s.replica_broker)
    assert (off == (brk == 3)).all()


def test_stats_on_random_cluster():
    s = random_cluster(RandomClusterSpec(num_brokers=20, num_partitions=500), seed=2)
    stats = compute_stats(s)
    avg = np.asarray(stats.avg)
    mx = np.asarray(stats.max)
    mn = np.asarray(stats.min)
    assert (mx >= avg - 1e-5).all() and (avg >= mn - 1e-5).all()
    assert (np.asarray(stats.std) >= 0).all()


def test_builder_rejects_sparse_broker_ids():
    b = ClusterModelBuilder()
    b.add_broker(BrokerSpec(0, rack="r0"))
    b.add_broker(BrokerSpec(2, rack="r0"))
    with pytest.raises(ValueError, match="dense"):
        b.build()


def test_replica_padding():
    spec = RandomClusterSpec(num_brokers=5, num_partitions=50, replica_capacity=512)
    s = random_cluster(spec, seed=0)
    assert s.shape.R == 512
    valid = np.asarray(s.replica_valid)
    assert valid.sum() < 512
    # padded rows carry no load in aggregates
    agg = compute_aggregates(s)
    total = float(np.asarray(agg.broker_load).sum())
    manual = float(np.asarray(s.replica_load)[valid].sum())
    assert np.isclose(total, manual, rtol=1e-4)


def test_validate_catches_double_leader():
    s = small_cluster()
    import dataclasses

    bad = dataclasses.replace(s, replica_is_leader=jnp.ones_like(s.replica_is_leader))
    problems = validate(bad, strict=False)
    assert any("leader" in p for p in problems)


def test_jbod_disk_modeling():
    b = ClusterModelBuilder()
    b.add_broker(BrokerSpec(0, rack="r0", disk_capacities=[1000.0, 2000.0]))
    b.add_broker(BrokerSpec(1, rack="r1", disk_capacities=[1500.0, 1500.0], bad_disks=[1]))
    load = np.array([1.0, 10.0, 10.0, 300.0], np.float32)
    b.add_partition(PartitionSpec("T", 0, [0, 1], load, replica_disks=[1, 1]))
    s = b.build()
    assert s.shape.max_disks_per_broker == 2
    assert float(s.broker_capacity[0, Resource.DISK]) == 3000.0
    assert bool(s.disk_alive[1, 1]) is False
    # replica on broker 1's dead disk is offline
    off = np.asarray(s.replica_offline)
    brk = np.asarray(s.replica_broker)
    assert off[brk == 1].all()
    agg = compute_aggregates(s)
    dl = np.asarray(agg.disk_load)
    assert np.isclose(dl[0, 1], 300.0) and dl[0, 0] == 0.0


def test_columnar_build_matches_builder():
    """build_state_columnar output is array-identical to feeding the same
    topology through ClusterModelBuilder one PartitionSpec at a time."""
    import numpy as np

    from cruise_control_tpu.models.builder import (
        BrokerSpec,
        ClusterModelBuilder,
        PartitionSpec,
        build_state_columnar,
    )
    from cruise_control_tpu.testing.synthetic import synthetic_topology

    topo = synthetic_topology(
        num_brokers=7, topics={"zeta": 5, "alpha": 9, "mid": 3}, seed=11
    )
    rng = np.random.default_rng(0)
    cols = topo.columns()
    P = len(topo.partitions)
    ll = rng.uniform(0, 50, (P, 4)).astype(np.float32)
    fl = rng.uniform(0, 20, (P, 4)).astype(np.float32)

    def spec(b):
        return BrokerSpec(
            b.broker_id, rack=b.rack, host=b.host, alive=(b.broker_id != 3),
            capacity=np.asarray([10.0, 2e5, 3e5, 4e6], np.float32),
        )

    builder = ClusterModelBuilder()
    for b in topo.brokers:
        builder.add_broker(spec(b))
    for i, p in enumerate(topo.partitions):
        lp = p.replicas.index(p.leader) if p.leader in p.replicas else 0
        builder.add_partition(PartitionSpec(
            p.topic, p.partition, list(p.replicas), ll[i],
            follower_load=fl[i], leader_pos=lp,
        ))
    want = builder.build()

    got, catalog = build_state_columnar(
        [spec(b) for b in topo.brokers], cols, ll, fl
    )
    assert catalog == builder.catalog
    assert got.shape == want.shape
    for f in (
        "replica_broker", "replica_partition", "replica_topic", "replica_pos",
        "replica_is_leader", "replica_valid", "replica_offline", "replica_disk",
        "replica_load_leader", "replica_load_follower", "broker_capacity",
        "broker_rack", "broker_host", "broker_alive", "disk_capacity",
        "disk_alive",
    ):
        np.testing.assert_array_equal(
            np.asarray(getattr(got, f)), np.asarray(getattr(want, f)), err_msg=f
        )


def test_columnar_build_respects_replica_capacity_padding():
    import numpy as np

    from cruise_control_tpu.models.builder import BrokerSpec, build_state_columnar
    from cruise_control_tpu.testing.synthetic import synthetic_topology

    topo = synthetic_topology(num_brokers=4, topics={"T": 6}, seed=1)
    cols = topo.columns()
    P = len(topo.partitions)
    ll = np.ones((P, 4), np.float32)
    state, _ = build_state_columnar(
        [BrokerSpec(b.broker_id, rack=b.rack, host=b.host) for b in topo.brokers],
        cols, ll, ll * 0.5, replica_capacity=100,
    )
    assert state.shape.num_replicas == 100
    n = int(np.asarray(state.replica_valid).sum())
    assert n == topo.num_replicas
    assert not np.asarray(state.replica_valid)[n:].any()
