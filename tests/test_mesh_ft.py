"""Mesh fault tolerance (parallel/ft.py + the watchdog's mesh seams).

The layer under test turns a chip dying mid-anneal into a detected,
bounded, RESUMABLE event instead of a bare rc=124: per-device probe
fan-out attributes a failed mesh dispatch to the specific chip
(DEVICE_LOST / COLLECTIVE_STALL), slice boundaries capture host-side
carry checkpoints, and the optimizer's width ladder rebuilds the mesh
over the survivors and resumes the remaining rounds byte-identically.
The acceptance pin at the bottom drives the whole story through a
supervised GoalOptimizer with an injected mid-anneal device loss —
the in-process twin of `bench.py --mesh-chaos`.
"""

import os
import signal
import subprocess
import sys
import textwrap
import threading
import time
import types

import pytest

import jax
import numpy as np

from cruise_control_tpu.analyzer import DEFAULT_CHAIN, OptimizerConfig
from cruise_control_tpu.analyzer.engine import (
    SegmentContext,
    segmented_execution,
)
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.common.blackbox import (
    RECORDER,
    read_spool,
    spool_verdict,
)
from cruise_control_tpu.common.device_watchdog import (
    BreakerState,
    CircuitBreaker,
    CollectiveStallError,
    DeviceDegradedError,
    DeviceLostError,
    DeviceSupervisor,
    FailureClass,
    MESH_FAILURE_CLASSES,
    classify_failure,
    device_op,
    probe_devices,
)
from cruise_control_tpu.common.dispatch import dispatch_meter
from cruise_control_tpu.common.sensors import SensorRegistry
from cruise_control_tpu.parallel.ft import CheckpointSlot, MeshFtController
from cruise_control_tpu.parallel.sharded import ShardedEngine, model_mesh
from cruise_control_tpu.testing import faults
from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _graft_entry():
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as g
    finally:
        sys.path.remove(REPO)
    return g


#: early stop disabled so the slice count is deterministic — the chaos
#: pins below inject at a specific slice boundary
CFG = OptimizerConfig(
    num_candidates=60,
    leadership_candidates=16,
    swap_candidates=8,
    steps_per_round=6,
    num_rounds=4,
    early_stop_violations=-1.0,
    seed=3,
)


def _state(seed=21, brokers=12, parts=160):
    return random_cluster(
        RandomClusterSpec(num_brokers=brokers, num_partitions=parts, skew=1.5),
        seed=seed,
    )


@pytest.fixture(autouse=True)
def _reset_recorder():
    yield
    RECORDER.configure(None)


@pytest.fixture(scope="module")
def mesh_state():
    return _state()


@pytest.fixture(scope="module")
def se8(mesh_state):
    return ShardedEngine(
        mesh_state, DEFAULT_CHAIN,
        mesh=model_mesh(np.asarray(jax.devices()[:8])), config=CFG,
    )


def _placements(state):
    return tuple(
        np.asarray(getattr(state, f))
        for f in ("replica_broker", "replica_is_leader", "replica_disk")
    )


def _same(a, b) -> bool:
    return all(bool((x == y).all()) for x, y in zip(_placements(a), _placements(b)))


# the fault harness's device-op seam, without compiling anything: a fake
# mesh receiver is enough for _dispatch_device_ids / _blackbox_fields
class FakeMeshEngine:
    def __init__(self, devices):
        self.mesh = types.SimpleNamespace(devices=np.asarray(devices, dtype=object))


@device_op("mesh.run")
def fake_mesh_run(engine):
    return "ran"


# ------------------------------------------------------- classification


def test_mesh_failure_taxonomy():
    assert MESH_FAILURE_CLASSES == {
        FailureClass.DEVICE_LOST, FailureClass.COLLECTIVE_STALL,
    }
    assert classify_failure(DeviceLostError("gone", (3,))) is FailureClass.DEVICE_LOST
    assert (
        classify_failure(CollectiveStallError("wedged", (1, 2)))
        is FailureClass.COLLECTIVE_STALL
    )
    # the backend's textual shape (and the fault harness's lookalike)
    assert (
        classify_failure(faults.device_lost_error("mesh.run", 5))
        is FailureClass.DEVICE_LOST
    )
    # DEVICE_LOST markers win over the generic runtime markers that would
    # otherwise retry forever against a chip that no longer exists
    assert (
        classify_failure(RuntimeError("INTERNAL: XLA: device coredump"))
        is FailureClass.DEVICE_LOST
    )
    # HANG / TRANSIENT are NOT mesh classes: no suspect chip to exclude
    assert FailureClass.HANG not in MESH_FAILURE_CLASSES
    assert FailureClass.TRANSIENT not in MESH_FAILURE_CLASSES


def test_device_loss_injector_latches_probes():
    """Once the scheduled loss fires, the chip's attribution probe fails
    too while every other chip's passes — exactly the asymmetry the
    classifier attributes on."""
    devs = jax.devices()
    with faults.device_loss(2, ops=("mesh.run",)) as log:
        # a dispatch NOT involving the chip falls through untouched
        assert fake_mesh_run(FakeMeshEngine(devs[4:])) == "ran"
        # probes before the latch: every chip healthy
        assert all(d is None for d in probe_devices(devs, timeout_s=10.0).values())
        with pytest.raises(RuntimeError, match="DEVICE_LOST"):
            fake_mesh_run(FakeMeshEngine(devs))
        results = probe_devices(devs, timeout_s=10.0)
        assert results[2] is not None and "DEVICE_LOST" in results[2]
        assert all(d is None for i, d in results.items() if i != 2)
    assert log.fired["mesh.run"] == 1 and log.fired["device.probe"] >= 1
    # nest-safe: the hook is restored on exit
    assert fake_mesh_run(FakeMeshEngine(devs)) == "ran"


def test_supervisor_attributes_device_loss_and_spares_main_breaker():
    """A mesh dispatch failure under `call(breaker=..., mesh_devices=...)`
    names the suspect chip via the probe fan-out, opens only the
    caller-owned per-width breaker, and records per-device health."""
    sup = DeviceSupervisor(
        op_timeout_s=30.0, max_retries=0, probe_timeout_s=10.0,
    )
    width_brk = CircuitBreaker(failure_threshold=1, probe_interval_s=60.0)
    devs = jax.devices()
    with faults.device_loss(5, ops=("mesh.run",)):
        with pytest.raises(DeviceDegradedError) as ei:
            sup.call(
                lambda: fake_mesh_run(FakeMeshEngine(devs)),
                op="optimize", breaker=width_brk, mesh_devices=devs,
            )
    assert ei.value.failure_class is FailureClass.DEVICE_LOST
    assert ei.value.device_ids == (5,)
    assert width_brk.state is BreakerState.OPEN
    # the single-device breaker never heard about it
    assert sup.breaker.state is BreakerState.CLOSED and sup.available()
    health = sup.device_health()
    assert health[5]["healthy"] is False and health[0]["healthy"] is True


def test_supervisor_upgrades_subset_hang_to_collective_stall(tmp_path):
    """A hung multi-device dispatch with a strict SUBSET of the mesh
    unresponsive becomes COLLECTIVE_STALL naming the wedged chip — and
    the black-box trail left behind carries the mesh width in flight,
    the record the SIGKILL/timeout verdicts replay to."""
    RECORDER.configure(str(tmp_path / "spool-1.jsonl"))
    state = _state(brokers=8, parts=64)
    engine = ShardedEngine(
        state, DEFAULT_CHAIN,
        mesh=model_mesh(np.asarray(jax.devices()[:8])), config=CFG,
    )
    sup = DeviceSupervisor(
        op_timeout_s=0.5, max_retries=0, probe_timeout_s=1.5,
        breaker_failure_threshold=100,
    )
    devs = jax.devices()
    g = _graft_entry()
    with faults.collective_stall(device_index=3, ops=("mesh.run",)):
        with pytest.raises(DeviceDegradedError) as ei:
            sup.call(
                lambda: engine.run(), op="optimize", mesh_devices=devs,
            )
        # read while the stall HOLDS: the abandoned dispatch is in flight
        # (at context exit the blocked worker returns and the End record
        # lands, so the in-flight window closes)
        records = read_spool(str(tmp_path / "spool-1.jsonl"))
        verdict = spool_verdict(str(tmp_path))
        fields = g._child_failure_fields(None, None, str(tmp_path))
    assert ei.value.failure_class is FailureClass.COLLECTIVE_STALL
    assert ei.value.device_ids == (3,)
    assert sup.device_health()[3]["healthy"] is False
    stuck = [
        r for r in records
        if r["t"] == "device-op" and r["ph"] == "B" and r["op"] == "mesh.run"
    ]
    assert stuck and stuck[-1]["mesh_shape"] == [1, 8]
    assert stuck[-1]["n_devices"] == 8
    assert verdict["mesh_in_flight"]["n_devices"] == 8
    assert verdict["mesh_in_flight"]["mesh_shape"] == [1, 8]
    # the dryrun timeout verdict embeds the same block (__graft_entry__)
    assert fields["mesh_in_flight"]["n_devices"] == 8
    assert fields["spool_configured"] is True


# -------------------------------------------- controller + checkpointing


def test_controller_per_width_breakers_and_probe_lifecycle():
    now = {"t": 0.0}
    ft = MeshFtController(probe_interval_s=10.0, clock=lambda: now["t"])
    brk = ft.acquire_width(8)
    assert brk is not None and brk.state is BreakerState.CLOSED
    brk.record_failure()
    assert brk.state is BreakerState.OPEN
    # widths are independent breakers
    assert ft.acquire_width(4) is not None
    assert ft.acquire_width(8) is None  # probe not due yet
    now["t"] = 11.0
    probe = ft.acquire_width(8)  # the attempt IS the half-open probe
    assert probe is brk and brk.state is BreakerState.HALF_OPEN
    ft.note_width_result(8, ok=False)  # failed probe re-arms the timer
    assert brk.state is BreakerState.OPEN and ft.acquire_width(8) is None
    now["t"] = 22.0
    assert ft.acquire_width(8) is brk
    ft.note_width_result(8, ok=True)
    assert brk.state is BreakerState.CLOSED


def test_controller_episode_fires_once_and_rearms_at_full_width():
    ft = MeshFtController()
    assert ft.poll_event() is None
    ft.note_degrade(lost=(6,), from_width=8, to_width=4,
                    failure_class="device_lost")
    assert ft.episodes == 1 and ft.episode_open
    event = ft.poll_event()
    assert event["lost_devices"] == [6] and event["episode"] == 1
    assert ft.poll_event() is None  # exactly once per episode
    # walking further down the ladder inside the episode: no re-fire
    ft.note_degrade(lost=(3,), from_width=4, to_width=2,
                    failure_class="collective_stall")
    assert ft.episodes == 1 and ft.poll_event() is None
    assert ft.last_event["to_width"] == 2
    # completing at reduced width keeps the episode open...
    ft.note_run_completed(width=2, full_width=8)
    assert ft.episode_open
    # ...recovery to FULL width closes it, re-arming the anomaly
    ft.note_run_completed(width=8, full_width=8)
    assert not ft.episode_open
    ft.note_degrade(lost=(1,), from_width=8, to_width=4,
                    failure_class="device_lost")
    assert ft.episodes == 2 and ft.poll_event()["episode"] == 2
    state = ft.state_json()
    assert state["episodes"] == 2 and state["activeWidth"] == 4


def test_offer_snapshot_cadence_one_in_flight_and_off_path():
    slot = CheckpointSlot()
    assert slot.latest() is None
    gate = threading.Event()
    landed = []

    def slow_sink(ckpt):
        gate.wait(10.0)
        slot.offer(ckpt)
        landed.append(ckpt)

    ctx = SegmentContext(0.0, snapshot_every=2, snapshot_sink=slow_sink)
    ctx.offer_snapshot(lambda: "b1")  # boundary 1: not due
    assert ctx.snapshots_taken == 0
    ctx.offer_snapshot(lambda: "b2")  # boundary 2: captured, persisting
    ctx.offer_snapshot(lambda: "b3")  # boundary 3: not due
    ctx.offer_snapshot(lambda: "b4")  # boundary 4: due but in flight → skip
    assert ctx.snapshots_taken == 1 and ctx.snapshots_skipped == 1
    assert slot.latest() is None  # persist still blocked
    gate.set()
    ctx.wait_snapshot()
    assert slot.latest() == "b2" and landed == ["b2"]
    # a raising sink is logged, never raised into the run it protects
    bad = SegmentContext(
        0.0, snapshot_every=1,
        snapshot_sink=lambda c: (_ for _ in ()).throw(OSError("disk full")),
    )
    bad.offer_snapshot(lambda: "x")
    bad.wait_snapshot()
    assert bad.snapshots_taken == 1
    # snapshot_every=0 (the default): capture must never even be called
    off = SegmentContext(0.0, snapshot_sink=slot.offer)
    off.offer_snapshot(lambda: pytest.fail("off path must not capture"))
    assert off.snapshots_taken == 0


# --------------------------------------------- segmented × mesh parity


@pytest.mark.slow
def test_segmented_mesh_parity_snapshots_and_reduced_width_resume(
    mesh_state, se8
):
    """THE checkpoint-layer invariant chain: a mesh run split into slices
    is byte-identical to the unsegmented mesh run; snapshots ride the
    slice boundaries only when asked (zero `mesh.snapshot` dispatches
    otherwise); and a checkpoint captured at width 8 resumes on a WIDTH-4
    mesh to the same bytes — full-K draws from the replicated key make
    the trajectory width-independent, so reduced-width resume is exact."""
    final, _ = se8.run()
    snaps = []
    ctx = SegmentContext(0.0, snapshot_every=1, snapshot_sink=snaps.append)
    with segmented_execution(ctx), dispatch_meter() as m_on:
        final_seg, hist_seg = se8.run()
    ctx.wait_snapshot()
    timing = next(h for h in hist_seg if h.get("timing"))
    assert timing["segmented"] is True and timing["segments"] >= 3
    assert timing["snapshots"] >= 2 and timing["snapshot_s"] >= 0.0
    assert m_on.counts["mesh.snapshot"] == timing["snapshots"]
    assert _same(final, final_seg)
    assert len(snaps) >= 2
    # checkpointing OFF: the segmented stream has zero snapshot dispatches
    with segmented_execution(SegmentContext(0.0)), dispatch_meter() as m_off:
        final_off, _ = se8.run()
    assert m_off.counts.get("mesh.snapshot", 0) == 0
    assert _same(final, final_off)
    # resume the mid-anneal checkpoint on a narrower mesh
    ck = snaps[1]
    assert ck.base >= 1 and ck.n_chains == 1
    se4 = ShardedEngine(
        mesh_state, DEFAULT_CHAIN,
        mesh=model_mesh(np.asarray(jax.devices()[:4])), config=CFG,
    )
    before = [np.array(leaf, copy=True) for leaf in jax.tree.leaves(ck.carry)]
    final4, hist4 = se4.run(resume=ck)
    t4 = next(h for h in hist4 if h.get("timing"))
    assert t4["resumed_from_round"] == int(ck.base)
    assert t4["mesh_shape"] == [1, 4]
    assert _same(final, final4)
    # the resume must not scribble into the checkpoint: device_put can
    # zero-copy alias the host trees and the slice programs donate the
    # carry — a second resume from the SAME snapshot has to be exact
    after = jax.tree.leaves(ck.carry)
    assert all(np.array_equal(a, b) for a, b in zip(before, after))
    final4b, _ = se4.run(resume=ck)
    assert _same(final4, final4b)


_MESH_KILL_CHILD = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    import jax; jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from cruise_control_tpu.analyzer import DEFAULT_CHAIN, OptimizerConfig
    from cruise_control_tpu.common.blackbox import RECORDER
    from cruise_control_tpu.parallel.sharded import ShardedEngine, model_mesh
    from cruise_control_tpu.testing import faults
    from cruise_control_tpu.testing.fixtures import (
        RandomClusterSpec, random_cluster,
    )

    RECORDER.configure(os.path.join({spool_dir!r}, f"spool-{{os.getpid()}}.jsonl"))
    state = random_cluster(RandomClusterSpec(
        num_brokers=8, num_partitions=48, skew=1.0), seed=0)
    cfg = OptimizerConfig(num_candidates=32, leadership_candidates=8,
                          swap_candidates=0, steps_per_round=2, num_rounds=2,
                          seed=0)
    se = ShardedEngine(state, DEFAULT_CHAIN,
                       mesh=model_mesh(np.asarray(jax.devices()[:8])),
                       config=cfg)
    # the injected stall IS the wedged collective: the mesh dispatch
    # blocks forever with its Begin record (mesh shape stamped) on disk
    with faults.collective_stall(ops=("mesh.run",)):
        se.run()
    print("UNREACHABLE")  # the parent kills us mid-dispatch
""")


def test_kill9_mid_mesh_dispatch_verdict_names_mesh_width(tmp_path):
    """The satellite's SIGKILL regression: kill -9 a process wedged
    inside a MESH dispatch — the surviving spool's verdict (and the
    dryrun timeout verdict built from it) must name the mesh width in
    flight, not just the op."""
    spool_dir = str(tmp_path / "spool")
    os.makedirs(spool_dir)
    child = subprocess.Popen(
        [sys.executable, "-c",
         _MESH_KILL_CHILD.format(repo=REPO, spool_dir=spool_dir)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        # wait for the in-flight mesh dispatch (the child is hung inside
        # it), then kill -9 — no cooperation from the child
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            records = read_spool(spool_dir)
            if any(
                r["t"] == "device-op" and r["ph"] == "B"
                and r.get("op") == "mesh.run" and r.get("n_devices") == 8
                for r in records
            ):
                break
            if child.poll() is not None:
                out, err = child.communicate(timeout=10)
                pytest.fail(
                    f"child exited rc={child.returncode} before hanging:\n"
                    f"{err.decode(errors='replace')[-2000:]}"
                )
            time.sleep(0.05)
        else:
            pytest.fail("child never dispatched on the mesh")
        os.kill(child.pid, signal.SIGKILL)
        child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()
    verdict = spool_verdict(spool_dir)
    assert verdict["mesh_in_flight"]["op"] == "mesh.run"
    assert verdict["mesh_in_flight"]["mesh_shape"] == [1, 8]
    assert verdict["mesh_in_flight"]["n_devices"] == 8
    fields = _graft_entry()._child_failure_fields(None, None, spool_dir)
    assert fields["mesh_in_flight"]["n_devices"] == 8


# ------------------------------------------------------- purge scoping


class _DummyEngine:
    def __init__(self):
        self.released = False

    def release(self):
        self.released = True


def test_purge_scoped_to_failing_mesh_not_single_device_engines():
    """The satellite bugfix pin: a mesh failure purges ONLY parallel
    engines whose device set intersects the suspects; single-device
    engines (and disjoint survivor-subset engines) stay cached.  A
    single-device breaker open with mesh-ft active likewise leaves the
    parallel cache alone — mesh engines are purged at THEIR failure
    site."""
    sup = DeviceSupervisor(op_timeout_s=5.0, breaker_failure_threshold=1)
    ft = MeshFtController()
    opt = GoalOptimizer(
        config=CFG, parallel_mode="sharded", supervisor=sup, mesh_ft=ft,
    )
    single, wide, narrow = _DummyEngine(), _DummyEngine(), _DummyEngine()
    opt._engines[("shape", "cfg")] = single
    opt._parallel_engines[("shape", "cfg", (0, 1, 2, 3, 4, 5, 6, 7))] = wide
    opt._parallel_engines[("shape", "cfg", (0, 1, 2, 3))] = narrow
    opt._purge_parallel_for_mesh_failure((5,), [0, 1, 2, 3, 4, 5, 6, 7])
    assert wide.released and not narrow.released and not single.released
    assert ("shape", "cfg", (0, 1, 2, 3)) in opt._parallel_engines
    assert ("shape", "cfg") in opt._engines
    # single-device breaker opens: only _engines dropped (ft active)
    sup.breaker.record_failure()
    assert sup.breaker.state is BreakerState.OPEN
    opt._maybe_purge_after_open()
    assert single.released and not opt._engines
    assert ("shape", "cfg", (0, 1, 2, 3)) in opt._parallel_engines
    # with mesh-ft disabled the mesh rides the single-device breaker, so
    # the pre-FT purge-everything behavior is preserved
    opt._mesh_ft = MeshFtController(enabled=False)
    opt._breaker_epoch = sup.open_epoch - 1  # simulate a new open epoch
    opt._maybe_purge_after_open()
    assert narrow.released and not opt._parallel_engines


# ------------------------------------------------- optimizer FT wiring


def test_goal_optimizer_default_mesh_ft_wiring():
    # supervised mesh mode: a default controller appears (checkpoint off)
    sup = DeviceSupervisor(op_timeout_s=5.0)
    opt = GoalOptimizer(config=CFG, parallel_mode="sharded", supervisor=sup)
    assert opt._mesh_ft is not None and opt._mesh_ft.enabled
    assert opt._mesh_ft.checkpoint_every_slices == 0
    # single-device mode carries none — zero behavior change
    assert GoalOptimizer(config=CFG, supervisor=sup)._mesh_ft is None
    # unsupervised mesh mode: no supervisor seam to ride, none built
    assert GoalOptimizer(config=CFG, parallel_mode="sharded")._mesh_ft is None


def test_config_mesh_ft_accessor_and_validation():
    from cruise_control_tpu.config import ConfigException, CruiseControlConfig

    c = CruiseControlConfig({
        "tpu.parallel.mode": "sharded",
        "tpu.mesh.ft.checkpoint.every.slices": 2,
    })
    ft = c.mesh_ft_controller()
    assert ft is not None and ft.enabled and ft.checkpoint_every_slices == 2
    assert CruiseControlConfig({}).mesh_ft_controller() is None  # single
    off = CruiseControlConfig({
        "tpu.parallel.mode": "sharded", "tpu.mesh.ft.enabled": False,
    }).mesh_ft_controller()
    assert off is not None and not off.enabled
    with pytest.raises(ConfigException):
        CruiseControlConfig({"tpu.mesh.ft.checkpoint.every.slices": -1})


def test_mesh_degraded_anomaly_and_facade_detector():
    from cruise_control_tpu.detector.anomalies import AnomalyType, MeshDegraded
    from cruise_control_tpu.service.facade import CruiseControl

    a = MeshDegraded(
        lost_devices=[6], from_width=8, to_width=4,
        failure_class="device_lost", episode=1,
    )
    assert a.anomaly_type is AnomalyType.MESH_DEGRADED
    assert a.fixable is False  # alert-only: the width ladder IS the fix
    assert "8->4" in a.description() and "device_lost" in a.description()
    # the facade detector drains the controller's once-per-episode event
    ft = MeshFtController()
    stub = types.SimpleNamespace(
        optimizer=types.SimpleNamespace(_mesh_ft=ft)
    )
    assert CruiseControl._detect_mesh_degraded(stub) is None
    ft.note_degrade(lost=(6,), from_width=8, to_width=4,
                    failure_class="device_lost")
    anomaly = CruiseControl._detect_mesh_degraded(stub)
    assert isinstance(anomaly, MeshDegraded)
    assert anomaly.lost_devices == [6] and anomaly.to_width == 4
    assert CruiseControl._detect_mesh_degraded(stub) is None  # drained
    # no controller (single-device mode): detector is a no-op
    none_stub = types.SimpleNamespace(optimizer=types.SimpleNamespace())
    assert CruiseControl._detect_mesh_degraded(none_stub) is None


# --------------------------------------------------- the acceptance pin


@pytest.mark.slow
def test_optimizer_degrade_and_resume_ladder(mesh_state):
    """Device 6 dies at the second slice boundary of a supervised sharded
    anneal: the ladder attributes the loss, opens the WIDTH-8 breaker
    (never the single-device one), rebuilds over the 4 survivors, resumes
    from the last carry checkpoint, and the final placements byte-equal a
    clean run's — with exactly one MESH_DEGRADED event armed."""
    reg = SensorRegistry()
    sup = DeviceSupervisor(
        op_timeout_s=120.0, max_retries=0, probe_timeout_s=10.0,
        sensors=reg,
    )
    ft = MeshFtController(checkpoint_every_slices=1, sensors=reg)
    opt = GoalOptimizer(
        config=CFG, parallel_mode="sharded", supervisor=sup, mesh_ft=ft,
        sensors=reg,
    )
    clean = GoalOptimizer(config=CFG, parallel_mode="sharded").optimize(mesh_state)

    LOST = 6
    tripped = threading.Event()
    boundary = {"n": 0}

    def chk():
        boundary["n"] += 1
        if boundary["n"] == 2:
            tripped.set()
            raise faults.device_lost_error("mesh.run", LOST)

    def probe_effect(op, fn, args, kwargs):
        if tripped.is_set() and getattr(args[0], "id", None) == LOST:
            raise faults.device_lost_error(op, LOST)
        return fn(*args, **kwargs)

    with faults.device_fault(
        probe_effect, ops=(faults.DEVICE_PROBE_OP,)
    ), segmented_execution(SegmentContext(0.0, chk)):
        result = opt.optimize(mesh_state)

    assert not result.degraded, "the ladder must serve from the mesh"
    rec = next(h for h in reversed(result.history) if h.get("mesh_ft"))
    assert rec["lost_devices"] == [LOST]
    assert rec["width"] == 4 and rec["full_width"] == 8
    assert rec["resumed"] is True and rec["resumed_from_round"] >= 1
    timing = next(
        h for h in result.history if h.get("timing") and h.get("segmented")
    )
    assert timing["resumed_from_round"] == rec["resumed_from_round"]
    assert timing["mesh_shape"] == [1, 4]
    # byte parity with the clean run: width-independent draws + exact
    # carry restore means the interrupted anneal loses NOTHING
    assert _same(clean.state_after, result.state_after)
    assert float(clean.objective_after) == float(result.objective_after)
    # one episode, one event, per-width breakers scoped correctly
    assert ft.episodes == 1 and ft.episode_open
    event = ft.poll_event()
    assert event is not None and event["failure_class"] == "device_lost"
    assert event["from_width"] == 8 and event["to_width"] == 4
    assert ft.poll_event() is None
    snap = ft.state_json()
    assert snap["breakers"]["8"]["state"] == "open"
    assert snap["breakers"]["4"]["state"] == "closed"
    assert sup.breaker.state is BreakerState.CLOSED and sup.available()
    # the width-8 engine (touching the lost chip) was purged; the
    # survivor-width engine stays cached for the next request
    cached_ids = [k[2] for k in opt._parallel_engines]
    assert all(LOST not in ids for ids in cached_ids)
    assert any(len(ids) == 4 for ids in cached_ids)
    # sensors: the resume and the attributed loss are both counted
    assert reg.get("analyzer.mesh-ft.resumes").count == 1
    assert reg.get("analyzer.mesh-ft.device-lost").count == 1
    assert reg.get("analyzer.mesh-ft.active-width").snapshot()["value"] == 4
