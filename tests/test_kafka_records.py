"""Record-batch v2 + Produce/Fetch/ListOffsets + metric-stream transports.

Covers the data plane the reference runs over `__CruiseControlMetrics`
(reporter producer -> topic -> sampler consumer) end to end over real
sockets against the fake wire-protocol cluster.
"""

import numpy as np

from cruise_control_tpu.kafka import KafkaAdminClient
from cruise_control_tpu.kafka.records import (
    Record,
    crc32c,
    decode_batches,
    encode_batch,
    read_zigzag,
    write_zigzag,
)
from cruise_control_tpu.kafka.transport import (
    KafkaMetricsConsumer,
    KafkaMetricsTransport,
)
from cruise_control_tpu.testing.fake_kafka import FakeKafkaCluster


def test_crc32c_check_value():
    # the canonical CRC-32C check vector
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0


def test_zigzag_roundtrip():
    for v in (0, 1, -1, 63, -64, 127, -128, 2**31, -(2**31), 10**15):
        out = bytearray()
        write_zigzag(out, v)
        got, off = read_zigzag(out, 0)
        assert got == v and off == len(out)


def test_batch_roundtrip():
    records = [(None, b"value-%d" % i) for i in range(10)] + [(b"key", b"v")]
    batch = encode_batch(records, base_offset=100, base_timestamp_ms=5000)
    out = decode_batches(batch)
    assert len(out) == 11
    assert out[0] == Record(offset=100, timestamp_ms=5000, key=None, value=b"value-0")
    assert out[-1].key == b"key" and out[-1].offset == 110
    # concatenated batches + trailing partial are handled
    two = batch + encode_batch([(None, b"x")], base_offset=111) + batch[:20]
    assert len(decode_batches(two)) == 12
    # null-value (tombstone) records decode without poisoning the cursor
    import struct as _struct
    from cruise_control_tpu.kafka.records import write_zigzag

    rec = bytearray()
    rec.append(0)
    write_zigzag(rec, 0)   # ts delta
    write_zigzag(rec, 0)   # offset delta
    write_zigzag(rec, 3)
    rec += b"key"
    write_zigzag(rec, -1)  # NULL value
    write_zigzag(rec, 0)   # headers
    body = bytearray()
    write_zigzag(body, len(rec))
    body += rec
    post = _struct.pack(">hiqqqhii", 0, 0, 7, 7, -1, -1, -1, 1) + bytes(body)
    from cruise_control_tpu.kafka.records import crc32c as _crc
    tomb = (_struct.pack(">qii", 5, 4 + 1 + 4 + len(post), -1) + b"\x02"
            + _struct.pack(">I", _crc(post)) + post)
    [t] = decode_batches(tomb)
    assert t.key == b"key" and t.value == b"" and t.offset == 5

    # corrupted CRC rejected
    bad = bytearray(batch)
    bad[30] ^= 0xFF
    try:
        decode_batches(bytes(bad))
        raise AssertionError("expected CRC failure")
    except ValueError:
        pass


def _cluster():
    return FakeKafkaCluster(
        brokers={i: {"rack": f"r{i%2}"} for i in range(3)},
        topics={
            "__CruiseControlMetrics": [
                {"partition": p, "leader": p % 3, "replicas": [p % 3]}
                for p in range(4)
            ],
        },
    ).start()


def test_produce_fetch_over_sockets():
    cluster = _cluster()
    client = KafkaAdminClient(cluster.bootstrap(), timeout_s=5.0)
    try:
        tr = KafkaMetricsTransport(client, flush_every=10_000)
        for i in range(25):
            tr.send(b"payload-%d" % i)
        tr.flush()
        consumer = KafkaMetricsConsumer(client)
        values = consumer.poll_records()
        assert sorted(values) == sorted(b"payload-%d" % i for i in range(25))
        # nothing new -> empty poll; new sends appear on the next poll
        assert consumer.poll_records() == []
        tr.send(b"late")
        tr.flush()
        assert consumer.poll_records() == [b"late"]
    finally:
        client.close()
        cluster.stop()


def test_reporter_to_sampler_loop_over_kafka():
    """The COMPLETE reference loop over wire-protocol sockets: metrics
    reporter -> produce -> __CruiseControlMetrics -> consumer ->
    reporter-sampler (native columnar path) -> partition samples."""
    from cruise_control_tpu.monitor.reporter_sampler import (
        CruiseControlMetricsReporterSampler,
    )
    from cruise_control_tpu.reporter.metrics import (
        BrokerMetric,
        MetricSerde,
        MetricType,
        PartitionMetric,
        TopicMetric,
    )
    from cruise_control_tpu.testing.synthetic import synthetic_topology

    cluster = _cluster()
    client = KafkaAdminClient(cluster.bootstrap(), timeout_s=5.0)
    try:
        topo = synthetic_topology(num_brokers=3, topics={"T0": 6}, seed=2)
        tr = KafkaMetricsTransport(client, flush_every=10_000)
        for b in range(3):
            tr.send(MetricSerde.serialize(
                BrokerMetric(MetricType.BROKER_CPU_UTIL, 1000, b, 50.0)))
            tr.send(MetricSerde.serialize(BrokerMetric(
                MetricType.BROKER_PRODUCE_REQUEST_RATE, 1000, b, 9.0)))
            tr.send(MetricSerde.serialize(
                TopicMetric(MetricType.TOPIC_BYTES_IN, 1000, b, 1e5, topic="T0")))
            tr.send(MetricSerde.serialize(
                TopicMetric(MetricType.TOPIC_BYTES_OUT, 1000, b, 2e5, topic="T0")))
        for p in topo.partitions:
            tr.send(MetricSerde.serialize(PartitionMetric(
                MetricType.PARTITION_SIZE, 1000, p.leader, 1e6,
                topic=p.topic, partition=p.partition)))
        tr.flush()

        consumer = KafkaMetricsConsumer(client)
        sampler = CruiseControlMetricsReporterSampler(consumer, lambda: topo)
        result = sampler.get_samples([], 0, 2000)
        assert len(result.partition_samples) == 6
        assert len(result.broker_samples) == 3
        vals = np.asarray(result.partition_samples[0].values, float)
        assert vals.sum() > 0
    finally:
        client.close()
        cluster.stop()


def test_kafka_sample_store_warm_restart():
    """Samples persisted to the Kafka store topics replay into a FRESH
    store instance — the reference KafkaSampleStore/SampleLoadingTask warm
    restart (KafkaSampleStore.java:117-128)."""
    from cruise_control_tpu.kafka.sample_store import KafkaSampleStore
    from cruise_control_tpu.monitor.sampling import (
        BrokerEntity,
        MetricSample,
        PartitionEntity,
        SamplingResult,
    )

    cluster = _cluster()
    client = KafkaAdminClient(cluster.bootstrap(), timeout_s=5.0)
    try:
        # old process interned {alpha: 0}; new process interns {alpha: 7} —
        # replay must follow the NAME, not the stale dense id
        store = KafkaSampleStore(
            client, topic_name_fn={0: "alpha"}.__getitem__,
        )
        for w in range(3):
            t = w * 1000 + 500
            store.store(SamplingResult(
                partition_samples=[
                    MetricSample(PartitionEntity(0, p), t,
                                 np.arange(4, dtype=np.float32) + p + w)
                    for p in range(5)
                ],
                broker_samples=[
                    MetricSample(BrokerEntity(b), t,
                                 np.full(4, float(b), np.float32))
                    for b in range(2)
                ],
            ))
        # "restart": a brand-new store over a brand-new client
        client2 = KafkaAdminClient(cluster.bootstrap(), timeout_s=5.0)
        try:
            fresh = KafkaSampleStore(
                client2, topic_id_fn={"alpha": 7}.__getitem__,
            )
            replayed = fresh.load()
            assert len(replayed) == 3  # one result per sample time
            total_p = sum(len(r.partition_samples) for r in replayed)
            total_b = sum(len(r.broker_samples) for r in replayed)
            assert total_p == 15 and total_b == 6
            assert all(
                s.entity.topic == 7
                for r in replayed for s in r.partition_samples
            )
            s0 = min(
                (s for r in replayed for s in r.partition_samples),
                key=lambda s: (s.time_ms, s.entity.partition),
            )
            # stored 4-wide; replay zero-pads to the live metric-def width
            np.testing.assert_allclose(s0.values[:4], [0.0, 1.0, 2.0, 3.0])
            assert not s0.values[4:].any()
        finally:
            client2.close()
    finally:
        client.close()
        cluster.stop()


def test_kafka_sample_store_load_drains_past_one_fetch_round(monkeypatch):
    """load() must replay the WHOLE persisted history, not one Fetch round —
    the reference SampleLoadingTask consumes to the log end
    (KafkaSampleStore.java:117-128).  A tiny per-fetch byte cap forces many
    rounds; a single poll_records() call would silently truncate."""
    import cruise_control_tpu.kafka.sample_store as ss
    from cruise_control_tpu.monitor.sampling import (
        MetricSample,
        PartitionEntity,
        SamplingResult,
    )

    class TinyFetchConsumer(KafkaMetricsConsumer):
        def __init__(self, client, topic):
            super().__init__(client, topic, max_bytes_per_fetch=512)

    monkeypatch.setattr(ss, "KafkaMetricsConsumer", TinyFetchConsumer)

    cluster = _cluster()
    client = KafkaAdminClient(cluster.bootstrap(), timeout_s=5.0)
    try:
        store = ss.KafkaSampleStore(client, topic_name_fn=lambda _t: "alpha")
        n_windows, per_window = 10, 8
        for w in range(n_windows):
            store.store(SamplingResult(
                partition_samples=[
                    MetricSample(PartitionEntity(0, p), w * 1000 + 500,
                                 np.full(4, float(w * per_window + p), np.float32))
                    for p in range(per_window)
                ],
                broker_samples=[],
            ))
        fresh = ss.KafkaSampleStore(client, topic_id_fn=lambda _n: 0)
        replayed = fresh.load()
        assert sum(len(r.partition_samples) for r in replayed) == n_windows * per_window
        assert len(replayed) == n_windows
    finally:
        client.close()
        cluster.stop()


def test_sample_store_replays_pre_extension_vector_width():
    """Samples persisted BEFORE a metric-def extension (e.g. the 36 -> 56
    broker percentile additions) must replay into the wider current def:
    short vectors zero-pad, longer ones truncate — a warm restart across
    an upgrade must not lose the persisted history (reference
    SampleLoadingTask warm restart)."""
    import numpy as np

    from cruise_control_tpu.kafka.sample_store import KafkaSampleStore
    from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF

    store = KafkaSampleStore.__new__(KafkaSampleStore)  # no cluster needed
    store.topic_id_fn = {"T0": 0}.__getitem__
    store.metric_def = KAFKA_METRIC_DEF
    m = KAFKA_METRIC_DEF.num_metrics
    old = np.arange(36, dtype=np.float32)  # pre-extension width
    s = store._unpack(store._pack(0, 0, 3, 1234, "T0", old))
    assert s.values.shape == (m,)
    assert np.all(s.values[:36] == old) and np.all(s.values[36:] == 0.0)
    long = np.arange(m + 7, dtype=np.float32)  # hypothetical future shrink
    s2 = store._unpack(store._pack(1, 5, 0, 99, "b", long))
    assert s2.values.shape == (m,) and np.all(s2.values == long[:m])
