"""SLO registry tests (common/slo.py): burn-rate windows, once-per-
episode alerting on injected clocks, the sensor/exposition surface, and
the GET /slo endpoint."""

import json
import urllib.request

import pytest

from cruise_control_tpu.common.exposition import parse_exposition, prometheus_text
from cruise_control_tpu.common.sensors import SensorRegistry
from cruise_control_tpu.common.slo import SloRegistry, SloSpec


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _registry(clock, *, sink=None, sensors=None, threshold=2.0):
    return SloRegistry(
        fast_window_s=60.0,
        slow_window_s=600.0,
        burn_threshold=threshold,
        clock=clock,
        anomaly_sink=sink,
        sensors=sensors,
    )


# ----------------------------------------------------------------------
# burn-rate math
# ----------------------------------------------------------------------


def test_burn_rate_is_bad_fraction_over_budget():
    clock = Clock()
    reg = _registry(clock)
    # objective 0.9 -> error budget 0.1: 1 bad in 10 = burn 1.0
    reg.register(SloSpec(name="s", description="d", objective=0.9))
    for i in range(9):
        reg.record("s", True)
    reg.record("s", False)
    (state,) = reg.tick()
    assert state["fastBurnRate"] == pytest.approx(1.0)
    assert state["slowBurnRate"] == pytest.approx(1.0)
    assert state["compliance"] == pytest.approx(0.9)
    assert not state["alerting"]


def test_windows_age_out_events():
    clock = Clock()
    reg = _registry(clock)
    reg.register(SloSpec(name="s", description="d", objective=0.9))
    reg.record("s", False)
    clock.t += 120.0  # past the fast window, inside the slow one
    reg.record("s", True)
    (state,) = reg.tick()
    assert state["fastBurnRate"] == 0.0  # only the good sample is recent
    assert state["slowBurnRate"] > 0.0


def test_no_samples_is_zero_burn_and_none_compliance():
    reg = _registry(Clock())
    reg.register(SloSpec(name="s", description="d", objective=0.99))
    (state,) = reg.tick()
    assert state["fastBurnRate"] == 0.0
    assert state["compliance"] is None


def test_unknown_record_ignored_and_duplicate_register_rejected():
    reg = _registry(Clock())
    reg.register(SloSpec(name="s", description="d", objective=0.99))
    reg.record("nope", False)  # a producer without a configured SLO
    with pytest.raises(ValueError, match="already registered"):
        reg.register(SloSpec(name="s", description="d", objective=0.5))
    with pytest.raises(ValueError, match="objective"):
        SloSpec(name="bad", description="d", objective=1.0)


def test_probe_none_means_no_data():
    clock = Clock()
    reg = _registry(clock)
    verdicts = iter([None, True, False])
    reg.register(SloSpec(
        name="s", description="d", objective=0.9,
        probe=lambda: next(verdicts),
    ))
    assert reg.tick()[0]["samples"] == 0  # None: skipped, not bad
    assert reg.tick()[0]["samples"] == 1
    state = reg.tick()[0]
    assert state["samples"] == 2 and state["badSamples"] == 1


def test_broken_probe_is_no_data_not_a_breach():
    reg = _registry(Clock())
    reg.register(SloSpec(
        name="s", description="d", objective=0.9,
        probe=lambda: 1 / 0,
    ))
    assert reg.tick()[0]["samples"] == 0


# ----------------------------------------------------------------------
# episodes: the acceptance story
# ----------------------------------------------------------------------


def test_sustained_breach_fires_exactly_once_per_episode():
    """An injected sustained freshness-style breach fires ONE SLO_BURN
    for the whole episode; recovery re-arms; a second breach fires a
    second anomaly — twice across two episodes, never more."""
    from cruise_control_tpu.detector.anomalies import AnomalyType, SloBurn

    clock = Clock()
    fired = []
    reg = _registry(clock, sink=fired.append)
    breaching = {"on": True}
    reg.register(SloSpec(
        name="proposal-freshness", description="d", objective=0.9,
        probe=lambda: not breaching["on"],
    ))
    # sustained breach: every tick for 3 fast windows samples bad
    for _ in range(30):
        reg.tick()
        clock.t += 6.0
    assert len(fired) == 1, "one episode must fire exactly one anomaly"
    anomaly = fired[0]
    assert isinstance(anomaly, SloBurn)
    assert anomaly.anomaly_type is AnomalyType.SLO_BURN
    assert anomaly.slo == "proposal-freshness"
    assert anomaly.fast_burn_rate >= 2.0
    assert not anomaly.fixable
    # recovery: good samples push the fast burn under the threshold
    breaching["on"] = False
    for _ in range(30):
        reg.tick()
        clock.t += 6.0
    (state,) = reg.tick()
    assert not state["alerting"]
    assert len(fired) == 1
    # second sustained breach = second episode = second anomaly
    breaching["on"] = True
    for _ in range(30):
        reg.tick()
        clock.t += 6.0
    assert len(fired) == 2
    assert fired[1].episode == 2


def test_blip_does_not_alert():
    """One bad sample in a sea of good must not page: the slow window
    exists to absorb blips."""
    clock = Clock()
    fired = []
    reg = _registry(clock, sink=fired.append, threshold=3.0)
    reg.register(SloSpec(name="s", description="d", objective=0.9))
    for i in range(60):
        reg.record("s", i != 30)  # one bad sample mid-stream
        reg.tick()
        clock.t += 6.0
    assert fired == []


def test_alert_failure_does_not_break_evaluation():
    clock = Clock()

    def sink(_):
        raise RuntimeError("notifier down")

    reg = _registry(clock, sink=sink)
    reg.register(SloSpec(
        name="s", description="d", objective=0.9, probe=lambda: False,
    ))
    for _ in range(20):
        reg.tick()
        clock.t += 6.0
    assert reg.tick()[0]["alerting"] is True  # evaluation survived


# ----------------------------------------------------------------------
# sensor / exposition surface
# ----------------------------------------------------------------------


def test_burn_gauges_render_in_lint_clean_exposition():
    clock = Clock()
    sensors = SensorRegistry()
    reg = _registry(clock, sensors=sensors)
    reg.register(SloSpec(name="pub", description="d", objective=0.9))
    reg.register(SloSpec(name="fresh", description="d", objective=0.99))
    for _ in range(10):
        reg.record("pub", False)
    reg.tick()
    body = prometheus_text(sensors)
    families = parse_exposition(body)
    burn = families["cruisecontrol_slo_burn_rate"]["samples"]
    by_label = {
        (l["slo"], l["window"]): v for _n, l, v in burn
    }
    assert by_label[("pub", "fast")] == pytest.approx(10.0)  # 100%/10% budget
    assert by_label[("fresh", "fast")] == 0.0
    assert "cruisecontrol_slo_compliance" in families
    assert "cruisecontrol_slo_evaluations_total" in families
    assert "cruisecontrol_slo_bad_samples_total" in families


def test_scheduler_feeds_urgent_queue_wait():
    from cruise_control_tpu.fleet.scheduler import DeviceScheduler, WorkClass

    clock = Clock()
    reg = _registry(clock)
    reg.register(SloSpec(
        name="urgent-queue-wait", description="d", objective=0.99,
    ))
    sched = DeviceScheduler(slice_budget_s=0.5)
    sched.slo_registry = reg
    assert sched.run(WorkClass.URGENT, lambda: 42) == 42
    sched.run(WorkClass.BACKGROUND, lambda: None)  # background: no sample
    (state,) = reg.tick()
    assert state["samples"] == 1 and state["badSamples"] == 0


# ----------------------------------------------------------------------
# service integration: /slo, /fleet rollup, facade wiring
# ----------------------------------------------------------------------


def test_service_slo_surface():
    """The full wiring: a simulated service registers the SLO set, the
    cold-start sample lands on the first proposal, GET /slo serves the
    registry, /fleet carries the burn summary, and the exposition (with
    the slo gauges) lints clean over HTTP."""
    from cruise_control_tpu.service.main import build_simulated_service
    from cruise_control_tpu.service.progress import OperationProgress

    app, fetcher, admin, sampler = build_simulated_service(seed=11)
    app.start()
    try:
        cc = app.cc
        assert cc.slo_registry is not None
        assert cc.slo_registry.names() == [
            "cold-start", "proposal-freshness", "streaming-publish",
        ]
        cc.proposals(OperationProgress())
        state = {s["name"]: s for s in cc.slo_registry.tick()}
        assert state["cold-start"]["samples"] == 1
        # a second proposal must not re-record the one-shot sample
        cc.proposals(OperationProgress(), ignore_cache=True)
        state = {s["name"]: s for s in cc.slo_registry.tick()}
        assert state["cold-start"]["samples"] == 1
        # the freshness probe sees the cached proposal: a good sample
        assert state["proposal-freshness"]["badSamples"] == 0
        base = f"http://{app.host}:{app.port}{app.prefix}"
        with urllib.request.urlopen(base + "/slo", timeout=30) as r:
            body = json.loads(r.read())
        assert body["numClusters"] == 1
        slos = {s["name"] for s in body["clusters"]["default"]["slos"]}
        assert "proposal-freshness" in slos
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            families = parse_exposition(r.read().decode())
        assert "cruisecontrol_slo_burn_rate" in families
        # /fleet rollup (single-cluster synthetic entry) carries the
        # per-SLO burn summary
        with urllib.request.urlopen(base + "/fleet", timeout=30) as r:
            fleet = json.loads(r.read())
        assert "proposal-freshness" in fleet["clusters"]["default"]["slo"]
    finally:
        app.stop()


def test_slo_disabled_leaves_no_registry():
    from cruise_control_tpu.config.app_config import CruiseControlConfig
    from cruise_control_tpu.service.main import build_simulated_service

    app, *_ = build_simulated_service(
        CruiseControlConfig({
            "webserver.http.port": 0, "slo.enabled": False,
        }),
        seed=12,
    )
    try:
        assert app.cc.slo_registry is None
        assert app.cc.sensors.get("slo.burn-rate") is None
    finally:
        app.stop()
