"""Optimizer engine tests.

Modeled on the reference's analyzer test strategy (SURVEY §4): deterministic
fixtures + randomized clusters, verified through invariants rather than
golden proposals (reference analyzer/OptimizationVerifier.java checks:
GOAL_VIOLATION, BROKEN_BROKERS, NEW_BROKERS, REGRESSION).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer import (
    DEFAULT_CHAIN,
    Engine,
    GoalOptimizer,
    OptimizationOptions,
    OptimizerConfig,
)
from cruise_control_tpu.models.aggregates import compute_aggregates
from cruise_control_tpu.models.state import validate
from cruise_control_tpu.testing.fixtures import (
    RandomClusterSpec,
    dead_broker_cluster,
    rack_violated_cluster,
    random_cluster,
    small_cluster,
)

FAST = OptimizerConfig(
    num_candidates=256, leadership_candidates=64, steps_per_round=24, num_rounds=3, seed=1
)


@pytest.fixture(scope="module")
def small_result():
    return GoalOptimizer(config=FAST).optimize(small_cluster())


def test_objective_improves(small_result):
    assert small_result.objective_after < small_result.objective_before
    assert small_result.balancedness_after >= small_result.balancedness_before


def test_final_state_valid(small_result):
    assert validate(small_result.state_after) == []


def test_proposals_match_diff(small_result):
    res = small_result
    before, after = res.state_before, res.state_after
    n_changed_parts = len(
        np.unique(
            np.asarray(before.replica_partition)[
                np.asarray(before.replica_valid)
                & (
                    (np.asarray(before.replica_broker) != np.asarray(after.replica_broker))
                    | (
                        np.asarray(before.replica_is_leader)
                        != np.asarray(after.replica_is_leader)
                    )
                )
            ]
        )
    )
    assert len(res.proposals) == n_changed_parts
    for p in res.proposals:
        # replica count preserved, leader heads the new replica list
        assert len(p.old_replicas) == len(p.new_replicas)
        if p.new_replicas:
            assert p.new_replicas[0] == p.new_leader


def test_rack_violation_fixed():
    res = GoalOptimizer(config=FAST).optimize(rack_violated_cluster())
    i = res.goal_names.index("RackAwareGoal")
    assert res.violations_before[i] > 0
    assert res.violations_after[i] == 0


def test_dead_broker_evacuated():
    res = GoalOptimizer(config=FAST).optimize(dead_broker_cluster())
    after = res.state_after
    on_dead = (
        np.asarray(after.replica_valid)
        & ~np.asarray(after.broker_alive)[np.asarray(after.replica_broker)]
    )
    assert not on_dead.any(), "BROKEN_BROKERS: replicas remain on dead broker"


def test_incremental_aggregates_stay_consistent():
    """The scatter-updated carry must equal a from-scratch aggregation.

    This pins the delta engine's bookkeeping against compute_aggregates —
    the TPU analog of reference ClusterModel.sanityCheck (ClusterModel.java:1081).
    """
    state = random_cluster(RandomClusterSpec(num_brokers=12, num_partitions=200, skew=1.0), seed=3)
    eng = Engine(state, DEFAULT_CHAIN, config=FAST)
    carry = eng.init_carry(jax.random.PRNGKey(0))
    temps = jnp.full((24,), 0.0, jnp.float32)
    carry, stats = eng._scan(eng.statics, carry, temps)
    assert int(stats["accepted"].sum()) > 0

    fresh = compute_aggregates(eng.carry_to_state(carry))
    np.testing.assert_allclose(
        np.asarray(carry.broker_load), np.asarray(fresh.broker_load), rtol=1e-4, atol=1e-2
    )
    np.testing.assert_array_equal(
        np.asarray(carry.broker_replica_count), np.asarray(fresh.broker_replica_count)
    )
    np.testing.assert_array_equal(
        np.asarray(carry.broker_leader_count), np.asarray(fresh.broker_leader_count)
    )
    np.testing.assert_array_equal(
        np.asarray(carry.part_rack_count), np.asarray(fresh.part_rack_count)
    )
    np.testing.assert_array_equal(
        np.asarray(carry.broker_topic_count), np.asarray(fresh.broker_topic_count)
    )
    np.testing.assert_allclose(
        np.asarray(carry.broker_potential_nw_out),
        np.asarray(fresh.broker_potential_nw_out),
        rtol=1e-4,
        atol=1e-2,
    )
    np.testing.assert_allclose(
        np.asarray(carry.broker_leader_bytes_in),
        np.asarray(fresh.broker_leader_bytes_in),
        rtol=1e-4,
        atol=1e-2,
    )
    np.testing.assert_allclose(
        np.asarray(carry.disk_load), np.asarray(fresh.disk_load), rtol=1e-4, atol=1e-2
    )


def test_greedy_never_worsens_objective():
    """At T=0 every accepted move must strictly improve the SA objective
    (REGRESSION check, reference AbstractGoal.java:92-101)."""
    state = random_cluster(RandomClusterSpec(num_brokers=10, num_partitions=150, skew=1.5), seed=5)
    chain = DEFAULT_CHAIN
    eng = Engine(state, chain, config=FAST)
    carry = eng.init_carry(jax.random.PRNGKey(2))
    obj_prev, _, _ = chain.evaluate(state)
    obj_prev = float(obj_prev)
    for _ in range(4):
        temps = jnp.full((8,), 0.0, jnp.float32)
        carry, _ = eng._scan(eng.statics, carry, temps)
        obj, _, _ = chain.evaluate(eng.carry_to_state(carry))
        assert float(obj) <= obj_prev + max(1e-5, abs(obj_prev) * 1e-3)
        obj_prev = float(obj)


def test_excluded_topics_do_not_move():
    state = random_cluster(RandomClusterSpec(num_brokers=8, num_partitions=100, skew=1.5), seed=7)
    T = state.shape.num_topics
    excluded = np.zeros(T, bool)
    excluded[:T // 2] = True
    opts = OptimizationOptions(excluded_topics=excluded)
    res = GoalOptimizer(config=FAST).optimize(state, options=opts)
    before, after = res.state_before, res.state_after
    moved = np.asarray(before.replica_broker) != np.asarray(after.replica_broker)
    moved &= np.asarray(before.replica_valid)
    bad = moved & excluded[np.asarray(before.replica_topic)]
    assert not bad.any(), "replica of an excluded topic was moved"


def test_excluded_brokers_receive_nothing():
    state = random_cluster(RandomClusterSpec(num_brokers=8, num_partitions=100, skew=1.5), seed=9)
    B = state.shape.B
    excluded = np.zeros(B, bool)
    excluded[0] = True
    opts = OptimizationOptions(excluded_brokers_for_replica_move=excluded)
    res = GoalOptimizer(config=FAST).optimize(state, options=opts)
    before, after = res.state_before, res.state_after
    moved = (
        np.asarray(before.replica_broker) != np.asarray(after.replica_broker)
    ) & np.asarray(before.replica_valid)
    assert not (np.asarray(after.replica_broker)[moved] == 0).any()


def test_tpu_beats_or_matches_greedy_oracle():
    """SURVEY §7 hard part (a): the batched annealer must match or beat the
    reference-style sequential greedy on the aggregate weighted objective."""
    from cruise_control_tpu.analyzer.greedy import greedy_optimize

    state = random_cluster(RandomClusterSpec(num_brokers=8, num_partitions=80, skew=1.5), seed=21)
    chain = DEFAULT_CHAIN
    greedy_final = greedy_optimize(
        state, chain, max_moves_per_goal=12, candidate_dests=6, seed=21
    )
    obj_greedy, _, _ = chain.evaluate(greedy_final)

    res = GoalOptimizer(config=FAST).optimize(state)
    assert res.objective_after <= float(obj_greedy) * (1 + 1e-4) + 1e-9


def test_intra_broker_disk_rebalance():
    """rebalance_disk mode: JBOD disks balance WITHOUT any inter-broker
    movement (reference default.intra.broker.goals, AnalyzerConfig.java:236;
    Executor.intraBrokerMoveReplicas:1036)."""
    from cruise_control_tpu.analyzer.goals import DEFAULT_INTRA_BROKER_GOAL_ORDER
    from cruise_control_tpu.analyzer.objective import GoalChain
    from cruise_control_tpu.testing.fixtures import random_cluster_fast

    # random_cluster_fast scatters replicas over random logdirs -> imbalance
    state = random_cluster_fast(
        RandomClusterSpec(
            num_brokers=6, num_partitions=200, disks_per_broker=4, deviation=1.0
        ),
        seed=7,
    )
    chain = GoalChain.from_names(DEFAULT_INTRA_BROKER_GOAL_ORDER)
    obj0, _, _ = chain.evaluate(state)
    opt = GoalOptimizer(
        chain=chain,
        config=OptimizerConfig(
            num_candidates=128, steps_per_round=16, num_rounds=3, intra_broker=True
        ),
    )
    res = opt.optimize(state)
    validate(res.state_after)
    assert res.objective_after < float(obj0)
    # no replica may change broker; all movement is logdir-to-logdir
    before_b = np.asarray(state.replica_broker)
    after_b = np.asarray(res.state_after.replica_broker)
    np.testing.assert_array_equal(before_b, after_b)
    before_l = np.asarray(state.replica_is_leader)
    after_l = np.asarray(res.state_after.replica_is_leader)
    np.testing.assert_array_equal(before_l, after_l)
    assert any(p.disk_moves for p in res.proposals)
    for p in res.proposals:
        assert sorted(p.old_replicas) == sorted(p.new_replicas)


def _rounds(history):
    """Round records only (history also carries ONE timing record)."""
    return [h for h in history if not h.get("timing")]


def test_early_stop_breaks_when_goals_satisfied():
    """A run starting from an already-satisfied cluster MUST early-stop
    (OptimizerConfig.early_stop_violations), and the exit must only ever
    fire with every goal truly satisfied."""
    state = random_cluster(
        RandomClusterSpec(num_brokers=6, num_partitions=60, skew=0.3), seed=3
    )
    cfg = dataclasses.replace(FAST, num_rounds=12, seed=5)
    eng = Engine(state, DEFAULT_CHAIN, config=cfg)
    final, history = eng.run()
    validate(final)
    _, viol, _ = DEFAULT_CHAIN.evaluate(final)
    if any(h.get("early_stop") for h in history):
        assert float(np.max(np.asarray(viol))) <= 1e-6
        assert len(_rounds(history)) < 12
    if float(np.max(np.asarray(viol))) <= 1e-9:
        # second run from the satisfied state: the stop is GUARANTEED on
        # an early round (this pins the feature against regressions that
        # silently disable the gate)
        eng2 = Engine(final, DEFAULT_CHAIN, config=cfg)
        _, history2 = eng2.run()
        assert any(h.get("early_stop") for h in history2)
        assert len(_rounds(history2)) < 12


def test_goal_order_permutations():
    """Reference RandomGoalTest shuffles goal priority order.  Here goal
    priority is encoded as rank-decayed weights, so the WEIGHTED objective
    legitimately depends on order — but each goal's raw violation is a pure
    function of state and must be identical under any permutation, and
    hard goals must outweigh any soft goal regardless of position."""
    names = [
        "RackAwareGoal", "DiskCapacityGoal", "ReplicaDistributionGoal",
        "CpuUsageDistributionGoal", "LeaderReplicaDistributionGoal",
    ]
    state = random_cluster(
        RandomClusterSpec(num_brokers=8, num_partitions=120, skew=1.2), seed=11
    )
    from cruise_control_tpu.analyzer.objective import GoalChain

    rng = np.random.default_rng(4)
    base = None
    for _ in range(3):
        order = list(rng.permutation(names))
        chain = GoalChain.from_names(order)
        _, viol, _ = chain.evaluate(state)
        key = dict(zip(chain.names(), np.asarray(viol).tolist()))
        if base is None:
            base = key
        else:
            for n in names:
                assert abs(key[n] - base[n]) < 1e-6
        # hard goals keep their boost wherever they land in the order
        w = dict(zip(chain.names(), chain.weights))
        soft_max = max(v for n, v in w.items()
                       if n in ("ReplicaDistributionGoal",
                                "CpuUsageDistributionGoal",
                                "LeaderReplicaDistributionGoal"))
        assert w["RackAwareGoal"] > soft_max
        assert w["DiskCapacityGoal"] > soft_max


@pytest.mark.parametrize("seed", [3, 17, 29])
def test_random_self_healing(seed):
    """Reference RandomSelfHealingTest: random clusters with dead brokers
    must evacuate them completely (BROKEN_BROKERS) and stay valid."""
    state = random_cluster(
        RandomClusterSpec(
            num_brokers=10, num_partitions=150, skew=0.8, num_dead_brokers=2
        ),
        seed=seed,
    )
    res = GoalOptimizer(config=FAST).optimize(state)
    after = res.state_after
    validate(after)
    on_dead = (
        np.asarray(after.replica_valid)
        & ~np.asarray(after.broker_alive)[np.asarray(after.replica_broker)]
    )
    assert not on_dead.any(), f"seed {seed}: replicas remain on dead brokers"
    # moved replicas may only land on alive brokers
    moved = (
        np.asarray(state.replica_broker) != np.asarray(after.replica_broker)
    ) & np.asarray(state.replica_valid)
    assert np.asarray(after.broker_alive)[np.asarray(after.replica_broker)[moved]].all()


@pytest.mark.parametrize("fused", [True, False])
def test_engine_precompile_async_swaps_in_compiled_programs(fused):
    """The warm-start pool (daemon threads — a stuck compile must never
    block process exit) compiles every run()-path program from abstract
    shapes, and _fn swaps the executables in; results must match the
    plain-jit path bit-for-bit (same programs, same inputs)."""
    from cruise_control_tpu.analyzer import DEFAULT_CHAIN, Engine, OptimizerConfig
    from cruise_control_tpu.analyzer.engine import _WarmedFn
    from cruise_control_tpu.models.state import validate
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster

    state = random_cluster(
        RandomClusterSpec(num_brokers=8, num_partitions=64, num_racks=4,
                          num_topics=5, skew=1.0),
        seed=0,
    )
    cfg = OptimizerConfig(num_candidates=128, leadership_candidates=32,
                          steps_per_round=4, num_rounds=2, fused_rounds=fused)
    warm = Engine(state, DEFAULT_CHAIN, config=cfg)
    warm.precompile_async()
    final_w, _ = warm.run()
    assert validate(final_w) == []
    names = (
        ("_jit_run_fused", "_jit_init")
        if fused
        else ("_scan", "_jit_init", "_jit_plan", "_jit_round_prep", "_jit_eval")
    )
    for name in names:
        assert isinstance(getattr(warm, name), _WarmedFn), name

    cold = Engine(state, DEFAULT_CHAIN, config=cfg)
    final_c, _ = cold.run()
    np.testing.assert_array_equal(
        np.asarray(final_w.replica_broker), np.asarray(final_c.replica_broker)
    )
    np.testing.assert_array_equal(
        np.asarray(final_w.replica_is_leader), np.asarray(final_c.replica_is_leader)
    )


def test_fused_matches_legacy_round_loop():
    """Tentpole parity pin: fixed seed, T=0 (init_temperature_scale=0) —
    the fused on-device round loop and the legacy Python round loop must
    produce the IDENTICAL accepted-move trajectory (same final placement,
    leadership, and logdirs), the same per-round accept counts and round
    budget (early stop / extra rounds included), and the same final
    objective."""
    state = random_cluster(
        RandomClusterSpec(num_brokers=10, num_partitions=150, skew=1.2), seed=13
    )
    base = dataclasses.replace(
        FAST, num_rounds=4, seed=9, init_temperature_scale=0.0
    )
    eng_f = Engine(
        state, DEFAULT_CHAIN, config=dataclasses.replace(base, fused_rounds=True)
    )
    final_f, hist_f = eng_f.run()
    eng_l = Engine(
        state, DEFAULT_CHAIN, config=dataclasses.replace(base, fused_rounds=False)
    )
    final_l, hist_l = eng_l.run()

    np.testing.assert_array_equal(
        np.asarray(final_f.replica_broker), np.asarray(final_l.replica_broker)
    )
    np.testing.assert_array_equal(
        np.asarray(final_f.replica_is_leader), np.asarray(final_l.replica_is_leader)
    )
    np.testing.assert_array_equal(
        np.asarray(final_f.replica_disk), np.asarray(final_l.replica_disk)
    )
    obj_f, _, _ = DEFAULT_CHAIN.evaluate(final_f)
    obj_l, _, _ = DEFAULT_CHAIN.evaluate(final_l)
    assert float(obj_f) == float(obj_l)

    def key(h):
        return (h["round"], h["accepted"], h.get("early_stop"), h.get("extra"))

    assert [key(h) for h in _rounds(hist_f)] == [key(h) for h in _rounds(hist_l)]


def test_history_timing_split_and_sync_contract():
    """OptimizerResult.history must carry ONE timing record with the
    device/host split; the fused path's contract is O(1) blocking syncs
    during optimization (vs O(num_rounds) legacy) — the assertable form
    of 'the round loop is device-resident'."""
    state = random_cluster(
        RandomClusterSpec(num_brokers=8, num_partitions=100, skew=1.0), seed=17
    )
    res_f = GoalOptimizer(config=FAST).optimize(state)
    timing = [h for h in res_f.history if h.get("timing")]
    assert len(timing) == 1
    t = timing[0]
    assert t["fused"] is True
    assert t["blocking_syncs"] == 1
    assert t["device_s"] >= 0.0 and t["host_extract_s"] >= 0.0

    cfg_l = dataclasses.replace(FAST, fused_rounds=False)
    res_l = GoalOptimizer(config=cfg_l).optimize(state)
    t_l = next(h for h in res_l.history if h.get("timing"))
    assert t_l["fused"] is False
    # per-round sync floor: at least one blocking fetch per executed round
    assert t_l["blocking_syncs"] >= len(_rounds(res_l.history))


def test_optimizer_config_validation():
    """Round-budget knobs are validated in one place; the interaction of
    early_stop_violations with max_extra_rounds resolves identically for
    both round-loop implementations via extra_round_budget."""
    with pytest.raises(ValueError):
        OptimizerConfig(num_rounds=0)
    with pytest.raises(ValueError):
        OptimizerConfig(steps_per_round=0)
    with pytest.raises(ValueError):
        OptimizerConfig(max_extra_rounds=-1)
    # early stop disabled => extra polish rounds disabled with it
    assert OptimizerConfig(early_stop_violations=-1.0).extra_round_budget == 0
    assert (
        OptimizerConfig(early_stop_violations=1e-6, max_extra_rounds=5)
        .extra_round_budget == 5
    )
    # both paths compare against the SAME f32-quantized threshold
    assert OptimizerConfig().early_stop_tol == float(np.float32(1e-6))


# --------------------------------------------------- mixed-precision scoring


def _placement_bits(state):
    return tuple(
        np.asarray(getattr(state, f))
        for f in ("replica_broker", "replica_is_leader", "replica_disk")
    )


def test_score_dtype_validation():
    with pytest.raises(ValueError):
        OptimizerConfig(score_dtype="float16")
    with pytest.raises(ValueError):
        OptimizerConfig(score_dtype="f32")


def test_f32_scoring_pin_is_bit_for_bit():
    """The fp32 fallback pin (analyzer.precision.score.dtype=float32, the
    default): the mixed-precision refactor must leave the default graph
    byte-identical — an explicit float32 config, the implicit default, and
    a bare chain.evaluate all produce bitwise-equal objectives and
    placements."""
    state = small_cluster()
    default = GoalOptimizer(config=FAST).optimize(state)
    explicit = GoalOptimizer(
        config=dataclasses.replace(FAST, score_dtype="float32")
    ).optimize(state)
    for a, b in zip(
        _placement_bits(default.state_after), _placement_bits(explicit.state_after)
    ):
        assert (a == b).all()
    assert np.float32(default.objective_after) == np.float32(
        explicit.objective_after
    )
    # the evaluate() kwarg itself: explicit float32 == no kwarg, bitwise
    obj_a, viol_a, sc_a = DEFAULT_CHAIN.evaluate(state)
    obj_b, viol_b, sc_b = DEFAULT_CHAIN.evaluate(state, score_dtype="float32")
    assert np.asarray(obj_a) == np.asarray(obj_b)
    assert (np.asarray(viol_a) == np.asarray(viol_b)).all()
    assert (np.asarray(sc_a) == np.asarray(sc_b)).all()


def test_bf16_scoring_holds_tolerance_gate():
    """bfloat16 goal-score accumulation must stay a numerics detail: the
    anneal still converges to a valid placement whose final f32-reported
    objective sits within analyzer.precision.tolerance (relative) of the
    f32 reference — the gate that must pass before the low-precision path
    is trusted (violations and reports stay f32 either way)."""
    from cruise_control_tpu.config.app_config import CruiseControlConfig

    tol = CruiseControlConfig({}).get("analyzer.precision.tolerance")
    state = small_cluster()
    f32 = GoalOptimizer(config=FAST).optimize(state)
    bf16 = GoalOptimizer(
        config=dataclasses.replace(FAST, score_dtype="bfloat16")
    ).optimize(state)
    assert validate(bf16.state_after) == []
    assert bf16.objective_after < bf16.objective_before
    ref = float(f32.objective_after)
    assert abs(float(bf16.objective_after) - ref) <= tol * max(abs(ref), 1e-6)
    # goal-chain evaluation of the SAME state: bf16 accumulation error on
    # the weighted sum itself must sit far inside the tolerance band
    obj_f, _, _ = DEFAULT_CHAIN.evaluate(state)
    obj_b, viol_b, _ = DEFAULT_CHAIN.evaluate(state, score_dtype="bfloat16")
    assert viol_b.dtype == jnp.float32  # violations never downcast
    assert abs(float(obj_b) - float(obj_f)) <= tol * max(abs(float(obj_f)), 1e-6)
