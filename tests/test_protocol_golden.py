"""Spec-pinned golden-byte conformance for EVERY wire API we speak.

Round-4 VERDICT: only 2 of 15 APIs had spec-derived golden bytes;
everything else was verified against testing/fake_kafka.py, which shares
an author with the client — circular.  This file removes the circularity:

  * an INDEPENDENT mini-encoder (`i16`/`s`/`arr`/`cs`/... below), written
    directly from the public protocol spec (kafka.apache.org/protocol),
    assembles every request/response frame field by field — it shares no
    code with cruise_control_tpu.kafka.codec;
  * each API in protocol.ALL_APIS + SASL_APIS is pinned in all four
    directions: encode_request, decode_request, encode_response,
    decode_response against those hand-assembled bytes;
  * record-batch v2 bytes are assembled from the spec layout with the CRC
    computed by a second, bit-at-a-time CRC-32C implementation anchored to
    the published check value crc32c("123456789") = 0xE3069283;
  * the SCRAM-SHA-256 exchange replays the RFC 7677 §3 test vector
    (published client/server messages for user "user" / password
    "pencil"), not a self-generated conversation.

No fake_kafka involvement anywhere in this file.

Reference parity: the reference inherits wire correctness from the
official kafka-clients jar (build.gradle dependency;
executor/ExecutorAdminUtils.java:1) and embedded-broker integration tests
(CCKafkaIntegrationTestHarness.java:17); these goldens play that
conformance role for our self-built client.
"""

import struct

import pytest

from cruise_control_tpu.kafka import protocol as proto
from cruise_control_tpu.kafka import records
from cruise_control_tpu.kafka.sasl import SaslCredentials, ScramClient

# --------------------------------------------------------------------------
# independent spec primitives (deliberately NOT cruise_control_tpu.kafka.codec)
# --------------------------------------------------------------------------


def i8(v):
    return struct.pack(">b", v)


def i16(v):
    return struct.pack(">h", v)


def i32(v):
    return struct.pack(">i", v)


def i64(v):
    return struct.pack(">q", v)


def u32(v):
    return struct.pack(">I", v)


def boolean(v):
    return b"\x01" if v else b"\x00"


def s(v):
    """Classic STRING / NULLABLE_STRING: INT16 length (-1 = null)."""
    if v is None:
        return i16(-1)
    return i16(len(v)) + v.encode()


def by(v):
    """Classic BYTES / NULLABLE_BYTES: INT32 length (-1 = null)."""
    if v is None:
        return i32(-1)
    return i32(len(v)) + v


def arr(items):
    """Classic ARRAY: INT32 count (-1 = null); items are pre-encoded bytes."""
    if items is None:
        return i32(-1)
    return i32(len(items)) + b"".join(items)


def uvarint(v):
    out = b""
    while True:
        b7 = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def cs(v):
    """COMPACT_STRING / COMPACT_NULLABLE_STRING: uvarint len+1 (0 = null)."""
    if v is None:
        return uvarint(0)
    return uvarint(len(v.encode()) + 1) + v.encode()


def carr(items):
    """COMPACT_ARRAY: uvarint count+1 (0 = null); items pre-encoded."""
    if items is None:
        return uvarint(0)
    return uvarint(len(items) + 1) + b"".join(items)


TAGS = uvarint(0)  # empty tagged-field buffer

CID = 7
CLIENT = "cc"


def req_header(api):
    """Request header: v1 for classic APIs, v2 (+tag buffer) for flexible
    (KIP-482).  client_id stays a classic nullable string in BOTH."""
    h = i16(api.key) + i16(api.version) + i32(CID) + s(CLIENT)
    return h + TAGS if api.flexible else h


def resp_header(api):
    """Response header: v0 classic, v1 (+tag buffer) flexible."""
    h = i32(CID)
    return h + TAGS if api.flexible else h


def frame(payload):
    return i32(len(payload)) + payload


def check(api, req_body, req_bytes, resp_body, resp_bytes):
    """Pin all four codec directions of one API against spec bytes."""
    req_payload = req_header(api) + req_bytes
    resp_payload = resp_header(api) + resp_bytes
    # client -> broker
    assert proto.encode_request(api, CID, CLIENT, req_body) == frame(req_payload), (
        f"{api.name} v{api.version} request encoding diverges from spec bytes"
    )
    # broker side parse (exercised by real brokers against our frames)
    got_api, got_cid, got_client, got_body = proto.decode_request(req_payload)
    assert (got_api, got_cid, got_client) == (api, CID, CLIENT)
    assert got_body == req_body
    # broker -> client
    assert proto.encode_response(api, CID, resp_body) == frame(resp_payload), (
        f"{api.name} v{api.version} response encoding diverges from spec bytes"
    )
    got_cid, got_body = proto.decode_response(api, resp_payload)
    assert got_cid == CID
    assert got_body == resp_body


# --------------------------------------------------------------------------
# one golden per API — request and response, hand-assembled per the spec
# --------------------------------------------------------------------------


def test_produce_v3():
    check(
        proto.PRODUCE,
        {"transactional_id": None, "acks": -1, "timeout_ms": 30000,
         "topic_data": [{"name": "t", "partition_data": [
             {"index": 0, "records": b"RB"}]}]},
        s(None) + i16(-1) + i32(30000)
        + arr([s("t") + arr([i32(0) + by(b"RB")])]),
        {"responses": [{"name": "t", "partition_responses": [
            {"index": 0, "error_code": 0, "base_offset": 5,
             "log_append_time_ms": -1}]}],
         "throttle_time_ms": 0},
        arr([s("t") + arr([i32(0) + i16(0) + i64(5) + i64(-1)])]) + i32(0),
    )


def test_fetch_v4():
    check(
        proto.FETCH,
        {"replica_id": -1, "max_wait_ms": 500, "min_bytes": 1,
         "max_bytes": 1048576, "isolation_level": 0,
         "topics": [{"topic": "t", "partitions": [
             {"partition": 0, "fetch_offset": 3, "partition_max_bytes": 65536}]}]},
        i32(-1) + i32(500) + i32(1) + i32(1048576) + i8(0)
        + arr([s("t") + arr([i32(0) + i64(3) + i32(65536)])]),
        {"throttle_time_ms": 0, "responses": [{"topic": "t", "partitions": [
            {"partition_index": 0, "error_code": 0, "high_watermark": 10,
             "last_stable_offset": 10, "aborted_transactions": None,
             "records": b"RB"}]}]},
        i32(0) + arr([s("t") + arr([
            i32(0) + i16(0) + i64(10) + i64(10) + arr(None) + by(b"RB")])]),
    )


def test_list_offsets_v1():
    check(
        proto.LIST_OFFSETS,
        {"replica_id": -1, "topics": [{"name": "t", "partitions": [
            {"partition_index": 0, "timestamp": -1}]}]},
        i32(-1) + arr([s("t") + arr([i32(0) + i64(-1)])]),
        {"topics": [{"name": "t", "partitions": [
            {"partition_index": 0, "error_code": 0, "timestamp": 123,
             "offset": 42}]}]},
        arr([s("t") + arr([i32(0) + i16(0) + i64(123) + i64(42)])]),
    )


def test_create_topics_v0():
    check(
        proto.CREATE_TOPICS,
        {"topics": [{"name": "t", "num_partitions": 2,
                     "replication_factor": 1,
                     "assignments": [{"partition_index": 0, "broker_ids": [0, 1]}],
                     "configs": [{"name": "k", "value": None}]}],
         "timeout_ms": 100},
        arr([s("t") + i32(2) + i16(1)
             + arr([i32(0) + arr([i32(0), i32(1)])])
             + arr([s("k") + s(None)])])
        + i32(100),
        {"topics": [{"name": "t", "error_code": 36}]},
        arr([s("t") + i16(36)]),
    )


def test_api_versions_v0():
    check(
        proto.API_VERSIONS,
        {},
        b"",
        {"error_code": 0, "api_keys": [
            {"api_key": 3, "min_version": 0, "max_version": 9}]},
        i16(0) + arr([i16(3) + i16(0) + i16(9)]),
    )


def test_metadata_v1():
    check(
        proto.METADATA,
        {"topics": ["a"]},
        arr([s("a")]),
        {"brokers": [{"node_id": 0, "host": "h", "port": 9092, "rack": None}],
         "controller_id": 0,
         "topics": [{"error_code": 0, "name": "a", "is_internal": False,
                     "partitions": [{"error_code": 0, "partition_index": 0,
                                     "leader_id": 0, "replica_nodes": [0, 1],
                                     "isr_nodes": [0]}]}]},
        arr([i32(0) + s("h") + i32(9092) + s(None)]) + i32(0)
        + arr([i16(0) + s("a") + boolean(False)
               + arr([i16(0) + i32(0) + i32(0)
                      + arr([i32(0), i32(1)]) + arr([i32(0)])])]),
    )


def test_metadata_v1_all_topics_null_array():
    """topics=null -> fetch-all (the monitor's refreshMetadata path)."""
    assert proto.encode_request(proto.METADATA, CID, CLIENT, {"topics": None}) == frame(
        req_header(proto.METADATA) + arr(None)
    )


def test_alter_partition_reassignments_v0_flexible():
    check(
        proto.ALTER_PARTITION_REASSIGNMENTS,
        {"timeout_ms": 1000, "topics": [{"name": "t", "partitions": [
            {"partition_index": 0, "replicas": [1, 2]}]}]},
        i32(1000)
        + carr([cs("t") + carr([i32(0) + carr([i32(1), i32(2)]) + TAGS]) + TAGS])
        + TAGS,
        {"throttle_time_ms": 0, "error_code": 0, "error_message": None,
         "responses": [{"name": "t", "partitions": [
             {"partition_index": 0, "error_code": 0, "error_message": None}]}]},
        i32(0) + i16(0) + cs(None)
        + carr([cs("t") + carr([i32(0) + i16(0) + cs(None) + TAGS]) + TAGS])
        + TAGS,
    )


def test_alter_partition_reassignments_v0_cancel_null_replicas():
    """replicas=null cancels an in-progress reassignment (KIP-455) — the
    executor's force-stop path; null inside a COMPACT_NULLABLE_ARRAY is the
    single byte 0x00."""
    body = {"timeout_ms": 1000, "topics": [{"name": "t", "partitions": [
        {"partition_index": 3, "replicas": None}]}]}
    expect = (
        i32(1000)
        + carr([cs("t") + carr([i32(3) + uvarint(0) + TAGS]) + TAGS])
        + TAGS
    )
    assert proto.encode_request(
        proto.ALTER_PARTITION_REASSIGNMENTS, CID, CLIENT, body
    ) == frame(req_header(proto.ALTER_PARTITION_REASSIGNMENTS) + expect)


def test_list_partition_reassignments_v0_flexible():
    check(
        proto.LIST_PARTITION_REASSIGNMENTS,
        {"timeout_ms": 1000, "topics": None},
        i32(1000) + uvarint(0) + TAGS,
        {"throttle_time_ms": 0, "error_code": 0, "error_message": None,
         "topics": [{"name": "t", "partitions": [
             {"partition_index": 0, "replicas": [1, 2],
              "adding_replicas": [2], "removing_replicas": []}]}]},
        i32(0) + i16(0) + cs(None)
        + carr([cs("t") + carr([
            i32(0) + carr([i32(1), i32(2)]) + carr([i32(2)]) + carr([]) + TAGS
        ]) + TAGS])
        + TAGS,
    )


def test_elect_leaders_v1():
    check(
        proto.ELECT_LEADERS,
        {"election_type": 0, "topic_partitions": [
            {"topic": "t", "partition_ids": [0, 1]}],
         "timeout_ms": 1000},
        i8(0) + arr([s("t") + arr([i32(0), i32(1)])]) + i32(1000),
        {"throttle_time_ms": 0, "error_code": 0,
         "replica_election_results": [{"topic": "t", "partition_results": [
             {"partition_id": 0, "error_code": 0, "error_message": None}]}]},
        i32(0) + i16(0) + arr([s("t") + arr([i32(0) + i16(0) + s(None)])]),
    )


def test_incremental_alter_configs_v0():
    check(
        proto.INCREMENTAL_ALTER_CONFIGS,
        {"resources": [{"resource_type": 2, "resource_name": "t",
                        "configs": [{"name": "k", "config_operation": 0,
                                     "value": "v"}]}],
         "validate_only": False},
        arr([i8(2) + s("t") + arr([s("k") + i8(0) + s("v")])]) + boolean(False),
        {"throttle_time_ms": 0, "responses": [
            {"error_code": 0, "error_message": None, "resource_type": 2,
             "resource_name": "t"}]},
        i32(0) + arr([i16(0) + s(None) + i8(2) + s("t")]),
    )


def test_describe_configs_v0():
    check(
        proto.DESCRIBE_CONFIGS,
        {"resources": [{"resource_type": 4, "resource_name": "1",
                        "configuration_keys": None}]},
        arr([i8(4) + s("1") + arr(None)]),
        {"throttle_time_ms": 0, "results": [
            {"error_code": 0, "error_message": None, "resource_type": 4,
             "resource_name": "1",
             "configs": [{"name": "k", "value": "v", "read_only": False,
                          "is_default": True, "is_sensitive": False}]}]},
        i32(0) + arr([i16(0) + s(None) + i8(4) + s("1")
                      + arr([s("k") + s("v") + boolean(False) + boolean(True)
                             + boolean(False)])]),
    )


def test_alter_replica_log_dirs_v1():
    check(
        proto.ALTER_REPLICA_LOG_DIRS,
        {"dirs": [{"path": "/d", "topics": [{"name": "t", "partitions": [0]}]}]},
        arr([s("/d") + arr([s("t") + arr([i32(0)])])]),
        {"throttle_time_ms": 0, "results": [
            {"topic_name": "t", "partitions": [
                {"partition_index": 0, "error_code": 0}]}]},
        i32(0) + arr([s("t") + arr([i32(0) + i16(0)])]),
    )


def test_describe_log_dirs_v0():
    check(
        proto.DESCRIBE_LOG_DIRS,
        {"topics": None},
        arr(None),
        {"throttle_time_ms": 0, "results": [
            {"error_code": 0, "log_dir": "/d", "topics": [
                {"name": "t", "partitions": [
                    {"partition_index": 0, "partition_size": 100,
                     "offset_lag": 0, "is_future_key": False}]}]}]},
        i32(0) + arr([i16(0) + s("/d")
                      + arr([s("t") + arr([i32(0) + i64(100) + i64(0)
                                           + boolean(False)])])]),
    )


def test_sasl_handshake_v1():
    check(
        proto.SASL_HANDSHAKE,
        {"mechanism": "SCRAM-SHA-256"},
        s("SCRAM-SHA-256"),
        {"error_code": 0, "mechanisms": ["SCRAM-SHA-256", "SCRAM-SHA-512"]},
        i16(0) + arr([s("SCRAM-SHA-256"), s("SCRAM-SHA-512")]),
    )


def test_sasl_authenticate_v0():
    check(
        proto.SASL_AUTHENTICATE,
        {"auth_bytes": b"n,,n=user,r=abc"},
        by(b"n,,n=user,r=abc"),
        {"error_code": 0, "error_message": None, "auth_bytes": b"sf"},
        i16(0) + s(None) + by(b"sf"),
    )


def test_every_api_has_a_golden():
    """The checks above must cover protocol.ALL_APIS + SASL_APIS exactly —
    adding an API without pinning its bytes fails here."""
    covered = {
        "Produce", "Fetch", "ListOffsets", "CreateTopics", "ApiVersions",
        "Metadata", "AlterPartitionReassignments", "ListPartitionReassignments",
        "ElectLeaders", "IncrementalAlterConfigs", "DescribeConfigs",
        "AlterReplicaLogDirs", "DescribeLogDirs", "SaslHandshake",
        "SaslAuthenticate",
    }
    assert {a.name for a in proto.ALL_APIS + proto.SASL_APIS} == covered


def test_tagged_field_forward_compat():
    """A response carrying an unknown tagged field (a newer broker) must be
    skipped per KIP-482, not corrupt the decode."""
    tagged = uvarint(1) + uvarint(0) + uvarint(3) + b"xyz"  # 1 field, tag 0, 3 bytes
    payload = (
        i32(CID) + tagged  # response header v1 with an unknown tagged field
        + i32(0) + i16(0) + cs(None) + carr([]) + TAGS
    )
    cid, body = proto.decode_response(proto.ALTER_PARTITION_REASSIGNMENTS, payload)
    assert cid == CID
    assert body == {"throttle_time_ms": 0, "error_code": 0,
                    "error_message": None, "responses": []}


# --------------------------------------------------------------------------
# record batch v2 — spec layout, CRC anchored to the published check value
# --------------------------------------------------------------------------


def _crc32c_ref(data: bytes) -> int:
    """Independent bit-at-a-time CRC-32C (reflected, poly 0x1EDC6F41 →
    reversed 0x82F63B78) — no shared code with kafka.records."""
    crc = 0xFFFFFFFF
    for b in data:
        crc ^= b
        for _ in range(8):
            crc = (crc >> 1) ^ 0x82F63B78 if crc & 1 else crc >> 1
    return crc ^ 0xFFFFFFFF


def test_crc32c_published_check_value():
    """CRC-32C("123456789") = 0xE3069283 (RFC 3720 appendix / iSCSI check
    value) — anchors BOTH implementations to the published constant."""
    assert _crc32c_ref(b"123456789") == 0xE3069283
    assert records.crc32c(b"123456789") == 0xE3069283


def zigzag(v):
    # (v << 1) ^ (v >> 63) is non-negative for any int in two's complement
    return uvarint((v << 1) ^ (v >> 63))


def test_record_batch_v2_golden_bytes():
    """One record (key b"k", value b"v") at baseOffset 0, timestamp 1234:
    every field hand-assembled per the spec's RecordBatch layout."""
    rec = (
        b"\x00"        # record attributes
        + zigzag(0)    # timestampDelta
        + zigzag(0)    # offsetDelta
        + zigzag(1) + b"k"
        + zigzag(1) + b"v"
        + zigzag(0)    # headers
    )
    body = zigzag(len(rec)) + rec
    post = (
        i16(0)         # attributes: no compression
        + i32(0)       # lastOffsetDelta
        + i64(1234)    # baseTimestamp
        + i64(1234)    # maxTimestamp
        + i64(-1) + i16(-1) + i32(-1)  # producerId/Epoch, baseSequence
        + i32(1)       # record count
        + body
    )
    batch_len = 4 + 1 + 4 + len(post)  # leaderEpoch + magic + crc + post
    expect = (
        i64(0)                       # baseOffset
        + i32(batch_len)
        + i32(-1)                    # partitionLeaderEpoch
        + b"\x02"                    # magic
        + u32(_crc32c_ref(post))     # CRC-32C over the post-crc section
        + post
    )
    got = records.encode_batch([(b"k", b"v")], base_timestamp_ms=1234)
    assert got == expect

    decoded = records.decode_batches(expect)
    assert len(decoded) == 1
    assert decoded[0] == records.Record(offset=0, timestamp_ms=1234,
                                        key=b"k", value=b"v")


def test_record_batch_crc_rejects_corruption():
    batch = bytearray(records.encode_batch([(None, b"payload")]))
    batch[-1] ^= 0xFF
    with pytest.raises(ValueError, match="CRC"):
        records.decode_batches(bytes(batch))


def test_record_batch_null_key_and_multi_record_offsets():
    """Null key encodes as zigzag(-1) = 0x01; offsetDeltas increment."""
    got = records.encode_batch([(None, b"a"), (None, b"bc")])
    decoded = records.decode_batches(got)
    assert [r.offset for r in decoded] == [0, 1]
    assert all(r.key is None for r in decoded)
    # pin the null-key byte inside the first record: length, attrs, tsDelta,
    # offsetDelta, THEN keyLen -1 -> 0x01
    post = got[21:]
    first_rec_off = 40 + len(zigzag(6))  # fixed header + record-length varint
    assert post[first_rec_off + 3] == 0x01  # keyLen: zigzag(-1)


# --------------------------------------------------------------------------
# SCRAM-SHA-256 — RFC 7677 §3 published test vector
# --------------------------------------------------------------------------

RFC7677_CLIENT_NONCE = "rOprNGfwEbeRWgbNEkqO"
RFC7677_SERVER_FIRST = (
    b"r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF$k0,"
    b"s=W22ZaJ0SNY7soEsUEjb6gQ==,i=4096"
)


def test_scram_sha256_rfc7677_vector():
    """Replays the RFC 7677 example conversation (user "user", password
    "pencil") byte-for-byte — client-first, client-final with the published
    proof, and verification of the published server signature."""
    client = ScramClient(
        SaslCredentials("user", "pencil", "SCRAM-SHA-256"),
        nonce=RFC7677_CLIENT_NONCE,
    )
    assert client.first() == b"n,,n=user,r=rOprNGfwEbeRWgbNEkqO"
    final = client.final(RFC7677_SERVER_FIRST)
    assert final == (
        b"c=biws,r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF$k0,"
        b"p=dHzbZapWIk4jUhN+Ute9ytag9zjfMHgsqmmiz7AndVQ="
    )
    # mutual auth: the published server-final signature must verify...
    client.verify(b"v=6rriTRBi23WpRR/wtup+mMhUZUn/dB5nLTJRsjl95G4=")
    # ...and a tampered one must not
    with pytest.raises(PermissionError):
        client.verify(b"v=AAAATRBi23WpRR/wtup+mMhUZUn/dB5nLTJRsjl95G4=")


def test_scram_username_escaping_rfc5802():
    """'=' and ',' in usernames must be sent as =3D / =2C (RFC 5802 §5.1)."""
    client = ScramClient(
        SaslCredentials("u=s,er", "pw", "SCRAM-SHA-256"), nonce="abc"
    )
    assert client.first() == b"n,,n=u=3Ds=2Cer,r=abc"
