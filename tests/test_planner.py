"""Scenario planner tests: what-if edits, batched evaluation, forecasting,
rightsizing, and the /simulate + /rightsize REST surface.

The headline pins (acceptance criteria of the planner subsystem):
  * identity-scenario parity — applying `Scenario()` produces BYTE-identical
    engine trajectories to the unmutated state (the pinning style of
    tests/test_bucketing.py)
  * a scenario batch of one planned shape reuses ONE compiled engine for
    the optimize pass (asserted via the analyzer.engine-cache-* counters)
  * POST /simulate with a 3-scenario batch and GET /rightsize return
    correct, schema-conforming results over the simulated service
"""

import dataclasses
import json
import time
import urllib.request

import numpy as np
import pytest

from cruise_control_tpu.analyzer import (
    DEFAULT_CHAIN,
    GoalChain,
    GoalOptimizer,
    OptimizerConfig,
    ScenarioEvaluator,
)
from cruise_control_tpu.common.sensors import SensorRegistry
from cruise_control_tpu.models.builder import (
    BrokerSpec,
    ClusterModelBuilder,
    PartitionSpec,
)
from cruise_control_tpu.models.state import ShapeBucketPolicy, validate
from cruise_control_tpu.planner import (
    BrokerAdd,
    LoadForecaster,
    Rightsizer,
    Scenario,
    apply_scenario,
    plan_shape,
)

FAST = OptimizerConfig(
    num_candidates=128, leadership_candidates=32, swap_candidates=16,
    steps_per_round=8, num_rounds=2, max_extra_rounds=2, seed=3,
)

POLICY = ShapeBucketPolicy(growth=1.25, floor=8)

_COMPACT_CHAIN = GoalChain.from_names([
    "OfflineReplicaGoal", "RackAwareGoal", "ReplicaCapacityGoal",
    "DiskCapacityGoal", "ReplicaDistributionGoal",
    "LeaderReplicaDistributionGoal", "NetworkInboundUsageDistributionGoal",
])


def _catalogued_cluster():
    """small_cluster topology rebuilt so the catalog is kept (rack/topic
    names resolve through it)."""
    b = ClusterModelBuilder()
    cap = np.array([100.0, 1000.0, 1000.0, 10000.0], np.float32)
    for i in range(3):
        b.add_broker(BrokerSpec(i, rack=f"r{i}", capacity=cap))
    loads = {
        ("T1", 0): [18.0, 90.0, 100.0, 750.0],
        ("T1", 1): [15.0, 80.0, 90.0, 650.0],
        ("T2", 0): [12.0, 70.0, 80.0, 550.0],
        ("T2", 1): [10.0, 60.0, 70.0, 450.0],
    }
    b.add_partition(PartitionSpec("T1", 0, [0, 1], np.array(loads[("T1", 0)], np.float32)))
    b.add_partition(PartitionSpec("T1", 1, [0, 1], np.array(loads[("T1", 1)], np.float32)))
    b.add_partition(PartitionSpec("T2", 0, [0, 2], np.array(loads[("T2", 0)], np.float32)))
    b.add_partition(PartitionSpec("T2", 1, [0, 1], np.array(loads[("T2", 1)], np.float32)))
    return b.build(), b.catalog


# ----------------------------------------------------------------------
# scenario spec: JSON round trip + validation
# ----------------------------------------------------------------------


def test_scenario_json_round_trip():
    sc = Scenario(
        name="storm",
        add_brokers=(BrokerAdd(count=2, rack="r1", capacity=(100.0, 1e3, 1e3, 1e4)),),
        remove_brokers=(0,),
        demote_brokers=(1,),
        kill_racks=("r2",),
        topic_load_factors={"T1": 2.0, "T2": (1.0, 2.0, 2.0, 1.5)},
        load_factor=1.1,
        load_delta=(0.0, 5.0, 5.0, 10.0),
    )
    rt = Scenario.from_json(sc.to_json())
    assert rt.to_json() == sc.to_json()
    assert rt.brokers_added == 2 and not rt.is_identity
    assert Scenario().is_identity
    assert Scenario.from_json({"name": "x"}).is_identity


def test_scenario_unknown_fields_rejected():
    with pytest.raises(ValueError, match="unknown scenario fields"):
        Scenario.from_json({"removeBrokres": [1]})


# ----------------------------------------------------------------------
# identity parity: byte-identical trajectories (tests/test_bucketing.py style)
# ----------------------------------------------------------------------


def _proposal_keys(proposals):
    return sorted(
        (p.partition, p.topic, p.old_leader, p.new_leader,
         p.old_replicas, p.new_replicas, p.disk_moves)
        for p in proposals
    )


def test_identity_scenario_byte_parity():
    """apply_scenario(state, Scenario()) must be invisible: every array
    byte-identical, every engine trajectory byte-identical."""
    state, catalog = _catalogued_cluster()
    ident = apply_scenario(state, Scenario(), catalog)
    assert ident.shape == state.shape
    for f in dataclasses.fields(type(state)):
        if f.name == "shape":
            continue
        a, b = np.asarray(getattr(state, f.name)), np.asarray(getattr(ident, f.name))
        assert np.array_equal(a, b) and a.dtype == b.dtype, f.name

    r1 = GoalOptimizer(chain=DEFAULT_CHAIN, config=FAST).optimize(state)
    r2 = GoalOptimizer(chain=DEFAULT_CHAIN, config=FAST).optimize(ident)
    assert r1.objective_after == r2.objective_after
    assert np.array_equal(r1.violations_after, r2.violations_after)
    assert np.array_equal(
        np.asarray(r1.state_after.replica_broker),
        np.asarray(r2.state_after.replica_broker),
    )
    assert np.array_equal(
        np.asarray(r1.state_after.replica_is_leader),
        np.asarray(r2.state_after.replica_is_leader),
    )
    assert _proposal_keys(r1.proposals) == _proposal_keys(r2.proposals)


def test_identity_parity_survives_shape_planning():
    """Even when the batch shape pads the base (a sibling scenario adds
    brokers), the identity member must score exactly like the padded base."""
    state, catalog = _catalogued_cluster()
    scenarios = [Scenario(name="id"), Scenario(name="add", add_brokers=(BrokerAdd(6),))]
    shape = plan_shape(state, scenarios, bucket=POLICY)
    assert shape.num_brokers > state.shape.num_brokers
    from cruise_control_tpu.models.builder import pad_state

    padded = pad_state(state, shape)
    ident = apply_scenario(padded, scenarios[0], catalog, shape=shape)
    for f in dataclasses.fields(type(padded)):
        if f.name == "shape":
            continue
        assert np.array_equal(
            np.asarray(getattr(padded, f.name)), np.asarray(getattr(ident, f.name))
        ), f.name


# ----------------------------------------------------------------------
# topology scenarios: dead rack, broker add, demote
# ----------------------------------------------------------------------


def test_dead_rack_scenario_marks_offline_and_fix_evacuates():
    state, catalog = _catalogued_cluster()
    sc = Scenario(name="lose-r0", kill_racks=("r0",))
    mutated = apply_scenario(state, sc, catalog)
    assert validate(mutated) == []
    alive = np.asarray(mutated.broker_alive) & np.asarray(mutated.broker_valid)
    assert not alive[0] and alive[1] and alive[2]  # broker 0 is rack r0
    offline = np.asarray(mutated.replica_offline) & np.asarray(mutated.replica_valid)
    on_b0 = np.asarray(mutated.replica_broker) == 0
    valid = np.asarray(mutated.replica_valid)
    assert (offline[valid & on_b0]).all()  # every replica on the dead broker

    # the anneal must evacuate the dead broker entirely
    opt = GoalOptimizer(chain=_COMPACT_CHAIN, config=FAST)
    res = opt.optimize(mutated)
    after_brokers = np.asarray(res.state_after.replica_broker)[
        np.asarray(res.state_after.replica_valid)
    ]
    assert 0 not in after_brokers
    assert res.num_inter_broker_moves > 0


def test_broker_add_scenario_activates_padding_rows():
    state, catalog = _catalogued_cluster()
    sc = Scenario(name="add2", add_brokers=(BrokerAdd(count=2),))
    mutated = apply_scenario(state, sc, catalog, bucket=POLICY)
    assert validate(mutated) == []
    bv = np.asarray(mutated.broker_valid)
    alive = np.asarray(mutated.broker_alive)
    new = np.asarray(mutated.broker_new)
    assert int(bv.sum()) == 5 and int((bv & alive).sum()) == 5
    assert int(new[bv].sum()) == 2  # the added brokers are NEW brokers
    # median capacity profile cloned onto the added rows
    caps = np.asarray(mutated.broker_capacity)
    for b in np.nonzero(new & bv)[0]:
        assert np.allclose(caps[b], [100.0, 1000.0, 1000.0, 10000.0])
    # rack round-robin keeps added brokers on existing rack ids
    assert np.asarray(mutated.broker_rack)[bv].max() < mutated.shape.num_racks


def test_add_more_brokers_than_padding_raises_without_plan():
    state, catalog = _catalogued_cluster()
    sc = Scenario(name="add99", add_brokers=(BrokerAdd(count=99),))
    # planned shape accommodates...
    mutated = apply_scenario(state, sc, catalog, bucket=POLICY)
    assert int(np.asarray(mutated.broker_valid).sum()) == 102
    # ...but a deliberately tight shape fails loudly
    with pytest.raises(ValueError, match="no padding broker rows"):
        apply_scenario(state, sc, catalog, shape=state.shape)


def test_demote_scenario_moves_leadership():
    state, catalog = _catalogued_cluster()
    sc = Scenario(name="demote-0", demote_brokers=(0,))
    mutated = apply_scenario(state, sc, catalog)
    assert validate(mutated) == []
    lead = np.asarray(mutated.replica_is_leader) & np.asarray(mutated.replica_valid)
    brokers = np.asarray(mutated.replica_broker)
    assert 0 not in set(brokers[lead])  # no leader left on broker 0


def test_load_scenarios_scale_and_delta():
    state, catalog = _catalogued_cluster()
    doubled = apply_scenario(
        state, Scenario(name="x2", topic_load_factors={"T1": 2.0}), catalog
    )
    t1 = np.asarray(state.replica_topic) == catalog.topic_id("T1")
    valid = np.asarray(state.replica_valid)
    assert np.allclose(
        np.asarray(doubled.replica_load_leader)[t1 & valid],
        2.0 * np.asarray(state.replica_load_leader)[t1 & valid],
    )
    other = valid & ~t1
    assert np.array_equal(
        np.asarray(doubled.replica_load_leader)[other],
        np.asarray(state.replica_load_leader)[other],
    )
    # absolute delta: leader gets all 4; follower only NW_IN + DISK
    delta = apply_scenario(
        state, Scenario(name="d", load_delta=(1.0, 10.0, 20.0, 30.0)), catalog
    )
    dl = np.asarray(delta.replica_load_leader) - np.asarray(state.replica_load_leader)
    df = np.asarray(delta.replica_load_follower) - np.asarray(state.replica_load_follower)
    assert np.allclose(dl[valid], [1.0, 10.0, 20.0, 30.0])
    assert np.allclose(df[valid], [0.0, 10.0, 0.0, 30.0])


# ----------------------------------------------------------------------
# batched evaluation: one program, one engine
# ----------------------------------------------------------------------


def test_batched_matches_sequential_objectives():
    state, catalog = _catalogued_cluster()
    scenarios = [
        Scenario(name="id"),
        Scenario(name="lose-r0", kill_racks=("r0",)),
        Scenario(name="t1x2", topic_load_factors={"T1": 2.0}),
        Scenario(name="add1", add_brokers=(BrokerAdd(1),)),
    ]
    ev = ScenarioEvaluator(chain=_COMPACT_CHAIN)
    shape = plan_shape(state, scenarios, bucket=POLICY)
    from cruise_control_tpu.models.builder import pad_state

    base = pad_state(state, shape) if shape != state.shape else state
    states = [apply_scenario(base, sc, catalog, shape=shape) for sc in scenarios]
    obj, viol, degraded = ev.evaluate_states(states)
    assert not degraded and obj.shape == (4,)
    # sequential twin must agree EXACTLY (the bench gate's contract:
    # batching is an execution detail, never a numerics change)
    for i, s in enumerate(states):
        o, v = ev._single_eval(s)
        assert float(o) == obj[i], (i, float(o), obj[i])
        assert np.array_equal(np.asarray(v, np.float64), viol[i])


def test_evaluate_reuses_one_engine_across_batch():
    """The optimize pass over a scenario batch must compile ONE engine and
    rebind it for every other scenario (analyzer.engine-cache-* counters —
    the planner acceptance criterion)."""
    state, catalog = _catalogued_cluster()
    sensors = SensorRegistry()
    opt = GoalOptimizer(chain=_COMPACT_CHAIN, config=FAST, sensors=sensors)
    ev = ScenarioEvaluator(chain=_COMPACT_CHAIN, optimizer=opt, sensors=sensors)
    scenarios = [
        Scenario(name="id"),
        Scenario(name="lose-r0", kill_racks=("r0",)),
        Scenario(name="add2", add_brokers=(BrokerAdd(2),)),
        Scenario(name="t2x3", topic_load_factors={"T2": 3.0}),
    ]
    outcomes = ev.evaluate(state, scenarios, catalog, optimize=True, bucket=POLICY)
    assert len(outcomes) == 4
    assert all(o.fix is not None for o in outcomes)
    assert opt.engine_cache_misses == 1, "scenario batch recompiled the engine"
    assert opt.engine_cache_hits == len(scenarios) - 1
    snap = sensors.snapshot()
    assert snap["analyzer.engine-cache-misses"]["count"] == 1
    assert snap["analyzer.engine-cache-hits"]["count"] == 3
    assert snap["planner.scenarios-evaluated"]["count"] == 4


def test_evaluate_rejects_oversized_batch():
    state, catalog = _catalogued_cluster()
    ev = ScenarioEvaluator(chain=_COMPACT_CHAIN, max_scenarios=2)
    with pytest.raises(ValueError, match="planner.max.scenarios"):
        ev.evaluate(state, [Scenario(name=str(i)) for i in range(3)], catalog)


def test_degraded_cpu_fallback_matches_device_numbers():
    """A breaker-open supervisor must not change the answers — only the
    route (sequential CPU) and the degraded flag."""
    from cruise_control_tpu.common.device_watchdog import DeviceSupervisor

    state, catalog = _catalogued_cluster()
    scenarios = [Scenario(name="id"), Scenario(name="lose-r0", kill_racks=("r0",))]
    ev_direct = ScenarioEvaluator(chain=_COMPACT_CHAIN)
    direct = ev_direct.evaluate(state, scenarios, catalog, bucket=POLICY)

    sup = DeviceSupervisor(
        op_timeout_s=30.0, breaker_failure_threshold=1, probe_interval_s=3600.0
    )
    sup.breaker.record_failure()  # breaker open: device path forbidden
    assert not sup.available()
    ev_degraded = ScenarioEvaluator(
        chain=_COMPACT_CHAIN, supervisor=sup, sensors=SensorRegistry()
    )
    degraded = ev_degraded.evaluate(state, scenarios, catalog, bucket=POLICY)
    assert all(o.degraded for o in degraded)
    for d, o in zip(degraded, direct):
        assert np.isclose(d.objective, o.objective, rtol=1e-6)
        assert d.violated_goals == o.violated_goals


# ----------------------------------------------------------------------
# forecasting
# ----------------------------------------------------------------------


def _history(n_topics=2, parts_per_topic=3, n_windows=5, slope=10.0):
    """Synthetic WindowedHistory: each topic's per-partition NW_IN grows
    `slope` per window; other resources flat."""
    from cruise_control_tpu.monitor import KAFKA_METRIC_DEF, WindowedMetricSampleAggregator
    from cruise_control_tpu.monitor.sampling import PartitionEntity

    agg = WindowedMetricSampleAggregator(n_windows, 1000, 1, KAFKA_METRIC_DEF)
    ents = [
        PartitionEntity(t, p) for t in range(n_topics) for p in range(parts_per_topic)
    ]
    nwin = KAFKA_METRIC_DEF.metric_id("LEADER_BYTES_IN")
    cpu = KAFKA_METRIC_DEF.metric_id("CPU_USAGE")
    for w in range(n_windows):
        vals = np.zeros((len(ents), KAFKA_METRIC_DEF.num_metrics), np.float32)
        vals[:, nwin] = 100.0 + slope * w
        vals[:, cpu] = 5.0
        agg.add_samples_columnar(ents, w * 1000 + 5, vals)
    # one more sample opens window n_windows so all n_windows complete
    agg.add_samples_columnar(ents, n_windows * 1000 + 5, vals)
    return agg, KAFKA_METRIC_DEF


@pytest.mark.parametrize("method", ["linear", "holt"])
def test_forecaster_fits_growing_trend(method):
    agg, mdef = _history(slope=10.0)
    history = agg.history_snapshot()
    fc = LoadForecaster(method=method, min_windows=3)
    trends = fc.fit(history, mdef, {0: "A", 1: "B"})
    assert sorted(t.topic for t in trends) == ["A", "B"]
    tr = trends[0]
    # per-partition NW_IN at newest window = 100 + 10*(W-1) = 140; topic
    # total = 3 * 140 = 420, growing 30/window
    assert tr.level[1] == pytest.approx(420.0, rel=0.05)
    assert tr.slope[1] == pytest.approx(30.0, rel=0.15)
    # 2 windows out -> (420 + 60) / 420
    sc = fc.scenario_at(trends, horizon_ms=2000, window_ms=1000)
    f = sc.topic_load_factors["A"]
    assert f[1] == pytest.approx(480.0 / 420.0, rel=0.05)
    # flat resources stay ~1.0, zero-load resources exactly 1.0
    assert f[0] == pytest.approx(1.0, abs=0.05)
    assert f[2] == 1.0  # NW_OUT never observed -> no change


def test_forecaster_clamps_runaway_factors():
    agg, mdef = _history(slope=500.0)
    fc = LoadForecaster(method="linear", min_windows=3, max_factor=3.0)
    scs = fc.scenarios(agg.history_snapshot(), mdef, [100_000])
    for f in scs[0].topic_load_factors.values():
        assert max(f) <= 3.0


def test_forecaster_skips_underobserved_topics():
    agg, mdef = _history(n_windows=3)
    fc = LoadForecaster(min_windows=5)
    assert fc.fit(agg.history_snapshot(), mdef) == []


# ----------------------------------------------------------------------
# aggregator history snapshot (satellite)
# ----------------------------------------------------------------------


def test_history_snapshot_windows_and_rolling():
    from cruise_control_tpu.monitor import KAFKA_METRIC_DEF, WindowedMetricSampleAggregator

    agg = WindowedMetricSampleAggregator(3, 1000, 2, KAFKA_METRIC_DEF)
    nwin = KAFKA_METRIC_DEF.metric_id("LEADER_BYTES_IN")

    def sample(e, t, v):
        vals = np.zeros(KAFKA_METRIC_DEF.num_metrics, np.float32)
        vals[nwin] = v
        agg.add_sample(e, t, vals)

    sample("a", 500, 10.0)
    sample("a", 600, 20.0)  # window 0 complete (2 samples), avg 15
    sample("a", 1500, 99.0)  # window 1: 1 sample -> incomplete
    sample("a", 2500, 7.0)  # window 2 opens; windows 0..1 completed
    h = agg.history_snapshot()
    assert list(h.window_indices) == [1, 0]  # newest -> oldest
    assert h.values[0, 1, nwin] == pytest.approx(15.0)  # AVG divided
    assert h.values[0, 0, nwin] == pytest.approx(99.0)
    assert bool(h.complete[0, 1]) and not bool(h.complete[0, 0])
    assert h.sample_counts[0, 1] == 2 and h.sample_counts[0, 0] == 1
    assert h.entities == ("a",)

    # entity growth mid-stream: new entity appears with zero history
    sample("b", 2600, 42.0)
    sample("b", 3500, 1.0)  # roll again
    h2 = agg.history_snapshot()
    assert h2.entities == ("a", "b")
    assert list(h2.window_indices) == [2, 1, 0]
    bi = h2.entities.index("b")
    assert h2.sample_counts[bi, 0] == 1  # only window 2 sampled for b
    assert h2.sample_counts[bi, 1] == 0 and h2.sample_counts[bi, 2] == 0

    # rolling far forward evicts: the snapshot only covers live windows
    sample("a", 10_500, 3.0)
    h3 = agg.history_snapshot()
    assert list(h3.window_indices) == [9, 8, 7]
    assert h3.sample_counts.sum() == 0  # all old cells were recycled

    # snapshot is a copy: mutating it cannot corrupt the ring
    h3.values[:] = -1.0
    assert agg.history_snapshot().values.min() >= 0.0


# ----------------------------------------------------------------------
# rightsizer
# ----------------------------------------------------------------------


def _rightsize_fixture(num_brokers=6, num_parts=12):
    b = ClusterModelBuilder()
    cap = np.array([100.0, 1000.0, 1000.0, 10000.0], np.float32)
    for i in range(num_brokers):
        b.add_broker(BrokerSpec(i, rack=f"r{i % 3}", capacity=cap))
    load = np.array([2.0, 20.0, 25.0, 100.0], np.float32)
    for p in range(num_parts):
        b.add_partition(
            PartitionSpec("T0", p, [p % num_brokers, (p + 1) % num_brokers], load)
        )
    return b.build(), b.catalog


def test_rightsizer_overprovisioned_cluster():
    state, catalog = _rightsize_fixture()
    opt = GoalOptimizer(chain=_COMPACT_CHAIN, config=FAST)
    ev = ScenarioEvaluator(chain=_COMPACT_CHAIN, optimizer=opt, max_scenarios=64)
    rs = Rightsizer(ev, max_broker_factor=1.5)
    out = rs.rightsize(state, catalog)
    assert out["provisionStatus"] == "OVER_PROVISIONED"
    assert out["minBrokers"] is not None and out["minBrokers"] < out["currentBrokers"]
    assert out["minBrokers"] >= 2  # replication-factor floor
    assert not out["undecided"]
    # the boundary is real: min is feasible, min-1 (if annealed) is not
    by_count = {c["brokers"]: c for c in out["candidates"]}
    assert by_count[out["minBrokers"]]["feasible"]
    # the screening curve covers the searched range endpoints
    lo, hi = out["searchedRange"]
    assert str(lo) in map(str, out["preMoveViolations"]) or lo in out["preMoveViolations"]


def test_rightsizer_underprovisioned_under_load():
    """Scaling every topic far past total capacity must demand MORE
    brokers than the cluster has (or prove even the ceiling infeasible)."""
    state, catalog = _rightsize_fixture(num_brokers=4, num_parts=8)
    chain = GoalChain.from_names([
        "OfflineReplicaGoal", "RackAwareGoal", "DiskCapacityGoal",
        "ReplicaDistributionGoal",
    ])
    opt = GoalOptimizer(chain=chain, config=FAST)
    ev = ScenarioEvaluator(chain=chain, optimizer=opt, max_scenarios=64)
    rs = Rightsizer(ev, max_broker_factor=2.0)
    # 8 parts x RF2 x 100 disk x 30 = 48000 total disk over usable 8000
    # per broker (10000 x 0.8 threshold): >= 6 brokers required, 4 exist
    heavy = Scenario(name="x30", load_factor=30.0)
    out = rs.rightsize(state, catalog, load_scenario=heavy)
    assert out["provisionStatus"] in ("UNDER_PROVISIONED", "UNDECIDED")
    if out["minBrokers"] is not None:
        assert out["minBrokers"] > out["currentBrokers"]
    assert out["loadScenario"]["loadFactor"] == 30.0


def test_rightsizer_exhausted_budget_reports_undecided_with_upper_bound():
    """A search whose anneal budget dies mid-bracket must say UNDECIDED
    (minBrokers null) and report the proven feasible count only as an
    UPPER bound — never as 'the minimum' (that could flip an
    over-provisioned cluster's verdict to UNDER_PROVISIONED)."""
    state, catalog = _rightsize_fixture()
    opt = GoalOptimizer(chain=_COMPACT_CHAIN, config=FAST)
    ev = ScenarioEvaluator(chain=_COMPACT_CHAIN, optimizer=opt, max_scenarios=64)
    rs = Rightsizer(ev, max_broker_factor=1.5)
    out = rs.rightsize(state, catalog, max_anneals=1)  # only check(hi) runs
    assert out["undecided"] and out["provisionStatus"] == "UNDECIDED"
    assert out["minBrokers"] is None
    assert out["minBrokersUpperBound"] == out["searchedRange"][1]
    assert out["annealsRun"] == 1


def test_rightsizer_monotone_floor_respects_replication():
    state, catalog = _rightsize_fixture()
    ev = ScenarioEvaluator(chain=_COMPACT_CHAIN, optimizer=GoalOptimizer(
        chain=_COMPACT_CHAIN, config=FAST
    ), max_scenarios=64)
    rs = Rightsizer(ev, min_brokers=1)
    assert rs._floor(state, 6) == 2  # max RF is 2


# ----------------------------------------------------------------------
# REST surface on the simulated service
# ----------------------------------------------------------------------


@pytest.fixture(scope="module")
def planner_service():
    from cruise_control_tpu.service.main import build_simulated_service

    app, fetcher, admin, sampler = build_simulated_service(seed=13)
    app.start()
    yield app
    app.stop()


def _request(app, method, endpoint, headers=None, **params):
    import urllib.parse

    q = urllib.parse.urlencode(params)
    url = f"http://{app.host}:{app.port}{app.prefix}/{endpoint}" + (f"?{q}" if q else "")
    req = urllib.request.Request(url, method=method, headers=headers or {})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


def _poll(app, method, endpoint, **params):
    status, payload, headers = _request(app, method, endpoint, **params)
    tid = headers.get("User-Task-ID")
    deadline = time.time() + 90
    while status == 202 and time.time() < deadline:
        time.sleep(0.3)
        status, payload, headers = _request(
            app, method, endpoint, headers={"User-Task-ID": tid}, **params
        )
    return status, payload


def test_simulate_endpoint_three_scenario_batch(planner_service):
    from cruise_control_tpu.service.schemas import validate_response

    app = planner_service
    racks = sorted({
        b["rack"]
        for b in _request(app, "GET", "kafka_cluster_state")[1]["KafkaBrokerState"].values()
    })
    scenarios = [
        {"name": "lose-rack", "killRacks": [racks[0]]},
        {"name": "add-3", "addBrokers": [{"count": 3}]},
        {"name": "double-T0", "topicLoadFactors": {"T0": 2.0}},
    ]
    status, payload = _poll(
        app, "POST", "simulate", scenarios=json.dumps(scenarios), optimize="true"
    )
    assert status == 200
    assert validate_response("simulate", payload) == []
    assert [s["name"] for s in payload["scenarios"]] == [
        "lose-rack", "add-3", "double-T0"
    ]
    by_name = {s["name"]: s for s in payload["scenarios"]}
    base_alive = payload["baseline"]["brokersAlive"]
    assert by_name["add-3"]["brokersAlive"] == base_alive + 3
    assert by_name["lose-rack"]["brokersAlive"] < base_alive
    # losing a rack strands replicas: hard goals violated, fix proposed
    assert not by_name["lose-rack"]["hardGoalsSatisfied"]
    assert "OfflineReplicaGoal" in by_name["lose-rack"]["violatedGoals"]
    assert by_name["lose-rack"]["fix"]["numReplicaMovements"] > 0
    # doubling load keeps broker count, raises the objective vs baseline
    assert by_name["double-T0"]["brokersAlive"] == base_alive
    assert by_name["double-T0"]["objective"] > payload["baseline"]["objective"]
    assert payload["degraded"] is False


def test_simulate_endpoint_rejects_bad_scenarios(planner_service):
    import urllib.error

    app = planner_service
    with pytest.raises(urllib.error.HTTPError) as e:
        _request(app, "POST", "simulate", scenarios="not json")
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _request(app, "POST", "simulate",
                 scenarios=json.dumps([{"removeBrokres": [0]}]))
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _request(app, "POST", "simulate")  # missing scenarios
    assert e.value.code == 400


def test_simulate_endpoint_full_batch_accepted_oversize_400(planner_service):
    """A batch of exactly planner.max.scenarios must be accepted (the
    internal baseline rider must not eat one slot); one more is a 400
    client error, not a 500 from inside the async task."""
    import urllib.error

    app = planner_service
    cap = app.cc.config.get("planner.max.scenarios")
    full = [{"name": f"s{i}"} for i in range(cap)]
    status, payload = _poll(
        app, "POST", "simulate", scenarios=json.dumps(full, separators=(",", ":"))
    )
    assert status == 200 and len(payload["scenarios"]) == cap
    with pytest.raises(urllib.error.HTTPError) as e:
        _request(app, "POST", "simulate",
                 scenarios=json.dumps(full + [{"name": "extra"}],
                                      separators=(",", ":")))
    assert e.value.code == 400
    assert "planner.max.scenarios" in json.loads(e.value.read())["errorMessage"]


def test_rightsize_endpoint_rejects_bad_bounds(planner_service):
    import urllib.error

    app = planner_service
    for params in (
        {"horizon_ms": "-5"},
        {"min_brokers": "0"},
        {"max_broker_factor": "0.5"},
    ):
        with pytest.raises(urllib.error.HTTPError) as e:
            _request(app, "GET", "rightsize", **params)
        assert e.value.code == 400


def test_rightsize_endpoint(planner_service):
    from cruise_control_tpu.service.schemas import validate_response

    app = planner_service
    status, payload = _poll(app, "GET", "rightsize")
    assert status == 200
    assert validate_response("rightsize", payload) == []
    assert payload["currentBrokers"] == 6
    assert payload["provisionStatus"] in (
        "RIGHT_SIZED", "OVER_PROVISIONED", "UNDER_PROVISIONED", "UNDECIDED"
    )
    if payload["minBrokers"] is not None:
        lo, hi = payload["searchedRange"]
        assert lo <= payload["minBrokers"] <= hi
    # with a horizon the forecast verdict rides along
    status, payload = _poll(app, "GET", "rightsize", horizon_ms="3600000")
    assert status == 200
    assert "forecast" in payload


def test_planner_sensors_exported(planner_service):
    app = planner_service
    status, payload, _ = _request(app, "GET", "state", substates="sensors")
    snap = payload["Sensors"]
    assert "planner.scenarios-evaluated" in snap
    assert "planner.rightsize-timer" in snap
