"""Goal function unit tests (M0) — semantics checks on deterministic fixtures."""

import dataclasses

import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config import DEFAULT_CONSTRAINT, BalancingConstraint
from cruise_control_tpu.models import compute_aggregates
from cruise_control_tpu.analyzer.goals import DEFAULT_GOAL_ORDER, GOALS_BY_NAME, get_goals
from cruise_control_tpu.testing.fixtures import (
    RandomClusterSpec,
    dead_broker_cluster,
    rack_violated_cluster,
    random_cluster,
    small_cluster,
)


def v(goal_name, state, constraint=DEFAULT_CONSTRAINT):
    agg = compute_aggregates(state)
    return float(GOALS_BY_NAME[goal_name].violation(state, agg, constraint))


def test_registry_resolves_default_order():
    goals = get_goals()
    assert [g.name for g in goals] == DEFAULT_GOAL_ORDER


def test_rack_aware_violation():
    assert v("RackAwareGoal", rack_violated_cluster()) > 0
    assert v("RackAwareGoal", small_cluster()) == 0.0


def test_offline_replica_goal():
    assert v("OfflineReplicaGoal", dead_broker_cluster()) > 0
    assert v("OfflineReplicaGoal", small_cluster()) == 0.0


def test_replica_capacity_goal():
    s = small_cluster()
    assert v("ReplicaCapacityGoal", s) == 0.0
    tight = dataclasses.replace(DEFAULT_CONSTRAINT, max_replicas_per_broker=3)
    # broker 0 has 4 replicas -> violation under cap of 3
    assert v("ReplicaCapacityGoal", s, tight) > 0


def test_capacity_goals_fire_on_overload():
    s = small_cluster()
    # broker 0: NW_OUT load = 100+90+80+70 = 340 > 0.8 * 1000? no (800) -> 0
    assert v("NetworkOutboundCapacityGoal", s) == 0.0
    tight = dataclasses.replace(DEFAULT_CONSTRAINT, capacity_threshold=(0.8, 0.8, 0.3, 0.8))
    # threshold 0.3 -> 300 < 340 on broker 0
    assert v("NetworkOutboundCapacityGoal", s, tight) > 0


def test_cpu_capacity_goal_host_resource():
    s = small_cluster()
    tight = dataclasses.replace(DEFAULT_CONSTRAINT, capacity_threshold=(0.3, 0.8, 0.8, 0.8))
    # broker 0 leader CPU = 18+15+12+10 = 55 > 30
    assert v("CpuCapacityGoal", s, tight) > 0
    assert v("CpuCapacityGoal", s) == 0.0


def test_resource_distribution_violated_on_skewed_cluster():
    s = small_cluster()
    # everything piled on broker 0 -> clearly outside the 1.1x band
    assert v("NetworkOutboundUsageDistributionGoal", s) > 0
    assert v("DiskUsageDistributionGoal", s) > 0


def test_resource_distribution_zero_on_perfectly_balanced():
    # uniform cluster: same load everywhere
    from cruise_control_tpu.models import BrokerSpec, ClusterModelBuilder, PartitionSpec

    b = ClusterModelBuilder()
    cap = np.array([100.0, 1000.0, 1000.0, 10000.0], np.float32)
    for i in range(4):
        b.add_broker(BrokerSpec(i, rack=f"r{i}", capacity=cap))
    load = np.array([4.0, 20.0, 20.0, 100.0], np.float32)
    # ring placement: every broker gets 2 replicas, 1 leader
    for p in range(4):
        b.add_partition(PartitionSpec("T", p, [p, (p + 1) % 4], load))
    s = b.build()
    assert v("ReplicaDistributionGoal", s) == 0.0
    assert v("LeaderReplicaDistributionGoal", s) == 0.0
    assert v("DiskUsageDistributionGoal", s) == 0.0


def test_leader_goals_on_skew():
    s = small_cluster()  # broker 0 leads everything
    assert v("LeaderReplicaDistributionGoal", s) > 0
    assert v("LeaderBytesInDistributionGoal", s) > 0


def test_preferred_leader_election_goal():
    s = small_cluster()
    assert v("PreferredLeaderElectionGoal", s) == 0.0
    # demote partition 0's preferred leader
    first_leader = int(np.flatnonzero(np.asarray(s.replica_is_leader))[0])
    part = int(s.replica_partition[first_leader])
    sibling = int(
        np.flatnonzero(
            (np.asarray(s.replica_partition) == part)
            & (np.arange(s.shape.R) != first_leader)
        )[0]
    )
    moved = s.with_leadership_moved(jnp.asarray(first_leader), jnp.asarray(sibling))
    assert v("PreferredLeaderElectionGoal", moved) > 0


def test_topic_replica_distribution():
    spec = RandomClusterSpec(num_brokers=10, num_topics=3, num_partitions=90, skew=3.0)
    s = random_cluster(spec, seed=3)
    assert v("TopicReplicaDistributionGoal", s) >= 0  # smoke: computes


def test_all_goals_finite_on_random_cluster():
    s = random_cluster(RandomClusterSpec(num_brokers=12, num_partitions=300, num_dead_brokers=1), seed=4)
    agg = compute_aggregates(s)
    for g in GOALS_BY_NAME.values():
        val = float(g.violation(s, agg, DEFAULT_CONSTRAINT))
        assert np.isfinite(val) and val >= 0, g.name
        sc = float(g.score(s, agg, DEFAULT_CONSTRAINT))
        assert np.isfinite(sc) and sc >= 0, g.name
