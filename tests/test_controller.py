"""Streaming controller tests: cold-prior byte parity, warm-start carry,
move-acceptance prior fitting, WindowedHistory delta extraction (topic
add/remove mid-stream, partial windows), LiveState in-place updates, and
the controller loop's publish/supersede contract."""

import dataclasses as dc

import numpy as np
import pytest

from cruise_control_tpu.analyzer import OptimizerConfig
from cruise_control_tpu.analyzer.engine import Engine, build_statics
from cruise_control_tpu.analyzer.objective import DEFAULT_CHAIN
from cruise_control_tpu.analyzer.options import DEFAULT_OPTIONS
from cruise_control_tpu.config.app_config import CruiseControlConfig
from cruise_control_tpu.controller.prior import MoveAcceptancePrior
from cruise_control_tpu.models.whatif import LiveState
from cruise_control_tpu.monitor.aggregator import WindowedMetricSampleAggregator
from cruise_control_tpu.monitor.delta import (
    extract_window_delta,
    reduce_complete_loads,
)
from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF
from cruise_control_tpu.monitor.sampling import PartitionEntity
from cruise_control_tpu.testing.fixtures import (
    RandomClusterSpec,
    random_cluster_fast,
)

SMALL = RandomClusterSpec(
    num_brokers=12, num_partitions=200, num_racks=4, num_topics=6, skew=1.0
)
CFG = OptimizerConfig(
    num_candidates=128, leadership_candidates=32, swap_candidates=16,
    steps_per_round=8, num_rounds=3, seed=0,
)


def _placements(state):
    return tuple(
        np.asarray(getattr(state, f))
        for f in ("replica_broker", "replica_is_leader", "replica_disk")
    )


def _same_placement(a, b) -> bool:
    return all(bool((x == y).all()) for x, y in zip(_placements(a), _placements(b)))


# ---------------------------------------------------------------- engine


def test_cold_prior_is_byte_identical_to_uniform_draws():
    """prior_enabled=True with a COLD prior (mix 0) must reproduce the
    pre-prior engine's trajectory bit-for-bit — the controller's parity
    guarantee (the uniform branch consumes the same key with the same
    arithmetic; the prior's extra draws ride fold_in-derived keys)."""
    state = random_cluster_fast(SMALL, seed=3)
    base, _ = Engine(state, DEFAULT_CHAIN, config=CFG).run()
    prior_on, hist = Engine(
        state, DEFAULT_CHAIN, config=dc.replace(CFG, prior_enabled=True)
    ).run()
    assert _same_placement(base, prior_on)
    # and the history (accept counts per round) matches too
    base2, hist2 = Engine(state, DEFAULT_CHAIN, config=CFG).run()
    assert [h.get("accepted") for h in hist] == [h.get("accepted") for h in hist2]


def test_warm_prior_biases_destinations_and_stays_valid():
    """A peaked prior changes the draw stream; the anneal still produces
    a valid, improving placement (feasibility masks do not care where a
    candidate came from)."""
    from cruise_control_tpu.models.state import validate

    state = random_cluster_fast(SMALL, seed=3)

    class Peaked:
        mix = 1.0
        weights = np.zeros((state.shape.num_topics, state.shape.B), np.float32)

    Peaked.weights[:, 0] = 1.0
    eng = Engine(
        state, DEFAULT_CHAIN, config=dc.replace(CFG, prior_enabled=True),
        prior=Peaked,
    )
    final, _ = eng.run()
    base, _ = Engine(state, DEFAULT_CHAIN, config=CFG).run()
    assert not _same_placement(final, base)  # the prior actually steers
    assert validate(final, strict=False) == []
    obj0, _, _ = DEFAULT_CHAIN.evaluate(state)
    obj1, _, _ = DEFAULT_CHAIN.evaluate(final)
    assert float(obj1) <= float(obj0)


def test_prior_rebind_is_data_only():
    """Feeding a refreshed prior through rebind must not recompile: same
    engine object, same shape, new statics."""
    state = random_cluster_fast(SMALL, seed=3)
    eng = Engine(
        state, DEFAULT_CHAIN, config=dc.replace(CFG, prior_enabled=True)
    )
    cold_mix = float(np.asarray(eng.statics.prior_mix))
    assert cold_mix == 0.0

    class P:
        mix = 0.25
        weights = np.ones((state.shape.num_topics, state.shape.B), np.float32)

    eng.rebind(state, prior=P)
    assert float(np.asarray(eng.statics.prior_mix)) == 0.25
    assert eng.statics.prior_dst_cdf.shape == (
        state.shape.num_topics, state.shape.B
    )


def test_prior_disabled_statics_carry_placeholder():
    state = random_cluster_fast(SMALL, seed=3)
    sx = build_statics(state, DEFAULT_OPTIONS)
    assert sx.prior_dst_cdf.shape == (1, 1)
    assert float(np.asarray(sx.prior_mix)) == 0.0


def test_warm_start_carry_fused_and_legacy_agree():
    """init_carry_from threads through both round loops; at a fixed seed
    the two produce identical warm-started trajectories (the fused/legacy
    parity contract extends to warm starts)."""
    state = random_cluster_fast(SMALL, seed=3)
    eng = Engine(state, DEFAULT_CHAIN, config=CFG)
    first, _ = eng.run()
    init = (first.replica_broker, first.replica_is_leader, first.replica_disk)
    fused, _ = eng.run(initial_placement=init)
    legacy_eng = Engine(
        state, DEFAULT_CHAIN, config=dc.replace(CFG, fused_rounds=False)
    )
    legacy, _ = legacy_eng.run(initial_placement=init)
    assert _same_placement(fused, legacy)


def test_warm_start_does_not_corrupt_the_source_placement():
    """The fused run donates its carry; the carry is seeded from the
    caller's placement arrays — they must be COPIED first, or the donated
    run scribbles over the published result's state_after."""
    state = random_cluster_fast(SMALL, seed=3)
    eng = Engine(state, DEFAULT_CHAIN, config=CFG)
    first, _ = eng.run()
    before = _placements(first)
    eng.run(initial_placement=(
        first.replica_broker, first.replica_is_leader, first.replica_disk
    ))
    after = _placements(first)  # re-read: still alive, still identical
    assert all(bool((a == b).all()) for a, b in zip(before, after))


# ----------------------------------------------------------------- prior


def _catalog(topics=("A", "B")):
    from cruise_control_tpu.models.builder import ClusterCatalog

    return ClusterCatalog(
        topics=tuple(topics),
        partitions=tuple((t, i) for t in topics for i in range(2)),
    )


def _proposal(topic_id, old, new):
    from cruise_control_tpu.analyzer.proposals import ExecutionProposal

    return ExecutionProposal(
        partition=0, topic=topic_id, old_leader=old[0], new_leader=new[0],
        old_replicas=tuple(old), new_replicas=tuple(new),
    )


def test_prior_fits_accepted_destinations_and_gates_on_observations():
    cat = _catalog()
    prior = MoveAcceptancePrior(mix=0.5, decay=1.0, min_observations=3)
    table = prior.table(cat, _shape(T=2, B=4))
    assert table.mix == 0.0  # cold
    prior.observe_proposals([_proposal(0, (1, 2), (3, 2))], cat)
    assert prior.table(cat, _shape(T=2, B=4)).mix == 0.0  # still < min
    prior.observe_proposals(
        [_proposal(0, (1, 2), (3, 2)), _proposal(1, (0, 1), (2, 1))], cat
    )
    t = prior.table(cat, _shape(T=2, B=4))
    assert t.mix == 0.5
    assert t.weights[0, 3] == pytest.approx(2.0)  # topic A -> broker 3, twice
    assert t.weights[1, 2] == pytest.approx(1.0)
    assert t.weights[0, 2] == 0.0  # broker already held the replica


def test_prior_decay_fades_and_executed_weighs_more():
    cat = _catalog()
    prior = MoveAcceptancePrior(mix=1.0, decay=0.5, min_observations=0)
    prior.observe_proposals([_proposal(0, (1,), (3,))], cat)
    prior.observe_executed([_proposal(1, (0,), (2,))], cat)
    t = prior.table(cat, _shape(T=2, B=4))
    # the first observation decayed once (0.5); the executed one is x4
    assert t.weights[0, 3] == pytest.approx(0.5)
    assert t.weights[1, 2] == pytest.approx(4.0)


def test_prior_survives_topic_churn():
    """Topics deleted from the catalog contribute nothing; unknown broker
    ids are dropped — stale knowledge can never corrupt a fresh table."""
    prior = MoveAcceptancePrior(mix=1.0, decay=1.0, min_observations=0)
    prior.observe_proposals([_proposal(0, (1,), (3,))], _catalog(("OLD", "B")))
    t = prior.table(_catalog(("NEW", "B")), _shape(T=2, B=4))
    assert t.weights.sum() == 0.0  # OLD is gone; nothing maps


def _shape(T, B):
    from cruise_control_tpu.models.state import ClusterShape

    return ClusterShape(
        num_replicas=8, num_brokers=B, num_partitions=4, num_topics=T,
        num_racks=2, num_hosts=B, max_disks_per_broker=1,
    )


def test_proposal_set_destination_pairs():
    """The columnar extraction must report exactly the brokers RECEIVING
    a replica they did not hold."""
    state = random_cluster_fast(SMALL, seed=3)
    eng = Engine(state, DEFAULT_CHAIN, config=CFG)
    final, _ = eng.run()
    from cruise_control_tpu.analyzer.proposals import extract_proposals

    ps = extract_proposals(state, final)
    tids, dsts = ps.destination_pairs()
    assert len(tids) == len(dsts)
    # cross-check against the materialized objects
    expected = []
    for p in ps:
        old = set(p.old_replicas)
        for b in p.new_replicas:
            if b not in old:
                expected.append((int(p.topic), int(b)))
    assert sorted(zip(tids.tolist(), dsts.tolist())) == sorted(expected)


# ------------------------------------------------------- window delta path


def _agg(num_windows=4, window_ms=1000, min_samples=2):
    return WindowedMetricSampleAggregator(
        num_windows=num_windows, window_ms=window_ms,
        min_samples_per_window=min_samples, metric_def=KAFKA_METRIC_DEF,
    )


def _sample(agg, entity, t_ms, cpu=1.0, nwin=10.0, nwout=5.0, disk=100.0):
    m = agg.metric_def
    vals = np.zeros(m.num_metrics, np.float32)
    vals[m.metric_id("CPU_USAGE")] = cpu
    vals[m.metric_id("LEADER_BYTES_IN")] = nwin
    vals[m.metric_id("LEADER_BYTES_OUT")] = nwout
    vals[m.metric_id("DISK_USAGE")] = disk
    agg.add_sample(entity, t_ms, vals)


def test_delta_partial_window_does_not_read_as_traffic_drop():
    """A half-sampled window holds a partial average; the completeness
    mask must keep it out of the reduction so the entity's loads hold
    steady instead of collapsing."""
    agg = _agg(min_samples=2)
    e = PartitionEntity(0, 0)
    for w in range(3):  # windows 0..2 fully sampled (2 samples each)
        _sample(agg, e, w * 1000 + 100, nwin=10.0)
        _sample(agg, e, w * 1000 + 600, nwin=10.0)
    _sample(agg, e, 3500)  # roll to window 3 (windows 0..2 completed)
    prev = agg.history_snapshot()
    # window 3 gets only ONE sample (partial) before window 4 opens
    _sample(agg, e, 4500)
    cur = agg.history_snapshot()
    delta = extract_window_delta(prev, cur, agg.metric_def)
    assert not delta.requires_reflatten
    red = reduce_complete_loads(cur, agg.metric_def)
    from cruise_control_tpu.common.resources import Resource

    i = cur.entities.index(e)
    # the partial window must NOT have dragged the NW_IN mean below 10
    assert red.loads[i][Resource.NW_IN] == pytest.approx(10.0)
    if delta.entities:  # if reported at all, the loads hold steady
        j = delta.entities.index(e)
        assert delta.loads[j][Resource.NW_IN] == pytest.approx(10.0)


def test_delta_entity_with_no_complete_window_is_stale_not_zero():
    agg = _agg(min_samples=3)
    e = PartitionEntity(0, 0)
    for w in range(3):
        _sample(agg, e, w * 1000 + 100)  # 1 sample/window < min_samples=3
    _sample(agg, e, 3500)
    prev = agg.history_snapshot()
    _sample(agg, e, 4500)
    cur = agg.history_snapshot()
    delta = extract_window_delta(prev, cur, agg.metric_def)
    assert e in delta.stale
    assert e not in delta.entities  # never emitted with fabricated zeros


def test_delta_mid_stream_topic_add_and_remove_force_reflatten():
    agg = _agg(min_samples=1)
    a, b = PartitionEntity(0, 0), PartitionEntity(1, 0)
    _sample(agg, a, 100)
    _sample(agg, a, 1100)
    _sample(agg, a, 2100)
    prev = agg.history_snapshot()
    _sample(agg, b, 3100)  # new topic appears mid-stream
    _sample(agg, a, 3200)
    cur = agg.history_snapshot()
    delta = extract_window_delta(prev, cur, agg.metric_def)
    assert delta.added == (b,)
    assert delta.requires_reflatten
    # removal: diff the other direction (an aggregator never forgets rows,
    # but a restarted one would — the delta contract covers both)
    back = extract_window_delta(cur, prev, agg.metric_def)
    assert back.removed == (b,)
    assert back.requires_reflatten


def test_delta_reports_changed_loads_absolute():
    agg = _agg(min_samples=1)
    e0, e1 = PartitionEntity(0, 0), PartitionEntity(0, 1)
    for w in range(3):
        _sample(agg, e0, w * 1000 + 100, nwin=10.0)
        _sample(agg, e1, w * 1000 + 100, nwin=20.0)
    # window 3 opens with e0's spike — still in progress, so invisible
    # to the prev snapshot
    _sample(agg, e0, 3100, nwin=40.0)
    _sample(agg, e1, 3100, nwin=20.0)
    prev = agg.history_snapshot()
    # rolling to window 4 COMPLETES the spike window
    _sample(agg, e0, 4100, nwin=40.0)
    _sample(agg, e1, 4100, nwin=20.0)
    cur = agg.history_snapshot()
    delta = extract_window_delta(prev, cur, agg.metric_def)
    from cruise_control_tpu.common.resources import Resource

    by_e = dict(zip(delta.entities, zip(delta.loads, delta.changed)))
    l0, c0 = by_e[e0]
    l1, c1 = by_e[e1]
    assert bool(c0) and not bool(c1)
    assert l0[Resource.NW_IN] > 10.0  # absolute new value, not an increment
    assert l1[Resource.NW_IN] == pytest.approx(20.0)


# ------------------------------------------------------------- live state


def test_live_state_scatter_matches_host_update_and_preserves_rest():
    state = random_cluster_fast(SMALL, seed=9)
    live = LiveState(state)
    rows = np.asarray([0, 3, 7], np.int32)
    ll = np.full((3, 4), 42.0, np.float32)
    fl = np.full((3, 4), 21.0, np.float32)
    rb_before = np.asarray(state.replica_broker).copy()
    # host copies BEFORE the update: donation invalidates the old device
    # arrays of the rewritten leaves (the ownership contract)
    ll_before = np.asarray(state.replica_load_leader).copy()
    live.set_partition_loads(rows, ll, fl)
    out = np.asarray(live.state.replica_load_leader)
    assert (out[rows] == 42.0).all()
    fout = np.asarray(live.state.replica_load_follower)
    assert (fout[rows] == 21.0).all()
    # untouched rows and placement arrays unchanged
    untouched = np.setdiff1d(np.arange(state.shape.R), rows)
    assert np.array_equal(out[untouched], ll_before[untouched])
    assert np.array_equal(np.asarray(live.state.replica_broker), rb_before)


def test_live_state_broker_liveness_rederives_offline():
    state = random_cluster_fast(SMALL, seed=9)
    live = LiveState(state)
    alive = np.asarray(state.broker_alive).copy()
    victim = int(np.asarray(state.replica_broker)[0])
    alive[victim] = False
    live.set_broker_liveness(alive)
    st = live.state
    off = np.asarray(st.replica_offline)
    rb = np.asarray(st.replica_broker)
    rv = np.asarray(st.replica_valid)
    assert (off[(rb == victim) & rv]).all()


# ------------------------------------------------------- controller loop


def _controller_service(extra=None, seed=5):
    from cruise_control_tpu.service.main import build_simulated_service

    props = {
        "partition.metrics.window.ms": 1000,
        "min.samples.per.partition.metrics.window": 1,
        "num.partition.metrics.windows": 3,
        "execution.progress.check.interval.ms": 100,
        "webserver.http.port": 0,
        "tpu.num.candidates": 128,
        "tpu.leadership.candidates": 32,
        "tpu.steps.per.round": 16,
        "tpu.num.rounds": 2,
        "controller.enabled": True,
        "controller.prior.min.observations": 8,
    }
    props.update(extra or {})
    return build_simulated_service(CruiseControlConfig(props), seed=seed)


def test_controller_replay_delta_path_and_publish():
    app, fetcher, admin, sampler = _controller_service()
    try:
        cc = app.cc
        ctl = cc.controller
        assert ctl is not None
        parts = sampler.all_partition_entities()
        for w in range(4, 9):
            sampler.drift(1.05)
            fetcher.fetch_once(parts, w * 1000, (w + 1) * 1000 - 1)
            info = ctl.run_once()
            assert info is not None
        stats = ctl.state_json()
        assert stats["fullReflattens"] == 1  # only the initial build
        assert stats["deltaApplies"] == 4
        assert stats["proposalsPublished"] == 5
        assert stats["warmStarts"] == 4
        # the published proposal serves /proposals without a rebuild
        assert cc._valid_cache() is not None
        assert cc._cache.source == "controller"
        st = cc.state()
        assert st["ControllerState"]["windowRolls"] == 5
        assert st["AnalyzerState"]["proposalSource"] == "controller"
        # idempotent tick: no new window -> no cycle
        assert ctl.run_once() is None
    finally:
        app.stop()


def test_controller_delta_bridges_first_seen_vs_catalog_topic_ids():
    """Aggregator entities carry FIRST-SEEN topology topic ids; the
    catalog/state ids are name-rank.  With topics first seen out of name
    order ("zeta" before "alpha"), a spike on zeta must land on ZETA's
    replica rows — not alpha's (the id-space bridge in _reflatten)."""
    from cruise_control_tpu.service.main import build_simulated_service

    props = {
        "partition.metrics.window.ms": 1000,
        "min.samples.per.partition.metrics.window": 1,
        "num.partition.metrics.windows": 3,
        "execution.progress.check.interval.ms": 100,
        "webserver.http.port": 0,
        "tpu.num.candidates": 128, "tpu.leadership.candidates": 32,
        "tpu.steps.per.round": 16, "tpu.num.rounds": 2,
        "controller.enabled": True,
    }
    app, fetcher, admin, sampler = build_simulated_service(
        CruiseControlConfig(props), topics={"zeta": 6, "alpha": 6}, seed=5
    )
    try:
        cc = app.cc
        ctl = cc.controller
        parts = sampler.all_partition_entities()
        fetcher.fetch_once(parts, 4000, 4999)
        assert ctl.run_once() is not None  # initial flatten
        catalog = cc.monitor.last_catalog
        assert catalog.topics == ("alpha", "zeta")  # name-rank space
        zeta_id = catalog.topic_id("zeta")
        st0 = ctl._live.state
        topic = np.asarray(st0.replica_topic)
        valid = np.asarray(st0.replica_valid)
        before = np.asarray(st0.replica_load_leader).copy()
        # spike ONLY zeta's traffic; the spiked window must COMPLETE
        # (roll once more) before the delta path may see it — the
        # completeness mask correctly hides the in-progress window
        sampler.drift(4.0, topic="zeta")
        fetcher.fetch_once(parts, 5000, 5999)
        info = ctl.run_once()
        assert info is not None and not info["reflattened"]
        fetcher.fetch_once(parts, 6000, 6999)
        info = ctl.run_once()
        assert info is not None and not info["reflattened"]
        assert info["delta_partitions"] > 0
        after = np.asarray(ctl._live.state.replica_load_leader)
        from cruise_control_tpu.common.resources import Resource

        zeta_rows = valid & (topic == zeta_id)
        alpha_rows = valid & (topic != zeta_id)
        assert (
            after[zeta_rows, Resource.NW_IN] > before[zeta_rows, Resource.NW_IN]
        ).all()
        # alpha's loads must be untouched by zeta's spike (jitter-free
        # check: alpha did not change at all this window beyond sampler
        # noise — compare against a 2x bound, far below the 4x spike)
        assert (
            after[alpha_rows, Resource.NW_IN]
            < 2.0 * np.maximum(before[alpha_rows, Resource.NW_IN], 1e-9)
        ).all()
    finally:
        app.stop()


def test_controller_cold_mode_matches_direct_optimize():
    """Cold parity: warm start off + delta off + prior mix 0 must equal
    today's flatten-and-anneal pipeline byte-for-byte."""
    app, fetcher, admin, sampler = _controller_service({
        "controller.warm.start.enabled": False,
        "controller.delta.enabled": False,
        "controller.prior.mix": 0.0,
    })
    try:
        cc = app.cc
        ctl = cc.controller
        parts = sampler.all_partition_entities()
        info = None
        for w in range(4, 7):
            sampler.drift(1.05)
            fetcher.fetch_once(parts, w * 1000, (w + 1) * 1000 - 1)
            info = ctl.run_once()
        assert ctl.state_json()["fullReflattens"] == 3
        fresh = cc.monitor.cluster_model()
        direct = cc.optimizer.optimize(fresh, options=cc._build_options(fresh))
        assert _same_placement(info["result"].state_after, direct.state_after)
    finally:
        app.stop()


def test_publish_supersede_keeps_freshest_generation():
    app, fetcher, admin, sampler = _controller_service()
    try:
        cc = app.cc
        ctl = cc.controller
        parts = sampler.all_partition_entities()
        sampler.drift(1.05)
        fetcher.fetch_once(parts, 4000, 4999)
        info = ctl.run_once()
        result = info["result"]
        assert cc._valid_cache() is not None
        gen_at_publish = cc._cache.model_generation
        # a fresher publish for the same generation supersedes the cache
        assert cc.publish_proposal(result) is True
        # simulate the cache holding a FRESHER generation than a late,
        # straggling publish: bump the cached generation stamp
        from cruise_control_tpu.monitor.load_monitor import ModelGeneration

        cc._cache.model_generation = ModelGeneration(
            metadata_generation=gen_at_publish.metadata_generation + 1,
            load_generation=gen_at_publish.load_generation,
        )
        assert cc.publish_proposal(result) is False  # stale publish dropped
    finally:
        app.stop()


def test_controller_survives_unrelated_model_builds():
    """An anomaly-detector round (or any cache-miss request) building a
    model bumps the monitor's load generation; that must neither evict
    the controller's published proposal nor sideline its future
    publishes — only a topology change or expiry invalidates them."""
    app, fetcher, admin, sampler = _controller_service()
    try:
        cc = app.cc
        ctl = cc.controller
        parts = sampler.all_partition_entities()
        sampler.drift(1.05)
        fetcher.fetch_once(parts, 4000, 4999)
        assert ctl.run_once() is not None
        assert cc._valid_cache() is not None
        # simulate a detector round: a model build bumps _load_generation
        cc.monitor.cluster_model()
        assert cc._valid_cache() is not None  # controller result survives
        assert cc._cache.source == "controller"
        # and the NEXT controller publish still lands (not judged stale
        # against the detector-bumped generation)
        sampler.drift(1.05)
        fetcher.fetch_once(parts, 5000, 5999)
        info = ctl.run_once()
        assert info is not None and info["published"]
    finally:
        app.stop()


def test_controller_lifecycle_and_precompute_standdown():
    """start_up starts the controller thread (and does NOT start the
    legacy precompute loop beside it); shutdown joins it."""
    app, fetcher, admin, sampler = _controller_service()
    try:
        cc = app.cc
        cc.start_up(precompute=True)
        assert cc.controller.running
        assert cc._precompute_thread is None
        cc.shutdown()
        assert not cc.controller.running
    finally:
        app.stop()


def test_controller_config_keys_parse_and_gate_construction():
    cfg = CruiseControlConfig({})
    assert cfg.get("controller.enabled") is False
    with pytest.raises(Exception):
        CruiseControlConfig({"controller.prior.mix": 1.5})
    # compile-cache key resolution: preferred name wins
    cfg2 = CruiseControlConfig({
        "tpu.compile.cache.dir": "/tmp/a", "tpu.compilation.cache.dir": "/tmp/b",
    })
    assert cfg2.compile_cache_dir() == "/tmp/a"
    assert CruiseControlConfig({}).compile_cache_dir() is not None  # legacy default


# ------------------------------------------------------------ fused cycle


def _replay(ctl, fetcher, sampler, windows, drift=1.05):
    parts = sampler.all_partition_entities()
    infos = []
    for w in windows:
        sampler.drift(drift)
        fetcher.fetch_once(parts, w * 1000, (w + 1) * 1000 - 1)
        info = ctl.run_once()
        assert info is not None
        infos.append(info)
    return infos


def test_fused_cycle_matches_staged_path_byte_for_byte():
    """The tentpole parity pin: the fused delta->re-anneal->extract device
    program must publish BYTE-IDENTICAL placements to the staged
    scatter-then-anneal path, every window — fusion is an execution
    detail, never a numerics change.  Also proves the dispatch contract
    (<= 2 device dispatches per fused steady-state cycle) and that
    controller.fusion.enabled=false pins the staged path (zero fused
    cycles)."""
    runs = {}
    for fusion in (True, False):
        app, fetcher, admin, sampler = _controller_service(
            {"controller.fusion.enabled": fusion}
        )
        try:
            ctl = app.cc.controller
            infos = _replay(ctl, fetcher, sampler, range(4, 9))
            runs[fusion] = (
                [_placements(i["result"].state_after) for i in infos],
                [i for i in infos],
                ctl.state_json(),
            )
        finally:
            app.stop()
    on_p, on_i, on_s = runs[True]
    off_p, off_i, off_s = runs[False]
    assert on_s["fusedCycles"] > 0 and off_s["fusedCycles"] == 0
    assert not on_i[0].get("fused")  # the reflatten cycle never fuses
    for a, b in zip(on_p, off_p):
        for x, y in zip(a, b):
            assert (x == y).all()
    for info in on_i:
        if info.get("fused"):
            # one program dispatch + one host extraction, metered — the
            # O(1) host<->device steady-state contract
            assert sum(info["dispatches"].values()) <= 2
    assert on_s["lastCycleDispatches"] <= 2


def test_cold_cycle_histogram_exclusion_and_one_shot_sensors():
    """The first published cycle (XLA cold compile) and the first fused
    cycle (fused-program compile) stay OUT of the steady-state
    window-roll-to-publish histogram; each reports through its own
    one-shot sensor instead."""
    app, fetcher, admin, sampler = _controller_service()
    try:
        ctl = app.cc.controller
        n = 5
        _replay(ctl, fetcher, sampler, range(4, 4 + n))
        stats = ctl.state_json()
        assert stats["proposalsPublished"] == n
        assert stats["coldCycleSeconds"] is not None
        assert stats["fusedColdCycleSeconds"] is not None
        hist = app.cc.sensors.get("controller.window-roll-to-publish-seconds")
        assert hist is not None and hist.count == n - 2
        assert (
            app.cc.sensors.gauge("controller.cold-compile-cycle-seconds").value
            > 0.0
        )
        assert (
            app.cc.sensors.gauge(
                "controller.fused-cold-compile-cycle-seconds"
            ).value
            > 0.0
        )
    finally:
        app.stop()


def test_reflatten_reason_counters():
    """fullReflattens stays the aggregate; fullReflattensByReason breaks
    it down so a reflatten storm is attributable (topology churn vs
    delta-disabled vs mid-stream entity churn) — and the reasons always
    sum to the aggregate."""
    app, fetcher, admin, sampler = _controller_service(
        {"controller.delta.enabled": False}
    )
    try:
        ctl = app.cc.controller
        _replay(ctl, fetcher, sampler, range(4, 7))
        stats = ctl.state_json()
        assert stats["fullReflattens"] == 3
        assert stats["fullReflattensByReason"] == {
            "initial": 1, "delta-disabled": 2,
        }
        assert sum(stats["fullReflattensByReason"].values()) == stats[
            "fullReflattens"
        ]
    finally:
        app.stop()
    app, fetcher, admin, sampler = _controller_service()
    try:
        ctl = app.cc.controller
        _replay(ctl, fetcher, sampler, range(4, 7))
        assert ctl.state_json()["fullReflattensByReason"] == {"initial": 1}
    finally:
        app.stop()


# ------------------------------------------------------- delta-sized plans


def test_plan_config_quantized_ladder():
    """Delta-sized candidate plans quantize to 1/2, 1/4 or 1/8 of full K
    (never an exact per-delta width — bounded compile count, at most
    three extra engine-cache keys per base config), hold the brownout
    floors, and return the SAME config object at full K so the engine
    cache key is stable."""
    app, fetcher, admin, sampler = _controller_service({
        "tpu.num.candidates": 1024,
        "controller.plan.min.candidates": 64,
        "controller.plan.candidates.per.partition": 4,
    })
    try:
        ctl = app.cc.controller
        cfg = ctl._opt_config
        tiny = ctl._plan_config(cfg, 4)  # needed=64 -> 1/8
        assert tiny.num_candidates == 128
        mid = ctl._plan_config(cfg, 64)  # needed=256 -> 1/4
        assert mid.num_candidates == 256
        # quantized: equal deltas map to EQUAL configs (cache-key stable)
        assert ctl._plan_config(cfg, 4) == tiny
        # a big delta needs full K: the identical object comes back
        assert ctl._plan_config(cfg, 600) is cfg
        # floors mirror brownout_config's
        assert tiny.leadership_candidates >= 8
        assert tiny.swap_candidates >= 0
    finally:
        app.stop()


def test_delta_sized_plans_hold_goal_quality():
    """A delta-sized (1/8-width) steady-state plan must land the same
    goal quality as full-K: equal-or-cleaner violations, objective within
    a few percent — the width was sized to the delta, not starved."""
    runs = {}
    for sizing in (True, False):
        app, fetcher, admin, sampler = _controller_service({
            "tpu.num.candidates": 1024,
            # a realistic round budget: the narrow plan trades width for
            # steps, so it needs the steps the production config has
            # (the 2-round harness default starves it into a residual)
            "tpu.num.rounds": 4,
            "tpu.steps.per.round": 24,
            "controller.plan.min.candidates": 64,
            "controller.plan.candidates.per.partition": 4,
            "controller.plan.sizing.enabled": sizing,
            # staged path only: keeps this test to one engine compile per
            # width (plan sizing is orthogonal to fusion)
            "controller.fusion.enabled": False,
        })
        try:
            ctl = app.cc.controller
            infos = _replay(ctl, fetcher, sampler, range(4, 8))
            runs[sizing] = (infos[-1]["result"], ctl.state_json())
        finally:
            app.stop()
    sized_res, sized_stats = runs[True]
    full_res, full_stats = runs[False]
    assert sized_stats["planSizedCycles"] > 0
    assert full_stats["planSizedCycles"] == 0
    sized_viol = float(np.max(sized_res.violations_after))
    full_viol = float(np.max(full_res.violations_after))
    tol = 1e-6
    assert sized_viol <= max(full_viol, tol)
    assert float(sized_res.objective_after) <= float(
        full_res.objective_after
    ) * 1.05 + tol
