"""Diagnose config5 (decommission self-healing) residual goal violations."""

import os
import sys
import time
import dataclasses as dc

sys.path.insert(0, "/root/repo")

from cruise_control_tpu.common.compilation_cache import enable_persistent_cache

enable_persistent_cache(os.environ.get("BENCH_COMPILE_CACHE", "~/.cache/cruise_control_tpu/xla"))

import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig
from cruise_control_tpu.analyzer.objective import DEFAULT_CHAIN
from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

SCALE = os.environ.get("DIAG_SCALE", "mid")
SPECS = {
    "mid": dict(
        num_brokers=500, num_racks=20, num_topics=100, num_partitions=50_000, skew=0.5,
        broker_capacity=(100.0, 300_000.0, 300_000.0, 3_000_000.0),
        mean_cpu=0.2, mean_nw_in=500.0, mean_nw_out=600.0, mean_disk=5000.0,
    ),
    "north_star": dict(
        num_brokers=2600, num_racks=52, num_topics=200, num_partitions=200_000,
        min_replication=2, max_replication=3, skew=0.5,
        broker_capacity=(100.0, 500_000.0, 500_000.0, 5_000_000.0),
        mean_cpu=0.15, mean_nw_in=400.0, mean_nw_out=500.0, mean_disk=4000.0,
    ),
}
SEARCH = dict(num_candidates=16384, leadership_candidates=4096,
              steps_per_round=64, num_rounds=8, seed=0)

state = random_cluster_fast(RandomClusterSpec(**SPECS[SCALE]), seed=42)
B = state.shape.B
n_dead = max(2, B // 100)
alive = np.asarray(state.broker_alive).copy()
alive[np.arange(B - n_dead, B)] = False
offline = np.asarray(state.replica_offline) | ~alive[np.asarray(state.replica_broker)]
state = dc.replace(
    state,
    broker_alive=jnp.asarray(alive),
    disk_alive=jnp.asarray(alive[:, None] & np.asarray(state.disk_alive)),
    replica_offline=jnp.asarray(offline),
)
opt = GoalOptimizer(config=OptimizerConfig(**SEARCH))
t0 = time.time()
res = opt.optimize(state, verbose=True)
print(f"wall={time.time()-t0:.1f}s scale={SCALE} dead={n_dead}", flush=True)
print("balancedness", round(res.balancedness_before, 2), "->", round(res.balancedness_after, 2))
print("objective", res.objective_before, "->", res.objective_after)
print("moves: replica", res.num_inter_broker_moves, "leader", res.num_leadership_moves)
print("history:", res.history)
for n, vb, va in zip(res.goal_names, res.violations_before, res.violations_after):
    if va > 1e-12 or vb > 1e-9:
        print(f"  {n:45s} {vb:.3e} -> {va:.3e}")
