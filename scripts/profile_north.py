"""Phase-level breakdown of GoalOptimizer.optimize at north-star scale.

Times every component of the measured (second) optimize() call: validate,
report, per-round plan/scan/refresh/early-stop checks, proposal
extraction.  Run on the real TPU to see where the 11.3s goes.
"""

import os
import sys
import time

sys.path.insert(0, "/root/repo")

from cruise_control_tpu.common.compilation_cache import enable_persistent_cache

enable_persistent_cache(os.environ.get("BENCH_COMPILE_CACHE", "~/.cache/cruise_control_tpu/xla"))

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig
from cruise_control_tpu.analyzer.objective import DEFAULT_CHAIN, balancedness_score
from cruise_control_tpu.analyzer.proposals import extract_proposals
from cruise_control_tpu.models.state import validate
from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

NORTH = RandomClusterSpec(
    num_brokers=2600, num_racks=52, num_topics=200, num_partitions=200_000,
    min_replication=2, max_replication=3, skew=0.5,
    broker_capacity=(100.0, 500_000.0, 500_000.0, 5_000_000.0),
    mean_cpu=0.15, mean_nw_in=400.0, mean_nw_out=500.0, mean_disk=4000.0,
)
SEARCH = dict(
    num_candidates=16384, leadership_candidates=4096,
    steps_per_round=64, num_rounds=8, seed=0,
)


def t(label, fn, *a, **k):
    t0 = time.monotonic()
    out = fn(*a, **k)
    out = jax.block_until_ready(out) if hasattr(out, "block_until_ready") or isinstance(out, (jax.Array,)) else out
    dt = time.monotonic() - t0
    print(f"  {label:38s} {dt*1000:9.1f} ms", flush=True)
    return out, dt


def main():
    print("device:", jax.devices()[0], flush=True)
    t0 = time.monotonic()
    state = random_cluster_fast(NORTH, seed=42)
    print(f"fixture: {time.monotonic()-t0:.1f}s", flush=True)

    opt = GoalOptimizer(config=OptimizerConfig(**SEARCH))
    t0 = time.monotonic()
    warm = opt.optimize(state)
    print(f"warmup optimize: {time.monotonic()-t0:.1f}s (wall_seconds={warm.wall_seconds:.1f})", flush=True)

    # ---- instrumented second run ----
    total0 = time.monotonic()
    _, d_val = t("validate(state)", validate, state)
    (out, d_rep) = t("report(state)", lambda: jax.block_until_ready(opt._report(state)))

    engine, _ = opt._engine_for(state, __import__("cruise_control_tpu.analyzer.options", fromlist=["DEFAULT_OPTIONS"]).DEFAULT_OPTIONS, opt.config)
    cfg = engine.config
    sx = engine.statics
    t0 = time.monotonic()
    carry = engine.init_carry(jax.random.PRNGKey(cfg.seed))
    jax.block_until_ready(carry.broker_load)
    print(f"  {'init_carry':38s} {(time.monotonic()-t0)*1000:9.1f} ms", flush=True)
    t0 = time.monotonic()
    t0_obj = float(engine._jit_objective(sx, carry)) * cfg.init_temperature_scale
    print(f"  {'initial objective':38s} {(time.monotonic()-t0)*1000:9.1f} ms", flush=True)

    full_checks_left = 2
    for rnd in range(cfg.num_rounds):
        t_round = 0.0 if rnd == cfg.num_rounds - 1 else t0_obj * (cfg.temperature_decay ** rnd)
        temps = jnp.full((cfg.steps_per_round,), t_round, jnp.float32)
        r0 = time.monotonic()
        plan = engine._jit_plan(sx, carry)
        jax.block_until_ready(plan.broker_cdf)
        d_plan = time.monotonic() - r0
        r0 = time.monotonic()
        carry, stats = engine._scan(sx, carry, temps, plan)
        jax.block_until_ready(carry.broker_load)
        d_scan = time.monotonic() - r0
        r0 = time.monotonic()
        carry = engine._jit_refresh(sx, carry)
        jax.block_until_ready(carry.broker_load)
        d_refresh = time.monotonic() - r0
        r0 = time.monotonic()
        cheap = float(engine._jit_cheap_violations(sx, carry))
        d_cheap = time.monotonic() - r0
        d_full = 0.0
        stopped = False
        if cfg.early_stop_violations >= 0 and rnd < cfg.num_rounds - 1 and full_checks_left > 0 and cheap <= cfg.early_stop_violations:
            r0 = time.monotonic()
            fullv = float(engine._jit_violations(sx, carry))
            d_full = time.monotonic() - r0
            if fullv <= cfg.early_stop_violations:
                stopped = True
            else:
                full_checks_left -= 1
        acc = int(jax.device_get(stats["accepted"]).sum())
        print(f"  round {rnd}: plan={d_plan*1000:7.1f} scan={d_scan*1000:8.1f} refresh={d_refresh*1000:7.1f} cheap={d_cheap*1000:6.1f} full={d_full*1000:6.1f} ms acc={acc} cheapv={cheap:.2e}{' STOP' if stopped else ''}", flush=True)
        if stopped:
            break
    final = engine.carry_to_state(carry)
    (_, d_rep2) = t("report(final)", lambda: jax.block_until_ready(opt._report(final)))
    _, d_val2 = t("validate(final)", validate, final)
    t0 = time.monotonic()
    props = extract_proposals(state, final)
    print(f"  {'extract_proposals':38s} {(time.monotonic()-t0)*1000:9.1f} ms  ({len(props)} proposals)", flush=True)
    print(f"TOTAL instrumented: {time.monotonic()-total0:.3f}s", flush=True)

    (obj_a, viol_a), _ = opt._report(final)
    print("balancedness_after:", balancedness_score(np.asarray(viol_a), opt.chain), flush=True)


if __name__ == "__main__":
    main()
