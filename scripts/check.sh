#!/usr/bin/env bash
# Round gate: the full test suite + the multi-chip dryrun must BOTH pass
# before a round ends (VERDICT r4: round 4 shipped a red suite because
# nothing forced a final full run).  Reference analog: the CircleCI gate
# running `./gradlew clean build` (.circleci/config.yml:16).
#
# Usage: scripts/check.sh [pytest-args...]
# Exit: nonzero if the suite or the dryrun fails.
set -u
cd "$(dirname "$0")/.."

echo "== check.sh: pytest tests/ -q $* =="
python -m pytest tests/ -q "$@"
suite_rc=$?

echo "== check.sh: dryrun_multichip(8) on virtual CPU mesh =="
GRAFT_FORCE_CPU=1 XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python - <<'EOF'
import __graft_entry__ as g
g.dryrun_multichip(8)
print("dryrun_multichip(8): OK")
EOF
dryrun_rc=$?

echo "== check.sh: single-chip entry compile check =="
GRAFT_FORCE_CPU=1 python - <<'EOF'
import jax, __graft_entry__ as g
fn, args = g.entry()
out = jax.jit(fn)(*args)
jax.block_until_ready(out)
print("entry(): OK")
EOF
entry_rc=$?

echo "== check.sh: bench.py --smoke (fused vs legacy perf path, CPU) =="
GRAFT_FORCE_CPU=1 python bench.py --smoke
smoke_rc=$?

echo "== check.sh: bench.py --mesh-smoke (1-vs-8-device mesh parity, CPU) =="
# named gate: a 1-device and an 8-virtual-device run of the same seeded
# anneal must reproduce the plain engine's placements byte-for-byte, and
# the per-round collective payload must match the gather-candidates-only
# schedule (0 bytes at n=1) — the mesh engine layer's core invariants
GRAFT_FORCE_CPU=1 XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python bench.py --mesh-smoke
mesh_rc=$?

echo "== check.sh: bench.py --mesh --smoke (sharded-model mesh at 25k/2M, CPU) =="
# named gate: the sharded-MODEL mode must (a) reproduce the plain engine's
# placements byte-for-byte at small geometry alongside the replicated
# mesh, and (b) hold <= 1/4 of the replicated model footprint per device
# at the 25k-broker / 2M-partition scale-out north star (full geometry,
# shrunken search) — scaling efficiency + collective bytes are recorded
# in BENCH_mesh_r01.json
GRAFT_FORCE_CPU=1 XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python bench.py --mesh --smoke
mesh_model_rc=$?

echo "== check.sh: bench.py --churn --smoke (shape-bucketed serving, CPU) =="
GRAFT_FORCE_CPU=1 python bench.py --churn --smoke
churn_rc=$?

echo "== check.sh: bench.py --scenarios --smoke (batched what-if evaluation, CPU) =="
# named gate: one batched N-scenario evaluation must be no slower than N
# sequential runs AND produce bit-identical per-scenario objectives —
# batching is an execution detail of the planner, never a numerics change
GRAFT_FORCE_CPU=1 python bench.py --scenarios --smoke
scenarios_rc=$?

echo "== check.sh: bench.py --streaming --smoke (incremental controller replay, CPU) =="
# named gate: a multi-window streaming replay must show (a) the COLD
# controller cycle reproduces today's flatten-and-anneal byte-for-byte,
# (b) warm-started incremental anneals converge in measurably fewer
# rounds at equal goal quality, (c) zero full re-flattens across
# metric-only windows (the in-place delta contract, asserted via
# sensors), and (d) the fused-cycle latency/dispatch contract: every
# steady-state delta cycle after the fused program compiles runs FUSED
# at <= 2 device dispatches (one program launch + one host extraction,
# proved by the dispatch meter) with a sub-second
# window-roll-to-publish p99 (cold-compile cycles excluded via their
# one-shot sensors)
GRAFT_FORCE_CPU=1 python bench.py --streaming --smoke
streaming_rc=$?

echo "== check.sh: streaming controller gate (prior parity, warm start, delta path) =="
# named gate: cold-prior byte parity, warm-start carry (fused==legacy,
# no donated-buffer corruption), move-acceptance prior fitting/decay,
# WindowedHistory delta extraction under topic churn + partial windows,
# LiveState in-place updates, publish/supersede
python -m pytest tests/test_controller.py -q
controller_rc=$?

echo "== check.sh: bench.py --coldstart --smoke (restart SLO: manifest+AOT prewarm, CPU) =="
# named gate: one child process per restart phase (truly cold /
# XLA-cache-only / manifest+AOT); the manifest+AOT phase must report
# ZERO fresh engine traces for manifest-listed buckets, a strictly
# lower cold-start-to-first-proposal wall than truly-cold, and the
# identical objective (the AOT path must never change results)
GRAFT_FORCE_CPU=1 python bench.py --coldstart --smoke
coldstart_rc=$?

echo "== check.sh: cold-start prewarm gate (manifest, AOT fallback ladder, warm pool) =="
# named gate: manifest round-trip + fingerprint rejection, corrupt/
# truncated AOT artifact -> plain-jit fallback (no crash, sensor
# incremented), aval-drift fallback (the r4 regression class),
# never-on-the-request-path, warm-pool priority ordering, fleet
# manifest merging
python -m pytest tests/test_prewarm.py -q
prewarm_rc=$?

echo "== check.sh: bench.py --fleet-smoke (shared-engine fleet economics, CPU) =="
# named gate: a 3-cluster fleet (2 sharing a shape bucket) must end with
# FEWER compiled engines than clusters (the shared AnalyzerCore is real)
# and each cluster's warm proposal wall within 1.5x a single-cluster
# baseline — multi-tenancy must not tax steady-state serving
GRAFT_FORCE_CPU=1 python bench.py --fleet-smoke
fleet_smoke_rc=$?

echo "== check.sh: device scheduler gate (QoS classes, preemption, shed/brownout, parity) =="
# named gate: segmented-vs-unsegmented anneal byte parity (placements,
# objectives, trajectories), urgent queue-to-dispatch wait <= one slice
# budget under a device_slowdown x 20-cluster burst with BACKGROUND
# shedding counted (zero URGENT sheds), aging (background delayed but
# never starved), brownout after sustained overload, FLEET_OVERLOAD
# once per episode, Retry-After on both 429 paths, and the
# scheduler-off byte-for-byte default
python -m pytest tests/test_scheduler.py -q
scheduler_rc=$?

echo "== check.sh: fleet HA gate (leases, fencing, kill-and-takeover) =="
# named gate: the chaos invariants — at most one lease holder per cluster
# at any instant (audit-trail-proven, incl. under seeded store partitions
# + clock skew), zero duplicate submissions across a kill-and-takeover,
# zero leaked throttles, a fenced zombie can neither journal nor mutate,
# and fleet.ha.enabled=false stays byte-for-byte classic
python -m pytest tests/test_fleet_ha.py -q
fleet_ha_rc=$?

echo "== check.sh: bench.py --ha-smoke (lease takeover SLO, CPU) =="
# named gate: 2 instances over 3 synthetic clusters sharing one lease
# store — kill one, time-to-takeover-to-first-proposal under budget and
# the single-holder invariant checked from the lease-store audit trail
GRAFT_FORCE_CPU=1 python bench.py --ha-smoke
ha_smoke_rc=$?

echo "== check.sh: fleet controller gate (N clusters, shared core, isolation) =="
# named gate: shared engine-cache hits across same-bucket clusters,
# per-cluster journal namespacing with zero cross-adoption on restart,
# cluster= routing + per-tenant 429 admission, N-cluster /metrics lint,
# and the 3-FakeKafkaCluster live-socket acceptance story
python -m pytest tests/test_fleet.py -q
fleet_rc=$?

echo "== check.sh: scenario planner gate (what-if parity, forecaster, rightsizer) =="
# named gate: the identity-scenario byte parity, dead-rack/broker-add
# semantics, engine-cache reuse across a scenario batch, and the
# /simulate & /rightsize surfaces — regressions here mislead capacity
# decisions silently
python -m pytest tests/test_planner.py -q
planner_rc=$?

echo "== check.sh: fault supervision gate (degraded mode, breaker, harness) =="
# named gate: every breaker transition / degraded proposal is pinned by
# deterministic fault injection (testing/faults.py), never by a real TPU
# misbehaving on cue.  Runs standalone so a fault-supervision regression
# is named in the summary even when the full suite was skipped via args.
python -m pytest tests/test_faults.py -q
faults_rc=$?

echo "== check.sh: mesh fault-tolerance gate (device loss, carry checkpoints, degrade-and-resume) =="
# named gate: probe fan-out attribution (DEVICE_LOST / COLLECTIVE_STALL
# naming the suspect chip), segmented-vs-unsegmented mesh byte parity,
# reduced-width resume from a slice-boundary carry checkpoint, per-width
# breakers that never open the single-device breaker, scoped parallel
# purge, and the once-per-episode MESH_DEGRADED surface
python -m pytest tests/test_mesh_ft.py -q
mesh_ft_rc=$?

echo "== check.sh: bench.py --mesh-chaos --smoke (mid-anneal device loss, CPU) =="
# named gate: inject a device loss mid-anneal on an 8-virtual-device
# mesh — the optimizer must resume at width 4 from the last checkpoint
# with placements BYTE-EQUAL to a clean uninterrupted run, checkpoint-off
# must keep the dispatch stream byte-for-byte with zero extra dispatches,
# and exactly one MESH_DEGRADED event must arm per degrade episode
GRAFT_FORCE_CPU=1 XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python bench.py --mesh-chaos --smoke
mesh_chaos_rc=$?

echo "== check.sh: crash-safe execution gate (journal recovery, reaper, adaptive) =="
# named gate: the kill-and-restart matrix (process crash mid-move /
# mid-leadership / mid-logdir-copy, truncated-journal replay, stuck-move
# reaper, adaptive-concurrency backoff) must hold regardless of what the
# full suite ran — a regression here strands real reassignments.
python -m pytest tests/test_executor_recovery.py -q
recovery_rc=$?

echo "== check.sh: /metrics exposition lint gate (live scrape) =="
# named gate: boot the simulated service, scrape GET /metrics over HTTP,
# and lint the body with the strict exposition parser (TYPE lines, label
# escaping, counter monotonicity, histogram bucket structure) — a
# malformed exposition breaks every dashboard silently
GRAFT_FORCE_CPU=1 python - <<'EOF'
import urllib.request

from cruise_control_tpu.common.exposition import parse_exposition
from cruise_control_tpu.service.main import build_simulated_service
from cruise_control_tpu.service.progress import OperationProgress

app, fetcher, admin, sampler = build_simulated_service(seed=1)
app.start()
try:
    # one proposal run so the analyzer/device sensor surface registers
    app.cc.proposals(OperationProgress())
    url = f"http://{app.host}:{app.port}{app.prefix}/metrics"
    with urllib.request.urlopen(url, timeout=30) as resp:
        assert resp.headers["Content-Type"].startswith("text/plain"), (
            resp.headers["Content-Type"]
        )
        families = parse_exposition(resp.read().decode())
    for fam in (
        "cruisecontrol_analyzer_proposal_computation_timer_seconds",
        "cruisecontrol_analyzer_proposal_computation_seconds",
        "cruisecontrol_tpu_device_live_buffers",
    ):
        assert fam in families, f"missing family {fam}"
    print(f"exposition lint: OK ({len(families)} families)")
finally:
    app.stop()
EOF
metrics_rc=$?

echo "== check.sh: trace overhead gate (tracing-on adds <2% to a smoke run) =="
# named gate: the flight recorder is ON by default on the hot proposal
# path, so its cost is pinned by measurement, not assumption
GRAFT_FORCE_CPU=1 python bench.py --trace-overhead
overhead_rc=$?

echo "== check.sh: black-box overhead gate (spool-on adds <2%, disabled path writes nothing) =="
# named gate: the crash-durable dispatch spool is ON by default wherever
# a durable dir exists; its per-dispatch write+flush must stay
# unmeasurable beside an engine run, recording must not perturb results
# (byte-identical placements), and the disabled path must write zero bytes
GRAFT_FORCE_CPU=1 python bench.py --blackbox-overhead
blackbox_overhead_rc=$?

echo "== check.sh: ledger overhead gate (diagnostics+ledger on adds <2%, byte-identical placements) =="
# named gate: convergence diagnostics + the decision ledger are ON by
# default; the per-run decision record and the diagnostics-on fused
# program must stay unmeasurable beside an engine run, placements must be
# byte-identical on vs off, and the disabled path must write zero bytes
GRAFT_FORCE_CPU=1 python bench.py --ledger-overhead
ledger_overhead_rc=$?

echo "== check.sh: decision ledger gate (durability, joins, calibration, /explain) =="
# named gate: torn-tail append-after-truncate, retention never pruning a
# pending-outcome episode, fleet two-cluster ledger isolation,
# disabled-path zero bytes, diagnostics byte-parity across
# plain/segmented/mesh, and the decision→outcome→calibration→/explain
# acceptance story
python -m pytest tests/test_ledger.py -q
ledger_rc=$?

echo "== check.sh: black-box gate (crash-durable spool, kill/hang post-mortems) =="
# named gate: a process killed -9 (or hang-timed-out) mid-anneal must
# leave a spool that replays to the exact in-flight dispatch (bucket,
# slice index, wait class), the dryrun timeout verdict must embed
# structured last-dispatch records, and the torn-tail/ring-rotation
# reader invariants must hold
python -m pytest tests/test_blackbox.py -q
blackbox_rc=$?

echo "== check.sh: SLO gate (burn-rate windows, once-per-episode alerting, /slo) =="
# named gate: multi-window burn-rate math on injected clocks, a
# sustained freshness breach fires SLO_BURN exactly once per episode
# (twice across two episodes), burn gauges render in a lint-clean
# /metrics scrape, and GET /slo serves the registry state
python -m pytest tests/test_slo.py -q
slo_rc=$?

echo "== check.sh: flight-recorder unit gate (trace model, exposition parser) =="
python -m pytest tests/test_trace.py -q
trace_rc=$?

echo
echo "check.sh summary: suite=$suite_rc dryrun=$dryrun_rc entry=$entry_rc smoke=$smoke_rc mesh=$mesh_rc mesh_model=$mesh_model_rc churn=$churn_rc streaming=$streaming_rc controller=$controller_rc coldstart=$coldstart_rc prewarm=$prewarm_rc fleet_smoke=$fleet_smoke_rc fleet=$fleet_rc fleet_ha=$fleet_ha_rc ha_smoke=$ha_smoke_rc scheduler=$scheduler_rc scenarios=$scenarios_rc planner=$planner_rc faults=$faults_rc mesh_ft=$mesh_ft_rc mesh_chaos=$mesh_chaos_rc recovery=$recovery_rc metrics=$metrics_rc overhead=$overhead_rc blackbox_overhead=$blackbox_overhead_rc ledger_overhead=$ledger_overhead_rc ledger=$ledger_rc blackbox=$blackbox_rc slo=$slo_rc trace=$trace_rc"
[ "$suite_rc" -eq 0 ] && [ "$dryrun_rc" -eq 0 ] && [ "$entry_rc" -eq 0 ] && [ "$smoke_rc" -eq 0 ] && [ "$mesh_rc" -eq 0 ] && [ "$mesh_model_rc" -eq 0 ] && [ "$churn_rc" -eq 0 ] && [ "$streaming_rc" -eq 0 ] && [ "$controller_rc" -eq 0 ] && [ "$coldstart_rc" -eq 0 ] && [ "$prewarm_rc" -eq 0 ] && [ "$fleet_smoke_rc" -eq 0 ] && [ "$fleet_rc" -eq 0 ] && [ "$fleet_ha_rc" -eq 0 ] && [ "$ha_smoke_rc" -eq 0 ] && [ "$scheduler_rc" -eq 0 ] && [ "$scenarios_rc" -eq 0 ] && [ "$planner_rc" -eq 0 ] && [ "$faults_rc" -eq 0 ] && [ "$mesh_ft_rc" -eq 0 ] && [ "$mesh_chaos_rc" -eq 0 ] && [ "$recovery_rc" -eq 0 ] && [ "$metrics_rc" -eq 0 ] && [ "$overhead_rc" -eq 0 ] && [ "$blackbox_overhead_rc" -eq 0 ] && [ "$ledger_overhead_rc" -eq 0 ] && [ "$ledger_rc" -eq 0 ] && [ "$blackbox_rc" -eq 0 ] && [ "$slo_rc" -eq 0 ] && [ "$trace_rc" -eq 0 ]
