"""Dev smoke: run the optimizer on small fixtures and print what happened."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig
from cruise_control_tpu.testing.fixtures import (
    RandomClusterSpec,
    dead_broker_cluster,
    rack_violated_cluster,
    random_cluster,
    small_cluster,
)


def run(name, state, cfg):
    opt = GoalOptimizer(config=cfg)
    res = opt.optimize(state, verbose=True)
    print(f"== {name} ==")
    print("  summary:", {k: (round(v, 4) if isinstance(v, float) else v) for k, v in res.summary().items()})
    print("  violations before:", dict(zip(res.goal_names, np.round(res.violations_before, 5))))
    print("  violations after: ", dict(zip(res.goal_names, np.round(res.violations_after, 5))))
    print("  history:", res.history)
    return res


if __name__ == "__main__":
    cfg = OptimizerConfig(num_candidates=256, leadership_candidates=64,
                          steps_per_round=32, num_rounds=4, seed=0)
    run("small", small_cluster(), cfg)
    run("rack", rack_violated_cluster(), cfg)
    run("dead", dead_broker_cluster(), cfg)
    cfg2 = OptimizerConfig(num_candidates=1024, leadership_candidates=256,
                           steps_per_round=64, num_rounds=6, seed=0)
    run("random50", random_cluster(RandomClusterSpec(num_brokers=20, num_partitions=500, skew=1.0)), cfg2)
