#!/usr/bin/env bash
# Start the cruise-control-tpu service (reference kafka-cruise-control-start.sh).
# Usage: scripts/cruise-control-start.sh [config.properties] [-daemon]
set -euo pipefail
base="$(cd "$(dirname "$0")/.." && pwd)"
config="${1:-}"
pidfile="${CRUISE_CONTROL_PID_FILE:-/tmp/cruise-control-tpu.pid}"
cmd=(python -m cruise_control_tpu.service.main)
[[ -n "$config" && "$config" != "-daemon" ]] && cmd+=("$config")
cd "$base"
if [[ "${*: -1}" == "-daemon" ]]; then
  nohup "${cmd[@]}" >"${CRUISE_CONTROL_LOG:-/tmp/cruise-control-tpu.log}" 2>&1 &
  echo $! >"$pidfile"
  echo "started pid $(cat "$pidfile") (log: ${CRUISE_CONTROL_LOG:-/tmp/cruise-control-tpu.log})"
else
  exec "${cmd[@]}"
fi
