"""Generate BASELINE_GREEDY.json: the greedy CPU oracle run to convergence
per bench config (VERDICT r2 weak #4 — the in-bench greedy was
budget-truncated, so `tpu_beats_greedy` compared against a cut-off run).

Builds the EXACT states bench.py uses (same specs/seeds/chains, imported
from bench) and runs `greedy_optimize` with generous caps.  Each entry
records the objective, wall seconds, move count, and whether the run
terminated on its own (`converged`) or hit the safety deadline.  bench.py
prefers these committed numbers over re-running greedy.

Usage:  [GREEDY_CONFIGS=1,2,3,5] [GREEDY_BUDGET_S=1800] python
scripts/gen_greedy_baselines.py
"""

import json
import os
import sys
import time

sys.path.insert(0, "/root/repo")

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the bench host pins the TPU platform in sitecustomize; the env var
    # alone is ignored — pin CPU explicitly so baseline generation can run
    # beside a TPU bench
    jax.config.update("jax_platforms", "cpu")

import bench  # noqa: E402 — spec/config source of truth

from cruise_control_tpu.analyzer.greedy import greedy_optimize  # noqa: E402
from cruise_control_tpu.analyzer.objective import DEFAULT_CHAIN, GoalChain  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "BASELINE_GREEDY.json")
BUDGET = float(os.environ.get("GREEDY_BUDGET_S", "1800"))


def _state_and_chain(name):
    from cruise_control_tpu.testing.fixtures import (
        RandomClusterSpec,
        random_cluster_fast,
        small_cluster,
    )

    if name == "config1":
        return small_cluster(), DEFAULT_CHAIN, dict(moves=20000, dests=8)
    if name == "config2":
        chain = GoalChain.from_names([
            "ReplicaCapacityGoal",
            "DiskUsageDistributionGoal",
            "NetworkInboundUsageDistributionGoal",
            "NetworkOutboundUsageDistributionGoal",
            "CpuUsageDistributionGoal",
        ])
        state = random_cluster_fast(RandomClusterSpec(**bench.SMALL_SPEC), seed=42)
        return state, chain, dict(moves=20000, dests=8)
    if name == "config3":
        chain = GoalChain.from_names([
            "RackAwareGoal",
            "DiskCapacityGoal",
            "IntraBrokerDiskCapacityGoal",
            "IntraBrokerDiskUsageDistributionGoal",
        ])
        state = random_cluster_fast(
            RandomClusterSpec(**{**bench.MID_SPEC, "disks_per_broker": 4}), seed=42
        )
        return state, chain, dict(moves=20000, dests=8)
    if name == "config5":
        import dataclasses as dc

        import jax.numpy as jnp
        import numpy as np

        state = random_cluster_fast(
            RandomClusterSpec(**bench.NORTH_STAR_SPEC), seed=42
        )
        B = state.shape.B
        n_dead = max(2, B // 100)
        alive = np.asarray(state.broker_alive).copy()
        alive[np.arange(B - n_dead, B)] = False
        offline = np.asarray(state.replica_offline) | ~alive[
            np.asarray(state.replica_broker)
        ]
        state = dc.replace(
            state,
            broker_alive=jnp.asarray(alive),
            disk_alive=jnp.asarray(alive[:, None] & np.asarray(state.disk_alive)),
            replica_offline=jnp.asarray(offline),
        )
        return state, DEFAULT_CHAIN, dict(moves=1000, dests=6)
    raise ValueError(name)


def main():
    wanted = (os.environ.get("GREEDY_CONFIGS") or "1,2,3,5").replace(" ", "").split(",")
    results = {}
    if os.path.exists(OUT):
        with open(OUT) as f:
            results = json.load(f)
    for n in wanted:
        name = f"config{n}"
        print(f"=== {name} (budget {BUDGET:.0f}s) ===", flush=True)
        state, chain, caps = _state_and_chain(name)
        t0 = time.time()
        final, info = greedy_optimize(
            state, chain, max_moves_per_goal=caps["moves"],
            candidate_dests=caps["dests"], seed=0, time_budget_s=BUDGET,
            return_info=True,
        )
        obj, _, _ = chain.evaluate(final)
        results[name] = dict(
            objective=float(obj),
            seconds=info["seconds"],
            moves=info["moves"],
            converged=info["converged"],
            budget_s=BUDGET,
            fingerprint=bench._baseline_fingerprint(state, chain),
        )
        print(f"{name}: {results[name]}", flush=True)
        with open(OUT, "w") as f:
            json.dump(results, f, indent=2, sort_keys=True)
        print(f"wrote {OUT} ({time.time()-t0:.0f}s)", flush=True)


if __name__ == "__main__":
    main()
