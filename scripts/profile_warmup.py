"""Where does the north-star warmup go?  trace vs lower vs compile."""

import os
import sys
import time

sys.path.insert(0, "/root/repo")

from cruise_control_tpu.common.compilation_cache import enable_persistent_cache

enable_persistent_cache(os.environ.get("BENCH_COMPILE_CACHE", "~/.cache/cruise_control_tpu/xla"))

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer import DEFAULT_CHAIN, Engine, OptimizerConfig
from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

NORTH = RandomClusterSpec(
    num_brokers=2600, num_racks=52, num_topics=200, num_partitions=200_000,
    min_replication=2, max_replication=3, skew=0.5,
    broker_capacity=(100.0, 500_000.0, 500_000.0, 5_000_000.0),
    mean_cpu=0.15, mean_nw_in=400.0, mean_nw_out=500.0, mean_disk=4000.0,
)

t0 = time.monotonic()
state = random_cluster_fast(NORTH, seed=42)
print(f"fixture {time.monotonic()-t0:.1f}s", flush=True)

t0 = time.monotonic()
cfg = OptimizerConfig(num_candidates=16384, leadership_candidates=4096,
                     steps_per_round=64, num_rounds=8, seed=0)
eng = Engine(state, DEFAULT_CHAIN, config=cfg)
print(f"engine build (statics) {time.monotonic()-t0:.1f}s", flush=True)

t0 = time.monotonic()
carry = eng.init_carry(jax.random.PRNGKey(0))
jax.block_until_ready(carry.broker_load)
print(f"init_carry (jit refresh compile+run) {time.monotonic()-t0:.1f}s", flush=True)

sx = eng.statics
plan = eng._jit_plan(sx, carry)
jax.block_until_ready(plan.broker_cdf)
temps = jnp.zeros((cfg.steps_per_round,), jnp.float32)

t0 = time.monotonic()
traced = eng._scan.trace(sx, carry, temps, plan)
t_trace = time.monotonic() - t0
t0 = time.monotonic()
lowered = traced.lower()
t_lower = time.monotonic() - t0
t0 = time.monotonic()
compiled = lowered.compile()
t_compile = time.monotonic() - t0
print(f"scan: trace={t_trace:.1f}s lower={t_lower:.1f}s compile={t_compile:.1f}s",
      flush=True)

t0 = time.monotonic()
out = compiled(sx, carry, temps, plan)
jax.block_until_ready(out[0].broker_load)
print(f"scan run {time.monotonic()-t0:.2f}s", flush=True)

for name, fn, args in (
    ("round_prep", eng._jit_round_prep, (sx, carry)),
    ("violations", eng._jit_violations, (sx, carry)),
    ("objective", eng._jit_objective, (sx, carry)),
):
    t0 = time.monotonic()
    tr = fn.trace(*args)
    lo = tr.lower()
    t_l = time.monotonic() - t0
    t0 = time.monotonic()
    co = lo.compile()
    print(f"{name}: trace+lower={t_l:.1f}s compile={time.monotonic()-t0:.1f}s", flush=True)
