#!/usr/bin/env bash
# Stop a daemonized cruise-control-tpu service (reference kafka-cruise-control-stop.sh).
set -euo pipefail
pidfile="${CRUISE_CONTROL_PID_FILE:-/tmp/cruise-control-tpu.pid}"
if [[ ! -f "$pidfile" ]]; then
  echo "no pid file at $pidfile" >&2
  exit 1
fi
pid="$(cat "$pidfile")"
kill "$pid" 2>/dev/null && echo "stopped pid $pid" || echo "pid $pid not running"
rm -f "$pidfile"
