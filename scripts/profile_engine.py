"""Profile the SA engine step on the current jax backend."""

import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer import DEFAULT_CHAIN, Engine, OptimizerConfig
from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

NORTH = RandomClusterSpec(
    num_brokers=2600, num_racks=52, num_topics=200, num_partitions=200_000,
    min_replication=2, max_replication=3, skew=0.5,
    broker_capacity=(100.0, 500_000.0, 500_000.0, 5_000_000.0),
    mean_cpu=0.15, mean_nw_in=400.0, mean_nw_out=500.0, mean_disk=4000.0,
)
MID = RandomClusterSpec(
    num_brokers=500, num_racks=20, num_topics=100, num_partitions=50_000, skew=0.5,
    broker_capacity=(100.0, 300_000.0, 300_000.0, 3_000_000.0),
    mean_cpu=0.2, mean_nw_in=500.0, mean_nw_out=600.0, mean_disk=5000.0,
)


def timed_scan(state, K, Kl, steps, label):
    cfg = OptimizerConfig(num_candidates=K, leadership_candidates=Kl,
                          steps_per_round=steps, num_rounds=1)
    t0 = time.time()
    eng = Engine(state, DEFAULT_CHAIN, config=cfg)
    carry = eng.init_carry(jax.random.PRNGKey(0))
    jax.block_until_ready(carry.broker_load)
    t_init = time.time() - t0
    temps = jnp.zeros((steps,), jnp.float32)
    t0 = time.time()
    carry2, stats = eng._scan(carry, temps)
    jax.block_until_ready(carry2.broker_load)
    t_compile_and_run = time.time() - t0
    t0 = time.time()
    carry3, stats = eng._scan(carry, temps)
    jax.block_until_ready(carry3.broker_load)
    t_run = time.time() - t0
    print(f"{label}: init={t_init:.2f}s compile+run={t_compile_and_run:.1f}s "
          f"run={t_run:.3f}s per_step={1000*t_run/steps:.2f}ms "
          f"accepted={int(jax.device_get(stats['accepted']).sum())}")
    return t_run / steps


print("device:", jax.devices()[0])
mid = random_cluster_fast(MID, seed=42)
north = random_cluster_fast(NORTH, seed=42)

timed_scan(mid, 4096, 1024, 16, "mid   K=4096")
timed_scan(mid, 1024, 256, 16, "mid   K=1024")
timed_scan(north, 4096, 1024, 16, "north K=4096")
timed_scan(north, 1024, 256, 16, "north K=1024")
timed_scan(north, 16384, 4096, 16, "north K=16384")
