"""Benchmark ladder: the five BASELINE.md configs, headline last.

Prints one JSON line per benchmark config, with the north-star line
(config 4: 2,600-broker / 200k-partition full-default-goals proposal,
target < 10 s on one TPU chip) printed LAST so drivers that parse the
final line get the headline metric.  `vs_baseline` on the headline is
wall / 10s (the fraction of the north-star budget used; < 1.0 beats it).

Configs (BASELINE.md "Benchmark configs to implement" + additions):
  1 deterministic 3-broker parity oracle vs reference-style greedy
  2 RandomCluster 50/5k, ResourceDistribution+ReplicaCapacity goals
  3 JBOD 500/50k, DiskCapacity+RackAware goals
  4 north-star 2600/200k, full default.goals          <- headline
  5 broker-decommission self-healing on the 2600/200k model
  6 cluster-model generation wall-clock at north-star scale
  7 ShardedEngine (model-sharded scale-out path) at north-star scale

Greedy comparisons (configs 1,2,3,5) run the CPU oracle
(cruise_control_tpu/analyzer/greedy.py) under a wall-clock budget — the
reference's sequential search runs minutes at scale (SURVEY §6); the
budgeted objective is what it achieves in comparable time.

Env: BENCH_CONFIGS="1,2,3,4,5" to select (default all);
BENCH_SCALE=north_star|mid|small retained for the headline fixture size.

`bench.py --churn [--smoke]` runs the topology-churn scenario instead:
N generations with partition creates (+ a broker add) served bucketed vs
exact, gating on "churned generations compile zero engines" (see churn()).

`bench.py --coldstart [--smoke]` runs the restart-SLO ladder instead: a
child process per phase (truly cold / XLA-cache-only / manifest+AOT)
measures cold-start-to-first-proposal and gates the manifest+AOT phase
on zero fresh traces for manifest buckets (see coldstart()).

warmup_s on the headline is the FIRST optimize() call in a fresh process
with a warm persistent XLA cache: engine statics build + program
trace/lower + cache-hit compile + one full proposal computation.  It is
the operator's honest time-to-first-proposal — and that first pass
already yields a complete usable proposal set (the service's precompute
loop caches it), not discarded warm-up work.  Cold cache (first process
ever) adds ~60s of XLA compilation on top.
"""

import json
import os
import sys
import time

import numpy as np

NORTH_STAR_SPEC = dict(
    num_brokers=2600,
    num_racks=52,
    num_topics=200,
    num_partitions=200_000,
    min_replication=2,
    max_replication=3,
    skew=0.5,
    broker_capacity=(100.0, 500_000.0, 500_000.0, 5_000_000.0),
    mean_cpu=0.15,
    mean_nw_in=400.0,
    mean_nw_out=500.0,
    mean_disk=4000.0,
)
MID_SPEC = dict(
    num_brokers=500,
    num_racks=20,
    num_topics=100,
    num_partitions=50_000,
    skew=0.5,
    broker_capacity=(100.0, 300_000.0, 300_000.0, 3_000_000.0),
    mean_cpu=0.2,
    mean_nw_in=500.0,
    mean_nw_out=600.0,
    mean_disk=5000.0,
)
SMALL_SPEC = dict(num_brokers=50, num_partitions=5000, num_racks=5, num_topics=20, skew=0.8)

SEARCH = dict(
    num_candidates=16384,
    leadership_candidates=4096,
    steps_per_round=int(os.environ.get("BENCH_STEPS", "64")),
    num_rounds=8,
    seed=0,
)
SEARCH_SMALL = dict(
    num_candidates=2048,
    leadership_candidates=512,
    steps_per_round=64,
    num_rounds=8,
    seed=0,
)


def _emit(**kv):
    print(json.dumps(kv), flush=True)


def _run_tpu(opt, state, chain):
    """Warm (compile) + measured run; returns (result, wall_s, warm_s)."""
    warm = opt.optimize(state)
    t0 = time.monotonic()
    res = opt.optimize(state)
    return res, time.monotonic() - t0, warm.wall_seconds


def _baseline_fingerprint(state, chain) -> str:
    """Cheap identity of (cluster, goal chain) a greedy baseline was built
    for — a changed spec/seed/fixture/chain must invalidate the committed
    number LOUDLY instead of silently comparing different clusters."""
    import hashlib

    s = state.shape
    n_valid = int(np.asarray(state.replica_valid).sum())
    # dead-broker topology is part of the problem (config5 decommission):
    # changing WHICH brokers die must invalidate the baseline
    alive = np.asarray(state.broker_valid) & np.asarray(state.broker_alive)
    n_alive = int(alive.sum())
    alive_sig = int(np.nonzero(~alive)[0].sum())
    # 4 significant digits: fixtures built partly on-device differ CPU vs
    # TPU in the last f32 bits, and the baseline is generated on CPU while
    # the bench checks on TPU — the signature must survive that noise while
    # still catching real spec/seed changes
    load_sig = float(np.asarray(state.replica_load_leader, np.float64).sum())
    names = ",".join(g.name for g in chain.goals)
    raw = f"{s.B}x{s.P}x{n_valid}|{n_alive}|{alive_sig}|{load_sig:.4g}|{names}"
    return hashlib.sha256(raw.encode()).hexdigest()[:16]


def _greedy_objective(config_name, state, chain, budget_s, *, moves=400, dests=8, seed=0):
    """Greedy-oracle comparison numbers for one bench config.

    Prefers the committed CONVERGED baseline (BASELINE_GREEDY.json, built by
    scripts/gen_greedy_baselines.py) — comparing against a budget-truncated
    oracle understates the bar (VERDICT r2 weak #4).  Falls back to an
    in-bench budgeted run, honestly labeled converged=False when cut off.
    Returns (objective, seconds, converged).
    """
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE_GREEDY.json")
    if os.path.exists(path):
        with open(path) as f:
            entry = json.load(f).get(config_name)
        if entry is not None:
            fp = _baseline_fingerprint(state, chain)
            if entry.get("fingerprint") not in (None, fp):
                print(
                    f"greedy baseline {config_name} is STALE "
                    f"(fingerprint {entry.get('fingerprint')} != {fp}); "
                    "re-run scripts/gen_greedy_baselines.py — falling back "
                    "to in-bench greedy",
                    file=sys.stderr,
                )
            else:
                return float(entry["objective"]), float(entry["seconds"]), bool(
                    entry["converged"]
                )
    from cruise_control_tpu.analyzer.greedy import greedy_optimize

    final, info = greedy_optimize(
        state, chain, max_moves_per_goal=moves, candidate_dests=dests, seed=seed,
        time_budget_s=budget_s, return_info=True,
    )
    obj, _, _ = chain.evaluate(final)
    return float(obj), info["seconds"], info["converged"]


def config_1():
    """Deterministic 3-broker parity oracle (DeterministicCluster analog)."""
    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig
    from cruise_control_tpu.analyzer.objective import DEFAULT_CHAIN
    from cruise_control_tpu.testing.fixtures import small_cluster

    state = small_cluster()
    opt = GoalOptimizer(config=OptimizerConfig(**SEARCH_SMALL))
    res, wall, _ = _run_tpu(opt, state, DEFAULT_CHAIN)
    greedy_obj, greedy_s, greedy_conv = _greedy_objective(
        "config1", state, DEFAULT_CHAIN, budget_s=120
    )
    _emit(
        metric="config1_deterministic_parity",
        value=round(wall, 3),
        unit="s",
        vs_baseline=round(res.objective_after / max(greedy_obj, 1e-12), 4),
        tpu_objective=round(res.objective_after, 6),
        greedy_objective=round(greedy_obj, 6),
        greedy_seconds=round(greedy_s, 1),
        greedy_converged=greedy_conv,
        tpu_beats_greedy=bool(res.objective_after <= greedy_obj * (1 + 1e-4) + 1e-9),
        balancedness_after=round(res.balancedness_after, 2),
    )


def config_2():
    """RandomCluster 50/5k, ResourceDistribution + ReplicaCapacity goals."""
    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig
    from cruise_control_tpu.analyzer.objective import GoalChain
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

    chain = GoalChain.from_names([
        "ReplicaCapacityGoal",
        "DiskUsageDistributionGoal",
        "NetworkInboundUsageDistributionGoal",
        "NetworkOutboundUsageDistributionGoal",
        "CpuUsageDistributionGoal",
    ])
    state = random_cluster_fast(RandomClusterSpec(**SMALL_SPEC), seed=42)
    opt = GoalOptimizer(chain=chain, config=OptimizerConfig(**SEARCH_SMALL))
    res, wall, warm = _run_tpu(opt, state, chain)
    greedy_obj, greedy_s, greedy_conv = _greedy_objective(
        "config2", state, chain, budget_s=60
    )
    _emit(
        metric="config2_random_50_5k",
        value=round(wall, 3),
        unit="s",
        vs_baseline=round(res.objective_after / max(greedy_obj, 1e-12), 4),
        tpu_objective=round(res.objective_after, 6),
        greedy_objective=round(greedy_obj, 6),
        greedy_seconds=round(greedy_s, 1),
        greedy_converged=greedy_conv,
        tpu_beats_greedy=bool(res.objective_after <= greedy_obj * (1 + 1e-4) + 1e-9),
        balancedness_before=round(res.balancedness_before, 2),
        balancedness_after=round(res.balancedness_after, 2),
        num_replica_moves=res.num_inter_broker_moves,
        warmup_s=round(warm, 1),
    )


def config_3():
    """JBOD 500-broker/50k-partition, DiskCapacity + RackAware goals."""
    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig
    from cruise_control_tpu.analyzer.objective import GoalChain
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

    chain = GoalChain.from_names([
        "RackAwareGoal",
        "DiskCapacityGoal",
        "IntraBrokerDiskCapacityGoal",
        "IntraBrokerDiskUsageDistributionGoal",
    ])
    state = random_cluster_fast(
        RandomClusterSpec(**{**MID_SPEC, "disks_per_broker": 4}), seed=42
    )
    opt = GoalOptimizer(chain=chain, config=OptimizerConfig(**SEARCH))
    res, wall, warm = _run_tpu(opt, state, chain)
    greedy_obj, greedy_s, greedy_conv = _greedy_objective(
        "config3", state, chain, budget_s=60
    )
    _emit(
        metric="config3_jbod_500_50k",
        value=round(wall, 3),
        unit="s",
        vs_baseline=round(res.objective_after / max(greedy_obj, 1e-12), 4),
        tpu_objective=round(res.objective_after, 6),
        greedy_objective=round(greedy_obj, 6),
        greedy_seconds=round(greedy_s, 1),
        greedy_converged=greedy_conv,
        tpu_beats_greedy=bool(res.objective_after <= greedy_obj * (1 + 1e-4) + 1e-9),
        balancedness_before=round(res.balancedness_before, 2),
        balancedness_after=round(res.balancedness_after, 2),
        num_replica_moves=res.num_inter_broker_moves,
        warmup_s=round(warm, 1),
    )


def config_6():
    """Cluster-model generation wall-clock at north-star scale.

    The monitor half of time-to-proposal: synthetic 2600-broker/200k-
    partition topology + a filled 4-window aggregator, timed through
    LoadMonitor.cluster_model() (aggregate -> columnar join ->
    build_state_columnar -> device arrays).  The reference meters this as
    its cluster-model-creation-timer sensor (monitor/LoadMonitor.java:100,510);
    round-3 VERDICT flagged it as unmeasured, target <= 1s warm.
    """
    from cruise_control_tpu.monitor import (
        KAFKA_METRIC_DEF,
        FixedCapacityResolver,
        LoadMonitor,
        ModelCompletenessRequirements,
        WindowedMetricSampleAggregator,
    )
    from cruise_control_tpu.monitor.sampling import PartitionEntity
    from cruise_control_tpu.monitor.topology import StaticMetadataProvider
    from cruise_control_tpu.testing.synthetic import synthetic_topology

    t_fx = time.monotonic()
    topo = synthetic_topology(
        num_brokers=NORTH_STAR_SPEC["num_brokers"],
        topics={f"t{i:03d}": 1000 for i in range(200)},  # 200k partitions
        seed=42,
    )
    cols = topo.columns()
    ents = [
        PartitionEntity(int(t), int(p))
        for t, p in zip(cols.part_topic, cols.part_num)
    ]
    agg = WindowedMetricSampleAggregator(
        4, 1000, 1, KAFKA_METRIC_DEF, initial_capacity=len(ents)
    )
    rng = np.random.default_rng(0)
    M = KAFKA_METRIC_DEF.num_metrics
    for w in range(5):
        agg.add_samples_columnar(
            ents, w * 1000 + 5, rng.uniform(1, 10, (len(ents), M)).astype(np.float32)
        )
    monitor = LoadMonitor(
        StaticMetadataProvider(topo),
        FixedCapacityResolver(list(NORTH_STAR_SPEC["broker_capacity"])),
        agg,
    )
    req = ModelCompletenessRequirements(min_required_num_windows=2)
    fixture_s = time.monotonic() - t_fx
    t0 = time.monotonic()
    state = monitor.cluster_model(req)
    first = time.monotonic() - t0
    walls = []
    for _ in range(3):
        t0 = time.monotonic()
        state = monitor.cluster_model(req)
        walls.append(time.monotonic() - t0)
    wall = sorted(walls)[1]  # median of 3
    _emit(
        metric="cluster_model_creation_north_star",
        value=round(wall, 3),
        unit="s",
        vs_baseline=round(wall / 1.0, 4),  # fraction of the 1s target
        first_call_s=round(first, 2),
        fixture_gen_s=round(fixture_s, 1),
        brokers=state.shape.B,
        partitions=state.shape.P,
        replicas=int(np.asarray(state.replica_valid).sum()),
        monitored_partitions=agg.num_entities(),
    )


def config_7():
    """ShardedEngine at NORTH-STAR scale on the available mesh (1 real
    device on the bench host), measured AGAINST the plain engine on the
    same fixture/config: proves the mesh-layer program — the multi-host
    scale-out path — compiles, fits in HBM, improves the objective at
    2600x200k, and emits the two driver-capturable targets: warm_start_s
    (time to first sharded proposal, < 30 s target) and
    shard_overhead_pct (sharded n=1 wall vs plain engine wall, < 10%
    target — the mesh layer's n=1 program traces to the plain fused
    program, VERDICT r5 item 4)."""
    import jax

    from cruise_control_tpu.analyzer import Engine, OptimizerConfig
    from cruise_control_tpu.analyzer.objective import DEFAULT_CHAIN
    from cruise_control_tpu.parallel.sharded import ShardedEngine, model_mesh

    state = _headline_state("north_star")
    cfg = OptimizerConfig(**{**SEARCH, "num_rounds": 4})
    n_dev = len(jax.devices())

    def timed_run(engine):
        t0 = time.monotonic()
        final, _history = engine.run()
        jax.block_until_ready(final.replica_broker)
        return final, time.monotonic() - t0

    # plain single-device reference: same fixture, same search config
    plain = Engine(state, DEFAULT_CHAIN, config=cfg)
    _, plain_warm = timed_run(plain)
    _, plain_wall = timed_run(plain)

    se = ShardedEngine(state, DEFAULT_CHAIN, mesh=model_mesh(), config=cfg)
    final, warm = timed_run(se)
    final, wall = timed_run(se)
    obj0, _, _ = DEFAULT_CHAIN.evaluate(state)
    obj1, _, _ = DEFAULT_CHAIN.evaluate(final)
    overhead_pct = (wall - plain_wall) / max(plain_wall, 1e-9) * 100.0
    _emit(
        metric="sharded_proposal_wall_clock_north_star",
        value=round(wall, 3),
        unit="s",
        vs_baseline=round(wall / 10.0, 4),
        n_devices=n_dev,
        brokers=state.shape.B,
        partitions=state.shape.P,
        objective_before=round(float(obj0), 5),
        objective_after=round(float(obj1), 5),
        improved=bool(float(obj1) < float(obj0)),
        warmup_s=round(warm, 1),
        warm_start_s=round(warm, 3),
        plain_wall_s=round(plain_wall, 3),
        plain_warm_start_s=round(plain_warm, 3),
        shard_overhead_pct=round(overhead_pct, 2),
        shard_overhead_ok=bool(overhead_pct < 10.0),
        collective_bytes_per_round=int(se.collective_bytes_per_round),
    )


def _headline_state(scale):
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

    specs = {
        "north_star": NORTH_STAR_SPEC,
        "mid": MID_SPEC,
        "small": SMALL_SPEC,
    }
    return random_cluster_fast(RandomClusterSpec(**specs[scale]), seed=42)


def config_5(opt, scale):
    """Broker decommission + offline-replica self-healing at headline scale.

    Reuses the headline optimizer/engine: same shape + config -> zero
    recompilation (statics rebind), the steady-state self-healing path.
    """
    import dataclasses as dc

    import jax.numpy as jnp

    from cruise_control_tpu.analyzer.objective import DEFAULT_CHAIN

    state = _headline_state(scale)
    # decommission 1% of brokers (>= 2): their replicas go offline
    B = state.shape.B
    n_dead = max(2, B // 100)
    alive = np.asarray(state.broker_alive).copy()
    dead_ids = np.arange(B - n_dead, B)
    alive[dead_ids] = False
    offline = np.asarray(state.replica_offline) | ~alive[np.asarray(state.replica_broker)]
    state = dc.replace(
        state,
        broker_alive=jnp.asarray(alive),
        disk_alive=jnp.asarray(alive[:, None] & np.asarray(state.disk_alive)),
        replica_offline=jnp.asarray(offline),
    )
    res, wall, _ = _run_tpu(opt, state, DEFAULT_CHAIN)
    after = res.state_after
    remaining = int(
        (
            np.asarray(after.replica_valid)
            & ~np.asarray(after.broker_alive)[np.asarray(after.replica_broker)]
        ).sum()
    )
    # the committed config5 baseline is generated at north-star scale ONLY —
    # after a scale fallback the entry would compare apples to oranges
    baseline_key = "config5" if scale == "north_star" else f"config5_{scale}"
    greedy_obj, greedy_s, greedy_conv = _greedy_objective(
        baseline_key, state, DEFAULT_CHAIN, budget_s=90, moves=100, dests=6
    )
    _emit(
        metric="config5_decommission_self_healing",
        value=round(wall, 3),
        unit="s",
        vs_baseline=round(res.objective_after / max(greedy_obj, 1e-12), 4),
        scale=scale,
        dead_brokers=int(n_dead),
        offline_replicas_before=int(offline.sum()),
        offline_replicas_after=remaining,
        evacuated=bool(remaining == 0),
        tpu_objective=round(res.objective_after, 6),
        greedy_objective=round(greedy_obj, 6),
        greedy_seconds=round(greedy_s, 1),
        greedy_converged=greedy_conv,
        tpu_beats_greedy=bool(res.objective_after <= greedy_obj * (1 + 1e-4) + 1e-9),
        balancedness_before=round(res.balancedness_before, 2),
        balancedness_after=round(res.balancedness_after, 2),
        violated_goals_after=res.violated_goals_after(1e-6),
        num_replica_moves=res.num_inter_broker_moves,
        num_leader_moves=res.num_leadership_moves,
    )


def config_4(scale_order):
    """North-star headline: full default.goals proposal wall-clock.

    Returns (optimizer, scale) so config 5 can reuse the compiled engine.
    """
    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig

    result = None
    opt = None
    used = None
    for sc in scale_order:
        try:
            t_gen = time.monotonic()
            state = _headline_state(sc)
            gen_s = time.monotonic() - t_gen
            cfg = OptimizerConfig(**SEARCH)
            from cruise_control_tpu.common.sensors import REGISTRY

            opt = GoalOptimizer(config=cfg, sensors=REGISTRY)
            # warm-up run compiles the engine for this cluster shape; the
            # measured run rebinds the cached engine (zero recompilation) —
            # steady-state service behavior, where the proposal precompute
            # loop reuses the compiled program (reference GoalOptimizer
            # proposal cache, analyzer/GoalOptimizer.java:276).
            warm = opt.optimize(state)
            t0 = time.monotonic()
            res = opt.optimize(state)
            wall = time.monotonic() - t0
            # device/host split from the history timing record: localizes a
            # wall-clock regression to device search vs host extraction.
            # The split is meaningful under async (TPU) dispatch only — on a
            # synchronous CPU backend device compute folds into dispatch
            # time and device_s is near zero (see Engine._run_fused).
            timing = next((h for h in res.history if h.get("timing")), {})
            result = dict(
                metric=f"proposal_wall_clock_{sc}",
                value=round(wall, 3),
                unit="s",
                vs_baseline=round(wall / 10.0, 4),
                device_s=timing.get("device_s"),
                host_extract_s=timing.get("host_extract_s"),
                blocking_syncs=timing.get("blocking_syncs"),
                scale=sc,
                brokers=state.shape.B,
                partitions=state.shape.P,
                replicas=int(np.asarray(state.replica_valid).sum()),
                balancedness_before=round(res.balancedness_before, 2),
                balancedness_after=round(res.balancedness_after, 2),
                objective_before=round(res.objective_before, 5),
                objective_after=round(res.objective_after, 5),
                num_replica_moves=res.num_inter_broker_moves,
                num_leader_moves=res.num_leadership_moves,
                violated_goals_after=res.violated_goals_after(1e-6),
                fixture_gen_s=round(gen_s, 1),
                warmup_s=round(warm.wall_seconds, 1),
                device=str(__import__("jax").devices()[0]),
                # flight-recorder per-stage rollup + sensor catalog: the
                # committed BENCH_*.json records where the wall went
                # (model build vs optimize vs device op), not just totals
                stage_summary=__import__(
                    "cruise_control_tpu.common.trace", fromlist=["TRACER"]
                ).TRACER.summarize(),
                sensors=REGISTRY.snapshot(),
            )
            used = sc
            break
        except Exception as e:  # noqa: BLE001 — fall back to a smaller scale
            print(f"bench scale {sc} failed: {e!r}", file=sys.stderr)
            continue
    if result is None:
        result = dict(metric="proposal_wall_clock", value=-1.0, unit="s", vs_baseline=-1.0)
    return opt, used, result


def smoke() -> int:
    """`bench.py --smoke`: CI-grade CPU check of the perf path in seconds.

    Runs the fused (default) and legacy round loops on a small fixture at
    T=0 (init_temperature_scale=0 makes the trajectories deterministic and
    comparable) and emits one JSON line with both wall-clocks, objectives,
    and the blocking-sync counts from the history timing split.  Exit is
    nonzero when the fused path's final objective regresses vs legacy or
    its O(1)-blocking-sync contract is broken — catching fused-round-loop
    regressions without the TPU tunnel.  Wall-clocks are reported (and
    only grossly gated) because CPU CI timing is noisy.
    """
    # the bench environment's sitecustomize pins the platform at interpreter
    # start; the config override before first backend use is the reliable
    # route (same mechanism as __graft_entry__ / tests/conftest.py)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import dataclasses as dc

    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig
    from cruise_control_tpu.common.sensors import REGISTRY
    from cruise_control_tpu.common.trace import TRACER
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

    state = random_cluster_fast(
        RandomClusterSpec(
            num_brokers=24, num_partitions=1500, num_racks=6, num_topics=12, skew=1.0
        ),
        seed=7,
    )
    base = OptimizerConfig(
        num_candidates=512, leadership_candidates=128, swap_candidates=64,
        steps_per_round=16, num_rounds=4, init_temperature_scale=0.0, seed=0,
    )
    out: dict = {}
    for name, cfg in (
        ("fused", dc.replace(base, fused_rounds=True)),
        ("legacy", dc.replace(base, fused_rounds=False)),
    ):
        opt = GoalOptimizer(config=cfg, sensors=REGISTRY)
        opt.optimize(state)  # warm-up: compile once, measure the steady state
        walls = []
        res = None
        for _ in range(3):
            t0 = time.monotonic()
            res = opt.optimize(state)
            walls.append(time.monotonic() - t0)
        timing = next((h for h in res.history if h.get("timing")), {})
        out[name] = dict(
            wall_s=round(min(walls), 3),
            objective=res.objective_after,
            blocking_syncs=timing.get("blocking_syncs"),
            device_s=timing.get("device_s"),
            host_extract_s=timing.get("host_extract_s"),
        )
    obj_ok = out["fused"]["objective"] <= out["legacy"]["objective"] * (1 + 1e-6) + 1e-9
    syncs_ok = (
        out["fused"]["blocking_syncs"] == 1
        and out["legacy"]["blocking_syncs"] >= base.num_rounds
    )
    ratio = out["fused"]["wall_s"] / max(out["legacy"]["wall_s"], 1e-9)
    wall_ok = ratio <= 1.5  # gross-regression tripwire only: CPU CI is noisy
    ok = obj_ok and syncs_ok and wall_ok
    _emit(
        metric="smoke_fused_vs_legacy",
        value=out["fused"]["wall_s"],
        unit="s",
        vs_baseline=round(ratio, 4),
        fused=out["fused"],
        legacy=out["legacy"],
        objective_parity=obj_ok,
        sync_contract=syncs_ok,
        ok=ok,
        # where the wall time went (flight-recorder per-stage rollup) and
        # the sensor catalog the run registered — the perf trajectory
        # records stage breakdowns, not just totals
        stage_summary=TRACER.summarize(),
        sensors=REGISTRY.snapshot(),
    )
    return 0 if ok else 1


def mesh_smoke() -> int:
    """`bench.py --mesh-smoke`: the mesh engine layer on a virtual
    8-device CPU mesh, in seconds.

    Gates the layer's core invariant — a 1-device and an 8-device run of
    the same seeded anneal reproduce the PLAIN engine's placements
    byte-for-byte (parallel/mesh.py: replicated RNG + full-K draws +
    gather-candidates-only), hence identical objectives — and reports the
    per-round collective payload bytes so the perf trajectory records
    what cross-shard candidate exchange actually costs.  Wall-clocks are
    reported but not gated (CPU CI timing is noisy; the n=1 overhead
    gate lives in config 7 on the bench host).

    Self-provisions the mesh: with fewer than 8 visible devices it
    re-execs itself in a child with JAX_PLATFORMS=cpu +
    --xla_force_host_platform_device_count=8 (the platform is pinned at
    first backend use — same mechanism as __graft_entry__'s dryrun).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < 8:
        if os.environ.get("MESH_SMOKE_CHILD"):
            print(
                "mesh-smoke: forced-CPU child still has "
                f"{len(jax.devices())} devices, need 8",
                file=sys.stderr,
            )
            return 1
        import subprocess

        env = dict(os.environ)
        env.update(
            MESH_SMOKE_CHILD="1",
            GRAFT_FORCE_CPU="1",
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=(
                env.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8"
            ).strip(),
        )
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--mesh-smoke"],
            env=env,
        ).returncode

    from cruise_control_tpu.analyzer import Engine, OptimizerConfig
    from cruise_control_tpu.analyzer.objective import DEFAULT_CHAIN
    from cruise_control_tpu.parallel.sharded import ShardedEngine, model_mesh
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

    state = random_cluster_fast(
        RandomClusterSpec(
            num_brokers=24, num_partitions=1500, num_racks=6, num_topics=12, skew=1.0
        ),
        seed=7,
    )
    cfg = OptimizerConfig(
        num_candidates=512, leadership_candidates=128, swap_candidates=64,
        steps_per_round=16, num_rounds=4, seed=0,
    )
    devices = jax.devices()

    def timed_run(engine):
        t0 = time.monotonic()
        final, _history = engine.run()
        jax.block_until_ready(final.replica_broker)
        return final, round(time.monotonic() - t0, 3)

    plain_final, plain_wall = timed_run(Engine(state, DEFAULT_CHAIN, config=cfg))
    out: dict = {}
    parity = True
    for n in (1, 8):
        se = ShardedEngine(
            state, DEFAULT_CHAIN, mesh=model_mesh(devices[:n]), config=cfg
        )
        final, wall = timed_run(se)
        obj, _, _ = DEFAULT_CHAIN.evaluate(final)
        same = all(
            bool(
                (
                    np.asarray(getattr(plain_final, f))
                    == np.asarray(getattr(final, f))
                ).all()
            )
            for f in ("replica_broker", "replica_is_leader", "replica_disk")
        )
        parity = parity and same
        out[f"n{n}"] = dict(
            wall_s=wall,
            objective=float(obj),
            byte_parity_vs_plain=same,
            collective_bytes_per_round=int(se.collective_bytes_per_round),
        )
    obj_plain, _, _ = DEFAULT_CHAIN.evaluate(plain_final)
    obj_ok = out["n1"]["objective"] == out["n8"]["objective"] == float(obj_plain)
    coll_ok = (
        out["n1"]["collective_bytes_per_round"] == 0
        and out["n8"]["collective_bytes_per_round"] > 0
    )
    ok = parity and obj_ok and coll_ok
    _emit(
        metric="mesh_smoke",
        value=out["n8"]["wall_s"],
        unit="s",
        vs_baseline=round(out["n8"]["wall_s"] / max(plain_wall, 1e-9), 4),
        n_devices=8,
        plain=dict(wall_s=plain_wall, objective=float(obj_plain)),
        **out,
        byte_parity=parity,
        objective_parity=obj_ok,
        collective_accounting=coll_ok,
        ok=ok,
    )
    return 0 if ok else 1


def mesh_chaos(smoke_mode: bool = False) -> int:
    """`bench.py --mesh-chaos [--smoke]`: the mesh fault-tolerance gate —
    device loss injected MID-ANNEAL on a virtual 8-device CPU mesh.

    Exercises the full degrade-and-resume ladder (analyzer/optimizer.py
    `_optimize_mesh_ft` + parallel/ft.py): a DEVICE_LOST-shaped failure
    surfaces at a slice boundary two slices into a supervised sharded
    anneal, the per-device probe fan-out pins it on the injected chip,
    and the run resumes on the 4 survivors from the last slice-boundary
    carry checkpoint.  Gates:

      * the chaos run completes NON-degraded at reduced width, resumed
        (not restarted) from the checkpointed round, with the lost chip
        named in the result's mesh_ft history record;
      * its placements are byte-identical to a clean full-width run —
        the replicated mesh's width-independence (full-K draws before
        slicing) makes reduced-width resume exact, so this one equality
        subsumes "byte-equal a clean reduced-width run from that
        checkpoint";
      * exactly ONE MESH_DEGRADED event per degrade episode (drained via
        poll_event; the episode stays open at reduced width);
      * the checkpoint-OFF path (tpu.mesh.ft.checkpoint.every.slices=0)
        is byte-for-byte the pre-FT behavior with an IDENTICAL dispatch
        stream — zero snapshot dispatches, zero extra anything.

    Checkpoint overhead (snapshot wall vs anneal wall) is reported, not
    gated — CPU CI timing is noise; the correctness gates above are not.
    Self-provisions 8 virtual devices exactly like `--mesh-smoke`.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < 8:
        if os.environ.get("MESH_CHAOS_CHILD"):
            print(
                "mesh-chaos: forced-CPU child still has "
                f"{len(jax.devices())} devices, need 8",
                file=sys.stderr,
            )
            return 1
        import subprocess

        env = dict(os.environ)
        env.update(
            MESH_CHAOS_CHILD="1",
            GRAFT_FORCE_CPU="1",
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=(
                env.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8"
            ).strip(),
        )
        argv = ["--mesh-chaos"] + (["--smoke"] if smoke_mode else [])
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + argv, env=env
        ).returncode

    import threading

    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig
    from cruise_control_tpu.analyzer.engine import SegmentContext, segmented_execution
    from cruise_control_tpu.common.device_watchdog import DeviceSupervisor
    from cruise_control_tpu.common.dispatch import dispatch_meter
    from cruise_control_tpu.common.sensors import SensorRegistry
    from cruise_control_tpu.parallel.ft import MeshFtController
    from cruise_control_tpu.testing import faults
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

    spec = (
        RandomClusterSpec(
            num_brokers=24, num_partitions=1500, num_racks=6, num_topics=12, skew=1.0
        )
        if smoke_mode
        else RandomClusterSpec(
            num_brokers=48, num_partitions=6000, num_racks=6, num_topics=24, skew=1.0
        )
    )
    state = random_cluster_fast(spec, seed=7)
    cfg = OptimizerConfig(
        num_candidates=512, leadership_candidates=128, swap_candidates=64,
        steps_per_round=16, num_rounds=4 if smoke_mode else 6, seed=0,
    )

    def make_opt(ft, sensors=None):
        return GoalOptimizer(
            config=cfg,
            parallel_mode="sharded",
            supervisor=DeviceSupervisor(
                op_timeout_s=600.0, max_retries=0, sensors=sensors
            ),
            mesh_ft=ft,
            sensors=sensors,
        )

    def timed(opt, run_state):
        t0 = time.monotonic()
        res = opt.optimize(run_state)
        return res, round(time.monotonic() - t0, 3)

    def same_result(a, b) -> bool:
        return float(a.objective_after) == float(b.objective_after) and all(
            bool(
                (
                    np.asarray(getattr(a.state_after, f))
                    == np.asarray(getattr(b.state_after, f))
                ).all()
            )
            for f in ("replica_broker", "replica_is_leader", "replica_disk")
        )

    out: dict = {}

    # -- baseline: FT disabled = the pre-FT supervised mesh path --------
    opt_pre = make_opt(MeshFtController(enabled=False))
    with dispatch_meter() as m_pre:
        base, base_wall = timed(opt_pre, state)
    out["baseline"] = dict(
        wall_s=base_wall, objective=float(base.objective_after),
        dispatches=dict(m_pre.counts),
    )

    # -- checkpoint-off parity: FT on, snapshots off — byte-for-byte ----
    opt_off = make_opt(MeshFtController(checkpoint_every_slices=0))
    with dispatch_meter() as m_off:
        off, off_wall = timed(opt_off, state)
    off_parity = same_result(base, off)
    off_dispatch_parity = m_off.counts == m_pre.counts
    off_zero_snapshots = (
        m_off.counts.get("mesh.snapshot", 0) == 0
        and m_off.counts.get("engine.snapshot", 0) == 0
    )
    out["checkpoint_off"] = dict(
        wall_s=off_wall, byte_parity=off_parity,
        dispatch_parity=off_dispatch_parity,
        zero_snapshot_dispatches=off_zero_snapshots,
        dispatches=dict(m_off.counts),
    )

    # -- segmented clean run, checkpoints ON: overhead report ----------
    reg_clean = SensorRegistry()
    opt_ckpt = make_opt(
        MeshFtController(checkpoint_every_slices=1, sensors=reg_clean),
        sensors=reg_clean,
    )
    with segmented_execution(SegmentContext(0.0)):
        ckpt, ckpt_wall = timed(opt_ckpt, state)
    ckpt_timing = next(
        (h for h in ckpt.history if h.get("timing") and h.get("segmented")), {}
    )
    ckpt_parity = same_result(base, ckpt)
    snapshots_taken = int(ckpt_timing.get("snapshots", 0))
    snapshot_s = float(ckpt_timing.get("snapshot_s", 0.0))
    out["checkpoint_on"] = dict(
        wall_s=ckpt_wall, byte_parity=ckpt_parity,
        segments=ckpt_timing.get("segments"),
        snapshots=snapshots_taken,
        snapshot_s=snapshot_s,
        overhead_vs_baseline=round(ckpt_wall / max(base_wall, 1e-9), 4),
    )

    # -- chaos: device 6 dies at the second slice boundary -------------
    LOST = 6
    reg = SensorRegistry()
    ft = MeshFtController(checkpoint_every_slices=1, sensors=reg)
    opt = make_opt(ft, sensors=reg)
    tripped = threading.Event()
    boundaries = {"n": 0}

    def chk():
        # the scheduler's between-slice pause callback doubles as the
        # injection point: two slices in, the next mesh dispatch would
        # fail — surface the backend's DEVICE_LOST shape right here
        boundaries["n"] += 1
        if boundaries["n"] == 2:
            tripped.set()
            raise faults.device_lost_error("mesh.run", LOST)

    def probe_effect(op, fn, args, kwargs):
        # latched like testing.faults.device_loss: once the chip is gone
        # its attribution probe fails too, every other chip's passes
        if tripped.is_set() and getattr(args[0], "id", None) == LOST:
            raise faults.device_lost_error(op, LOST)
        return fn(*args, **kwargs)

    with faults.device_fault(
        probe_effect, ops=(faults.DEVICE_PROBE_OP,)
    ) as plog, segmented_execution(SegmentContext(0.0, chk)):
        chaos, chaos_wall = timed(opt, state)

    ft_rec = next(
        (h for h in reversed(chaos.history) if h.get("mesh_ft")), {}
    )
    chaos_timing = next(
        (h for h in chaos.history if h.get("timing") and h.get("segmented")), {}
    )
    event = ft.poll_event()
    event_drained_once = event is not None and ft.poll_event() is None
    resumes = getattr(reg.get("analyzer.mesh-ft.resumes"), "count", 0)
    device_lost = getattr(reg.get("analyzer.mesh-ft.device-lost"), "count", 0)
    chaos_ok = (
        not chaos.degraded
        and ft_rec.get("resumed") is True
        and ft_rec.get("width") == 4
        and ft_rec.get("full_width") == 8
        and ft_rec.get("lost_devices") == [LOST]
        and int(ft_rec.get("resumed_from_round") or 0) >= 1
        and chaos_timing.get("resumed_from_round") == ft_rec.get("resumed_from_round")
        and ft.episodes == 1
        and event_drained_once
        and event.get("failure_class") == "device_lost"
        and ft.episode_open  # still at reduced width: not healed yet
        and resumes == 1
        and device_lost >= 1
    )
    chaos_parity = same_result(base, chaos)
    out["chaos"] = dict(
        wall_s=chaos_wall,
        byte_parity_vs_clean=chaos_parity,
        resumed_from_round=ft_rec.get("resumed_from_round"),
        lost_devices=ft_rec.get("lost_devices"),
        width=ft_rec.get("width"),
        episodes=ft.episodes,
        event=event,
        probes=dict(plog.fired),
        degrade_contract=chaos_ok,
        mesh_ft_state=ft.state_json(),
        sensors=reg.snapshot(),
    )

    ok = (
        off_parity and off_dispatch_parity and off_zero_snapshots
        and ckpt_parity and snapshots_taken >= 1
        and chaos_ok and chaos_parity
    )
    _emit(
        metric="mesh_chaos",
        value=chaos_wall,
        unit="s",
        vs_baseline=round(chaos_wall / max(base_wall, 1e-9), 4),
        n_devices=8,
        **out,
        ok=ok,
    )
    return 0 if ok else 1


MESH_NORTH_STAR_SPEC = dict(
    num_brokers=25_000,
    num_racks=100,
    num_topics=400,
    num_partitions=2_000_000,
    min_replication=2,
    max_replication=3,
    skew=0.5,
    broker_capacity=(100.0, 500_000.0, 500_000.0, 5_000_000.0),
    mean_cpu=0.15,
    mean_nw_in=400.0,
    mean_nw_out=500.0,
    mean_disk=4000.0,
)


def _per_device_model_bytes(statics) -> dict:
    """Bytes of the PLACED engine statics resident per device id —
    replicated leaves bill their full copy to every device, sharded
    leaves bill each device its own row block."""
    import jax

    out: dict = {}
    for leaf in jax.tree_util.tree_leaves(statics):
        if hasattr(leaf, "addressable_shards"):
            for sh in leaf.addressable_shards:
                out[sh.device.id] = out.get(sh.device.id, 0) + int(sh.data.nbytes)
    return out


def mesh(smoke_mode: bool) -> int:
    """`bench.py --mesh [--smoke]`: the sharded-MODEL mesh mode at the
    scale-out north star — 25k brokers / 2M partitions on 8 chips
    (virtual CPU devices under check.sh; real chips on a device host).

    Two gates plus a scaling report, written to BENCH_mesh_r01.json:

      1. PARITY (small geometry): plain engine, replicated mesh and
         sharded-model mesh runs of one seeded anneal must produce
         byte-identical placements and equal objectives.  The state is
         pre-padded to the shard multiple so every mode normalizes by
         the same padded partition count, and loads are integer-quantized
         so the sharded mode's psum'd partial sums are exact
         (parallel/model_shard.py "Byte parity").
      2. MEMORY (north-star shape): the sharded run's per-device placed
         model bytes must be <= 1/4 of the replicated footprint (the
         whole point of sharding the model axis: 8 chips hold ~1/8 each).

    Scaling efficiency = plain 1-device wall / (n * sharded n-device
    wall) over the warm (post-compile) runs — reported, not gated: on
    the virtual CPU mesh all 8 "devices" share the host's cores, so CI
    efficiency is meaningless; the number is the record a device host
    fills in.  Per-device peak live bytes ride along from
    common/profiling.per_device_live_bytes (the scraped counterpart is
    the `tpu.device.peak-live-bytes-by-bucket` collector).

    Smoke mode shrinks the SEARCH (2 steps, 1 round, 256 candidates) but
    keeps the full 25k/2M geometry — the memory claim is about the model
    arrays, which exist at full scale either way.
    """
    import dataclasses

    import jax

    jax.config.update("jax_platforms", "cpu") if os.environ.get(
        "GRAFT_FORCE_CPU"
    ) else None
    if len(jax.devices()) < 8:
        if os.environ.get("MESH_BENCH_CHILD"):
            print(
                "mesh: forced-CPU child still has "
                f"{len(jax.devices())} devices, need 8",
                file=sys.stderr,
            )
            return 1
        import subprocess

        env = dict(os.environ)
        env.update(
            MESH_BENCH_CHILD="1",
            GRAFT_FORCE_CPU="1",
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=(
                env.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8"
            ).strip(),
        )
        argv = ["--mesh"] + (["--smoke"] if smoke_mode else [])
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + argv, env=env
        ).returncode

    import jax.numpy as jnp

    from cruise_control_tpu.analyzer import Engine, OptimizerConfig
    from cruise_control_tpu.analyzer.objective import DEFAULT_CHAIN
    from cruise_control_tpu.common.profiling import per_device_live_bytes
    from cruise_control_tpu.models.builder import pad_state
    from cruise_control_tpu.models.sharding import shard_multiple_shape
    from cruise_control_tpu.parallel.mesh import MeshEngine, grid_mesh
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

    n_dev = 8
    devices = jax.devices()[:n_dev]
    record: dict = dict(
        metric="mesh_model_sharded_north_star",
        mode="smoke" if smoke_mode else "full",
        n_devices=n_dev,
        platform=devices[0].platform,
    )

    def timed_run(engine):
        t0 = time.monotonic()
        final, history = engine.run()
        jax.block_until_ready(final.replica_broker)
        return final, history, round(time.monotonic() - t0, 3)

    # ---- gate 1: 3-way byte parity at small geometry --------------------
    small = random_cluster_fast(
        RandomClusterSpec(num_brokers=12, num_partitions=160, skew=1.5), seed=21
    )
    # integer-quantized loads: psum partial sums add exactly in f32
    small = dataclasses.replace(
        small,
        replica_load_leader=jnp.round(small.replica_load_leader * 8),
        replica_load_follower=jnp.round(small.replica_load_follower * 8),
    )
    # pre-pad so all three modes normalize by the same padded shape
    small = pad_state(small, shard_multiple_shape(small.shape, n_dev))
    small_cfg = OptimizerConfig(
        num_candidates=60, leadership_candidates=16, swap_candidates=8,
        steps_per_round=6, num_rounds=3, seed=3,
    )
    mesh2d = grid_mesh(1, n_dev, devices)
    finals = {}
    for name, eng in (
        ("plain", Engine(small, DEFAULT_CHAIN, config=small_cfg)),
        ("replicated", MeshEngine(small, DEFAULT_CHAIN, mesh=mesh2d, config=small_cfg)),
        ("sharded", MeshEngine(
            small, DEFAULT_CHAIN, mesh=mesh2d, config=small_cfg,
            model_shard_min_partitions=1,
        )),
    ):
        if name == "sharded" and not eng.model_sharded:
            print("mesh: sharded engine fell back to replicated", file=sys.stderr)
            return 1
        final, _, _ = timed_run(eng)
        obj, viol, _ = DEFAULT_CHAIN.evaluate(final)
        finals[name] = (final, float(obj), np.asarray(viol))
    parity = True
    for f in ("replica_broker", "replica_is_leader", "replica_disk"):
        vals = [np.asarray(getattr(finals[n][0], f)) for n in ("plain", "replicated", "sharded")]
        parity &= bool((vals[0] == vals[1]).all()) and bool((vals[1] == vals[2]).all())
    objs = [finals[n][1] for n in ("plain", "replicated", "sharded")]
    viols = [finals[n][2] for n in ("plain", "replicated", "sharded")]
    parity &= objs[0] == objs[1] == objs[2]
    parity &= bool((viols[0] == viols[1]).all()) and bool((viols[1] == viols[2]).all())
    record["small_geometry_parity"] = dict(
        byte_identical=bool(parity), objective=objs[0],
        shape=dict(B=small.shape.B, P=small.shape.P, R=small.shape.R),
    )
    del finals

    # ---- gate 2 + scaling: the 25k / 2M north-star shape ----------------
    t0 = time.monotonic()
    state = random_cluster_fast(RandomClusterSpec(**MESH_NORTH_STAR_SPEC), seed=11)
    record["fixture"] = dict(
        brokers=state.shape.B, partitions=state.shape.P, replicas=state.shape.R,
        gen_s=round(time.monotonic() - t0, 1),
    )
    search = (
        dict(num_candidates=256, leadership_candidates=64, swap_candidates=32,
             steps_per_round=2, num_rounds=1, seed=0)
        if smoke_mode
        else {**SEARCH, "num_rounds": 4}
    )
    cfg = OptimizerConfig(**search)

    sharded = MeshEngine(
        state, DEFAULT_CHAIN, mesh=grid_mesh(1, n_dev, devices), config=cfg,
        model_shard_min_partitions=500_000,
    )
    if not sharded.model_sharded:
        print("mesh: north-star engine fell back to replicated", file=sys.stderr)
        return 1
    dev_bytes = _per_device_model_bytes(sharded.statics)
    replicated_bytes = sum(
        int(getattr(leaf, "nbytes", 0))
        for leaf in jax.tree_util.tree_leaves(sharded.engine.statics)
    )
    max_dev_bytes = max(dev_bytes.values())
    mem_ok = max_dev_bytes <= replicated_bytes / 4
    final, hist, cold_wall = timed_run(sharded)
    _, _, warm_wall = timed_run(sharded)
    obj0, _, _ = DEFAULT_CHAIN.evaluate(state)
    obj1, _, _ = DEFAULT_CHAIN.evaluate(final)
    timing = next((h for h in hist if h.get("timing")), hist[-1] if hist else {})
    peak = per_device_live_bytes()
    record["north_star"] = dict(
        sharded_wall_s=warm_wall,
        sharded_wall_incl_compile_s=cold_wall,
        objective_before=round(float(obj0), 6),
        objective_after=round(float(obj1), 6),
        improved=bool(float(obj1) < float(obj0)),
        per_device_model_bytes={str(k): v for k, v in sorted(dev_bytes.items())},
        replicated_model_bytes=replicated_bytes,
        max_device_fraction_of_replicated=round(max_dev_bytes / replicated_bytes, 4),
        model_bytes_quarter_gate=bool(mem_ok),
        collective_bytes_per_round=int(timing.get("collective_bytes") or 0),
        model_psum_bytes_per_round=int(timing.get("model_psum_bytes") or 0),
        per_device_peak_live_bytes={str(k): int(v) for k, v in sorted(peak.items())},
    )
    del final, sharded

    plain = Engine(state, DEFAULT_CHAIN, config=cfg)
    _, _, plain_cold = timed_run(plain)
    _, _, plain_warm = timed_run(plain)
    del plain
    efficiency = plain_warm / (n_dev * max(warm_wall, 1e-9))
    record["scaling"] = dict(
        plain_n1_wall_s=plain_warm,
        plain_n1_wall_incl_compile_s=plain_cold,
        sharded_n8_wall_s=warm_wall,
        scaling_efficiency=round(efficiency, 4),
        note="virtual CPU devices share host cores; efficiency is the "
             "record a real 8-chip host fills in",
    )
    ok = parity and mem_ok
    record.update(value=warm_wall, unit="s", vs_baseline=round(warm_wall / 10.0, 4), ok=ok)
    _emit(**record)
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_mesh_r01.json")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    return 0 if ok else 1


def trace_overhead() -> int:
    """`bench.py --trace-overhead`: tracing is ON by default on the hot
    proposal path, so its cost is gated, not assumed.  Runs the smoke
    workload with the flight recorder enabled vs disabled (same compiled
    engine, min-of-N walls) and fails when tracing adds more than 2%.
    A small absolute epsilon keeps sub-millisecond CPU timing noise from
    failing runs whose spans cost nothing."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig
    from cruise_control_tpu.common.trace import Tracer
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

    state = random_cluster_fast(
        RandomClusterSpec(
            num_brokers=24, num_partitions=1500, num_racks=6, num_topics=12, skew=1.0
        ),
        seed=7,
    )
    cfg = OptimizerConfig(
        num_candidates=512, leadership_candidates=128, swap_candidates=64,
        steps_per_round=16, num_rounds=4, init_temperature_scale=0.0, seed=0,
    )
    reps = 7
    walls: dict[str, float] = {}
    n_spans = 0
    for mode in ("traced", "untraced"):
        tracer = Tracer(enabled=(mode == "traced"))
        opt = GoalOptimizer(config=cfg, tracer=tracer)
        opt.optimize(state)  # warm: compile outside the measurement
        best = float("inf")
        for _ in range(reps):
            t0 = time.monotonic()
            opt.optimize(state)
            best = min(best, time.monotonic() - t0)
        walls[mode] = best
        if mode == "traced":
            n_spans = len(tracer._all_spans())
    overhead = walls["traced"] / max(walls["untraced"], 1e-9) - 1.0
    ok = walls["traced"] <= walls["untraced"] * 1.02 + 0.002
    _emit(
        metric="trace_overhead_smoke",
        value=round(walls["traced"], 4),
        unit="s",
        vs_baseline=round(overhead, 4),
        traced_wall_s=round(walls["traced"], 4),
        untraced_wall_s=round(walls["untraced"], 4),
        overhead_pct=round(overhead * 100, 2),
        spans_recorded=n_spans,
        ok=ok,
    )
    return 0 if ok else 1


def blackbox_overhead() -> int:
    """`bench.py --blackbox-overhead`: the black-box dispatch spool is ON
    by default wherever a durable directory exists, so its cost is gated
    by measurement, not assumption — same shape as --trace-overhead.

    Runs the smoke workload with the recorder spooling to a temp
    directory vs disabled (same compiled engine, min-of-N walls) and
    fails past 2% overhead; also pins the DISABLED path leaves no spool
    file and that recording changes NOTHING about results (byte-identical
    placements) — observation must never perturb the optimization."""
    import os as _os
    import tempfile

    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig
    from cruise_control_tpu.common.blackbox import RECORDER
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

    state = random_cluster_fast(
        RandomClusterSpec(
            num_brokers=24, num_partitions=1500, num_racks=6, num_topics=12, skew=1.0
        ),
        seed=7,
    )
    cfg = OptimizerConfig(
        num_candidates=512, leadership_candidates=128, swap_candidates=64,
        steps_per_round=16, num_rounds=4, init_temperature_scale=0.0, seed=0,
    )
    reps = 7
    walls: dict[str, float] = {}
    placements: dict[str, object] = {}
    spool_dir = tempfile.mkdtemp(prefix="blackbox-bench-")
    records_written = 0

    def _spool_bytes() -> int:
        return sum(
            _os.path.getsize(_os.path.join(spool_dir, f))
            for f in _os.listdir(spool_dir)
        )

    try:
        for mode in ("recorded", "disabled"):
            if mode == "recorded":
                RECORDER.configure(
                    _os.path.join(spool_dir, f"spool-{_os.getpid()}.jsonl")
                )
            else:
                RECORDER.configure(None)
            opt = GoalOptimizer(config=cfg)
            result = opt.optimize(state)  # warm: compile outside the measurement
            placements[mode] = np.asarray(result.state_after.replica_broker)
            best = float("inf")
            for _ in range(reps):
                t0 = time.monotonic()
                opt.optimize(state)
                best = min(best, time.monotonic() - t0)
            walls[mode] = best
            if mode == "recorded":
                records_written = RECORDER.state_json()["recordsWritten"]
                bytes_after_recorded = _spool_bytes()
    finally:
        RECORDER.configure(None)
    overhead = walls["recorded"] / max(walls["disabled"], 1e-9) - 1.0
    parity = bool((placements["recorded"] == placements["disabled"]).all())
    # the disabled pin: the whole disabled run wrote ZERO spool bytes
    no_writes_when_disabled = _spool_bytes() == bytes_after_recorded
    ok = (
        walls["recorded"] <= walls["disabled"] * 1.02 + 0.002
        and parity
        and records_written > 0
        and no_writes_when_disabled
    )
    _emit(
        metric="blackbox_overhead_smoke",
        value=round(walls["recorded"], 4),
        unit="s",
        vs_baseline=round(overhead, 4),
        recorded_wall_s=round(walls["recorded"], 4),
        disabled_wall_s=round(walls["disabled"], 4),
        overhead_pct=round(overhead * 100, 2),
        records_written=records_written,
        disabled_parity=parity,
        ok=ok,
    )
    return 0 if ok else 1


def ledger_overhead() -> int:
    """`bench.py --ledger-overhead`: convergence diagnostics + the
    decision ledger are ON by default, so their cost is gated by
    measurement, not assumption — same shape as --blackbox-overhead.

    Runs the smoke workload with diagnostics compiled in AND one ledger
    decision record written per run, vs both off (min-of-N walls), and
    fails past 2% overhead; also pins that the diagnostics-on engine
    produces BYTE-IDENTICAL placements to diagnostics-off (observation
    must never perturb the search) and that the disabled path writes
    ZERO ledger bytes."""
    import dataclasses as _dc
    import os as _os
    import tempfile

    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig
    from cruise_control_tpu.analyzer.ledger import (
        DecisionLedger,
        build_decision_record,
    )
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

    state = random_cluster_fast(
        RandomClusterSpec(
            num_brokers=24, num_partitions=1500, num_racks=6, num_topics=12, skew=1.0
        ),
        seed=7,
    )
    base_cfg = OptimizerConfig(
        num_candidates=512, leadership_candidates=128, swap_candidates=64,
        steps_per_round=16, num_rounds=4, init_temperature_scale=0.0, seed=0,
    )
    reps = 7
    walls: dict[str, float] = {}
    placements: dict[str, object] = {}
    ledger_dir = tempfile.mkdtemp(prefix="ledger-bench-")
    records_written = 0
    conv_rounds = None

    def _dir_bytes() -> int:
        return sum(
            _os.path.getsize(_os.path.join(ledger_dir, f))
            for f in _os.listdir(ledger_dir)
        )

    for mode in ("recorded", "disabled"):
        cfg = _dc.replace(base_cfg, diagnostics=(mode == "recorded"))
        led = (
            DecisionLedger(_os.path.join(ledger_dir, "decision-ledger.jsonl"))
            if mode == "recorded"
            else None
        )

        def run_once(opt=GoalOptimizer(config=cfg), led=led):
            result = opt.optimize(state)
            if led is not None:
                led.record_decision(
                    build_decision_record(result, source="bench")
                )
            return result

        result = run_once()  # warm: compile outside the measurement
        placements[mode] = np.asarray(result.state_after.replica_broker)
        if mode == "recorded":
            timing = next(h for h in result.history if h.get("timing"))
            conv_rounds = timing["convergence"]["rounds"]
        best = float("inf")
        for _ in range(reps):
            t0 = time.monotonic()
            run_once()
            best = min(best, time.monotonic() - t0)
        walls[mode] = best
        if mode == "recorded":
            records_written = led.records_written
            bytes_after_recorded = _dir_bytes()
            led.close()
    overhead = walls["recorded"] / max(walls["disabled"], 1e-9) - 1.0
    parity = bool((placements["recorded"] == placements["disabled"]).all())
    # the disabled pin: the whole disabled run wrote ZERO ledger bytes
    no_writes_when_disabled = _dir_bytes() == bytes_after_recorded
    ok = (
        walls["recorded"] <= walls["disabled"] * 1.02 + 0.002
        and parity
        and records_written > 0
        and conv_rounds is not None
        and conv_rounds >= 1
        and no_writes_when_disabled
    )
    _emit(
        metric="ledger_overhead_smoke",
        value=round(walls["recorded"], 4),
        unit="s",
        vs_baseline=round(overhead, 4),
        recorded_wall_s=round(walls["recorded"], 4),
        disabled_wall_s=round(walls["disabled"], 4),
        overhead_pct=round(overhead * 100, 2),
        decisions_recorded=records_written,
        convergence_rounds=conv_rounds,
        diagnostics_parity=parity,
        disabled_zero_bytes=no_writes_when_disabled,
        ok=ok,
    )
    return 0 if ok else 1


def fleet_smoke() -> int:
    """`bench.py --fleet-smoke`: the fleet controller's economics gate.

    Boots a 3-cluster simulated fleet (east/west share a bucketed shape,
    south has its own) behind ONE shared AnalyzerCore and gates:

      * compiled-engine count < cluster count (same-bucket clusters rebind
        one engine — the whole point of the shared core), with at least
        one engine-cache HIT recorded on the shared registry;
      * per-cluster WARM proposal wall within 1.5x a single-cluster
        baseline of the same geometry — multi-tenancy must not tax the
        steady-state serving path (compiles excluded: both sides measure
        after their first run).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    from cruise_control_tpu.service.main import (
        build_simulated_fleet,
        build_simulated_service,
    )
    from cruise_control_tpu.service.progress import OperationProgress

    reps = 3

    def warm_wall(fn) -> float:
        fn()  # first run pays compile/cache-load; the gate is steady state
        best = float("inf")
        for _ in range(reps):
            t0 = time.monotonic()
            fn()
            best = min(best, time.monotonic() - t0)
        return best

    # single-cluster baselines, one per fleet geometry (the default
    # build_simulated_service matches east/west; south is the bigger one)
    geometries = {
        "small": dict(num_brokers=6, topics={"T0": 12, "T1": 12}),
        "large": dict(num_brokers=12, topics={"T0": 48, "T1": 48}),
    }
    baselines = {}
    for name, geo in geometries.items():
        app, fetcher, admin, sampler = build_simulated_service(seed=31, **geo)
        baselines[name] = warm_wall(
            lambda cc=app.cc: cc.proposals(OperationProgress(), ignore_cache=True)
        )
        app.stop()

    app, fleet = build_simulated_fleet(seed=31)
    opt = fleet.core.optimizer
    per_cluster = {}
    for cid in fleet.contexts:
        per_cluster[cid] = warm_wall(
            lambda cc=fleet.facade(cid): cc.proposals(
                OperationProgress(), ignore_cache=True
            )
        )
    engines = opt.cache_size
    hits = opt.engine_cache_hits
    ratios = {
        cid: per_cluster[cid]
        / max(baselines["large" if cid == "south" else "small"], 1e-9)
        for cid in per_cluster
    }
    # 1.5x + a small absolute epsilon: these are ~100ms CPU walls and a
    # scheduler hiccup must not flake the gate
    ok_wall = all(
        per_cluster[cid]
        <= 1.5 * baselines["large" if cid == "south" else "small"] + 0.05
        for cid in per_cluster
    )
    ok_engines = engines < len(fleet.contexts) and hits >= 1
    sched_report = _scheduler_burst()
    ok = ok_wall and ok_engines and sched_report["ok"]
    _emit(
        metric="fleet_smoke",
        value=round(max(per_cluster.values()), 4),
        unit="s",
        vs_baseline=round(max(ratios.values()), 3),
        clusters=len(fleet.contexts),
        compiled_engines=engines,
        engine_cache_hits=hits,
        per_cluster_wall_s={k: round(v, 4) for k, v in per_cluster.items()},
        baseline_wall_s={k: round(v, 4) for k, v in baselines.items()},
        wall_ratio={k: round(v, 3) for k, v in ratios.items()},
        ok_engines=ok_engines,
        ok_wall=ok_wall,
        scheduler=sched_report,
        ok=ok,
    )
    fleet.shutdown()
    return 0 if ok else 1


def _scheduler_burst(n_clusters: int = 20, duration_s: float = 2.0) -> dict:
    """Device-scheduler overload gate (stepping toward the ROADMAP
    `bench.py --fleet` 100-cluster freshness-SLO gate): a 20-cluster
    synthetic burst of BACKGROUND drift cycles under `device_slowdown`,
    with URGENT broker-failure-fix dispatches injected throughout.

    Reports per-class p50/p99 queue-to-dispatch wait + deadline-miss
    ratio and GATES: urgent p99 wait <= one slice budget, zero urgent
    sheds, every shed counted in fleet.scheduler.shed-total.  Synthetic
    device work (sleep-shaped slices through the @device_op seam) keeps
    the burst deterministic and CPU-cheap — the engine-level parity and
    preemption mechanics are pinned by tests/test_scheduler.py."""
    import threading

    from cruise_control_tpu.common.device_watchdog import device_op
    from cruise_control_tpu.fleet.scheduler import (
        BackgroundShedError,
        DeviceScheduler,
        WorkClass,
    )
    from cruise_control_tpu.testing import faults

    slice_s = 0.05
    slowdown = 3.0
    sched = DeviceScheduler(
        slice_budget_s=slice_s * slowdown * 1.5,
        freshness_slo_s=1.0,
        aging_s=0.5,
        shed_queue_depth=max(4, n_clusters // 3),
        brownout_after_s=duration_s / 2,
    )

    @device_op("engine.run")
    def device_slice():
        time.sleep(slice_s)

    from cruise_control_tpu.analyzer.engine import current_segment_context

    def background_cycle():
        ctx = current_segment_context()
        for i in range(3):
            device_slice()
            if ctx is not None and ctx.checkpoint is not None and i < 2:
                ctx.checkpoint()

    stop = threading.Event()
    count_lock = threading.Lock()
    shed_count = [0]
    brownout_runs = [0]

    def cluster_loop(cid):
        while not stop.is_set():
            try:
                if sched.brownout_active:
                    with count_lock:
                        brownout_runs[0] += 1
                sched.run(
                    WorkClass.BACKGROUND, background_cycle,
                    cluster_id=f"c{cid}", op="controller-cycle",
                )
            except BackgroundShedError:
                # locked: 20 threads race this count, and the gate below
                # compares it for EXACT equality with the scheduler's own
                # lock-protected shed counter
                with count_lock:
                    shed_count[0] += 1
                time.sleep(0.02)

    urgent_waits: list[float] = []
    urgent_device_s = slice_s * slowdown
    with faults.device_slowdown(slowdown) as log:
        threads = [
            threading.Thread(target=cluster_loop, args=(i,), daemon=True)
            for i in range(n_clusters)
        ]
        for t in threads:
            t.start()
        time.sleep(0.3)  # let the burst pile up
        deadline = time.monotonic() + duration_s
        while time.monotonic() < deadline:
            t0 = time.monotonic()
            sched.run(
                WorkClass.URGENT, device_slice, cluster_id="cX",
                op="fix:broker-failure",
            )
            urgent_waits.append(time.monotonic() - t0 - urgent_device_s)
            time.sleep(0.1)
        stop.set()
        for t in threads:
            t.join(5.0)

    def pct(xs, p):
        if not xs:
            return 0.0
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(round(p * (len(xs) - 1))))]

    st = sched.state_json()
    dispatches = sum(st["dispatches"].values())
    misses = st["deadlineMisses"]
    per_class = {
        cls: dict(
            p50=round(pct([w for w in waits], 0.50), 4),
            p99=round(pct([w for w in waits], 0.99), 4),
            missRatio=round(
                misses[cls] / max(1, st["dispatches"][cls]), 3
            ),
        )
        for cls, waits in (("urgent", urgent_waits),)
    }
    urgent_p99 = pct(urgent_waits, 0.99)
    ok_urgent = urgent_p99 <= sched.slice_budget_s
    ok_sheds = (
        st["shedTotal"]["urgent"] == 0
        and st["shedTotal"]["background"] == shed_count[0]
        and shed_count[0] >= 1
    )
    return dict(
        clusters=n_clusters,
        sliceBudgetS=sched.slice_budget_s,
        urgentInjected=len(urgent_waits),
        urgentWait=per_class["urgent"],
        waitSeconds=st.get("waitSeconds"),
        deadlineMissRatioByClass={
            c: round(misses[c] / max(1, st["dispatches"][c]), 3)
            for c in misses
        },
        dispatches=st["dispatches"],
        totalDispatches=dispatches,
        shedTotal=st["shedTotal"],
        preemptions=st["preemptions"],
        overloadEpisodes=st["overloadEpisodes"],
        brownoutRuns=brownout_runs[0],
        deviceOpCalls=log.total_calls,
        ok_urgent_p99=ok_urgent,
        ok_sheds_counted=ok_sheds,
        ok=ok_urgent and ok_sheds,
    )


def ha_smoke() -> int:
    """`bench.py --ha-smoke`: the fleet-HA takeover SLO gate.

    Two in-process instances (A, B) share ONE lease/journal directory and
    one set of 3 simulated clusters — the exact coordination surface real
    instances share.  A starts first and owns everything; B stands by,
    heart-beating but unable to steal a live lease.  Then A is killed
    (heartbeats stop, nothing released — a crash, not a shutdown) and the
    gate holds that:

      * B acquires every cluster and serves its first post-takeover
        proposal within the budget (lease expiry + heartbeat + CPU
        compile headroom) — the measured time-to-takeover SLO;
      * the lease store's audit trail proves at most one holder per
        cluster at any instant across the whole run (the single-holder
        invariant, checked mechanically, not trusted).
    """
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    from cruise_control_tpu.executor.admin import SimulatedClusterAdmin
    from cruise_control_tpu.fleet.leases import single_holder_violations
    from cruise_control_tpu.monitor.topology import StaticMetadataProvider
    from cruise_control_tpu.service.main import build_simulated_fleet
    from cruise_control_tpu.service.progress import OperationProgress
    from cruise_control_tpu.testing.synthetic import (
        SyntheticWorkloadSampler,
        synthetic_topology,
    )

    ttl, renew, slack = 1.5, 0.4, 0.2
    journal_dir = tempfile.mkdtemp(prefix="cc-ha-smoke-")
    backends = {}
    for i, cid in enumerate(("c1", "c2", "c3")):
        topo = synthetic_topology(
            num_brokers=6, topics={"T0": 12, "T1": 12}, seed=41 + i
        )
        meta = StaticMetadataProvider(topo)
        backends[cid] = (
            meta,
            SimulatedClusterAdmin(meta, link_rate_bytes_per_s=1e12),
            SyntheticWorkloadSampler(topo, seed=41 + i),
        )

    def instance(iid):
        return build_simulated_fleet({
            "fleet.clusters": "c1,c2,c3",
            "fleet.ha.enabled": "true",
            "fleet.ha.instance.id": iid,
            "fleet.ha.lease.ttl.s": ttl,
            "fleet.ha.renew.s": renew,
            "fleet.ha.skew.slack.s": slack,
            "executor.journal.dir": journal_dir,
            "anomaly.detection.interval.ms": 3_600_000,
            "tpu.prewarm.enabled": "false",
        }, backends=backends)

    app_a, fleet_a = instance("A")
    app_b, fleet_b = instance("B")
    lm_a, lm_b = fleet_a.lease_manager, fleet_b.lease_manager

    fleet_a.start_up()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and len(lm_a.owned_clusters()) < 3:
        time.sleep(0.02)
    owned_a = sorted(lm_a.owned_clusters())

    fleet_b.start_up()  # stands by: a live lease cannot be stolen
    time.sleep(3 * renew)
    stolen = sorted(lm_b.owned_clusters())

    t_kill = time.monotonic()
    lm_a.kill()  # crash: no release — B must wait out the TTL
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and len(lm_b.owned_clusters()) < 3:
        time.sleep(0.02)
    takeover_s = time.monotonic() - t_kill
    owned_b = sorted(lm_b.owned_clusters())
    fleet_b.facade("c1").proposals(OperationProgress(), ignore_cache=True)
    first_proposal_s = time.monotonic() - t_kill

    violations = single_holder_violations(lm_b.store.audit_events())
    # lease expiry (ttl + slack past A's last renewal, found within one
    # heartbeat) + the takeover's reconciliation/activation + one cold
    # CPU engine compile for the first proposal
    budget = ttl + slack + 2 * renew + 45.0
    ok = (
        owned_a == ["c1", "c2", "c3"]
        and stolen == []
        and owned_b == ["c1", "c2", "c3"]
        and first_proposal_s <= budget
        and violations == []
    )
    _emit(
        metric="ha_smoke",
        value=round(first_proposal_s, 3),
        unit="s",
        vs_baseline=round(first_proposal_s / budget, 3),
        takeover_s=round(takeover_s, 3),
        time_to_first_proposal_s=round(first_proposal_s, 3),
        budget_s=budget,
        lease_ttl_s=ttl,
        owned_before_kill=owned_a,
        stolen_while_alive=stolen,
        owned_after_takeover=owned_b,
        single_holder_violations=violations,
        audit_events=len(lm_b.store.audit_events()),
        ok=ok,
    )
    fleet_b.shutdown()
    fleet_a.shutdown()
    return 0 if ok else 1


def _churn_states(n_gens, *, brokers, partitions, parts_per_gen, broker_add_at, seed):
    """One synthetic churn stream: generation g has `partitions + g*delta`
    partitions (partition creates) and one broker added at broker_add_at —
    the monitor's view of a live cluster between proposal calls."""
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

    states = []
    for g in range(n_gens):
        b = brokers + (1 if broker_add_at is not None and g >= broker_add_at else 0)
        states.append(random_cluster_fast(
            RandomClusterSpec(
                num_brokers=b,
                num_partitions=partitions + g * parts_per_gen,
                num_racks=6,
                num_topics=12,
                skew=1.0,
            ),
            seed=seed,
        ))
    return states


def churn(smoke_mode: bool) -> int:
    """`bench.py --churn [--smoke]`: serve a stream of churned generations.

    N model generations with partitions created every generation (and one
    broker add mid-stream) are served twice: with shape bucketing (states
    padded to ShapeBucketPolicy buckets, the service default) and exact.
    Emits one JSON line with p50/p95 proposal wall-clock and the engine
    compile count for each mode.  Gate (--smoke, wired into
    scripts/check.sh): every bucketed generation whose shape matches the
    previous one must hit the engine cache — churned generations compile
    ZERO engines — while the exact mode recompiles per generation.
    """
    import jax

    if smoke_mode:
        jax.config.update("jax_platforms", "cpu")
    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig
    from cruise_control_tpu.models.builder import pad_state
    from cruise_control_tpu.models.state import DEFAULT_BUCKET_POLICY

    if smoke_mode:
        scale = dict(brokers=24, partitions=1200, parts_per_gen=9,
                     broker_add_at=3, seed=11)
        n_gens = 6
        cfg = OptimizerConfig(
            num_candidates=512, leadership_candidates=128, swap_candidates=64,
            steps_per_round=16, num_rounds=3, seed=0,
        )
    else:
        scale = dict(brokers=500, partitions=50_000, parts_per_gen=250,
                     broker_add_at=4, seed=11)
        n_gens = 8
        cfg = OptimizerConfig(**SEARCH)

    states = _churn_states(n_gens, **scale)
    out: dict = {}
    in_bucket_compiles = 0
    in_bucket_gens = 0
    for mode in ("bucketed", "exact"):
        if mode == "bucketed":
            served = [
                pad_state(s, DEFAULT_BUCKET_POLICY.bucket_shape(s.shape))
                for s in states
            ]
        else:
            served = states
        opt = GoalOptimizer(config=cfg)
        walls, compiles = [], []
        for g, s in enumerate(served):
            misses0 = opt.engine_cache_misses
            t0 = time.monotonic()
            res = opt.optimize(s)
            walls.append(time.monotonic() - t0)
            compiled = opt.engine_cache_misses - misses0
            compiles.append(compiled)
            if mode == "bucketed" and g > 0:
                if served[g].shape == served[g - 1].shape:
                    in_bucket_gens += 1
                    in_bucket_compiles += compiled
            del res
        ws = sorted(walls[1:] or walls)  # steady state: drop the cold gen 0

        def pct(p):
            return round(ws[min(len(ws) - 1, int(p * len(ws)))], 3)

        out[mode] = dict(
            p50_wall_s=pct(0.50), p95_wall_s=pct(0.95),
            first_gen_s=round(walls[0], 3),
            compiles=int(sum(compiles)), per_gen_compiles=compiles,
            cache_hits=opt.engine_cache_hits,
        )
    # the scenario must actually exercise in-bucket churn, and those
    # generations must be compile-free (the acceptance gate)
    scenario_ok = in_bucket_gens >= 3
    zero_ok = in_bucket_compiles == 0
    exact_recompiles = out["exact"]["compiles"] >= max(2, n_gens - 2)
    ok = scenario_ok and zero_ok and exact_recompiles
    _emit(
        metric="churn_bucketed_vs_exact",
        value=out["bucketed"]["p50_wall_s"],
        unit="s",
        vs_baseline=round(
            out["bucketed"]["p50_wall_s"] / max(out["exact"]["p50_wall_s"], 1e-9), 4
        ),
        generations=n_gens,
        in_bucket_generations=in_bucket_gens,
        churned_generation_compiles=in_bucket_compiles,
        bucketed=out["bucketed"],
        exact=out["exact"],
        ok=ok,
    )
    return 0 if ok else 1


def scenarios_bench(smoke_mode: bool) -> int:
    """`bench.py --scenarios [--smoke]`: batched what-if evaluation gate.

    Builds one base cluster and N what-if scenarios (rack loss, broker
    adds, broker removals, topic load scaling) of ONE planned shape, then
    scores them two ways: (a) ONE batched vmap program over the stacked
    states — the planner's serving path — and (b) N sequential
    single-state evaluations of the same jitted program.  Gate (--smoke,
    wired into scripts/check.sh): the batched pass must be no slower than
    the sequential pass (steady state, both warmed) and must produce
    IDENTICAL per-scenario objectives — batching is a pure execution
    detail, never a numerics change.
    """
    import jax

    if smoke_mode:
        jax.config.update("jax_platforms", "cpu")
    from cruise_control_tpu.analyzer.scenario_eval import ScenarioEvaluator
    from cruise_control_tpu.planner.scenario import (
        BrokerAdd,
        Scenario,
        apply_scenario,
        plan_shape,
    )
    from cruise_control_tpu.testing.fixtures import (
        RandomClusterSpec,
        random_cluster_fast,
    )

    if smoke_mode:
        spec = RandomClusterSpec(
            num_brokers=24, num_partitions=1500, num_racks=6, num_topics=12,
            skew=0.8,
        )
        n_scenarios = 12
        reps = 5
    else:
        spec = RandomClusterSpec(
            num_brokers=500, num_partitions=50_000, num_racks=20,
            num_topics=100, skew=0.5,
        )
        n_scenarios = 32
        reps = 3
    state = random_cluster_fast(spec, seed=7)
    scenarios = []
    for i in range(n_scenarios):
        kind = i % 4
        if kind == 0:
            scenarios.append(Scenario(name=f"kill-rack-{i}", kill_racks=(i % spec.num_racks,)))
        elif kind == 1:
            scenarios.append(Scenario(name=f"add-{i}", add_brokers=(BrokerAdd(count=1 + i % 3),)))
        elif kind == 2:
            scenarios.append(Scenario(
                name=f"remove-{i}", remove_brokers=(i % spec.num_brokers,)
            ))
        else:
            scenarios.append(Scenario(
                name=f"scale-{i}", topic_load_factors={i % spec.num_topics: 1.0 + 0.25 * (i % 5)}
            ))
    shape = plan_shape(state, scenarios)
    if shape != state.shape:
        from cruise_control_tpu.models.builder import pad_state

        state = pad_state(state, shape)  # pad once: scenario states alias it
    states = [apply_scenario(state, sc, shape=shape) for sc in scenarios]

    ev = ScenarioEvaluator(max_scenarios=max(32, n_scenarios))
    # warm both programs (compile outside the measurement: the gate is
    # about serving, and one batch program amortizes like any engine)
    ev.evaluate_states(states)
    obj_seq_warm, _ = ev._evaluate_cpu(states[:1])  # noqa: F841 — warm cpu jit
    t0 = time.monotonic()
    for _ in range(reps):
        batched_obj, batched_viol, _ = ev.evaluate_states(states)
    batched_s = (time.monotonic() - t0) / reps

    # sequential twin: same chain/constraint, one jitted single-state
    # program reused across scenarios (its own best case)
    import jax as _jax

    def one(s):
        obj, viol, _ = ev.chain.evaluate(s, constraint=ev.constraint)
        return obj, viol

    seq_fn = _jax.jit(one)
    seq_fn(states[0])  # warm
    t0 = time.monotonic()
    for _ in range(reps):
        seq = [_jax.device_get(seq_fn(s)) for s in states]
    sequential_s = (time.monotonic() - t0) / reps
    seq_obj = np.asarray([float(o) for o, _ in seq])

    identical = bool(np.array_equal(batched_obj.astype(np.float32), seq_obj.astype(np.float32)))
    ok = identical and batched_s <= sequential_s
    _emit(
        metric="scenario_batched_vs_sequential",
        value=round(batched_s, 4),
        unit="s",
        vs_baseline=round(batched_s / max(sequential_s, 1e-9), 4),
        scenarios=n_scenarios,
        batched_wall_s=round(batched_s, 4),
        sequential_wall_s=round(sequential_s, 4),
        identical_objectives=identical,
        max_objective_delta=float(np.abs(batched_obj - seq_obj).max()),
        shape=dict(R=shape.R, B=shape.B, P=shape.P),
        ok=ok,
    )
    return 0 if ok else 1


def streaming(smoke_mode: bool) -> int:
    """`bench.py --streaming [--smoke]`: the streaming controller's gate —
    a multi-window replay of always-on incremental rebalancing.

    Replays N metric windows of a drifting synthetic workload through two
    controller configurations:

      * WARM — the production path: device-resident model, in-place
        window deltas (no re-flatten while the shape bucket holds),
        warm-start carry from the previous accepted placement, learned
        move-acceptance prior mixed into the destination draws;
      * COLD — warm starts off, delta path off (full re-flatten per
        window), prior mix 0: byte-for-byte today's
        flatten-and-anneal-from-scratch pipeline.

    Gates:
      * parity: the COLD controller's final-window placement is
        byte-identical to a direct `optimizer.optimize` over a freshly
        built model (cold prior + full re-flatten == today's results);
      * rounds: WARM anneals converge in measurably fewer rounds than
        COLD at equal-or-better objective;
      * in-place contract (sensors): across N metric-only windows the
        WARM controller re-flattens exactly once (the initial build) and
        delta-applies N-1 times.
    Also reports sustained proposals/sec for the trajectory record.
    """
    import jax

    if smoke_mode:
        jax.config.update("jax_platforms", "cpu")
    from cruise_control_tpu.config.app_config import CruiseControlConfig
    from cruise_control_tpu.service.main import build_simulated_service

    n_windows = 12 if smoke_mode else 100
    geometry = (
        dict(num_brokers=6, topics={"T0": 12, "T1": 12})
        if smoke_mode
        else dict(num_brokers=24, topics={"T0": 96, "T1": 96, "T2": 48})
    )
    base_props = {
        "partition.metrics.window.ms": 1000,
        "min.samples.per.partition.metrics.window": 1,
        "num.partition.metrics.windows": 3,
        "execution.progress.check.interval.ms": 100,
        "webserver.http.port": 0,
        "tpu.num.candidates": 256,
        "tpu.leadership.candidates": 64,
        "tpu.steps.per.round": 24,
        "tpu.num.rounds": 4,
        "controller.enabled": True,
        # the prior warms mid-replay so the tail windows run prior-mixed
        "controller.prior.min.observations": 16,
    }

    def replay(mode_props, *, drift=1.03, seed=5):
        app, fetcher, admin, sampler = build_simulated_service(
            CruiseControlConfig({**base_props, **mode_props}), seed=seed,
            **geometry,
        )
        cc = app.cc
        ctl = cc.controller
        parts = sampler.all_partition_entities()
        wms = 1000
        rounds, objectives, violations = [], [], []
        fused, dispatches = [], []
        last_result = None
        t0 = time.monotonic()
        for w in range(4, 4 + n_windows):
            sampler.drift(drift)
            fetcher.fetch_once(parts, w * wms, (w + 1) * wms - 1)
            info = ctl.run_once()
            assert info is not None, f"window {w} produced no cycle"
            rounds.append(info["rounds"])
            objectives.append(info["objective"])
            violations.append(float(np.max(info["result"].violations_after)))
            fused.append(bool(info.get("fused")))
            dispatches.append(sum(info.get("dispatches", {}).values()))
            last_result = info["result"]
        wall = time.monotonic() - t0
        stats = ctl.state_json()
        app.stop()
        return dict(
            rounds=rounds, objectives=objectives, violations=violations,
            fused=fused, dispatches=dispatches,
            wall_s=wall, stats=stats, cc=cc, last_result=last_result,
        )

    warm = replay({})
    cold = replay({
        "controller.warm.start.enabled": False,
        "controller.delta.enabled": False,
        "controller.prior.mix": 0.0,
    })

    # parity: over the cold replay's final window, run the plain
    # request-path optimizer on a freshly built model — identical
    # placements prove the controller's cold cycle IS today's pipeline
    cc = cold["cc"]
    fresh = cc.monitor.cluster_model()
    direct = cc.optimizer.optimize(fresh, options=cc._build_options(fresh))
    ctl_after = cold["last_result"].state_after
    parity = all(
        bool(
            (
                np.asarray(getattr(ctl_after, f))
                == np.asarray(getattr(direct.state_after, f))
            ).all()
        )
        for f in ("replica_broker", "replica_is_leader", "replica_disk")
    )

    # steady-state rounds (drop the cold-start window both sides pay)
    warm_rounds = warm["rounds"][1:]
    cold_rounds = cold["rounds"][1:]
    warm_mean = sum(warm_rounds) / max(1, len(warm_rounds))
    cold_mean = sum(cold_rounds) / max(1, len(cold_rounds))
    rounds_ok = warm_mean <= cold_mean - 1.0
    # "equal objective": every warm window either clears the goal chain
    # to the early-stop tolerance (the point at which more rounds only
    # polish the noise-level dispersion tiebreaker cold's extra rounds
    # keep shaving) or matches cold's objective outright
    tol = 1e-6
    obj_ok = all(
        wv <= tol or wo <= co * (1 + 1e-6) + 1e-9
        for wo, co, wv in zip(
            warm["objectives"][1:], cold["objectives"][1:],
            warm["violations"][1:],
        )
    )
    inplace_ok = (
        warm["stats"]["fullReflattens"] == 1
        and warm["stats"]["deltaApplies"] == n_windows - 1
        and cold["stats"]["fullReflattens"] == n_windows
    )
    # the headline latency metric (ROADMAP item 4): window-roll-to-
    # published-proposal p50/p99 from the controller's histogram.  The
    # first published cycle (XLA cold compile) and the first FUSED cycle
    # (fused-program compile) are excluded — each reports through its own
    # one-shot sensor — so the histogram holds n_windows - 2 steady-state
    # samples and the p99 is an honest steady-state claim
    hist = warm["cc"].sensors.get("controller.window-roll-to-publish-seconds")
    publish_p50 = publish_p99 = None
    hist_ok = hist is not None and hist.count == n_windows - 2
    if hist is not None and hist.count:
        # None (JSON null), never NaN, when empty: the failing run's
        # record must stay parseable by strict JSON consumers
        publish_p50 = round(hist.quantile(0.5), 4)
        publish_p99 = round(hist.quantile(0.99), 4)
    # the fusion contract (tentpole gate): every steady-state delta
    # cycle after the fused program compiles runs FUSED, and a fused
    # cycle costs exactly one program dispatch + one host extraction —
    # proved by the controller's dispatch meter, not assumed.  Window 0
    # is the reflatten, window 1 goes staged while the warm engine cache
    # fills; everything after must fuse.
    fused_ok = all(warm["fused"][2:]) and not warm["fused"][0]
    dispatch_ok = all(
        d <= 2 for d, f in zip(warm["dispatches"], warm["fused"]) if f
    )
    sub_second_ok = publish_p99 is not None and publish_p99 < 1.0
    ok = (
        parity and rounds_ok and obj_ok and inplace_ok and hist_ok
        and fused_ok and dispatch_ok and sub_second_ok
    )
    rec = dict(
        metric="streaming_warm_vs_cold",
        value=round(warm["wall_s"], 3),
        unit="s",
        vs_baseline=round(warm["wall_s"] / max(cold["wall_s"], 1e-9), 4),
        windows=n_windows,
        window_roll_to_publish_p50_s=publish_p50,
        window_roll_to_publish_p99_s=publish_p99,
        publish_histogram_ok=hist_ok,
        fused_cycles=warm["stats"]["fusedCycles"],
        fused_ok=fused_ok,
        dispatches_per_fused_cycle_max=max(
            (d for d, f in zip(warm["dispatches"], warm["fused"]) if f),
            default=None,
        ),
        dispatch_ok=dispatch_ok,
        sub_second_ok=sub_second_ok,
        cold_cycle_s=warm["stats"]["coldCycleSeconds"],
        fused_cold_cycle_s=warm["stats"]["fusedColdCycleSeconds"],
        plan_sized_cycles=warm["stats"]["planSizedCycles"],
        reflattens_by_reason=warm["stats"]["fullReflattensByReason"],
        proposals_per_sec=round(n_windows / max(warm["wall_s"], 1e-9), 3),
        cold_proposals_per_sec=round(n_windows / max(cold["wall_s"], 1e-9), 3),
        warm_rounds_mean=round(warm_mean, 3),
        cold_rounds_mean=round(cold_mean, 3),
        warm_rounds=warm["rounds"],
        cold_rounds=cold["rounds"],
        warm_violations_max=max(warm["violations"]),
        cold_violations_max=max(cold["violations"]),
        warm_reflattens=warm["stats"]["fullReflattens"],
        warm_delta_applies=warm["stats"]["deltaApplies"],
        cold_reflattens=cold["stats"]["fullReflattens"],
        prior=warm["stats"]["prior"],
        cold_parity=parity,
        rounds_ok=rounds_ok,
        objective_ok=obj_ok,
        inplace_ok=inplace_ok,
        ok=ok,
    )
    _emit(**rec)
    if not smoke_mode:
        # the committed trajectory record (BENCHLOG.md convention): one
        # JSON file per full streaming run, beside the BENCH_r*.json
        # headline records
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_streaming_r01.json")
        with open(path, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
    return 0 if ok else 1


def _coldstart_child() -> int:
    """`bench.py --coldstart-child` (internal): ONE restart phase in a
    truly fresh process.  Builds the simulated service against the
    parent's cache/manifest directories, runs start_up (the boot-prewarm
    path under test), serves one proposal, and emits the honest
    cold-start-to-first-proposal wall + the compile-cache boot report
    (fresh-trace vs AOT-load counts per bucket)."""
    t0 = time.monotonic()
    import jax

    if os.environ.get("COLDSTART_SMOKE"):
        jax.config.update("jax_platforms", "cpu")
    from cruise_control_tpu.common import compilation_cache
    from cruise_control_tpu.config.app_config import CruiseControlConfig
    from cruise_control_tpu.service.main import build_simulated_service
    from cruise_control_tpu.service.progress import OperationProgress

    phase = os.environ["COLDSTART_PHASE"]
    smoke = bool(os.environ.get("COLDSTART_SMOKE"))
    props = {
        "partition.metrics.window.ms": 1000,
        "min.samples.per.partition.metrics.window": 1,
        "num.partition.metrics.windows": 3,
        "webserver.http.port": 0,
        "tpu.compile.cache.dir": os.environ["COLDSTART_CACHE_DIR"],
        "tpu.prewarm.manifest.dir": os.environ["COLDSTART_MANIFEST_DIR"],
        # the xla-cache-only phase is PR 9's slice: persistent compile
        # cache on, no manifest, no AOT — tracing is paid again
        "tpu.prewarm.enabled": phase != "xla-cache",
        "anomaly.detection.interval.ms": 3_600_000,
    }
    if smoke:
        props.update({
            # candidates >= engine.AOT_MIN_CANDIDATES: the smoke engine
            # must be AOT-worthy or phase 1 writes no artifact to gate on
            "tpu.num.candidates": 1024, "tpu.leadership.candidates": 128,
            "tpu.swap.candidates": 64, "tpu.steps.per.round": 16,
            "tpu.num.rounds": 3,
        })
        geometry = dict(num_brokers=6, topics={"T0": 12, "T1": 12})
    else:
        props.update({
            "tpu.num.candidates": 2048, "tpu.leadership.candidates": 512,
            "tpu.steps.per.round": 64, "tpu.num.rounds": 6,
        })
        geometry = dict(num_brokers=24, topics={"T0": 96, "T1": 96, "T2": 48})
    app, fetcher, admin, sampler = build_simulated_service(
        CruiseControlConfig(props), seed=3, **geometry
    )
    cc = app.cc
    cc.start_up(detection_interval_s=3600)
    # deterministic gate: wait for the manifest replay to ENQUEUE its
    # engines (compiles continue on the warm pool; the request below
    # waits per-program exactly like any warm start)
    cc._boot_prewarm_done.wait(timeout=300)
    prewarm_wait_s = time.monotonic() - t0
    res = cc.proposals(OperationProgress(), ignore_cache=True)
    wall = time.monotonic() - t0
    report = compilation_cache.boot_report() or {}
    store = cc.core.prewarm_store
    manifest_buckets = []
    if store is not None:
        # flush background AOT exports so the NEXT phase finds artifacts
        # (after the measurement — exports are never on the serving path)
        store.drain(300)
        manifest_buckets = sorted(set(store.manifest_bucket_keys()))
    from cruise_control_tpu.analyzer.prewarm import bucket_key

    _emit(
        metric="coldstart_phase",
        phase=phase,
        value=round(wall, 3),
        unit="s",
        cold_start_to_first_proposal_s=round(wall, 3),
        boot_prewarm_wait_s=round(prewarm_wait_s, 3),
        served_bucket=bucket_key(res.state_before.shape),
        manifest_buckets=manifest_buckets,
        engine_traces=report.get("engineTraces", {}),
        xla_entries_at_boot=report.get("entriesAtBoot"),
        xla_new_compiles=report.get("newCompiles"),
        objective_after=res.objective_after,
        num_proposals=len(res.proposals),
        prewarmed_buckets=int(
            cc.sensors.snapshot()
            .get("analyzer.boot-prewarm-buckets", {})
            .get("count", 0)
        ),
    )
    cc.shutdown()
    return 0


def coldstart(smoke_mode: bool) -> int:
    """`bench.py --coldstart [--smoke]`: the restart SLO gate.

    Spawns a CHILD PROCESS per phase against one shared on-disk
    cache/manifest directory — process boundaries are the only honest way
    to measure cold starts (jit caches, tracing, and module imports are
    all per-process):

      1. cold         — empty disk: full trace + XLA compile bill
                        (manifest + AOT artifacts are WRITTEN here, off
                        the serving path);
      2. xla-cache    — PR 9's slice: compile skipped, tracing paid,
                        nothing prewarmed until the request asks;
      3. manifest-aot — this PR: boot prewarm replays the manifest and
                        deserializes the fused program, so the request
                        hits a compiling-or-compiled engine with ZERO
                        fresh traces for manifest buckets.

    Gates (--smoke, wired into scripts/check.sh): the manifest-aot phase
    reports zero fresh traces for every manifest-listed bucket, its
    cold-start-to-first-proposal wall is strictly below the truly-cold
    phase, and all three phases produce the identical objective (the AOT
    path must not change results).  Headline mode reports the three walls
    for BENCHLOG.md without the CPU-noise-sensitive wall gate.
    """
    import subprocess
    import tempfile

    tmp = tempfile.mkdtemp(prefix="cc-coldstart-")
    cache_dir = os.path.join(tmp, "xla")
    manifest_dir = os.path.join(tmp, "prewarm")
    phases = ("cold", "xla-cache", "manifest-aot")
    out: dict[str, dict] = {}
    try:
        for phase in phases:
            env = dict(os.environ)
            env.update(
                COLDSTART_PHASE=phase,
                COLDSTART_CACHE_DIR=cache_dir,
                COLDSTART_MANIFEST_DIR=manifest_dir,
            )
            if smoke_mode:
                env.update(COLDSTART_SMOKE="1", GRAFT_FORCE_CPU="1",
                           JAX_PLATFORMS="cpu")
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--coldstart-child"],
                env=env, capture_output=True, text=True, timeout=1800,
            )
            line = next(
                (ln for ln in reversed(proc.stdout.splitlines())
                 if ln.startswith("{")),
                None,
            )
            if proc.returncode != 0 or line is None:
                print(f"coldstart phase {phase} failed (rc={proc.returncode}):\n"
                      f"{proc.stderr[-4000:]}", file=sys.stderr)
                _emit(metric="coldstart_to_first_proposal", value=-1.0,
                      unit="s", vs_baseline=-1.0, failed_phase=phase, ok=False)
                return 1
            out[phase] = json.loads(line)
    finally:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)

    aot = out["manifest-aot"]
    cold = out["cold"]
    # zero fresh traces for every manifest-listed bucket on the AOT phase
    traces = aot["engine_traces"]
    fresh_by_bucket = {
        b: traces.get(b, {}).get("fresh", 0) for b in aot["manifest_buckets"]
    }
    traces_ok = bool(aot["manifest_buckets"]) and all(
        v == 0 for v in fresh_by_bucket.values()
    )
    aot_loads = sum(
        traces.get(b, {}).get("aot", 0) for b in aot["manifest_buckets"]
    )
    wall_ok = aot["cold_start_to_first_proposal_s"] < cold[
        "cold_start_to_first_proposal_s"
    ]
    # vs the xla-cache phase: reported, not gated — the acceptance gate
    # is vs truly-cold (at smoke scale the two warm phases sit within
    # CPU-scheduler noise of each other; the trace-skip proof is the
    # zero-fresh-traces count, which cannot be noise)
    wall_below_xla = aot["cold_start_to_first_proposal_s"] < out["xla-cache"][
        "cold_start_to_first_proposal_s"
    ]
    obj_ok = (
        out["cold"]["objective_after"]
        == out["xla-cache"]["objective_after"]
        == aot["objective_after"]
    )
    prewarm_ok = aot["prewarmed_buckets"] >= 1
    ok = traces_ok and obj_ok and prewarm_ok and (wall_ok or not smoke_mode)
    _emit(
        metric="coldstart_to_first_proposal",
        value=aot["cold_start_to_first_proposal_s"],
        unit="s",
        vs_baseline=round(
            aot["cold_start_to_first_proposal_s"]
            / max(cold["cold_start_to_first_proposal_s"], 1e-9),
            4,
        ),
        cold_start_to_first_proposal_s={
            p: out[p]["cold_start_to_first_proposal_s"] for p in phases
        },
        xla_new_compiles={p: out[p]["xla_new_compiles"] for p in phases},
        manifest_buckets=aot["manifest_buckets"],
        fresh_traces_manifest_buckets=fresh_by_bucket,
        aot_loads_manifest_buckets=aot_loads,
        prewarmed_buckets=aot["prewarmed_buckets"],
        zero_fresh_traces=traces_ok,
        wall_below_cold=wall_ok,
        wall_below_xla_cache=wall_below_xla,
        objective_parity=obj_ok,
        ok=ok,
    )
    return 0 if ok else 1


def main():
    if "--coldstart-child" in sys.argv:
        sys.exit(_coldstart_child())
    if "--coldstart" in sys.argv:
        sys.exit(coldstart("--smoke" in sys.argv))
    if "--streaming" in sys.argv:
        sys.exit(streaming("--smoke" in sys.argv))
    if "--fleet-smoke" in sys.argv:
        sys.exit(fleet_smoke())
    if "--ha-smoke" in sys.argv:
        sys.exit(ha_smoke())
    if "--mesh-smoke" in sys.argv:
        sys.exit(mesh_smoke())
    if "--mesh-chaos" in sys.argv:
        sys.exit(mesh_chaos("--smoke" in sys.argv))
    if "--mesh" in sys.argv:
        sys.exit(mesh("--smoke" in sys.argv))
    if "--trace-overhead" in sys.argv:
        sys.exit(trace_overhead())
    if "--blackbox-overhead" in sys.argv:
        sys.exit(blackbox_overhead())
    if "--ledger-overhead" in sys.argv:
        sys.exit(ledger_overhead())
    if "--scenarios" in sys.argv:
        sys.exit(scenarios_bench("--smoke" in sys.argv))
    if "--churn" in sys.argv:
        sys.exit(churn("--smoke" in sys.argv))
    if "--smoke" in sys.argv:
        sys.exit(smoke())

    from cruise_control_tpu.common.compilation_cache import enable_persistent_cache
    # shared accelerator liveness gate (also run by __graft_entry__'s
    # dryrun): a wedged backend yields a diagnosable record, not an opaque
    # process-timeout kill
    from cruise_control_tpu.common.device_watchdog import device_watchdog

    device_error = device_watchdog()
    if device_error is not None:
        _emit(
            metric="proposal_wall_clock",
            value=-1.0,
            unit="s",
            vs_baseline=-1.0,
            error=device_error,
        )
        os._exit(1)  # daemon probe thread may be wedged in the runtime

    # persistent XLA cache: repeat bench runs skip the ~70s warm-up compile,
    # making warmup_s the honest time-to-first-proposal of a restarted
    # service with a warm cache
    enable_persistent_cache(
        os.environ.get("BENCH_COMPILE_CACHE", "~/.cache/cruise_control_tpu/xla")
    )
    scale = os.environ.get("BENCH_SCALE", "auto")
    scale_order = [scale] if scale != "auto" else ["north_star", "mid", "small"]
    wanted = set(
        (os.environ.get("BENCH_CONFIGS") or "1,2,3,4,5,6,7").replace(" ", "").split(",")
    )

    for n, fn in (("1", config_1), ("2", config_2), ("3", config_3),
                  ("6", config_6), ("7", config_7)):
        if n in wanted:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — one config must not sink the rest
                print(f"bench config {n} failed: {e!r}", file=sys.stderr)

    headline = dict(metric="proposal_wall_clock", value=-1.0, unit="s", vs_baseline=-1.0)
    opt = used = None
    if "4" in wanted:
        opt, used, headline = config_4(scale_order)
    if "5" in wanted:
        if opt is None or used is None:
            print(
                "bench config 5 skipped: it reuses config 4's compiled engine — "
                "include 4 in BENCH_CONFIGS",
                file=sys.stderr,
            )
        else:
            try:
                config_5(opt, used)
            except Exception as e:  # noqa: BLE001
                print(f"bench config 5 failed: {e!r}", file=sys.stderr)
    if "4" in wanted:
        _emit(**headline)  # headline LAST: drivers parse the final line


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    main()
