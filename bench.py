"""Headline benchmark: rebalance-proposal wall-clock on a synthetic cluster.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

The north-star target (BASELINE.md) is a full default-goal-chain proposal
for a 2,600-broker / 200k-partition cluster in < 10 s on one TPU chip —
vs. minutes for the reference's single-threaded greedy GoalOptimizer
(reference analyzer/GoalOptimizer.java:416, no published numbers).
`vs_baseline` reports value / 10s, i.e. the fraction of the north-star
budget used (< 1.0 beats the target).

Scale via BENCH_SCALE env: "north_star" (2600/200k), "mid" (500/50k),
"small" (50/5k). Default tries the largest that fits and falls back.
"""

import json
import os
import sys
import time

import numpy as np


def build_cluster(scale: str):
    from cruise_control_tpu.testing.fixtures import RandomClusterSpec, random_cluster_fast

    specs = {
        "north_star": RandomClusterSpec(
            num_brokers=2600,
            num_racks=52,
            num_topics=200,
            num_partitions=200_000,
            min_replication=2,
            max_replication=3,
            skew=0.5,
            broker_capacity=(100.0, 500_000.0, 500_000.0, 5_000_000.0),
            mean_cpu=0.15,
            mean_nw_in=400.0,
            mean_nw_out=500.0,
            mean_disk=4000.0,
        ),
        "mid": RandomClusterSpec(
            num_brokers=500,
            num_racks=20,
            num_topics=100,
            num_partitions=50_000,
            skew=0.5,
            broker_capacity=(100.0, 300_000.0, 300_000.0, 3_000_000.0),
            mean_cpu=0.2,
            mean_nw_in=500.0,
            mean_nw_out=600.0,
            mean_disk=5000.0,
        ),
        "small": RandomClusterSpec(num_brokers=50, num_partitions=5000, skew=0.8),
    }
    return random_cluster_fast(specs[scale], seed=42), scale


def main():
    from cruise_control_tpu.analyzer import GoalOptimizer, OptimizerConfig

    scale = os.environ.get("BENCH_SCALE", "auto")
    order = [scale] if scale != "auto" else ["north_star", "mid", "small"]

    result = None
    for sc in order:
        try:
            t_gen = time.monotonic()
            state, sc = build_cluster(sc)
            gen_s = time.monotonic() - t_gen
            cfg = OptimizerConfig(
                num_candidates=16384,
                leadership_candidates=4096,
                steps_per_round=64,
                num_rounds=8,
                seed=0,
            )
            opt = GoalOptimizer(config=cfg)
            # warm-up run compiles the engine for this cluster shape; the
            # measured run rebinds the cached engine (zero recompilation) —
            # steady-state service behavior, where the proposal precompute
            # loop reuses the compiled program (reference GoalOptimizer
            # proposal cache, analyzer/GoalOptimizer.java:276).
            warm = opt.optimize(state, config=cfg)
            t0 = time.monotonic()
            res = opt.optimize(state)
            wall = time.monotonic() - t0
            result = dict(
                metric=f"proposal_wall_clock_{sc}",
                value=round(wall, 3),
                unit="s",
                vs_baseline=round(wall / 10.0, 4),
                scale=sc,
                brokers=state.shape.B,
                partitions=state.shape.P,
                replicas=int(np.asarray(state.replica_valid).sum()),
                balancedness_before=round(res.balancedness_before, 2),
                balancedness_after=round(res.balancedness_after, 2),
                objective_before=round(res.objective_before, 5),
                objective_after=round(res.objective_after, 5),
                num_replica_moves=res.num_inter_broker_moves,
                num_leader_moves=res.num_leadership_moves,
                violated_goals_after=res.violated_goals_after(1e-6),
                fixture_gen_s=round(gen_s, 1),
                warmup_s=round(warm.wall_seconds, 1),
                device=str(__import__("jax").devices()[0]),
            )
            break
        except Exception as e:  # noqa: BLE001 — fall back to a smaller scale
            print(f"bench scale {sc} failed: {e!r}", file=sys.stderr)
            continue

    if result is None:
        result = dict(metric="proposal_wall_clock", value=-1.0, unit="s", vs_baseline=-1.0)
    print(json.dumps(result))


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    main()
