"""Learned per-topic-pair move-acceptance prior.

The annealer's destination draws are uniform over the allowed broker
list; near a converged placement almost every drawn candidate is
rejected, so the candidate budget is spent re-discovering the same few
productive (topic, destination) pairs each run.  The RL-tuned scorer of
"Learning to Score" (arxiv 2603.10545) and the reinforced-GA proposal
policy of arxiv 1905.02494 both show that a learned move distribution
cuts search rounds dramatically; this module is the simplest honest
instance of that idea: an exponentially-decayed count of ACCEPTED moves,
keyed by (source topic, destination broker) pairs, fitted online from

  * past anneal trajectories — every published proposal set's replica
    moves (ProposalSet.destination_pairs), and
  * executed proposals — moves the executor actually applied, weighted
    higher (they survived operator/execution scrutiny, the strongest
    acceptance signal available).

Keys are TOPIC NAMES (stable across model generations and shape-bucket
churn) + broker ids; materialization back onto a generation's dense
topic-id axis rides the build catalog.  A cold prior (fewer than
`min_observations` decayed observations) materializes with mix 0.0 —
the engine then reproduces the uniform draw stream byte-for-byte
(analyzer/engine.py Engine._sample_dests).
"""

from __future__ import annotations

import dataclasses
import threading

import numpy as np


@dataclasses.dataclass(frozen=True)
class PriorTable:
    """One model generation's materialized prior (build_statics input).

    weights[t, b]: decayed acceptance mass of moves of topic t's replicas
    onto broker b, on the generation's padded (T, B) axes.  mix: fraction
    of destination draws taken from the prior (0.0 = cold = uniform
    byte-parity)."""

    weights: np.ndarray  # f32[T, B]
    mix: float
    observations: float  # decayed total behind the table (observability)


class MoveAcceptancePrior:
    """Online-fitted move-acceptance distribution (thread-safe).

    `decay` applies once per observation batch (one anneal's proposals, or
    one execution), so ancient traffic patterns fade; entries below a
    floor are pruned so the table never accretes unboundedly under topic
    churn.  `observe_executed` weighs a pair `executed_weight` times an
    anneal observation.
    """

    PRUNE_FLOOR = 1e-3

    def __init__(
        self,
        *,
        mix: float = 0.5,
        decay: float = 0.9,
        min_observations: int = 64,
        executed_weight: float = 4.0,
    ):
        if not 0.0 <= mix <= 1.0:
            raise ValueError(f"mix must be in [0, 1], got {mix}")
        if not 0.0 < decay <= 1.0:
            raise ValueError(f"decay must be in (0, 1], got {decay}")
        self.mix = mix
        self.decay = decay
        self.min_observations = min_observations
        self.executed_weight = executed_weight
        self._lock = threading.Lock()
        self._w: dict[tuple[str, int], float] = {}
        self._observations = 0.0  # decayed total

    # ------------------------------------------------------------- fitting

    def _pairs(self, proposals, catalog):
        """(topic_name, dst_broker) move pairs from a proposal container
        (columnar ProposalSet or a plain ExecutionProposal list)."""
        topics = catalog.topics if catalog is not None else ()
        pairs: list[tuple[str, int]] = []
        dest = getattr(proposals, "destination_pairs", None)
        if dest is not None:
            tids, brokers = dest()
            for t, b in zip(tids.tolist(), brokers.tolist()):
                if 0 <= t < len(topics):
                    pairs.append((topics[t], int(b)))
            return pairs
        for p in proposals:
            old = set(p.old_replicas)
            t = int(p.topic)
            if not 0 <= t < len(topics):
                continue
            for b in p.new_replicas:
                if b not in old:
                    pairs.append((topics[t], int(b)))
        return pairs

    def _observe(self, pairs, weight: float) -> int:
        if not pairs:
            return 0
        with self._lock:
            d = self.decay
            if d < 1.0:
                self._observations *= d
                w = self._w
                for k in [k for k, v in w.items() if v * d < self.PRUNE_FLOOR]:
                    del w[k]
                for k in self._w:
                    self._w[k] *= d
            for k in pairs:
                self._w[k] = self._w.get(k, 0.0) + weight
            self._observations += weight * len(pairs)
        return len(pairs)

    def observe_proposals(self, proposals, catalog) -> int:
        """Fit from one anneal's published proposal set; returns the
        number of (topic, destination) pairs observed."""
        return self._observe(self._pairs(proposals, catalog), 1.0)

    def observe_executed(self, proposals, catalog) -> int:
        """Fit from proposals the executor actually applied (weighted
        `executed_weight`)."""
        return self._observe(
            self._pairs(proposals, catalog), self.executed_weight
        )

    # ------------------------------------------------------------ reading

    @property
    def observations(self) -> float:
        with self._lock:
            return self._observations

    @property
    def ready(self) -> bool:
        """Enough decayed observations to justify a non-zero mix."""
        return self.observations >= self.min_observations

    def table(self, catalog, shape) -> PriorTable:
        """Materialize onto one generation's padded (T, B) axes.

        Topics absent from this generation's catalog (deleted mid-stream)
        simply contribute nothing; brokers beyond the padded axis are
        dropped (they cannot be destinations).  A not-ready prior returns
        mix 0.0 — the byte-parity cold path."""
        T, B = shape.num_topics, shape.B
        w = np.zeros((T, B), np.float32)
        with self._lock:
            obs = self._observations
            if self._w and catalog is not None:
                tid = {t: i for i, t in enumerate(catalog.topics)}
                for (tname, b), v in self._w.items():
                    t = tid.get(tname)
                    if t is not None and 0 <= b < B:
                        w[t, b] += v
        mix = self.mix if obs >= self.min_observations else 0.0
        return PriorTable(weights=w, mix=mix, observations=obs)

    def state_json(self) -> dict:
        with self._lock:
            return {
                "observations": round(self._observations, 3),
                "pairs": len(self._w),
                "ready": self._observations >= self.min_observations,
                "mix": self.mix,
                "decay": self.decay,
            }
