"""Streaming controller — always-on incremental rebalancing.

The fifth subsystem beside monitor/analyzer/executor/planner/detector
(ROADMAP item 3): an always-on control loop that keeps the flattened
ClusterState device-resident, applies metric-window deltas in place
(models/whatif.py LiveState), re-anneals incrementally on every window
roll (warm-start carry + the learned move-acceptance prior of
controller/prior.py), and publishes each result into the facade's
proposal cache so the service always holds a continuously-fresh proposal.
"""

from cruise_control_tpu.controller.prior import MoveAcceptancePrior, PriorTable
from cruise_control_tpu.controller.streaming import StreamingController

__all__ = ["MoveAcceptancePrior", "PriorTable", "StreamingController"]
