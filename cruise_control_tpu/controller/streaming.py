"""StreamingController — the always-on incremental rebalancing loop.

Today's proposal path re-flattens the whole ClusterModel and anneals from
scratch on every request; under heavy traffic the service repays the full
model-build + anneal bill on every window roll.  The controller inverts
that: it owns a device-resident flattened ClusterState (models/whatif.py
LiveState) and, each time the partition aggregator rolls a metric window,

  1. extracts the window DELTA from two WindowedHistory snapshots
     (monitor/delta.py) — honoring the completeness mask, so half-sampled
     windows never read as traffic drops — and scatters only the changed
     partitions' loads into the live arrays (donated buffers, the fused
     anneal's trick; no re-flatten while the shape bucket holds);
  2. re-anneals INCREMENTALLY: the previous accepted placement seeds the
     carry (engine.init_carry_from) and the learned per-topic-pair
     move-acceptance prior (controller/prior.py) is folded into the
     engine's destination sampling, so converged regions are not
     re-derived from uniform luck;
  3. publishes the result into the facade's proposal cache
     (CruiseControl.publish_proposal), superseding any staler cached
     proposal — `/proposals` always serves the freshest answer `/state`
     reports.

Topology deltas: a broker death/revival applies in place
(LiveState.set_broker_liveness); entity churn (topics/partitions created
or deleted) and metadata-generation bumps force a full re-flatten —
counted by `controller.full-reflattens`, which the streaming bench gate
asserts stays at the initial 1 across metric-only windows.

Cold-parity contract: with warm starts off, the delta path off, and a
cold prior, one controller cycle is byte-for-byte today's
re-flatten-and-anneal (gated by `bench.py --streaming` and
tests/test_controller.py).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time

import numpy as np

from cruise_control_tpu.common.blackbox import RECORDER as _BLACKBOX
from cruise_control_tpu.common.resources import NUM_RESOURCES
from cruise_control_tpu.controller.prior import MoveAcceptancePrior
from cruise_control_tpu.models.whatif import LiveState
from cruise_control_tpu.monitor import ModelCompletenessRequirements
from cruise_control_tpu.monitor.delta import extract_window_delta

log = logging.getLogger(__name__)

#: latency-shaped bucket boundaries for the streaming hot path — finer
#: below 1 s than the default ladder because the headline target
#: (`slo.streaming.publish.target.s`, ROADMAP item 4) is sub-second
STREAMING_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 0.75, 1.0,
    1.5, 2.5, 5.0, 10.0, 30.0, 60.0,
)


@dataclasses.dataclass
class _ModelIndex:
    """Host-side join index of one flatten: everything needed to map a
    window delta's (topic, partition) entities onto replica rows without
    touching the device."""

    topology_generation: int
    catalog: object
    history: object  # WindowedHistory the live arrays are synced to
    part_rows: np.ndarray  # i32[P, max_rf] replica rows per pid (R pads)
    part_lookup: dict  # (first-seen topic_id, partition_number) -> pid
    #: ReducedLoads of `history` — cached so the next cycle's diff does
    #: not re-reduce the [E, W, 4] tensor it already reduced as `cur`
    reduced: object = None

    def model_generation(self):
        """The generation the live model REFLECTS right now: the topology
        generation it was flattened from + the aggregator generation of
        the window snapshot its loads are synced to (both counters are
        the same ones LoadMonitor.model_generation reports, so publish
        freshness comparisons stay meaningful across sources).  Advances
        with every delta cycle — a publish must never be stamped with the
        reflatten-time generation or the first unrelated model build
        (detector rounds) would sideline the controller permanently."""
        from cruise_control_tpu.monitor.load_monitor import ModelGeneration

        return ModelGeneration(
            metadata_generation=self.topology_generation,
            load_generation=int(self.history.generation),
        )


class StreamingController:
    """One per cluster facade; the fleet manager's per-cluster facades
    each own one (CruiseControl builds it when `controller.enabled`)."""

    def __init__(self, cc):
        cfg = cc.config
        self.cc = cc
        self.monitor = cc.monitor
        self.optimizer = cc.optimizer
        self.sensors = cc.sensors
        self.tracer = cc.tracer
        self.poll_interval_s = cfg.get("controller.poll.interval.ms") / 1000.0
        self.warm_start = cfg.get("controller.warm.start.enabled")
        self.delta_enabled = cfg.get("controller.delta.enabled")
        #: fuse delta-scatter + re-anneal + extraction into ONE device
        #: program on steady-state cycles (controller.fusion.enabled);
        #: requires warm starts (the fused program seeds from the prior
        #: placement) and a single-device engine
        self.fusion_enabled = cfg.get("controller.fusion.enabled")
        #: size the candidate plan from the delta's changed-entity count
        #: (controller.plan.*): quantized width steps so compile count
        #: stays bounded, full-K on reflatten
        self.plan_sizing = cfg.get("controller.plan.sizing.enabled")
        self.plan_cands_per_partition = cfg.get(
            "controller.plan.candidates.per.partition"
        )
        self.plan_min_candidates = cfg.get("controller.plan.min.candidates")
        self.prior = MoveAcceptancePrior(
            mix=cfg.get("controller.prior.mix"),
            decay=cfg.get("controller.prior.decay"),
            min_observations=cfg.get("controller.prior.min.observations"),
        )
        # warm-start carry and the move-acceptance prior are single-device
        # engine features; under a mesh mode the controller still runs —
        # device-resident deltas + always-fresh publishes — but each
        # anneal is cold (passing warm inputs would make EVERY cycle
        # raise and the "always-on" loop would be permanently dead)
        if self.optimizer.parallel_mode != "single":
            if self.warm_start or self.prior.mix > 0.0:
                log.warning(
                    "streaming controller: warm starts and the move-"
                    "acceptance prior are disabled under "
                    "tpu.parallel.mode=%r (single-device features)",
                    self.optimizer.parallel_mode,
                )
            self.warm_start = False
            self.prior.mix = 0.0
            self.fusion_enabled = False
        #: prior sampling is compiled in only when a non-zero mix could
        #: ever apply — mix 0 keeps the engine program (and its cache key)
        #: byte-identical to the request path's
        self._opt_config = dataclasses.replace(
            cfg.optimizer_config(), prior_enabled=self.prior.mix > 0.0
        )
        self._requirements = ModelCompletenessRequirements(
            min_required_num_windows=1,
            min_monitored_partitions_percentage=cfg.get(
                "min.valid.partition.ratio"
            ),
        )
        #: streaming publish-latency SLO target (`slo.streaming.publish.
        #: target.s`): each window-roll-to-publish wall feeds the
        #: "streaming-publish" SLO as a good/bad sample
        self._publish_target_s = cfg.get("slo.streaming.publish.target.s")
        # mint the hot-path histograms EAGERLY so their boundaries are
        # always the streaming ladder — a reader getting there first must
        # never fix them at the default ladder
        for stage in (
            "window-roll-to-publish", "delta-extract", "scatter", "anneal",
            "host-extract", "publish",
        ):
            self.sensors.histogram(
                f"controller.{stage}-seconds", buckets=STREAMING_BUCKETS
            )
        self._live: LiveState | None = None
        self._index: _ModelIndex | None = None
        self._warm = None  # (shape, replica_broker, replica_is_leader, replica_disk)
        #: fetch_before_host of the reflattened state — placement columns
        #: are delta-invariant between reflattens, so the fused cycle
        #: reuses this dict (only replica_disk_bytes refreshes, from the
        #: cycle payload) instead of re-fetching bulk arrays every window
        self._before_host: dict | None = None
        self._last_window: int | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._boot_gate: threading.Event | None = None
        self._lock = threading.Lock()  # one cycle at a time (thread + run_once)
        # /state ControllerState internals (sensors carry the same counts
        # as monotonic series; these are the structured view)
        self._stats = dict(
            windowRolls=0, deltaApplies=0, fullReflattens=0,
            # per-reason breakout of fullReflattens (initial / topology /
            # delta-disabled / entities) so a p99 regression attributes
            # to a cause; the aggregate stays for compatibility
            fullReflattensByReason={},
            incrementalAnneals=0, warmStarts=0, proposalsPublished=0,
            fusedCycles=0, planSizedCycles=0,
            lastRounds=None, lastObjective=None, lastWallSeconds=None,
            lastWindowIndex=None, lastPublishMs=None, lastError=None,
            lastCycleDispatches=None, coldCycleSeconds=None,
            fusedColdCycleSeconds=None,
            loopFailures=0, cyclesShed=0, brownoutCycles=0,
        )

    # ----------------------------------------------------------- lifecycle

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self, *, boot_gate: threading.Event | None = None) -> None:
        """`boot_gate` (facade start_up): the boot-time manifest prewarm's
        completion event.  The loop thread starts immediately (running is
        True) but waits — bounded — for the gate before its first cycle,
        so the active buckets' compiles are already in flight on the warm
        pool when the controller takes ownership of proposal publishing
        (PR 9 parks the bucket-prewarm path while the controller runs;
        boot is the one window the manifest prewarm has)."""
        if self.running:
            return
        self._boot_gate = boot_gate
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="streaming-controller"
        )
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)
        self._thread = None

    def _loop(self) -> None:
        gate = getattr(self, "_boot_gate", None)
        if gate is not None:
            # bounded: a wedged prewarm must not keep the always-on loop
            # parked forever — after the budget the controller proceeds
            # and the remaining compiles just overlap its first cycles
            deadline = time.monotonic() + 120.0
            while (
                not gate.is_set()
                and not self._stop.is_set()
                and time.monotonic() < deadline
            ):
                gate.wait(0.2)
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.run_once()
            except Exception as e:  # noqa: BLE001 — the loop must keep ticking
                self._stats["loopFailures"] += 1
                self._stats["lastError"] = repr(e)
                self.sensors.counter("controller.loop-failures").inc()
                log.warning("streaming controller cycle failed", exc_info=True)

    # ------------------------------------------------------------- one tick

    def run_once(self):
        """One control cycle; returns a cycle-info dict when a window roll
        was processed, None when there was nothing to do.  Public so tests
        and the streaming bench drive the loop deterministically."""
        with self._lock:
            return self._run_once_locked()

    def _run_once_locked(self):
        agg = self.monitor.partition_aggregator
        cur_w = agg.current_window_index
        if cur_w is None:
            return None
        if (
            self._last_window is not None
            and cur_w <= self._last_window
            and self._live is not None
        ):
            return None  # no window roll since the last cycle
        try:
            history = agg.history_snapshot()
        except ValueError:
            return None  # no completed window yet
        t0 = time.monotonic()
        with self.tracer.span(
            "controller.window-roll", component="controller",
            window_index=int(cur_w),
        ) as sp:
            from cruise_control_tpu.common.dispatch import dispatch_meter

            # per-cycle device-dispatch accounting: the fused steady-state
            # contract is <= 2 (one program dispatch + one host
            # extraction), proved by counting at the choke points — the
            # streaming bench's smoke gate reads this same meter
            with dispatch_meter() as meter:
                if _BLACKBOX.enabled:
                    # the cycle is a dispatch-bearing unit of work: its
                    # begin/end (and any hang between them) belongs in the
                    # durable spool beside the engine records it triggers
                    with _BLACKBOX.record(
                        "controller-cycle", window=int(cur_w),
                        cluster=self.cc.cluster_id or "",
                    ):
                        info = self._cycle(history, sp)
                else:
                    info = self._cycle(history, sp)
            wall = time.monotonic() - t0
            self._stats["lastCycleDispatches"] = meter.total
            self.sensors.gauge("controller.cycle-dispatches").set(meter.total)
            info["dispatches"] = dict(meter.counts)
            if info.get("published"):
                first_fused = bool(
                    info.get("fused") and self._stats["fusedCycles"] == 1
                )
                if self._stats["proposalsPublished"] > 1 and not first_fused:
                    # the HEADLINE latency: metric-window roll observed ->
                    # superseding proposal published, with the cycle's
                    # trace id as the OpenMetrics exemplar so a p99
                    # outlier on a dashboard links straight to its /trace
                    # replay.  The FIRST published cycle is excluded — it
                    # pays the cold XLA compile, and one restart sample
                    # would dominate a steady-state p99 (same exclusion
                    # the calibration sampler and streaming-publish SLO
                    # apply); it reports through the one-shot cold-compile
                    # sensor below instead.
                    self.sensors.histogram(
                        "controller.window-roll-to-publish-seconds",
                        buckets=STREAMING_BUCKETS,
                    ).observe(
                        wall,
                        exemplar=(
                            {"trace_id": sp.trace_id} if sp.trace_id else None
                        ),
                    )
                elif first_fused:
                    # the first FUSED cycle compiles the fused cycle
                    # program — its wall is a compile artifact too, so it
                    # reports through its own one-shot sensor instead of
                    # skewing the steady-state p99
                    self._stats["fusedColdCycleSeconds"] = round(wall, 6)
                    self.sensors.gauge(
                        "controller.fused-cold-compile-cycle-seconds"
                    ).set(wall)
                elif self._stats["coldCycleSeconds"] is None:
                    # one-shot cold-compile sensor: the first published
                    # cycle's wall (trace + XLA compile + anneal), kept
                    # out of the steady-state histogram but never hidden
                    self._stats["coldCycleSeconds"] = round(wall, 6)
                    self.sensors.gauge(
                        "controller.cold-compile-cycle-seconds"
                    ).set(wall)
                reg = getattr(self.cc, "slo_registry", None)
                # the FIRST cycle pays the cold XLA compile and will blow
                # any sub-second target — that wall is the cold-start
                # SLO's sample, and feeding it here would fire a spurious
                # SLO_BURN on every restart (the histogram above still
                # reports it honestly)
                if reg is not None and self._stats["incrementalAnneals"] > 1:
                    reg.record(
                        "streaming-publish", wall <= self._publish_target_s
                    )
        self._last_window = cur_w
        self._stats["windowRolls"] += 1
        self._stats["lastWindowIndex"] = int(cur_w)
        self._stats["lastWallSeconds"] = round(wall, 6)
        self.sensors.counter("controller.window-rolls").inc()
        return info

    def _cycle(self, history, sp) -> dict:
        info: dict = dict(reflattened=False, delta_partitions=0)
        delta_rows = None
        topo_gen = self.monitor.metadata.topology().generation
        idx = self._index
        if (
            self._live is None
            or idx is None
            or not self.delta_enabled
            or topo_gen != idx.topology_generation
        ):
            # topology outranks delta-disabled: the reason decides whether
            # the warm placement survives, and a membership change must
            # clear it in EVERY mode (a stale warm start could
            # double-place a partition)
            if self._live is None or idx is None:
                reason = "initial"
            elif topo_gen != idx.topology_generation:
                reason = "topology"
            else:
                reason = "delta-disabled"
            self._reflatten(history, reason=reason)
            info["reflattened"] = True
            info["reflatten_reason"] = reason
        else:
            t_ex = time.monotonic()
            delta = extract_window_delta(
                idx.history, history,
                self.monitor.partition_aggregator.metric_def,
                prev_reduced=idx.reduced,
            )
            self._stage_observe(
                "controller.delta-extract-seconds", time.monotonic() - t_ex, sp
            )
            if delta.requires_reflatten:
                # topics/partitions appeared or vanished mid-stream: the
                # in-place path cannot express membership churn
                self._reflatten(history, reason="entities")
                info["reflattened"] = True
                info["reflatten_reason"] = "entities"
            else:
                delta_rows = self._delta_rows(delta)
                info["delta_partitions"] = delta_rows[3]
                self._stats["deltaApplies"] += 1
                self.sensors.counter("controller.delta-applies").inc()
                if delta_rows[3]:
                    self.sensors.counter("controller.delta-partitions").inc(
                        delta_rows[3]
                    )
                idx.history = history
                idx.reduced = delta.reduced
        sp.set(
            reflattened=info["reflattened"],
            delta_partitions=info["delta_partitions"],
        )
        info.update(self._anneal(sp, delta=delta_rows))
        return info

    def _stage_observe(self, name: str, wall_s: float, sp) -> None:
        """One hot-path stage sample into its latency Histogram, exemplar
        = this cycle's trace id (delta-extract / scatter / anneal /
        host-extract / publish — the stages `controller.window-roll-to-
        publish-seconds` is the sum of)."""
        self.sensors.histogram(name, buckets=STREAMING_BUCKETS).observe(
            wall_s,
            exemplar={"trace_id": sp.trace_id} if sp.trace_id else None,
        )

    # ----------------------------------------------------- flatten / delta

    def _reflatten(self, history, *, reason: str) -> None:
        """Full model build — the slow path the delta machinery exists to
        avoid; every occurrence is counted and reasoned."""
        from cruise_control_tpu.analyzer.engine import partition_replica_table

        # generation BEFORE the build: if a metadata refresh lands while
        # the model builds, this stamp is older than what the build
        # consumed and the next cycle's generation check re-flattens —
        # the safe direction (stamping the AFTER generation could pin a
        # pre-refresh model as current until the next topology bump)
        topo_gen = self.monitor.metadata.topology().generation
        with self.monitor.acquire_for_model_generation():
            state = self.monitor.cluster_model(self._requirements)
        catalog = self.monitor.last_catalog
        # aggregator entities carry FIRST-SEEN topology topic ids (the
        # sampler/partitions_fn space the monitor's own load join uses);
        # the catalog/state ids are name-rank.  The lookup must bridge the
        # two spaces or a cluster whose topics first appear out of name
        # order scatters window loads onto the wrong topics' replicas.
        lookup = {}
        if catalog is not None:
            parts = self.monitor.metadata.topology().partitions
            if self.monitor.topic_filter is not None:
                parts = tuple(
                    p for p in parts if self.monitor.topic_filter(p.topic)
                )
            first_seen: dict = {}
            for p in parts:
                first_seen.setdefault(p.topic, len(first_seen))
            pid_by_name = {
                (tname, int(pnum)): pid
                for pid, (tname, pnum) in enumerate(catalog.partitions)
            }
            for p in parts:
                pid = pid_by_name.get((p.topic, int(p.partition)))
                if pid is not None:
                    lookup[(first_seen[p.topic], int(p.partition))] = pid
        self._live = LiveState(state)
        self._index = _ModelIndex(
            topology_generation=topo_gen,
            catalog=catalog,
            history=history,
            part_rows=partition_replica_table(state),
            part_lookup=lookup,
        )
        if self._warm is not None and self._warm[0] != state.shape:
            self._warm = None  # bucket changed: the placement axes moved
        if reason in ("topology", "entities"):
            # membership may have changed under the old placement — a
            # stale warm start could double-place a partition
            self._warm = None
        # the fused cycle's BEFORE-placement host cache: placement columns
        # are delta-invariant until the next reflatten, so one fetch here
        # (off the steady-state path) serves every fused extraction
        if self.fusion_enabled:
            from cruise_control_tpu.analyzer.proposals import fetch_before_host

            self._before_host = fetch_before_host(state)
        else:
            self._before_host = None
        self._stats["fullReflattens"] += 1
        by = self._stats["fullReflattensByReason"]
        by[reason] = by.get(reason, 0) + 1
        self.sensors.counter("controller.full-reflattens").inc()
        self.sensors.counter(f"controller.reflatten.{reason}").inc()

    def _delta_rows(self, delta):
        """One window delta as a replica-row scatter triple
        `(rows, ll_rows, fl_rows, n_partitions)` — shared by the staged
        path (LiveState.set_partition_loads) and the fused cycle (the
        same scatter, in-graph); `(None, None, None, 0)` when no mapped
        partition changed."""
        idx = self._index
        changed = delta.changed
        if not changed.any():
            return None, None, None, 0
        ents = [e for e, c in zip(delta.entities, changed) if c]
        ll = delta.loads[changed]
        pids = []
        keep = []
        for i, e in enumerate(ents):
            pid = idx.part_lookup.get((int(e.topic), int(e.partition)))
            if pid is not None:
                pids.append(pid)
                keep.append(i)
        if not pids:
            return None, None, None, 0
        ll = ll[keep]
        fl = self.monitor.follower_loads(ll)
        rows_p = idx.part_rows[np.asarray(pids)]  # [n, max_rf], R pads
        R = self._live.shape.R
        valid = rows_p < R
        counts = valid.sum(1)
        rows = rows_p[valid].astype(np.int32)
        ll_rows = np.repeat(ll, counts, axis=0)
        fl_rows = np.repeat(fl, counts, axis=0)
        return rows, ll_rows, fl_rows, len(pids)

    # -------------------------------------------------------------- anneal

    def _plan_config(self, cfg, delta_partitions: int):
        """Delta-sized candidate plan: a 50-partition window roll must not
        pay the full-K sampling plan.  The width needed is
        max(plan.min.candidates, delta_partitions x candidates-per-
        partition), quantized to one of THREE fixed fractions of full K
        (1/2, 1/4, 1/8) so each base config yields at most three extra
        engine-cache keys (brownout_config's bounded-compile idiom) —
        never an exact per-delta width, which would compile per cycle.
        Full K whenever the need reaches K/2 (and always on reflatten,
        where there is no delta)."""
        K = cfg.num_candidates
        needed = max(
            int(self.plan_min_candidates),
            int(delta_partitions) * int(self.plan_cands_per_partition),
        )
        if needed * 2 > K:
            return cfg
        f = 0.5
        while f > 0.125 and K * (f / 2) >= needed:
            f /= 2
        return dataclasses.replace(
            cfg,
            num_candidates=max(64, int(K * f)),
            leadership_candidates=max(8, int(cfg.leadership_candidates * f)),
            swap_candidates=max(0, int(cfg.swap_candidates * f)),
        )

    def _anneal(self, sp, delta=None) -> dict:
        """One cycle's re-anneal.  `delta` is the window's scatter triple
        `(rows, ll_rows, fl_rows, n_partitions)` on steady-state cycles
        (None on reflatten cycles, whose scatter is the flatten itself).

        Steady state prefers the FUSED path: scatter + warm re-anneal +
        extraction as one donated device program
        (GoalOptimizer.optimize_streaming_cycle), submitted INTERACTIVE —
        an operator-facing latency path — and granted unsegmented by the
        scheduler's fast path when nothing else waits.  The staged path
        (host scatter, then a supervised BACKGROUND optimize) remains the
        fallback for: fusion off, no warm placement yet, no cached engine
        (the staged run builds and caches it, so the NEXT cycle fuses),
        mesh parallel modes, and supervisor-breaker-open."""
        state = self._live.state
        catalog = self._index.catalog
        warm = None
        if self.warm_start and self._warm is not None and self._warm[0] == state.shape:
            warm = self._warm[1:]
        prior_table = (
            self.prior.table(catalog, state.shape)
            if self._opt_config.prior_enabled
            else None
        )
        options = self.cc._build_options(state)
        # drift cycles are BACKGROUND work on the shared device: under
        # the scheduler they run segmented (preemptible by URGENT fix
        # pipelines), shed under transient overload (counted — the cycle
        # is skipped, the stale proposal keeps serving inside its
        # freshness SLO), and run BROWNED OUT — reduced candidate width,
        # not skipped — under sustained overload
        sched = self.cc.scheduler
        cfg = self._opt_config
        plan_sized = False
        if delta is not None and self.plan_sizing:
            sized = self._plan_config(cfg, delta[3])
            plan_sized = sized is not cfg
            cfg = sized
        brownout = False
        if sched is not None and sched.brownout_active:
            cfg = sched.brownout_config(cfg)
            brownout = True
        # fused eligibility is decided BEFORE submission so the work
        # class is honest: only a cycle that will actually take the
        # one-dispatch fast path rides the INTERACTIVE queue.  The
        # engine-cache check makes the first cycle after a (re)start or a
        # fresh plan width go staged — which builds and caches the
        # engine — and every later one fused.
        fused_ready = (
            delta is not None
            and self.fusion_enabled
            and warm is not None
            and self.optimizer.parallel_mode == "single"
            and self.optimizer.has_engine_for(state.shape, config=cfg)
        )
        if delta is not None and not fused_ready:
            # staged scatter, BEFORE submission: a shed cycle must still
            # leave the live loads current (the window was consumed —
            # idx.history already advanced)
            rows, ll_rows, fl_rows, _n = delta
            t_sc = time.monotonic()
            if rows is not None:
                self._live.set_partition_loads(rows, ll_rows, fl_rows)
            self._stage_observe(
                "controller.scatter-seconds", time.monotonic() - t_sc, sp
            )
            state = self._live.state
        ran = dict(fused=False)

        def _run():
            # the anneal timer lives INSIDE the scheduled body: it must
            # keep measuring anneal wall, not scheduler queue wait —
            # fleet.scheduler.wait-timer.background already reports the
            # wait separately
            t_an = time.monotonic()
            with self.sensors.timer("controller.anneal-timer").time():
                r = None
                if fused_ready:
                    rows, ll_rows, fl_rows, _n = delta
                    if rows is None:
                        # nothing changed this window: an empty scatter
                        # (all-sentinel rows) still re-anneals fused
                        rows = np.zeros(0, np.int32)
                        ll_rows = fl_rows = np.zeros(
                            (0, NUM_RESOURCES), np.float32
                        )
                    out = self.optimizer.optimize_streaming_cycle(
                        state,
                        rows=rows,
                        leader_loads=ll_rows,
                        follower_loads=fl_rows,
                        initial_placement=warm,
                        options=options,
                        config=cfg,
                        prior=prior_table,
                        before_host=self._before_host,
                    )
                    if out is not None:
                        r, (new_ll, new_fl) = out
                        # ownership hand-back: the cycle donated the live
                        # load buffers and returned the scattered pair
                        self._live.adopt_loads(new_ll, new_fl)
                        ran["fused"] = True
                if r is None:
                    if fused_ready:
                        # lost the engine-cache race between the check
                        # and the call: the in-graph scatter never ran,
                        # so scatter staged before annealing
                        rows, ll_rows, fl_rows, _n = delta
                        if rows is not None:
                            self._live.set_partition_loads(
                                rows, ll_rows, fl_rows
                            )
                    r = self.optimizer.optimize(
                        self._live.state,
                        options=options,
                        config=cfg,
                        initial_placement=warm,
                        prior=prior_table,
                    )
            self._stage_observe(
                "controller.anneal-seconds", time.monotonic() - t_an, sp
            )
            return r

        if sched is None:
            result = _run()
        else:
            from cruise_control_tpu.fleet.scheduler import (
                BackgroundShedError,
                WorkClass,
            )

            try:
                result = sched.run(
                    WorkClass.INTERACTIVE if fused_ready
                    else WorkClass.BACKGROUND,
                    _run,
                    cluster_id=self.cc.cluster_id or "",
                    op="controller-cycle",
                    freshness_slo_s=self.cc._freshness_slo_s,
                )
            except BackgroundShedError:
                self._stats["cyclesShed"] += 1
                self.sensors.counter("controller.cycles-shed").inc()
                sp.set(shed=True)
                return dict(shed=True, rounds=0, warm_start=False,
                            published=False)
        if brownout:
            self._stats["brownoutCycles"] += 1
        if ran["fused"]:
            self._stats["fusedCycles"] += 1
            self.sensors.counter("controller.fused-cycles").inc()
        if plan_sized:
            self._stats["planSizedCycles"] += 1
            self.sensors.counter("controller.plan-sized-cycles").inc()
        timing = next((h for h in result.history if h.get("timing")), {})
        if timing.get("host_extract_s") is not None:
            # the fused run's one blocking host fetch — the stage the
            # ROADMAP fusion audit targets, now measured per cycle
            self._stage_observe(
                "controller.host-extract-seconds",
                timing["host_extract_s"], sp,
            )
        rounds = sum(1 for h in result.history if not h.get("timing"))
        after = result.state_after
        self._warm = (
            state.shape, after.replica_broker, after.replica_is_leader,
            after.replica_disk,
        )
        observed = self.prior.observe_proposals(result.proposals, catalog)
        t_pub = time.monotonic()
        published = self.cc.publish_proposal(
            result, generation=self._index.model_generation(),
            prior_table=prior_table,
            # the FIRST publish (cold-compile cycle) is excluded from
            # calibration sampling — the same exclusion the streaming-
            # publish SLO applies — so restarts can't fire a spurious
            # MODEL_DRIFT off a cold, possibly-degraded first anneal
            calibration_eligible=self._stats["incrementalAnneals"] > 0,
        )
        self._stage_observe(
            "controller.publish-seconds", time.monotonic() - t_pub, sp
        )
        self._stats["incrementalAnneals"] += 1
        self._stats["lastRounds"] = rounds
        self._stats["lastObjective"] = result.objective_after
        if warm is not None:
            self._stats["warmStarts"] += 1
            self.sensors.counter("controller.warm-starts").inc()
        if published:
            self._stats["proposalsPublished"] += 1
            self._stats["lastPublishMs"] = int(time.time() * 1000)
            self.sensors.counter("controller.proposals-published").inc()
        self.sensors.counter("controller.incremental-anneals").inc()
        self.sensors.gauge("controller.rounds-last").set(rounds)
        self.sensors.gauge("controller.prior-observations").set(
            self.prior.observations
        )
        sp.set(
            rounds=rounds,
            warm_start=warm is not None,
            prior_mix=(prior_table.mix if prior_table is not None else 0.0),
            published=published,
            objective_after=result.objective_after,
            fused=ran["fused"],
            plan_candidates=cfg.num_candidates,
        )
        return dict(
            rounds=rounds,
            warm_start=warm is not None,
            objective=result.objective_after,
            prior_observed=observed,
            published=published,
            fused=ran["fused"],
            result=result,
        )

    # ---------------------------------------------------- executor feedback

    def observe_executed(self, proposals) -> None:
        """Executed proposals are the strongest acceptance signal the
        prior gets (facade._execute feeds every execution through here)."""
        idx = self._index
        catalog = idx.catalog if idx is not None else self.monitor.last_catalog
        if catalog is None:
            return
        self.prior.observe_executed(proposals, catalog)
        self.sensors.gauge("controller.prior-observations").set(
            self.prior.observations
        )

    # ---------------------------------------------------------------- state

    def state_json(self) -> dict:
        out = dict(self._stats)
        out["running"] = self.running
        out["warmStartEnabled"] = self.warm_start
        out["deltaEnabled"] = self.delta_enabled
        out["prior"] = self.prior.state_json()
        return out
