"""Windowed metric sample aggregation — array-native rebuild of the core
aggregator.

Reference: cruise-control-core monitor/sampling/aggregator/
MetricSampleAggregator.java:84 (addSample:141-175, aggregate:193),
RawMetricValues.java (cyclic per-window buffers + extrapolation),
AggregationOptions/MetricSampleCompleteness (completeness math).

The reference keeps one RawMetricValues object per entity (HashMap of
cyclic float arrays, per-entity locks).  Here ALL entities share three
dense ring tensors:

    acc    f32[E, W, M]   per-window accumulated value per metric
    counts i16[E, W]      samples per window
    (ring axis W covers num_windows + 1; one slot is the in-progress
     "current" window, exactly like the reference's current window)

addSample is a vectorized scatter of a sample batch; aggregate() computes
validity, extrapolation, and completeness for every entity at once with
masked array ops instead of per-entity walks.  At LinkedIn scale
(SURVEY §3.2: millions of samples per window) this is the difference
between a hash-map hot loop and a handful of numpy kernels; the output
tensor feeds the ClusterState builder directly.

Extrapolation semantics (reference Extrapolation.java, preference order):
  NONE                 count >= min_samples
  AVG_AVAILABLE        min_samples > count >= max(1, min_samples/2)
  AVG_ADJACENT         count == 0, both neighbor windows have full samples
  FORCED_INSUFFICIENT  count >= 1
  NO_VALID_EXTRAPOLATION  otherwise (window invalid for the entity)
"""

from __future__ import annotations

import dataclasses
import threading

import numpy as np

from cruise_control_tpu.monitor.metricdef import MetricDef, ValueComputingStrategy


class Extrapolation:
    """Per-(entity, window) extrapolation codes (reference Extrapolation.java)."""

    NONE = 0
    AVG_AVAILABLE = 1
    AVG_ADJACENT = 2
    FORCED_INSUFFICIENT = 3
    NO_VALID_EXTRAPOLATION = 4


@dataclasses.dataclass(frozen=True)
class AggregationOptions:
    """Reference AggregationOptions.java (granularity ENTITY vs ENTITY_GROUP)."""

    min_valid_entity_ratio: float = 0.95
    min_valid_entity_group_ratio: float = 0.0
    min_valid_windows: int = 1
    #: max windows an entity may cover via extrapolation and stay valid
    #: (reference MetricSampleAggregator._maxAllowedExtrapolationsPerEntity)
    max_allowed_extrapolations_per_entity: int = 5
    #: "ENTITY" or "ENTITY_GROUP": group granularity invalidates a whole
    #: group (= topic) when any member entity is invalid
    granularity: str = "ENTITY"


@dataclasses.dataclass(frozen=True)
class MetricSampleCompleteness:
    """Reference MetricSampleCompleteness.java."""

    generation: int
    valid_windows: np.ndarray  # i64[Wv] window indices that passed the ratio checks
    valid_entity_ratio_by_window: np.ndarray  # f32[Wv]
    valid_entity_ratio: float
    valid_entity_group_ratio: float


@dataclasses.dataclass(frozen=True)
class AggregationResult:
    """Reference ValuesAndExtrapolations + completeness, for all entities.

    values[e, w, m] is the aggregated metric value of entity e in (valid)
    window w; entity_valid marks entities meeting the options' criteria.
    """

    window_indices: np.ndarray  # i64[Wv] newest -> oldest
    values: np.ndarray  # f32[E, Wv, M]
    window_valid: np.ndarray  # bool[E, Wv]
    extrapolation: np.ndarray  # i8[E, Wv]
    entity_valid: np.ndarray  # bool[E]
    completeness: MetricSampleCompleteness


@dataclasses.dataclass(frozen=True)
class WindowedHistory:
    """Read-only snapshot of the aggregator's completed windows.

    The forecaster's (planner/forecast.py) input contract: per-entity
    per-window aggregated values plus a completeness mask, WITHOUT the
    extrapolation/validity policy aggregate() layers on top — a trend fit
    wants raw observations and an honest "this cell was sampled" bit, and
    it must not reach into the ring buffers (`_acc`/`_roll_to` slots are
    private and move under the lock).
    """

    window_indices: np.ndarray  # i64[Wv] newest -> oldest
    window_ms: int
    values: np.ndarray  # f32[E, Wv, M] per-window values (strategy-reduced)
    sample_counts: np.ndarray  # i32[E, Wv] samples behind each cell
    complete: np.ndarray  # bool[E, Wv] cell met min_samples (no extrapolation)
    entities: tuple  # row order of the E axis
    generation: int


class WindowedMetricSampleAggregator:
    """Dense ring-buffer aggregator over a dynamic entity set.

    Entities are interned to dense row ids on first sample (reference keys
    by Entity objects; our entity keys are any hashable, typically
    (topic_id, partition_id) or broker_id).  Entity groups (topic) support
    ENTITY_GROUP granularity completeness.
    """

    def __init__(
        self,
        num_windows: int,
        window_ms: int,
        min_samples_per_window: int,
        metric_def: MetricDef,
        *,
        initial_capacity: int = 1024,
    ):
        if num_windows < 1:
            raise ValueError("need at least one available window")
        self.num_windows = num_windows
        self.window_ms = window_ms
        self.min_samples = max(1, min_samples_per_window)
        self.half_min = max(1, min_samples_per_window // 2)
        self.metric_def = metric_def
        self._M = metric_def.num_metrics
        self._W = num_windows + 1  # + current window
        self._strategies = np.array(
            [
                {"avg": 0, "max": 1, "latest": 2}[m.strategy.value]
                for m in metric_def.all_infos()
            ],
            np.int8,
        )
        self._lock = threading.RLock()
        self._entity_rows: dict = {}
        self._entity_group: dict = {}  # entity key -> group key
        self._capacity = initial_capacity
        self._acc = np.zeros((initial_capacity, self._W, self._M), np.float32)
        self._latest_ts = np.full((initial_capacity, self._W, self._M), -1, np.int64)
        self._counts = np.zeros((initial_capacity, self._W), np.int32)
        self._current_window: int | None = None  # window index (time//window_ms)
        self._oldest_window: int | None = None
        self._generation = 0

    # ------------------------------------------------------------------

    @property
    def generation(self) -> int:
        return self._generation

    @property
    def current_window_index(self) -> int | None:
        return self._current_window

    def num_entities(self) -> int:
        return len(self._entity_rows)

    def _row(self, entity) -> int:
        row = self._entity_rows.get(entity)
        if row is None:
            row = len(self._entity_rows)
            if row >= self._capacity:
                self._grow(max(2 * self._capacity, row + 1))
            self._entity_rows[entity] = row
            self._generation += 1
        return row

    def _grow(self, new_cap: int):
        for name in ("_acc", "_latest_ts", "_counts"):
            old = getattr(self, name)
            new = np.zeros((new_cap, *old.shape[1:]), old.dtype)
            if name == "_latest_ts":
                new[...] = -1
            new[: old.shape[0]] = old
            setattr(self, name, new)
        self._capacity = new_cap

    def _slot(self, window_index: int) -> int:
        return window_index % self._W

    def _roll_to(self, window_index: int):
        """Advance the current window, clearing slots that get recycled
        (reference RawMetricValues window rolling / WindowIndexedArrays)."""
        if self._current_window is None:
            self._current_window = window_index
            self._oldest_window = window_index
            return
        if window_index <= self._current_window:
            return
        if window_index - self._current_window >= self._W:
            # the jump recycles every slot (e.g. bootstrap after a long gap):
            # clear the whole ring at once instead of window-by-window
            self._acc[:] = 0.0
            self._latest_ts[:] = -1
            self._counts[:] = 0
        else:
            for w in range(self._current_window + 1, window_index + 1):
                slot = self._slot(w)
                self._acc[:, slot] = 0.0
                self._latest_ts[:, slot] = -1
                self._counts[:, slot] = 0
        self._current_window = window_index
        self._oldest_window = max(
            self._oldest_window or 0, window_index - self.num_windows
        )
        self._generation += 1

    # ------------------------------------------------------------------

    def add_sample(self, entity, time_ms: int, values, group=None) -> bool:
        """Add one sample (reference MetricSampleAggregator.addSample:141).

        values: f32[M] (metric-id indexed) or dict name->value.
        Returns False if the sample is too old (its window already rolled out).
        """
        with self._lock:
            if isinstance(values, dict):
                arr = np.zeros(self._M, np.float32)
                for k, v in values.items():
                    arr[self.metric_def.metric_id(k)] = v
                values = arr
            else:
                values = np.asarray(values, np.float32)
            w = time_ms // self.window_ms
            if self._current_window is None or w > self._current_window:
                self._roll_to(w)
            if w < (self._oldest_window or 0):
                return False  # too old (reference rejects samples out of range)
            row = self._row(entity)
            if group is not None:
                self._entity_group[entity] = group
            slot = self._slot(w)
            avg = self._strategies == 0
            mx = self._strategies == 1
            latest = self._strategies == 2
            self._acc[row, slot, avg] += values[avg]
            if self._counts[row, slot] == 0:
                self._acc[row, slot, mx] = values[mx]
            else:
                self._acc[row, slot, mx] = np.maximum(self._acc[row, slot, mx], values[mx])
            newer = time_ms >= self._latest_ts[row, slot, latest]
            lat_ids = np.nonzero(latest)[0][newer]
            self._acc[row, slot, lat_ids] = values[lat_ids]
            self._latest_ts[row, slot, lat_ids] = time_ms
            self._counts[row, slot] += 1
            return True

    def add_samples_batch(self, entities: list, times_ms: np.ndarray, values: np.ndarray, groups=None):
        """Bulk add (the metrics-reporter consumer path at scale)."""
        for i, e in enumerate(entities):
            self.add_sample(e, int(times_ms[i]), values[i], None if groups is None else groups[i])

    def add_samples_columnar(
        self, entities: list, time_ms: int, values: np.ndarray, groups=None
    ) -> bool:
        """Vectorized add of one sample per entity, all stamped time_ms.

        The scale path for a sampler that drains a whole fetch window at
        once: per-strategy accumulation runs as array ops (np.add.at /
        np.maximum.at honor duplicate entities exactly like repeated
        add_sample calls).  values: f32[N, M].  Returns False when the
        window already rolled out.
        """
        with self._lock:
            values = np.asarray(values, np.float32)
            w = time_ms // self.window_ms
            if self._current_window is None or w > self._current_window:
                self._roll_to(w)
            if w < (self._oldest_window or 0):
                return False
            rows = np.fromiter(
                (self._row(e) for e in entities), np.int64, count=len(entities)
            )
            if groups is not None:
                for e, g in zip(entities, groups):
                    self._entity_group[e] = g
            slot = self._slot(w)
            acc = self._acc[:, slot]  # [cap, M] view
            counts = self._counts[:, slot]
            avg_ids = np.nonzero(self._strategies == 0)[0]
            mx_ids = np.nonzero(self._strategies == 1)[0]
            lat_ids = np.nonzero(self._strategies == 2)[0]
            # MAX: rows at count 0 take the incoming value, so seed them
            # with -inf before the running maximum
            fresh = rows[counts[rows] == 0]
            if mx_ids.size:
                acc[np.ix_(fresh, mx_ids)] = -np.inf
                np.maximum.at(acc, (rows[:, None], mx_ids[None, :]), values[:, mx_ids])
            if avg_ids.size:
                np.add.at(acc, (rows[:, None], avg_ids[None, :]), values[:, avg_ids])
            if lat_ids.size:
                ts = self._latest_ts[:, slot]
                newer = time_ms >= ts[np.ix_(rows, lat_ids)]
                # plain fancy assignment: later duplicates win, like the
                # per-sample path's >= check at equal timestamps
                upd = np.where(newer, values[:, lat_ids], acc[np.ix_(rows, lat_ids)])
                acc[np.ix_(rows, lat_ids)] = upd
                ts[np.ix_(rows, lat_ids)] = np.where(
                    newer, time_ms, ts[np.ix_(rows, lat_ids)]
                )
            np.add.at(counts, rows, 1)
            return True

    # ------------------------------------------------------------------

    def aggregate(self, options: AggregationOptions | None = None) -> AggregationResult:
        """Aggregate all completed windows (reference aggregate:193).

        Vectorized: one pass computes per-(entity, window) validity +
        extrapolation, per-window entity ratios, per-entity validity, and
        group validity.
        """
        options = options or AggregationOptions()
        with self._lock:
            if self._current_window is None:
                raise ValueError("no samples added yet")
            E = len(self._entity_rows)
            newest = self._current_window - 1  # exclude in-progress window
            oldest = max(self._oldest_window or 0, newest - self.num_windows + 1)
            if newest < oldest:
                raise ValueError("no completed windows yet")
            widx = np.arange(newest, oldest - 1, -1, np.int64)  # newest -> oldest
            slots = widx % self._W
            # fancy indexing yields a fresh array — safe to mutate in place
            # (no second copy; at reference scale these are ~100MB tensors)
            values = self._acc[:E][:, slots]  # [E, Wv, M]
            counts = self._counts[:E][:, slots]  # [E, Wv]

            # window values by strategy.  AVG dominates the metric def
            # (35/36 Kafka metrics), so divide the WHOLE tensor in place and
            # restore the few non-AVG columns — a full-array op beats a
            # fancy gather+scatter over nearly all columns at 200k entities
            avg = self._strategies == 0
            nonavg = np.nonzero(~avg)[0]
            saved = values[:, :, nonavg].copy()
            with np.errstate(invalid="ignore", divide="ignore"):
                values /= np.maximum(counts[..., None], 1)
            values[:, :, nonavg] = saved

            if (counts >= self.min_samples).all():
                # healthy fast path — every (entity, window) cell fully
                # sampled, the steady-state norm: no extrapolation masks,
                # no neighbor machinery.  At 200k entities this skips
                # ~1/3 of the aggregation wall (the reference's
                # cluster-model-creation-timer path,
                # monitor/LoadMonitor.java:100,510)
                ext = np.full((E, widx.size), Extrapolation.NONE, np.int8)
                window_valid = np.ones((E, widx.size), bool)
                entity_valid = np.ones(E, bool)
            else:
                ext = np.full(
                    (E, widx.size), Extrapolation.NO_VALID_EXTRAPOLATION, np.int8
                )
                ext[counts >= 1] = Extrapolation.FORCED_INSUFFICIENT
                # AVG_ADJACENT: zero-count window whose neighbors (in
                # window-index space) both have >= min_samples
                cnt_full = self._counts[:E]  # ring layout
                left = np.clip(widx + 1, 0, None)  # newer neighbor
                right = widx - 1
                left_ok = np.zeros((E, widx.size), bool)
                right_ok = np.zeros((E, widx.size), bool)
                in_range = (left <= self._current_window)
                left_ok[:, in_range] = cnt_full[:, (left[in_range]) % self._W] >= self.min_samples
                in_range_r = right >= oldest
                right_ok[:, in_range_r] = cnt_full[:, (right[in_range_r]) % self._W] >= self.min_samples
                adj = (counts == 0) & left_ok & right_ok
                ext[adj] = Extrapolation.AVG_ADJACENT
                # fill adjacent-average values
                if adj.any():
                    e_i, w_i = np.nonzero(adj)
                    lv = self._acc[:E][e_i, (widx[w_i] + 1) % self._W]
                    lc = cnt_full[e_i, (widx[w_i] + 1) % self._W]
                    rv = self._acc[:E][e_i, (widx[w_i] - 1) % self._W]
                    rc = cnt_full[e_i, (widx[w_i] - 1) % self._W]
                    lval = lv.copy()
                    rval = rv.copy()
                    lval[:, avg] = lv[:, avg] / np.maximum(lc[:, None], 1)
                    rval[:, avg] = rv[:, avg] / np.maximum(rc[:, None], 1)
                    values[e_i, w_i] = 0.5 * (lval + rval)
                ext[counts >= self.half_min] = Extrapolation.AVG_AVAILABLE
                ext[counts >= self.min_samples] = Extrapolation.NONE

                window_valid = ext != Extrapolation.NO_VALID_EXTRAPOLATION
                extrapolated = window_valid & (ext != Extrapolation.NONE)
                too_many_ext = (
                    extrapolated.sum(1)
                    > options.max_allowed_extrapolations_per_entity
                )
                entity_valid = window_valid.all(axis=1) & ~too_many_ext

            # group validity: all entities of the group must be valid.
            # The hash pass over E entities only runs when group
            # granularity is requested — the default ENTITY path skips it
            entity_group_valid = entity_valid
            if options.granularity == "ENTITY_GROUP":
                keys = list(self._entity_rows)
                group_of = np.fromiter(
                    (hash(self._entity_group.get(k, k)) for k in keys),
                    np.int64,
                    count=len(keys),
                )
                _, inv = np.unique(group_of, return_inverse=True)
                bad_groups = np.bincount(inv, weights=~entity_valid) > 0
                entity_group_valid = entity_valid & ~bad_groups[inv]
                entity_valid = entity_group_valid

            ratio_by_window = window_valid.mean(axis=0) if E else np.zeros(widx.size)
            ratio_ok = ratio_by_window >= options.min_valid_entity_ratio
            valid_windows = widx[ratio_ok]
            if valid_windows.size < options.min_valid_windows:
                pass  # caller decides via completeness (reference throws NotEnoughValidWindowsException)

            completeness = MetricSampleCompleteness(
                generation=self._generation,
                valid_windows=valid_windows,
                valid_entity_ratio_by_window=ratio_by_window.astype(np.float32),
                valid_entity_ratio=float(entity_valid.mean()) if E else 0.0,
                valid_entity_group_ratio=float(entity_group_valid.mean()) if E else 0.0,
            )
            return AggregationResult(
                window_indices=widx,
                values=values,
                window_valid=window_valid,
                extrapolation=ext,
                entity_valid=entity_valid,
                completeness=completeness,
            )

    def history_snapshot(self) -> WindowedHistory:
        """Windowed-history snapshot for trend fitting (WindowedHistory).

        Covers every COMPLETED window still in the ring (the in-progress
        current window is excluded, like aggregate()), newest first.
        Values are strategy-reduced (AVG divided by count, MAX/LATEST as
        stored) but NOT extrapolated; `complete` marks cells that met
        min_samples on their own.  All arrays are copies — safe to hold
        across further sampling and window rolls.
        """
        with self._lock:
            if self._current_window is None:
                raise ValueError("no samples added yet")
            E = len(self._entity_rows)
            newest = self._current_window - 1
            oldest = max(self._oldest_window or 0, newest - self.num_windows + 1)
            if newest < oldest:
                raise ValueError("no completed windows yet")
            widx = np.arange(newest, oldest - 1, -1, np.int64)
            slots = widx % self._W
            values = self._acc[:E][:, slots].copy()  # [E, Wv, M]
            counts = self._counts[:E][:, slots].copy()  # [E, Wv]
            avg = self._strategies == 0
            nonavg = np.nonzero(~avg)[0]
            saved = values[:, :, nonavg].copy()
            with np.errstate(invalid="ignore", divide="ignore"):
                values /= np.maximum(counts[..., None], 1)
            values[:, :, nonavg] = saved
            return WindowedHistory(
                window_indices=widx,
                window_ms=self.window_ms,
                values=values,
                sample_counts=counts.astype(np.int32),
                complete=counts >= self.min_samples,
                entities=tuple(self._entity_rows),
                generation=self._generation,
            )

    def entities(self) -> list:
        return list(self._entity_rows)

    def entity_index(self) -> dict:
        return dict(self._entity_rows)

    def entity_key_rows(self) -> tuple:
        """(sorted int64 keys, matching rows) for vectorized entity lookup.

        Keys encode (entity.topic << 32) | entity.partition — the
        partition-entity layout the monitor's columnar model-generation
        path joins against with np.searchsorted instead of E dict probes.
        Non-partition entities are rejected loudly (a silent key collision
        would join wrong loads).  Cached until the entity set grows.
        """
        with self._lock:  # sample ingestion grows the dict concurrently
            cached = getattr(self, "_key_rows_cache", None)
            if cached is not None and cached[0] == len(self._entity_rows):
                return cached[1]

            def _key(e) -> int:
                # loud failure beats colliding join keys: a non-partition
                # entity or out-of-range id here would silently join wrong
                # loads via the old getattr-default fallback
                topic = getattr(e, "topic", None)
                part = getattr(e, "partition", None)
                if topic is None or part is None:
                    raise TypeError(
                        "entity_key_rows requires PartitionEntity-shaped "
                        f"entities (topic, partition); got {type(e).__name__}"
                    )
                topic, part = int(topic), int(part)
                if not (0 <= part < 2**32 and 0 <= topic < 2**31):
                    raise ValueError(
                        f"partition entity ids out of key range: "
                        f"topic={topic} partition={part}"
                    )
                return (topic << 32) | part

            keys = np.fromiter(
                (_key(e) for e in self._entity_rows),
                np.int64,
                count=len(self._entity_rows),
            )
            rows = np.fromiter(self._entity_rows.values(), np.int64, count=keys.size)
            order = np.argsort(keys)
            out = (keys[order], rows[order])
            self._key_rows_cache = (len(self._entity_rows), out)
            return out
