"""CPU utilization estimation.

Reference: model/ModelUtils.java:53-84 (follower CPU derived from leader
byte rates via static coefficients; leader CPU per core estimation) and
model/LinearRegressionModelParameters.java (optional trained linear
regression from broker samples).

The regression here is a tiny closed-form least-squares on host (numpy) —
training data volumes are trivial; no reason to involve the device.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# reference ModelUtils static coefficients (ModelUtils.java:30-36):
# CPU contribution weights of leader bytes-in / bytes-out / follower bytes-in
LEADER_BYTES_IN_CPU_WEIGHT = 0.7
LEADER_BYTES_OUT_CPU_WEIGHT = 0.15
FOLLOWER_BYTES_IN_CPU_WEIGHT = 0.15


#: (leader bytes-in, leader bytes-out, follower bytes-in) weight triple —
#: reference MonitorConfig {leader.network.inbound, leader.network.outbound,
#: follower.network.inbound}.weight.for.cpu.util
DEFAULT_CPU_WEIGHTS = (
    LEADER_BYTES_IN_CPU_WEIGHT,
    LEADER_BYTES_OUT_CPU_WEIGHT,
    FOLLOWER_BYTES_IN_CPU_WEIGHT,
)


def follower_cpu_util(
    leader_bytes_in: float,
    leader_bytes_out: float,
    leader_cpu: float,
    weights: tuple[float, float, float] = DEFAULT_CPU_WEIGHTS,
) -> float:
    """CPU a follower of this partition would use, from leader-side rates
    (reference ModelUtils.getFollowerCpuUtilFromLeaderLoad:53-67)."""
    w_in, w_out, w_follow = weights
    total = w_in * leader_bytes_in + w_out * leader_bytes_out
    if total <= 0:
        return 0.0
    return leader_cpu * (w_follow * leader_bytes_in) / total


def follower_cpu_util_array(
    leader_loads: np.ndarray,
    leader_cpu: np.ndarray,
    weights: tuple[float, float, float] = DEFAULT_CPU_WEIGHTS,
) -> np.ndarray:
    """Vectorized follower CPU for [N, 4] leader loads."""
    from cruise_control_tpu.common.resources import Resource

    w_in, w_out, w_follow = weights
    bin_ = leader_loads[:, Resource.NW_IN]
    bout = leader_loads[:, Resource.NW_OUT]
    total = w_in * bin_ + w_out * bout
    out = np.where(
        total > 0, leader_cpu * w_follow * bin_ / np.maximum(total, 1e-12), 0.0
    )
    return out.astype(np.float32)


@dataclasses.dataclass
class LinearRegressionModelParameters:
    """Broker CPU =~ w . [leader_bytes_in, leader_bytes_out, follower_bytes_in]
    (reference model/LinearRegressionModelParameters.java).

    Accumulates training samples; `train` solves least squares; once
    trained, `estimate` replaces the static-coefficient path.

    Bucketed readiness (reference MonitorConfig
    linear.regression.model.{cpu.util.bucket.size,
    required.samples.per.bucket, min.num.cpu.util.buckets}): samples are
    binned by CPU utilization percent, and training requires enough
    DISTINCT load levels — a model fit only on idle-broker samples would
    extrapolate garbage at peak.
    """

    min_samples_to_train: int = 100
    #: CPU-util bucket width in percent points
    cpu_util_bucket_size: int = 5
    #: samples a bucket needs before it counts as covered
    required_samples_per_bucket: int = 100
    #: covered buckets required before training may run
    min_num_cpu_util_buckets: int = 5

    def __post_init__(self):
        self._x: list[np.ndarray] = []
        self._y: list[float] = []
        self.coefficients: np.ndarray | None = None

    def add_sample(self, leader_bytes_in: float, leader_bytes_out: float,
                   follower_bytes_in: float, cpu_util: float):
        self._x.append(np.array([leader_bytes_in, leader_bytes_out, follower_bytes_in]))
        self._y.append(cpu_util)

    @property
    def num_samples(self) -> int:
        return len(self._y)

    @property
    def trained(self) -> bool:
        return self.coefficients is not None

    def bucket_coverage(self) -> dict[int, int]:
        """{bucket index: sample count}, bucketing CPU util (0..1) by
        cpu_util_bucket_size percent points."""
        counts: dict[int, int] = {}
        width = max(1, self.cpu_util_bucket_size)
        for y in self._y:
            b = int(min(max(y, 0.0), 1.0) * 100) // width
            counts[b] = counts.get(b, 0) + 1
        return counts

    def ready_to_train(self) -> bool:
        if len(self._y) < self.min_samples_to_train:
            return False
        covered = sum(
            1 for n in self.bucket_coverage().values()
            if n >= self.required_samples_per_bucket
        )
        return covered >= self.min_num_cpu_util_buckets

    def train(self, *, force: bool = False) -> bool:
        """force (the explicit /train path) skips the bucket-COVERAGE gate —
        an operator may fit on whatever load levels exist — but never the
        minimum-sample floor: a fit on a handful of points is noise."""
        if len(self._y) < self.min_samples_to_train:
            return False
        if not force and not self.ready_to_train():
            return False
        x = np.stack(self._x)
        y = np.asarray(self._y)
        coef, *_ = np.linalg.lstsq(x, y, rcond=None)
        self.coefficients = np.maximum(coef, 0.0)
        return True

    def estimate(self, leader_bytes_in: float, leader_bytes_out: float,
                 follower_bytes_in: float) -> float:
        if self.coefficients is None:
            raise ValueError("model not trained")
        return float(
            self.coefficients @ np.array([leader_bytes_in, leader_bytes_out, follower_bytes_in])
        )

    def follower_cpu_array(self, leader_loads: np.ndarray) -> np.ndarray:
        """Trained follower-CPU estimate for [N, 4] leader loads: a follower
        ingests the partition's bytes-in as replication traffic, so its CPU
        is the regression's follower-bytes-in coefficient applied to NW_IN
        (reference ModelUtils.java:84 switches to the trained estimator once
        LinearRegressionModelParameters has converged)."""
        from cruise_control_tpu.common.resources import Resource

        if self.coefficients is None:
            raise ValueError("model not trained")
        return (self.coefficients[2] * leader_loads[:, Resource.NW_IN]).astype(np.float32)

    def state(self) -> dict:
        return {
            "trained": self.trained,
            "numSamples": self.num_samples,
            "coefficients": None if self.coefficients is None else self.coefficients.tolist(),
        }
