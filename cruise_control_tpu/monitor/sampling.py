"""Metric sampling framework: sampler SPI, fetcher, raw-metric processing.

Reference: monitor/sampling/MetricSampler.java (plugin SPI),
MetricFetcherManager.java:145 (scheduled fetch loops),
CruiseControlMetricsProcessor.java (raw broker/topic/partition metrics ->
partition & broker samples, incl. CPU attribution),
holder/PartitionMetricSample.java + BrokerMetricSample.java.

The TPU rebuild keeps sampling host-side (it is network I/O) but makes the
sample payloads dense arrays keyed by the MetricDef so they pour straight
into the windowed aggregation tensors.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Protocol

import numpy as np

from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF, MetricDef


@dataclasses.dataclass(frozen=True)
class PartitionEntity:
    """Aggregation entity for one partition; group = topic (reference
    monitor/sampling/PartitionEntity.java)."""

    topic: int
    partition: int

    @property
    def group(self):
        return self.topic


@dataclasses.dataclass(frozen=True)
class BrokerEntity:
    """Reference monitor/sampling/BrokerEntity.java."""

    broker_id: int


@dataclasses.dataclass(frozen=True)
class MetricSample:
    """One entity's metrics at one time (reference
    cruise-control-core monitor/sampling/MetricSample.java)."""

    entity: object
    time_ms: int
    values: np.ndarray  # f32[M] indexed by MetricDef ids


@dataclasses.dataclass(frozen=True)
class SamplingResult:
    partition_samples: list[MetricSample]
    broker_samples: list[MetricSample]


class MetricSampler(Protocol):
    """Pluggable sampler SPI (reference monitor/sampling/MetricSampler.java).

    Implementations fetch metrics for the assigned partitions between two
    timestamps — from the metrics-reporter topic, a REST endpoint, files,
    or synthetic generators in tests.
    """

    def get_samples(
        self, assigned_partitions: list[PartitionEntity], start_ms: int, end_ms: int
    ) -> SamplingResult:
        ...


class SampleStore(Protocol):
    """Persists samples for warm restart (reference KafkaSampleStore.java:117)."""

    def store(self, result: SamplingResult) -> None:
        ...

    def load(self) -> list[SamplingResult]:
        ...

    def close(self) -> None:
        ...


class NoopSampleStore:
    """Reference monitor/sampling/NoopSampleStore.java."""

    def store(self, result: SamplingResult) -> None:
        pass

    def load(self) -> list[SamplingResult]:
        return []

    def close(self) -> None:
        pass


class InMemorySampleStore:
    """Bounded in-memory store, useful for tests and single-process runs."""

    def __init__(self, max_results: int = 10_000):
        self._results: list[SamplingResult] = []
        self._max = max_results
        self._lock = threading.Lock()

    def store(self, result: SamplingResult) -> None:
        with self._lock:
            self._results.append(result)
            if len(self._results) > self._max:
                self._results = self._results[-self._max:]

    def load(self) -> list[SamplingResult]:
        with self._lock:
            return list(self._results)

    def close(self) -> None:
        pass


class FileSampleStore:
    """npz-file-backed store — the warm-restart path when there is no Kafka
    sample topic (role of reference KafkaSampleStore, storage swapped for
    local files)."""

    def __init__(self, path: str):
        import os

        self.path = path
        os.makedirs(path, exist_ok=True)
        self._n = len(self._files())

    def _files(self):
        import glob
        import os

        return sorted(glob.glob(os.path.join(self.path, "samples_*.npz")))

    def store(self, result: SamplingResult) -> None:
        import os

        def pack(samples: list[MetricSample]):
            if not samples:
                return np.zeros((0, 3), np.int64), np.zeros((0, 0), np.float32)
            meta = np.array(
                [
                    [
                        getattr(s.entity, "topic", getattr(s.entity, "broker_id", -1)),
                        getattr(s.entity, "partition", -1),
                        s.time_ms,
                    ]
                    for s in samples
                ],
                np.int64,
            )
            vals = np.stack([s.values for s in samples])
            return meta, vals

        pm, pv = pack(result.partition_samples)
        bm, bv = pack(result.broker_samples)
        np.savez_compressed(
            os.path.join(self.path, f"samples_{self._n:08d}.npz"),
            part_meta=pm, part_values=pv, broker_meta=bm, broker_values=bv,
        )
        self._n += 1

    def load(self) -> list[SamplingResult]:
        out = []
        for f in self._files():
            z = np.load(f)
            ps = [
                MetricSample(PartitionEntity(int(t), int(p)), int(ts), v)
                for (t, p, ts), v in zip(z["part_meta"], z["part_values"])
            ]
            bs = [
                MetricSample(BrokerEntity(int(b)), int(ts), v)
                for (b, _, ts), v in zip(z["broker_meta"], z["broker_values"])
            ]
            out.append(SamplingResult(ps, bs))
        return out

    def close(self) -> None:
        pass


class MetricSamplerPartitionAssignor:
    """Splits the partition universe into per-fetcher disjoint sets
    (reference monitor/sampling/MetricSamplerPartitionAssignor.java:1 —
    the default assignor distributes each topic's partitions so fetcher
    loads stay balanced while a topic's partitions stay together as far as
    the balance allows).

    A rotating round-robin walks topics in order and deals their partitions
    across the fetcher sets, carrying the cursor between topics: every set
    ends within one partition of even, and no topic can serialize a round
    on one fetcher.
    """

    def assign(
        self, partitions: list[PartitionEntity], num_fetchers: int
    ) -> list[list[PartitionEntity]]:
        if num_fetchers <= 1:
            return [list(partitions)]
        by_topic: dict[object, list[PartitionEntity]] = {}
        for p in partitions:
            by_topic.setdefault(p.topic, []).append(p)
        sets: list[list[PartitionEntity]] = [[] for _ in range(num_fetchers)]
        k = 0
        for _topic, plist in sorted(by_topic.items(), key=lambda kv: str(kv[0])):
            for p in plist:
                sets[k].append(p)
                k = (k + 1) % num_fetchers
        return sets


class MetricFetcherManager:
    """Schedules sampling rounds and feeds aggregators + sample store
    (reference monitor/sampling/MetricFetcherManager.java:35-56,145,
    SamplingFetcher.java:32).  `num_fetchers > 1` splits each round's
    partition universe across a thread pool via the assignor — the
    reference's fetcher-pool parallelism (num.metric.fetchers) — and merges
    the per-fetcher results; each fetch is timed and failure-counted into
    the sensor registry, with monitor self-observability gauges
    (monitored-partitions-percentage, num-partitions-with-flaw: reference
    docs/wiki User Guide/Sensors.md:9-17).
    """

    def __init__(
        self,
        sampler: MetricSampler,
        partition_aggregator,
        broker_aggregator,
        sample_store: SampleStore | None = None,
        *,
        sampling_interval_ms: int = 120_000,
        num_fetchers: int = 1,
        assignor: MetricSamplerPartitionAssignor | None = None,
        sensors=None,
    ):
        from cruise_control_tpu.common.sensors import SensorRegistry

        self.sampler = sampler
        self.partition_aggregator = partition_aggregator
        self.broker_aggregator = broker_aggregator
        self.sample_store = sample_store or NoopSampleStore()
        self.sampling_interval_ms = sampling_interval_ms
        self.num_fetchers = max(1, num_fetchers)
        self.assignor = assignor or MetricSamplerPartitionAssignor()
        # per-instance default, NOT the module-global registry: the health
        # gauges below close over self, so a global default would let a
        # second manager silently take over the gauge names and would pin
        # every stopped manager alive via the registry (the facade scopes
        # its registry per instance for the same reason)
        self.sensors = sensors if sensors is not None else SensorRegistry()
        self._pool = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.total_samples = 0
        self.failed_fetches = 0
        #: last round's monitor-health numbers (also exported as gauges)
        self.last_monitored_percentage = 100.0
        self.last_partitions_with_flaw = 0
        self.sensors.gauge(
            "monitor.monitored-partitions-percentage",
            lambda: self.last_monitored_percentage,
        )
        self.sensors.gauge(
            "monitor.num-partitions-with-flaw",
            lambda: self.last_partitions_with_flaw,
        )

    def _fetch_one(
        self, partitions: list[PartitionEntity], start_ms: int, end_ms: int
    ) -> SamplingResult:
        """One fetcher's sampling call, timed + failure-counted
        (reference MetricFetcherManager fetch timer/failure sensors :53-56)."""
        try:
            with self.sensors.timer("monitor.metric-fetch").time():
                return self.sampler.get_samples(partitions, start_ms, end_ms)
        except Exception:
            self.failed_fetches += 1
            self.sensors.counter("monitor.metric-fetch-failures").inc()
            raise

    def fetch_once(self, partitions: list[PartitionEntity], start_ms: int, end_ms: int) -> int:
        """One sampling round (reference fetchPartitionMetricSamples:145);
        with num_fetchers > 1 the round fans out over disjoint partition
        sets and merges (reference MetricSamplerPartitionAssignor split)."""
        if self.num_fetchers > 1 and len(partitions) > 1:
            from concurrent.futures import ThreadPoolExecutor

            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.num_fetchers, thread_name_prefix="metric-fetcher"
                )
            sets = [
                s for s in self.assignor.assign(partitions, self.num_fetchers) if s
            ]
            futures = [
                self._pool.submit(self._fetch_one, s, start_ms, end_ms) for s in sets
            ]
            parts: list[MetricSample] = []
            brokers: list[MetricSample] = []
            errors = []
            for f in futures:
                try:
                    r = f.result()
                    parts.extend(r.partition_samples)
                    brokers.extend(r.broker_samples)
                except Exception as e:  # noqa: BLE001 — surface after merging
                    errors.append(e)
            if errors and not parts and not brokers:
                raise errors[0]
            result = SamplingResult(parts, brokers)
        else:
            result = self._fetch_one(partitions, start_ms, end_ms)
        self._update_health(partitions, result)
        n = self._absorb(result)
        self.sample_store.store(result)
        return n

    def _update_health(
        self, assigned: list[PartitionEntity], result: SamplingResult
    ) -> None:
        """Monitor self-observability (reference Sensors.md
        monitored-partitions-percentage / num-partitions-with-flaw)."""
        if not assigned:
            return
        sampled = {
            (s.entity.topic, s.entity.partition) for s in result.partition_samples
        }
        n_ok = sum(1 for p in assigned if (p.topic, p.partition) in sampled)
        self.last_monitored_percentage = 100.0 * n_ok / len(assigned)
        flawed = sum(
            1 for s in result.partition_samples if not np.all(np.isfinite(s.values))
        )
        self.last_partitions_with_flaw = flawed + (len(assigned) - n_ok)

    def _absorb(self, result: SamplingResult) -> int:
        n = 0
        for s in result.partition_samples:
            if self.partition_aggregator.add_sample(
                s.entity, s.time_ms, s.values, group=getattr(s.entity, "group", None)
            ):
                n += 1
        if self.broker_aggregator is not None:
            for s in result.broker_samples:
                if self.broker_aggregator.add_sample(s.entity, s.time_ms, s.values):
                    n += 1
        self.total_samples += n
        return n

    def load_samples(self) -> int:
        """Warm restart from the sample store (reference SampleLoadingTask)."""
        n = 0
        for result in self.sample_store.load():
            n += self._absorb(result)
        return n

    def start(self, partitions_fn, *, interval_s: float | None = None):
        interval = interval_s or self.sampling_interval_ms / 1000.0

        def loop():
            while not self._stop.wait(interval):
                now = int(time.time() * 1000)
                try:
                    self.fetch_once(partitions_fn(), now - self.sampling_interval_ms, now)
                except Exception:  # noqa: BLE001 — keep the loop alive like the reference fetchers
                    pass

        self._thread = threading.Thread(target=loop, daemon=True, name="metric-fetcher")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
