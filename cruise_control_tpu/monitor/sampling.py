"""Metric sampling framework: sampler SPI, fetcher, raw-metric processing.

Reference: monitor/sampling/MetricSampler.java (plugin SPI),
MetricFetcherManager.java:145 (scheduled fetch loops),
CruiseControlMetricsProcessor.java (raw broker/topic/partition metrics ->
partition & broker samples, incl. CPU attribution),
holder/PartitionMetricSample.java + BrokerMetricSample.java.

The TPU rebuild keeps sampling host-side (it is network I/O) but makes the
sample payloads dense arrays keyed by the MetricDef so they pour straight
into the windowed aggregation tensors.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Protocol

import numpy as np

from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF, MetricDef


@dataclasses.dataclass(frozen=True)
class PartitionEntity:
    """Aggregation entity for one partition; group = topic (reference
    monitor/sampling/PartitionEntity.java)."""

    topic: int
    partition: int

    @property
    def group(self):
        return self.topic


@dataclasses.dataclass(frozen=True)
class BrokerEntity:
    """Reference monitor/sampling/BrokerEntity.java."""

    broker_id: int


@dataclasses.dataclass(frozen=True)
class MetricSample:
    """One entity's metrics at one time (reference
    cruise-control-core monitor/sampling/MetricSample.java)."""

    entity: object
    time_ms: int
    values: np.ndarray  # f32[M] indexed by MetricDef ids


@dataclasses.dataclass(frozen=True)
class SamplingResult:
    partition_samples: list[MetricSample]
    broker_samples: list[MetricSample]


class MetricSampler(Protocol):
    """Pluggable sampler SPI (reference monitor/sampling/MetricSampler.java).

    Implementations fetch metrics for the assigned partitions between two
    timestamps — from the metrics-reporter topic, a REST endpoint, files,
    or synthetic generators in tests.
    """

    def get_samples(
        self, assigned_partitions: list[PartitionEntity], start_ms: int, end_ms: int
    ) -> SamplingResult:
        ...


class SampleStore(Protocol):
    """Persists samples for warm restart (reference KafkaSampleStore.java:117)."""

    def store(self, result: SamplingResult) -> None:
        ...

    def load(self) -> list[SamplingResult]:
        ...

    def close(self) -> None:
        ...


class NoopSampleStore:
    """Reference monitor/sampling/NoopSampleStore.java."""

    def store(self, result: SamplingResult) -> None:
        pass

    def load(self) -> list[SamplingResult]:
        return []

    def close(self) -> None:
        pass


class InMemorySampleStore:
    """Bounded in-memory store, useful for tests and single-process runs."""

    def __init__(self, max_results: int = 10_000):
        self._results: list[SamplingResult] = []
        self._max = max_results
        self._lock = threading.Lock()

    def store(self, result: SamplingResult) -> None:
        with self._lock:
            self._results.append(result)
            if len(self._results) > self._max:
                self._results = self._results[-self._max:]

    def load(self) -> list[SamplingResult]:
        with self._lock:
            return list(self._results)

    def close(self) -> None:
        pass


class FileSampleStore:
    """npz-file-backed store — the warm-restart path when there is no Kafka
    sample topic (role of reference KafkaSampleStore, storage swapped for
    local files)."""

    def __init__(self, path: str):
        import os

        self.path = path
        os.makedirs(path, exist_ok=True)
        self._n = len(self._files())

    def _files(self):
        import glob
        import os

        return sorted(glob.glob(os.path.join(self.path, "samples_*.npz")))

    def store(self, result: SamplingResult) -> None:
        import os

        def pack(samples: list[MetricSample]):
            if not samples:
                return np.zeros((0, 3), np.int64), np.zeros((0, 0), np.float32)
            meta = np.array(
                [
                    [
                        getattr(s.entity, "topic", getattr(s.entity, "broker_id", -1)),
                        getattr(s.entity, "partition", -1),
                        s.time_ms,
                    ]
                    for s in samples
                ],
                np.int64,
            )
            vals = np.stack([s.values for s in samples])
            return meta, vals

        pm, pv = pack(result.partition_samples)
        bm, bv = pack(result.broker_samples)
        np.savez_compressed(
            os.path.join(self.path, f"samples_{self._n:08d}.npz"),
            part_meta=pm, part_values=pv, broker_meta=bm, broker_values=bv,
        )
        self._n += 1

    def load(self) -> list[SamplingResult]:
        out = []
        for f in self._files():
            z = np.load(f)
            ps = [
                MetricSample(PartitionEntity(int(t), int(p)), int(ts), v)
                for (t, p, ts), v in zip(z["part_meta"], z["part_values"])
            ]
            bs = [
                MetricSample(BrokerEntity(int(b)), int(ts), v)
                for (b, _, ts), v in zip(z["broker_meta"], z["broker_values"])
            ]
            out.append(SamplingResult(ps, bs))
        return out

    def close(self) -> None:
        pass


class MetricFetcherManager:
    """Schedules sampling rounds and feeds aggregators + sample store
    (reference monitor/sampling/MetricFetcherManager.java:145,
    SamplingFetcher.java:32).  Synchronous `fetch_once` plus an optional
    background thread; partition assignment is a single list here because
    the Python sampler SPI takes the whole batch (the reference splits
    across fetcher threads — our samplers vectorize instead).
    """

    def __init__(
        self,
        sampler: MetricSampler,
        partition_aggregator,
        broker_aggregator,
        sample_store: SampleStore | None = None,
        *,
        sampling_interval_ms: int = 120_000,
    ):
        self.sampler = sampler
        self.partition_aggregator = partition_aggregator
        self.broker_aggregator = broker_aggregator
        self.sample_store = sample_store or NoopSampleStore()
        self.sampling_interval_ms = sampling_interval_ms
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.total_samples = 0
        self.failed_fetches = 0

    def fetch_once(self, partitions: list[PartitionEntity], start_ms: int, end_ms: int) -> int:
        """One sampling round (reference fetchPartitionMetricSamples:145)."""
        try:
            result = self.sampler.get_samples(partitions, start_ms, end_ms)
        except Exception:
            self.failed_fetches += 1
            raise
        n = self._absorb(result)
        self.sample_store.store(result)
        return n

    def _absorb(self, result: SamplingResult) -> int:
        n = 0
        for s in result.partition_samples:
            if self.partition_aggregator.add_sample(
                s.entity, s.time_ms, s.values, group=getattr(s.entity, "group", None)
            ):
                n += 1
        if self.broker_aggregator is not None:
            for s in result.broker_samples:
                if self.broker_aggregator.add_sample(s.entity, s.time_ms, s.values):
                    n += 1
        self.total_samples += n
        return n

    def load_samples(self) -> int:
        """Warm restart from the sample store (reference SampleLoadingTask)."""
        n = 0
        for result in self.sample_store.load():
            n += self._absorb(result)
        return n

    def start(self, partitions_fn, *, interval_s: float | None = None):
        interval = interval_s or self.sampling_interval_ms / 1000.0

        def loop():
            while not self._stop.wait(interval):
                now = int(time.time() * 1000)
                try:
                    self.fetch_once(partitions_fn(), now - self.sampling_interval_ms, now)
                except Exception:  # noqa: BLE001 — keep the loop alive like the reference fetchers
                    pass

        self._thread = threading.Thread(target=loop, daemon=True, name="metric-fetcher")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
