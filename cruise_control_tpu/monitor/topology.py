"""Cluster topology description + metadata provider SPI.

Plays the role of the reference's Kafka `Cluster` metadata +
common/MetadataClient.java:1 (refreshMetadata against brokers).  The
monitor consumes topology through this SPI so the same LoadMonitor serves
a real Kafka-backed provider, the simulated cluster backend
(executor tests), and synthetic fixtures.
"""

from __future__ import annotations

import dataclasses
from typing import Protocol

import numpy as np


@dataclasses.dataclass(frozen=True)
class BrokerNode:
    broker_id: int
    rack: str
    host: str
    alive: bool = True
    logdirs: tuple[str, ...] = ()
    offline_logdirs: tuple[str, ...] = ()
    is_new: bool = False


@dataclasses.dataclass(frozen=True)
class PartitionInfo:
    topic: str
    partition: int
    leader: int  # broker id, -1 if none
    replicas: tuple[int, ...]  # broker ids, preferred order
    replica_logdirs: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class ClusterTopology:
    brokers: tuple[BrokerNode, ...]
    partitions: tuple[PartitionInfo, ...]
    generation: int = 0

    def broker_ids(self) -> list[int]:
        return [b.broker_id for b in self.brokers]

    def alive_broker_ids(self) -> set[int]:
        return {b.broker_id for b in self.brokers if b.alive}

    def topics(self) -> list[str]:
        seen: dict[str, None] = {}
        for p in self.partitions:
            seen.setdefault(p.topic, None)
        return list(seen)

    @property
    def num_replicas(self) -> int:
        return sum(len(p.replicas) for p in self.partitions)

    def columns(self) -> "TopologyColumns":
        """Columnar view of the partition list (cached per instance).

        ONE Python pass over the PartitionInfo objects; everything
        downstream of this (model generation, builder) is array ops —
        the reference meters cluster-model creation as a first-class
        sensor (monitor/LoadMonitor.java:100,510) and this is what keeps
        that path O(P) numpy instead of O(replicas) Python."""
        cached = getattr(self, "_columns_cache", None)
        if cached is not None:
            return cached
        topic_ids: dict[str, int] = {}
        P = len(self.partitions)
        part_topic = np.empty(P, np.int32)
        part_num = np.empty(P, np.int32)
        part_leader_pos = np.empty(P, np.int32)
        counts = np.empty(P, np.int32)
        flat: list[tuple[int, ...]] = [()] * P
        for i, p in enumerate(self.partitions):
            tid = topic_ids.setdefault(p.topic, len(topic_ids))
            part_topic[i] = tid
            part_num[i] = p.partition
            counts[i] = len(p.replicas)
            flat[i] = p.replicas
            # leader position within the replica list (0 when leaderless)
            part_leader_pos[i] = (
                p.replicas.index(p.leader) if p.leader in p.replicas else 0
            )
        replica_broker = np.fromiter(
            (b for r in flat for b in r), np.int32, count=int(counts.sum())
        )
        offsets = np.zeros(P + 1, np.int64)
        np.cumsum(counts, out=offsets[1:])
        cols = TopologyColumns(
            topic_names=tuple(topic_ids),
            part_topic=part_topic,
            part_num=part_num,
            part_leader_pos=part_leader_pos,
            replica_counts=counts,
            replica_offsets=offsets,
            replica_broker=replica_broker,
        )
        object.__setattr__(self, "_columns_cache", cols)
        return cols


@dataclasses.dataclass(frozen=True)
class TopologyColumns:
    """Array-encoded ClusterTopology.partitions (see ClusterTopology.columns).

    Topic ids are FIRST-SEEN order — the same assignment rule the samplers
    use for PartitionEntity, so entity keys line up without a rename pass.
    """

    topic_names: tuple[str, ...]
    part_topic: np.ndarray  # int32 [P] first-seen topic id
    part_num: np.ndarray  # int32 [P]
    part_leader_pos: np.ndarray  # int32 [P] leader index into the replica list
    replica_counts: np.ndarray  # int32 [P]
    replica_offsets: np.ndarray  # int64 [P+1] segment starts into replica_broker
    replica_broker: np.ndarray  # int32 [sum(counts)] flattened replica lists


class MetadataProvider(Protocol):
    """Reference common/MetadataClient.java role."""

    def topology(self) -> ClusterTopology:
        ...

    def refresh(self) -> ClusterTopology:
        ...


class StaticMetadataProvider:
    """Fixed topology (tests, simulations); mutate via set_topology."""

    def __init__(self, topology: ClusterTopology):
        self._topology = topology

    def topology(self) -> ClusterTopology:
        return self._topology

    def refresh(self) -> ClusterTopology:
        return self._topology

    def set_topology(self, topology: ClusterTopology):
        self._topology = dataclasses.replace(
            topology, generation=self._topology.generation + 1
        )
