"""Cluster topology description + metadata provider SPI.

Plays the role of the reference's Kafka `Cluster` metadata +
common/MetadataClient.java:1 (refreshMetadata against brokers).  The
monitor consumes topology through this SPI so the same LoadMonitor serves
a real Kafka-backed provider, the simulated cluster backend
(executor tests), and synthetic fixtures.
"""

from __future__ import annotations

import dataclasses
from typing import Protocol


@dataclasses.dataclass(frozen=True)
class BrokerNode:
    broker_id: int
    rack: str
    host: str
    alive: bool = True
    logdirs: tuple[str, ...] = ()
    offline_logdirs: tuple[str, ...] = ()
    is_new: bool = False


@dataclasses.dataclass(frozen=True)
class PartitionInfo:
    topic: str
    partition: int
    leader: int  # broker id, -1 if none
    replicas: tuple[int, ...]  # broker ids, preferred order
    replica_logdirs: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class ClusterTopology:
    brokers: tuple[BrokerNode, ...]
    partitions: tuple[PartitionInfo, ...]
    generation: int = 0

    def broker_ids(self) -> list[int]:
        return [b.broker_id for b in self.brokers]

    def alive_broker_ids(self) -> set[int]:
        return {b.broker_id for b in self.brokers if b.alive}

    def topics(self) -> list[str]:
        seen: dict[str, None] = {}
        for p in self.partitions:
            seen.setdefault(p.topic, None)
        return list(seen)

    @property
    def num_replicas(self) -> int:
        return sum(len(p.replicas) for p in self.partitions)


class MetadataProvider(Protocol):
    """Reference common/MetadataClient.java role."""

    def topology(self) -> ClusterTopology:
        ...

    def refresh(self) -> ClusterTopology:
        ...


class StaticMetadataProvider:
    """Fixed topology (tests, simulations); mutate via set_topology."""

    def __init__(self, topology: ClusterTopology):
        self._topology = topology

    def topology(self) -> ClusterTopology:
        return self._topology

    def refresh(self) -> ClusterTopology:
        return self._topology

    def set_topology(self, topology: ClusterTopology):
        self._topology = dataclasses.replace(
            topology, generation=self._topology.generation + 1
        )
