"""Monitor layer: sampling, windowed aggregation, cluster-model generation.

Reference: cruise-control/.../monitor/ (LoadMonitor.java, sampling/,
metricdefinition/) + cruise-control-core aggregator.
"""

from cruise_control_tpu.monitor.aggregator import (
    AggregationOptions,
    AggregationResult,
    Extrapolation,
    MetricSampleCompleteness,
    WindowedMetricSampleAggregator,
)
from cruise_control_tpu.monitor.capacity import (
    BrokerCapacityInfo,
    FileCapacityResolver,
    FixedCapacityResolver,
)
from cruise_control_tpu.monitor.completeness import (
    DEFAULT_REQUIREMENTS,
    ModelCompletenessRequirements,
)
from cruise_control_tpu.monitor.load_monitor import (
    LoadMonitor,
    ModelGeneration,
    MonitorState,
    NotEnoughValidWindowsError,
)
from cruise_control_tpu.monitor.metricdef import (
    KAFKA_METRIC_DEF,
    MetricDef,
    MetricScope,
    ValueComputingStrategy,
)
from cruise_control_tpu.monitor.sampling import (
    BrokerEntity,
    FileSampleStore,
    InMemorySampleStore,
    MetricFetcherManager,
    MetricSample,
    MetricSampler,
    NoopSampleStore,
    PartitionEntity,
    SamplingResult,
)
from cruise_control_tpu.monitor.topology import (
    BrokerNode,
    ClusterTopology,
    MetadataProvider,
    PartitionInfo,
    StaticMetadataProvider,
)
