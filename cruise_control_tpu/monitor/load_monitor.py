"""LoadMonitor — sampling/aggregation orchestration + ClusterState generation.

Reference: monitor/LoadMonitor.java:81 — clusterModel():485-568 (metadata
refresh -> partition aggregation -> rack/broker creation with capacity
resolver -> per-partition load population -> bad-broker marking),
acquireForModelGeneration():390 (semaphore), meetCompletenessRequirements():616,
and monitor/task/LoadMonitorTaskRunner.java:33 (state machine
NOT_STARTED/RUNNING/SAMPLING/PAUSED/BOOTSTRAPPING/TRAINING/LOADING).

The generation step is the monitor's whole purpose: it turns the windowed
aggregation tensors + live topology into the array-encoded ClusterState
the TPU optimizer consumes.  Everything here is host-side numpy — the
device boundary starts at the optimizer.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
import time

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.models.builder import BrokerSpec
from cruise_control_tpu.models.state import ClusterState
from cruise_control_tpu.monitor.aggregator import (
    AggregationOptions,
    WindowedMetricSampleAggregator,
)
from cruise_control_tpu.monitor.capacity import BrokerCapacityConfigResolver
from cruise_control_tpu.monitor.completeness import (
    DEFAULT_REQUIREMENTS,
    ModelCompletenessRequirements,
)
from cruise_control_tpu.monitor.cpu_model import follower_cpu_util_array
from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF, MetricDef
from cruise_control_tpu.monitor.topology import ClusterTopology, MetadataProvider


class MonitorState(enum.Enum):
    """Reference LoadMonitorTaskRunner.LoadMonitorTaskRunnerState."""

    NOT_STARTED = "NOT_STARTED"
    RUNNING = "RUNNING"
    SAMPLING = "SAMPLING"
    PAUSED = "PAUSED"
    BOOTSTRAPPING = "BOOTSTRAPPING"
    TRAINING = "TRAINING"
    LOADING = "LOADING"


class NotEnoughValidWindowsError(Exception):
    """Reference NotEnoughValidWindowsException."""


class BrokerCapacityEstimationError(Exception):
    """A request forbade capacity estimation but a broker's capacity could
    only be estimated (reference BrokerCapacityResolutionException +
    sanityCheckCapacityEstimation)."""


@dataclasses.dataclass(frozen=True)
class ModelGeneration:
    """(metadata generation, load/sample generation) pair
    (reference monitor/ModelGeneration.java)."""

    metadata_generation: int
    load_generation: int


class LoadMonitor:
    """Builds ClusterStates from aggregated samples + topology."""

    def __init__(
        self,
        metadata: MetadataProvider,
        capacity_resolver: BrokerCapacityConfigResolver,
        partition_aggregator: WindowedMetricSampleAggregator,
        *,
        metric_def: MetricDef = KAFKA_METRIC_DEF,
        max_concurrent_model_generations: int = 1,
        replica_capacity: int | None = None,
        regression=None,
        topic_filter=None,
        max_allowed_extrapolations: int = 5,
        cpu_weights: tuple[float, float, float] | None = None,
        bucket_policy=None,
    ):
        from cruise_control_tpu.monitor.cpu_model import DEFAULT_CPU_WEIGHTS

        #: reference MonitorConfig max.allowed.extrapolations.per.partition —
        #: partitions whose windows extrapolate more than this are invalid
        self.max_allowed_extrapolations = max_allowed_extrapolations
        #: static follower-CPU coefficients (reference MonitorConfig
        #: {leader.network.inbound,leader.network.outbound,
        #: follower.network.inbound}.weight.for.cpu.util)
        self.cpu_weights = cpu_weights or DEFAULT_CPU_WEIGHTS
        self.metadata = metadata
        self.capacity_resolver = capacity_resolver
        self.partition_aggregator = partition_aggregator
        self.metric_def = metric_def
        #: optional str -> bool predicate; topics failing it are invisible
        #: to the cluster model (the service's OWN metrics/sample-store
        #: topics must not be modeled as workload — the reference processor
        #: skips its metrics topic the same way)
        self.topic_filter = topic_filter
        #: optional LinearRegressionModelParameters — once trained (via the
        #: task runner's /train flow) it replaces the static-coefficient
        #: follower-CPU estimate (reference ModelUtils.java:84)
        self.regression = regression
        #: optional models.state.ShapeBucketPolicy — built models are padded
        #: to bucketed shapes so the analyzer's compiled engines survive
        #: topology churn (config tpu.shape.bucket.*; None = exact shapes)
        self.bucket_policy = bucket_policy
        self._state = MonitorState.NOT_STARTED
        # reference acquireForModelGeneration():390 — semaphore bounding
        # concurrent model generations
        self._model_semaphore = threading.Semaphore(max_concurrent_model_generations)
        self._replica_capacity = replica_capacity
        self._generation_lock = threading.Lock()
        self._load_generation = 0
        self._paused_reason: str | None = None
        # metric column ids resolved once
        self._cpu_id = metric_def.metric_id("CPU_USAGE")
        self._disk_id = metric_def.metric_id("DISK_USAGE")
        self._nwin_id = metric_def.metric_id("LEADER_BYTES_IN")
        self._nwout_id = metric_def.metric_id("LEADER_BYTES_OUT")
        #: id<->name catalog of the most recent cluster_model() build
        self.last_catalog = None

    # ------------------------------------------------------------------

    @property
    def state(self) -> MonitorState:
        return self._state

    def start(self):
        self._state = MonitorState.RUNNING

    def pause(self, reason: str = "user request"):
        """Reference LoadMonitor.pauseMetricSampling."""
        self._state = MonitorState.PAUSED
        self._paused_reason = reason

    def resume(self):
        self._state = MonitorState.RUNNING
        self._paused_reason = None

    def acquire_for_model_generation(self, timeout_s: float = 600.0):
        """Context manager bounding concurrent model generations
        (reference acquireForModelGeneration:390)."""
        monitor = self

        class _Ctx:
            def __enter__(self):
                if not monitor._model_semaphore.acquire(timeout=timeout_s):
                    raise TimeoutError("could not acquire model-generation semaphore")
                return monitor

            def __exit__(self, *exc):
                monitor._model_semaphore.release()
                return False

        return _Ctx()

    # ------------------------------------------------------------------

    def meets_completeness_requirements(
        self, requirements: ModelCompletenessRequirements
    ) -> bool:
        """Reference meetCompletenessRequirements():616."""
        try:
            agg = self.partition_aggregator.aggregate(
                AggregationOptions(
                    min_valid_entity_ratio=requirements.min_monitored_partitions_percentage,
                    max_allowed_extrapolations_per_entity=self.max_allowed_extrapolations,
                )
            )
        except ValueError:
            return False
        enough_windows = (
            agg.completeness.valid_windows.size >= requirements.min_required_num_windows
        )
        enough_partitions = (
            agg.completeness.valid_entity_ratio
            >= requirements.min_monitored_partitions_percentage
        )
        return enough_windows and enough_partitions

    def cluster_model(
        self,
        requirements: ModelCompletenessRequirements = DEFAULT_REQUIREMENTS,
        *,
        allow_capacity_estimation: bool = True,
    ) -> ClusterState:
        """Generate the array-encoded cluster model
        (reference LoadMonitor.clusterModel():485-568; timed like its
        cluster-model-creation-timer sensor, LoadMonitor.java:100,510).

        Traced as the `monitor.cluster_model` span of whatever operation
        requested the model (the flight recorder's first pipeline stage) —
        the served (bucketed) shape and generation ride as attributes so a
        trace shows which compiled-engine bucket this build landed in."""
        from cruise_control_tpu.common.sensors import REGISTRY
        from cruise_control_tpu.common.trace import TRACER

        sensors = getattr(self, "sensors", None) or REGISTRY
        tracer = getattr(self, "tracer", None) or TRACER
        with sensors.timer("monitor.cluster-model-creation-timer").time():
            with tracer.span("monitor.cluster_model", component="monitor") as sp:
                state = self._cluster_model_impl(
                    requirements, allow_capacity_estimation=allow_capacity_estimation
                )
                s = state.shape
                sp.set(
                    brokers=s.B, partitions=s.P, replicas=s.R,
                    topics=s.num_topics, load_generation=self._load_generation,
                )
                return state

    def _cluster_model_impl(
        self,
        requirements: ModelCompletenessRequirements,
        *,
        allow_capacity_estimation: bool = True,
    ) -> ClusterState:
        topology = self.metadata.refresh()
        if self.topic_filter is not None:
            import dataclasses as _dc

            topology = _dc.replace(
                topology,
                partitions=tuple(
                    p for p in topology.partitions if self.topic_filter(p.topic)
                ),
            )
        agg = self.partition_aggregator.aggregate(
            AggregationOptions(
                min_valid_entity_ratio=requirements.min_monitored_partitions_percentage,
                max_allowed_extrapolations_per_entity=self.max_allowed_extrapolations,
            )
        )
        if agg.completeness.valid_windows.size < requirements.min_required_num_windows:
            raise NotEnoughValidWindowsError(
                f"{agg.completeness.valid_windows.size} valid windows < "
                f"required {requirements.min_required_num_windows}"
            )
        if (
            agg.completeness.valid_entity_ratio
            < requirements.min_monitored_partitions_percentage
        ):
            raise NotEnoughValidWindowsError(
                f"valid partition ratio {agg.completeness.valid_entity_ratio:.3f} < "
                f"required {requirements.min_monitored_partitions_percentage:.3f}"
            )
        state = self._build_state(
            topology, agg, allow_capacity_estimation=allow_capacity_estimation
        )
        with self._generation_lock:
            self._load_generation = agg.completeness.generation
        return state

    def model_generation(self) -> ModelGeneration:
        return ModelGeneration(
            metadata_generation=self.metadata.topology().generation,
            load_generation=self._load_generation,
        )

    # ------------------------------------------------------------------

    def _window_reduced_loads(self, agg) -> dict:
        """Reduce [E, W, M] window values to per-entity [4] loads.

        AVG-strategy resources average over valid windows; DISK (LATEST)
        takes the newest valid window (reference model/Load.expectedUtilizationFor,
        model/Load.java:84-118 — AVG vs LATEST per KafkaMetricDef strategy).
        The reduction itself is monitor/delta.py's `reduce_windowed_loads`
        — ONE implementation for the model build and the streaming
        controller's delta path, so the two cannot drift.
        """
        from cruise_control_tpu.monitor.delta import reduce_windowed_loads

        # slice the 4 consumed metric columns FIRST: the reduction then
        # runs on [E, W, 4] instead of the full [E, W, M] tensor
        cols = [self._cpu_id, self._nwin_id, self._nwout_id, self._disk_id]
        return reduce_windowed_loads(agg.values[:, :, cols], agg.window_valid)

    def _build_state(
        self,
        topology: ClusterTopology,
        agg,
        *,
        allow_capacity_estimation: bool = True,
    ) -> ClusterState:
        loads = self._window_reduced_loads(agg)
        broker_specs = []
        for b in topology.brokers:
            info = self.capacity_resolver.capacity_for_broker(b.rack, b.host, b.broker_id)
            if not allow_capacity_estimation and info.estimation_info:
                # reference sanityCheckCapacityEstimation: requests that
                # forbid estimation fail loudly when any broker capacity is
                # an estimate rather than a resolved value
                raise BrokerCapacityEstimationError(
                    f"broker {b.broker_id} capacity is estimated "
                    f"({info.estimation_info}) and the request disallows "
                    "capacity estimation"
                )
            disk_caps = None
            bad_disks = None
            if info.disk_capacities:
                logdirs = b.logdirs or tuple(info.disk_capacities)
                disk_caps = [info.disk_capacities.get(d, 0.0) for d in logdirs]
                bad = set(b.offline_logdirs)
                bad_disks = [i for i, d in enumerate(logdirs) if d in bad] or None
            broker_specs.append(
                BrokerSpec(
                    b.broker_id,
                    rack=b.rack,
                    host=b.host,
                    capacity=np.asarray(info.capacity, np.float32),
                    disk_capacities=disk_caps,
                    alive=b.alive,
                    new_broker=b.is_new,
                    bad_disks=bad_disks,
                )
            )

        # columnar join: topology partitions -> aggregator entity rows.
        # Unmonitored partitions get zero load (reference populates only
        # monitored partitions; include_all_topics keeps them in the model).
        cols = topology.columns()
        part_keys = (cols.part_topic.astype(np.int64) << 32) | cols.part_num
        ekeys, erows = self.partition_aggregator.entity_key_rows()
        P = part_keys.size
        if ekeys.size:
            pos = np.minimum(np.searchsorted(ekeys, part_keys), ekeys.size - 1)
            monitored = ekeys[pos] == part_keys
            row_of_part = np.where(monitored, erows[pos], 0)
        else:
            monitored = np.zeros(P, bool)
            row_of_part = np.zeros(P, np.int64)
        leader_load = np.zeros((P, NUM_RESOURCES), np.float32)
        follower_load = np.zeros((P, NUM_RESOURCES), np.float32)
        if np.any(monitored):
            m_rows = row_of_part[monitored]
            ll = loads[m_rows]
            leader_load[monitored] = ll
            follower_load[monitored] = self.follower_loads(ll)

        from cruise_control_tpu.models.builder import build_state_columnar

        state, catalog = build_state_columnar(
            broker_specs,
            cols,
            leader_load,
            follower_load,
            replica_capacity=self._replica_capacity,
            bucket_policy=self.bucket_policy,
        )
        self.last_catalog = catalog
        return state

    def follower_loads(self, loads: np.ndarray) -> np.ndarray:
        """[N, 4] follower twin of per-partition leader loads: NW_OUT
        zeroed, CPU the follower share (the trained regression when
        available, else the static coefficients) — ONE function for the
        model build and the streaming controller's in-place delta path,
        so the two can never disagree on follower semantics."""
        loads = np.asarray(loads, np.float32)
        if self.regression is not None and self.regression.trained:
            follower_cpu = self.regression.follower_cpu_array(loads)
        else:
            follower_cpu = follower_cpu_util_array(
                loads, loads[:, Resource.CPU], weights=self.cpu_weights
            )
        fl = loads.copy()
        fl[:, Resource.NW_OUT] = 0.0
        fl[:, Resource.CPU] = follower_cpu
        return fl

    # ------------------------------------------------------------------

    def monitor_state(self) -> dict:
        """STATE endpoint payload (reference LoadMonitorState)."""
        try:
            agg = self.partition_aggregator.aggregate()
            windows = agg.completeness.valid_windows.size
            ratio = agg.completeness.valid_entity_ratio
        except ValueError:
            windows, ratio = 0, 0.0
        return {
            "state": self._state.value,
            "reasonOfLatestPauseOrResume": self._paused_reason,
            "numValidWindows": int(windows),
            "monitoredPartitionsPercentage": round(float(ratio) * 100.0, 3),
            "numMonitoredPartitions": self.partition_aggregator.num_entities(),
            "loadGeneration": self._load_generation,
        }
