"""Metric-window delta extraction over WindowedHistory snapshots.

The streaming controller (controller/streaming.py) keeps the flattened
ClusterState device-resident and, on every window roll, wants to ship
ONLY what changed — not rebuild the whole model.  This module diffs two
read-only `WindowedHistory` snapshots of the partition aggregator
(monitor/aggregator.py) into a per-entity load update plus the
generation-level facts that force a full re-flatten (entities appearing
or vanishing mid-stream = topics created/deleted).

Completeness discipline: the reduction honors the history's `complete`
mask, never the raw values — a half-sampled window (the current window
just rolled, a fetcher hiccup) holds a partial SUM-derived average whose
value is biased low, and folding it in would read as a traffic drop and
trigger spurious re-anneals toward a phantom load profile.  Entities with
NO fully-sampled window in the snapshot are reported `stale` (hold their
previous loads) rather than updated.

Resource semantics mirror LoadMonitor._window_reduced_loads: CPU/NW_IN/
NW_OUT average over (complete) windows, DISK takes the newest complete
window (LATEST strategy — disk usage is a level, not a rate).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.monitor.aggregator import WindowedHistory
from cruise_control_tpu.monitor.metricdef import MetricDef


@dataclasses.dataclass(frozen=True)
class ReducedLoads:
    """Per-entity [4] loads reduced from one WindowedHistory snapshot."""

    entities: tuple
    loads: np.ndarray  # f32[E, 4] in Resource order
    usable: np.ndarray  # bool[E] entity had >= 1 complete window


@dataclasses.dataclass(frozen=True)
class WindowDelta:
    """What changed between two WindowedHistory snapshots.

    `entities`/`loads` cover every entity (present in BOTH snapshots) with
    at least one complete window in the newer snapshot — new ABSOLUTE
    loads, not increments, so the consumer scatters idempotently.
    `changed` marks the subset whose reduced loads actually moved.
    `added`/`removed` are entity-set diffs (mid-stream topic or partition
    create/delete): the delta path cannot express them in place, so the
    consumer must re-flatten.  `stale` entities had no complete window and
    keep their previous loads.
    """

    entities: tuple
    loads: np.ndarray  # f32[N, 4] Resource order (absolute)
    changed: np.ndarray  # bool[N]
    added: tuple
    removed: tuple
    stale: tuple
    windows_advanced: int
    #: the NEW snapshot's ReducedLoads — the consumer caches it and hands
    #: it back as `prev_reduced` next cycle, so an always-on loop never
    #: re-reduces the same [E, W, 4] tensor twice
    reduced: "ReducedLoads | None" = None

    @property
    def requires_reflatten(self) -> bool:
        return bool(self.added or self.removed)


def _load_columns(metric_def: MetricDef) -> list[int]:
    return [
        metric_def.metric_id("CPU_USAGE"),
        metric_def.metric_id("LEADER_BYTES_IN"),
        metric_def.metric_id("LEADER_BYTES_OUT"),
        metric_def.metric_id("DISK_USAGE"),
    ]


def reduce_windowed_loads(values: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """[E, W, 4] per-window load columns (CPU, NW_IN, NW_OUT, DISK — the
    `_load_columns` slice order) + bool[E, W] usable-window mask ->
    f32[E, 4] in Resource order: the AVG resources average over masked
    windows, DISK takes the NEWEST masked window (LATEST strategy — disk
    usage is a level, not a rate; window axis is newest -> oldest).

    The ONE reduction both the full model build
    (LoadMonitor._window_reduced_loads, masked by aggregate validity) and
    the streaming delta path (reduce_complete_loads, masked by raw
    completeness) apply — a strategy change lands in both or neither.
    Rows with an all-False mask reduce to 0 mean / window-0 latest; the
    caller's usable/monitored mask decides what to do with them.
    """
    n = np.maximum(mask.sum(1), 1)[:, None]
    mean = (values * mask[..., None]).sum(1) / n  # [E, 4]
    first = np.argmax(mask, axis=1)  # newest masked window per entity
    latest = values[np.arange(values.shape[0]), first]  # [E, 4]
    loads = np.empty((values.shape[0], NUM_RESOURCES), np.float32)
    loads[:, Resource.CPU] = mean[:, 0]
    loads[:, Resource.NW_IN] = mean[:, 1]
    loads[:, Resource.NW_OUT] = mean[:, 2]
    loads[:, Resource.DISK] = latest[:, 3]
    return loads


def reduce_complete_loads(
    history: WindowedHistory, metric_def: MetricDef
) -> ReducedLoads:
    """Reduce a history snapshot to per-entity [4] loads over COMPLETE
    windows only (see module docstring for why partial windows are out)."""
    cols = _load_columns(metric_def)
    complete = history.complete  # [E, W]
    usable = complete.sum(1) > 0
    loads = reduce_windowed_loads(history.values[:, :, cols], complete)
    loads[~usable] = 0.0
    return ReducedLoads(
        entities=history.entities, loads=loads, usable=usable
    )


def extract_window_delta(
    prev: WindowedHistory,
    cur: WindowedHistory,
    metric_def: MetricDef,
    *,
    rtol: float = 1e-6,
    prev_reduced: ReducedLoads | None = None,
) -> WindowDelta:
    """Diff two snapshots of the SAME aggregator into a WindowDelta.

    `prev` must be the snapshot the consumer's device state was last
    synchronized to; `cur` the fresh one.  Entity ORDER may differ between
    snapshots (the aggregator interns new entities at the tail) — the diff
    joins on entity identity, not row position.  `prev_reduced` (the
    `reduced` field of the previous cycle's WindowDelta) skips re-reducing
    the prev snapshot.
    """
    prev_red = (
        prev_reduced
        if prev_reduced is not None and prev_reduced.entities == prev.entities
        else reduce_complete_loads(prev, metric_def)
    )
    cur_red = reduce_complete_loads(cur, metric_def)
    prev_rows = {e: i for i, e in enumerate(prev.entities)}
    cur_set = set(cur.entities)
    added = tuple(e for e in cur.entities if e not in prev_rows)
    removed = tuple(e for e in prev.entities if e not in cur_set)

    entities: list = []
    rows_cur: list[int] = []
    rows_prev: list[int] = []
    stale: list = []
    for i, e in enumerate(cur.entities):
        j = prev_rows.get(e)
        if j is None:
            continue  # new entity: reported via `added`
        if not cur_red.usable[i]:
            stale.append(e)  # no fully-sampled window yet: hold loads
            continue
        entities.append(e)
        rows_cur.append(i)
        rows_prev.append(j)
    if entities:
        loads = cur_red.loads[rows_cur]
        old = prev_red.loads[rows_prev]
        old_usable = prev_red.usable[rows_prev]
        scale = np.maximum(np.abs(old), np.abs(loads))
        changed = (np.abs(loads - old) > rtol * np.maximum(scale, 1e-12)).any(1)
        # entities unusable in PREV had no trusted baseline — treat as
        # changed so the device state converges to the first honest value
        changed |= ~old_usable
    else:
        loads = np.zeros((0, NUM_RESOURCES), np.float32)
        changed = np.zeros(0, bool)
    return WindowDelta(
        entities=tuple(entities),
        loads=loads.astype(np.float32),
        changed=changed,
        added=added,
        removed=removed,
        stale=tuple(stale),
        windows_advanced=int(cur.window_indices[0] - prev.window_indices[0]),
        reduced=cur_red,
    )
