"""Broker capacity resolution.

Reference: config/BrokerCapacityConfigResolver.java (SPI),
BrokerCapacityConfigFileResolver.java (reads config/capacity*.json with
JBOD per-logdir DISK maps and a brokerId=-1 default), BrokerCapacityInfo.java.
The JSON schema is kept compatible with the reference's capacity files.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Protocol

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource

DEFAULT_BROKER_ID = -1


@dataclasses.dataclass(frozen=True)
class BrokerCapacityInfo:
    """Reference config/BrokerCapacityInfo.java."""

    capacity: np.ndarray  # f32[4] indexed by Resource (DISK = sum of logdirs)
    disk_capacities: dict[str, float] | None = None  # logdir -> MB (JBOD)
    num_cores: int = 1
    estimation_info: str = ""

    @property
    def is_jbod(self) -> bool:
        return bool(self.disk_capacities) and len(self.disk_capacities) > 1


class BrokerCapacityConfigResolver(Protocol):
    """SPI (reference config/BrokerCapacityConfigResolver.java)."""

    def capacity_for_broker(self, rack: str, host: str, broker_id: int) -> BrokerCapacityInfo:
        ...


class FixedCapacityResolver:
    """Same capacity for every broker — test/synthetic default."""

    def __init__(self, capacity, disk_capacities: dict[str, float] | None = None, num_cores: int = 1):
        self._info = BrokerCapacityInfo(
            np.asarray(capacity, np.float32), disk_capacities, num_cores
        )

    def capacity_for_broker(self, rack: str, host: str, broker_id: int) -> BrokerCapacityInfo:
        return self._info


class FileCapacityResolver:
    """Reads the reference's capacity JSON schema
    (reference config/BrokerCapacityConfigFileResolver.java, schema
    config/capacity.json + capacityJBOD.json: DISK either a scalar or a
    {logdir: MB} map; brokerId "-1" provides the default)."""

    def __init__(self, path: str):
        with open(path) as f:
            doc = json.load(f)
        self._by_id: dict[int, BrokerCapacityInfo] = {}
        for entry in doc["brokerCapacities"]:
            bid = int(entry["brokerId"])
            cap = entry["capacity"]
            disk = cap["DISK"]
            disks = None
            if isinstance(disk, dict):
                disks = {k: float(v) for k, v in disk.items()}
                disk_total = sum(disks.values())
            else:
                disk_total = float(disk)
            cpu = cap["CPU"]
            if isinstance(cpu, dict):
                # cores schema (reference config/capacityCores.json):
                # CPU = {"num.cores": N}; utilization stays percent-based
                # with the core count carried alongside
                cores = int(cpu["num.cores"])
                cpu_cap = 100.0
            else:
                cores = int(entry.get("numCores", 1))
                cpu_cap = float(cpu)
            arr = np.zeros(NUM_RESOURCES, np.float32)
            arr[Resource.CPU] = cpu_cap
            arr[Resource.NW_IN] = float(cap["NW_IN"])
            arr[Resource.NW_OUT] = float(cap["NW_OUT"])
            arr[Resource.DISK] = disk_total
            self._by_id[bid] = BrokerCapacityInfo(arr, disks, cores)
        if DEFAULT_BROKER_ID not in self._by_id:
            raise ValueError("capacity file must define the default broker (-1)")

    def capacity_for_broker(self, rack: str, host: str, broker_id: int) -> BrokerCapacityInfo:
        return self._by_id.get(broker_id, self._by_id[DEFAULT_BROKER_ID])
