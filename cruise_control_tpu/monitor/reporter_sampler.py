"""Sampler that consumes the metrics-reporter stream.

Reference: monitor/sampling/CruiseControlMetricsReporterSampler.java:41
(poll loop over __CruiseControlMetrics) +
CruiseControlMetricsProcessor.java (raw broker/topic/partition metrics ->
PartitionMetricSample / BrokerMetricSample, including CPU attribution:
broker CPU is apportioned to leader partitions by their share of the
broker's produce/fetch bytes).
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np

from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF, MetricDef
from cruise_control_tpu.monitor.sampling import (
    BrokerEntity,
    MetricSample,
    PartitionEntity,
    SamplingResult,
)
from cruise_control_tpu.monitor.topology import ClusterTopology
from cruise_control_tpu.reporter.metrics import (
    BrokerMetric,
    MetricType,
    PartitionMetric,
    TopicMetric,
)
from cruise_control_tpu.reporter.reporter import InMemoryTransport

# raw broker metric -> aggregate broker metric name (KafkaMetricDef)
_BROKER_METRIC_MAP = {
    MetricType.BROKER_PRODUCE_REQUEST_RATE: "BROKER_PRODUCE_REQUEST_RATE",
    MetricType.BROKER_CONSUMER_FETCH_REQUEST_RATE: "BROKER_CONSUMER_FETCH_REQUEST_RATE",
    MetricType.BROKER_FOLLOWER_FETCH_REQUEST_RATE: "BROKER_FOLLOWER_FETCH_REQUEST_RATE",
    MetricType.BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT: "BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT",
    MetricType.BROKER_REQUEST_QUEUE_SIZE: "BROKER_REQUEST_QUEUE_SIZE",
    MetricType.BROKER_RESPONSE_QUEUE_SIZE: "BROKER_RESPONSE_QUEUE_SIZE",
    MetricType.BROKER_LOG_FLUSH_RATE: "BROKER_LOG_FLUSH_RATE",
    MetricType.BROKER_LOG_FLUSH_TIME_MS_MAX: "BROKER_LOG_FLUSH_TIME_MS_MAX",
    MetricType.BROKER_LOG_FLUSH_TIME_MS_MEAN: "BROKER_LOG_FLUSH_TIME_MS_MEAN",
    # slow-broker evidence + training inputs (reference
    # SlowBrokerFinder.java:99 byte rates and request latencies)
    MetricType.BROKER_PRODUCE_LOCAL_TIME_MS_MEAN: "BROKER_PRODUCE_LOCAL_TIME_MS_MEAN",
    MetricType.BROKER_PRODUCE_LOCAL_TIME_MS_MAX: "BROKER_PRODUCE_LOCAL_TIME_MS_MAX",
    MetricType.ALL_TOPIC_BYTES_IN: "LEADER_BYTES_IN",
    MetricType.ALL_TOPIC_BYTES_OUT: "LEADER_BYTES_OUT",
    MetricType.ALL_TOPIC_REPLICATION_BYTES_IN: "REPLICATION_BYTES_IN_RATE",
    MetricType.ALL_TOPIC_REPLICATION_BYTES_OUT: "REPLICATION_BYTES_OUT_RATE",
}
# percentile latencies (reference reporter ids 43-62): MetricType and
# KafkaMetricDef names coincide, so the map rows are mechanical
_BROKER_METRIC_MAP.update({
    mt: mt.name
    for mt in MetricType
    if mt.name.endswith(("_50TH", "_999TH"))
})


class CruiseControlMetricsReporterSampler:
    """MetricSampler over an InMemoryTransport (Kafka consumer in prod)."""

    #: the service's own topics never become workload samples (the
    #: reference CruiseControlMetricsProcessor skips its metrics topic)
    DEFAULT_EXCLUDED = r"^__(KafkaCruiseControl|CruiseControlMetrics).*"

    def __init__(
        self,
        transport: InMemoryTransport,
        topology_provider,
        *,
        metric_def: MetricDef = KAFKA_METRIC_DEF,
        topic_filter=None,
        allow_cpu_estimation: bool = True,
    ):
        """allow_cpu_estimation (reference MonitorConfig
        sampling.allow.cpu.capacity.estimation): when False, partitions on
        a broker that reported no CPU metric are NOT sampled at all — a
        byte-share CPU attribution against an unknown broker CPU would be
        an estimate the operator forbade."""
        import re

        self.transport = transport
        self.topology_provider = topology_provider
        self.metric_def = metric_def
        self.allow_cpu_estimation = allow_cpu_estimation
        if topic_filter is None:
            rx = re.compile(self.DEFAULT_EXCLUDED)
            topic_filter = lambda name: not rx.match(str(name))  # noqa: E731
        self.topic_filter = topic_filter
        self._topic_ids: dict[str, int] = {}

    def _topic_id(self, topic: str) -> int:
        if topic not in self._topic_ids:
            # dense ids in first-seen order; the monitor's builder re-sorts
            self._topic_ids[topic] = len(self._topic_ids)
        return self._topic_ids[topic]

    def get_samples(self, assigned_partitions, start_ms: int, end_ms: int) -> SamplingResult:
        topo: ClusterTopology = self.topology_provider()
        m = self.metric_def
        cpu_id = m.metric_id("CPU_USAGE")
        disk_id = m.metric_id("DISK_USAGE")
        nwin_id = m.metric_id("LEADER_BYTES_IN")
        nwout_id = m.metric_id("LEADER_BYTES_OUT")

        part_size: dict[tuple[str, int], float] = {}
        topic_bytes_in: dict[tuple[int, str], float] = defaultdict(float)
        topic_bytes_out: dict[tuple[int, str], float] = defaultdict(float)
        broker_cpu: dict[int, float] = {}
        broker_values: dict[int, np.ndarray] = {}
        times: dict[int, int] = {}

        if getattr(self.transport, "framed_native", hasattr(self.transport, "poll_framed")):
            # columnar fast path: one native pass over the whole batch
            # (cruise_control_tpu/native/serde.cpp), numpy masks instead of
            # a per-record object loop — the JVM sampler's hot loop analog
            from cruise_control_tpu.native import batch_deserialize

            b = batch_deserialize(self.transport.poll_framed())
            if len(b):
                # latest report time per broker
                order = np.argsort(b.broker_ids, kind="stable")
                bids = b.broker_ids[order]
                tms = b.times_ms[order]
                uniq, starts = np.unique(bids, return_index=True)
                maxes = np.maximum.reduceat(tms, starts)
                times.update(
                    (int(u), int(t)) for u, t in zip(uniq, maxes)
                )
                part_mask = (b.class_ids == 2) & (
                    b.metric_types == int(MetricType.PARTITION_SIZE)
                )
                for i in np.nonzero(part_mask)[0]:
                    part_size[(b.topics[b.topic_ids[i]], int(b.partitions[i]))] = float(
                        b.values[i]
                    )
                for mask, store in (
                    ((b.class_ids == 1) & (b.metric_types == int(MetricType.TOPIC_BYTES_IN)),
                     topic_bytes_in),
                    ((b.class_ids == 1) & (b.metric_types == int(MetricType.TOPIC_BYTES_OUT)),
                     topic_bytes_out),
                ):
                    for i in np.nonzero(mask)[0]:
                        store[(int(b.broker_ids[i]), b.topics[b.topic_ids[i]])] = float(
                            b.values[i]
                        )
                broker_mask = b.class_ids == 0
                for i in np.nonzero(broker_mask)[0]:
                    mt = MetricType(int(b.metric_types[i]))
                    if mt == MetricType.BROKER_CPU_UTIL:
                        broker_cpu[int(b.broker_ids[i])] = float(b.values[i])
                    else:
                        name = _BROKER_METRIC_MAP.get(mt)
                        if name is not None:
                            v = broker_values.setdefault(
                                int(b.broker_ids[i]), np.zeros(m.num_metrics, np.float32)
                            )
                            v[m.metric_id(name)] = float(b.values[i])
        else:
            for r in self.transport.poll():
                times[r.broker_id] = max(times.get(r.broker_id, 0), r.time_ms)
                if isinstance(r, PartitionMetric) and r.metric_type == MetricType.PARTITION_SIZE:
                    part_size[(r.topic, r.partition)] = r.value
                elif isinstance(r, TopicMetric):
                    if r.metric_type == MetricType.TOPIC_BYTES_IN:
                        topic_bytes_in[(r.broker_id, r.topic)] = r.value
                    elif r.metric_type == MetricType.TOPIC_BYTES_OUT:
                        topic_bytes_out[(r.broker_id, r.topic)] = r.value
                elif isinstance(r, BrokerMetric):
                    if r.metric_type == MetricType.BROKER_CPU_UTIL:
                        broker_cpu[r.broker_id] = r.value
                    else:
                        name = _BROKER_METRIC_MAP.get(r.metric_type)
                        if name is not None:
                            v = broker_values.setdefault(
                                r.broker_id, np.zeros(m.num_metrics, np.float32)
                            )
                            v[m.metric_id(name)] = r.value

        # leader partitions per (broker, topic) for byte attribution
        leaders: dict[tuple[int, str], list] = defaultdict(list)
        for p in topo.partitions:
            if self.topic_filter(p.topic):
                leaders[(p.leader, p.topic)].append(p)

        t_mid = (start_ms + end_ms) // 2
        partition_samples: list[MetricSample] = []
        for (broker, topic), parts in leaders.items():
            tb_in = topic_bytes_in.get((broker, topic), 0.0)
            tb_out = topic_bytes_out.get((broker, topic), 0.0)
            sizes = np.array([part_size.get((topic, p.partition), 0.0) for p in parts])
            if tb_in == 0.0 and tb_out == 0.0 and sizes.sum() == 0.0:
                # nothing reported for this (broker, topic): emitting zero
                # samples would poison the windows as real measurements
                continue
            total = sizes.sum()
            shares = sizes / total if total > 0 else np.full(len(parts), 1.0 / max(len(parts), 1))
            # CPU attribution: broker CPU split across leader partitions by
            # their byte share (reference CruiseControlMetricsProcessor)
            if not self.allow_cpu_estimation and broker not in broker_cpu:
                continue
            b_cpu = broker_cpu.get(broker, 0.0)
            b_total_in = sum(
                topic_bytes_in.get((broker, t2), 0.0) for (b2, t2) in topic_bytes_in if b2 == broker
            )
            for p, share in zip(parts, shares):
                vals = np.zeros(m.num_metrics, np.float32)
                vals[disk_id] = part_size.get((topic, p.partition), 0.0)
                vals[nwin_id] = tb_in * share
                vals[nwout_id] = tb_out * share
                if b_total_in > 0:
                    vals[cpu_id] = b_cpu * (tb_in * share) / b_total_in
                partition_samples.append(
                    MetricSample(
                        PartitionEntity(self._topic_id(topic), p.partition), t_mid, vals
                    )
                )

        broker_samples = [
            MetricSample(BrokerEntity(b), times.get(b, t_mid), v)
            for b, v in broker_values.items()
        ]
        return SamplingResult(partition_samples, broker_samples)
