"""Metric definitions for monitoring.

Reference: cruise-control-core metricdef/MetricDef.java + MetricInfo.java
(registry with AVG/MAX/LATEST value-computing strategies) and
monitor/metricdefinition/KafkaMetricDef.java:42-135 (the Kafka taxonomy,
COMMON vs BROKER_ONLY scopes, resource attribution).

Array consequence: a MetricDef is the index space of the metric axis in
the windowed aggregation tensors ([entities, windows, metrics]) — each
MetricInfo's `id` is its column.
"""

from __future__ import annotations

import dataclasses
import enum

from cruise_control_tpu.common.resources import Resource


class ValueComputingStrategy(enum.Enum):
    """How multiple samples within one window combine
    (reference metricdef/ValueComputingStrategy.java)."""

    AVG = "avg"
    MAX = "max"
    LATEST = "latest"


class MetricScope(enum.Enum):
    """COMMON metrics exist per partition AND per broker; BROKER_ONLY only
    per broker (reference KafkaMetricDef.DefScope, KafkaMetricDef.java:265)."""

    COMMON = "common"
    BROKER_ONLY = "broker_only"


@dataclasses.dataclass(frozen=True)
class MetricInfo:
    name: str
    id: int
    strategy: ValueComputingStrategy
    scope: MetricScope
    resource: Resource | None  # which balanced resource it attributes to
    to_predict: bool = False  # input to the CPU estimation model


class MetricDef:
    """Ordered metric registry (reference metricdef/MetricDef.java)."""

    def __init__(self):
        self._by_name: dict[str, MetricInfo] = {}
        self._infos: list[MetricInfo] = []

    def define(
        self,
        name: str,
        strategy: ValueComputingStrategy,
        scope: MetricScope = MetricScope.COMMON,
        resource: Resource | None = None,
        to_predict: bool = False,
    ) -> "MetricDef":
        if name in self._by_name:
            raise ValueError(f"metric {name} already defined")
        info = MetricInfo(name, len(self._infos), strategy, scope, resource, to_predict)
        self._by_name[name] = info
        self._infos.append(info)
        return self

    def info(self, name: str) -> MetricInfo:
        return self._by_name[name]

    def metric_id(self, name: str) -> int:
        return self._by_name[name].id

    @property
    def num_metrics(self) -> int:
        return len(self._infos)

    def all_infos(self) -> list[MetricInfo]:
        return list(self._infos)

    def resource_metric_ids(self, resource: Resource) -> list[int]:
        return [m.id for m in self._infos if m.resource == resource]

    def common_metric_ids(self) -> list[int]:
        return [m.id for m in self._infos if m.scope == MetricScope.COMMON]


def kafka_metric_def() -> MetricDef:
    """The Kafka metric taxonomy (reference KafkaMetricDef.java:44-80).

    Column order mirrors the reference declaration order so serialized
    sample payloads stay comparable.
    """
    AVG = ValueComputingStrategy.AVG
    LATEST = ValueComputingStrategy.LATEST
    C, B = MetricScope.COMMON, MetricScope.BROKER_ONLY
    d = MetricDef()
    d.define("CPU_USAGE", AVG, C, Resource.CPU, to_predict=True)
    d.define("DISK_USAGE", LATEST, C, Resource.DISK)
    d.define("LEADER_BYTES_IN", AVG, C, Resource.NW_IN)
    d.define("LEADER_BYTES_OUT", AVG, C, Resource.NW_OUT)
    d.define("PRODUCE_RATE", AVG, C)
    d.define("FETCH_RATE", AVG, C)
    d.define("MESSAGE_IN_RATE", AVG, C)
    d.define("REPLICATION_BYTES_IN_RATE", AVG, C, Resource.NW_IN)
    d.define("REPLICATION_BYTES_OUT_RATE", AVG, C, Resource.NW_OUT)
    for name in (
        "BROKER_PRODUCE_REQUEST_RATE",
        "BROKER_CONSUMER_FETCH_REQUEST_RATE",
        "BROKER_FOLLOWER_FETCH_REQUEST_RATE",
        "BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT",
        "BROKER_REQUEST_QUEUE_SIZE",
        "BROKER_RESPONSE_QUEUE_SIZE",
        "BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX",
        "BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN",
        "BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MAX",
        "BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN",
        "BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MAX",
        "BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN",
        "BROKER_PRODUCE_TOTAL_TIME_MS_MAX",
        "BROKER_PRODUCE_TOTAL_TIME_MS_MEAN",
        "BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MAX",
        "BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MEAN",
        "BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MAX",
        "BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MEAN",
        "BROKER_PRODUCE_LOCAL_TIME_MS_MAX",
        "BROKER_PRODUCE_LOCAL_TIME_MS_MEAN",
        "BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MAX",
        "BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MEAN",
        "BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MAX",
        "BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MEAN",
        "BROKER_LOG_FLUSH_RATE",
        "BROKER_LOG_FLUSH_TIME_MS_MAX",
        "BROKER_LOG_FLUSH_TIME_MS_MEAN",
        # percentile latencies (reference KafkaMetricDef BROKER_ONLY v5
        # additions; SlowBrokerFinder evidence) — ingested from the
        # reference reporter plugin's RawMetricType ids 43-62
        "BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_50TH",
        "BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_999TH",
        "BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_50TH",
        "BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_999TH",
        "BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_50TH",
        "BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_999TH",
        "BROKER_PRODUCE_TOTAL_TIME_MS_50TH",
        "BROKER_PRODUCE_TOTAL_TIME_MS_999TH",
        "BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_50TH",
        "BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_999TH",
        "BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_50TH",
        "BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_999TH",
        "BROKER_PRODUCE_LOCAL_TIME_MS_50TH",
        "BROKER_PRODUCE_LOCAL_TIME_MS_999TH",
        "BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_50TH",
        "BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_999TH",
        "BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_50TH",
        "BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_999TH",
        "BROKER_LOG_FLUSH_TIME_MS_50TH",
        "BROKER_LOG_FLUSH_TIME_MS_999TH",
    ):
        d.define(name, AVG, B)
    return d


KAFKA_METRIC_DEF = kafka_metric_def()
