"""Load monitor task runner — sampling/bootstrap/training scheduling.

Reference: monitor/task/LoadMonitorTaskRunner.java:33,56 (state machine
NOT_STARTED/RUNNING/SAMPLING/PAUSED/BOOTSTRAPPING/TRAINING/LOADING),
BootstrapTask.java (3 bootstrap modes: RANGE, SINCE, RECENT),
TrainingTask.java (feeds LinearRegressionModelParameters),
SampleLoadingTask.java (warm restart from the sample store).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from cruise_control_tpu.monitor.cpu_model import LinearRegressionModelParameters
from cruise_control_tpu.monitor.load_monitor import LoadMonitor, MonitorState
from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF
from cruise_control_tpu.monitor.sampling import MetricFetcherManager


class LoadMonitorTaskRunner:
    """Coordinates the sampling loop with one-shot bootstrap/train/load
    tasks, enforcing the reference's exclusive-state rules (a bootstrap
    cannot start while training, etc.)."""

    def __init__(
        self,
        monitor: LoadMonitor,
        fetcher: MetricFetcherManager,
        partitions_fn: Callable[[], list],
        *,
        window_ms: int,
        regression: LinearRegressionModelParameters | None = None,
        auto_train: bool = False,
    ):
        """auto_train (reference MonitorConfig use.linear.regression.model):
        harvest broker samples continuously and train the CPU regression
        as soon as its bucket coverage suffices — no explicit /train
        needed."""
        self.monitor = monitor
        self.fetcher = fetcher
        self.partitions_fn = partitions_fn
        self.window_ms = window_ms
        self.regression = regression or LinearRegressionModelParameters()
        self.auto_train = auto_train
        self._lock = threading.Lock()
        self._bootstrap_progress = 0.0
        self._harvested_until = 0
        self._harvest_lock = threading.Lock()
        self._auto_stop = threading.Event()
        self._auto_thread: threading.Thread | None = None

    # ------------------------------------------------------------------

    def _enter(self, state: MonitorState):
        with self._lock:
            if self.monitor.state in (
                MonitorState.BOOTSTRAPPING,
                MonitorState.TRAINING,
                MonitorState.LOADING,
            ):
                raise RuntimeError(f"monitor busy: {self.monitor.state.value}")
            self._prev_state = self.monitor.state
            self.monitor._state = state

    def _exit(self):
        with self._lock:
            self.monitor._state = self._prev_state

    # ------------------------------------------------------------------

    def start(self, *, interval_s: float | None = None):
        self.monitor.start()
        self.fetcher.start(self.partitions_fn, interval_s=interval_s)
        if self.auto_train and self._auto_thread is None:
            # a stop()/start() cycle must revive auto-training
            self._auto_stop.clear()
            tick = interval_s or self.window_ms / 1000.0

            def loop():
                while not self._auto_stop.wait(tick):
                    try:
                        self.maybe_auto_train()
                    except Exception:  # noqa: BLE001 — keep the loop alive
                        pass

            self._auto_thread = threading.Thread(
                target=loop, daemon=True, name="cpu-model-auto-train"
            )
            self._auto_thread.start()

    def stop(self):
        self._auto_stop.set()
        if self._auto_thread is not None:
            self._auto_thread.join(timeout=5)
            self._auto_thread = None
        self.fetcher.stop()

    def load_samples(self) -> int:
        """Warm restart (reference SampleLoadingTask)."""
        self._enter(MonitorState.LOADING)
        try:
            return self.fetcher.load_samples()
        finally:
            self._exit()

    def bootstrap_range(self, start_ms: int, end_ms: int, clear_metrics: bool = False) -> int:
        """RANGE bootstrap: replay samples for [start, end)
        (reference BootstrapTask RANGE mode; LoadMonitor.bootstrap:325-345)."""
        return self._bootstrap(start_ms, end_ms, clear_metrics)

    def bootstrap_since(self, start_ms: int, clear_metrics: bool = False) -> int:
        """SINCE bootstrap: from start to now."""
        return self._bootstrap(start_ms, int(time.time() * 1000), clear_metrics)

    def bootstrap_recent(self, clear_metrics: bool = True) -> int:
        """RECENT bootstrap: enough trailing windows to satisfy completeness."""
        now = int(time.time() * 1000)
        span = self.window_ms * (self.monitor.partition_aggregator.num_windows + 1)
        return self._bootstrap(now - span, now, clear_metrics)

    def _bootstrap(self, start_ms: int, end_ms: int, clear_metrics: bool) -> int:
        self._enter(MonitorState.BOOTSTRAPPING)
        try:
            if clear_metrics:
                agg = self.monitor.partition_aggregator
                fresh = type(agg)(
                    num_windows=agg.num_windows,
                    window_ms=agg.window_ms,
                    min_samples_per_window=agg.min_samples,
                    metric_def=agg.metric_def,
                )
                self.monitor.partition_aggregator = fresh
                self.fetcher.partition_aggregator = fresh
            total = 0
            parts = self.partitions_fn()
            # replay at most the windows the aggregation ring can retain —
            # older samples would immediately roll out again (reference
            # BootstrapTask replays only what the sample store covers)
            max_windows = self.monitor.partition_aggregator.num_windows + 1
            n_windows = max(1, min((end_ms - start_ms) // self.window_ms, max_windows))
            start_ms = max(start_ms, end_ms - n_windows * self.window_ms)
            for i in range(n_windows):
                w_start = start_ms + i * self.window_ms
                w_end = min(w_start + self.window_ms - 1, end_ms)
                total += self.fetcher.fetch_once(parts, w_start, w_end)
                self._bootstrap_progress = (i + 1) / n_windows
            return total
        finally:
            self._exit()

    def _harvest(self, start_ms: int, end_ms: int) -> int:
        """Feed broker windows inside [start_ms, end_ms) into the
        regression; returns the number of samples added.

        Windows at or below the watermark are ALWAYS skipped and the
        watermark always advances — the explicit /train path and the
        auto-train thread share one regression, and either re-harvesting
        the other's windows would double-count samples and skew the fit.
        Serialized by a lock for the same reason."""
        with self._harvest_lock:
            start_ms = max(start_ms, self._harvested_until)
            agg = self.fetcher.broker_aggregator
            if agg is None or not agg.num_entities():
                return 0
            try:
                res = agg.aggregate()
            except ValueError:  # no completed broker windows yet
                return 0
            m = KAFKA_METRIC_DEF
            added = 0
            for e_idx in range(res.values.shape[0]):
                for w in range(res.values.shape[1]):
                    if not res.window_valid[e_idx, w]:
                        continue
                    # NB: broker windows have their OWN span (reference
                    # broker.metrics.window.ms), not the partition span
                    # this runner was built with
                    w_start = int(res.window_indices[w]) * agg.window_ms
                    if not (start_ms <= w_start < end_ms):
                        continue
                    v = res.values[e_idx, w]
                    self.regression.add_sample(
                        float(v[m.metric_id("LEADER_BYTES_IN")]),
                        float(v[m.metric_id("LEADER_BYTES_OUT")]),
                        float(v[m.metric_id("REPLICATION_BYTES_IN_RATE")]),
                        float(v[m.metric_id("CPU_USAGE")]),
                    )
                    added += 1
                    self._harvested_until = max(
                        self._harvested_until, w_start + agg.window_ms
                    )
            return added

    def train(self, start_ms: int, end_ms: int) -> dict:
        """Reference TrainingTask: harvest (bytes-in, bytes-out, follower
        bytes-in, cpu) tuples from broker samples into the regression —
        restricted to windows inside [start_ms, end_ms) as requested
        (reference LoadMonitor.train:354 passes the range through).

        An explicit /train is an operator decision: it fits the model even
        when bucket coverage is below the auto-train gate (force=True)."""
        self._enter(MonitorState.TRAINING)
        try:
            self._harvest(start_ms, end_ms)
            trained = self.regression.train(force=True)
            return {"trained": trained, **self.regression.state()}
        finally:
            self._exit()

    def maybe_auto_train(self) -> bool:
        """Continuous training loop body (use.linear.regression.model):
        harvest only windows NEWER than the watermark (repeat harvesting
        would double-count and skew the fit), then train once the
        bucket-coverage gate passes."""
        if self.regression.trained:
            return True
        import time as _time

        self._harvest(self._harvested_until, int(_time.time() * 1000))
        if self.regression.ready_to_train():
            return self.regression.train()
        return False

    def state(self) -> dict:
        return {
            "monitorState": self.monitor.state.value,
            "bootstrapProgressPct": round(100.0 * self._bootstrap_progress, 1),
            "trainingState": self.regression.state(),
            "totalSamples": self.fetcher.total_samples,
        }
