"""Load monitor task runner — sampling/bootstrap/training scheduling.

Reference: monitor/task/LoadMonitorTaskRunner.java:33,56 (state machine
NOT_STARTED/RUNNING/SAMPLING/PAUSED/BOOTSTRAPPING/TRAINING/LOADING),
BootstrapTask.java (3 bootstrap modes: RANGE, SINCE, RECENT),
TrainingTask.java (feeds LinearRegressionModelParameters),
SampleLoadingTask.java (warm restart from the sample store).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from cruise_control_tpu.monitor.cpu_model import LinearRegressionModelParameters
from cruise_control_tpu.monitor.load_monitor import LoadMonitor, MonitorState
from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF
from cruise_control_tpu.monitor.sampling import MetricFetcherManager


class LoadMonitorTaskRunner:
    """Coordinates the sampling loop with one-shot bootstrap/train/load
    tasks, enforcing the reference's exclusive-state rules (a bootstrap
    cannot start while training, etc.)."""

    def __init__(
        self,
        monitor: LoadMonitor,
        fetcher: MetricFetcherManager,
        partitions_fn: Callable[[], list],
        *,
        window_ms: int,
        regression: LinearRegressionModelParameters | None = None,
    ):
        self.monitor = monitor
        self.fetcher = fetcher
        self.partitions_fn = partitions_fn
        self.window_ms = window_ms
        self.regression = regression or LinearRegressionModelParameters()
        self._lock = threading.Lock()
        self._bootstrap_progress = 0.0

    # ------------------------------------------------------------------

    def _enter(self, state: MonitorState):
        with self._lock:
            if self.monitor.state in (
                MonitorState.BOOTSTRAPPING,
                MonitorState.TRAINING,
                MonitorState.LOADING,
            ):
                raise RuntimeError(f"monitor busy: {self.monitor.state.value}")
            self._prev_state = self.monitor.state
            self.monitor._state = state

    def _exit(self):
        with self._lock:
            self.monitor._state = self._prev_state

    # ------------------------------------------------------------------

    def start(self, *, interval_s: float | None = None):
        self.monitor.start()
        self.fetcher.start(self.partitions_fn, interval_s=interval_s)

    def stop(self):
        self.fetcher.stop()

    def load_samples(self) -> int:
        """Warm restart (reference SampleLoadingTask)."""
        self._enter(MonitorState.LOADING)
        try:
            return self.fetcher.load_samples()
        finally:
            self._exit()

    def bootstrap_range(self, start_ms: int, end_ms: int, clear_metrics: bool = False) -> int:
        """RANGE bootstrap: replay samples for [start, end)
        (reference BootstrapTask RANGE mode; LoadMonitor.bootstrap:325-345)."""
        return self._bootstrap(start_ms, end_ms, clear_metrics)

    def bootstrap_since(self, start_ms: int, clear_metrics: bool = False) -> int:
        """SINCE bootstrap: from start to now."""
        return self._bootstrap(start_ms, int(time.time() * 1000), clear_metrics)

    def bootstrap_recent(self, clear_metrics: bool = True) -> int:
        """RECENT bootstrap: enough trailing windows to satisfy completeness."""
        now = int(time.time() * 1000)
        span = self.window_ms * (self.monitor.partition_aggregator.num_windows + 1)
        return self._bootstrap(now - span, now, clear_metrics)

    def _bootstrap(self, start_ms: int, end_ms: int, clear_metrics: bool) -> int:
        self._enter(MonitorState.BOOTSTRAPPING)
        try:
            if clear_metrics:
                agg = self.monitor.partition_aggregator
                fresh = type(agg)(
                    num_windows=agg.num_windows,
                    window_ms=agg.window_ms,
                    min_samples_per_window=agg.min_samples,
                    metric_def=agg.metric_def,
                )
                self.monitor.partition_aggregator = fresh
                self.fetcher.partition_aggregator = fresh
            total = 0
            parts = self.partitions_fn()
            # replay at most the windows the aggregation ring can retain —
            # older samples would immediately roll out again (reference
            # BootstrapTask replays only what the sample store covers)
            max_windows = self.monitor.partition_aggregator.num_windows + 1
            n_windows = max(1, min((end_ms - start_ms) // self.window_ms, max_windows))
            start_ms = max(start_ms, end_ms - n_windows * self.window_ms)
            for i in range(n_windows):
                w_start = start_ms + i * self.window_ms
                w_end = min(w_start + self.window_ms - 1, end_ms)
                total += self.fetcher.fetch_once(parts, w_start, w_end)
                self._bootstrap_progress = (i + 1) / n_windows
            return total
        finally:
            self._exit()

    def train(self, start_ms: int, end_ms: int) -> dict:
        """Reference TrainingTask: harvest (bytes-in, bytes-out, follower
        bytes-in, cpu) tuples from broker samples into the regression —
        restricted to windows inside [start_ms, end_ms) as requested
        (reference LoadMonitor.train:354 passes the range through)."""
        self._enter(MonitorState.TRAINING)
        try:
            agg = self.fetcher.broker_aggregator
            if agg is not None and agg.num_entities():
                try:
                    res = agg.aggregate()
                except ValueError:  # no completed broker windows yet
                    res = None
            else:
                res = None
            if res is not None:
                m = KAFKA_METRIC_DEF
                for e_idx in range(res.values.shape[0]):
                    for w in range(res.values.shape[1]):
                        if not res.window_valid[e_idx, w]:
                            continue
                        # NB: broker windows have their OWN span (reference
                        # broker.metrics.window.ms), not the partition span
                        # this runner was built with
                        w_start = int(res.window_indices[w]) * agg.window_ms
                        if not (start_ms <= w_start < end_ms):
                            continue
                        v = res.values[e_idx, w]
                        self.regression.add_sample(
                            float(v[m.metric_id("LEADER_BYTES_IN")]),
                            float(v[m.metric_id("LEADER_BYTES_OUT")]),
                            float(v[m.metric_id("REPLICATION_BYTES_IN_RATE")]),
                            float(v[m.metric_id("CPU_USAGE")]),
                        )
            trained = self.regression.train()
            return {"trained": trained, **self.regression.state()}
        finally:
            self._exit()

    def state(self) -> dict:
        return {
            "monitorState": self.monitor.state.value,
            "bootstrapProgressPct": round(100.0 * self._bootstrap_progress, 1),
            "trainingState": self.regression.state(),
            "totalSamples": self.fetcher.total_samples,
        }
