"""TopicConfigProvider SPI — per-topic Kafka configs for detectors/goals.

Reference: config/TopicConfigProvider.java (pluggable via
topic.config.provider.class).  The primary consumer here is the
replication-factor anomaly finder, which needs each topic's
min.insync.replicas: a topic whose RF is below (minISR + 1) cannot
tolerate a broker loss without going under min-ISR, so the finder flags
it even when RF meets the global target
(reference detector/TopicReplicationFactorAnomalyFinder.java uses the
provider the same way).
"""

from __future__ import annotations

from typing import Protocol


class TopicConfigProvider(Protocol):
    def topic_configs(self, topics: list[str]) -> dict[str, dict[str, str]]:
        """{topic: {config name: value}} for the requested topics."""
        ...


class StaticTopicConfigProvider:
    """Fixed config map (tests / clusters without a config channel)."""

    def __init__(self, configs: dict[str, dict[str, str]] | None = None):
        self._configs = configs or {}

    def topic_configs(self, topics: list[str]) -> dict[str, dict[str, str]]:
        return {t: self._configs.get(t, {}) for t in topics}


class KafkaTopicConfigProvider:
    """Reads topic configs over the wire client's DescribeConfigs
    (reference KafkaAdminTopicConfigProvider).

    Constructed by the facade as cls(config, admin) — the provider pulls
    the wire client off the cluster admin; direct construction may pass
    client= instead."""

    _TOPIC_RESOURCE = 2  # ConfigResource type TOPIC

    def __init__(self, config=None, admin=None, *, client=None):
        if client is None:
            client = getattr(admin, "client", None)
        if client is None or not hasattr(client, "describe_configs"):
            raise ValueError(
                "KafkaTopicConfigProvider needs a wire client "
                "(a KafkaClusterAdmin admin, or client=)"
            )
        self.client = client

    def topic_configs(self, topics: list[str]) -> dict[str, dict[str, str]]:
        if not topics:
            return {}
        described = self.client.describe_configs(
            [(self._TOPIC_RESOURCE, t) for t in topics]
        )
        return {
            name: dict(cfg)
            for (rtype, name), cfg in described.items()
            if rtype == self._TOPIC_RESOURCE
        }


def min_insync_replicas_map(
    provider: TopicConfigProvider | None, topics: list[str]
) -> dict[str, int]:
    """{topic: min.insync.replicas} in ONE batch provider call — per-topic
    fetches would turn a detection tick into thousands of admin RPCs."""
    if provider is None or not topics:
        return {t: 1 for t in topics}
    try:
        configs = provider.topic_configs(list(topics))
    except Exception:  # noqa: BLE001 — config channel failure must not kill detection
        return {t: 1 for t in topics}
    out = {}
    for t in topics:
        try:
            out[t] = int(configs.get(t, {}).get("min.insync.replicas", 1))
        except (TypeError, ValueError):
            out[t] = 1
    return out
