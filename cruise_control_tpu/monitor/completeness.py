"""Model completeness requirements.

Reference: monitor/ModelCompletenessRequirements.java and
MonitorUtils.combineLoadRequirementOptions (the stricter of two
requirements wins when goals are combined).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ModelCompletenessRequirements:
    min_required_num_windows: int = 1
    min_monitored_partitions_percentage: float = 0.98
    include_all_topics: bool = False

    def stronger(self, other: "ModelCompletenessRequirements | None") -> "ModelCompletenessRequirements":
        """Combine two requirements, keeping the stricter of each field
        (reference MonitorUtils.combineLoadRequirementOptions)."""
        if other is None:
            return self
        return ModelCompletenessRequirements(
            min_required_num_windows=max(
                self.min_required_num_windows, other.min_required_num_windows
            ),
            min_monitored_partitions_percentage=max(
                self.min_monitored_partitions_percentage,
                other.min_monitored_partitions_percentage,
            ),
            include_all_topics=self.include_all_topics or other.include_all_topics,
        )

    def weaker(self, other: "ModelCompletenessRequirements | None") -> "ModelCompletenessRequirements":
        if other is None:
            return self
        return ModelCompletenessRequirements(
            min_required_num_windows=min(
                self.min_required_num_windows, other.min_required_num_windows
            ),
            min_monitored_partitions_percentage=min(
                self.min_monitored_partitions_percentage,
                other.min_monitored_partitions_percentage,
            ),
            include_all_topics=self.include_all_topics and other.include_all_topics,
        )


DEFAULT_REQUIREMENTS = ModelCompletenessRequirements()
