"""Detector layer: anomaly detection + self-healing dispatch.

Reference: cruise-control/.../detector/ (AnomalyDetector.java, 5 detectors,
notifier/).
"""

from cruise_control_tpu.detector.anomalies import (
    Anomaly,
    AnomalyType,
    BrokerFailures,
    DiskFailures,
    ExecutionStuck,
    GoalViolations,
    SlowBrokers,
    TopicPartitionSizeAnomaly,
    TopicReplicationFactorAnomaly,
)
from cruise_control_tpu.detector.detector import (
    AnomalyDetector,
    AnomalyDetectorState,
    AnomalyRecord,
    SelfHealingActions,
)
from cruise_control_tpu.detector.detectors import (
    BrokerFailureDetector,
    DiskFailureDetector,
    GoalViolationDetector,
    PartitionSizeAnomalyFinder,
    SlowBrokerFinder,
    TopicReplicationFactorAnomalyFinder,
)
from cruise_control_tpu.detector.notifier import (
    Action,
    AnomalyNotificationResult,
    AnomalyNotifier,
    NoopNotifier,
    SelfHealingNotifier,
)
